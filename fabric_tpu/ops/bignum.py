"""Multi-limb modular arithmetic for JAX/TPU.

Replaces the Go-stdlib constant-time P-256 assembly the reference leans on
(SURVEY.md §2.12: crypto/elliptic P-256 under bccsp/sw) with batched,
compiler-friendly integer math. Design notes:

- **Radix 2^13, 20 limbs** (260 bits for 256-bit fields). 13-bit limbs make
  products fit comfortably in 32 bits (26-bit products), so a full CIOS
  Montgomery multiplication runs with *lazy carries* entirely in uint32:
  each of the 20 outer iterations adds two <2^27 products per limb, for a
  worst-case accumulator below 20 * 2^27 * (1 + eps) < 2^32.
- **Limb-unpacked representation**: inside kernels a big number is a
  *tuple of 20 arrays*, each shaped (*batch) — plain SSA values. This is
  the crucial TPU design choice: a stacked (20, B) layout forces
  dynamic-index/concatenate ops inside the CIOS loop, each of which
  breaks XLA fusion and round-trips every intermediate through HBM
  (measured ~5x whole-kernel slowdown). Unpacked limbs give XLA one pure
  elementwise DAG it can fuse freely; carries become ordinary data
  dependencies. The batch dimension rides the VPU lanes.
- **No constant-time requirement**: verification consumes public data
  (signatures, public keys, digests), so data-dependent selects are fine —
  but never data-dependent *shapes* or control flow; everything is one
  fixed XLA program.

Stacked (NLIMBS, *batch) arrays remain the interface at kernel boundaries
(`split`/`restack` convert). Values are canonical (every limb < 2^13,
value < modulus) unless a caller tracks a laxer bound (see
fabric_tpu.ops.p256_kernel.FE).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Canonical limb parameters live in the jax-free common tier
# (fabric_tpu/common/limbparams.py) so host code and tools can use them
# without pulling in jax; re-exported here under the historical names.
from fabric_tpu.common.limbparams import (  # noqa: F401
    LIMB_BITS,
    LIMB_MASK,
    NLIMBS,
    RADIX_BITS,
)

# A big number inside a kernel: tuple of NLIMBS arrays, each (*batch).
LimbVec = Tuple[jax.Array, ...]


# ---------------------------------------------------------------------------
# Host conversions
# ---------------------------------------------------------------------------


def int_to_limbs(x: int, nlimbs: int = NLIMBS) -> np.ndarray:
    """Python int -> little-endian 13-bit limbs, shape (nlimbs,) uint32."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros(nlimbs, dtype=np.uint32)
    for i in range(nlimbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def ints_to_limbs(xs, nlimbs: int = NLIMBS) -> np.ndarray:
    """Batch of ints -> (nlimbs, B) uint32 (limb-major)."""
    out = np.zeros((nlimbs, len(xs)), dtype=np.uint32)
    for j, x in enumerate(xs):
        out[:, j] = int_to_limbs(x, nlimbs)
    return out


def limbs_to_int(a) -> int:
    """(nlimbs,) limbs -> Python int."""
    a = np.asarray(a)
    val = 0
    for i in range(a.shape[0] - 1, -1, -1):
        val = (val << LIMB_BITS) | int(a[i])
    return val


def limbs_to_ints(a) -> list:
    """(nlimbs, B) -> list of B Python ints."""
    a = np.asarray(a)
    return [limbs_to_int(a[:, j]) for j in range(a.shape[1])]


# ---------------------------------------------------------------------------
# Packing between stacked arrays and unpacked limb tuples
# ---------------------------------------------------------------------------


def split(x: jax.Array) -> LimbVec:
    """(NLIMBS, *batch) -> tuple of NLIMBS (*batch) arrays."""
    return tuple(x[i] for i in range(x.shape[0]))

def restack(xs: Sequence[jax.Array]) -> jax.Array:
    return jnp.stack(tuple(xs), axis=0)


# ---------------------------------------------------------------------------
# Carry propagation (pure data-dependency chains; fusion-friendly)
# ---------------------------------------------------------------------------


def carry_l(xs: Sequence[jax.Array]) -> Tuple[List[jax.Array], jax.Array]:
    """Carry-propagate a limb list (uint32 or int32; the arithmetic shift
    on int32 makes negative limbs borrow). Returns (canonical limbs,
    carry_out)."""
    out = []
    c = None
    for x in xs:
        t = x if c is None else x + c
        c = t >> LIMB_BITS
        out.append(t & LIMB_MASK)
    return out, c


def carry_u32(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    ys, c = carry_l(split(x))
    return restack(ys), c


carry_i32 = carry_u32  # dtype decides signedness; same chain


# ---------------------------------------------------------------------------
# Montgomery context
# ---------------------------------------------------------------------------


class MontCtx:
    """Precomputed Montgomery constants for an odd modulus m < 2^256.

    R = 2^260 (one limb-width above 256 bits). Per-limb constants are
    numpy uint32/int32 *scalars* so they enter traces as broadcastable
    XLA constants.
    """

    def __init__(self, modulus: int):
        if modulus % 2 == 0:
            raise ValueError("modulus must be odd")
        self.m = modulus
        r = 1 << RADIX_BITS
        self.m_limbs = int_to_limbs(modulus)
        self.m_scalars = tuple(np.uint32(v) for v in self.m_limbs)
        self.m_scalars_i32 = tuple(np.int32(v) for v in self.m_limbs)
        self.r2_limbs = int_to_limbs((r * r) % modulus)
        self.one_mont = int_to_limbs(r % modulus)
        self.one = int_to_limbs(1)
        # m' = -m^-1 mod 2^13 for the REDC quotient digit.
        self.m0inv = np.uint32((-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        # k*m as int32 per-limb scalars, for borrow-free subtraction.
        self.km_scalars_i32 = {
            k: tuple(np.int32(v) for v in int_to_limbs(k * modulus))
            for k in range(1, 9)
        }
        # Per-limb shift decomposition m_j = 2^a - 2^b (b = -1 for a plain
        # power of two; None entry = limb is 0 or not decomposable). The
        # crypto moduli are Solinas primes whose 13-bit limbs are almost
        # all of this form — P-256's p decomposes COMPLETELY and has
        # m0inv == 1, which turns the entire q*m half of CIOS (plus the
        # REDC quotient multiply) into shifts and subtracts. Measured
        # 1.46x on the TPU kernel's Montgomery multiply.
        self.limb_shift_decomp: List = []
        for v in self.m_limbs:
            v = int(v)
            d = None
            if v == 0:
                d = "zero"
            else:
                for hi in range(2 * LIMB_BITS + 1):
                    if (1 << hi) == v:
                        d = (hi, -1)
                        break
                    for lo in range(hi):
                        if (1 << hi) - (1 << lo) == v:
                            d = (hi, lo)
                            break
                    if d:
                        break
            self.limb_shift_decomp.append(d)

    def const(self, value_limbs: np.ndarray) -> Tuple[np.uint32, ...]:
        return tuple(np.uint32(v) for v in value_limbs)

    def qm_term(self, q: jax.Array, j: int):
        """q * m_j, as shifts/subtracts when the limb decomposes (never
        underflows: 2^a - 2^b with a > b gives (q<<a) >= (q<<b)), else the
        plain multiply. Returns None for zero limbs."""
        d = self.limb_shift_decomp[j]
        if d == "zero":
            return None
        if d is None:
            return q * self.m_scalars[j]
        hi, lo = d
        if lo < 0:
            return q << np.uint32(hi)
        # interval domain sees [-(8191<<12), 8191<<13]; hi > lo makes the
        # subtraction non-negative, bounded by q*m_j <= 8191*8192 < 2^26
        return (q << np.uint32(hi)) - (q << np.uint32(lo))  # fabflow: disable=limb-overflow  # hi>lo => result in [0, 8191<<13 = 67100672 < 2**27]; relational fact outside the interval domain


def cond_sub_l(ctx: MontCtx, xs: Sequence[jax.Array]) -> List[jax.Array]:
    """One conditional subtract: x - m if x >= m else x (limbs canonical)."""
    d = [x.astype(jnp.int32) - mj for x, mj in zip(xs, ctx.m_scalars_i32)]
    limbs, c = carry_l(d)
    keep = c < 0  # borrow out -> x < m
    return [jnp.where(keep, x, l.astype(jnp.uint32)) for x, l in zip(xs, limbs)]


def reduce_canonical_l(ctx: MontCtx, xs: Sequence[jax.Array], times: int) -> List[jax.Array]:
    xs = list(xs)
    for _ in range(times):
        xs = cond_sub_l(ctx, xs)
    return xs


# ---------------------------------------------------------------------------
# Core multiply (CIOS Montgomery, lazy carries)
#
# Two trace shapes for identical math, chosen by FABRIC_TPU_CIOS_UNROLL
# (default: unrolled off-CPU, looped on CPU):
# - unrolled: 20 Python iterations -> one flat elementwise DAG XLA fuses
#   freely; fastest at runtime (the TPU/bench path).
# - looped: lax.fori_loop whose body is ~10 vector ops on stacked
#   (NLIMBS, B) arrays. ~40x smaller traced graph; XLA:CPU compiles the
#   full ECDSA verify kernel in seconds instead of >10 minutes. The
#   stacked layout costs runtime (dynamic-index breaks fusion), which is
#   irrelevant for tests/dryrun.
# ---------------------------------------------------------------------------


import contextlib as _contextlib
import threading as _threading

_cios_override = _threading.local()


@_contextlib.contextmanager
def force_looped_cios():
    """Trace-time override: use the looped CIOS inside this context even
    off-CPU. The pairing kernel traces hundreds of stacked multiplies
    inside scan bodies; unrolled CIOS there produces graphs big enough
    that the remote compile service drops them."""
    prev = getattr(_cios_override, "looped", False)
    _cios_override.looped = True
    try:
        yield
    finally:
        _cios_override.looped = prev


def _cios_unrolled() -> bool:
    import os

    if getattr(_cios_override, "looped", False):
        return False
    forced = os.environ.get("FABRIC_TPU_CIOS_UNROLL", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    return jax.default_backend() != "cpu"


def mont_mul_l(
    ctx: MontCtx,
    a: Sequence[jax.Array],
    b: Sequence[jax.Array],
    nreduce: int = 1,
) -> List[jax.Array]:
    """Montgomery product a*b*R^-1 mod m on canonical-limb inputs.

    Values may be up to 4m; with inputs <= c1*m, c2*m the pre-reduction
    output is < m*(1 + c1*c2*m/2^260), so nreduce=1 suffices for
    c1*c2 <= 16.
    """
    if not _cios_unrolled():
        return _mont_mul_l_looped(ctx, a, b, nreduce)
    m0inv = ctx.m0inv
    zero = jnp.zeros_like(a[0])
    t: List[jax.Array] = [zero] * NLIMBS
    # Static headroom proof (mechanized by tools/fabflow over this very
    # loop): with canonical 13-bit limbs, each iteration adds at most
    # ai*b[j] + q*m_j <= 8191^2 + 8191*2^13 = 134193153 < 2^27 per limb,
    # plus the shifted-down carry (<= 327657).  The abstractly-unrolled
    # 20-iteration worst case is 2684174334 < 0.625 * 2^32 < 2^32 - 1,
    # so the uint32 lazy-carry accumulator can never wrap.  Adding ONE
    # more accumulation term per iteration (e.g. a third product) would
    # push the bound to ~0.94 * 2^32 and an extra limb (NLIMBS=21) to
    # ~0.66 * 2^32 — the gate recomputes this on every change.
    for i in range(NLIMBS):
        ai = a[i]
        t0 = t[0] + ai * b[0]
        if int(m0inv) == 1:  # m ≡ -1 mod 2^13 (P-256's p): q is free
            q = t0 & LIMB_MASK
        else:
            q = ((t0 & LIMB_MASK) * m0inv) & LIMB_MASK
        qm0 = ctx.qm_term(q, 0)
        carry0 = (t0 if qm0 is None else t0 + qm0) >> LIMB_BITS
        # u_j for j=1..19, shifted down one limb; u_0's low bits vanish.
        nt = []
        for j in range(1, NLIMBS):
            u = t[j] + ai * b[j]
            qm = ctx.qm_term(q, j)
            nt.append(u if qm is None else u + qm)
        nt[0] = nt[0] + carry0
        nt.append(zero)
        t = nt
    limbs, _ = carry_l(t)  # value < 2m for canonical inputs; carry_out 0
    return reduce_canonical_l(ctx, limbs, nreduce)


def _mont_mul_l_looped(
    ctx: MontCtx,
    a: Sequence[jax.Array],
    b: Sequence[jax.Array],
    nreduce: int,
) -> List[jax.Array]:
    """Same CIOS recurrence with the outer i-loop as lax.fori_loop and the
    inner j-loop vectorized over a stacked (NLIMBS, B) accumulator."""
    from jax import lax

    batch = jnp.broadcast_shapes(
        *(jnp.shape(x) for x in a), *(jnp.shape(y) for y in b)
    )
    a_s = jnp.stack(tuple(jnp.broadcast_to(jnp.asarray(x), batch) for x in a))
    b_s = jnp.stack(tuple(jnp.broadcast_to(jnp.asarray(y), batch) for y in b))
    m_s = jnp.asarray(ctx.m_limbs, dtype=jnp.uint32).reshape(
        (NLIMBS,) + (1,) * len(batch)
    )
    m0inv = ctx.m0inv

    def body(i, t):
        ai = a_s[i]
        t0 = t[0] + ai * b_s[0]
        q = ((t0 & LIMB_MASK) * m0inv) & LIMB_MASK
        carry0 = (t0 + q * m_s[0]) >> LIMB_BITS
        # same accumulator recurrence as the unrolled form: per-limb
        # growth < 2^27 per step, 20-step worst case < 0.625 * 2^32
        # (fabflow unrolls lax.fori_loop(0, NLIMBS) and re-proves it)
        nt = t[1:] + ai * b_s[1:] + q * m_s[1:]
        nt = nt.at[0].add(carry0)
        return jnp.concatenate([nt, jnp.zeros_like(t[:1])])

    t = lax.fori_loop(
        0, NLIMBS, body, jnp.zeros_like(a_s), unroll=False
    )
    limbs, _ = carry_l(split(t))
    return reduce_canonical_l(ctx, limbs, nreduce)


def add_raw_l(a: Sequence[jax.Array], b: Sequence[jax.Array]) -> List[jax.Array]:
    """Limb-canonical addition WITHOUT modular reduction (value = a+b)."""
    limbs, _ = carry_l([x + y for x, y in zip(a, b)])
    return limbs


def sub_mod_l(
    ctx: MontCtx,
    a: Sequence[jax.Array],
    b: Sequence[jax.Array],
    b_bound: int,
    nreduce: int,
) -> List[jax.Array]:
    """a - b + b_bound*m, carried in int32 (no borrow underflow), reduced
    with `nreduce` conditional subtracts."""
    kp = ctx.km_scalars_i32[b_bound]
    d = [
        x.astype(jnp.int32) + kpj - y.astype(jnp.int32)
        for x, y, kpj in zip(a, b, kp)
    ]
    limbs, _ = carry_l(d)
    return reduce_canonical_l(ctx, [l.astype(jnp.uint32) for l in limbs], nreduce)


def const_l(limbs: np.ndarray) -> Tuple[np.uint32, ...]:
    """A compile-time constant as broadcastable per-limb scalars."""
    return tuple(np.uint32(v) for v in limbs)


def bcast_l(limbs: np.ndarray, like: jax.Array) -> List[jax.Array]:
    """A constant materialized at `like`'s batch shape."""
    return [jnp.full(like.shape, np.uint32(v), dtype=jnp.uint32) for v in limbs]


def to_mont_l(ctx: MontCtx, xs: Sequence[jax.Array], nreduce: int = 1) -> List[jax.Array]:
    return mont_mul_l(ctx, xs, const_l(ctx.r2_limbs), nreduce=nreduce)


def from_mont_l(ctx: MontCtx, xs: Sequence[jax.Array]) -> List[jax.Array]:
    return mont_mul_l(ctx, xs, const_l(ctx.one))


def mont_pow_l(ctx: MontCtx, xs: Sequence[jax.Array], exponent: int) -> List[jax.Array]:
    """x^exponent in the Montgomery domain.

    Branch-free fixed-window form: scan over static 2-bit exponent digits
    (MSB-first); each step squares twice and multiplies by a selected
    entry of {1, x, x^2, x^3}. 384 multiplies for a 256-bit exponent —
    the same count as optimal square-and-multiply — while keeping the
    traced graph small (the scan body traces once).
    """
    from jax import lax

    nbits = exponent.bit_length()
    ndigits = (nbits + 1) // 2
    digits = np.array(
        [(exponent >> (2 * (ndigits - 1 - i))) & 3 for i in range(ndigits)],
        dtype=np.int32,
    )
    x1 = list(xs)
    x2 = mont_mul_l(ctx, x1, x1)
    x3 = mont_mul_l(ctx, x2, x1)
    one = const_l(ctx.one_mont)
    # table[d][j]: limb j of the digit-d multiplier, materialized (4, B)
    table = [jnp.stack([jnp.broadcast_to(one[j], x1[j].shape), x1[j], x2[j], x3[j]])
             for j in range(NLIMBS)]
    acc0 = [jnp.broadcast_to(jnp.asarray(one[j]), x1[j].shape) for j in range(NLIMBS)]

    def body(acc, d):
        acc = list(acc)
        acc = mont_mul_l(ctx, acc, acc)
        acc = mont_mul_l(ctx, acc, acc)
        mult = [t[d] for t in table]
        return tuple(mont_mul_l(ctx, acc, mult)), None

    acc, _ = lax.scan(body, tuple(acc0), jnp.asarray(digits))
    return list(acc)


def eq_l(a: Sequence[jax.Array], b: Sequence[jax.Array]) -> jax.Array:
    out = None
    for x, y in zip(a, b):
        e = x == y
        out = e if out is None else (out & e)
    return out


def is_zero_l(a: Sequence[jax.Array]) -> jax.Array:
    out = None
    for x in a:
        e = x == 0
        out = e if out is None else (out & e)
    return out


# ---------------------------------------------------------------------------
# Stacked-array wrappers (interface / test convenience)
# ---------------------------------------------------------------------------


def mont_mul(ctx: MontCtx, a: jax.Array, b: jax.Array, nreduce: int = 1) -> jax.Array:
    return restack(mont_mul_l(ctx, split(a), split(b), nreduce))


def add_raw(a: jax.Array, b: jax.Array) -> jax.Array:
    return restack(add_raw_l(split(a), split(b)))


def sub_mod(ctx: MontCtx, a: jax.Array, b: jax.Array, b_bound: int, nreduce: int) -> jax.Array:
    return restack(sub_mod_l(ctx, split(a), split(b), b_bound, nreduce))


def to_mont(ctx: MontCtx, x: jax.Array, nreduce: int = 1) -> jax.Array:
    return restack(to_mont_l(ctx, split(x), nreduce))


def from_mont(ctx: MontCtx, x: jax.Array) -> jax.Array:
    return restack(from_mont_l(ctx, split(x)))


def mont_pow(ctx: MontCtx, x: jax.Array, exponent: int) -> jax.Array:
    return restack(mont_pow_l(ctx, split(x), exponent))


def reduce_canonical(x: jax.Array, ctx: MontCtx, times: int) -> jax.Array:
    return restack(reduce_canonical_l(ctx, split(x), times))


def cond_sub(x: jax.Array, ctx: MontCtx) -> jax.Array:
    return restack(cond_sub_l(ctx, split(x)))


def eq_limbs(a: jax.Array, b: jax.Array) -> jax.Array:
    return eq_l(split(a), split(b))


def is_zero(a: jax.Array) -> jax.Array:
    return is_zero_l(split(a))
