"""Batched ECDSA-P-256 verification as one XLA program.

This is the TPU replacement for the per-endorsement `ecdsa.Verify` hot loop
the reference burns CPU on (reference: common/policies/policy.go:369-399 ->
msp/identities.go:169 -> bccsp/sw/ecdsa.go:41; SURVEY.md §3.1 "HOT").
Instead of one goroutine per transaction (reference v20/validator.go:193-208),
we flatten (tx × endorsement) into one padded batch dimension and verify the
whole block in a single fixed-shape device program.

Math layout:

- field elements: Montgomery residues as *unpacked* 13-bit limbs — tuples
  of 20 (B,) arrays (see fabric_tpu.ops.bignum for why unpacked limbs are
  the TPU-critical choice: pure elementwise DAGs fuse; stacked layouts
  spill every intermediate to HBM);
- point arithmetic: *complete* projective formulas for a=-3 short
  Weierstrass curves (Renes–Costello–Batina, EUROCRYPT 2016, algs 4/6).
  Complete formulas have no special cases for infinity/doubling, which is
  exactly what a branch-free SIMD batch needs;
- scalar recomposition: u1*G + u2*Q with 4-bit fixed windows, MSB-first
  Horner loop (R = 16R + d1*G + d2*Q). G multiples come from a host
  precomputed table; Q multiples are built per lane;
- scalar inversion s^-1 mod n uses branch-free fixed-window Fermat
  exponentiation; the final x-coordinate test is done projectively
  (X == r*Z), so Z is never inverted.

The per-lane boolean output is bit-exact with the reference's
`ecdsa.Verify` decision; DER parsing, the low-S rule and r/s range checks
happen host-side (cheap, irregular) and arrive here as the `valid_in`
mask.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.common import p256
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import fieldops as fo

CTX_P = bn.MontCtx(p256.P)
CTX_N = bn.MontCtx(p256.N)

_R = 1 << bn.RADIX_BITS
B_MONT = bn.int_to_limbs((p256.B * _R) % p256.P)
ONE_MONT_P = bn.int_to_limbs(_R % p256.P)
N_LIMBS = bn.int_to_limbs(p256.N)

WINDOW_BITS = 4
NUM_WINDOWS = 64  # 256 bits / 4

P_MINUS_N_LIMBS = bn.int_to_limbs(p256.P - p256.N)

LimbVec = bn.LimbVec


# Shared lazy-reduction machinery (fabric_tpu.ops.fieldops) bound to the
# P-256 modulus; local names preserved for the formula bodies below.
FIELD = fo.Field(CTX_P)
FE = fo.FE
fe = fo.Field.fe
fe_mul = FIELD.mul
fe_add = FIELD.add
fe_sub = FIELD.sub


def fe_norm(a: FE) -> FE:
    # (unconditional form: callers rely on bound-1 output even for
    # bound-1 inputs annotated wider — see _horner_micro's renorm)
    return FE(tuple(bn.reduce_canonical_l(CTX_P, a.limbs, a.bound - 1)), 1)


_B_FE = FE(bn.const_l(B_MONT), 1)
_IDENT_X = FE(bn.const_l(bn.int_to_limbs(0)), 1)
_IDENT_Y = FE(bn.const_l(ONE_MONT_P), 1)
_IDENT_Z = FE(bn.const_l(bn.int_to_limbs(0)), 1)


Point = fo.Point
point_identity_like = FIELD.identity_like


def point_add(p: Point, q: Point) -> Point:
    """Complete addition, RCB 2016 algorithm 4 (a = -3). Handles identity
    and p == q with no branches."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    bb = _B_FE

    t0 = fe_mul(x1, x2)
    t1 = fe_mul(y1, y2)
    t2 = fe_mul(z1, z2)
    t3 = fe_add(x1, y1)
    t4 = fe_add(x2, y2)
    t3 = fe_mul(t3, t4)
    t4 = fe_add(t0, t1)
    t3 = fe_sub(t3, t4)
    t4 = fe_add(y1, z1)
    t5 = fe_add(y2, z2)
    t4 = fe_mul(t4, t5)
    t5 = fe_add(t1, t2)
    t4 = fe_sub(t4, t5)
    x3 = fe_add(x1, z1)
    y3 = fe_add(x2, z2)
    x3 = fe_mul(x3, y3)
    y3 = fe_add(t0, t2)
    y3 = fe_sub(x3, y3)
    z3 = fe_mul(bb, t2)
    x3 = fe_sub(y3, z3)
    z3 = fe_add(x3, x3)
    x3 = fe_add(x3, z3)
    z3 = fe_sub(t1, x3)
    x3 = fe_add(t1, x3)  # bound 4
    y3 = fe_mul(bb, y3)
    t1 = fe_add(t2, t2)
    t2 = fe_add(t1, t2)
    y3 = fe_sub(y3, t2)
    y3 = fe_sub(y3, t0)
    t1 = fe_add(y3, y3)
    y3 = fe_add(t1, y3)  # bound 3
    t1 = fe_add(t0, t0)
    t0 = fe_add(t1, t0)
    t0 = fe_sub(t0, t2)
    t1 = fe_mul(t4, y3)
    t2 = fe_mul(t0, y3)
    y3 = fe_mul(x3, z3)
    y3 = fe_add(y3, t2)
    x3 = fe_mul(t3, x3)
    x3 = fe_sub(x3, t1)
    z3 = fe_mul(t4, z3)
    t1 = fe_mul(t3, t0)
    z3 = fe_add(z3, t1)
    return Point(x3, fe_norm(y3), fe_norm(z3))


def point_double(p: Point) -> Point:
    """Complete doubling, RCB 2016 algorithm 6 (a = -3)."""
    x, y, z = p
    bb = _B_FE

    t0 = fe_mul(x, x)
    t1 = fe_mul(y, y)
    t2 = fe_mul(z, z)
    t3 = fe_mul(x, y)
    t3 = fe_add(t3, t3)
    z3 = fe_mul(x, z)
    z3 = fe_add(z3, z3)
    y3 = fe_mul(bb, t2)
    y3 = fe_sub(y3, z3)
    x3 = fe_add(y3, y3)
    y3 = fe_add(x3, y3)  # bound 3
    x3 = fe_sub(t1, y3)
    y3 = fe_add(t1, y3)  # bound 4
    y3 = fe_mul(x3, y3)
    x3 = fe_mul(x3, t3)
    t3 = fe_add(t2, t2)
    t2 = fe_add(t2, t3)  # bound 3
    z3 = fe_mul(bb, z3)
    z3 = fe_sub(z3, t2)
    z3 = fe_sub(z3, t0)
    t3 = fe_add(z3, z3)
    z3 = fe_add(z3, t3)  # bound 3
    t3 = fe_add(t0, t0)
    t0 = fe_add(t3, t0)
    t0 = fe_sub(t0, t2)
    t0 = fe_mul(t0, z3)
    y3 = fe_add(y3, t0)
    t0 = fe_mul(y, z)
    t0 = fe_add(t0, t0)
    z3 = fe_mul(t0, z3)
    x3 = fe_sub(x3, z3)
    z3 = fe_mul(t0, t1)
    z3 = fe_add(z3, z3)
    z3 = fe_add(z3, z3)  # bound 4
    return Point(x3, fe_norm(y3), fe_norm(z3))


# ---------------------------------------------------------------------------
# Fixed-base small-multiples table for G (host precompute)
# ---------------------------------------------------------------------------

_G_TABLE: np.ndarray | None = None


def g_small_table() -> np.ndarray:
    """(16, 3, 20) uint32: entry d = projective Montgomery coords of d*G.

    Used inside the Horner window loop (R = 16R + d1*G + d2*Q): everything
    added at window w is scaled by the remaining doublings, so the table
    holds *plain* small multiples — a pre-scaled comb table would get
    double-scaled.
    """
    global _G_TABLE
    if _G_TABLE is not None:
        return _G_TABLE

    one_m = _R % p256.P
    table = np.zeros((16, 3, bn.NLIMBS), dtype=np.uint32)
    table[0, 1] = bn.int_to_limbs(one_m)  # identity (0 : R : 0)
    acc = None
    for d in range(1, 16):
        acc = p256.point_add(acc, p256.GENERATOR)
        x, y = acc
        table[d, 0] = bn.int_to_limbs((x * _R) % p256.P)  # fabtrace: disable=transfer-in-loop  # one-time generator table: 15 fixed rows built once per process (memoized in _G_TABLE above), never per lane
        table[d, 1] = bn.int_to_limbs((y * _R) % p256.P)  # fabtrace: disable=transfer-in-loop  # one-time generator table: 15 fixed rows built once per process (memoized in _G_TABLE above), never per lane
        table[d, 2] = bn.int_to_limbs(one_m)  # fabtrace: disable=transfer-in-loop  # one-time generator table: 15 fixed rows built once per process (memoized in _G_TABLE above), never per lane
    _G_TABLE = table
    return table


# ---------------------------------------------------------------------------
# Scalar digit extraction
# ---------------------------------------------------------------------------


def scalar_digits_msb(u: Sequence[jax.Array]) -> jax.Array:
    """Canonical limbs (tuple) -> (64, B) 4-bit digits, MSB window first."""
    digits = []
    for w in range(NUM_WINDOWS):  # w = 0 is the most significant window
        bit = (NUM_WINDOWS - 1 - w) * WINDOW_BITS
        limb, off = divmod(bit, bn.LIMB_BITS)
        d = u[limb] >> off
        if off > bn.LIMB_BITS - WINDOW_BITS and limb + 1 < bn.NLIMBS:
            d = d | (u[limb + 1] << (bn.LIMB_BITS - off))
        digits.append(d & (16 - 1))
    return jnp.stack(digits, axis=0)


def _select_point(table: jax.Array, idx: jax.Array) -> Point:
    return fo.one_hot_select(table, idx, 16)


_pack_point = fo.pack_point


def _unpack_point(c: Sequence[Sequence[jax.Array]]) -> Point:
    return fo.unpack_point(c, x_bound=1)


# ---------------------------------------------------------------------------
# Window-loop variants
#
# Two trace shapes for the same math, picked per backend:
# - "inline": 64-step scan whose body inlines 4 doubles + 2 adds (~6 point
#   ops). Fastest to compile on the CPU backend (tests, dryrun) and
#   cheapest at runtime.
# - "micro": 384-step UNIFORM scan whose body is a single complete
#   point_add — completeness (RCB16) makes add(acc, acc) a correct double
#   and handles the identity, so every step is the same op with a selected
#   operand: 64 windows x [dbl,dbl,dbl,dbl,+Q(d2),+G(d1)]. The traced
#   graph is ~6x smaller, which is what gets it through the axon remote-
#   compile service (it drops oversized XLA programs with an EOF).
# Override with FABRIC_TPU_KERNEL_VARIANT=inline|micro.
# ---------------------------------------------------------------------------


def _kernel_variant() -> str:
    import os

    forced = os.environ.get("FABRIC_TPU_KERNEL_VARIANT", "auto")
    if forced in ("inline", "micro", "microcond"):
        return forced
    try:
        backend = jax.default_backend()
    except Exception:  # fablint: disable=broad-except  # backend init flake (r4: UNAVAILABLE
        # raised HERE at trace time, killing the whole bench). Assume the
        # accelerator variant; the dispatch itself will surface the real
        # error to the provider's retry/fallback machinery.
        return "microcond"
    return "microcond" if backend not in ("cpu",) else "inline"


def _horner_loop(d1, d2, q_table, g_table, qx) -> Point:
    variant = _kernel_variant()
    if variant == "micro":
        return _horner_micro(d1, d2, q_table, g_table, qx)
    if variant == "microcond":
        return _horner_microcond(d1, d2, q_table, g_table, qx)
    return _horner_inline(d1, d2, q_table, g_table, qx)


def _horner_inline(d1, d2, q_table, g_table, qx) -> Point:
    def win_body(carry, xs):
        d1w, d2w = xs
        acc = _unpack_point(carry)
        for _ in range(WINDOW_BITS):
            acc = point_double(acc)
        acc = point_add(acc, _select_point(q_table, d2w))
        acc = point_add(acc, _select_point(g_table, d1w))
        return _pack_point(acc), None

    carry, _ = lax.scan(
        win_body, _pack_point(point_identity_like(qx[0])), (d1, d2)
    )
    return _unpack_point(carry)


def _horner_micro(d1, d2, q_table, g_table, qx) -> Point:
    steps = NUM_WINDOWS * 6
    kinds = jnp.asarray(np.tile([0, 0, 0, 0, 1, 2], NUM_WINDOWS), dtype=jnp.uint32)
    digits = jnp.zeros((steps, d1.shape[1]), dtype=d1.dtype)
    digits = digits.at[4::6].set(d2).at[5::6].set(d1)

    def micro_body(carry, xs):
        kind, digit = xs
        # the carried x3 leaves point_add with bound 4 (y3/z3 are normed);
        # renormalize so add(acc, acc) respects the lazy-reduction bounds
        acc = Point(
            fe_norm(FE(tuple(carry[0]), 4)), fe(carry[1]), fe(carry[2])
        )
        q_op = _select_point(q_table, digit)
        g_op = _select_point(g_table, digit)

        def mix(coord_idx):
            a = [acc.x, acc.y, acc.z][coord_idx]
            qo = [q_op.x, q_op.y, q_op.z][coord_idx]
            go = [g_op.x, g_op.y, g_op.z][coord_idx]
            is_dbl = kind == 0
            is_q = kind == 1
            return FE(
                tuple(
                    jnp.where(is_dbl, al, jnp.where(is_q, ql, gl))
                    for al, ql, gl in zip(a.limbs, qo.limbs, go.limbs)
                ),
                1,
            )

        operand = Point(mix(0), mix(1), mix(2))
        res = point_add(acc, operand)
        return _pack_point(res), None

    carry, _ = lax.scan(
        micro_body, _pack_point(point_identity_like(qx[0])), (kinds, digits)
    )
    return _unpack_point(carry)


def _horner_microcond(d1, d2, q_table, g_table, qx) -> Point:
    """384-step scan like _horner_micro, but the body dispatches through
    lax.switch on the step kind (a scalar scan input, so XLA's
    conditional runs ONE branch at runtime): double steps run
    point_double and skip the 16-entry table contractions entirely —
    they are 4 of every 6 steps, so most iterations avoid both the
    q-table one-hot reduction and the 3-way operand mix. Graph size
    stays scan-body-bounded (~3 point ops), well inside what the remote
    compile service accepts."""
    steps = NUM_WINDOWS * 6
    kinds = jnp.asarray(np.tile([0, 0, 0, 0, 1, 2], NUM_WINDOWS), dtype=jnp.int32)
    digits = jnp.zeros((steps, d1.shape[1]), dtype=d1.dtype)
    digits = digits.at[4::6].set(d2).at[5::6].set(d1)

    def micro_body(carry, xs):
        kind, digit = xs
        acc = Point(
            fe_norm(FE(tuple(carry[0]), 4)), fe(carry[1]), fe(carry[2])
        )

        def do_double(_):
            return _pack_point(point_double(acc))

        def do_add_q(_):
            return _pack_point(point_add(acc, _select_point(q_table, digit)))

        def do_add_g(_):
            return _pack_point(point_add(acc, _select_point(g_table, digit)))

        res = lax.switch(kind, (do_double, do_add_q, do_add_g), None)
        return res, None

    carry, _ = lax.scan(
        micro_body, _pack_point(point_identity_like(qx[0])), (kinds, digits)
    )
    return _unpack_point(carry)


# ---------------------------------------------------------------------------
# The batched verifier
# ---------------------------------------------------------------------------


def verify_batch_device(
    e: jax.Array,
    r: jax.Array,
    s: jax.Array,
    qx: jax.Array,
    qy: jax.Array,
    valid_in: jax.Array,
) -> jax.Array:
    """Core batched verify. Limb inputs (20, B) uint32 canonical; valid_in
    (B,) bool (host prechecks: DER ok, low-S, 1 <= r,s < n, Q on curve).
    Returns (B,) bool.

    Semantics (Go crypto/ecdsa.Verify): w = s^-1 mod n; u1 = e*w; u2 = r*w;
    (x, y) = u1*G + u2*Q; accept iff the sum is not infinity and
    x mod n == r.
    """
    e_t, r_t, s_t = bn.split(e), bn.split(r), bn.split(s)
    qx_t, qy_t = bn.split(qx), bn.split(qy)

    # --- scalar field: u1 = e/s, u2 = r/s (mod n) ---
    s_m = bn.to_mont_l(CTX_N, s_t)
    s_inv = bn.mont_pow_l(CTX_N, s_m, p256.N - 2)
    e_m = bn.to_mont_l(CTX_N, e_t)  # e < 2^256 (may exceed n; reduced here)
    r_m = bn.to_mont_l(CTX_N, r_t)
    u1 = bn.from_mont_l(CTX_N, bn.mont_mul_l(CTX_N, e_m, s_inv))
    u2 = bn.from_mont_l(CTX_N, bn.mont_mul_l(CTX_N, r_m, s_inv))

    d1 = scalar_digits_msb(u1)  # (64, B)
    d2 = scalar_digits_msb(u2)

    # --- per-lane table of small multiples of Q ---
    q_pt = Point(
        fe(bn.to_mont_l(CTX_P, qx_t)),
        fe(bn.to_mont_l(CTX_P, qy_t)),
        FE(tuple(bn.bcast_l(ONE_MONT_P, qx[0])), 1),
    )

    def tab_body(carry, _):
        pt = _unpack_point(carry)
        nxt = point_add(pt, q_pt)
        packed = _pack_point(nxt)
        return packed, jnp.stack(
            [bn.restack(carry[0]), bn.restack(carry[1]), bn.restack(carry[2])]
        )

    _, q_multiples = lax.scan(tab_body, _pack_point(q_pt), None, length=15)
    ident = point_identity_like(qx[0])
    ident_row = jnp.stack(
        [bn.restack(ident.x.limbs), bn.restack(ident.y.limbs), bn.restack(ident.z.limbs)]
    )[None]
    q_table = jnp.concatenate([ident_row, q_multiples], axis=0)  # (16,3,20,B)

    # --- main window loop: R = 16R + d1*G + d2*Q, MSB first (Horner) ---
    g_table = jnp.asarray(g_small_table())  # (16, 3, 20)
    acc = _horner_loop(d1, d2, q_table, g_table, qx)

    # --- final comparison, projectively: for Z != 0,
    #   x_affine == v  <=>  X == v*Z  (mod p, Montgomery domain)
    # so the candidate v in {r, r+n} is lifted once and multiplied by Z —
    # 4 field muls instead of the 386-multiply Fermat inversion of Z.
    x_can = bn.reduce_canonical_l(CTX_P, acc.x.limbs, 3)  # bound 4 -> canonical
    r_plus_n, _ = bn.carry_l(
        [x + np.uint32(nv) for x, nv in zip(r_t, N_LIMBS)]
    )  # value < 2^257, fits in 20 limbs
    r_m_p = bn.to_mont_l(CTX_P, r_t)
    rpn_m_p = bn.to_mont_l(CTX_P, r_plus_n)  # value < 2p: reduced canonical
    rz = bn.mont_mul_l(CTX_P, r_m_p, acc.z.limbs)
    rpnz = bn.mont_mul_l(CTX_P, rpn_m_p, acc.z.limbs)
    # the r+n candidate only exists as an affine x when r+n < p (Go checks
    # x mod n == r with x < p; to_mont reduced r+n mod p, so an unsuppressed
    # wrapped value could falsely match x = r+n-p)
    diff = [
        x.astype(jnp.int32) - np.int32(d)
        for x, d in zip(r_t, P_MINUS_N_LIMBS)
    ]
    _, borrow = bn.carry_l(diff)
    rpn_in_range = borrow < 0  # r < p - n  <=>  r + n < p
    matches = bn.eq_l(x_can, rz) | (rpn_in_range & bn.eq_l(x_can, rpnz))
    not_inf = ~bn.is_zero_l(acc.z.limbs)
    return valid_in & not_inf & matches


verify_batch_jit = jax.jit(verify_batch_device)


# ---------------------------------------------------------------------------
# Bytes-in variant: unpack + key gather ON DEVICE.
#
# The host is a single core and the accelerator sits behind a network
# tunnel, so the e2e bottleneck is host prep + H2D bytes, not the kernel
# (measured: kernel 114ms/16k lanes vs ~530ms host+transfer). Shipping
# the raw 32-byte scalars and a per-lane key index instead of 13-bit limb
# matrices cuts the transfer ~5x and moves the bit-twiddling to the VPU.
# ---------------------------------------------------------------------------


def bytes_to_limbs_device(b: jax.Array) -> jax.Array:
    """(B, 32) uint8 big-endian -> (20, B) uint32 13-bit limbs (device)."""
    u = b.astype(jnp.uint32)
    limbs = []
    for j in range(bn.NLIMBS):
        bit_lo = j * bn.LIMB_BITS
        k0 = bit_lo // 8  # little-endian byte index
        shift = np.uint32(bit_lo % 8)
        acc = u[:, 31 - k0] >> shift
        if k0 + 1 < 32:
            acc = acc | (u[:, 31 - (k0 + 1)] << (np.uint32(8) - shift))
        if k0 + 2 < 32:
            acc = acc | (u[:, 31 - (k0 + 2)] << (np.uint32(16) - shift))
        limbs.append(acc & np.uint32(bn.LIMB_MASK))
    return jnp.stack(limbs, axis=0)


def verify_batch_bytes_device(
    e_b: jax.Array,  # (B, 32) uint8 big-endian digests
    r_b: jax.Array,  # (B, 32) uint8 big-endian r
    s_b: jax.Array,  # (B, 32) uint8 big-endian s
    kx: jax.Array,  # (20, K) uint32 limb columns of the DISTINCT keys
    ky: jax.Array,
    key_idx: jax.Array,  # (B,) int32 lane -> key column
    valid_in: jax.Array,  # (B,) bool
) -> jax.Array:
    e = bytes_to_limbs_device(e_b)
    r = bytes_to_limbs_device(r_b)
    s = bytes_to_limbs_device(s_b)
    qx = jnp.take(kx, key_idx, axis=1)
    qy = jnp.take(ky, key_idx, axis=1)
    return verify_batch_device(e, r, s, qx, qy, valid_in)


verify_batch_bytes_jit = jax.jit(verify_batch_bytes_device)
