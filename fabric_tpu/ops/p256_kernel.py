"""Batched ECDSA-P-256 verification as one XLA program.

This is the TPU replacement for the per-endorsement `ecdsa.Verify` hot loop
the reference burns CPU on (reference: common/policies/policy.go:369-399 ->
msp/identities.go:169 -> bccsp/sw/ecdsa.go:41; SURVEY.md §3.1 "HOT").
Instead of one goroutine per transaction (reference v20/validator.go:193-208),
we flatten (tx × endorsement) into one padded batch dimension and verify the
whole block in a single fixed-shape device program.

Math layout:

- field elements: Montgomery residues in 20×13-bit limbs, limb-major
  ``(20, B)`` (see fabric_tpu.ops.bignum);
- point arithmetic: *complete* projective formulas for a=-3 short
  Weierstrass curves (Renes–Costello–Batina, EUROCRYPT 2016, algs 4/6).
  Complete formulas have no special cases for infinity/doubling, which is
  exactly what a branch-free SIMD batch needs;
- scalar recomposition: u1*G + u2*Q with 4-bit fixed windows, MSB-first.
  The G part uses a host-precomputed 64×16-entry comb table (G is a global
  constant); the Q part builds a per-lane 16-entry table of small multiples;
- scalar inversion s^-1 mod n and the final Z^-1 mod p use Fermat
  exponentiation (branch-free square-and-multiply over static exponent
  bits).

The per-lane boolean output is bit-exact with the reference's
`ecdsa.Verify` decision; DER parsing, the low-S rule and r/s range checks
happen host-side (cheap, irregular) and arrive here as the `valid_in` mask.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.crypto import p256
from fabric_tpu.ops import bignum as bn

CTX_P = bn.MontCtx(p256.P)
CTX_N = bn.MontCtx(p256.N)

_R = 1 << bn.RADIX_BITS
B_MONT = bn.int_to_limbs((p256.B * _R) % p256.P)
N_LIMBS = bn.int_to_limbs(p256.N)

WINDOW_BITS = 4
NUM_WINDOWS = 64  # 256 bits / 4


class FE(NamedTuple):
    """A mod-p field element with a static value bound (value < bound * p).

    Bounds are tracked at trace time so the lazy-reduction discipline of the
    RCB formulas is machine-checked: `mul` requires bound products <= 16
    (then a single conditional subtract renormalizes), `add` accumulates
    bounds, `sub` renormalizes to canonical.
    """

    limbs: jax.Array
    bound: int


def fe(limbs: jax.Array, bound: int = 1) -> FE:
    return FE(limbs, bound)


def fe_mul(a: FE, b: FE) -> FE:
    assert a.bound * b.bound <= 16, (a.bound, b.bound)
    return FE(bn.mont_mul(CTX_P, a.limbs, b.limbs, nreduce=1), 1)


def fe_add(a: FE, b: FE) -> FE:
    assert a.bound + b.bound <= 8, (a.bound, b.bound)
    return FE(bn.add_raw(a.limbs, b.limbs), a.bound + b.bound)


def fe_sub(a: FE, b: FE) -> FE:
    # a - b + bound(b)*p, then conditional subtracts back to canonical.
    return FE(
        bn.sub_mod(CTX_P, a.limbs, b.limbs, b.bound, nreduce=a.bound + b.bound - 1), 1
    )


def fe_norm(a: FE) -> FE:
    return FE(bn.reduce_canonical(a.limbs, CTX_P, a.bound - 1), 1)


def _const_fe(value_mod_p: int, like: jax.Array) -> FE:
    return FE(bn._bc(bn.int_to_limbs(value_mod_p), like), 1)


class Point(NamedTuple):
    """Projective (X:Y:Z), coordinates in the Montgomery domain."""

    x: FE
    y: FE
    z: FE


def point_identity(like: jax.Array) -> Point:
    one_m = (_R % p256.P)
    return Point(_const_fe(0, like), _const_fe(one_m, like), _const_fe(0, like))


def _b_fe(like: jax.Array) -> FE:
    return FE(bn._bc(B_MONT, like), 1)


def point_add(p: Point, q: Point) -> Point:
    """Complete addition, RCB 2016 algorithm 4 (a = -3). Handles identity
    and p == q with no branches."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    bb = _b_fe(x1.limbs)

    t0 = fe_mul(x1, x2)
    t1 = fe_mul(y1, y2)
    t2 = fe_mul(z1, z2)
    t3 = fe_add(x1, y1)
    t4 = fe_add(x2, y2)
    t3 = fe_mul(t3, t4)
    t4 = fe_add(t0, t1)
    t3 = fe_sub(t3, t4)
    t4 = fe_add(y1, z1)
    t5 = fe_add(y2, z2)
    t4 = fe_mul(t4, t5)
    t5 = fe_add(t1, t2)
    t4 = fe_sub(t4, t5)
    x3 = fe_add(x1, z1)
    y3 = fe_add(x2, z2)
    x3 = fe_mul(x3, y3)
    y3 = fe_add(t0, t2)
    y3 = fe_sub(x3, y3)
    z3 = fe_mul(bb, t2)
    x3 = fe_sub(y3, z3)
    z3 = fe_add(x3, x3)
    x3 = fe_add(x3, z3)
    z3 = fe_sub(t1, x3)
    x3 = fe_add(t1, x3)  # bound 4
    y3 = fe_mul(bb, y3)
    t1 = fe_add(t2, t2)
    t2 = fe_add(t1, t2)
    y3 = fe_sub(y3, t2)
    y3 = fe_sub(y3, t0)
    t1 = fe_add(y3, y3)
    y3 = fe_add(t1, y3)  # bound 3
    t1 = fe_add(t0, t0)
    t0 = fe_add(t1, t0)
    t0 = fe_sub(t0, t2)
    t1 = fe_mul(t4, y3)
    t2 = fe_mul(t0, y3)
    y3 = fe_mul(x3, z3)
    y3 = fe_add(y3, t2)
    x3 = fe_mul(t3, x3)
    x3 = fe_sub(x3, t1)
    z3 = fe_mul(t4, z3)
    t1 = fe_mul(t3, t0)
    z3 = fe_add(z3, t1)
    return Point(x3, fe_norm(y3), fe_norm(z3))


def point_double(p: Point) -> Point:
    """Complete doubling, RCB 2016 algorithm 6 (a = -3)."""
    x, y, z = p
    bb = _b_fe(x.limbs)

    t0 = fe_mul(x, x)
    t1 = fe_mul(y, y)
    t2 = fe_mul(z, z)
    t3 = fe_mul(x, y)
    t3 = fe_add(t3, t3)
    z3 = fe_mul(x, z)
    z3 = fe_add(z3, z3)
    y3 = fe_mul(bb, t2)
    y3 = fe_sub(y3, z3)
    x3 = fe_add(y3, y3)
    y3 = fe_add(x3, y3)  # bound 3
    x3 = fe_sub(t1, y3)
    y3 = fe_add(t1, y3)  # bound 4
    y3 = fe_mul(x3, y3)
    x3 = fe_mul(x3, t3)
    t3 = fe_add(t2, t2)
    t2 = fe_add(t2, t3)  # bound 3
    z3 = fe_mul(bb, z3)
    z3 = fe_sub(z3, t2)
    z3 = fe_sub(z3, t0)
    t3 = fe_add(z3, z3)
    z3 = fe_add(z3, t3)  # bound 3
    t3 = fe_add(t0, t0)
    t0 = fe_add(t3, t0)
    t0 = fe_sub(t0, t2)
    t0 = fe_mul(t0, z3)
    y3 = fe_add(y3, t0)
    t0 = fe_mul(y, z)
    t0 = fe_add(t0, t0)
    z3 = fe_mul(t0, z3)
    x3 = fe_sub(x3, z3)
    z3 = fe_mul(t0, t1)
    z3 = fe_add(z3, z3)
    z3 = fe_add(z3, z3)  # bound 4
    return Point(x3, fe_norm(y3), fe_norm(z3))


# ---------------------------------------------------------------------------
# Fixed-base comb table for G (host precompute)
# ---------------------------------------------------------------------------

_G_TABLE: np.ndarray | None = None


def g_small_table() -> np.ndarray:
    """(16, 3, 20) uint32: entry d = projective Montgomery coords of d*G.

    Used inside the Horner window loop (R = 16R + d1*G + d2*Q): everything
    added at window w is scaled by the remaining doublings, so the table
    holds *plain* small multiples — a pre-scaled comb table would get
    double-scaled.
    """
    global _G_TABLE
    if _G_TABLE is not None:
        return _G_TABLE

    one_m = _R % p256.P
    table = np.zeros((16, 3, bn.NLIMBS), dtype=np.uint32)
    table[0, 1] = bn.int_to_limbs(one_m)  # identity (0 : R : 0)
    acc = None
    for d in range(1, 16):
        acc = p256.point_add(acc, p256.GENERATOR)
        x, y = acc
        table[d, 0] = bn.int_to_limbs((x * _R) % p256.P)
        table[d, 1] = bn.int_to_limbs((y * _R) % p256.P)
        table[d, 2] = bn.int_to_limbs(one_m)
    _G_TABLE = table
    return table


# ---------------------------------------------------------------------------
# Scalar digit extraction
# ---------------------------------------------------------------------------


def scalar_digits_msb(u: jax.Array) -> jax.Array:
    """(20, B) canonical limbs -> (64, B) 4-bit digits, MSB window first."""
    digits = []
    for w in range(NUM_WINDOWS):  # w = 0 is the most significant window
        bit = (NUM_WINDOWS - 1 - w) * WINDOW_BITS
        limb, off = divmod(bit, bn.LIMB_BITS)
        d = u[limb] >> off
        if off > bn.LIMB_BITS - WINDOW_BITS and limb + 1 < bn.NLIMBS:
            d = d | (u[limb + 1] << (bn.LIMB_BITS - off))
        digits.append(d & (16 - 1))
    return jnp.stack(digits, axis=0)


def _one_hot_select(table: jax.Array, idx: jax.Array) -> Tuple[jax.Array, ...]:
    """table (16, 3, 20, B) or (16, 3, 20); idx (B,) -> three (20, B) arrays."""
    oh = (jnp.arange(16, dtype=jnp.uint32)[:, None] == idx[None, :]).astype(jnp.uint32)
    if table.ndim == 4:  # per-lane table
        sel = (table * oh[:, None, None, :]).sum(axis=0)  # (3, 20, B)
    else:  # shared constant table
        sel = jnp.einsum("kcl,kb->clb", table, oh)  # (3, 20, B)
    return sel[0], sel[1], sel[2]


# ---------------------------------------------------------------------------
# The batched verifier
# ---------------------------------------------------------------------------


def verify_batch_device(
    e: jax.Array,
    r: jax.Array,
    s: jax.Array,
    qx: jax.Array,
    qy: jax.Array,
    valid_in: jax.Array,
) -> jax.Array:
    """Core batched verify. All limb inputs (20, B) uint32 canonical;
    valid_in (B,) bool (host prechecks: DER ok, low-S, 1 <= r,s < n, Q on
    curve). Returns (B,) bool.

    Semantics (Go crypto/ecdsa.Verify): w = s^-1 mod n; u1 = e*w; u2 = r*w;
    (x, y) = u1*G + u2*Q; accept iff the sum is not infinity and
    x mod n == r.
    """
    batch = e.shape[1:]

    # --- scalar field: u1 = e/s, u2 = r/s (mod n) ---
    s_m = bn.to_mont(CTX_N, s)
    s_inv = bn.mont_pow(CTX_N, s_m, p256.N - 2)
    e_m = bn.to_mont(CTX_N, e)  # e < 2^256 (may exceed n; to_mont reduces)
    r_m = bn.to_mont(CTX_N, r)
    u1 = bn.from_mont(CTX_N, bn.mont_mul(CTX_N, e_m, s_inv))
    u2 = bn.from_mont(CTX_N, bn.mont_mul(CTX_N, r_m, s_inv))

    d1 = scalar_digits_msb(u1)  # (64, B)
    d2 = scalar_digits_msb(u2)

    # --- per-lane table of small multiples of Q ---
    q_pt = Point(
        fe(bn.to_mont(CTX_P, qx)),
        fe(bn.to_mont(CTX_P, qy)),
        _const_fe(_R % p256.P, qx),
    )

    def _pack(p: Point) -> jax.Array:
        return jnp.stack([p.x.limbs, p.y.limbs, p.z.limbs], axis=0)

    def _unpack(a: jax.Array) -> Point:
        return Point(fe(a[0]), fe(a[1]), fe(a[2]))

    def tab_body(acc, _):
        pt = _unpack(acc)
        return _pack(point_add(pt, q_pt)), acc

    _, q_multiples = lax.scan(tab_body, _pack(q_pt), None, length=15)
    ident_row = _pack(point_identity(qx))[None]
    q_table = jnp.concatenate([ident_row, q_multiples], axis=0)  # (16, 3, 20, B)

    # --- main window loop: R = 16R + d1*G + d2*Q, MSB first (Horner) ---
    g_table = jnp.asarray(g_small_table())  # (16, 3, 20)

    def win_body(carry, xs):
        d1w, d2w = xs
        acc = _unpack(carry)
        for _ in range(WINDOW_BITS):
            acc = point_double(acc)
        qx_s, qy_s, qz_s = _one_hot_select(q_table, d2w)
        acc = point_add(acc, Point(fe(qx_s), fe(qy_s), fe(qz_s)))
        gx_s, gy_s, gz_s = _one_hot_select(g_table, d1w)
        acc = point_add(acc, Point(fe(gx_s), fe(gy_s), fe(gz_s)))
        return _pack(acc), None

    carry, _ = lax.scan(win_body, _pack(point_identity(qx)), (d1, d2))
    acc = _unpack(carry)

    # --- affine x and the final comparison ---
    z_inv = bn.mont_pow(CTX_P, acc.z.limbs, p256.P - 2)
    x_aff = bn.from_mont(CTX_P, bn.mont_mul(CTX_P, acc.x.limbs, z_inv))
    r_plus_n, _ = bn.carry_u32(r + bn._bc(N_LIMBS, r))  # value < 2^257, fits
    matches = bn.eq_limbs(x_aff, r) | bn.eq_limbs(x_aff, r_plus_n)
    not_inf = ~bn.is_zero(acc.z.limbs)
    return valid_in & not_inf & matches


verify_batch_jit = jax.jit(verify_batch_device)
