"""Device kernels: limb bignum, batched P-256 ECDSA verify, SHA-256."""
