"""Bounded accelerator probe.

`jax.devices()` initializes the backend on first call; when the
accelerator is reached through a tunnel (this topology) a dead or
stalled tunnel makes that call HANG — round 4's benchmark died with
rc=1 on an UNAVAILABLE raise, and a judge re-run then hung >25 minutes
inside the same first device call. Everything that *optionally* uses
the device (bccsp.default_provider, bench.py, CLI probes) must go
through this module instead of calling jax.devices() inline.

The probe runs in a daemon thread and is cached for the process:
- first call starts the thread and waits up to `timeout_s`;
- a timeout returns None but leaves the thread probing, so a *slow*
  (rather than dead) backend flips later calls to success;
- a raise inside the probe (UNAVAILABLE at init) is cached as failure.

Reference contrast: the reference's bccsp factory (bccsp/factory,
SURVEY §2.1) probes PKCS#11 libraries synchronously because a local
.so either loads or errors instantly; a remote accelerator has the
third state — hung — which is the one that needs the thread.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

_lock = threading.Lock()
_thread: Optional[threading.Thread] = None
_state = {"status": "unknown", "devices": None, "error": None}


def _worker() -> None:
    try:
        import jax

        devs = jax.devices()
        with _lock:
            _state["status"] = "ok"
            _state["devices"] = devs
    except Exception as exc:  # noqa: BLE001 - cache any init failure
        with _lock:
            _state["status"] = "error"
            _state["error"] = str(exc)


def default_timeout() -> float:
    return float(os.environ.get("FABRIC_TPU_PROBE_TIMEOUT_S", "60"))


def probe_devices(timeout_s: Optional[float] = None) -> Optional[List]:
    """jax.devices() bounded by `timeout_s` (default
    FABRIC_TPU_PROBE_TIMEOUT_S or 60s). None = not available (yet)."""
    global _thread
    if timeout_s is None:
        timeout_s = default_timeout()
    with _lock:
        if _state["status"] == "ok":
            return _state["devices"]
        if _state["status"] == "error":
            return None
        if _thread is None:
            _thread = threading.Thread(
                target=_worker, name="device-probe", daemon=True
            )
            _thread.start()
        t = _thread
    t.join(timeout_s)
    with _lock:
        return _state["devices"] if _state["status"] == "ok" else None


def probe_error() -> Optional[str]:
    """The cached init error, or a timeout pseudo-error, or None if the
    probe succeeded / hasn't concluded."""
    with _lock:
        if _state["status"] == "error":
            return _state["error"]
        if _state["status"] == "unknown" and _thread is not None:
            return "device probe timed out (backend init hung)"
        return None


def accelerator_present(timeout_s: Optional[float] = None) -> bool:
    devs = probe_devices(timeout_s)
    return bool(devs) and any(d.platform != "cpu" for d in devs)


# -- out-of-process probe ---------------------------------------------------
#
# The daemon-thread probe above bounds the CALLER's wait but cannot kill
# a backend init that wedges (round-5: the thread sat inside a hung
# tunnel forever, and the "timed out" pseudo-error was re-derived per
# caller).  The subprocess probe gets a HARD bound — the kernel kills
# the child — at the cost of a fresh interpreter + jax import per cold
# probe (~10s on a healthy box), so it suits batch/CLI entrypoints
# (bench.py) rather than the library path: bccsp.default_provider keeps
# the cheap in-process probe, whose worst case is one wedged daemon
# thread in a process that has already degraded to the software
# provider.

_sub_state: dict = {}


def probe_subprocess(timeout_s: float):
    """(ok, error): ok iff a non-CPU accelerator answered from a freshly
    spawned python within timeout_s.  Cached for the process."""
    if "verdict" in _sub_state:
        return _sub_state["verdict"]
    import json
    import subprocess
    import sys

    code = (
        "import json, sys\n"
        "import jax\n"
        "print(json.dumps([d.platform for d in jax.devices()]))\n"
    )
    ok, error = False, None
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        if res.returncode == 0:
            try:
                platforms = json.loads(
                    res.stdout.strip().splitlines()[-1]
                )
                ok = any(p != "cpu" for p in platforms)
                if not ok:
                    error = (
                        f"no accelerator device (platforms={platforms})"
                    )
            except (ValueError, IndexError):
                error = f"probe emitted garbage: {res.stdout[:200]!r}"
        else:
            error = (res.stderr or res.stdout or "probe failed")[-300:]
    except subprocess.TimeoutExpired:
        error = (
            f"device probe subprocess exceeded {timeout_s:.0f}s "
            "(backend init hung) and was killed"
        )
    except Exception as exc:  # noqa: BLE001 - probing must never raise
        error = f"probe subprocess error: {exc}"[:300]
    _sub_state["verdict"] = (ok, error)
    return ok, error
