"""Bounded accelerator probe.

`jax.devices()` initializes the backend on first call; when the
accelerator is reached through a tunnel (this topology) a dead or
stalled tunnel makes that call HANG — round 4's benchmark died with
rc=1 on an UNAVAILABLE raise, and a judge re-run then hung >25 minutes
inside the same first device call. Everything that *optionally* uses
the device (bccsp.default_provider, bench.py, CLI probes) must go
through this module instead of calling jax.devices() inline.

The probe runs in a daemon thread and is cached for the process:
- first call starts the thread and waits up to `timeout_s`;
- a timeout returns None but leaves the thread probing, so a *slow*
  (rather than dead) backend flips later calls to success;
- a raise inside the probe (UNAVAILABLE at init) is cached as failure.

Reference contrast: the reference's bccsp factory (bccsp/factory,
SURVEY §2.1) probes PKCS#11 libraries synchronously because a local
.so either loads or errors instantly; a remote accelerator has the
third state — hung — which is the one that needs the thread.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

_lock = threading.Lock()
_thread: Optional[threading.Thread] = None
_state = {"status": "unknown", "devices": None, "error": None}


def _worker() -> None:
    try:
        import jax

        devs = jax.devices()
        with _lock:
            _state["status"] = "ok"
            _state["devices"] = devs
    except Exception as exc:  # noqa: BLE001 - cache any init failure
        with _lock:
            _state["status"] = "error"
            _state["error"] = str(exc)


def default_timeout() -> float:
    return float(os.environ.get("FABRIC_TPU_PROBE_TIMEOUT_S", "60"))


def probe_devices(timeout_s: Optional[float] = None) -> Optional[List]:
    """jax.devices() bounded by `timeout_s` (default
    FABRIC_TPU_PROBE_TIMEOUT_S or 60s). None = not available (yet)."""
    global _thread
    if timeout_s is None:
        timeout_s = default_timeout()
    with _lock:
        if _state["status"] == "ok":
            return _state["devices"]
        if _state["status"] == "error":
            return None
        if _thread is None:
            _thread = threading.Thread(
                target=_worker, name="device-probe", daemon=True
            )
            _thread.start()
        t = _thread
    t.join(timeout_s)
    with _lock:
        return _state["devices"] if _state["status"] == "ok" else None


def probe_error() -> Optional[str]:
    """The cached init error, or a timeout pseudo-error, or None if the
    probe succeeded / hasn't concluded."""
    with _lock:
        if _state["status"] == "error":
            return _state["error"]
        if _state["status"] == "unknown" and _thread is not None:
            return "device probe timed out (backend init hung)"
        return None


def accelerator_present(timeout_s: Optional[float] = None) -> bool:
    devs = probe_devices(timeout_s)
    return bool(devs) and any(d.platform != "cpu" for d in devs)
