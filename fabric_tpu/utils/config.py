"""Node-config environment overrides (reference viper behavior: the
sampleconfig YAMLs are overridable with CORE_* / ORDERER_* variables,
core/peer/config.go + orderer/common/localconfig — e.g.
CORE_PEER_LISTENADDRESS=0.0.0.0:7051 overrides peer.listenAddress).

Mapping rule (viper's EnvKeyReplacer): strip the prefix, split on "_",
walk the config tree matching segments case-insensitively against
existing keys.  Only EXISTING scalar leaves are overridden — unknown
paths are ignored (viper would create them, but silently materializing
typo'd keys into live config is the part of viper nobody wants).
Values parse as YAML scalars so booleans/ints come through typed.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import yaml


def apply_env_overrides(
    cfg: Dict, prefix: str, env: Optional[Dict[str, str]] = None
) -> Dict:
    """Mutates and returns ``cfg`` with ``<prefix>_SECTION_KEY=value``
    overrides applied (case-insensitive key matching, nested via '_')."""
    env = os.environ if env is None else env
    want = prefix.upper() + "_"
    for name, value in env.items():
        if not name.upper().startswith(want):
            continue
        segments = name[len(want):].split("_")
        if not segments:
            continue
        _apply_one(cfg, segments, value)
    return cfg


def _apply_one(node: Dict, segments, value: str) -> None:
    # keys themselves may contain no underscores in our YAMLs, so each
    # env segment matches exactly one key level; a segment that matches
    # nothing aborts the override (unknown path)
    for i, seg in enumerate(segments):
        if not isinstance(node, dict):
            return
        key = _match_key(node, seg)
        if key is None:
            return
        if i == len(segments) - 1:
            if isinstance(node[key], dict):
                return  # refuse to replace a whole section with a scalar
            try:
                node[key] = yaml.safe_load(value)
            except yaml.YAMLError:
                node[key] = value
            return
        node = node[key]


def _match_key(node: Dict, segment: str) -> Optional[str]:
    seg = segment.lower()
    for key in node:
        if isinstance(key, str) and key.lower() == seg:
            return key
    return None
