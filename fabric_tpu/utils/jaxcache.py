"""Persistent XLA compilation cache setup, shared by bench.py,
tests/conftest.py and __graft_entry__.py.

The driver environment imports jax at interpreter startup (an axon
sitecustomize registers the TPU-tunnel PJRT plugin), so cache env vars
set by our entry points latch too late — jax.config.update is read
dynamically and is the only reliable path. First-ever compiles of the
ECDSA verify kernel cost minutes (XLA:CPU and the axon remote-compile
tunnel alike); cached runs are seconds, and the cache directory survives
rounds on disk while staying out of git.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CACHE_DIR = os.path.join(REPO_ROOT, ".jax_cache")


def enable_compile_cache(cache_dir: str = CACHE_DIR) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def pin_cpu_mesh(n_devices: int = 8) -> None:
    """Pin jax to the host-CPU platform with >= n_devices virtual devices.

    Must run before ANY backend/array initialisation: the driver/test
    environment preloads an axon TPU plugin whose AOT client can be
    version-skewed against the terminal (round-1 MULTICHIP failure:
    `libtpu version mismatch` raised inside device_put), so sharding
    checks run on a hermetic CPU mesh and never touch the accelerator
    client. If XLA_FLAGS already forces a host device count (conftest,
    driver), that wins; otherwise use the dynamic config key.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    if "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""
    ):
        return
    if hasattr(jax.config, "jax_num_cpu_devices"):
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except Exception:
            # Backend already initialised (called twice in-process):
            # an in-process no-op by design — callers assert on the
            # resulting device count.  Do NOT fall through to the env
            # route: mutating XLA_FLAGS here would leak a forced device
            # count into every later-spawned subprocess.
            pass
        return
    # This jax predates the dynamic key (0.4.37 has no
    # jax_num_cpu_devices — the bench n_devices sweep found the silent
    # no-op).  XLA_FLAGS is still honored because no backend exists
    # until the first jax use; if one already exists this is a no-op
    # and the caller's device-count assertion reports it.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
