"""ctypes bindings for the C++ host runtime (native/fabric_native.cc).

The native library accelerates the irregular byte work feeding the TPU
kernels — batched SHA-256 and strict-DER ECDSA signature parsing — and
is optional: when the shared object is missing (or the build toolchain
is absent) every entry point falls back to the pure-Python
implementation with identical semantics, so nothing above this module
needs to care. Build with ``make -C native`` (attempted automatically
once per process).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fabric_tpu.common.flogging import must_get_logger

logger = must_get_logger("native")

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SO_PATH = os.path.join(_REPO, "native", "libfabric_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(
                    ["make", "-C", os.path.dirname(_SO_PATH)],
                    capture_output=True,
                    timeout=120,
                    check=True,
                )
            except Exception as exc:
                logger.warning(
                    "native library build failed (%s); using the Python "
                    "parsers", exc,
                )
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        if not hasattr(lib, "fn_block_parse"):
            # stale prebuilt .so predating the block parser: rebuild and
            # reload. Safe because the Makefile compiles to a temp file
            # and renames — the inode the stale handle has mapped is
            # never rewritten (no SIGBUS), and the renamed path is a NEW
            # inode, so dlopen (which dedups by dev:ino) returns a fresh
            # handle rather than the stale one. On any failure the stale
            # handle keeps serving der/sha and block parsing falls back
            # to the Python parser (consumers gate on hasattr).
            try:
                subprocess.run(
                    ["make", "-C", os.path.dirname(_SO_PATH), "-B"],
                    capture_output=True,
                    timeout=120,
                    check=True,
                )
                lib = ctypes.CDLL(_SO_PATH)
            except Exception as exc:
                logger.warning(
                    "stale native library rebuild failed (%s); block "
                    "parsing falls back to the Python parser", exc,
                )
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.fn_batch_sha256.argtypes = [u8p, u64p, u64p, ctypes.c_int64, u8p]
        lib.fn_batch_sha256.restype = None
        lib.fn_batch_der_parse.argtypes = [
            u8p, u64p, u64p, ctypes.c_int64, u8p, u8p, u8p, u8p,
        ]
        lib.fn_batch_der_parse.restype = None
        try:
            i32p = ctypes.POINTER(ctypes.c_int32)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.fn_block_parse.argtypes = [u8p, u64p, u64p, ctypes.c_int64]
            lib.fn_block_parse.restype = ctypes.c_void_p
            lib.fn_block_counts.argtypes = [ctypes.c_void_p, i64p]
            lib.fn_block_counts.restype = None
            lib.fn_block_pertx.argtypes = [
                ctypes.c_void_p, i32p, i32p, u8p, u64p,
            ]
            lib.fn_block_pertx.restype = None
            lib.fn_block_jobs.argtypes = [
                ctypes.c_void_p, i64p, i64p, u8p, u64p, u64p, u8p,
            ]
            lib.fn_block_jobs.restype = None
            lib.fn_block_uniq.argtypes = [ctypes.c_void_p, u64p]
            lib.fn_block_uniq.restype = None
            lib.fn_block_ns.argtypes = [ctypes.c_void_p, i64p, u8p, u64p]
            lib.fn_block_ns.restype = None
            lib.fn_block_wkeys.argtypes = [
                ctypes.c_void_p, i64p, i64p, u8p, u64p, u64p,
            ]
            lib.fn_block_wkeys.restype = None
            lib.fn_block_free.argtypes = [ctypes.c_void_p]
            lib.fn_block_free.restype = None
            lib.fn_sha256_backend.restype = ctypes.c_int
        except AttributeError:
            # still missing after the rebuild attempt above: serve
            # der/sha only; block parsing uses the Python fallback
            pass
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _pack(chunks: Sequence[bytes]):
    lens = np.array([len(c) for c in chunks], dtype=np.uint64)
    offsets = np.zeros(len(chunks), dtype=np.uint64)
    if len(chunks) > 1:
        offsets[1:] = np.cumsum(lens[:-1])
    blob = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    if blob.size == 0:
        blob = np.zeros(1, dtype=np.uint8)
    return blob, offsets, lens


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def batch_sha256(msgs: Sequence[bytes]) -> np.ndarray:
    """(N, 32) uint8 digests."""
    n = len(msgs)
    if n == 0:
        return np.zeros((0, 32), dtype=np.uint8)
    lib = _load()
    if lib is None:
        import hashlib

        return np.frombuffer(
            b"".join(hashlib.sha256(m).digest() for m in msgs), dtype=np.uint8
        ).reshape(n, 32)
    blob, offsets, lens = _pack(msgs)
    out = np.zeros((n, 32), dtype=np.uint8)
    lib.fn_batch_sha256(
        _u8(blob), _u64(offsets), _u64(lens), n, _u8(out)
    )
    return out


def batch_der_parse(
    sigs: Sequence[bytes],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(r[N,32], s[N,32], ok[N], low_s[N]) — ok=0 for malformed DER or
    out-of-range values; low_s mirrors utils.IsLowS (s <= n/2)."""
    n = len(sigs)
    r = np.zeros((n, 32), dtype=np.uint8)
    s = np.zeros((n, 32), dtype=np.uint8)
    ok = np.zeros(n, dtype=np.uint8)
    low_s = np.zeros(n, dtype=np.uint8)
    if n == 0:
        return r, s, ok, low_s
    lib = _load()
    if lib is None:
        from fabric_tpu.common import der, p256

        for i, sig in enumerate(sigs):
            try:
                ri, si = der.unmarshal_signature(sig)
            except Exception:
                continue
            if not (1 <= ri < p256.N and 1 <= si < p256.N):
                continue
            ok[i] = 1
            low_s[i] = 1 if p256.is_low_s(si) else 0
            r[i] = np.frombuffer(ri.to_bytes(32, "big"), dtype=np.uint8)
            s[i] = np.frombuffer(si.to_bytes(32, "big"), dtype=np.uint8)
        return r, s, ok, low_s
    blob, offsets, lens = _pack(sigs)
    lib.fn_batch_der_parse(
        _u8(blob), _u64(offsets), _u64(lens), n,
        _u8(r), _u8(s), _u8(ok), _u8(low_s),
    )
    return r, s, ok, low_s
