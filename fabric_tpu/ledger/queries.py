"""Rich (selector) queries over JSON state values.

The reference delegates rich queries to CouchDB's Mango selector language
(reference core/ledger/kvledger/txmgmt/statedb/statecouchdb/statecouchdb.go:695
ExecuteQuery; query syntax per CouchDB /_find). Here the selector engine is
embedded: the same JSON selector documents are evaluated directly over the
namespace's rows, so rich queries need no external database. Like the
reference, rich-query results are NOT phantom-protected — they add no
range read to the rwset (documented Fabric behavior for CouchDB queries).

Supported (the subset Fabric chaincodes actually use): implicit-AND field
matches, dotted paths, $eq $ne $gt $gte $lt $lte $in $nin $exists $regex
$size $type, combinators $and $or $not $nor, arrays via $elemMatch, plus
top-level limit / skip / sort / fields.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple


class QueryError(ValueError):
    """Malformed selector document."""


_TYPE_NAMES = {
    "null": type(None),
    "boolean": bool,
    "number": (int, float),
    "string": str,
    "array": list,
    "object": dict,
}


def parse_query(query) -> Dict[str, Any]:
    """Query string/dict -> normalized {selector, limit, skip, sort, fields}."""
    if isinstance(query, (str, bytes)):
        try:
            query = json.loads(query)
        except json.JSONDecodeError as e:
            raise QueryError(f"invalid query JSON: {e}") from e
    if not isinstance(query, dict):
        raise QueryError("query must be a JSON object")
    if "selector" not in query:
        raise QueryError('query missing "selector"')
    out = {
        "selector": query["selector"],
        "limit": query.get("limit"),
        "skip": query.get("skip", 0),
        "sort": query.get("sort"),
        "fields": query.get("fields"),
    }
    if not isinstance(out["selector"], dict):
        raise QueryError("selector must be an object")
    return out


def _lookup(doc: Any, path: str):
    """Dotted-path lookup; returns (found, value)."""
    cur = doc
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return False, None
    return True, cur


def _cmp_ok(a, b) -> bool:
    """CouchDB compares within type families; cross-type comparisons
    simply don't match here."""
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool)
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return True
    return type(a) is type(b) and isinstance(a, (str, int, float))


def _match_op(op: str, cond, value, found: bool) -> bool:
    if op == "$exists":
        return found is bool(cond) or found == bool(cond)
    if not found:
        return False
    if op == "$eq":
        return value == cond
    if op == "$ne":
        return value != cond
    if op in ("$gt", "$gte", "$lt", "$lte"):
        if not _cmp_ok(value, cond):
            return False
        if op == "$gt":
            return value > cond
        if op == "$gte":
            return value >= cond
        if op == "$lt":
            return value < cond
        return value <= cond
    if op == "$in":
        return isinstance(cond, list) and value in cond
    if op == "$nin":
        return isinstance(cond, list) and value not in cond
    if op == "$regex":
        return isinstance(value, str) and re.search(cond, value) is not None
    if op == "$size":
        return isinstance(value, list) and len(value) == cond
    if op == "$type":
        t = _TYPE_NAMES.get(cond)
        if t is None:
            raise QueryError(f"unknown $type {cond!r}")
        if cond == "number":
            return isinstance(value, t) and not isinstance(value, bool)
        return isinstance(value, t)
    if op == "$elemMatch":
        return isinstance(value, list) and any(
            matches(cond, el) if isinstance(el, dict) else _field_match(el, cond)
            for el in value
        )
    raise QueryError(f"unsupported operator {op!r}")


def _field_match(value, cond) -> bool:
    """Scalar-vs-condition for $elemMatch over scalar arrays."""
    if isinstance(cond, dict):
        return all(_match_op(op, c, value, True) for op, c in cond.items())
    return value == cond


def matches(selector: Dict[str, Any], doc: Any) -> bool:
    """Does `doc` satisfy `selector` (implicit AND across entries)?"""
    for field, cond in selector.items():
        if field == "$and":
            if not all(matches(s, doc) for s in cond):
                return False
        elif field == "$or":
            if not any(matches(s, doc) for s in cond):
                return False
        elif field == "$nor":
            if any(matches(s, doc) for s in cond):
                return False
        elif field == "$not":
            if matches(cond, doc):
                return False
        elif field.startswith("$"):
            raise QueryError(f"unsupported combinator {field!r}")
        else:
            found, value = _lookup(doc, field)
            if isinstance(cond, dict) and any(
                k.startswith("$") for k in cond
            ):
                for op, c in cond.items():
                    if not _match_op(op, c, value, found):
                        return False
            else:
                if not found or value != cond:
                    return False
    return True


def execute(
    rows: Iterable[Tuple[str, bytes]], query
) -> List[Tuple[str, bytes]]:
    """Run a parsed/raw query over (key, value_bytes) rows. Non-JSON
    values never match (CouchDB stores them as attachments, invisible to
    selectors). Returns (key, value_bytes) with `fields` projection
    applied to the returned JSON when requested."""
    q = parse_query(query)
    selector = q["selector"]
    hits: List[Tuple[str, bytes, Any]] = []
    for key, raw in rows:
        try:
            doc = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if matches(selector, doc):
            hits.append((key, raw, doc))

    if q["sort"]:
        for spec in reversed(q["sort"]):
            if isinstance(spec, str):
                field, direction = spec, "asc"
            else:
                (field, direction), = spec.items()
            hits.sort(
                key=lambda h, f=field: _sort_key(h[2], f),
                reverse=(direction == "desc"),
            )
    if q["skip"]:
        hits = hits[q["skip"]:]
    if q["limit"] is not None:
        hits = hits[: q["limit"]]

    out: List[Tuple[str, bytes]] = []
    for key, raw, doc in hits:
        if q["fields"]:
            proj = {f: doc[f] for f in q["fields"] if f in doc}
            out.append((key, json.dumps(proj, sort_keys=True).encode()))
        else:
            out.append((key, raw))
    return out


def _sort_key(doc, field):
    found, v = _lookup(doc, field)
    # sort groups: missing < null < bool < number < string
    if not found:
        return (0, 0)
    if v is None:
        return (1, 0)
    if isinstance(v, bool):
        return (2, v)
    if isinstance(v, (int, float)):
        return (3, v)
    if isinstance(v, str):
        return (4, v)
    return (5, json.dumps(v))


# ---------------------------------------------------------------------------
# bookmark pagination (reference statecouchdb.go:567 range pagination /
# :653 ExecuteQueryWithPagination; chaincode GetQueryResultWithPagination)
# ---------------------------------------------------------------------------


def encode_bookmark(offset: int) -> str:
    """Opaque resumption token (CouchDB bookmarks are opaque strings; here
    the payload is the count of result rows already consumed)."""
    import base64

    return base64.urlsafe_b64encode(
        json.dumps({"o": offset}).encode()
    ).decode()


def decode_bookmark(bookmark: str) -> int:
    import base64

    if not bookmark:
        return 0
    try:
        doc = json.loads(base64.urlsafe_b64decode(bookmark.encode()))
        offset = doc["o"]
        if not isinstance(offset, int) or offset < 0:
            raise ValueError
        return offset
    except Exception as e:  # noqa: BLE001
        raise QueryError(f"invalid bookmark {bookmark!r}") from e


def execute_paginated(
    rows: Iterable[Tuple[str, bytes]],
    query,
    page_size: int,
    bookmark: str = "",
) -> Tuple[List[Tuple[str, bytes]], str]:
    """One page of rich-query results plus the next bookmark.

    The page size overrides any `limit`/`skip` in the query document
    (the reference rejects limit+pagination together,
    statecouchdb.go:700 validateQueryMetadata; skip is ignored in favor
    of the bookmark).  The returned bookmark resumes after the last
    returned row; passing it back with the same query and a stable
    snapshot yields the next page.  An exhausted result set returns the
    bookmark pointing past the end (fetched count < page_size tells the
    caller to stop, as with CouchDB)."""
    if page_size <= 0:
        raise QueryError("pageSize must be a positive integer")
    q = parse_query(query)
    if q["limit"] is not None or q["skip"]:
        raise QueryError(
            "limit/skip cannot be combined with pagination (use the "
            "bookmark + pageSize contract)"
        )
    offset = decode_bookmark(bookmark)
    all_hits = execute(
        rows,
        {"selector": q["selector"], "sort": q["sort"], "fields": q["fields"]},
    )
    page = all_hits[offset : offset + page_size]
    return page, encode_bookmark(offset + len(page))
