"""Persistent versioned state + history on an embedded B-tree (sqlite3).

The stateleveldb analog (reference core/ledger/kvledger/txmgmt/statedb/
stateleveldb/stateleveldb.go:185 ApplyUpdates; history db.go:79): state and
the history index live in ONE sqlite file per channel, written atomically
per block together with a savepoint. Restart recovery replays only the
blocks above the savepoint instead of the whole chain (the reference's
recoverDBs contract — state is a derived cache but recovery cost must not
grow with chain length).

sqlite is the idiomatic embedded choice here: it is in the Python stdlib
(no external service, matching the "pure-embedded equivalents" rule of
SURVEY.md §2.12 item 3), its B-tree gives ordered range scans like
LevelDB, and WAL-mode commits are atomic. Rich selector queries
(statecouchdb.go:695) run over the same rows via fabric_tpu.ledger.queries.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterator, List, Optional, Tuple

from fabric_tpu.common.faults import fault_point
from fabric_tpu.ledger import queries as rich_queries
from fabric_tpu.ledger.rwset import Version
from fabric_tpu.ledger.statedb import (
    BatchEntry,
    HashedUpdateBatch,
    PvtUpdateBatch,
    UpdateBatch,
    VersionedValue,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS state (
  ns TEXT NOT NULL, key TEXT NOT NULL,
  value BLOB NOT NULL, block INTEGER NOT NULL, txn INTEGER NOT NULL,
  metadata BLOB,
  PRIMARY KEY (ns, key)
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS hashed (
  ns TEXT NOT NULL, coll TEXT NOT NULL, keyhash BLOB NOT NULL,
  value BLOB NOT NULL, block INTEGER NOT NULL, txn INTEGER NOT NULL,
  metadata BLOB,
  PRIMARY KEY (ns, coll, keyhash)
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS pvt (
  ns TEXT NOT NULL, coll TEXT NOT NULL, key TEXT NOT NULL,
  value BLOB NOT NULL, block INTEGER NOT NULL, txn INTEGER NOT NULL,
  PRIMARY KEY (ns, coll, key)
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS history (
  ns TEXT NOT NULL, key TEXT NOT NULL,
  block INTEGER NOT NULL, txn INTEGER NOT NULL,
  PRIMARY KEY (ns, key, block, txn)
) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS meta (
  k TEXT PRIMARY KEY, v BLOB NOT NULL
) WITHOUT ROWID;
"""


class SqliteVersionedDB:
    """Same read/write surface as statedb.VersionedDB, durably on disk."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        # one connection shared across the peer's threads (endorser gRPC
        # workers read while the commit pipeline writes); sqlite3 objects
        # are not thread-safe, so every access serializes on this lock
        self._lock = threading.RLock()
        self._closed = False
        # coherence stamp for device-resident derived caches
        # (mvcc_device.ResidentDeviceValidator): bumped whenever state is
        # mutated OUT OF BAND of the validator flow (clear / rebuild /
        # rollback), so a resident version table can detect it went stale
        # and must never emit a mask from a dead generation
        self.state_generation = 0
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.executescript(_SCHEMA)
        self._db.commit()

    def bump_generation(self) -> None:
        self.state_generation += 1

    def close(self) -> None:
        """Idempotent (recovery error paths may close twice)."""
        if self._closed:
            return
        self._closed = True
        self._db.close()

    def _one(self, sql, params=()):
        with self._lock:
            return self._db.execute(sql, params).fetchone()

    def _all(self, sql, params=()):
        with self._lock:
            return self._db.execute(sql, params).fetchall()

    # -- savepoint ---------------------------------------------------------
    def savepoint(self) -> Optional[int]:
        """Height of the last block whose writes are durably applied, or
        None for a fresh database (stateleveldb GetLatestSavePoint)."""
        row = self._one("SELECT v FROM meta WHERE k='savepoint'")
        return int(row[0]) if row else None

    def commit_hash(self) -> bytes:
        row = self._one("SELECT v FROM meta WHERE k='commit_hash'")
        return bytes(row[0]) if row else b""

    # -- reads -------------------------------------------------------------
    def get_state(self, ns: str, key: str) -> Optional[VersionedValue]:
        row = self._one(
            "SELECT value, block, txn, metadata FROM state WHERE ns=? AND key=?",
            (ns, key),
        )
        if row is None:
            return None
        return VersionedValue(
            bytes(row[0]),
            Version(row[1], row[2]),
            bytes(row[3]) if row[3] is not None else None,
        )

    def get_state_metadata(self, ns: str, key: str) -> Optional[bytes]:
        vv = self.get_state(ns, key)
        return vv.metadata if vv else None

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get_state(ns, key)
        return vv.version if vv else None

    def get_hashed_state(
        self, ns: str, coll: str, key_hash: bytes
    ) -> Optional[VersionedValue]:
        row = self._one(
            "SELECT value, block, txn, metadata FROM hashed "
            "WHERE ns=? AND coll=? AND keyhash=?",
            (ns, coll, key_hash),
        )
        if row is None:
            return None
        return VersionedValue(
            bytes(row[0]),
            Version(row[1], row[2]),
            bytes(row[3]) if row[3] is not None else None,
        )

    def get_hashed_metadata(
        self, ns: str, coll: str, key_hash: bytes
    ) -> Optional[bytes]:
        vv = self.get_hashed_state(ns, coll, key_hash)
        return vv.metadata if vv else None

    def get_key_hash_version(
        self, ns: str, coll: str, key_hash: bytes
    ) -> Optional[Version]:
        vv = self.get_hashed_state(ns, coll, key_hash)
        return vv.version if vv else None

    def get_private_data(
        self, ns: str, coll: str, key: str
    ) -> Optional[VersionedValue]:
        row = self._one(
            "SELECT value, block, txn FROM pvt WHERE ns=? AND coll=? AND key=?",
            (ns, coll, key),
        )
        if row is None:
            return None
        return VersionedValue(bytes(row[0]), Version(row[1], row[2]))

    def get_state_range(
        self, ns: str, start_key: str, end_key: str, include_end: bool
    ) -> Iterator[Tuple[str, VersionedValue]]:
        """Ordered scan (sqlite BINARY collation == UTF-8 byte order ==
        Python str code-point order, so bounds agree with the in-memory
        VersionedDB and the reference's LevelDB)."""
        if end_key:
            op = "<=" if include_end else "<"
            rows = self._all(
                f"SELECT key, value, block, txn, metadata FROM state "
                f"WHERE ns=? AND key>=? AND key{op}? ORDER BY key",
                (ns, start_key, end_key),
            )
        else:
            rows = self._all(
                "SELECT key, value, block, txn, metadata FROM state "
                "WHERE ns=? AND key>=? ORDER BY key",
                (ns, start_key),
            )
        for key, value, blk, txn, md in rows:
            yield key, VersionedValue(
                bytes(value),
                Version(blk, txn),
                bytes(md) if md is not None else None,
            )

    def num_keys(self) -> int:
        return self._one("SELECT COUNT(*) FROM state")[0]

    def iter_all_state(self) -> Iterator[Tuple[str, str, VersionedValue]]:
        for ns, key, value, blk, txn, md in self._all(
            "SELECT ns, key, value, block, txn, metadata FROM state "
            "ORDER BY ns, key"
        ):
            yield ns, key, VersionedValue(
                bytes(value),
                Version(blk, txn),
                bytes(md) if md is not None else None,
            )

    def iter_all_hashed(
        self,
    ) -> Iterator[Tuple[str, str, bytes, VersionedValue]]:
        for ns, coll, kh, value, blk, txn, md in self._all(
            "SELECT ns, coll, keyhash, value, block, txn, metadata "
            "FROM hashed ORDER BY ns, coll, keyhash"
        ):
            yield ns, coll, bytes(kh), VersionedValue(
                bytes(value),
                Version(blk, txn),
                bytes(md) if md is not None else None,
            )

    # -- rich queries (statecouchdb ExecuteQuery analog) --------------------
    def execute_query(self, ns: str, query) -> List[Tuple[str, bytes]]:
        return rich_queries.execute(self._query_rows(ns), query)

    def execute_query_paginated(
        self, ns: str, query, page_size: int, bookmark: str = ""
    ):
        """One page + next bookmark (statecouchdb.go:653)."""
        return rich_queries.execute_paginated(
            self._query_rows(ns), query, page_size, bookmark
        )

    def _query_rows(self, ns: str):
        return (
            (key, bytes(value))
            for key, value in self._all(
                "SELECT key, value FROM state WHERE ns=? ORDER BY key", (ns,)
            )
        )

    # -- history ------------------------------------------------------------
    def get_history(self, ns: str, key: str) -> List[Version]:
        return [
            Version(b, t)
            for b, t in self._all(
                "SELECT block, txn FROM history WHERE ns=? AND key=? "
                "ORDER BY block, txn",
                (ns, key),
            )
        ]

    # -- writes -------------------------------------------------------------
    def apply_updates(
        self,
        batch: UpdateBatch,
        hashed: Optional[HashedUpdateBatch] = None,
        pvt: Optional[PvtUpdateBatch] = None,
    ) -> None:
        self.commit_block(batch, hashed, pvt, savepoint=None)

    def commit_block(
        self,
        batch: UpdateBatch,
        hashed: Optional[HashedUpdateBatch] = None,
        pvt: Optional[PvtUpdateBatch] = None,
        savepoint: Optional[int] = None,
        commit_hash: Optional[bytes] = None,
        history: bool = True,
    ) -> None:
        """One block's state + history + savepoint, atomically."""
        db = self._db
        with self._lock, db:  # one transaction
            for (ns, key), entry in batch.items():
                if entry.value is None:
                    db.execute(
                        "DELETE FROM state WHERE ns=? AND key=?", (ns, key)
                    )
                else:
                    db.execute(
                        "INSERT OR REPLACE INTO state VALUES (?,?,?,?,?,?)",
                        (
                            ns,
                            key,
                            entry.value,
                            entry.version.block_num,
                            entry.version.tx_num,
                            entry.metadata,
                        ),
                    )
                if history:
                    db.execute(
                        "INSERT OR REPLACE INTO history VALUES (?,?,?,?)",
                        (ns, key, entry.version.block_num, entry.version.tx_num),
                    )
            for (ns, coll, key_hash), entry in (hashed.items() if hashed else ()):
                if entry.value is None:
                    db.execute(
                        "DELETE FROM hashed WHERE ns=? AND coll=? AND keyhash=?",
                        (ns, coll, key_hash),
                    )
                else:
                    db.execute(
                        "INSERT OR REPLACE INTO hashed VALUES (?,?,?,?,?,?,?)",
                        (
                            ns,
                            coll,
                            key_hash,
                            entry.value,
                            entry.version.block_num,
                            entry.version.tx_num,
                            entry.metadata,
                        ),
                    )
            for (ns, coll, key), entry in (pvt.items() if pvt else ()):
                if entry.value is None:
                    db.execute(
                        "DELETE FROM pvt WHERE ns=? AND coll=? AND key=?",
                        (ns, coll, key),
                    )
                else:
                    db.execute(
                        "INSERT OR REPLACE INTO pvt VALUES (?,?,?,?,?,?)",
                        (
                            ns,
                            coll,
                            key,
                            entry.value,
                            entry.version.block_num,
                            entry.version.tx_num,
                        ),
                    )
            if savepoint is not None:
                # kill window (fabcrash): every row above is written but
                # the transaction is uncommitted — a kill here rolls the
                # whole block back on reopen (WAL discards), leaving the
                # state db exactly one block behind the block store
                fault_point("persistent.commit.mid", key=int(savepoint))
                db.execute(
                    "INSERT OR REPLACE INTO meta VALUES ('savepoint', ?)",
                    (str(savepoint).encode(),),
                )
            if commit_hash is not None:
                db.execute(
                    "INSERT OR REPLACE INTO meta VALUES ('commit_hash', ?)",
                    (commit_hash,),
                )

    def iter_all_pvt(
        self,
    ) -> Iterator[Tuple[str, str, str, VersionedValue]]:
        """Deterministic walk of the cleartext private state (crash-
        harness digests; the pvt sibling of iter_all_state)."""
        for ns, coll, key, value, blk, txn in self._all(
            "SELECT ns, coll, key, value, block, txn FROM pvt "
            "ORDER BY ns, coll, key"
        ):
            yield ns, coll, key, VersionedValue(bytes(value), Version(blk, txn))

    def clear(self) -> None:
        """Drop all derived data (peer node rebuild-dbs).  Out-of-band
        state mutation: bumps the generation stamp so resident version
        tables built over this db fail closed instead of serving stale
        versions."""
        self.bump_generation()
        with self._lock, self._db as db:
            for table in ("state", "hashed", "pvt", "history", "meta", "confighistory"):
                try:
                    db.execute(f"DELETE FROM {table}")
                except sqlite3.OperationalError:
                    pass  # optional table (confighistory) not created yet
