"""MVCC validation and update-batch preparation.

Host-sequential reference semantics, mirroring
core/ledger/kvledger/txmgmt/validation/validator.go:82-281 exactly:

- transactions scan in block order; each VALID tx's writes apply to the
  running update batch before the next tx validates (apply-as-you-go);
- a public read conflicts if (a) the key was written by a preceding valid
  tx in this block (updates.Exists) or (b) the committed version differs
  from the read version (version.AreSame) -> MVCC_READ_CONFLICT;
- range queries re-execute against committed-state + in-block updates
  (updates shadow, deletes hide) and compare results ->
  PHANTOM_READ_CONFLICT;
- hashed (private-collection) reads check like public reads ->
  MVCC_READ_CONFLICT.

This module is the oracle and the fallback; the device fixpoint path for
the no-range-query common case lives in mvcc_device.py (SURVEY P5).
Merkle-summarized range queries (rangequery_validator.go hash variant)
re-execute through the same results helper as simulation and compare
summaries incrementally (_validate_merkle_range_query below).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

from fabric_tpu.protos import kv_rwset_pb2


def serialize_metadata_entries(entries) -> bytes:
    """statemetadata.Serialize: KVMetadataWrite{entries} proto bytes (the
    statedb storage form of key metadata)."""
    msg = kv_rwset_pb2.KVMetadataWrite()
    for name, value in entries:
        e = msg.entries.add()
        e.name = name
        e.value = value
    return msg.SerializeToString()


def deserialize_metadata(metadata_bytes: Optional[bytes]) -> Optional[dict]:
    """statemetadata.Deserialize: storage bytes -> {name: value}."""
    if metadata_bytes is None:
        return None
    msg = kv_rwset_pb2.KVMetadataWrite()
    msg.ParseFromString(metadata_bytes)
    return {e.name: e.value for e in msg.entries}

from fabric_tpu.ledger.rwset import (
    KVRead,
    RangeQueryInfo,
    TxRwSet,
    Version,
    versions_same,
)
from fabric_tpu.ledger.statedb import (
    HashedUpdateBatch,
    UpdateBatch,
    VersionedDB,
    VersionedValue,
)
from fabric_tpu.common.txflags import TxValidationCode


def _combined_range_iter(
    db: VersionedDB,
    updates: UpdateBatch,
    ns: str,
    start_key: str,
    end_key: str,
    include_end: bool,
) -> Iterator[Tuple[str, Version]]:
    """Merge committed state with pending in-block updates for a range scan
    (reference combined_iterator.go): updates take precedence; deletes in
    updates hide committed keys."""
    upd_in_range = sorted(
        (key, val)
        for (uns, key), val in updates.items()
        if uns == ns
        and key >= start_key
        and (not end_key or (key <= end_key if include_end else key < end_key))
    )
    upd_idx = 0
    committed = db.get_state_range(ns, start_key, end_key, include_end)

    def next_committed():
        return next(committed, None)

    cur = next_committed()
    while cur is not None or upd_idx < len(upd_in_range):
        if upd_idx < len(upd_in_range) and (cur is None or upd_in_range[upd_idx][0] <= cur[0]):
            key, entry = upd_in_range[upd_idx]
            if cur is not None and cur[0] == key:
                cur = next_committed()  # shadowed
            upd_idx += 1
            if entry.value is not None:  # deletes yield nothing
                yield key, entry.version
        else:
            assert cur is not None
            yield cur[0], cur[1].version
            cur = next_committed()


class UnsupportedRangeQueryError(NotImplementedError):
    """Kept for API compatibility; no longer raised (the Merkle variant is
    implemented below)."""


class Validator:
    """Block-level MVCC validator over a VersionedDB."""

    def __init__(self, db: VersionedDB):
        self.db = db

    def validate_and_prepare_batch(
        self,
        block_num: int,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
        do_mvcc: bool = True,
    ) -> Tuple[List[TxValidationCode], UpdateBatch, HashedUpdateBatch]:
        """Returns final per-tx codes plus the prepared update batches.

        incoming_codes carry the upstream (signature/policy) verdicts:
        only txs arriving VALID are MVCC-checked and applied
        (reference kvledger commit path: txvalidator flags first, then
        validateAndPrepareBatch skips already-invalid txs).
        """
        updates = UpdateBatch()
        hashed_updates = HashedUpdateBatch()
        out: List[TxValidationCode] = []
        for tx_num, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes, strict=True)):
            if code != TxValidationCode.VALID or rwset is None:
                out.append(code)
                continue
            vcode = self._validate_tx(rwset, updates, hashed_updates) if do_mvcc else TxValidationCode.VALID
            out.append(vcode)
            if vcode == TxValidationCode.VALID:
                self._apply_write_set(
                    rwset, Version(block_num, tx_num), updates, hashed_updates
                )
        return out, updates, hashed_updates

    # -- per-tx validation (validator.go validateTx) ----------------------
    def _validate_tx(
        self, rwset: TxRwSet, updates: UpdateBatch, hashed_updates: HashedUpdateBatch
    ) -> TxValidationCode:
        for ns_rw in rwset.ns_rw_sets:
            ns = ns_rw.namespace
            for read in ns_rw.reads:
                if not self._validate_kv_read(ns, read, updates):
                    return TxValidationCode.MVCC_READ_CONFLICT
            for rqi in ns_rw.range_queries:
                if not self._validate_range_query(ns, rqi, updates):
                    return TxValidationCode.PHANTOM_READ_CONFLICT
            for coll in ns_rw.coll_hashed:
                for hread in coll.hashed_reads:
                    if hashed_updates.contains(ns, coll.collection_name, hread.key_hash):
                        return TxValidationCode.MVCC_READ_CONFLICT
                    committed = self.db.get_key_hash_version(
                        ns, coll.collection_name, hread.key_hash
                    )
                    if not versions_same(committed, hread.version):
                        return TxValidationCode.MVCC_READ_CONFLICT
        return TxValidationCode.VALID

    def _validate_kv_read(self, ns: str, read: KVRead, updates: UpdateBatch) -> bool:
        if updates.exists(ns, read.key):
            return False
        return versions_same(self.db.get_version(ns, read.key), read.version)

    def _validate_range_query(
        self, ns: str, rqi: RangeQueryInfo, updates: UpdateBatch
    ) -> bool:
        # ItrExhausted=false: EndKey is the last key actually seen, so the
        # re-execution must include it (validator.go validateRangeQuery).
        include_end = not rqi.itr_exhausted
        actual = _combined_range_iter(
            self.db, updates, ns, rqi.start_key, rqi.end_key, include_end
        )
        if rqi.reads_merkle_hashes is not None:
            return self._validate_merkle_range_query(rqi, actual)
        for expected in rqi.raw_reads:
            got = next(actual, None)
            if got is None or got[0] != expected.key or not versions_same(got[1], expected.version):
                return False
        return next(actual, None) is None

    @staticmethod
    def _validate_merkle_range_query(rqi: RangeQueryInfo, actual) -> bool:
        """Re-execute the range and rebuild the Merkle summary with the
        recorded max_degree, comparing max-level hashes as they finalize
        so a mismatch in the early results exits before hashing the rest
        (rangequery_validator.go rangeQueryHashValidator.validate)."""
        from fabric_tpu.ledger.merkle import RangeQueryResultsHelper

        in_degree, in_level, in_hashes = rqi.reads_merkle_hashes
        if in_degree < 2:
            # a crafted/zero-default summary must invalidate THIS tx as a
            # phantom read, not raise out of the whole block commit (the
            # _MerkleTree constructor rejects max_degree < 2)
            return False
        helper = RangeQueryResultsHelper(True, in_degree)
        last_matched = -1
        for key, version in actual:
            helper.add_result(KVRead(key, version))
            _deg, level, hashes = helper.merkle_summary()
            if level < in_level:
                continue  # still under construction, nothing to compare
            # >= (not ==): a level spill can shrink the in-construction
            # list below entries we already matched; defer to the final
            # post-done() comparison instead of indexing past it
            if last_matched >= len(hashes) - 1:
                continue
            if len(hashes) > len(in_hashes):
                return False  # more entries than simulation recorded
            last_matched += 1
            if hashes[last_matched] != in_hashes[last_matched]:
                return False
        _raw, summary = helper.done()
        return summary == rqi.reads_merkle_hashes

    # -- write application (tx_ops.go prepareTxOps + applyWriteSet) -------
    # keyOps flags mirroring tx_ops.go:160-167
    _UPSERT = 1
    _MD_UPDATE = 2
    _MD_DELETE = 4
    _KEY_DELETE = 8

    def _apply_write_set(
        self,
        rwset: TxRwSet,
        height: Version,
        updates: UpdateBatch,
        hashed_updates: HashedUpdateBatch,
    ) -> None:
        """Apply one VALID tx's writes to the running batch, merging value
        and metadata updates like the reference's prepareTxOps: a
        value-only write carries forward the latest metadata, a
        metadata-only write carries forward the latest value (and is a
        no-op if the key does not exist)."""
        txops: dict = {}  # (ns, coll, key) -> [flags, value, metadata]

        def op(ck):
            return txops.setdefault(ck, [0, None, None])

        for ns_rw in rwset.ns_rw_sets:
            ns = ns_rw.namespace
            for w in ns_rw.writes:
                o = op((ns, "", w.key))
                if w.is_delete:
                    o[0] |= self._KEY_DELETE
                else:
                    o[0] |= self._UPSERT
                    o[1] = w.value
            for mw in ns_rw.metadata_writes:
                o = op((ns, "", mw.key))
                if mw.entries is None:
                    o[0] |= self._MD_DELETE
                else:
                    o[0] |= self._MD_UPDATE
                    o[2] = serialize_metadata_entries(mw.entries)
            for coll in ns_rw.coll_hashed:
                cname = coll.collection_name
                for hw in coll.hashed_writes:
                    o = op((ns, cname, hw.key_hash))
                    if hw.is_delete:
                        o[0] |= self._KEY_DELETE
                    else:
                        o[0] |= self._UPSERT
                        o[1] = hw.value_hash
                for mw in coll.metadata_writes:
                    o = op((ns, cname, mw.key_hash))
                    if mw.entries is None:
                        o[0] |= self._MD_DELETE
                    else:
                        o[0] |= self._MD_UPDATE
                        o[2] = serialize_metadata_entries(mw.entries)

        for (ns, coll, key), (flags, value, metadata) in txops.items():
            if flags & self._KEY_DELETE:
                if coll == "":
                    updates.delete(ns, key, height)
                else:
                    hashed_updates.put(ns, coll, key, None, height)
                continue
            upsert = bool(flags & self._UPSERT)
            md_touched = bool(flags & (self._MD_UPDATE | self._MD_DELETE))
            if upsert and not md_touched:
                # merge the latest committed / in-block metadata
                metadata = self._latest_metadata(
                    ns, coll, key, updates, hashed_updates
                )
            elif md_touched and not upsert:
                value = self._latest_value(
                    ns, coll, key, updates, hashed_updates
                )
                if value is None:
                    continue  # metadata on a non-existent key: no-op
            if coll == "":
                updates.put(ns, key, value, height, metadata)
            else:
                hashed_updates.put(ns, coll, key, value, height, metadata)

    def _latest_value(self, ns, coll, key, updates, hashed_updates):
        if coll == "":
            entry = updates.get(ns, key)
            if entry is not None:
                return entry.value
            vv = self.db.get_state(ns, key)
            return vv.value if vv else None
        entry = hashed_updates.get(ns, coll, key)
        if entry is not None:
            return entry.value
        vv = self.db.get_hashed_state(ns, coll, key)
        return vv.value if vv else None

    def _latest_metadata(self, ns, coll, key, updates, hashed_updates):
        if coll == "":
            entry = updates.get(ns, key)
            if entry is not None:
                return entry.metadata
            return self.db.get_state_metadata(ns, key)
        entry = hashed_updates.get(ns, coll, key)
        if entry is not None:
            return entry.metadata
        return self.db.get_hashed_metadata(ns, coll, key)
