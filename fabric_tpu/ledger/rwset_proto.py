"""TxRwSet <-> proto bytes (reference rwsetutil/rwset_proto_util.go)."""

from __future__ import annotations

from typing import Optional

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.protos import kv_rwset_pb2, protoutil, rwset_pb2


def _set_version(msg, version: Optional[rw.Version]) -> None:
    if version is not None:
        msg.version.block_num = version.block_num
        msg.version.tx_num = version.tx_num


def serialize_tx_rwset(txrw: rw.TxRwSet) -> bytes:
    out = rwset_pb2.TxReadWriteSet()
    out.data_model = rwset_pb2.TxReadWriteSet.KV
    for ns in txrw.ns_rw_sets:
        kv = kv_rwset_pb2.KVRWSet()
        for r in ns.reads:
            kr = kv.reads.add()
            kr.key = r.key
            _set_version(kr, r.version)
        for q in ns.range_queries:
            rq = kv.range_queries_info.add()
            rq.start_key = q.start_key
            rq.end_key = q.end_key
            rq.itr_exhausted = q.itr_exhausted
            if q.reads_merkle_hashes is not None:
                rq.reads_merkle_hashes.max_degree = q.reads_merkle_hashes[0]
                rq.reads_merkle_hashes.max_level = q.reads_merkle_hashes[1]
                rq.reads_merkle_hashes.max_level_hashes.extend(
                    q.reads_merkle_hashes[2]
                )
            else:
                rq.raw_reads.SetInParent()
                for r in q.raw_reads:
                    kr = rq.raw_reads.kv_reads.add()
                    kr.key = r.key
                    _set_version(kr, r.version)
        for w in ns.writes:
            kw = kv.writes.add()
            kw.key = w.key
            kw.is_delete = w.is_delete
            kw.value = w.value
        for mw in ns.metadata_writes:
            m = kv.metadata_writes.add()
            m.key = mw.key
            for name, value in mw.entries or ():
                e = m.entries.add()
                e.name = name
                e.value = value
        ns_out = out.ns_rwset.add()
        ns_out.namespace = ns.namespace
        ns_out.rwset = kv.SerializeToString()
        for coll in ns.coll_hashed:
            h = kv_rwset_pb2.HashedRWSet()
            for hr in coll.hashed_reads:
                m = h.hashed_reads.add()
                m.key_hash = hr.key_hash
                _set_version(m, hr.version)
            for hw in coll.hashed_writes:
                m = h.hashed_writes.add()
                m.key_hash = hw.key_hash
                m.is_delete = hw.is_delete
                m.value_hash = hw.value_hash
            for mw in coll.metadata_writes:
                m = h.metadata_writes.add()
                m.key_hash = mw.key_hash
                for name, value in mw.entries or ():
                    e = m.entries.add()
                    e.name = name
                    e.value = value
            c = ns_out.collection_hashed_rwset.add()
            c.collection_name = coll.collection_name
            c.hashed_rwset = h.SerializeToString()
    return out.SerializeToString()
