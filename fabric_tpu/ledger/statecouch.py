"""CouchDB-compatible REST state adapter (reference core/ledger/
kvledger/txmgmt/statedb/statecouchdb/statecouchdb.go).

The embedded sqlite store (`ledger/persistent.py`) is this framework's
default state backend and already serves rich selector queries +
bookmark pagination (`ledger/queries.py`); what it cannot offer is the
reference's OPERATIONAL story — an external CouchDB a deployment
already runs, with its own replication/backup/inspection tooling. This
adapter speaks that REST dialect for the public-state surface:

* one database per (channel, namespace), named like the reference's
  `<channel>_<namespace>` (couchdb dbname mangling);
* documents are `{_id: key, ~version: "h:t", ...json fields}` with a
  `_attachments.valueBytes` for non-JSON values — byte-compatible with
  what the reference writes, so a Fabric-populated CouchDB reads back
  verbatim;
* commits go through `_bulk_docs` with the reference's REVISION CACHE
  (statecouchdb.go:695 bulk-preload: one `_all_docs?keys=` round trip
  fetches the _revs of every key the block writes, instead of one GET
  per key);
* range scans ride `_all_docs?startkey&endkey&limit`, rich queries pass
  the selector to `/_find` VERBATIM with CouchDB's own opaque bookmark
  flowing back to the client (the cursor contract shim callers see).

Scope note, honestly: hashed/private collections, history and the
commit-hash chain stay on the embedded store (SURVEY §2.12.3 keeps
external services out of the consensus-critical path); this adapter is
the operational mirror for the PUBLIC state, the part CouchDB tooling
actually inspects. Tested against an in-process fake CouchDB
(tests/test_statecouch.py) because this image has no external service.
"""

from __future__ import annotations

import base64
import json
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from fabric_tpu.ledger.rwset import Version
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedValue


class CouchError(Exception):
    pass


def _version_str(v: Version) -> str:
    return f"{v.block_num}:{v.tx_num}"


def _parse_version(s: str) -> Version:
    h, _, t = s.partition(":")
    return Version(int(h), int(t))


def couch_db_name(channel: str, ns: str) -> str:
    """The reference's mangling (couchdbutil CreateCouchDatabase):
    lowercase, [a-z0-9_$()+/-] only, `<channel>_<ns>`."""
    raw = f"{channel}_{ns}".lower() if ns else channel.lower()
    return "".join(
        c if c.isalnum() or c in "_$()+-/" else "$" for c in raw
    )


class CouchClient:
    """Minimal CouchDB REST client (http.client via urllib; no external
    deps). Every method raises CouchError on non-2xx."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def _req(
        self, method: str, path: str, body: Optional[dict] = None
    ) -> dict:
        data = None
        headers = {"Accept": "application/json"}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            f"{self.base}{path}", data=data, method=method, headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return {"_not_found": True}
            if exc.code == 412:
                # PUT /{db} on an existing database (file_exists)
                try:
                    return json.loads(exc.read() or b"{}")
                except ValueError:
                    return {"error": "file_exists"}
            raise CouchError(
                f"{method} {path} -> {exc.code}: {exc.read()[:200]}"
            ) from exc
        except OSError as exc:
            raise CouchError(f"{method} {path}: {exc}") from exc

    def ensure_db(self, db: str) -> None:
        out = self._req("PUT", f"/{db}")
        if out.get("error") not in (None, "file_exists"):
            raise CouchError(f"create {db}: {out}")

    def get_doc(self, db: str, key: str) -> Optional[dict]:
        out = self._req(
            "GET",
            f"/{db}/{urllib.parse.quote(key, safe='')}?attachments=true",
        )
        return None if out.get("_not_found") else out

    def bulk_docs(self, db: str, docs: List[dict]) -> List[dict]:
        out = self._req("POST", f"/{db}/_bulk_docs", {"docs": docs})
        if isinstance(out, dict):
            raise CouchError(f"_bulk_docs: {out}")
        return out

    def all_docs(
        self,
        db: str,
        *,
        keys: Optional[List[str]] = None,
        startkey: Optional[str] = None,
        endkey: Optional[str] = None,
        limit: Optional[int] = None,
        include_docs: bool = False,
    ) -> dict:
        if keys is not None:
            return self._req("POST", f"/{db}/_all_docs", {"keys": keys})
        params = []
        if startkey is not None:
            params.append(("startkey", json.dumps(startkey)))
        if endkey is not None:
            # exclusive end bound like the reference's range scans
            params.append(("endkey", json.dumps(endkey)))
            params.append(("inclusive_end", "false"))
        if limit is not None:
            params.append(("limit", str(limit)))
        if include_docs:
            params.append(("include_docs", "true"))
            # attachment DATA, not stubs: binary values must round-trip
            # through scans exactly like point reads
            params.append(("attachments", "true"))
        qs = "&".join(f"{k}={urllib.parse.quote(v)}" for k, v in params)
        return self._req("GET", f"/{db}/_all_docs" + (f"?{qs}" if qs else ""))

    def find(self, db: str, body: dict) -> dict:
        out = self._req("POST", f"/{db}/_find", body)
        if "docs" not in out:
            raise CouchError(f"_find: {out}")
        return out


def _to_doc(key: str, value: bytes, version: Version, metadata=None) -> dict:
    """Reference doc shape (couchdoc_conv.go): JSON values inline,
    binary under the valueBytes attachment."""
    doc: dict = {"_id": key, "~version": _version_str(version)}
    try:
        fields = json.loads(value)
        if not isinstance(fields, dict) or any(
            k.startswith(("_", "~")) for k in fields
        ):
            raise ValueError
        doc.update(fields)
    except (ValueError, UnicodeDecodeError):
        doc["_attachments"] = {
            "valueBytes": {
                "content_type": "application/octet-stream",
                "data": base64.b64encode(value).decode(),
            }
        }
    if metadata:
        doc["~metadata"] = base64.b64encode(metadata).decode()
    return doc


def _from_doc(doc: dict) -> VersionedValue:
    version = _parse_version(doc["~version"])
    att = (doc.get("_attachments") or {}).get("valueBytes")
    if att is not None and "data" in att:
        value = base64.b64decode(att["data"])
    else:
        fields = {
            k: v
            for k, v in doc.items()
            if not k.startswith(("_", "~"))
        }
        value = json.dumps(fields, sort_keys=True).encode()
    md = doc.get("~metadata")
    return VersionedValue(
        value, version, base64.b64decode(md) if md else None
    )


def _has_attachment_stub(doc: dict) -> bool:
    """True when a doc carries attachment STUBS (no inline data) — the
    /_find endpoint can never inline attachments, so binary values need
    a follow-up point read (the reference statecouchdb re-fetches the
    same way)."""
    atts = doc.get("_attachments") or {}
    return any("data" not in a for a in atts.values())


class CouchStateAdapter:
    """Public-state operational mirror over one CouchDB endpoint."""

    # explicit limit on every /_find: CouchDB's silent default is 25,
    # which would truncate unpaginated queries (the reference sets
    # internalQueryLimit, default 1000, on every query)
    QUERY_LIMIT = 1000

    def __init__(self, client: CouchClient, channel: str):
        self.client = client
        self.channel = channel
        self._dbs: Dict[str, str] = {}
        # revision cache (statecouchdb.go committedDataCache): _id -> _rev
        self._revs: Dict[Tuple[str, str], str] = {}

    def _db(self, ns: str) -> str:
        db = self._dbs.get(ns)
        if db is None:
            db = couch_db_name(self.channel, ns)
            self.client.ensure_db(db)
            self._dbs[ns] = db
        return db

    # -- reads -------------------------------------------------------------
    def get_state(self, ns: str, key: str) -> Optional[VersionedValue]:
        doc = self.client.get_doc(self._db(ns), key)
        if doc is None:
            return None
        self._revs[(ns, key)] = doc.get("_rev", "")
        return _from_doc(doc)

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get_state(ns, key)
        return vv.version if vv else None

    def get_state_range(
        self, ns: str, start: str, end: str, limit: Optional[int] = None
    ) -> Iterator[Tuple[str, VersionedValue]]:
        out = self.client.all_docs(
            self._db(ns),
            startkey=start or None,
            endkey=end or None,
            limit=limit,
            include_docs=True,
        )
        for row in out.get("rows", []):
            doc = row.get("doc")
            if doc:
                if _has_attachment_stub(doc):
                    doc = self.client.get_doc(self._db(ns), row["id"]) or doc
                yield row["id"], _from_doc(doc)

    def execute_query(
        self,
        ns: str,
        selector: dict,
        page_size: Optional[int] = None,
        bookmark: str = "",
    ) -> Tuple[List[Tuple[str, bytes]], str]:
        """Selector passes to /_find VERBATIM; CouchDB's opaque bookmark
        flows back — persistent cursor across RESTARTED iterators, the
        piece the embedded store's offset tokens could not provide."""
        body: dict = {"selector": selector, "limit": page_size or self.QUERY_LIMIT}
        if bookmark:
            body["bookmark"] = bookmark
        out = self.client.find(self._db(ns), body)
        rows = []
        for doc in out["docs"]:
            if _has_attachment_stub(doc):
                # /_find cannot inline attachments: binary values need a
                # point re-read (statecouchdb executeQueryWithBookmark)
                doc = self.client.get_doc(self._db(ns), doc["_id"]) or doc
            vv = _from_doc(doc)
            rows.append((doc["_id"], vv.value))
        return rows, out.get("bookmark", "")

    # -- commit ------------------------------------------------------------
    def preload_revisions(self, ns: str, keys: Sequence[str]) -> None:
        """Bulk-preload the revision cache for a block's written keys
        (statecouchdb.go:695): ONE _all_docs round trip instead of a GET
        per key."""
        missing = [k for k in keys if (ns, k) not in self._revs]
        if not missing:
            return
        out = self.client.all_docs(self._db(ns), keys=list(missing))
        for row in out.get("rows", []):
            rev = (row.get("value") or {}).get("rev")
            if rev and not (row.get("value") or {}).get("deleted"):
                self._revs[(ns, row["id"])] = rev

    def apply_updates(self, batch: UpdateBatch) -> None:
        """Block commit: per-namespace _bulk_docs with cached _revs;
        conflicts refresh the cache and retry once (the reference's
        retry loop on sporadic revision conflicts)."""
        by_ns: Dict[str, List[Tuple[str, object]]] = {}
        for (ns, key), entry in batch.items():
            by_ns.setdefault(ns, []).append((key, entry))
        for ns, entries in by_ns.items():
            self.preload_revisions(ns, [k for k, _e in entries])
            self._flush_ns(ns, entries, retry=True)

    def _flush_ns(self, ns: str, entries, retry: bool) -> None:
        docs = []
        for key, entry in entries:
            if entry.value is None:
                doc = {"_id": key, "_deleted": True}
            else:
                doc = _to_doc(key, entry.value, entry.version, entry.metadata)
            rev = self._revs.get((ns, key))
            if rev:
                doc["_rev"] = rev
            docs.append(doc)
        results = self.client.bulk_docs(self._db(ns), docs)
        conflicts = []
        for res in results:
            key = res.get("id")
            if res.get("ok"):
                if res.get("rev"):
                    self._revs[(ns, key)] = res["rev"]
                continue
            if res.get("error") == "conflict" and retry:
                self._revs.pop((ns, key), None)
                conflicts.append(key)
            else:
                raise CouchError(f"bulk update {ns}/{key}: {res}")
        if conflicts:
            entry_map = dict(entries)
            self.preload_revisions(ns, conflicts)
            self._flush_ns(
                ns, [(k, entry_map[k]) for k in conflicts], retry=False
            )
