"""Read/write-set datamodel (reference rwsetutil + kvrwset protos).

Shapes mirror fabric-protos ledger/rwset/kvrwset (KVRead/KVWrite/
RangeQueryInfo/KVReadHash/KVWriteHash) and rwsetutil's internal TxRwSet /
NsRwSet / CollHashedRwSet (core/ledger/kvledger/txmgmt/rwsetutil/
rwset_proto_util.go:32-48).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass(frozen=True, order=True)
class Version:
    """Logical version = (block height, tx index) —
    reference core/ledger/internal/version.Height."""

    block_num: int
    tx_num: int


def versions_same(a: Optional[Version], b: Optional[Version]) -> bool:
    """reference version.AreSame: nil == nil, nil != non-nil."""
    return a == b


@dataclass(frozen=True)
class KVRead:
    key: str
    version: Optional[Version]  # None: key did not exist at simulation time


@dataclass(frozen=True)
class KVWrite:
    key: str
    is_delete: bool = False
    value: bytes = b""


@dataclass(frozen=True)
class RangeQueryInfo:
    """Phantom-read check payload. raw_reads is the observed result list;
    reads_merkle_hashes (max_degree, max_level, max_level_hashes) is the
    space-saving Merkle summary the reference uses for big result sets
    (kvrwset.QueryReadsMerkleSummary, built by
    rwsetutil/query_results_helper.go)."""

    start_key: str
    end_key: str
    itr_exhausted: bool
    raw_reads: Tuple[KVRead, ...] = ()
    reads_merkle_hashes: Optional[Tuple[int, int, Tuple[bytes, ...]]] = None


@dataclass(frozen=True)
class KVMetadataWrite:
    """Key-level metadata update (kvrwset.KVMetadataWrite). `entries` is
    a name->value tuple list; None entries means metadata delete
    (reference tx_ops.go applyMetadata: nil Entries -> metadataDelete)."""

    key: str
    entries: Optional[Tuple[Tuple[str, bytes], ...]] = None


@dataclass(frozen=True)
class KVMetadataWriteHash:
    key_hash: bytes
    entries: Optional[Tuple[Tuple[str, bytes], ...]] = None


@dataclass(frozen=True)
class KVReadHash:
    key_hash: bytes
    version: Optional[Version]


@dataclass(frozen=True)
class KVWriteHash:
    key_hash: bytes
    is_delete: bool = False
    value_hash: bytes = b""


@dataclass(frozen=True)
class CollHashedRwSet:
    collection_name: str
    hashed_reads: Tuple[KVReadHash, ...] = ()
    hashed_writes: Tuple[KVWriteHash, ...] = ()
    metadata_writes: Tuple[KVMetadataWriteHash, ...] = ()


@dataclass(frozen=True)
class NsRwSet:
    namespace: str
    reads: Tuple[KVRead, ...] = ()
    writes: Tuple[KVWrite, ...] = ()
    range_queries: Tuple[RangeQueryInfo, ...] = ()
    coll_hashed: Tuple[CollHashedRwSet, ...] = ()
    metadata_writes: Tuple[KVMetadataWrite, ...] = ()


@dataclass(frozen=True)
class TxRwSet:
    ns_rw_sets: Tuple[NsRwSet, ...] = ()
