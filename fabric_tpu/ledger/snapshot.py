"""Ledger snapshots (reference core/ledger/kvledger/snapshot.go:
generateSnapshot :94, CreateFromSnapshot :221).

Export writes a deterministic directory:
  public_state.data          (ns, key, value, version, metadata) sorted
  private_state_hashes.data  (ns, coll, key_hash, value_hash, version)
  txids.data                 sorted committed TxIDs
  _snapshot_signable_metadata.json
      channel name, height, last/prev block hash, per-file SHA-256 —
      the cross-peer comparable fingerprint (the reference signs this).

Import (join-by-snapshot) builds a fresh ledger whose block store starts
at the snapshot height with no block prefix; state and the txid
dedup index come from the snapshot files; history before the snapshot is
unavailable, exactly like the reference.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Dict, List, Tuple

from fabric_tpu.ledger.rwset import Version

SIGNABLE_METADATA = "_snapshot_signable_metadata.json"
PUBLIC_STATE = "public_state.data"
PVT_HASHES = "private_state_hashes.data"
TXIDS = "txids.data"


def _w(out, b: bytes) -> None:
    out.write(struct.pack("<I", len(b)))
    out.write(b)


def _r(f) -> bytes:
    hdr = f.read(4)
    if len(hdr) < 4:
        raise EOFError
    (ln,) = struct.unpack("<I", hdr)
    return f.read(ln)  # fabwire: disable=unbounded-wire-alloc  # snapshot data files are sha256-sealed: verify_snapshot checks every file against the signed metadata digest before create_from_snapshot parses a byte, and f.read caps at EOF


def _version_bytes(v: Version) -> bytes:
    return struct.pack("<QQ", v.block_num, v.tx_num)


def _version_from(b: bytes) -> Version:
    bn, tn = struct.unpack("<QQ", b)
    return Version(bn, tn)


def generate_snapshot(ledger, out_dir: str) -> Dict[str, str]:
    """Export the ledger at its current height. Returns the signable
    metadata dict (also written to disk)."""
    os.makedirs(out_dir, exist_ok=True)
    if ledger.height == 0:
        raise ValueError("cannot snapshot an empty ledger")

    with open(os.path.join(out_dir, PUBLIC_STATE), "wb") as f:
        for ns, key, vv in ledger.state_db.iter_all_state():
            _w(f, ns.encode())
            _w(f, key.encode())
            _w(f, vv.value)
            _w(f, _version_bytes(vv.version))
            _w(f, vv.metadata or b"")

    with open(os.path.join(out_dir, PVT_HASHES), "wb") as f:
        for ns, coll, kh, vv in ledger.state_db.iter_all_hashed():
            _w(f, ns.encode())
            _w(f, coll.encode())
            _w(f, kh)
            _w(f, vv.value)
            _w(f, _version_bytes(vv.version))

    with open(os.path.join(out_dir, TXIDS), "wb") as f:
        for txid in sorted(ledger.block_store._by_txid):
            _w(f, txid.encode())

    files = {}
    for name in (PUBLIC_STATE, PVT_HASHES, TXIDS):
        with open(os.path.join(out_dir, name), "rb") as f:
            files[name] = hashlib.sha256(f.read()).hexdigest()
    last = ledger.block_store.get_block_by_number(ledger.height - 1)
    from fabric_tpu.protos import protoutil

    meta = {
        "channel_name": ledger.channel_id,
        "last_block_number": ledger.height - 1,
        "last_block_hash": protoutil.block_header_hash(last.header).hex(),
        "previous_block_hash": last.header.previous_hash.hex(),
        "snapshot_files_raw_hashes": files,
        "state_db_type": "embedded",
    }
    with open(os.path.join(out_dir, SIGNABLE_METADATA), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return meta


def verify_snapshot(snap_dir: str) -> dict:
    """Check per-file hashes against the signable metadata; returns the
    metadata (import-side integrity check)."""
    with open(os.path.join(snap_dir, SIGNABLE_METADATA)) as f:
        meta = json.load(f)
    for name, want in meta["snapshot_files_raw_hashes"].items():
        with open(os.path.join(snap_dir, name), "rb") as f:
            got = hashlib.sha256(f.read()).hexdigest()
        if got != want:
            raise ValueError(f"snapshot file {name} hash mismatch")
    return meta


def create_from_snapshot(snap_dir: str, ledger_dir: str):
    """Join-by-snapshot: build a KVLedger for the snapshot's channel at
    height last_block_number+1 (kvledger CreateFromSnapshot)."""
    from fabric_tpu.ledger.blockstore import BlockStore
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.ledger.statedb import (
        HashedUpdateBatch,
        UpdateBatch,
    )

    meta = verify_snapshot(snap_dir)
    channel_id = meta["channel_name"]
    height = meta["last_block_number"] + 1
    last_hash = bytes.fromhex(meta["last_block_hash"])

    txids: List[str] = []
    with open(os.path.join(snap_dir, TXIDS), "rb") as f:
        while True:
            try:
                txids.append(_r(f).decode())
            except EOFError:
                break

    # bootstrap the block store BEFORE the ledger opens it; pre-snapshot
    # txids persist in a sidecar so dedup survives restarts
    chain_path = os.path.join(ledger_dir, f"{channel_id}.chain")
    BlockStore.bootstrap_from_snapshot(
        chain_path, height, last_hash, pre_snapshot_txids=txids
    ).close()

    ledger = KVLedger(ledger_dir, channel_id)

    updates = UpdateBatch()
    with open(os.path.join(snap_dir, PUBLIC_STATE), "rb") as f:
        while True:
            try:
                ns = _r(f).decode()
            except EOFError:
                break
            key = _r(f).decode()
            value = _r(f)
            version = _version_from(_r(f))
            md = _r(f)
            updates.put(ns, key, value, version, md or None)
    hashed = HashedUpdateBatch()
    with open(os.path.join(snap_dir, PVT_HASHES), "rb") as f:
        while True:
            try:
                ns = _r(f).decode()
            except EOFError:
                break
            coll = _r(f).decode()
            kh = _r(f)
            vh = _r(f)
            version = _version_from(_r(f))
            hashed.put(ns, coll, kh, vh, version)
    ledger.state_db.apply_updates(updates, hashed)

    return ledger


class SnapshotRequestManager:
    """Pending snapshot requests for one channel (reference
    core/ledger/kvledger/snapshot_mgr.go: SubmitSnapshotRequest :60,
    CancelSnapshotRequest :78, PendingSnapshotRequests :91).

    Height 0 means "the next committed block".  When the committer
    reaches a requested height (on_block_committed), the snapshot is
    generated into  <snapshots_root>/<channel>/<height>/  and the request
    retires.  Requests at or below the current height are rejected, as
    the reference does."""

    def __init__(self, ledger, snapshots_root: str):
        import threading

        self._ledger = ledger
        self._root = snapshots_root
        self._pending: set = set()
        self._lock = threading.Lock()
        self.generated: Dict[int, str] = {}

    def submit(self, height: int = 0) -> int:
        with self._lock:
            current = self._ledger.height
            if height == 0:
                height = current  # next block to commit has this number
            elif height < current:
                raise ValueError(
                    f"requested snapshot height {height} cannot be less "
                    f"than the current height {current}"
                )
            if height in self._pending:
                raise ValueError(
                    f"duplicate snapshot request for height {height}"
                )
            self._pending.add(height)
            return height

    def cancel(self, height: int) -> None:
        with self._lock:
            if height not in self._pending:
                raise ValueError(
                    f"no snapshot request exists for height {height}"
                )
            self._pending.discard(height)

    def pending(self) -> List[int]:
        with self._lock:
            return sorted(self._pending)

    def on_block_committed(self, wait: bool = False) -> None:
        """Commit hook: ledger.height-1 is the block just committed.

        Generation runs on a worker thread so a large state export never
        stalls the commit path (the reference generates snapshots after
        commit, outside the critical section).  ``wait=True`` blocks
        until the export finishes (tests/synchronous callers)."""
        import threading

        committed = self._ledger.height - 1
        with self._lock:
            if committed not in self._pending:
                return
            self._pending.discard(committed)
        out_dir = os.path.join(
            self._root, self._ledger.channel_id, str(committed)
        )

        def work():
            generate_snapshot(self._ledger, out_dir)
            with self._lock:
                self.generated[committed] = out_dir

        if wait:
            work()
        else:
            threading.Thread(  # fablife: disable=thread-unjoined  # one-shot export whose completion is PUBLISHED in generated[committed]; the manager has no teardown surface, and wait=True is the synchronous path for callers that need the join semantics
                target=work, name=f"snapshot-{committed}", daemon=True
            ).start()
