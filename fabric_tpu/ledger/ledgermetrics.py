"""Committer metrics (reference core/ledger/kvledger/metrics.go +
gossip/privdata/coordinator.go:161-163): the histograms/gauges/counters
every peer emits from the commit hot path, built over the metrics SPI so
prometheus/statsd/disabled providers all work."""

from __future__ import annotations

from typing import Optional

from fabric_tpu.common.metrics import (
    CounterOpts,
    GaugeOpts,
    HistogramOpts,
    Provider,
)


class CommitterMetrics:
    """One instance per node; label 'channel' selects the ledger."""

    def __init__(self, provider: Provider):
        self.blockchain_height = provider.new_gauge(
            GaugeOpts(
                namespace="ledger",
                name="blockchain_height",
                help="Height of the chain in blocks.",
                label_names=("channel",),
            )
        )
        self.block_processing_time = provider.new_histogram(
            HistogramOpts(
                namespace="ledger",
                name="block_processing_time",
                help="Time taken in seconds for ledger block processing.",
                label_names=("channel",),
            )
        )
        self.blockstorage_commit_time = provider.new_histogram(
            HistogramOpts(
                namespace="ledger",
                name="blockstorage_and_pvtdata_commit_time",
                help="Time taken in seconds for committing the block and "
                "private data to storage.",
                label_names=("channel",),
            )
        )
        self.statedb_commit_time = provider.new_histogram(
            HistogramOpts(
                namespace="ledger",
                name="statedb_commit_time",
                help="Time taken in seconds for committing block changes "
                "to state db.",
                label_names=("channel",),
            )
        )
        self.transaction_count = provider.new_counter(
            CounterOpts(
                namespace="ledger",
                name="transaction_count",
                help="Number of transactions processed.",
                label_names=("channel", "validation_code"),
            )
        )
        self.validation_duration = provider.new_histogram(
            HistogramOpts(
                namespace="gossip",
                subsystem="privdata",
                name="validation_duration",
                help="Time it takes to validate a block (in seconds).",
                label_names=("channel",),
            )
        )

    # -- commit-path hooks -------------------------------------------------
    def observe_commit(
        self,
        channel_id: str,
        flags,
        height: int,
        validate_seconds: float,
        store_seconds: float,
        state_seconds: float,
    ) -> None:
        self.blockchain_height.with_labels("channel", channel_id).set(height)
        self.block_processing_time.with_labels("channel", channel_id).observe(
            validate_seconds + store_seconds + state_seconds
        )
        self.validation_duration.with_labels("channel", channel_id).observe(
            validate_seconds
        )
        self.blockstorage_commit_time.with_labels("channel", channel_id).observe(
            store_seconds
        )
        self.statedb_commit_time.with_labels("channel", channel_id).observe(
            state_seconds
        )
        from fabric_tpu.common.txflags import TxValidationCode

        for code in flags.asarray():
            self.transaction_count.with_labels(
                "channel",
                channel_id,
                "validation_code",
                TxValidationCode(int(code)).name,
            ).add(1)
