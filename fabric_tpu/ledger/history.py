"""History index + GetHistoryForKey (reference core/ledger/kvledger/
history/db.go + kv_scanner in query_executer.go).

The reference keeps a LevelDB index of (ns, key) -> [(blockNum, txNum)]
written at commit and resolves values by re-reading the block from the
block store at query time (history/query_executer.go:71-112). Here the
index lives on the KVLedger (rebuilt by replay — a derived cache like
state) and this module resolves each version to the committed write.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional

from fabric_tpu.ledger.rwset import Version


@dataclass(frozen=True)
class KeyModification:
    """One historical write (peer.KeyModification analog)."""

    tx_id: str
    version: Version
    value: bytes
    is_delete: bool


def get_history_for_key(ledger, ns: str, key: str) -> List[KeyModification]:
    """Newest-first history of committed writes to (ns, key), resolved
    from the block store (history/query_executer.go getKeyModification)."""
    from fabric_tpu.protos import protoutil
    from fabric_tpu.ledger.txparse import parse_transaction

    out: List[KeyModification] = []
    for version in reversed(ledger.get_history_for_key(ns, key)):
        block = ledger.block_store.get_block_by_number(version.block_num)
        if block is None:
            continue
        parsed = parse_transaction(
            version.tx_num, block.data.data[version.tx_num]
        )
        if parsed.rwset is None:
            continue
        for ns_rw in parsed.rwset.ns_rw_sets:
            if ns_rw.namespace != ns:
                continue
            for w in ns_rw.writes:
                if w.key == key:
                    out.append(
                        KeyModification(
                            tx_id=parsed.tx_id,
                            version=version,
                            value=w.value,
                            is_delete=w.is_delete,
                        )
                    )
    return out
