"""Range-query results Merkle summarization.

Mirrors reference rwsetutil/query_results_helper.go: results stream in
one at a time; once more than `max_degree` accumulate, the batch is
proto-serialized (kvrwset.QueryReads), hashed, and becomes a leaf-level
node in a degree-bounded Merkle tree.  If the total result count never
exceeds `max_degree`, no hashing happens and the raw reads are kept —
exactly the reference's space/size trade.

The summary triple (max_degree, max_level, max_level_hashes) is what
lands in RangeQueryInfo.reads_merkle_hashes and what the validator's
re-execution must reproduce (rangequery_validator.go
rangeQueryHashValidator).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.protos import kv_rwset_pb2

LEAF_LEVEL = 1


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def serialize_kv_reads(reads: List[rw.KVRead]) -> bytes:
    """proto.Marshal(QueryReads{kv_reads}) — the leaf pre-image
    (query_results_helper.go serializeKVReads)."""
    msg = kv_rwset_pb2.QueryReads()
    for r in reads:
        kr = msg.kv_reads.add()
        kr.key = r.key
        if r.version is not None:
            kr.version.block_num = r.version.block_num
            kr.version.tx_num = r.version.tx_num
    return msg.SerializeToString()


class _MerkleTree:
    """Degree-bounded incremental tree (query_results_helper.go
    merkleTree): a level spills into its parent as soon as it exceeds
    max_degree nodes; done() folds leftovers upward."""

    def __init__(self, max_degree: int):
        if max_degree < 2:
            raise ValueError("max_degree must be >= 2")
        self.tree: Dict[int, List[bytes]] = {}
        self.max_level = LEAF_LEVEL
        self.max_degree = max_degree

    def update(self, leaf_hash: bytes) -> None:
        self.tree.setdefault(LEAF_LEVEL, []).append(leaf_hash)
        level = LEAF_LEVEL
        while len(self.tree.get(level, ())) > self.max_degree:
            combined = _hash(b"".join(self.tree[level]))
            del self.tree[level]
            level += 1
            self.tree.setdefault(level, []).append(combined)
            self.max_level = max(self.max_level, level)

    def done(self) -> None:
        level = LEAF_LEVEL
        while level < self.max_level:
            hashes = self.tree.get(level, ())
            if not hashes:
                level += 1
                continue
            h = hashes[0] if len(hashes) == 1 else _hash(b"".join(hashes))
            self.tree.pop(level, None)
            level += 1
            self.tree.setdefault(level, []).append(h)
        final = self.tree.get(self.max_level, ())
        if len(final) > self.max_degree:
            combined = _hash(b"".join(final))
            del self.tree[self.max_level]
            self.max_level += 1
            self.tree[self.max_level] = [combined]

    def is_empty(self) -> bool:
        return self.max_level == LEAF_LEVEL and not self.tree.get(LEAF_LEVEL)

    def summary(self) -> Tuple[int, int, Tuple[bytes, ...]]:
        return (
            self.max_degree,
            self.max_level,
            tuple(self.tree.get(self.max_level, ())),
        )


class RangeQueryResultsHelper:
    """Feed results with add_result(); done() returns
    (raw_reads | None, summary | None) — exactly one non-None unless no
    results were ever added (then raw_reads is an empty tuple)."""

    def __init__(self, enable_hashing: bool, max_degree: int = 50):
        self.pending: List[rw.KVRead] = []
        self.hashing = enable_hashing
        self.max_degree = max_degree
        self.mt = _MerkleTree(max_degree) if enable_hashing else None

    def add_result(self, read: rw.KVRead) -> None:
        self.pending.append(read)
        if self.hashing and len(self.pending) > self.max_degree:
            self._process_pending()

    def _process_pending(self) -> None:
        assert self.mt is not None
        data = serialize_kv_reads(self.pending)
        self.pending = []
        self.mt.update(_hash(data))

    def merkle_summary(self) -> Optional[Tuple[int, int, Tuple[bytes, ...]]]:
        """Intermediate summary for the validator's early-mismatch exit
        (GetMerkleSummary)."""
        if not self.hashing:
            return None
        return self.mt.summary()

    def done(self):
        if not self.hashing or self.mt.is_empty():
            return tuple(self.pending), None
        if self.pending:
            self._process_pending()
        self.mt.done()
        return (), self.mt.summary()
