"""Ledger layer: rwsets, versioned state DB, MVCC, block store, kvledger."""
