"""Versioned state database (reference statedb SPI + stateleveldb).

An embedded ordered KV store holding (value, version) per (namespace, key)
plus the hashed private-data namespaces (privacyenabledstate analog). The
in-memory index is a dict plus a sorted-key view for range scans; the
kvledger layer persists through snapshots of the block store (state is a
derived cache, rebuildable — the reference's crash-consistency model,
SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

from fabric_tpu.ledger.rwset import Version


@dataclass(frozen=True)
class VersionedValue:
    value: bytes
    version: Version
    metadata: Optional[bytes] = None  # serialized KVMetadataWrite entries


class BatchEntry(NamedTuple):
    """One pending update: value None = key delete; metadata is the
    serialized state metadata carried with the write (None = no
    metadata / metadata deleted)."""

    value: Optional[bytes]
    version: Version
    metadata: Optional[bytes] = None


class UpdateBatch:
    """Pending writes of a block (reference statedb.UpdateBatch): puts AND
    deletes both carry the committing version; deletes shadow reads."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str], BatchEntry] = {}

    def put(
        self,
        ns: str,
        key: str,
        value: bytes,
        version: Version,
        metadata: Optional[bytes] = None,
    ) -> None:
        self._updates[(ns, key)] = BatchEntry(value, version, metadata)

    def delete(self, ns: str, key: str, version: Version) -> None:
        self._updates[(ns, key)] = BatchEntry(None, version)

    def exists(self, ns: str, key: str) -> bool:
        return (ns, key) in self._updates

    def get(self, ns: str, key: str) -> Optional[BatchEntry]:
        return self._updates.get((ns, key))

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)


class HashedUpdateBatch:
    """Private-data hashed writes: keyed (ns, collection, key_hash)."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str, bytes], BatchEntry] = {}

    def put(
        self,
        ns: str,
        coll: str,
        key_hash: bytes,
        value_hash: Optional[bytes],
        version: Version,
        metadata: Optional[bytes] = None,
    ) -> None:
        self._updates[(ns, coll, key_hash)] = BatchEntry(
            value_hash, version, metadata
        )

    def contains(self, ns: str, coll: str, key_hash: bytes) -> bool:
        return (ns, coll, key_hash) in self._updates

    def get(self, ns: str, coll: str, key_hash: bytes) -> Optional[BatchEntry]:
        return self._updates.get((ns, coll, key_hash))

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)


class PvtUpdateBatch:
    """Cleartext private-data writes keyed (ns, collection, key)
    (reference privacyenabledstate UpdateBatch.PvtUpdates)."""

    def __init__(self):
        self._updates: Dict[Tuple[str, str, str], BatchEntry] = {}

    def put(
        self,
        ns: str,
        coll: str,
        key: str,
        value: Optional[bytes],
        version: Version,
    ) -> None:
        self._updates[(ns, coll, key)] = BatchEntry(value, version)

    def get(self, ns: str, coll: str, key: str) -> Optional[BatchEntry]:
        return self._updates.get((ns, coll, key))

    def items(self):
        return self._updates.items()

    def __len__(self):
        return len(self._updates)


class VersionedDB:
    """Committed state: (ns, key) -> VersionedValue, ordered per namespace."""

    def __init__(self):
        self._data: Dict[str, Dict[str, VersionedValue]] = {}
        self._sorted_keys: Dict[str, List[str]] = {}
        self._hashed: Dict[Tuple[str, str, bytes], VersionedValue] = {}
        self._pvt: Dict[Tuple[str, str, str], VersionedValue] = {}
        # coherence stamp for device-resident derived caches (see
        # SqliteVersionedDB.state_generation): out-of-band mutators
        # (rollback / rebuild / anything bypassing the validator flow)
        # must bump_generation() so resident version tables fail closed
        self.state_generation = 0

    def bump_generation(self) -> None:
        self.state_generation += 1

    # -- reads ------------------------------------------------------------
    def get_state(self, ns: str, key: str) -> Optional[VersionedValue]:
        return self._data.get(ns, {}).get(key)

    def get_state_metadata(self, ns: str, key: str) -> Optional[bytes]:
        """Serialized VALIDATION_PARAMETER et al. for a key (reference
        statedb GetStateMetadata)."""
        vv = self.get_state(ns, key)
        return vv.metadata if vv else None

    def get_version(self, ns: str, key: str) -> Optional[Version]:
        vv = self.get_state(ns, key)
        return vv.version if vv else None

    def get_hashed_state(
        self, ns: str, coll: str, key_hash: bytes
    ) -> Optional[VersionedValue]:
        return self._hashed.get((ns, coll, key_hash))

    def get_hashed_metadata(
        self, ns: str, coll: str, key_hash: bytes
    ) -> Optional[bytes]:
        vv = self._hashed.get((ns, coll, key_hash))
        return vv.metadata if vv else None

    def get_key_hash_version(self, ns: str, coll: str, key_hash: bytes) -> Optional[Version]:
        entry = self._hashed.get((ns, coll, key_hash))
        return entry.version if entry else None

    def get_private_data(
        self, ns: str, coll: str, key: str
    ) -> Optional[VersionedValue]:
        """Cleartext private read (privacyenabledstate GetPrivateData);
        returns None when this peer never received the collection data."""
        return self._pvt.get((ns, coll, key))

    def get_state_range(
        self, ns: str, start_key: str, end_key: str, include_end: bool
    ) -> Iterator[Tuple[str, VersionedValue]]:
        """Sorted iteration over [start_key, end_key) or [..., end_key].
        Empty end_key means an open-ended scan (reference semantics)."""
        keys = self._sorted_keys.get(ns, [])
        i = bisect.bisect_left(keys, start_key)
        table = self._data.get(ns, {})
        while i < len(keys):
            k = keys[i]
            if end_key:
                if include_end:
                    if k > end_key:
                        break
                elif k >= end_key:
                    break
            yield k, table[k]
            i += 1

    # -- writes -----------------------------------------------------------
    def apply_updates(
        self,
        batch: UpdateBatch,
        hashed: Optional[HashedUpdateBatch] = None,
        pvt: Optional[PvtUpdateBatch] = None,
    ) -> None:
        for (ns, key), entry in batch.items():
            table = self._data.setdefault(ns, {})
            keys = self._sorted_keys.setdefault(ns, [])
            if entry.value is None:
                if key in table:
                    del table[key]
                    idx = bisect.bisect_left(keys, key)
                    if idx < len(keys) and keys[idx] == key:
                        keys.pop(idx)
            else:
                if key not in table:
                    bisect.insort(keys, key)
                table[key] = VersionedValue(
                    entry.value, entry.version, entry.metadata
                )
        if hashed is not None:
            for (ns, coll, key_hash), entry in hashed.items():
                if entry.value is None:
                    self._hashed.pop((ns, coll, key_hash), None)
                else:
                    self._hashed[(ns, coll, key_hash)] = VersionedValue(
                        entry.value, entry.version, entry.metadata
                    )
        if pvt is not None:
            for (ns, coll, key), entry in pvt.items():
                if entry.value is None:
                    self._pvt.pop((ns, coll, key), None)
                else:
                    self._pvt[(ns, coll, key)] = VersionedValue(
                        entry.value, entry.version
                    )

    def num_keys(self) -> int:
        return sum(len(t) for t in self._data.values())

    # -- full iteration (snapshot export) ----------------------------------
    def iter_all_state(self) -> Iterator[Tuple[str, str, VersionedValue]]:
        """Deterministic (ns, key, value) iteration over all public state."""
        for ns in sorted(self._data):
            table = self._data[ns]
            for key in self._sorted_keys[ns]:
                yield ns, key, table[key]

    def iter_all_hashed(
        self,
    ) -> Iterator[Tuple[str, str, bytes, VersionedValue]]:
        for ns, coll, kh in sorted(self._hashed):
            yield ns, coll, kh, self._hashed[(ns, coll, kh)]

    # -- rich queries (statecouchdb.go:695 analog) -------------------------
    def execute_query(self, ns: str, query):
        """Selector query over a namespace's JSON values (see
        fabric_tpu.ledger.queries). Not phantom-protected, like the
        reference's CouchDB queries."""
        from fabric_tpu.ledger import queries as rich_queries

        table = self._data.get(ns, {})
        rows = (
            (key, table[key].value) for key in self._sorted_keys.get(ns, [])
        )
        return rich_queries.execute(rows, query)

    def execute_query_paginated(
        self, ns: str, query, page_size: int, bookmark: str = ""
    ):
        """One page + next bookmark (statecouchdb.go:653
        ExecuteQueryWithPagination)."""
        from fabric_tpu.ledger import queries as rich_queries

        table = self._data.get(ns, {})
        rows = (
            (key, table[key].value) for key in self._sorted_keys.get(ns, [])
        )
        return rich_queries.execute_paginated(rows, query, page_size, bookmark)
