"""Private-data collection model (reference core/common/privdata/
collection.go, simplecollection.go, membershipinfo.go).

CollectionAccess wraps a StaticCollectionConfig: membership is a
signature-policy evaluation over the peer's identity (SimpleCollection
.AccessFilter), BTL feeds the pvtdata store's purge policy, and
member_only_read/write gate chaincode access at simulation time
(core/chaincode/handler.go errorIfCreatorHasNoReadAccess).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from fabric_tpu.policy import proto_convert
from fabric_tpu.policy.ast import SignaturePolicyEnvelope
from fabric_tpu.protos import collection_pb2, protoutil


class NoSuchCollectionError(Exception):
    pass


class CollectionAccess:
    def __init__(self, cfg: collection_pb2.StaticCollectionConfig):
        self.name = cfg.name
        self.required_peer_count = cfg.required_peer_count
        self.maximum_peer_count = cfg.maximum_peer_count
        self.block_to_live = cfg.block_to_live
        self.member_only_read = cfg.member_only_read
        self.member_only_write = cfg.member_only_write
        self._policy_env: Optional[SignaturePolicyEnvelope] = None
        if cfg.member_orgs_policy.HasField("signature_policy"):
            self._policy_env = proto_convert.unmarshal_envelope(
                cfg.member_orgs_policy.signature_policy.SerializeToString()
            )

    def is_member(self, identity, msp) -> bool:
        """AccessFilter: does the identity satisfy the member-orgs policy?
        Principal matching only — no signature involved (the reference
        evaluates the policy over a SignedData with the membership
        identity; satisfaction is by principal)."""
        if self._policy_env is None:
            return False
        from fabric_tpu.policy.evaluator import evaluate_host
        from fabric_tpu.policy.proto_convert import principal_for

        import numpy as np

        num_p = len(self._policy_env.identities)
        sat = np.zeros((1, num_p), dtype=bool)
        for p, principal_proto in enumerate(self._policy_env.identities):
            try:
                msp.satisfies_principal(identity, principal_for(principal_proto))
                sat[0, p] = True
            except Exception:  # fablint: disable=broad-except  # mismatch = sat stays False, the explicit mask write
                pass
        return evaluate_host(self._policy_env, sat)


class CollectionStore:
    """Per-channel collection registry resolved from lifecycle definitions
    (reference core/common/privdata/store.go backed by lscc/_lifecycle)."""

    def __init__(
        self,
        # ns -> serialized CollectionConfigPackage (lifecycle.collections)
        get_collections_bytes: Callable[[str], bytes],
    ):
        self._get = get_collections_bytes

    def package(self, ns: str) -> collection_pb2.CollectionConfigPackage:
        raw = self._get(ns) or b""
        pkg = collection_pb2.CollectionConfigPackage()
        if raw:
            pkg.ParseFromString(raw)
        return pkg

    def collection(self, ns: str, coll: str) -> CollectionAccess:
        for cfg in self.package(ns).config:
            static = cfg.static_collection_config
            if static.name == coll:
                return CollectionAccess(static)
        raise NoSuchCollectionError(f"collection {ns}/{coll} not found")

    def has_collection(self, ns: str, coll: str) -> bool:
        try:
            self.collection(ns, coll)
            return True
        except NoSuchCollectionError:
            return False

    def btl_policy(self) -> Callable[[str, str], int]:
        """(ns, coll) -> block_to_live for the pvtdata store (0 = forever)."""

        def btl(ns: str, coll: str) -> int:
            try:
                return int(self.collection(ns, coll).block_to_live)
            except NoSuchCollectionError:
                return 0

        return btl


def build_collection_config_package(
    collections: Sequence[Dict],
) -> collection_pb2.CollectionConfigPackage:
    """Helper for tests/tools: [{name, policy (DSL or env), required/max/
    btl/member_only_*}] -> proto package."""
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.policy.proto_convert import marshal_envelope

    pkg = collection_pb2.CollectionConfigPackage()
    for c in collections:
        cfg = pkg.config.add()
        static = cfg.static_collection_config
        static.name = c["name"]
        policy = c.get("policy")
        if isinstance(policy, str):
            policy = from_dsl(policy)
        if policy is not None:
            static.member_orgs_policy.signature_policy.ParseFromString(
                marshal_envelope(policy)
            )
        static.required_peer_count = c.get("required_peer_count", 0)
        static.maximum_peer_count = c.get("maximum_peer_count", 1)
        static.block_to_live = c.get("block_to_live", 0)
        static.member_only_read = c.get("member_only_read", False)
        static.member_only_write = c.get("member_only_write", False)
    return pkg
