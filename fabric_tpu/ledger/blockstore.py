"""Append-only block store with indexes (reference common/ledger/blkstorage).

Format: one file per channel of varint-length-prefixed serialized Block
protos (the reference's blockfile format, blockfile_mgr.go). Indexes
(number -> offset, hash -> number, txid -> (number, txNum)) are rebuilt by
scanning on open — the block file is the source of truth, everything else
is a derived cache (the reference's crash-consistency model, SURVEY.md §5).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.protos import common_pb2, protoutil


def _write_varint(f, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            f.write(bytes([b | 0x80]))
        else:
            f.write(bytes([b]))
            return


def _read_varint(f) -> Optional[int]:
    shift = 0
    out = 0
    while True:
        c = f.read(1)
        if not c:
            return None if shift == 0 else _raise_trunc()
        b = c[0]
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            return out
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _raise_trunc():
    raise ValueError("truncated block file")


def extract_tx_ids(block: common_pb2.Block) -> List[str]:
    """Best-effort TxID extraction per tx (empty string when unparsable)."""
    out = []
    for data in block.data.data:
        txid = ""
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, data)
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            txid = chdr.tx_id
        except ValueError:
            pass
        out.append(txid)
    return out


class BlockStore:
    """One channel's chain on disk."""

    def __init__(self, path: str):
        self.path = path
        self._offsets: List[int] = []  # (number - base) -> file offset
        self._by_hash: Dict[bytes, int] = {}
        self._by_txid: Dict[str, Tuple[int, int]] = {}
        self._last_hash = b""
        # Snapshot bootstrap (reference bootstrapFromSnapshotInfo): a store
        # created from a snapshot starts at a nonzero height with no block
        # files for the prefix; base.meta records (base_height, last_hash).
        self._base = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        meta_path = self.path + ".base"
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                raw = f.read().split(b"\n", 1)
            self._base = int(raw[0])
            self._last_hash = bytes.fromhex(raw[1].decode()) if len(raw) > 1 else b""
        self._load_pretxids()
        self._rebuild_index()
        self._f = open(self.path, "ab")

    def _load_pretxids(self) -> None:
        """Pre-snapshot TxIDs (duplicate-TxID protection for txs whose
        blocks are not stored) persist in a sidecar file, or a restart
        would forget them and re-admit replayed transactions."""
        pretx_path = self.path + ".pretxids"
        if os.path.exists(pretx_path):
            with open(pretx_path) as f:
                for line in f:
                    txid = line.strip()
                    if txid:
                        self._by_txid.setdefault(txid, (-1, -1))

    @classmethod
    def bootstrap_from_snapshot(
        cls,
        path: str,
        height: int,
        last_hash: bytes,
        pre_snapshot_txids: Optional[List[str]] = None,
    ) -> "BlockStore":
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            raise ValueError(f"block store already exists at {path}")
        with open(path + ".base", "wb") as f:
            f.write(str(height).encode() + b"\n" + last_hash.hex().encode())
        if pre_snapshot_txids:
            with open(path + ".pretxids", "w") as f:
                for txid in pre_snapshot_txids:
                    f.write(txid + "\n")
        return cls(path)

    # -- index ------------------------------------------------------------
    def _rebuild_index(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            valid_end = 0
            while True:
                off = f.tell()
                try:
                    ln = _read_varint(f)
                    if ln is None:
                        break
                    raw = f.read(ln)
                    if len(raw) != ln:
                        break  # partial tail write -> truncate
                    block = protoutil.unmarshal(common_pb2.Block, raw)
                except ValueError:
                    break  # unparseable tail (torn write) -> truncate
                # A parseable block with the wrong number is NOT a torn
                # tail: halt and preserve the file rather than silently
                # truncating committed blocks.
                self._index_block(block, off)
                valid_end = f.tell()
        size = os.path.getsize(self.path)
        if size != valid_end:
            # crash recovery: drop the partial tail (blockfile_helper.go)
            with open(self.path, "ab") as f:
                f.truncate(valid_end)

    def _index_block(self, block: common_pb2.Block, offset: int) -> None:
        num = block.header.number
        if num != self._base + len(self._offsets):
            raise ValueError(f"out-of-order block {num}")
        self._offsets.append(offset)
        h = protoutil.block_header_hash(block.header)
        self._by_hash[h] = num
        self._last_hash = h
        for tx_num, txid in enumerate(extract_tx_ids(block)):
            if txid and txid not in self._by_txid:
                self._by_txid[txid] = (num, tx_num)

    # -- writes -----------------------------------------------------------
    def add_block(self, block: common_pb2.Block) -> None:
        if block.header.number != self.height:
            raise ValueError(
                f"block number should be {self.height} but is {block.header.number}"
            )
        if self.height > 0 and block.header.previous_hash != self._last_hash:
            raise ValueError("unexpected previous-block hash")
        off = self._f.tell()
        raw = block.SerializeToString()
        _write_varint(self._f, len(raw))
        self._f.write(raw)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._index_block(block, off)

    # -- reads ------------------------------------------------------------
    @property
    def height(self) -> int:
        return self._base + len(self._offsets)

    @property
    def base_height(self) -> int:
        """First block number actually present (0 unless snapshot-bootstrapped)."""
        return self._base

    @property
    def last_block_hash(self) -> bytes:
        return self._last_hash

    def get_block_by_number(self, number: int) -> Optional[common_pb2.Block]:
        idx = number - self._base
        if idx < 0 or idx >= len(self._offsets):
            return None
        with open(self.path, "rb") as f:
            f.seek(self._offsets[idx])
            ln = _read_varint(f)
            return protoutil.unmarshal(common_pb2.Block, f.read(ln))

    def get_block_by_hash(self, block_hash: bytes) -> Optional[common_pb2.Block]:
        num = self._by_hash.get(block_hash)
        return None if num is None else self.get_block_by_number(num)

    def get_tx_loc(self, txid: str) -> Optional[Tuple[int, int]]:
        return self._by_txid.get(txid)

    def tx_exists(self, txid: str) -> bool:
        return txid in self._by_txid

    def iter_blocks(self, start: int = 0) -> Iterator[common_pb2.Block]:
        for n in range(max(start, self._base), self.height):
            yield self.get_block_by_number(n)

    def truncate_to(self, target_height: int) -> None:
        """Rollback support (reference blkstorage reset.go/rollback.go):
        drop every block with number >= target_height and rebuild the
        derived indexes."""
        if target_height < self._base:
            raise ValueError(
                f"cannot roll back below snapshot base {self._base}"
            )
        if target_height >= self.height:
            return
        keep = target_height - self._base
        self._f.close()
        cut = (
            self._offsets[keep]
            if keep < len(self._offsets)
            else os.path.getsize(self.path)
        )
        with open(self.path, "ab") as f:
            f.truncate(cut)
        self._offsets = []
        self._by_hash = {}
        self._by_txid = {}
        self._last_hash = b""
        meta_path = self.path + ".base"
        if os.path.exists(meta_path) and self._base:
            with open(meta_path, "rb") as f:
                raw = f.read().split(b"\n", 1)
            self._last_hash = (
                bytes.fromhex(raw[1].decode()) if len(raw) > 1 else b""
            )
        self._load_pretxids()  # the sidecar survives rollbacks
        self._rebuild_index()
        self._f = open(self.path, "ab")

    def close(self) -> None:
        self._f.close()
