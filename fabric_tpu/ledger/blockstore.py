"""Append-only block store with indexes (reference common/ledger/blkstorage).

Format: one file per channel of doubly-checksummed frames —
``u32 len || u32 crc32(len) || payload || u32 crc32(payload)`` of
serialized Block protos (the reference's blockfile format,
blockfile_mgr.go, with both the length prefix AND the payload covered
by checksums).  Indexes (number -> offset, hash -> number, txid ->
(number, txNum)) are rebuilt by scanning on open — the block file is
the source of truth, everything else is a derived cache (the
reference's crash-consistency model, SURVEY.md §5).

Crash-consistency contract (fabcrash, PR 13): a crash can only ever
leave a PREFIX of one in-flight frame at the tail.  Recovery therefore
repairs exactly that — a truncated header, a frame shorter than its
(header-checksum-validated) length prefix, or a payload-checksum
mismatch that reaches EOF — by truncating to the last whole frame
(loud log + ``fabric_ledger_torn_tail_total``).  Damage a single
interrupted append cannot explain (a full header whose own checksum
fails, a bad frame with valid bytes AFTER it, a checksum-valid frame
that does not parse or is out of order) is corruption, and the store
fails closed: it refuses to open (:class:`LedgerCorruptionError`)
rather than silently drop committed blocks.  The header checksum is
what makes the torn/corrupt split SOUND: without it, a flipped bit
inflating a mid-file length prefix would masquerade as a torn tail and
silently truncate every later committed block.
``FABRIC_TPU_RECOVERY_STRICT=0`` downgrades the refusal to an
operator-forced salvage (truncate to the last good frame) for
forensics and manual repair.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.protos import common_pb2, protoutil

logger = must_get_logger("blockstore")


class LedgerCorruptionError(ValueError):
    """The on-disk store is inconsistent in a way recovery cannot repair
    forward (damage beyond one interrupted append).  Raised instead of
    serving: a peer must fail closed and loud, never serve a chain it
    cannot prove whole.  Subclasses ValueError so callers treating
    store errors generically keep working."""


def recovery_strict() -> bool:
    """Live read of the FABRIC_TPU_RECOVERY_STRICT toggle (default
    strict).  ``0`` switches refusals into salvage-and-log: the store
    truncates to the last provably-whole record instead of refusing to
    open — an operator forensics mode, never a default."""
    return os.environ.get("FABRIC_TPU_RECOVERY_STRICT", "1") != "0"


def refuse_corrupt(log, subject: str, why: str, reason: str, salvage: str) -> None:
    """The ONE refusal contract every store shares: count the refusal,
    log CRITICAL, and raise :class:`LedgerCorruptionError` (strict, the
    default) or log the operator-forced salvage and return
    (FABRIC_TPU_RECOVERY_STRICT=0).  ``salvage`` names what salvage
    mode will do — it doubles as the hint in the strict message."""
    fabobs.obs_count(
        "fabric_ledger_recovery_refusals_total", reason=reason
    )
    if recovery_strict():
        log.critical(
            "%s is corrupt (%s): refusing to serve; set "
            "FABRIC_TPU_RECOVERY_STRICT=0 to %s for forensics",
            subject, why, salvage,
        )
        raise LedgerCorruptionError(f"{subject}: {why}")
    log.critical(
        "%s is corrupt (%s): SALVAGING — %s "
        "(FABRIC_TPU_RECOVERY_STRICT=0)",
        subject, why, salvage,
    )


#: frame header: u32 payload length + u32 crc32 of those length bytes.
#: A torn append leaves a PREFIX of a valid frame, so any full 8-byte
#: header at a frame boundary either validates or proves corruption —
#: which is what lets recovery trust the length when classifying a
#: short frame as a torn tail.
_HEADER = struct.Struct("<II")


def frame_header(payload_len: int) -> bytes:
    len_bytes = struct.pack("<I", payload_len)
    return len_bytes + struct.pack("<I", zlib.crc32(len_bytes))


def read_frame_header(raw8: bytes) -> Optional[int]:
    """Payload length from a full 8-byte header, or None when the
    header's own checksum fails (corruption, never a torn write)."""
    ln, hcrc = _HEADER.unpack(raw8)
    if zlib.crc32(raw8[:4]) != hcrc:
        return None
    return ln


def fsync_dir(path: str) -> None:
    """fsync the directory containing ``path``: on some filesystems a
    file-only fsync persists the data but not the metadata (size /
    directory entry) that makes it reachable after a crash."""
    dirname = os.path.dirname(path) or "."
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # O_RDONLY on a directory unsupported (exotic fs)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def extract_tx_ids(block: common_pb2.Block) -> List[str]:
    """Best-effort TxID extraction per tx (empty string when unparsable)."""
    out = []
    for data in block.data.data:
        txid = ""
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, data)
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            txid = chdr.tx_id
        except ValueError:
            pass
        out.append(txid)
    return out


class BlockStore:
    """One channel's chain on disk."""

    def __init__(self, path: str):
        self.path = path
        self._offsets: List[int] = []  # (number - base) -> file offset
        self._by_hash: Dict[bytes, int] = {}
        self._by_txid: Dict[str, Tuple[int, int]] = {}
        self._last_hash = b""
        # Snapshot bootstrap (reference bootstrapFromSnapshotInfo): a store
        # created from a snapshot starts at a nonzero height with no block
        # files for the prefix; base.meta records (base_height, last_hash).
        self._base = 0
        #: bytes dropped by the last torn-tail repair (0 = clean open);
        #: crash harness introspection, reset on every _rebuild_index
        self.torn_tail_bytes = 0
        # close() may race a node-shell teardown thread against the
        # owner: the flag flips under a leaf lock
        self._close_lock = threading.Lock()
        self._closed = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        meta_path = self.path + ".base"
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                raw = f.read().split(b"\n", 1)
            self._base = int(raw[0])
            self._last_hash = bytes.fromhex(raw[1].decode()) if len(raw) > 1 else b""
        self._load_pretxids()
        self._rebuild_index()
        self._f = open(self.path, "ab")

    def _load_pretxids(self) -> None:
        """Pre-snapshot TxIDs (duplicate-TxID protection for txs whose
        blocks are not stored) persist in a sidecar file, or a restart
        would forget them and re-admit replayed transactions."""
        pretx_path = self.path + ".pretxids"
        if os.path.exists(pretx_path):
            with open(pretx_path) as f:
                for line in f:
                    txid = line.strip()
                    if txid:
                        self._by_txid.setdefault(txid, (-1, -1))

    @classmethod
    def bootstrap_from_snapshot(
        cls,
        path: str,
        height: int,
        last_hash: bytes,
        pre_snapshot_txids: Optional[List[str]] = None,
    ) -> "BlockStore":
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            raise ValueError(f"block store already exists at {path}")
        with open(path + ".base", "wb") as f:
            f.write(str(height).encode() + b"\n" + last_hash.hex().encode())
        if pre_snapshot_txids:
            with open(path + ".pretxids", "w") as f:
                for txid in pre_snapshot_txids:
                    f.write(txid + "\n")
        return cls(path)

    # -- index ------------------------------------------------------------
    def _refuse(self, why: str) -> None:
        """Irreparable damage: fail closed (strict, the default) or let
        the caller salvage-truncate (FABRIC_TPU_RECOVERY_STRICT=0)."""
        refuse_corrupt(
            logger, f"block store {self.path}", why, "corrupt-chain",
            "truncate to the last whole block",
        )

    def _rebuild_index(self) -> None:
        self.torn_tail_bytes = 0
        if not os.path.exists(self.path):
            return
        size = os.path.getsize(self.path)
        refused = False  # salvage truncation, NOT a benign torn tail
        with open(self.path, "rb") as f:
            valid_end = 0
            while True:
                off = f.tell()
                header = f.read(_HEADER.size)
                if not header:
                    break  # clean EOF at a frame boundary
                if len(header) < _HEADER.size:
                    break  # torn header at the tail
                ln = read_frame_header(header)
                if ln is None:
                    # a torn append leaves a PREFIX of a valid frame, so
                    # a full header that fails its own checksum is
                    # corruption — and the length cannot be trusted to
                    # classify anything beyond it
                    self._refuse(f"frame header checksum failed at offset {off}")
                    refused = True
                    break
                raw = f.read(ln)
                crc = f.read(4)
                if len(raw) != ln or len(crc) != 4:
                    # header-validated length overshoots EOF: torn tail
                    break
                if zlib.crc32(raw) != struct.unpack("<I", crc)[0]:
                    # a torn write can only damage the LAST frame; a bad
                    # checksum with valid bytes after it is corruption
                    if f.tell() < size:
                        self._refuse(f"payload checksum mismatch at offset {off}")
                        refused = True
                    break
                try:
                    block = protoutil.unmarshal(common_pb2.Block, raw)
                except ValueError:
                    # checksum-valid but unparseable: fully written
                    # garbage, not a torn append — never repairable
                    self._refuse(f"checksummed frame at offset {off} does not parse")
                    refused = True
                    break
                try:
                    # a parseable block with the wrong number is NOT a
                    # torn tail either: corruption, fail closed
                    self._index_block(block, off)
                except ValueError as exc:
                    self._refuse(str(exc))
                    refused = True
                    break
                valid_end = f.tell()
        if size != valid_end:
            dropped = size - valid_end
            if refused:
                # operator-forced salvage of refused corruption: the
                # refusal counter already fired — do NOT book this as a
                # benign torn-tail repair
                logger.critical(
                    "block store %s: salvage dropped %d bytes after "
                    "block %d (FABRIC_TPU_RECOVERY_STRICT=0)",
                    self.path, dropped, self.height - 1,
                )
            else:
                self.torn_tail_bytes = dropped
                logger.warning(
                    "block store %s: truncating %d-byte torn tail after "
                    "block %d (crash recovery)",
                    self.path, dropped, self.height - 1,
                )
                fabobs.obs_count(
                    "fabric_ledger_torn_tail_total", store="chain"
                )
            with open(self.path, "ab") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())
            fsync_dir(self.path)

    def _index_block(self, block: common_pb2.Block, offset: int) -> None:
        num = block.header.number
        if num != self._base + len(self._offsets):
            raise ValueError(f"out-of-order block {num}")
        self._offsets.append(offset)
        h = protoutil.block_header_hash(block.header)
        self._by_hash[h] = num
        self._last_hash = h
        for tx_num, txid in enumerate(extract_tx_ids(block)):
            if txid and txid not in self._by_txid:
                self._by_txid[txid] = (num, tx_num)

    # -- writes -----------------------------------------------------------
    def add_block(self, block: common_pb2.Block) -> None:
        num = block.header.number
        if num != self.height:
            raise ValueError(
                f"block number should be {self.height} but is {num}"
            )
        if self.height > 0 and block.header.previous_hash != self._last_hash:
            raise ValueError("unexpected previous-block hash")
        off = self._f.tell()
        raw = block.SerializeToString()
        try:
            # three writes on purpose: a large payload bypasses the
            # Python buffer while the trailing checksum stays buffered,
            # so a kill in the pre_fsync window leaves a genuinely torn
            # frame for recovery to repair (the fabcrash matrix
            # exercises exactly this)
            self._f.write(frame_header(len(raw)))
            self._f.write(raw)
            self._f.write(struct.pack("<I", zlib.crc32(raw)))
            # kill window: frame (partially) in Python/OS buffers,
            # nothing guaranteed durable yet
            fault_point("blockstore.append.pre_fsync", key=int(num))
            self._f.flush()
            os.fsync(self._f.fileno())
            # kill window: frame durable, directory metadata possibly not
            fault_point("blockstore.append.post_fsync", key=int(num))
            fsync_dir(self.path)
            # kill window: fully durable, in-memory index not yet updated
            fault_point("blockstore.append.pre_index", key=int(num))
        except Exception:
            # a failed append (injected raise, ENOSPC, fsync error) must
            # not leave a partial frame in place: an in-process
            # redelivery retry would stack a duplicate frame AFTER it,
            # which strict recovery then refuses as mid-file damage.
            # Roll the file back to the pre-append offset.  (A kill
            # never reaches here — os._exit skips unwinding — so the
            # torn tail stays for restart recovery, as intended.)
            try:
                # best effort: close() flushes the buffer and may itself
                # raise the same underlying error (ENOSPC) — the
                # truncate below must still run
                self._f.close()
            except OSError:
                pass
            with open(self.path, "ab") as f:
                f.truncate(off)
                f.flush()
                os.fsync(f.fileno())
            self._f = open(self.path, "ab")
            raise
        self._index_block(block, off)

    # -- reads ------------------------------------------------------------
    @property
    def height(self) -> int:
        return self._base + len(self._offsets)

    @property
    def base_height(self) -> int:
        """First block number actually present (0 unless snapshot-bootstrapped)."""
        return self._base

    @property
    def last_block_hash(self) -> bytes:
        return self._last_hash

    def get_block_by_number(self, number: int) -> Optional[common_pb2.Block]:
        idx = number - self._base
        if idx < 0 or idx >= len(self._offsets):
            return None
        with open(self.path, "rb") as f:
            f.seek(self._offsets[idx])
            header = f.read(_HEADER.size)
            ln = (
                read_frame_header(header)
                if len(header) == _HEADER.size
                else None
            )
            raw = f.read(ln) if ln is not None else b""
            crc = f.read(4)
        if ln is None or len(raw) != ln or len(crc) != 4 or (
            zlib.crc32(raw) != struct.unpack("<I", crc)[0]
        ):
            # the frame checksummed clean at index-build time: this is
            # on-disk rot after open — never serve the damaged block
            raise LedgerCorruptionError(
                f"{self.path}: block {number} failed its checksum on read"
            )
        return protoutil.unmarshal(common_pb2.Block, raw)

    def get_block_by_hash(self, block_hash: bytes) -> Optional[common_pb2.Block]:
        num = self._by_hash.get(block_hash)
        return None if num is None else self.get_block_by_number(num)

    def get_tx_loc(self, txid: str) -> Optional[Tuple[int, int]]:
        return self._by_txid.get(txid)

    def tx_exists(self, txid: str) -> bool:
        return txid in self._by_txid

    def iter_blocks(self, start: int = 0) -> Iterator[common_pb2.Block]:
        for n in range(max(start, self._base), self.height):
            yield self.get_block_by_number(n)

    def truncate_to(self, target_height: int) -> None:
        """Rollback support (reference blkstorage reset.go/rollback.go):
        drop every block with number >= target_height and rebuild the
        derived indexes."""
        if target_height < self._base:
            raise ValueError(
                f"cannot roll back below snapshot base {self._base}"
            )
        if target_height >= self.height:
            return
        keep = target_height - self._base
        self._f.close()
        cut = (
            self._offsets[keep]
            if keep < len(self._offsets)
            else os.path.getsize(self.path)
        )
        with open(self.path, "ab") as f:
            f.truncate(cut)
            f.flush()
            os.fsync(f.fileno())
        fsync_dir(self.path)
        self._offsets = []
        self._by_hash = {}
        self._by_txid = {}
        self._last_hash = b""
        meta_path = self.path + ".base"
        if os.path.exists(meta_path) and self._base:
            with open(meta_path, "rb") as f:
                raw = f.read().split(b"\n", 1)
            self._last_hash = (
                bytes.fromhex(raw[1].decode()) if len(raw) > 1 else b""
            )
        self._load_pretxids()  # the sidecar survives rollbacks
        self._rebuild_index()
        self._f = open(self.path, "ab")
        with self._close_lock:
            self._closed = False

    def close(self) -> None:
        """Idempotent and safe on a partially-constructed store (recovery
        error paths close what exists)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        f = getattr(self, "_f", None)
        if f is not None:
            f.close()
