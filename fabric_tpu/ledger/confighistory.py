"""Collection-config history (reference core/ledger/confighistory/mgr.go:
record every committed change to a chaincode's collection config, keyed
by committing block, and answer "most recent config at or below block N"
— what pvt-data reconciliation and expiry need to interpret OLD blocks
under the config that was in force when they committed).

The manager watches committed update batches for writes to the
`_lifecycle` namespace's `.../Collections` field (the reference hooks
the same seam via its ledger commit listener / DeployedChaincodeInfoProvider)
and appends (namespace, block, config bytes) rows. Persistent ledgers
store rows in the state sqlite file; in-memory ledgers keep a dict.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from fabric_tpu.lifecycle import NAMESPACE as LIFECYCLE_NS

_COLLECTIONS_KEY = re.compile(r"^namespaces/fields/([^/]+)/Collections$")


class ConfigHistoryMgr:
    def __init__(self, db=None):
        """db: SqliteVersionedDB to persist into (shares the channel's
        state file), or None for the in-memory form."""
        self._db = db
        if db is not None:
            with db._lock:
                db._db.execute(
                    "CREATE TABLE IF NOT EXISTS confighistory ("
                    "ns TEXT NOT NULL, block INTEGER NOT NULL, "
                    "config BLOB NOT NULL, PRIMARY KEY (ns, block)"
                    ") WITHOUT ROWID"
                )
                db._db.commit()
        self._mem: Dict[str, List[Tuple[int, bytes]]] = {}

    # -- commit-time hook --------------------------------------------------
    def record_from_updates(self, block_num: int, updates) -> None:
        """Scan one block's public update batch for collection-config
        writes (confighistory mgr.go HandleStateUpdates)."""
        for (ns, key), entry in updates.items():
            if ns != LIFECYCLE_NS or entry.value is None:
                continue
            m = _COLLECTIONS_KEY.match(key)
            if not m:
                continue
            self.record(m.group(1), block_num, entry.value)

    def record(self, chaincode: str, block_num: int, config: bytes) -> None:
        if self._db is not None:
            with self._db._lock, self._db._db as conn:
                conn.execute(
                    "INSERT OR REPLACE INTO confighistory VALUES (?,?,?)",
                    (chaincode, block_num, config),
                )
        else:
            rows = self._mem.setdefault(chaincode, [])
            rows[:] = [r for r in rows if r[0] != block_num]
            rows.append((block_num, config))
            rows.sort()

    # -- queries (mgr.go MostRecentCollectionConfigBelow) ------------------
    def most_recent_below(
        self, chaincode: str, block_num: int
    ) -> Optional[Tuple[int, bytes]]:
        """(committing block, config bytes) of the newest config recorded
        at a block STRICTLY below block_num, or None."""
        if self._db is not None:
            row = self._db._one(
                "SELECT block, config FROM confighistory "
                "WHERE ns=? AND block<? ORDER BY block DESC LIMIT 1",
                (chaincode, block_num),
            )
            return (row[0], bytes(row[1])) if row else None
        best = None
        for blk, cfg in self._mem.get(chaincode, []):
            if blk < block_num:
                best = (blk, cfg)
        return best
