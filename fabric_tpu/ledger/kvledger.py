"""Per-channel ledger: commit orchestration (reference
core/ledger/kvledger/kv_ledger.go:596-680 + lockbased_txmgr.go).

Commit path per block:
1. MVCC validate-and-prepare against committed state + in-block writes
   (updates TRANSACTIONS_FILTER for MVCC/phantom conflicts);
2. commit-hash chaining: commitHash = SHA-256(varint(len(filter)) ||
   filter || deterministic-update-bytes || previousCommitHash), stored in
   block metadata COMMIT_HASH (kv_ledger.go:758-770) — byte-exact with
   the reference, including the txmgr Updates/KVWrite proto and
   order-preserving version encoding;
3. block appended to the block store;
4. state DB apply; history DB entries.

State and history are derived caches: on open, any blocks present in the
store but missing from state are replayed (recoverDBs analog).
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs, flogging
from fabric_tpu.common.faults import fault_point
from fabric_tpu.ledger.blockstore import BlockStore, refuse_corrupt
from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.pvtdatastore import MissingEntry, PvtDataStore, PvtEntry
from fabric_tpu.ledger.rwset import TxRwSet, Version
from fabric_tpu.ledger.statedb import (
    HashedUpdateBatch,
    PvtUpdateBatch,
    UpdateBatch,
    VersionedDB,
)
from fabric_tpu.protos import common_pb2, protoutil, txmgr_updates_pb2
from fabric_tpu.ledger.txparse import parse_transaction
from fabric_tpu.common.txflags import TxValidationCode, ValidationFlags

logger = flogging.must_get_logger("kvledger")


def encode_order_preserving_varuint64(n: int) -> bytes:
    """reference common/ledger/util EncodeOrderPreservingVarUint64:
    [num-significant-bytes][big-endian significant bytes]."""
    be = n.to_bytes(8, "big")
    stripped = be.lstrip(b"\x00")
    return bytes([len(stripped)]) + stripped


def version_to_bytes(v: Version) -> bytes:
    return encode_order_preserving_varuint64(
        v.block_num
    ) + encode_order_preserving_varuint64(v.tx_num)


def _proto_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def deterministic_update_bytes(
    updates: UpdateBatch, hashed: HashedUpdateBatch
) -> bytes:
    """txmgr deterministicBytesForPubAndHashUpdates: namespaces sorted,
    public writes then collections (sorted), keys sorted; namespace/
    collection fields set only on the first entry of each group; the empty
    namespace (channel config) is skipped."""
    # NB: metadata is deliberately excluded from the commit hash —
    # reference update_batch_bytes.go only serializes value writes.
    pub_by_ns: Dict[str, Dict[str, Tuple[Optional[bytes], Version]]] = {}
    for (ns, key), entry in updates.items():
        pub_by_ns.setdefault(ns, {})[key] = (entry.value, entry.version)
    hashed_by_ns: Dict[str, Dict[str, Dict[bytes, Tuple[Optional[bytes], Version]]]] = {}
    for (ns, coll, key_hash), entry in hashed.items():
        hashed_by_ns.setdefault(ns, {}).setdefault(coll, {})[key_hash] = (
            entry.value,
            entry.version,
        )

    msg = txmgr_updates_pb2.Updates()
    for ns in sorted(set(pub_by_ns) | set(hashed_by_ns)):
        if ns == "":
            continue
        first_in_ns = True

        def add(key: bytes, value: Optional[bytes], version: Version, coll: str = ""):
            # `coll` is set only on the first entry of a collection group
            # (caller passes "" for the rest), matching the reference's
            # field-elision rule for both namespace and collection.
            nonlocal first_in_ns
            kv = msg.kvwrites.add()
            if first_in_ns:
                kv.namespace = ns.encode()
                first_in_ns = False
            if coll:
                kv.collection = coll.encode()
            kv.key = key
            kv.isDelete = value is None
            if value is not None:
                kv.value = value
            kv.version_bytes = version_to_bytes(version)

        for key in sorted(pub_by_ns.get(ns, {})):
            value, version = pub_by_ns[ns][key]
            add(key.encode(), value, version)
        for coll in sorted(hashed_by_ns.get(ns, {})):
            for j, key_hash in enumerate(sorted(hashed_by_ns[ns][coll])):
                vh, version = hashed_by_ns[ns][coll][key_hash]
                add(key_hash, vh, version, coll=coll if j == 0 else "")
    return msg.SerializeToString()


def pvt_data_matches_hashes(
    rwset: Optional[TxRwSet], ns: str, coll: str, raw: bytes
) -> bool:
    """Does a cleartext KVRWSet match the tx's on-block hashed writes for
    (ns, coll)? Used to screen untrusted (gossip-fetched) private data
    before commit — a mismatch is treated as missing, never an error
    (reference gossip/privdata purge of invalid fetched data)."""
    from fabric_tpu.protos import kv_rwset_pb2

    expected: Dict[bytes, Tuple[bool, bytes]] = {}
    if rwset is not None:
        for ns_rw in rwset.ns_rw_sets:
            if ns_rw.namespace != ns:
                continue
            for c in ns_rw.coll_hashed:
                if c.collection_name == coll:
                    for hw in c.hashed_writes:
                        expected[hw.key_hash] = (hw.is_delete, hw.value_hash)
    kv = kv_rwset_pb2.KVRWSet()
    try:
        kv.ParseFromString(raw)
    except Exception:  # fablint: disable=broad-except  # malformed pvt payload = explicit False (lane invalid)
        return False
    for w in kv.writes:
        kh = hashlib.sha256(w.key.encode()).digest()
        exp = expected.get(kh)
        if exp is None:
            return False
        is_del, vh = exp
        if w.is_delete != is_del:
            return False
        if not w.is_delete and hashlib.sha256(w.value).digest() != vh:
            return False
    return True


class KVLedger:
    """One channel's ledger (block store + state + history).

    `persistent=True` (the default) keeps state + history in an embedded
    on-disk B-tree (fabric_tpu.ledger.persistent, the stateleveldb
    analog) with a per-block savepoint, so reopening a tall ledger
    replays only the blocks committed after the last durable state write
    instead of the whole chain (kv_ledger.go recoverDBs). In-memory mode
    remains for simulation/tests and rebuilds everything by replay."""

    def __init__(
        self,
        ledger_dir: str,
        channel_id: str,
        btl_policy=None,
        persistent: bool = True,
        device_mvcc: bool = False,
        # optional public-state mirror (ledger/statecouch.CouchStateAdapter):
        # receives each block's public UpdateBatch after the embedded
        # commit — best-effort, a mirror outage never blocks consensus
        state_mirror=None,
    ):
        self.state_mirror = state_mirror
        self.channel_id = channel_id
        self.persistent = persistent
        # SURVEY P5: resolve block-internal MVCC invalidation chains on
        # device (mvcc_device.DeviceValidator) instead of the Python scan
        self.device_mvcc = device_mvcc
        self.history: Dict[Tuple[str, str], List[Version]] = {}
        self.commit_hash = b""
        self._closed = False
        try:
            self.block_store = BlockStore(
                os.path.join(ledger_dir, f"{channel_id}.chain")
            )
            self.pvt_store = PvtDataStore(
                os.path.join(ledger_dir, f"{channel_id}.pvtdata"),
                btl_policy=btl_policy,
            )
            if persistent:
                from fabric_tpu.ledger.persistent import SqliteVersionedDB

                self.state_db = SqliteVersionedDB(
                    os.path.join(ledger_dir, f"{channel_id}.state.db")
                )
            else:
                self.state_db = VersionedDB()
            from fabric_tpu.ledger.confighistory import ConfigHistoryMgr

            self.config_history = ConfigHistoryMgr(
                self.state_db if persistent else None
            )
            self._recover()
        except BaseException:
            # a refused recovery — whether raised opening a store (a
            # corrupt chain/pvtdata refuses in its constructor) or
            # during replay — must not leak the file handles already
            # open: the operator will reopen (possibly with
            # RECOVERY_STRICT=0) or run the offline admin CLI against
            # the same directory
            self.close()
            raise

    # -- recovery: replay the block store into derived state ---------------
    def _recover(self) -> None:
        """Replay blocks the store has but the derived caches lack
        (kv_ledger.go recoverDBs), hardened for the fabcrash kill
        windows:

        * block store AHEAD of the state db (crash after append, before
          the sqlite transaction committed): replay the gap idempotently
          into state + history + pvt (INSERT OR REPLACE semantics);
        * pvt store BEHIND a stored block (its torn tail was truncated):
          record missing-data markers so the reconciler re-fetches — the
          hashed writes are on-block and already replayed;
        * state db AHEAD of the block store (chain truncated behind our
          back): nothing can be repaired forward — refuse to serve
          (strict, the default) or rebuild the derived caches from the
          chain (FABRIC_TPU_RECOVERY_STRICT=0 salvage)."""
        height = self.block_store.height
        start = 0
        if self.persistent:
            savepoint = self.state_db.savepoint()
            if savepoint is not None:
                if savepoint >= height:
                    refuse_corrupt(
                        logger,
                        f"[{self.channel_id}] state db",
                        f"savepoint {savepoint} is AHEAD of block store "
                        f"height {height}: the chain lost committed "
                        f"blocks behind our back",
                        "statedb-ahead",
                        "rebuild derived state from the surviving chain",
                    )
                    self.state_db.clear()
                    savepoint = None
            if savepoint is not None:
                start = savepoint + 1
                self.commit_hash = self.state_db.commit_hash()
        # pvt torn-tail repair for blocks the state db already covers —
        # the replay loop below repairs its own blocks' pvt gaps.  On a
        # snapshot-bootstrapped ledger blocks below the base are not
        # stored: nothing to derive markers from, start at the base.
        for bn in range(
            max(
                self.pvt_store.last_committed_block + 1,
                self.block_store.base_height,
            ),
            min(start, height),
        ):
            block = self.block_store.get_block_by_number(bn)
            self._repair_pvt_gap(
                block, self._extract_rwsets(block), self._codes(block)
            )
        recovered = 0
        for block in self.block_store.iter_blocks(start):
            self._apply_committed_block(block)
            recovered += 1
        if recovered and self.persistent:
            # persistent mode replays only a crash gap (non-persistent
            # replays the whole chain by design every open)
            logger.warning(
                "[%s] recovery replayed %d block(s) above state savepoint "
                "into state/pvt", self.channel_id, recovered,
            )
            fabobs.obs_count(
                "fabric_ledger_recovered_blocks_total", recovered
            )

    def _apply_committed_block(self, block: common_pb2.Block) -> None:
        flags = self._extract_flags(block)
        rwsets = self._extract_rwsets(block)
        # Restore the COMMIT_HASH chain so post-restart commits keep
        # chaining from the last stored hash (kv_ledger.go recoverDBs +
        # addBlockCommitHash: the chain must not reset on restart).
        metas = block.metadata.metadata
        if len(metas) > common_pb2.COMMIT_HASH and metas[common_pb2.COMMIT_HASH]:
            meta = protoutil.unmarshal(
                common_pb2.Metadata, metas[common_pb2.COMMIT_HASH]
            )
            self.commit_hash = meta.value
        codes = [
            TxValidationCode.VALID
            if flags.is_valid(i)
            else TxValidationCode(int(flags.asarray()[i]))
            for i in range(len(flags))
        ]
        validator = Validator(self.state_db)
        # On replay the stored filter already includes MVCC verdicts; apply
        # writes of the VALID txs without re-deciding.
        updates = UpdateBatch()
        hashed = HashedUpdateBatch()
        for tx_num, (rwset, code) in enumerate(zip(rwsets, codes)):
            if code == TxValidationCode.VALID and rwset is not None:
                validator._apply_write_set(
                    rwset, Version(block.header.number, tx_num), updates, hashed
                )
        # pvt cleartext state is derived from the pvt store on replay
        if self.pvt_store.last_committed_block < block.header.number:
            self._repair_pvt_gap(block, rwsets, codes)
        pvt_batch = self._pvt_batch(
            block.header.number,
            self.pvt_store.get_pvt_data_by_block(block.header.number),
            codes,
            rwsets,
            verify_hashes=False,
        )
        self._commit_state(block, updates, hashed, pvt_batch)

    def _codes(self, block: common_pb2.Block) -> List[TxValidationCode]:
        flags = self._extract_flags(block)
        return [TxValidationCode(int(c)) for c in flags.asarray()]

    def _repair_pvt_gap(self, block, rwsets, codes) -> None:
        """The pvt record for an already-stored block is gone (its torn
        tail was truncated by recovery).  The cleartext cannot be
        recreated locally — record missing markers for every collection
        the block's VALID txs wrote, so the guard invariant (pvt store
        never behind the chain) holds and the reconciler re-fetches.
        The on-block hashed writes replay regardless."""
        missing = [
            MissingEntry(tx_num, ns_rw.namespace, coll.collection_name)
            for tx_num, (rwset, code) in enumerate(zip(rwsets, codes))
            if code == TxValidationCode.VALID and rwset is not None
            for ns_rw in rwset.ns_rw_sets
            for coll in ns_rw.coll_hashed
            if coll.hashed_writes
        ]
        logger.warning(
            "[%s] pvt store behind stored block %d on recovery: "
            "recording %d missing-data marker(s) for the reconciler",
            self.channel_id, block.header.number, len(missing),
        )
        self.pvt_store.commit(block.header.number, [], missing)

    def _extract_flags(self, block: common_pb2.Block) -> ValidationFlags:
        raw = bytes(block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER])
        return (
            ValidationFlags.from_bytes(raw)
            if raw
            else ValidationFlags(len(block.data.data), TxValidationCode.VALID)
        )

    def _extract_rwsets(self, block: common_pb2.Block) -> List[Optional[TxRwSet]]:
        return [
            parse_transaction(i, data).rwset
            for i, data in enumerate(block.data.data)
        ]

    # -- the commit path ---------------------------------------------------
    def commit(
        self,
        block: common_pb2.Block,
        rwsets: Optional[List[Optional[TxRwSet]]] = None,
        pvt_data: Optional[Dict[Tuple[int, str, str], bytes]] = None,
        missing_pvt: Optional[List[MissingEntry]] = None,
    ) -> ValidationFlags:
        """ValidateAndPrepare + commit (kv_ledger.go commit): assumes the
        block already carries the txvalidator's TRANSACTIONS_FILTER; MVCC
        verdicts are merged in here and the final filter is what gets
        stored. `rwsets` lets the caller share the validator's parse pass
        (hot path); when absent the block is re-decoded (replay path).

        `pvt_data` maps (tx_num, ns, collection) -> serialized cleartext
        KVRWSet assembled by the coordinator; writes are hash-checked
        against the tx's on-block hashed rwset before being applied
        (kv_ledger.go CommitLegacy's pvt data validation)."""
        import time as _time

        t0 = _time.perf_counter()
        flags = self._extract_flags(block)
        if rwsets is None:
            rwsets = self._extract_rwsets(block)
        incoming = [TxValidationCode(int(c)) for c in flags.asarray()]
        if self.device_mvcc:
            from fabric_tpu.ledger.mvcc_device import DeviceValidator

            validator = DeviceValidator(self.state_db)
        else:
            validator = Validator(self.state_db)
        codes, updates, hashed = validator.validate_and_prepare_batch(
            block.header.number, rwsets, incoming
        )
        # Assemble + hash-check private data FIRST: anything that can raise
        # must run before commit_hash is chained or any store is touched,
        # or a failed commit leaves this peer's COMMIT_HASH diverged from
        # the network on retry.
        entries = [
            PvtEntry(tx_num, ns, coll, raw)
            for (tx_num, ns, coll), raw in sorted((pvt_data or {}).items())
            if tx_num < len(codes) and codes[tx_num] == TxValidationCode.VALID
        ]
        pvt_batch = self._pvt_batch(
            block.header.number, entries, codes, rwsets, verify_hashes=True
        )
        # A tx that ended up invalid (e.g. MVCC) needs no private data —
        # a missing marker for it would feed the reconciler forever.
        missing = [
            m
            for m in (missing_pvt or [])
            if m.tx_num < len(codes)
            and codes[m.tx_num] == TxValidationCode.VALID
        ]

        for i, code in enumerate(codes):
            flags.set_flag(i, code)
        protoutil.init_block_metadata(block)
        block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()

        # commit hash (kv_ledger.go addBlockCommitHash)
        update_bytes = deterministic_update_bytes(updates, hashed)
        filter_bytes = flags.tobytes()
        value = (
            _proto_varint(len(filter_bytes))
            + filter_bytes
            + update_bytes
            + self.commit_hash
        )
        self.commit_hash = hashlib.sha256(value).digest()
        meta = common_pb2.Metadata()
        meta.value = self.commit_hash
        block.metadata.metadata[common_pb2.COMMIT_HASH] = meta.SerializeToString()

        # pvtdata store commit precedes the block append (store.go Commit);
        # if a crash hit between the two last time, the pvt record for this
        # block is already durable — skip, don't error, so redelivery of
        # the block can complete the interrupted commit.
        t1 = _time.perf_counter()
        # kill window (fabcrash): nothing for this block is durable yet —
        # a kill here loses the block entirely and the restart re-pulls it
        fault_point("kvledger.commit.pre_pvt", key=int(block.header.number))
        if self.pvt_store.last_committed_block < block.header.number:
            self.pvt_store.commit(block.header.number, entries, missing)

        self.block_store.add_block(block)
        # kill window (fabcrash): pvt + block durable, state db not —
        # recovery replays this block into state/pvt idempotently
        fault_point(
            "kvledger.commit.post_block", key=int(block.header.number)
        )
        t2 = _time.perf_counter()
        self._commit_state(block, updates, hashed, pvt_batch)
        t3 = _time.perf_counter()
        # per-stage split for the commit log line + committer metrics
        # (reference kv_ledger.go:663-672 state_validation /
        # block_and_pvtdata_commit / state_commit)
        self.last_commit_timings = {
            "state_validation": t1 - t0,
            "block_and_pvtdata_commit": t2 - t1,
            "state_commit": t3 - t2,
        }
        return flags

    def _pvt_batch(
        self,
        block_num: int,
        entries: List[PvtEntry],
        codes: List[TxValidationCode],
        rwsets: List[Optional[TxRwSet]],
        verify_hashes: bool,
    ) -> PvtUpdateBatch:
        """Cleartext private writes -> state batch, checked against the
        tx's hashed rwset (the on-block source of truth)."""
        import hashlib as _hashlib

        from fabric_tpu.protos import kv_rwset_pb2

        batch = PvtUpdateBatch()
        for e in entries:
            if e.tx_num >= len(codes) or codes[e.tx_num] != TxValidationCode.VALID:
                continue
            expected: Dict[bytes, Tuple[bool, bytes]] = {}
            rwset = rwsets[e.tx_num] if e.tx_num < len(rwsets) else None
            if rwset is not None:
                for ns_rw in rwset.ns_rw_sets:
                    if ns_rw.namespace != e.namespace:
                        continue
                    for coll in ns_rw.coll_hashed:
                        if coll.collection_name == e.collection:
                            for hw in coll.hashed_writes:
                                expected[hw.key_hash] = (hw.is_delete, hw.value_hash)
            kv = kv_rwset_pb2.KVRWSet()
            kv.ParseFromString(e.rwset)
            for w in kv.writes:
                kh = _hashlib.sha256(w.key.encode()).digest()
                exp = expected.get(kh)
                if verify_hashes:
                    if exp is None:
                        raise ValueError(
                            f"pvt write {e.namespace}/{e.collection}/{w.key} "
                            "not present in the hashed rwset"
                        )
                    is_del, vh = exp
                    if w.is_delete != is_del or (
                        not w.is_delete
                        and _hashlib.sha256(w.value).digest() != vh
                    ):
                        raise ValueError(
                            f"pvt value hash mismatch for "
                            f"{e.namespace}/{e.collection}/{w.key}"
                        )
                batch.put(
                    e.namespace,
                    e.collection,
                    w.key,
                    None if w.is_delete else w.value,
                    Version(block_num, e.tx_num),
                )
        return batch

    def _commit_state(
        self,
        block: common_pb2.Block,
        updates: UpdateBatch,
        hashed: HashedUpdateBatch,
        pvt: Optional[PvtUpdateBatch] = None,
    ) -> None:
        if self.persistent:
            # state + history + savepoint + commit hash, one transaction
            self.state_db.commit_block(
                updates,
                hashed,
                pvt,
                savepoint=block.header.number,
                commit_hash=self.commit_hash,
            )
        else:
            for (ns, key), entry in updates.items():
                self.history.setdefault((ns, key), []).append(entry.version)
            self.state_db.apply_updates(updates, hashed, pvt)
        # collection-config history (confighistory/mgr.go commit hook)
        self.config_history.record_from_updates(block.header.number, updates)
        if self.state_mirror is not None and len(updates):
            # operational mirror (statecouch): best-effort, post-commit —
            # the embedded store is authoritative and a mirror outage
            # must never block the commit path
            try:
                self.state_mirror.apply_updates(updates)
            except Exception as exc:  # noqa: BLE001
                logger.warning(
                    "[%s] state mirror update failed at block %d: %s",
                    self.channel_id, block.header.number, exc,
                )

    def commit_reconciled_pvt(self, items) -> int:
        """Reconciler write-back (reference reconcile.go ->
        CommitPvtDataOfOldBlocks): late-arriving private data for already
        committed blocks, hash-checked against the on-block hashed rwset;
        entries that fail verification are dropped, good ones land in the
        pvt store AND the cleartext pvt state. `items` is
        [(block_num, tx_num, ns, coll, kvrwset_bytes)]; returns how many
        entries were accepted."""
        by_block: Dict[int, List[PvtEntry]] = {}
        for block_num, tx_num, ns, coll, raw in items:
            by_block.setdefault(block_num, []).append(
                PvtEntry(tx_num, ns, coll, raw)
            )
        accepted = 0
        for block_num in sorted(by_block):
            block = self.block_store.get_block_by_number(block_num)
            if block is None:
                continue
            flags = self._extract_flags(block)
            rwsets = self._extract_rwsets(block)
            codes = [TxValidationCode(int(c)) for c in flags.asarray()]
            good: List[PvtEntry] = []
            batch = PvtUpdateBatch()
            for entry in by_block[block_num]:
                try:
                    if not self._pvt_entry_complete(entry, rwsets):
                        continue  # subset/empty payload: an attacker must
                        # not be able to clear the missing marker
                    one = self._pvt_batch(
                        block_num, [entry], codes, rwsets, verify_hashes=True
                    )
                except Exception:  # fablint: disable=broad-except  # includes proto DecodeError;
                    # one forged/mismatched/garbled entry must not abort
                    # the rest of the batch
                    continue
                for (ns, coll, key), e in one.items():
                    # never regress pvt state a LATER block already wrote
                    # (reference CommitPvtDataOfOldBlocks version check)
                    current = self.state_db.get_private_data(ns, coll, key)
                    if current is not None and not (
                        current.version.block_num < e.version.block_num
                        or (
                            current.version.block_num == e.version.block_num
                            and current.version.tx_num <= e.version.tx_num
                        )
                    ):
                        continue
                    batch.put(ns, coll, key, e.value, e.version)
                good.append(entry)
            if not good:
                continue
            self.pvt_store.commit_pvt_data_of_old_blocks(block_num, good)
            self.state_db.apply_updates(UpdateBatch(), None, batch)
            accepted += len(good)
        return accepted

    def _pvt_entry_complete(self, entry: PvtEntry, rwsets) -> bool:
        """The payload must cover EVERY key hash the tx's on-block hashed
        rwset lists for this collection — partial data must not clear the
        missing marker."""
        import hashlib as _hashlib

        from fabric_tpu.protos import kv_rwset_pb2

        expected = set()
        rwset = rwsets[entry.tx_num] if entry.tx_num < len(rwsets) else None
        if rwset is None:
            return False
        for ns_rw in rwset.ns_rw_sets:
            if ns_rw.namespace != entry.namespace:
                continue
            for coll in ns_rw.coll_hashed:
                if coll.collection_name == entry.collection:
                    expected = {hw.key_hash for hw in coll.hashed_writes}
        if not expected:
            return False
        kv = kv_rwset_pb2.KVRWSet()
        kv.ParseFromString(entry.rwset)
        provided = {
            _hashlib.sha256(w.key.encode()).digest() for w in kv.writes
        }
        return provided == expected

    # -- admin ops (reference kvledger reset.go / rollback.go /
    #    rebuild_dbs.go: state & history are derived caches over the
    #    block store, so both ops are truncate-then-replay) -------------
    def rebuild_dbs(self) -> None:
        """Drop the derived state/history caches and replay the block
        store (peer node rebuild-dbs / reset). Refused on a
        snapshot-bootstrapped ledger: pre-snapshot state exists only in
        the (gone) snapshot, not the block store (the reference refuses
        reset/rollback/rebuild on bootstrapped ledgers too)."""
        if self.block_store.base_height > 0:
            raise ValueError(
                "cannot rebuild a snapshot-bootstrapped ledger: state "
                f"below block {self.block_store.base_height} is not in "
                "the block store"
            )
        if self.persistent:
            self.state_db.clear()
        else:
            # carry the generation stamp forward (+1): a resident MVCC
            # table bound to the old db must see the rebuild as an
            # out-of-band mutation, not a fresh generation-0 twin
            old_generation = self.state_db.state_generation
            self.state_db = VersionedDB()
            self.state_db.state_generation = old_generation + 1
        from fabric_tpu.ledger.confighistory import ConfigHistoryMgr

        self.config_history = ConfigHistoryMgr(
            self.state_db if self.persistent else None
        )
        self.history = {}
        self.commit_hash = b""
        self._recover()

    def rollback(self, target_block: int) -> None:
        """Roll the channel back so target_block is the last block."""
        if self.block_store.base_height > 0:
            raise ValueError(
                "cannot roll back a snapshot-bootstrapped ledger"
            )
        self.block_store.truncate_to(target_block + 1)
        # the pvt store must rewind too, or re-committed blocks skip pvt
        # persistence (last_committed guard) and replay stale records
        self.pvt_store.rollback_to(target_block + 1)
        self.rebuild_dbs()

    def close(self) -> None:
        """Release file handles/connections (ledgermgmt.Close): required
        before another process (or the offline admin CLI) opens the same
        ledger directory.  Idempotent and safe on a partially-constructed
        ledger — recovery error paths call it before re-raising, and a
        crash-restart runbook may close defensively."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        for store in (
            getattr(self, "block_store", None),
            getattr(self, "pvt_store", None),
            getattr(self, "state_db", None) if self.persistent else None,
        ):
            if store is not None:
                store.close()

    # -- queries (qscc analog) --------------------------------------------
    @property
    def height(self) -> int:
        return self.block_store.height

    def get_state(self, ns: str, key: str) -> Optional[bytes]:
        vv = self.state_db.get_state(ns, key)
        return vv.value if vv else None

    def get_private_data(self, ns: str, coll: str, key: str) -> Optional[bytes]:
        vv = self.state_db.get_private_data(ns, coll, key)
        return vv.value if vv else None

    def get_history_for_key(self, ns: str, key: str) -> List[Version]:
        if self.persistent:
            return self.state_db.get_history(ns, key)
        return list(self.history.get((ns, key), []))

    def execute_query(self, ns: str, query) -> List[Tuple[str, bytes]]:
        """Rich selector query over committed state (statecouchdb.go:695)."""
        return self.state_db.execute_query(ns, query)

    def tx_exists(self, txid: str) -> bool:
        return self.block_store.tx_exists(txid)
