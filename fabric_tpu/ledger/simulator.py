"""Transaction simulator + rwset builder (reference
core/ledger/kvledger/txmgmt/txmgr lockbased_txmgr.go NewTxSimulator and
rwsetutil/rwset_builder.go).

Simulation runs against the committed state snapshot: reads record the
observed version (KVRead), writes are buffered (KVWrite, last-write-wins),
range scans record RangeQueryInfo for phantom-read revalidation, and
private-data writes produce both the cleartext TxPvtReadWriteSet (stored
off-block) and the on-block hashed rwset (CollHashedRwSet). Matching the
reference's lockbased simulator: reads do NOT observe the tx's own
buffered writes, and paginated/range queries after writes to the same
namespace are the caller's concern.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.ledger.statedb import VersionedDB
from fabric_tpu.protos import kv_rwset_pb2, rwset_pb2

# Composite-key sentinel (reference shim uses U+0000 as min unicode rune).
COMPOSITE_KEY_NS = "\x00"
MAX_UNICODE_RUNE = "\U0010ffff"


@dataclass
class PvtKVWrite:
    key: str
    is_delete: bool
    value: bytes


@dataclass
class TxSimulationResults:
    """Public rwset (goes into the ChaincodeAction `results` field) plus
    the private cleartext write-sets keyed (namespace, collection)."""

    rwset: rw.TxRwSet
    pvt_writes: Dict[Tuple[str, str], List[PvtKVWrite]]

    @property
    def public_bytes(self) -> bytes:
        return serialize_tx_rwset(self.rwset)

    def pvt_rwset_bytes(self) -> bytes:
        """Serialized TxPvtReadWriteSet (rwset.proto:24) for the transient
        store / pvtdata store."""
        out = rwset_pb2.TxPvtReadWriteSet()
        out.data_model = rwset_pb2.TxReadWriteSet.KV
        by_ns: Dict[str, Dict[str, List[PvtKVWrite]]] = {}
        for (ns, coll), writes in self.pvt_writes.items():
            by_ns.setdefault(ns, {})[coll] = writes
        for ns in sorted(by_ns):
            ns_msg = out.ns_pvt_rwset.add()
            ns_msg.namespace = ns
            for coll in sorted(by_ns[ns]):
                coll_msg = ns_msg.collection_pvt_rwset.add()
                coll_msg.collection_name = coll
                coll_msg.rwset = collection_kvrwset_bytes(by_ns[ns][coll])
        return out.SerializeToString()


def collection_kvrwset_bytes(writes: List[PvtKVWrite]) -> bytes:
    """One collection's cleartext writes -> serialized KVRWSet — the ONE
    encoding shared by the transient store, the pvt store and the gossip
    dissemination path (divergent copies would make pushed and stored
    payloads differ byte-for-byte)."""
    kv = kv_rwset_pb2.KVRWSet()
    for w in writes:
        kw = kv.writes.add()
        kw.key = w.key
        kw.is_delete = w.is_delete
        kw.value = w.value
    return kv.SerializeToString()


class SimulationError(Exception):
    pass


class TxSimulator:
    """rwset_builder.go semantics with deterministic output ordering
    (reads/writes sorted by key at GetTxSimulationResults time)."""

    def __init__(
        self,
        state_db: VersionedDB,
        tx_id: str = "",
        pvt_reader=None,  # callable (ns, coll, key) -> Optional[bytes]
        range_query_hashing_max_degree: int = 50,  # ledger config
        # MaxDegreeQueryReadsHashing default; 0 disables summarization
    ):
        self._db = state_db
        self.tx_id = tx_id
        self._pvt_reader = pvt_reader
        self._rq_max_degree = range_query_hashing_max_degree
        self._done = False
        # ns -> key -> KVRead (first read wins, like the reference builder)
        self._reads: Dict[str, Dict[str, rw.KVRead]] = {}
        self._writes: Dict[str, Dict[str, rw.KVWrite]] = {}
        self._metadata_writes: Dict[str, Dict[str, rw.KVMetadataWrite]] = {}
        self._range_queries: Dict[str, List[rw.RangeQueryInfo]] = {}
        self._hashed_reads: Dict[Tuple[str, str], Dict[bytes, rw.KVReadHash]] = {}
        self._hashed_writes: Dict[Tuple[str, str], Dict[bytes, rw.KVWriteHash]] = {}
        self._pvt_writes: Dict[Tuple[str, str], Dict[str, PvtKVWrite]] = {}
        # paginated queries restrict the tx to read-only (reference
        # lockbased_tx_simulator.go: checkBeforePaginatedQueries /
        # checkPaginatedQueryPerformed reject the mixed case)
        self._paginated_queries_performed = False

    def _check_open(self) -> None:
        if self._done:
            raise SimulationError("simulator already closed")

    # -- public state -----------------------------------------------------
    def get_state(self, ns: str, key: str) -> Optional[bytes]:
        self._check_open()
        vv = self._db.get_state(ns, key)
        self._reads.setdefault(ns, {}).setdefault(
            key, rw.KVRead(key, vv.version if vv else None)
        )
        return vv.value if vv else None

    def set_state(self, ns: str, key: str, value: bytes) -> None:
        self._check_open()
        self._check_no_paginated_queries()
        if not key:
            raise SimulationError("empty key is not supported")
        self._writes.setdefault(ns, {})[key] = rw.KVWrite(key, False, value)

    def delete_state(self, ns: str, key: str) -> None:
        self._check_open()
        self._check_no_paginated_queries()
        self._writes.setdefault(ns, {})[key] = rw.KVWrite(key, True, b"")

    def _check_no_paginated_queries(self) -> None:
        if self._paginated_queries_performed:
            raise SimulationError(
                "writes are not allowed in a transaction that has "
                "performed paginated queries (read-only contract)"
            )

    def get_state_metadata(self, ns: str, key: str) -> Optional[bytes]:
        self._check_open()
        return self._db.get_state_metadata(ns, key)

    def set_state_metadata(
        self, ns: str, key: str, entries: Optional[Dict[str, bytes]]
    ) -> None:
        """entries None = delete metadata (tx_ops.go metadataDelete)."""
        self._check_open()
        tup = (
            tuple(sorted(entries.items())) if entries is not None else None
        )
        self._metadata_writes.setdefault(ns, {})[key] = rw.KVMetadataWrite(  # fabdep: disable=unguarded-shared-write  # TxSimulator is tx-scoped: the chaincode shim drives it from exactly one thread at a time
            key, tup
        )

    def get_state_range_scan_iterator(
        self, ns: str, start_key: str, end_key: str
    ) -> Iterator[Tuple[str, bytes]]:
        """Fully-consumed range scan recording RangeQueryInfo raw reads
        (validator.go:211-237 phantom-read input). The iterator is eager:
        itr_exhausted=True, matching a chaincode that drains the iterator;
        partial consumption would need the lazy form."""
        self._check_open()
        from fabric_tpu.ledger.merkle import RangeQueryResultsHelper

        helper = RangeQueryResultsHelper(
            self._rq_max_degree > 0, max(self._rq_max_degree, 2)
        )
        results: List[Tuple[str, bytes]] = []
        for key, vv in self._db.get_state_range(ns, start_key, end_key, False):
            helper.add_result(rw.KVRead(key, vv.version))
            results.append((key, vv.value))
        raw_reads, summary = helper.done()
        self._range_queries.setdefault(ns, []).append(
            rw.RangeQueryInfo(
                start_key=start_key,
                end_key=end_key,
                itr_exhausted=True,
                raw_reads=raw_reads,
                reads_merkle_hashes=summary,
            )
        )
        return iter(results)

    def execute_query(self, ns: str, query) -> List[Tuple[str, bytes]]:
        """Rich selector query (chaincode GetQueryResult; reference
        statecouchdb.go:695). Like the reference's CouchDB path, results
        add NO reads to the rwset — rich queries are not phantom-protected
        (documented Fabric behavior)."""
        self._check_open()
        return self._db.execute_query(ns, query)

    # -- pagination (bookmark contract) -----------------------------------
    def execute_query_with_pagination(
        self, ns: str, query, page_size: int, bookmark: str = ""
    ) -> Tuple[List[Tuple[str, bytes]], str]:
        """GetQueryResultWithPagination (statecouchdb.go:653): one page +
        the resumption bookmark.  Like the reference
        (lockbased_tx_simulator.go checkBeforePaginatedQueries), paginated
        queries are for read-only transactions: performing one marks the
        simulation and later writes are rejected."""
        self._check_open()
        self._paginated_queries_performed = True
        return self._db.execute_query_paginated(ns, query, page_size, bookmark)

    def get_state_range_with_pagination(
        self, ns: str, start_key: str, end_key: str, page_size: int,
        bookmark: str = "",
    ) -> Tuple[List[Tuple[str, bytes]], str]:
        """GetStateByRangeWithPagination (statecouchdb.go:567): the
        bookmark is the next key to resume from; returned keys record
        plain reads (MVCC-protected) but no phantom-protecting range
        record, matching the reference's paginated range contract."""
        self._check_open()
        if page_size <= 0:
            raise ValueError("pageSize must be a positive integer")
        self._paginated_queries_performed = True
        start = bookmark or start_key
        results: List[Tuple[str, bytes]] = []
        next_bookmark = ""
        for key, vv in self._db.get_state_range(ns, start, end_key, False):
            if len(results) == page_size:
                next_bookmark = key
                break
            self._reads.setdefault(ns, {}).setdefault(
                key, rw.KVRead(key, vv.version)
            )
            results.append((key, vv.value))
        return results, next_bookmark

    # -- private data -----------------------------------------------------
    def get_private_data(self, ns: str, coll: str, key: str) -> Optional[bytes]:
        self._check_open()
        key_hash = hashlib.sha256(key.encode()).digest()
        version = self._db.get_key_hash_version(ns, coll, key_hash)
        self._hashed_reads.setdefault((ns, coll), {}).setdefault(
            key_hash, rw.KVReadHash(key_hash, version)
        )
        if self._pvt_reader is None:
            return None
        return self._pvt_reader(ns, coll, key)

    def get_private_data_hash(self, ns: str, coll: str, key: str) -> Optional[bytes]:
        """GetPrivateDataHash: readable by non-members; does NOT add to the
        read-set (reference simulator semantics)."""
        self._check_open()
        key_hash = hashlib.sha256(key.encode()).digest()
        vv = self._db.get_hashed_state(ns, coll, key_hash)
        return vv.value if vv else None

    def set_private_data(self, ns: str, coll: str, key: str, value: bytes) -> None:
        self._check_open()
        if not key:
            raise SimulationError("empty key is not supported")
        key_hash = hashlib.sha256(key.encode()).digest()
        self._hashed_writes.setdefault((ns, coll), {})[key_hash] = rw.KVWriteHash(  # fabdep: disable=unguarded-shared-write  # TxSimulator is tx-scoped: the chaincode shim drives it from exactly one thread at a time
            key_hash, False, hashlib.sha256(value).digest()
        )
        self._pvt_writes.setdefault((ns, coll), {})[key] = PvtKVWrite(  # fabdep: disable=unguarded-shared-write  # TxSimulator is tx-scoped: the chaincode shim drives it from exactly one thread at a time
            key, False, value
        )

    def delete_private_data(self, ns: str, coll: str, key: str) -> None:
        self._check_open()
        key_hash = hashlib.sha256(key.encode()).digest()
        self._hashed_writes.setdefault((ns, coll), {})[key_hash] = rw.KVWriteHash(  # fabdep: disable=unguarded-shared-write  # TxSimulator is tx-scoped: the chaincode shim drives it from exactly one thread at a time
            key_hash, True, b""
        )
        self._pvt_writes.setdefault((ns, coll), {})[key] = PvtKVWrite(key, True, b"")  # fabdep: disable=unguarded-shared-write  # TxSimulator is tx-scoped: the chaincode shim drives it from exactly one thread at a time

    # -- results ----------------------------------------------------------
    def get_tx_simulation_results(self) -> TxSimulationResults:
        self._check_open()
        self._done = True
        ns_names = sorted(
            set(self._reads)
            | set(self._writes)
            | set(self._metadata_writes)
            | set(self._range_queries)
            | {ns for ns, _ in self._hashed_reads}
            | {ns for ns, _ in self._hashed_writes}
        )
        ns_sets: List[rw.NsRwSet] = []
        for ns in ns_names:
            colls = sorted(
                {c for n, c in self._hashed_reads if n == ns}
                | {c for n, c in self._hashed_writes if n == ns}
            )
            coll_hashed = tuple(
                rw.CollHashedRwSet(
                    collection_name=coll,
                    hashed_reads=tuple(
                        self._hashed_reads.get((ns, coll), {})[kh]
                        for kh in sorted(self._hashed_reads.get((ns, coll), {}))
                    ),
                    hashed_writes=tuple(
                        self._hashed_writes.get((ns, coll), {})[kh]
                        for kh in sorted(self._hashed_writes.get((ns, coll), {}))
                    ),
                )
                for coll in colls
            )
            ns_sets.append(
                rw.NsRwSet(
                    namespace=ns,
                    reads=tuple(
                        self._reads.get(ns, {})[k]
                        for k in sorted(self._reads.get(ns, {}))
                    ),
                    writes=tuple(
                        self._writes.get(ns, {})[k]
                        for k in sorted(self._writes.get(ns, {}))
                    ),
                    range_queries=tuple(self._range_queries.get(ns, [])),
                    coll_hashed=coll_hashed,
                    metadata_writes=tuple(
                        self._metadata_writes.get(ns, {})[k]
                        for k in sorted(self._metadata_writes.get(ns, {}))
                    ),
                )
            )
        pvt = {
            (ns, coll): [w for _, w in sorted(writes.items())]
            for (ns, coll), writes in self._pvt_writes.items()
        }
        return TxSimulationResults(rwset=rw.TxRwSet(tuple(ns_sets)), pvt_writes=pvt)


def create_composite_key(object_type: str, attributes: List[str]) -> str:
    """shim.CreateCompositeKey: \\x00-delimited, validated UTF-8."""
    key = COMPOSITE_KEY_NS + object_type + COMPOSITE_KEY_NS
    for attr in attributes:
        key += attr + COMPOSITE_KEY_NS
    return key


def split_composite_key(key: str) -> Tuple[str, List[str]]:
    parts = key.split(COMPOSITE_KEY_NS)
    # parts[0] is empty (leading sentinel); last is empty (trailing)
    components = [p for p in parts[1:] if p != ""]
    return components[0], components[1:]
