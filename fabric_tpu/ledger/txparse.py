"""Per-transaction structural validation (reference
core/common/validation/msgvalidation.go) — host-side parsing phase.

Lives in the ledger layer (historically validation.msgvalidation, which
still re-exports everything here): the parser builds ledger.rwset
objects and is consumed from below the validation pipeline — kvledger's
commit path and the history store re-parse committed transactions — so
keeping it above the ledger created an import cycle.

The reference validates each tx in its own goroutine, verifying the
creator signature inline (ValidateTransaction :248-330). The TPU pipeline
splits that into:

  parse phase (this module, host): all structural checks; emits
      *signature jobs* instead of verifying inline;
  batch phase (device): every signature in the block — creator sigs and
      endorsement sigs — verified in ONE batched kernel call;
  assembly phase (validation.validator): reference-ordered code priority
      consuming the boolean results.

Check order replicated exactly (msgvalidation.go ValidateTransaction):
nil envelope -> NIL_ENVELOPE; payload unmarshal -> BAD_PAYLOAD; header/
channel-header/signature-header problems -> BAD_COMMON_HEADER; creator
deserialize/cert-validate/signature -> BAD_CREATOR_SIGNATURE; TxID
recompute -> BAD_PROPOSAL_TXID; endorser-tx structure (single action,
proposal-hash binding) -> INVALID_ENDORSER_TRANSACTION; unknown type ->
UNSUPPORTED_TX_PAYLOAD.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import List, Optional, Tuple

from fabric_tpu.protos import common_pb2, kv_rwset_pb2, peer_pb2, protoutil, rwset_pb2
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.common.txflags import TxValidationCode

SUPPORTED_HEADER_TYPES = {
    common_pb2.ENDORSER_TRANSACTION,
    common_pb2.CONFIG_UPDATE,
    common_pb2.CONFIG,
}


class SigJob:
    """One deferred signature check: verify `signature` by the identity
    serialized in `identity_bytes` over `data`.

    When the native block parser produced the job, `digest` carries the
    precomputed SHA-256 of the signed bytes and `data` is b"" (the
    payload is never materialized — endorsement jobs sign
    prp_bytes||endorser, which would otherwise need a copy per job)."""

    __slots__ = ("identity_bytes", "signature", "data", "digest")

    def __init__(
        self,
        identity_bytes: bytes,
        signature: bytes,
        data: bytes,
        digest: Optional[bytes] = None,
    ):
        self.identity_bytes = identity_bytes
        self.signature = signature
        self.data = data
        self.digest = digest


def writes_to_namespace(ns_rw) -> bool:
    """Reference dispatcher.txWritesToNamespace: public writes, metadata
    writes, or per-collection hashed (metadata) writes."""
    if ns_rw.writes or ns_rw.metadata_writes:
        return True
    for coll in ns_rw.coll_hashed:
        if coll.hashed_writes or coll.metadata_writes:
            return True
    return False


class ParsedTx:
    """Host-parse result for one block position.

    The rwset is materialized lazily: the native block parser has
    already validated the rwset's structure (walk_tx_rwset in
    native/blockparse.cc mirrors parse_tx_rwset's acceptance), so the
    Python object tree is only built when a consumer (MVCC, commit,
    legacy writeset checks) actually needs it."""

    __slots__ = (
        "index",
        "code",
        "header_type",
        "channel_id",
        "tx_id",
        "creator",
        "creator_sig_job",
        "endorsement_jobs",
        "namespace",
        "config_data",
        "_rwset",
        "_rwset_raw",
        "_ns_entries",
        "_has_md_writes",
    )

    def __init__(self, index: int):
        self.index = index
        self.code: TxValidationCode = TxValidationCode.NOT_VALIDATED
        self.header_type: int = -1
        self.channel_id: str = ""
        self.tx_id: str = ""
        self.creator: bytes = b""
        # deferred signature checks
        self.creator_sig_job: Optional[SigJob] = None
        self.endorsement_jobs: List[SigJob] = []
        # endorser-tx artifacts (builtin v20 VSCC inputs)
        self.namespace: str = ""
        self.config_data: bytes = b""
        self._rwset: Optional[rw.TxRwSet] = None
        self._rwset_raw: Optional[bytes] = None
        # (namespace, writes_to_namespace) per ns_rw_set, order-preserving
        self._ns_entries: Optional[List[Tuple[str, bool]]] = None
        self._has_md_writes: Optional[bool] = None

    @property
    def rwset(self) -> Optional[rw.TxRwSet]:
        if self._rwset is None and self._rwset_raw is not None:
            raw, self._rwset_raw = self._rwset_raw, None
            try:
                self._rwset = parse_tx_rwset(raw)
            except ValueError:
                # acceptance divergence between the native wire walker
                # (walk_tx_rwset) and the Python parser over untrusted tx
                # bytes: degrade to BAD_RWSET for THIS tx instead of
                # letting the exception abort the whole block commit
                from fabric_tpu.common import flogging

                flogging.must_get_logger("validation").warning(
                    "native/Python rwset parse divergence on tx %d "
                    "(len=%d) — marking BAD_RWSET; add to fuzzer corpus",
                    self.index, len(raw),
                )
                self.code = TxValidationCode.BAD_RWSET
        return self._rwset

    @rwset.setter
    def rwset(self, value: Optional[rw.TxRwSet]) -> None:
        self._rwset = value
        self._rwset_raw = None

    @property
    def ns_entries(self) -> Optional[List[Tuple[str, bool]]]:
        """[(namespace, writes_to_namespace)] in rwset order, or None
        for non-endorser / failed txs — what _assemble_codes needs
        without materializing the rwset object tree."""
        if self._ns_entries is None and self.rwset is not None:
            self._ns_entries = [
                (ns.namespace, writes_to_namespace(ns))
                for ns in self.rwset.ns_rw_sets
            ]
        return self._ns_entries

    @property
    def has_md_writes(self) -> bool:
        """Any public or collection-hashed metadata write — the trigger
        for the sequential SBE pass (statebased.BlockDependencies)."""
        if self._has_md_writes is None:
            rwset = self.rwset
            self._has_md_writes = rwset is not None and any(
                ns.metadata_writes
                or any(c.metadata_writes for c in ns.coll_hashed)
                for ns in rwset.ns_rw_sets
            )
        return self._has_md_writes

    @property
    def structurally_valid(self) -> bool:
        return self.code == TxValidationCode.NOT_VALIDATED


def _parse_version(v: kv_rwset_pb2.Version, present: bool) -> Optional[rw.Version]:
    if not present:
        return None
    return rw.Version(v.block_num, v.tx_num)


def parse_tx_rwset(results: bytes) -> rw.TxRwSet:
    """proto TxReadWriteSet bytes -> internal TxRwSet
    (reference rwsetutil.TxRwSetFromProtoMsg)."""
    txrw = protoutil.unmarshal(rwset_pb2.TxReadWriteSet, results)
    ns_sets = []
    for ns in txrw.ns_rwset:
        kv = protoutil.unmarshal(kv_rwset_pb2.KVRWSet, ns.rwset)
        reads = tuple(
            rw.KVRead(r.key, _parse_version(r.version, r.HasField("version")))
            for r in kv.reads
        )
        writes = tuple(
            rw.KVWrite(w.key, w.is_delete, w.value) for w in kv.writes
        )
        # proto3 cannot distinguish nil from empty entries; like the
        # reference, empty means metadata delete (None here)
        md_writes = tuple(
            rw.KVMetadataWrite(
                m.key,
                tuple((e.name, e.value) for e in m.entries) or None,
            )
            for m in kv.metadata_writes
        )
        rqs = []
        for q in kv.range_queries_info:
            raw_reads: Tuple[rw.KVRead, ...] = ()
            merkle = None
            if q.HasField("raw_reads"):
                raw_reads = tuple(
                    rw.KVRead(r.key, _parse_version(r.version, r.HasField("version")))
                    for r in q.raw_reads.kv_reads
                )
            if q.HasField("reads_merkle_hashes"):
                merkle = (
                    q.reads_merkle_hashes.max_degree,
                    q.reads_merkle_hashes.max_level,
                    tuple(q.reads_merkle_hashes.max_level_hashes),
                )
            rqs.append(
                rw.RangeQueryInfo(
                    q.start_key, q.end_key, q.itr_exhausted, raw_reads, merkle
                )
            )
        colls = []
        for coll in ns.collection_hashed_rwset:
            h = protoutil.unmarshal(kv_rwset_pb2.HashedRWSet, coll.hashed_rwset)
            colls.append(
                rw.CollHashedRwSet(
                    coll.collection_name,
                    tuple(
                        rw.KVReadHash(
                            r.key_hash,
                            _parse_version(r.version, r.HasField("version")),
                        )
                        for r in h.hashed_reads
                    ),
                    tuple(
                        rw.KVWriteHash(w.key_hash, w.is_delete, w.value_hash)
                        for w in h.hashed_writes
                    ),
                    tuple(
                        rw.KVMetadataWriteHash(
                            m.key_hash,
                            tuple((e.name, e.value) for e in m.entries)
                            or None,
                        )
                        for m in h.metadata_writes
                    ),
                )
            )
        ns_sets.append(
            rw.NsRwSet(
                ns.namespace, reads, writes, tuple(rqs), tuple(colls), md_writes
            )
        )
    return rw.TxRwSet(tuple(ns_sets))


def parse_transaction(index: int, data: bytes) -> ParsedTx:
    """Structural validation of one block entry; fills early codes and
    deferred signature jobs. Never verifies a signature."""
    out = ParsedTx(index)
    if not data:
        out.code = TxValidationCode.NIL_ENVELOPE
        return out
    try:
        env = protoutil.unmarshal(common_pb2.Envelope, data)
    except ValueError:
        out.code = TxValidationCode.INVALID_OTHER_REASON
        return out

    if not env.payload:
        out.code = TxValidationCode.BAD_PAYLOAD
        return out
    try:
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
    except ValueError:
        out.code = TxValidationCode.BAD_PAYLOAD
        return out

    # validateCommonHeader
    if not payload.HasField("header"):
        out.code = TxValidationCode.BAD_COMMON_HEADER
        return out
    try:
        chdr = protoutil.unmarshal(
            common_pb2.ChannelHeader, payload.header.channel_header
        )
        shdr = protoutil.unmarshal(
            common_pb2.SignatureHeader, payload.header.signature_header
        )
    except ValueError:
        out.code = TxValidationCode.BAD_COMMON_HEADER
        return out
    if chdr.type not in SUPPORTED_HEADER_TYPES or chdr.epoch != 0:
        out.code = TxValidationCode.BAD_COMMON_HEADER
        return out
    if not shdr.nonce or not shdr.creator:
        out.code = TxValidationCode.BAD_COMMON_HEADER
        return out

    out.header_type = chdr.type
    out.channel_id = chdr.channel_id
    out.tx_id = chdr.tx_id
    out.creator = shdr.creator
    # checkSignatureFromCreator, deferred: signature over the full payload
    # bytes (msgvalidation.go:284 verifies env.Signature over env.Payload).
    out.creator_sig_job = SigJob(shdr.creator, env.signature, env.payload)

    if chdr.type == common_pb2.ENDORSER_TRANSACTION:
        if not protoutil.check_tx_id(chdr.tx_id, shdr.nonce, shdr.creator):
            out.code = TxValidationCode.BAD_PROPOSAL_TXID
            return out
        code = _parse_endorser_tx(out, payload)
        if code is not None:
            out.code = code
        return out
    if chdr.type == common_pb2.CONFIG:
        out.config_data = payload.data
        return out
    # CONFIG_UPDATE passes header validation but is not expected inside
    # blocks; the reference codes it UNKNOWN_TX_TYPE at the validator level.
    return out


def _parse_endorser_tx(out: ParsedTx, payload: common_pb2.Payload) -> Optional[TxValidationCode]:
    """validateEndorserTransaction + the artifact extraction the builtin
    v20 plugin performs (validation_logic.go extractValidationArtifacts)."""
    try:
        tx = protoutil.unmarshal(peer_pb2.Transaction, payload.data)
    except ValueError:
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION
    if len(tx.actions) != 1:
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION
    action = tx.actions[0]
    try:
        act_shdr = protoutil.unmarshal(common_pb2.SignatureHeader, action.header)
    except ValueError:
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION
    if not act_shdr.nonce or not act_shdr.creator:
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION
    try:
        cap = protoutil.unmarshal(peer_pb2.ChaincodeActionPayload, action.payload)
        prp_bytes = cap.action.proposal_response_payload
        prp = protoutil.unmarshal(peer_pb2.ProposalResponsePayload, prp_bytes)
    except ValueError:
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION

    # proposal-hash binding: sha256(channel_header || action sig header ||
    # chaincode proposal payload) must equal prp.proposal_hash
    # (GetProposalHash2, protoutil/txutils.go:431).
    h = hashlib.sha256()
    h.update(payload.header.channel_header)
    h.update(action.header)
    h.update(cap.chaincode_proposal_payload)
    if not hmac.compare_digest(h.digest(), prp.proposal_hash):
        return TxValidationCode.INVALID_ENDORSER_TRANSACTION

    # --- builtin v20 artifact extraction (runs later in the reference,
    # inside the plugin; failure codes preserved) ---
    try:
        cc_action = protoutil.unmarshal(peer_pb2.ChaincodeAction, prp.extension)
    except ValueError:
        return TxValidationCode.BAD_RESPONSE_PAYLOAD
    if not cc_action.HasField("chaincode_id") or not cc_action.chaincode_id.name:
        return TxValidationCode.INVALID_OTHER_REASON
    try:
        out.rwset = parse_tx_rwset(cc_action.results)
    except ValueError:
        return TxValidationCode.BAD_RWSET
    out.namespace = cc_action.chaincode_id.name

    # endorsement signature jobs: data = prp_bytes || endorser identity
    # (statebased/validator_keylevel.go:243-251)
    for endorsement in cap.action.endorsements:
        out.endorsement_jobs.append(
            SigJob(
                endorsement.endorser,
                endorsement.signature,
                prp_bytes + endorsement.endorser,
            )
        )
    return None
