"""Device-accelerated MVCC validation (SURVEY §2.13 P5).

The host oracle (`mvcc.Validator`) mirrors the reference's sequential
apply-as-you-go scan (core/ledger/kvledger/txmgmt/validation/
validator.go:82-281): a read conflicts if the committed version differs
from the read version, or if ANY earlier *valid* tx in the block wrote
the key.  The "earlier valid" clause makes the scan look inherently
sequential; this module re-expresses it as a Jacobi fixpoint that XLA
vectorizes:

  valid⁰[t]   = incoming-VALID[t] ∧ all committed-version checks pass
  validⁱ⁺¹[t] = valid⁰[t] ∧ ¬∃ read (t,k): min{u : u writes k, validⁱ[u]} < t

Each sweep is two segment reductions (min over writers per key, max over
bad-reads per tx) plus gathers — all fixed-shape, MXU/VPU-friendly ops.
Because tx t's validity depends only on txs u < t, the dependency graph
is a DAG and the sweep converges to the unique sequential answer in at
most (longest invalidation chain + 1) iterations — in real blocks, 2-3.

Scope: public KV reads/writes/deletes and private-collection hashed
reads/writes (the hot path).  Blocks containing range queries or
metadata writes fall back to the host oracle, which stays the
single source of truth for those shapes (and for update-batch
construction, which is host work either way since the state DB is host
memory/sqlite).

Shapes are bucketed to powers of two (SURVEY P7) so repeated blocks of
similar size reuse one compiled program.

Performance status (measured, round 3, single v5e chip over the axon
tunnel): the device resolver is bit-exact but LOSES to the host scan at
every realistic block size — 5k txs: host ~31-71ms vs device ~164ms;
20k txs: host ~305ms vs device ~527ms.  The loss is structural for this
topology, not a tuning gap: the Python flatten/encode pass costs about
as much as the host oracle's whole scan (both walk every read/write and
hit the same get_version dict), so the device path can only ever add
dispatch+transfer latency on top.  The win condition is a
locally-attached chip with the block's rwsets already device-resident
(e.g. fused into the signature batch that ships block bytes anyway) —
not available here.  Hence `ledger.deviceMVCC` stays opt-in and the
host scan is the default; this class remains the differential-tested
device expression of the algorithm for when that fusion exists.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.rwset import TxRwSet, Version
from fabric_tpu.ledger.statedb import (
    HashedUpdateBatch,
    UpdateBatch,
    VersionedDB,
)
from fabric_tpu.validation.txflags import TxValidationCode

_NO_VERSION = (-1, -1)  # sentinel for "key absent" (None version)


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


@partial(jax.jit, static_argnames=("num_txs", "num_keys"))
def _resolve(
    r_tx,
    r_key,
    r_static_bad,
    w_tx,
    w_key,
    *,
    num_txs: int,
    num_keys: int,
):
    """Fixpoint validity resolution.  Padded lanes use tx index num_txs
    and key index num_keys (one spare segment each).  Empty segments in
    segment_max fill with int32 min, hence the `<= 0` tests."""
    T1 = num_txs + 1
    K1 = num_keys + 1
    big = jnp.int32(T1 + 1)

    static_bad = jax.ops.segment_max(
        r_static_bad.astype(jnp.int32), r_tx, num_segments=T1
    )
    base_valid = static_bad <= 0  # padded tx slot T is irrelevant

    def sweep(valid):
        live_writer = jnp.where(valid[w_tx], w_tx.astype(jnp.int32), big)
        # min valid writer index per key; empty segments -> int32 max
        min_writer = jax.ops.segment_min(live_writer, w_key, num_segments=K1)
        read_bad = min_writer[r_key] < r_tx.astype(jnp.int32)
        any_bad = jax.ops.segment_max(
            read_bad.astype(jnp.int32), r_tx, num_segments=T1
        )
        return base_valid & (any_bad <= 0)

    def cond(carry):
        return carry[1]

    def body(carry):
        valid, _ = carry
        new = sweep(valid)
        return new, jnp.any(new != valid)

    valid, _ = lax.while_loop(cond, body, (base_valid, jnp.array(True)))
    return valid


class DeviceValidator:
    """Drop-in for mvcc.Validator with a device fast path.

    Correctness contract: identical codes and update batches to the host
    oracle for every block; differential-tested in
    tests/test_mvcc_device.py.
    """

    def __init__(self, db: VersionedDB):
        self.db = db
        self._host = Validator(db)
        self.last_path = "host"  # introspection for tests/bench

    # -- encoding ---------------------------------------------------------
    def _encode(
        self,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
    ):
        """Flatten the block into read/write arrays, or None when a shape
        outside the device scope (range query, metadata write) appears in
        a tx that would actually be validated."""
        key_ids: dict = {}
        r_tx: List[int] = []
        r_key: List[int] = []
        r_bad: List[bool] = []
        w_tx: List[int] = []
        w_key: List[int] = []

        def kid(k) -> int:
            i = key_ids.get(k)
            if i is None:
                i = len(key_ids)
                key_ids[k] = i
            return i

        for t, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes)):
            if code != TxValidationCode.VALID or rwset is None:
                continue
            for ns_rw in rwset.ns_rw_sets:
                if ns_rw.range_queries or ns_rw.metadata_writes:
                    return None
                ns = ns_rw.namespace
                for read in ns_rw.reads:
                    committed = self.db.get_version(ns, read.key)
                    r_tx.append(t)
                    r_key.append(kid((ns, "", read.key)))
                    r_bad.append(committed != read.version)
                for w in ns_rw.writes:
                    w_tx.append(t)
                    w_key.append(kid((ns, "", w.key)))
                for coll in ns_rw.coll_hashed:
                    if coll.metadata_writes:
                        return None
                    cn = coll.collection_name
                    for hread in coll.hashed_reads:
                        committed = self.db.get_key_hash_version(
                            ns, cn, hread.key_hash
                        )
                        r_tx.append(t)
                        r_key.append(kid((ns, cn, hread.key_hash)))
                        r_bad.append(committed != hread.version)
                    for hw in coll.hashed_writes:
                        w_tx.append(t)
                        w_key.append(kid((ns, cn, hw.key_hash)))
        return r_tx, r_key, r_bad, w_tx, w_key, len(key_ids)

    # -- public API (mirrors mvcc.Validator) ------------------------------
    def validate_and_prepare_batch(
        self,
        block_num: int,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
        do_mvcc: bool = True,
    ) -> Tuple[List[TxValidationCode], UpdateBatch, HashedUpdateBatch]:
        if not do_mvcc:
            return self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes, do_mvcc=False
            )
        enc = self._encode(tx_rwsets, incoming_codes)
        if enc is None:
            self.last_path = "host"
            return self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes
            )
        self.last_path = "device"
        r_tx, r_key, r_bad, w_tx, w_key, n_keys = enc
        T = len(tx_rwsets)
        K = max(n_keys, 1)
        R = _next_pow2(max(len(r_tx), 1))
        W = _next_pow2(max(len(w_tx), 1))
        Tb = _next_pow2(T)
        Kb = _next_pow2(K)

        def col(vals, pad_to, pad_val, dtype=np.int32):
            a = np.full(pad_to, pad_val, dtype=dtype)
            a[: len(vals)] = vals
            return a

        valid = _resolve(
            col(r_tx, R, Tb),
            col(r_key, R, Kb),
            col(r_bad, R, 0, dtype=np.bool_),
            col(w_tx, W, Tb),
            col(w_key, W, Kb),
            num_txs=Tb,
            num_keys=Kb,
        )
        valid = np.asarray(valid)

        updates = UpdateBatch()
        hashed_updates = HashedUpdateBatch()
        out: List[TxValidationCode] = []
        for t, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes)):
            if code != TxValidationCode.VALID or rwset is None:
                out.append(code)
                continue
            if valid[t]:
                out.append(TxValidationCode.VALID)
                self._host._apply_write_set(
                    rwset, Version(block_num, t), updates, hashed_updates
                )
            else:
                out.append(TxValidationCode.MVCC_READ_CONFLICT)
        return out, updates, hashed_updates
