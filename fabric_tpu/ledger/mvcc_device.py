"""Device-accelerated MVCC validation (SURVEY §2.13 P5).

The host oracle (`mvcc.Validator`) mirrors the reference's sequential
apply-as-you-go scan (core/ledger/kvledger/txmgmt/validation/
validator.go:82-281): a read conflicts if the committed version differs
from the read version, or if ANY earlier *valid* tx in the block wrote
the key.  The "earlier valid" clause makes the scan look inherently
sequential; this module re-expresses it as a Jacobi fixpoint that XLA
vectorizes:

  valid⁰[t]   = incoming-VALID[t] ∧ all committed-version checks pass
  validⁱ⁺¹[t] = valid⁰[t] ∧ ¬∃ read (t,k): min{u : u writes k, validⁱ[u]} < t

Each sweep is two segment reductions (min over writers per key, max over
bad-reads per tx) plus gathers — all fixed-shape, MXU/VPU-friendly ops.
Because tx t's validity depends only on txs u < t, the dependency graph
is a DAG and the sweep converges to the unique sequential answer in at
most (longest invalidation chain + 1) iterations — in real blocks, 2-3.

Scope: public KV reads/writes/deletes and private-collection hashed
reads/writes (the hot path).  Blocks containing range queries or
metadata writes fall back to the host oracle, which stays the
single source of truth for those shapes (and for update-batch
construction, which is host work either way since the state DB is host
memory/sqlite).

Shapes are bucketed to powers of two (SURVEY P7) so repeated blocks of
similar size reuse one compiled program.

Performance status (measured, round 3, single v5e chip over the axon
tunnel): the device resolver is bit-exact but LOSES to the host scan at
every realistic block size — 5k txs: host ~31-71ms vs device ~164ms;
20k txs: host ~305ms vs device ~527ms.  The loss is structural for this
topology, not a tuning gap: the Python flatten/encode pass costs about
as much as the host oracle's whole scan (both walk every read/write and
hit the same get_version dict), so the device path can only ever add
dispatch+transfer latency on top.  The win condition is a
locally-attached chip with the block's rwsets already device-resident
(e.g. fused into the signature batch that ships block bytes anyway) —
not available here.  Hence `ledger.deviceMVCC` stays opt-in and the
host scan is the default; this class remains the differential-tested
device expression of the algorithm for when that fusion exists.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.common import fabobs
from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.rwset import TxRwSet, Version
from fabric_tpu.ledger.statedb import (
    HashedUpdateBatch,
    UpdateBatch,
    VersionedDB,
)
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.txflags import TxValidationCode

logger = must_get_logger("mvcc_device")

_NO_VERSION = (-1, -1)  # sentinel for "key absent" (None version)


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _col(vals: Sequence[int], pad_to: int, pad_val: int, dtype=np.int32):
    a = np.full(pad_to, pad_val, dtype=dtype)
    a[: len(vals)] = vals
    return a


@partial(jax.jit, static_argnames=("num_txs", "num_keys"))
def _resolve(
    r_tx,
    r_key,
    r_static_bad,
    w_tx,
    w_key,
    *,
    num_txs: int,
    num_keys: int,
):
    """Fixpoint validity resolution.  Padded lanes use tx index num_txs
    and key index num_keys (one spare segment each).  Empty segments in
    segment_max fill with int32 min, hence the `<= 0` tests."""
    T1 = num_txs + 1
    K1 = num_keys + 1
    big = jnp.int32(T1 + 1)

    static_bad = jax.ops.segment_max(
        r_static_bad.astype(jnp.int32), r_tx, num_segments=T1
    )
    base_valid = static_bad <= 0  # padded tx slot T is irrelevant

    def sweep(valid):
        live_writer = jnp.where(valid[w_tx], w_tx.astype(jnp.int32), big)
        # min valid writer index per key; empty segments -> int32 max
        min_writer = jax.ops.segment_min(live_writer, w_key, num_segments=K1)
        read_bad = min_writer[r_key] < r_tx.astype(jnp.int32)
        any_bad = jax.ops.segment_max(
            read_bad.astype(jnp.int32), r_tx, num_segments=T1
        )
        return base_valid & (any_bad <= 0)

    def cond(carry):
        return carry[1]

    def body(carry):
        valid, _ = carry
        new = sweep(valid)
        return new, jnp.any(new != valid)

    valid, _ = lax.while_loop(cond, body, (base_valid, jnp.array(True)))
    return valid


class DeviceValidator:
    """Drop-in for mvcc.Validator with a device fast path.

    Correctness contract: identical codes and update batches to the host
    oracle for every block; differential-tested in
    tests/test_mvcc_device.py.
    """

    def __init__(self, db: VersionedDB):
        self.db = db
        self._host = Validator(db)
        self.last_path = "host"  # introspection for tests/bench

    # -- encoding ---------------------------------------------------------
    def _encode(
        self,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
    ):
        """Flatten the block into read/write arrays, or None when a shape
        outside the device scope (range query, metadata write) appears in
        a tx that would actually be validated."""
        key_ids: dict = {}
        r_tx: List[int] = []
        r_key: List[int] = []
        r_bad: List[bool] = []
        w_tx: List[int] = []
        w_key: List[int] = []

        def kid(k) -> int:
            i = key_ids.get(k)
            if i is None:
                i = len(key_ids)
                key_ids[k] = i
            return i

        for t, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes)):
            if code != TxValidationCode.VALID or rwset is None:
                continue
            for ns_rw in rwset.ns_rw_sets:
                if ns_rw.range_queries or ns_rw.metadata_writes:
                    return None
                ns = ns_rw.namespace
                for read in ns_rw.reads:
                    committed = self.db.get_version(ns, read.key)
                    r_tx.append(t)
                    r_key.append(kid((ns, "", read.key)))
                    r_bad.append(committed != read.version)
                for w in ns_rw.writes:
                    w_tx.append(t)
                    w_key.append(kid((ns, "", w.key)))
                for coll in ns_rw.coll_hashed:
                    if coll.metadata_writes:
                        return None
                    cn = coll.collection_name
                    for hread in coll.hashed_reads:
                        committed = self.db.get_key_hash_version(
                            ns, cn, hread.key_hash
                        )
                        r_tx.append(t)
                        r_key.append(kid((ns, cn, hread.key_hash)))
                        r_bad.append(committed != hread.version)
                    for hw in coll.hashed_writes:
                        w_tx.append(t)
                        w_key.append(kid((ns, cn, hw.key_hash)))
        return r_tx, r_key, r_bad, w_tx, w_key, len(key_ids)

    # -- public API (mirrors mvcc.Validator) ------------------------------
    def validate_and_prepare_batch(
        self,
        block_num: int,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
        do_mvcc: bool = True,
    ) -> Tuple[List[TxValidationCode], UpdateBatch, HashedUpdateBatch]:
        if not do_mvcc:
            return self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes, do_mvcc=False
            )
        enc = self._encode(tx_rwsets, incoming_codes)
        if enc is None:
            self.last_path = "host"
            return self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes
            )
        self.last_path = "device"
        r_tx, r_key, r_bad, w_tx, w_key, n_keys = enc
        T = len(tx_rwsets)
        K = max(n_keys, 1)
        R = _next_pow2(max(len(r_tx), 1))
        W = _next_pow2(max(len(w_tx), 1))
        Tb = _next_pow2(T)
        Kb = _next_pow2(K)

        valid = _resolve(
            _col(r_tx, R, Tb),
            _col(r_key, R, Kb),
            _col(r_bad, R, 0, dtype=np.bool_),
            _col(w_tx, W, Tb),
            _col(w_key, W, Kb),
            num_txs=Tb,
            num_keys=Kb,
        )
        return self._emit(
            np.asarray(valid), tx_rwsets, incoming_codes, block_num
        )

    def _emit(
        self, valid, tx_rwsets, incoming_codes, block_num
    ) -> Tuple[List[TxValidationCode], UpdateBatch, HashedUpdateBatch]:
        """Device verdicts -> (codes, update batches); shared with the
        resident variant so code-mapping fixes cannot diverge."""
        updates = UpdateBatch()
        hashed_updates = HashedUpdateBatch()
        out: List[TxValidationCode] = []
        for t, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes)):
            if code != TxValidationCode.VALID or rwset is None:
                out.append(code)
                continue
            if valid[t]:
                out.append(TxValidationCode.VALID)
                self._host._apply_write_set(
                    rwset, Version(block_num, t), updates, hashed_updates
                )
            else:
                out.append(TxValidationCode.MVCC_READ_CONFLICT)
        return out, updates, hashed_updates


# ---------------------------------------------------------------------------
# Device-RESIDENT version table (round-5 experiment, VERDICT r4 #4)
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("num_txs", "num_keys", "cap"),
    donate_argnums=(0,),
)
def _resolve_resident(
    versions,      # (cap, 2) int32 device-resident committed versions
    init_idx,      # (I,) slots to initialize this launch (new keys +
    init_ver,      # (I, 2)  host-fallback refresh), sentinel cap = no-op
    r_gid,         # (R,) global slot per read (committed lookup)
    r_ver,         # (R, 2) version the read claims
    r_tx,
    r_key,         # (R,) block-local dense key id (fixpoint segments)
    w_tx,
    w_key,         # (W,) block-local dense key id
    w_gid,         # (W,) global slot per write (commit scatter)
    w_ver,         # (W, 2) version the write commits ((-1,-1) = delete)
    *,
    num_txs: int,
    num_keys: int,
    cap: int,
):
    """One launch per block: initialize fresh slots, check every read
    against the RESIDENT committed table (no host get_version probes),
    run the validity fixpoint, and scatter the valid writes' versions
    back into the table — which never leaves the device."""
    versions = versions.at[init_idx].set(init_ver, mode="drop")
    committed = versions[jnp.clip(r_gid, 0, cap - 1)]
    r_static_bad = jnp.any(committed != r_ver, axis=1)

    valid = _resolve(
        r_tx, r_key, r_static_bad, w_tx, w_key,
        num_txs=num_txs, num_keys=num_keys,
    )

    # commit: LAST valid writer per key wins (tx order = index order)
    T1 = num_txs + 1
    K1 = num_keys + 1
    live = valid[w_tx]
    writer = jnp.where(live, w_tx.astype(jnp.int32), jnp.int32(-1))
    last_writer = jax.ops.segment_max(writer, w_key, num_segments=K1)
    is_last = live & (w_tx.astype(jnp.int32) == last_writer[w_key])
    scatter_idx = jnp.where(is_last, w_gid, jnp.int32(cap))
    versions = versions.at[scatter_idx].set(w_ver, mode="drop")
    return valid, versions


class ResidentDeviceValidator(DeviceValidator):
    """DeviceValidator variant that keeps the (ns, coll, key) -> version
    table RESIDENT in device memory across blocks (the win condition
    named in round 3's measurements: the per-block host encode pass no
    longer probes db.get_version per read — committed-version checks,
    the fixpoint, and the version-table update are one device launch).

    Coherence contract: all commits for the tracked namespaces flow
    through validate_and_prepare_batch (the kvledger path). Blocks that
    fall back to the host oracle (range queries / metadata writes)
    refresh the resident entries of the keys they wrote via the pending
    init queue.  State mutated BEHIND the validator's back (rollback +
    re-commit, rebuild_dbs, clear) is detected via an explicit
    GENERATION STAMP: the db carries ``state_generation`` (bumped by
    every out-of-band mutator), the table records the generation it was
    built against, and every block checks the stamp BEFORE trusting the
    table and AGAIN after the device launch — a stale table is dropped
    and the block re-resolves against live state (host oracle for the
    mid-block race, a fresh table otherwise).  A mask is never emitted
    from a dead table generation; ``invalidate()`` remains the manual
    seam.

    A key's slot is assigned on first sight and its committed version
    seeded from the host db ONCE (one probe per key lifetime, not one
    per block per read)."""

    def __init__(self, db: VersionedDB, capacity: int = 1 << 17):
        super().__init__(db)
        self._cap = capacity
        self._index: dict = {}  # (ns, coll, key) -> slot
        self._dev_versions = None  # lazily created on first device block
        self._pending_init: List[Tuple[int, Tuple[int, int]]] = []
        # generation stamp: the db.state_generation this table was built
        # against; None = no live table.  Deterministic invalidation
        # counter for harness scorecards (fabobs mirrors it).
        self._table_generation: Optional[int] = None
        self.invalidations = 0

    # -- coherence ---------------------------------------------------------
    def _db_generation(self) -> int:
        return getattr(self.db, "state_generation", 0)

    def invalidate(self) -> None:
        """Drop the resident table (state changed behind our back)."""
        self._index.clear()
        self._dev_versions = None
        self._pending_init.clear()
        self._table_generation = None

    def _note_stale(self, block_num: int, when: str) -> None:
        self.invalidations += 1
        fabobs.obs_count("fabric_mvcc_table_invalidations_total")
        logger.warning(
            "resident MVCC table generation %s went stale %s block %d "
            "(db generation %d): dropping residency and re-resolving "
            "against live state",
            self._table_generation, when, block_num, self._db_generation(),
        )
        self.invalidate()

    def _note_batches(self, updates: UpdateBatch, hashed: HashedUpdateBatch):
        """Queue refreshes for host-committed writes of tracked keys."""
        for (ns, key), entry in updates.items():
            slot = self._index.get((ns, "", key))
            if slot is not None:
                ver = (
                    _NO_VERSION
                    if entry.value is None
                    else (entry.version.block_num, entry.version.tx_num)
                )
                self._pending_init.append((slot, ver))
        for (ns, coll, key_hash), entry in hashed.items():
            slot = self._index.get((ns, coll, key_hash))
            if slot is not None:
                ver = (
                    _NO_VERSION
                    if entry.value is None
                    else (entry.version.block_num, entry.version.tx_num)
                )
                self._pending_init.append((slot, ver))

    def _slot(self, k, inits: List[Tuple[int, Tuple[int, int]]]) -> int:
        slot = self._index.get(k)
        if slot is None:
            slot = len(self._index)
            self._index[k] = slot
            ns, coll, key = k
            committed = (
                self.db.get_key_hash_version(ns, coll, key)
                if coll
                else self.db.get_version(ns, key)
            )
            inits.append(
                (
                    slot,
                    (committed.block_num, committed.tx_num)
                    if committed is not None
                    else _NO_VERSION,
                )
            )
        return slot

    # -- public API --------------------------------------------------------
    def validate_and_prepare_batch(
        self,
        block_num: int,
        tx_rwsets: Sequence[Optional[TxRwSet]],
        incoming_codes: Sequence[TxValidationCode],
        do_mvcc: bool = True,
    ) -> Tuple[List[TxValidationCode], UpdateBatch, HashedUpdateBatch]:
        if not do_mvcc:
            out = self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes, do_mvcc=False
            )
            # commits still flow: tracked resident entries must refresh
            self._note_batches(out[1], out[2])
            return out
        # generation check (per block, BEFORE the table is trusted):
        # state changed behind our back invalidates every resident
        # version — fail closed, re-resolve, never serve stale
        gen_at_start = self._db_generation()
        if (
            self._dev_versions is not None
            and self._table_generation != gen_at_start
        ):
            self._note_stale(block_num, "before")
        enc = self._encode_resident(tx_rwsets, incoming_codes, block_num)
        if enc is None:
            self.last_path = "host"
            out = self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes
            )
            self._note_batches(out[1], out[2])
            return out
        self.last_path = "device"
        (r_tx, r_key, r_gid, r_ver, w_tx, w_key, w_gid, w_ver,
         n_keys, inits) = enc
        # dedupe by slot, LATEST entry wins: XLA scatter order for
        # duplicate indices is undefined, and two queued refreshes of
        # the same key must not let the stale one survive
        merged = {}
        for slot, v in self._pending_init + inits:
            merged[slot] = v
        inits = list(merged.items())
        self._pending_init = []

        # capacity growth (doubling) before the launch that needs it:
        # resolve the final capacity on host first, then extend the
        # device table ONCE — the old per-doubling concatenate allocated
        # (and for each new shape compiled) one intermediate per pass
        old_cap = self._cap
        while len(self._index) > self._cap:
            self._cap *= 2
        if self._dev_versions is not None and self._cap > old_cap:
            self._dev_versions = jnp.concatenate(
                [
                    self._dev_versions,
                    jnp.full((self._cap - old_cap, 2), -1, dtype=jnp.int32),
                ]
            )
        if self._dev_versions is None:
            self._dev_versions = jnp.full(
                (self._cap, 2), -1, dtype=jnp.int32
            )
        # stamp the table with the generation its seeds were read under
        self._table_generation = gen_at_start

        T = len(tx_rwsets)
        K = max(n_keys, 1)
        R = _next_pow2(max(len(r_tx), 1))
        W = _next_pow2(max(len(w_tx), 1))
        Ib = _next_pow2(max(len(inits), 1))
        Tb = _next_pow2(T)
        Kb = _next_pow2(K)

        def col2(pairs, pad_to):
            a = np.full((pad_to, 2), -1, dtype=np.int32)
            if pairs:
                a[: len(pairs)] = pairs
            return a

        init_idx = _col([i for i, _v in inits], Ib, self._cap)
        init_ver = col2([v for _i, v in inits], Ib)
        try:
            valid, self._dev_versions = _resolve_resident(
                self._dev_versions,
                init_idx,
                init_ver,
                _col(r_gid, R, self._cap),
                col2(r_ver, R),
                _col(r_tx, R, Tb),
                _col(r_key, R, Kb),
                _col(w_tx, W, Tb),
                _col(w_key, W, Kb),
                _col(w_gid, W, self._cap),
                col2(w_ver, W),
                num_txs=Tb,
                num_keys=Kb,
                cap=self._cap,
            )
        except Exception as exc:
            # the table buffer is DONATED into the launch: after any
            # dispatch failure its contents are unreliable — drop the
            # residency and serve this block from the host oracle
            logger.warning(
                "device MVCC dispatch failed (%s); dropping residency and "
                "validating this block on the host", exc,
            )
            self.invalidate()
            self.last_path = "host"
            out = self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes
            )
            self._note_batches(out[1], out[2])
            return out

        if self._db_generation() != gen_at_start:
            # state mutated mid-block (between encode/launch and here):
            # the verdicts came from a DEAD table generation — discard
            # them unseen and re-resolve on the host against live state
            self._note_stale(block_num, "during")
            self.last_path = "host"
            out = self._host.validate_and_prepare_batch(
                block_num, tx_rwsets, incoming_codes
            )
            self._note_batches(out[1], out[2])
            return out

        return self._emit(
            np.asarray(valid), tx_rwsets, incoming_codes, block_num
        )

    # -- encoding ----------------------------------------------------------
    def _encode_resident(self, tx_rwsets, incoming_codes, block_num):
        """Like DeviceValidator._encode but WITHOUT per-read host
        get_version probes: reads carry their claimed version and a
        global resident slot; the committed comparison happens on
        device. Writes carry the version they would commit."""
        inits: List[Tuple[int, Tuple[int, int]]] = []
        local_ids: dict = {}
        r_tx: List[int] = []
        r_key: List[int] = []
        r_gid: List[int] = []
        r_ver: List[Tuple[int, int]] = []
        w_tx: List[int] = []
        w_key: List[int] = []
        w_gid: List[int] = []
        w_ver: List[Tuple[int, int]] = []

        def lid(k) -> int:
            i = local_ids.get(k)
            if i is None:
                i = len(local_ids)
                local_ids[k] = i
            return i

        def abort():
            # slots assigned during this walk stay in the index; their
            # seeds must not be lost or the slots would sit at the
            # uninitialized sentinel forever (false conflicts later)
            self._pending_init.extend(inits)
            return None

        for t, (rwset, code) in enumerate(zip(tx_rwsets, incoming_codes)):
            if code != TxValidationCode.VALID or rwset is None:
                continue
            for ns_rw in rwset.ns_rw_sets:
                if ns_rw.range_queries or ns_rw.metadata_writes:
                    return abort()
                ns = ns_rw.namespace
                for read in ns_rw.reads:
                    k = (ns, "", read.key)
                    r_tx.append(t)
                    r_key.append(lid(k))
                    r_gid.append(self._slot(k, inits))
                    v = read.version
                    r_ver.append(
                        (v.block_num, v.tx_num) if v is not None else _NO_VERSION
                    )
                for w in ns_rw.writes:
                    k = (ns, "", w.key)
                    w_tx.append(t)
                    w_key.append(lid(k))
                    w_gid.append(self._slot(k, inits))
                    w_ver.append(
                        _NO_VERSION if w.is_delete else (block_num, t)
                    )
                for coll in ns_rw.coll_hashed:
                    if coll.metadata_writes:
                        return abort()
                    cn = coll.collection_name
                    for hread in coll.hashed_reads:
                        k = (ns, cn, hread.key_hash)
                        r_tx.append(t)
                        r_key.append(lid(k))
                        r_gid.append(self._slot(k, inits))
                        v = hread.version
                        r_ver.append(
                            (v.block_num, v.tx_num)
                            if v is not None
                            else _NO_VERSION
                        )
                    for hw in coll.hashed_writes:
                        k = (ns, cn, hw.key_hash)
                        w_tx.append(t)
                        w_key.append(lid(k))
                        w_gid.append(self._slot(k, inits))
                        w_ver.append(
                            _NO_VERSION if hw.is_delete else (block_num, t)
                        )
        return (
            r_tx, r_key, r_gid, r_ver, w_tx, w_key, w_gid, w_ver,
            len(local_ids), inits,
        )
