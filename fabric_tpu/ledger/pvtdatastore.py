"""Private-data store (reference core/ledger/pvtdatastorage/store.go).

Persists per-block private write-sets (cleartext TxPvtReadWriteSet
payloads) next to the block store, with:

* BTL (block-to-live) expiry per (namespace, collection) — expired
  entries are purged at commit time (pvtstatepurgemgmt analog);
* missing-data bookkeeping for collections the peer is entitled to but
  did not have at commit (feeds the reconciler, reconcile_missing_
  pvtdata.go);
* commit protocol: prepare(block_num, data) then committed marker, so a
  crash between pvtdata and block commit is detectable on recovery
  (store.go Commit + pendingCommit semantics).

File format: one append-only file of doubly-checksummed records
(``u32 len || u32 crc32(len) || body || u32 crc32(body)`` — the block
store's frame discipline):
  record = {block_num, [(tx_num, ns, coll, rwset_bytes)], [missing keys]}
serialized as a PvtBlockRecord proto-free binary layout (length-prefixed
fields) — simple, deterministic, rebuildable by scan like the block store,
and carrying the same crash-consistency contract (fabcrash, PR 13): a torn
tail record is truncated on recovery (loud log +
``fabric_ledger_torn_tail_total``); damage one interrupted append cannot
explain (including a corrupted length prefix, caught by the header
checksum) fails closed via :class:`~fabric_tpu.ledger.blockstore.
LedgerCorruptionError` (salvageable with FABRIC_TPU_RECOVERY_STRICT=0).
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common import fabobs
from fabric_tpu.ledger.blockstore import (
    frame_header,
    fsync_dir,
    read_frame_header,
    refuse_corrupt,
)

logger = must_get_logger("pvtdatastore")


@dataclass(frozen=True)
class PvtEntry:
    tx_num: int
    namespace: str
    collection: str
    rwset: bytes  # serialized KVRWSet (cleartext writes)


@dataclass(frozen=True)
class MissingEntry:
    tx_num: int
    namespace: str
    collection: str
    eligible: bool = True  # peer is entitled but lacked the data


def _w_bytes(out: bytearray, b: bytes) -> None:
    out += struct.pack("<I", len(b))
    out += b


def _r_bytes(buf: memoryview, off: int) -> Tuple[bytes, int]:
    (ln,) = struct.unpack_from("<I", buf, off)
    off += 4
    if off + ln > len(buf):
        raise ValueError("truncated record")
    return bytes(buf[off : off + ln]), off + ln


class PvtDataStore:
    def __init__(self, path: str, btl_policy=None):
        """btl_policy: callable (ns, coll) -> int blocks-to-live (0 = keep
        forever), matching the reference's BTLPolicy from collection
        configs."""
        self.path = path
        self.btl = btl_policy or (lambda ns, coll: 0)
        # block_num -> entries (committed, unexpired)
        self._by_block: Dict[int, List[PvtEntry]] = {}
        self._missing: Dict[int, List[MissingEntry]] = {}
        self._last_committed = -1
        self._closed = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._recover()
        self._f = open(self.path, "ab")

    # -- persistence ------------------------------------------------------
    def _refuse(self, why: str) -> None:
        """Same fail-closed discipline as BlockStore._refuse: strict
        (default) raises; FABRIC_TPU_RECOVERY_STRICT=0 salvages."""
        refuse_corrupt(
            logger, f"pvtdata store {self.path}", why, "corrupt-pvtdata",
            "truncate to the last whole record",
        )

    def _recover(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        buf = memoryview(data)
        off = 0
        valid_end = 0
        refused = False  # salvage truncation, NOT a benign torn tail
        while off < len(data):
            if off + 8 > len(buf):
                break  # torn header at the tail
            ln = read_frame_header(bytes(buf[off : off + 8]))
            if ln is None:
                # a full header failing its own checksum is corruption
                # (a torn append leaves a PREFIX of a valid record)
                self._refuse(f"record header checksum failed at offset {off}")
                refused = True
                break
            end = off + 8 + ln + 4
            if end > len(buf):
                break  # header-validated length overshoots EOF: torn tail
            body = bytes(buf[off + 8 : off + 8 + ln])
            (crc,) = struct.unpack_from("<I", buf, off + 8 + ln)
            if zlib.crc32(body) != crc:
                # one interrupted append can only damage the LAST record
                if end < len(data):
                    self._refuse(f"checksum mismatch at offset {off}")
                    refused = True
                break
            try:
                self._load_record(body)
            except (struct.error, ValueError, IndexError):
                # checksum-valid but undecodable: fully written garbage,
                # never a torn append
                self._refuse(f"checksummed record at offset {off} does not parse")
                refused = True
                break
            off = end
            valid_end = off
        if valid_end != len(data):
            if refused:
                logger.critical(
                    "pvtdata store %s: salvage dropped %d bytes "
                    "(FABRIC_TPU_RECOVERY_STRICT=0)",
                    self.path, len(data) - valid_end,
                )
            else:
                logger.warning(
                    "pvtdata store %s: truncating %d-byte torn tail "
                    "(crash recovery)", self.path, len(data) - valid_end,
                )
                fabobs.obs_count(
                    "fabric_ledger_torn_tail_total", store="pvtdata"
                )
            with open(self.path, "ab") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())
            fsync_dir(self.path)

    def _load_record(self, rec: bytes) -> None:
        """Replay one record. Multiple records for the same block are the
        backfill case (commit_pvt_data_of_old_blocks appends): entries
        accumulate and clear any matching missing markers, reproducing
        the in-memory state at the time of the crash."""
        buf = memoryview(rec)
        (block_num, n_entries, n_missing) = struct.unpack_from("<QII", buf, 0)
        # Each entry consumes >= 4 bytes, so a count larger than the crc'd
        # body is a corrupt or hostile record: refuse it before the loops
        # allocate per-count (the decode_verify_request discipline).
        if n_entries > len(rec) or n_missing > len(rec):
            raise ValueError(
                f"pvt record counts exceed body size (entries={n_entries} "
                f"missing={n_missing} len={len(rec)})"
            )
        off = 16
        entries = []
        for _ in range(n_entries):
            (tx_num,) = struct.unpack_from("<I", buf, off)
            off += 4
            ns, off = _r_bytes(buf, off)
            coll, off = _r_bytes(buf, off)
            rwset, off = _r_bytes(buf, off)
            entries.append(PvtEntry(tx_num, ns.decode(), coll.decode(), rwset))
        missing = []
        for _ in range(n_missing):
            (tx_num, eligible) = struct.unpack_from("<IB", buf, off)
            off += 5
            ns, off = _r_bytes(buf, off)
            coll, off = _r_bytes(buf, off)
            missing.append(
                MissingEntry(tx_num, ns.decode(), coll.decode(), bool(eligible))
            )
        self._by_block.setdefault(block_num, []).extend(entries)
        still = [
            m
            for m in self._missing.get(block_num, [])
            if not any(
                e.tx_num == m.tx_num
                and e.namespace == m.namespace
                and e.collection == m.collection
                for e in entries
            )
        ] + missing
        if still:
            self._missing[block_num] = still
        else:
            self._missing.pop(block_num, None)
        self._last_committed = max(self._last_committed, block_num)

    def _append_record(
        self,
        block_num: int,
        entries: Sequence[PvtEntry],
        missing: Sequence[MissingEntry],
    ) -> None:
        body = bytearray(struct.pack("<QII", block_num, len(entries), len(missing)))
        for e in entries:
            body += struct.pack("<I", e.tx_num)
            _w_bytes(body, e.namespace.encode())
            _w_bytes(body, e.collection.encode())
            _w_bytes(body, e.rwset)
        for m in missing:
            body += struct.pack("<IB", m.tx_num, int(m.eligible))
            _w_bytes(body, m.namespace.encode())
            _w_bytes(body, m.collection.encode())
        body_bytes = bytes(body)
        out = bytearray(frame_header(len(body_bytes)))
        out += body_bytes
        out += struct.pack("<I", zlib.crc32(body_bytes))
        self._f.write(out)
        self._f.flush()
        os.fsync(self._f.fileno())
        fsync_dir(self.path)

    # -- commit path (store.go Commit) ------------------------------------
    def commit(
        self,
        block_num: int,
        entries: Sequence[PvtEntry],
        missing: Sequence[MissingEntry] = (),
    ) -> None:
        if block_num <= self._last_committed:
            raise ValueError(
                f"pvtdata for block {block_num} already committed "
                f"(last committed {self._last_committed})"
            )
        self._append_record(block_num, entries, missing)
        self._by_block[block_num] = list(entries)
        if missing:
            self._missing[block_num] = list(missing)
        self._last_committed = block_num
        self._purge_expired(block_num)

    def _purge_expired(self, current_block: int) -> None:
        """BTL purge (pvtstatepurgemgmt): entries whose
        birth + btl < current are dropped from the in-memory view; the
        file keeps history (compaction is a rewrite, as in the reference's
        leveldb purge batches)."""
        for bnum in list(self._by_block):
            kept = []
            for e in self._by_block[bnum]:
                btl = self.btl(e.namespace, e.collection)
                if btl and bnum + btl < current_block:
                    continue
                kept.append(e)
            if kept:
                self._by_block[bnum] = kept
            elif self._by_block[bnum]:
                self._by_block[bnum] = []

    # -- queries ----------------------------------------------------------
    def get_pvt_data_by_block(self, block_num: int) -> List[PvtEntry]:
        return list(self._by_block.get(block_num, []))

    def get_pvt_data(
        self, block_num: int, tx_num: int
    ) -> List[PvtEntry]:
        return [
            e for e in self._by_block.get(block_num, []) if e.tx_num == tx_num
        ]

    @property
    def last_committed_block(self) -> int:
        return self._last_committed

    # -- missing data / reconciliation ------------------------------------
    def get_missing_pvt_data(
        self, max_blocks: int = 0
    ) -> Dict[int, List[MissingEntry]]:
        """Oldest-first missing-data view (GetMissingPvtDataInfoForMostRecentBlocks
        inverted to oldest-first for deterministic reconciliation)."""
        out: Dict[int, List[MissingEntry]] = {}
        for bnum in sorted(self._missing):
            out[bnum] = list(self._missing[bnum])
            if max_blocks and len(out) >= max_blocks:
                break
        return out

    def commit_pvt_data_of_old_blocks(
        self, block_num: int, entries: Sequence[PvtEntry]
    ) -> None:
        """Reconciler write-back (CommitPvtDataOfOldBlocks): store
        late-arriving pvtdata and clear the matching missing markers."""
        if block_num > self._last_committed:
            raise ValueError("cannot backfill a block that is not committed")
        self._append_record(block_num, entries, ())
        self._by_block.setdefault(block_num, []).extend(entries)
        still = [
            m
            for m in self._missing.get(block_num, [])
            if not any(
                e.tx_num == m.tx_num
                and e.namespace == m.namespace
                and e.collection == m.collection
                for e in entries
            )
        ]
        if still:
            self._missing[block_num] = still
        else:
            self._missing.pop(block_num, None)

    def rollback_to(self, height: int) -> None:
        """Drop every record for block >= height and compact the file
        (KVLedger.rollback counterpart; the reference's pvtdata store
        rollback in kvledger rollback.go)."""
        self._f.close()
        self._by_block = {b: e for b, e in self._by_block.items() if b < height}
        self._missing = {b: m for b, m in self._missing.items() if b < height}
        self._last_committed = max(self._by_block, default=-1)
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            self._f = f
            for bnum in sorted(self._by_block):
                self._append_record(
                    bnum, self._by_block[bnum], self._missing.get(bnum, [])
                )
        os.replace(tmp, self.path)
        fsync_dir(self.path)
        self._f = open(self.path, "ab")
        self._closed = False

    def close(self) -> None:
        """Idempotent; tolerates a partially-constructed store."""
        if self._closed:
            return
        self._closed = True
        f = getattr(self, "_f", None)
        if f is not None:
            f.close()
