"""Compatibility shim: txflags moved to ``fabric_tpu.common.txflags``.

TxValidationCode/ValidationFlags are leaf types consumed by both the
ledger (MVCC, metrics, kvledger) and the validation pipeline; keeping
them under validation/ created the ledger<->validation import cycle the
fabdep layering gate forbids, so the implementation now lives in the
lowest shared layer.  This shim aliases the real module, so
``fabric_tpu.validation.txflags is fabric_tpu.common.txflags`` and
every historical import keeps working.
"""

import sys as _sys

from fabric_tpu.common import txflags as _impl

_sys.modules[__name__] = _impl
