"""Validation-plugin dispatch (reference core/committer/txvalidator/v20/
plugindispatcher + core/handlers/library/registry.go).

Resolves, per chaincode namespace, WHICH validation plugin runs and with
WHAT policy — from the committed _lifecycle state when available, else
from legacy static definitions. The reference loads Go .so plugins
(registry.go:134 plugin.Open); here plugins are registered callables and
the builtin plugin is the batched device validator.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from fabric_tpu.lifecycle import NAMESPACE as LIFECYCLE_NS
from fabric_tpu.lifecycle import LifecycleResources
from fabric_tpu.policy.ast import SignaturePolicyEnvelope
from fabric_tpu.policy.proto_convert import (
    PolicyConversionError,
    unmarshal_application_policy,
)


class PluginRegistry:
    """Named validation plugins (library/registry.go analog). A plugin is
    whatever the caller wants to dispatch on — the BlockValidator only
    checks that the resolved name exists."""

    def __init__(self):
        self._plugins: Dict[str, object] = {"builtin": object(), "vscc": object()}

    def register(self, name: str, plugin: object) -> None:
        self._plugins[name] = plugin

    def load(self, name: str, ref: str) -> object:
        """Dynamic plugin loading, the Go `plugin.Open` analog
        (core/handlers/library/registry.go:134): `ref` is
        "module.path:attribute"; the attribute (or module) becomes the
        registered plugin object."""
        import importlib
        import inspect

        mod_name, _, attr = ref.partition(":")
        mod = importlib.import_module(mod_name)
        plugin = getattr(mod, attr) if attr else mod
        if inspect.isclass(plugin):
            plugin = plugin()  # class reference: instantiate
        self.register(name, plugin)
        return plugin

    def get(self, name: str) -> Optional[object]:
        return self._plugins.get(name)

    def exists(self, name: str) -> bool:
        return name in self._plugins


class LifecycleRegistry:
    """ChaincodeRegistry drop-in that resolves definitions from committed
    _lifecycle state (valinforetriever/shim.go: lifecycle first, legacy
    fallback)."""

    def __init__(
        self,
        state_get: Callable[[str, str], Optional[bytes]],
        legacy=None,
        plugin_registry: Optional[PluginRegistry] = None,
    ):
        """state_get(ns, key) -> committed state bytes."""
        from fabric_tpu.validation.validator import ChaincodeDefinition

        self._cd_cls = ChaincodeDefinition
        self._legacy = legacy
        self.plugins = plugin_registry or PluginRegistry()
        self._resources = LifecycleResources(
            public_get=lambda key: state_get(LIFECYCLE_NS, key),
            public_put=self._readonly,
            org_get=lambda org, key: None,
            org_put=self._readonly,
            org_names=[],
        )

    @staticmethod
    def _readonly(*_args):
        raise RuntimeError("validator-side lifecycle view is read-only")

    def get(self, name: str):
        info = self._resources.validation_info(name)
        if info is None:
            return self._legacy.get(name) if self._legacy else None
        plugin_name, vp_bytes = info
        plugin_name = plugin_name or "builtin"
        if not self.plugins.exists(plugin_name):
            # unresolvable plugin invalidates the tx (reference
            # plugin_validator.go getOrCreatePlugin error path) — surfaced
            # as a missing definition -> INVALID_CHAINCODE
            return None
        try:
            policy = unmarshal_application_policy(vp_bytes)
        except PolicyConversionError:
            return None
        return self._cd_cls(name, policy, plugin=plugin_name)
