"""Commit-time validation pipeline (reference core/committer/txvalidator)."""

from fabric_tpu.common.txflags import TxValidationCode, ValidationFlags

__all__ = ["TxValidationCode", "ValidationFlags"]
