"""Block validator (reference core/committer/txvalidator/v20/validator.go +
plugindispatcher + builtin v20 VSCC), TPU-batched.

The reference fans out a goroutine per transaction and verifies each
signature inline. Here a block is validated in four phases:

1. host parse: structural checks per tx (msgvalidation), emitting
   deferred signature jobs;
2. device batch: EVERY signature in the block (creator + endorsement)
   verified in one batched kernel call (P1+P2 of SURVEY.md §2.13
   collapsed into a single (tx x sig) lane dimension);
3. host principal matching: (signer, principal) satisfaction bits with an
   identity/principal cache;
4. policy circuits: txs grouped by endorsement policy, each group
   evaluated as one vectorized greedy-cauthdsl batch; then TxID duplicate
   marking and reference-ordered code assembly.

Output parity surface: the TRANSACTIONS_FILTER uint8 array in block
metadata, bit-exact with the reference for every supported scenario.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fabric_tpu.crypto.bccsp import Provider
from fabric_tpu.msp.identity import Identity, MSPError, MSPManager
from fabric_tpu.policy.ast import SignaturePolicyEnvelope
from fabric_tpu.policy.evaluator import compile_batched_numpy, evaluate_host
from fabric_tpu.protos import common_pb2, msp_principal_pb2, protoutil
from fabric_tpu.validation.blockparse import ParsedBlock, parse_block
from fabric_tpu.ledger.txparse import ParsedTx, SigJob
from fabric_tpu.validation.statebased import (
    VALIDATION_PARAMETER,
    BlockDependencies,
    KeyLevelEvaluator,
)
from fabric_tpu.ledger.mvcc import deserialize_metadata
from fabric_tpu.common.txflags import TxValidationCode, ValidationFlags


class ValidationError(Exception):
    """Terminal validation failure — aborts block processing (the
    reference's VSCCExecutionFailureError / config-tx apply errors)."""


@dataclass
class ChaincodeDefinition:
    """What the dispatcher needs per namespace (reference
    plugindispatcher valinforetriever / _lifecycle cache)."""

    name: str
    endorsement_policy: SignaturePolicyEnvelope
    plugin: str = "builtin"


class ChaincodeRegistry:
    """Static stand-in for the _lifecycle validation-info source."""

    def __init__(self, definitions: Sequence[ChaincodeDefinition] = ()):
        self._defs = {d.name: d for d in definitions}

    def define(self, definition: ChaincodeDefinition) -> None:
        self._defs[definition.name] = definition

    def get(self, name: str) -> Optional[ChaincodeDefinition]:
        return self._defs.get(name)

    def names(self) -> List[str]:
        return sorted(self._defs)


# policy-group map: (policy envelope, plugin) -> (definition,
# [(tx index, namespace), ...])
PolicyGroups = Dict[
    Tuple[SignaturePolicyEnvelope, str],
    Tuple["ChaincodeDefinition", List[Tuple[int, str]]],
]


# re-export: moved to ledger.txparse so the parse layer can share it
from fabric_tpu.ledger.txparse import (  # noqa: E402
    writes_to_namespace as _writes_to_namespace,
)


# re-export: moved to policy.proto_convert so the policy manager and
# ledger collections can use it without importing the validation layer
from fabric_tpu.policy.proto_convert import principal_for  # noqa: E402,F401


class BlockValidator:
    """Per-channel validator: block -> TRANSACTIONS_FILTER."""

    def __init__(
        self,
        channel_id: str,
        msp_manager: MSPManager,
        provider: Provider,
        registry: ChaincodeRegistry,
        tx_exists: Optional[Callable[[str], bool]] = None,
        apply_config: Optional[Callable[[bytes], None]] = None,
        get_state_metadata: Optional[Callable[[str, str, object], Optional[bytes]]] = None,
        get_collection_ep: Optional[
            Callable[[str, str], Optional[SignaturePolicyEnvelope]]
        ] = None,
        writeset_check: Optional[Callable] = None,
        plugin_registry=None,
    ):
        # optional extra write-set rule, e.g. the v12 system-namespace
        # guards on legacy channels (validation/legacy.check_v12_writeset)
        self.writeset_check = writeset_check
        # named custom validation plugins (dispatcher.PluginRegistry);
        # definitions whose plugin resolves to an object with a
        # `validate` callable dispatch there instead of the builtin path
        self.plugin_registry = plugin_registry
        self.channel_id = channel_id
        self.msp_manager = msp_manager
        self.provider = provider
        # backend label of the most recent signature batch (see
        # _batch_verify_sigs); None until a block has been validated
        self.last_sig_backend: Optional[str] = None
        self.registry = registry
        self.tx_exists = tx_exists or (lambda txid: False)
        self.apply_config = apply_config
        # committed key metadata for state-based endorsement:
        # (ns, coll, key) -> serialized metadata bytes
        self.get_state_metadata = get_state_metadata or (
            lambda ns, coll, key: None
        )
        self.get_collection_ep = get_collection_ep
        # caches (reference msp/cache + discovery/authcache analogs)
        self._principal_cache: Dict[Tuple[bytes, bytes], bool] = {}
        # keyed by the (hashable, frozen) envelope itself — id() would
        # alias freed envelopes after a policy upgrade
        self._policy_fn_cache: Dict[SignaturePolicyEnvelope, Callable] = {}
        self._principals_cache: Dict[
            SignaturePolicyEnvelope,
            List[Tuple[msp_principal_pb2.MSPPrincipal, bytes]],
        ] = {}
        # serialized identity bytes -> validated Identity (or None when
        # deserialization / cert-chain validation failed). The native
        # parser interns identity bytes so every job of the same signer
        # hits ONE entry here instead of re-walking the MSP caches
        # (reference msp/cache/cache.go DeserializeIdentity memoization).
        #
        # THE cross-stage shared state of the commit pipeline: stage A
        # (collect_sig_jobs, on the deliver thread preparing block N+1)
        # reads/fills it while stage B (validate, on the committer
        # thread finishing block N) clears it on a config tx — the
        # pipeline audit driven by fabdep's unguarded-shared-write rule
        # found the unlocked clear could drop entries mid-fill and, far
        # worse, a stage-A size-check clear racing a stage-B CRL-rotation
        # clear could resurrect a pre-rotation identity from a stale
        # local reference. Every access now holds _ident_lock.
        self._ident_cache: Dict[bytes, Optional[Identity]] = {}
        self._ident_lock = threading.Lock()
        # generation counter, bumped on every CRL-rotation clear: a
        # stage-A fill that started BEFORE the clear must not land
        # AFTER it (it would resurrect an identity validated against
        # the pre-rotation CRL); fills compare generations and drop
        self._ident_gen = 0
        # per-policy memo of circuit verdicts keyed by the tx's signer
        # pattern (tuple of (Identity, sig_ok)); the dict holds strong
        # refs to the Identity objects so keys can never alias.
        self._pattern_memo: Dict[
            SignaturePolicyEnvelope, Dict[tuple, bool]
        ] = {}

    # ------------------------------------------------------------------
    def validate(
        self,
        block: common_pb2.Block,
        parsed: Optional[Sequence[ParsedTx]] = None,
        sig_results: Optional[Dict[int, bool]] = None,
    ) -> ValidationFlags:
        """Validate a block; writes TRANSACTIONS_FILTER metadata and
        returns the flags (reference Validate, v20/validator.go:180-265).

        `parsed` lets the caller share one parse pass with the commit
        stage instead of re-decoding every envelope; `sig_results` lets a
        multi-channel scheduler run the device batch for several channels
        at once (fabric_tpu.parallel.multichannel) and hand each
        validator its pre-computed per-job verdicts."""
        data = list(block.data.data)
        if parsed is None:
            parsed = parse_block(data)

        if sig_results is None:
            sig_results = self._batch_verify_sigs(parsed)
        flags = ValidationFlags(len(data))
        txid_array: List[str] = [""] * len(data)

        policy_groups = self._assemble_codes(parsed, sig_results, flags, txid_array)
        policy_groups, plugin_results = self._dispatch_custom_plugins(
            policy_groups, parsed, flags, block
        )
        self._evaluate_policies(policy_groups, parsed, flags, plugin_results)

        # duplicate TxIDs: vs ledger first (checkTxIdDupsLedger), then
        # in-block (markTXIdDuplicates) — first occurrence wins.
        for tx in parsed:
            i = tx.index
            if flags.flag(i) == TxValidationCode.NOT_VALIDATED:
                # a lazy rwset materialization during the policy phase may
                # have demoted the tx (native/Python parse divergence —
                # see ParsedTx.rwset); honor it before declaring VALID
                if tx.code == TxValidationCode.BAD_RWSET:
                    flags.set_flag(i, TxValidationCode.BAD_RWSET)
                    continue
                flags.set_flag(i, TxValidationCode.VALID)
                txid_array[i] = tx.tx_id
        seen: Dict[str, int] = {}
        for i, txid in enumerate(txid_array):
            if not txid:
                continue
            # endorser txs already paid the ledger probe in
            # _assemble_codes (pre-dispatch DUPLICATE_TXID priority);
            # only non-endorser txids still need the ledger check here
            if parsed[i].header_type != common_pb2.ENDORSER_TRANSACTION and (
                self.tx_exists(txid)
            ):
                flags.set_flag(i, TxValidationCode.DUPLICATE_TXID)
                txid_array[i] = ""
                continue
            if txid in seen:
                flags.set_flag(i, TxValidationCode.DUPLICATE_TXID)
            else:
                seen[txid] = i

        protoutil.init_block_metadata(block)
        block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()
        return flags

    # ------------------------------------------------------------------
    def invalidate_identity_caches(self) -> None:
        """MSPs/CRLs rotated: drop every identity-derived cache.  Called
        by the config-tx path and by any out-of-band rotation (admin CRL
        push, the fabchaos crl_rotation scenario).  The ident-cache
        clear + generation bump is thread-safe — an in-flight stage-A
        fill validated against the pre-rotation CRL compares generations
        and drops.  The principal/pattern memos have a single
        reader/writer (the validate() thread), so calling this from any
        other thread is safe only while no validate() is in flight."""
        with self._ident_lock:
            self._ident_cache.clear()
            self._ident_gen += 1
        self._principal_cache.clear()
        self._pattern_memo.clear()

    # ------------------------------------------------------------------
    def collect_sig_jobs(
        self, parsed: Sequence[ParsedTx]
    ) -> Tuple[List[SigJob], Dict[int, Optional[Identity]], List, List[bytes], List[bytes]]:
        """Phase-2 host prep: every deferred signature job in the block,
        identities deserialized + cert-chain/CRL validated (reference
        identities.go:107), verifiable jobs flattened into (keys, sigs,
        digests) device-batch inputs. Digests precomputed by the native
        parser are used as-is; Python-parsed jobs are hashed here in one
        provider batch."""
        jobs: List[SigJob] = []
        for tx in parsed:
            if tx.creator_sig_job is not None:
                jobs.append(tx.creator_sig_job)
            jobs.extend(tx.endorsement_jobs)
        keys, payloads, sigs = [], [], []
        job_identity: Dict[int, Optional[Identity]] = {}
        ident_cache = self._ident_cache
        with self._ident_lock:
            if len(ident_cache) > 8192:
                ident_cache.clear()
        _MISS = object()
        for job in jobs:
            ibytes = job.identity_bytes
            with self._ident_lock:
                ident = ident_cache.get(ibytes, _MISS)
                gen = self._ident_gen
            if ident is _MISS:
                # cert-chain walk + CRL check run OUTSIDE the lock (the
                # expensive part; a racing duplicate fill is idempotent)
                try:
                    ident, msp = self.msp_manager.deserialize_identity(ibytes)
                    msp.validate(ident)  # cert chain + CRL (identities.go:107)
                except MSPError:
                    ident = None
                with self._ident_lock:
                    if self._ident_gen == gen:
                        ident_cache[ibytes] = ident
                    # else: a config tx rotated MSPs/CRLs while we were
                    # validating — the result reflects the OLD CRL, so
                    # it must not enter the post-rotation cache
            job_identity[id(job)] = ident
            if ident is None:
                continue
            keys.append(ident.public_key)
            sigs.append(job.signature)
            payloads.append(job.digest if job.digest is not None else job)
        # one batched digest pass over the payloads that still need
        # hashing (pure-Python parse path), behind the provider SPI
        raw_idx = [k for k, p in enumerate(payloads) if isinstance(p, SigJob)]
        if raw_idx:
            hashed = self.provider.batch_hash(
                [payloads[k].data for k in raw_idx]
            )
            for k, d in zip(raw_idx, hashed):
                payloads[k] = d
        return jobs, job_identity, keys, sigs, payloads

    def finish_sig_results(
        self,
        jobs: Sequence[SigJob],
        job_identity: Dict[int, Optional[Identity]],
        ok_list: Sequence[bool],
    ) -> Dict[int, bool]:
        """Map per-lane device verdicts back to {id(job): bool}; jobs whose
        identity failed deserialization/validation are False."""
        results: Dict[int, bool] = {}
        it = iter(ok_list)
        for job in jobs:
            if job_identity[id(job)] is None:
                results[id(job)] = False
            else:
                results[id(job)] = bool(next(it))
        self._job_identity = job_identity
        self._sig_results = results
        return results

    def _batch_verify_sigs(self, parsed: Sequence[ParsedTx]) -> Dict[int, bool]:
        """Verify every deferred signature job in one device batch.
        Returns {id(job): bool}. Identity deserialization/validation
        failures mark the job False (the per-code mapping happens during
        assembly)."""
        jobs, job_identity, keys, sigs, digests = self.collect_sig_jobs(parsed)
        dispatch = getattr(self.provider, "batch_verify_async", None)
        if dispatch is not None:
            # overlap the device round-trip with the verdict-independent
            # host work of the policy epilogue: principal matching is a
            # property of (identity, principal), not of the signature
            # verdicts, so the satisfaction cache can warm while the
            # kernel runs (P4 discipline inside one block)
            resolver = dispatch(keys, sigs, digests)
            self._prewarm_satisfaction(parsed, job_identity)
            ok_list = resolver()
        else:
            ok_list = self.provider.batch_verify(keys, sigs, digests)
        # record which execution path this batch ACTUALLY took (device /
        # sw:fastec / sw:hostec / sw:p256 / degraded) — snapshot AFTER the
        # verdicts resolve so the batch that first trips the provider into
        # degraded mode is labeled degraded, not "tpu"; bench and ops
        # surfaces read it so a silent-fallback run is always labeled
        describe = getattr(self.provider, "describe_backend", None)
        self.last_sig_backend = (
            describe() if describe else type(self.provider).__name__
        )
        return self.finish_sig_results(jobs, job_identity, ok_list)

    def _prewarm_satisfaction(
        self, parsed: Sequence[ParsedTx], job_identity: Dict[int, Optional[Identity]]
    ) -> None:
        # per-namespace memo: blocks usually invoke a handful of
        # chaincodes, so resolve definition + principal list once per
        # namespace, not once per tx (LifecycleRegistry.get builds a
        # fresh definition object per call)
        by_ns: Dict[str, Optional[List]] = {}
        seen = set()
        for tx in parsed:
            if (
                not tx.structurally_valid
                or tx.header_type != common_pb2.ENDORSER_TRANSACTION
            ):
                continue
            pairs = by_ns.get(tx.namespace, False)
            if pairs is False:
                definition = self.registry.get(tx.namespace)
                pairs = (
                    None
                    if definition is None
                    else self._principal_pairs(definition.endorsement_policy)
                )
                by_ns[tx.namespace] = pairs
            if pairs is None:
                continue
            for job in tx.endorsement_jobs:
                ident = job_identity.get(id(job))
                if ident is None or (id(ident), tx.namespace) in seen:
                    continue
                seen.add((id(ident), tx.namespace))
                for pr, pr_bytes in pairs:
                    self._satisfies(ident, pr, pr_bytes)

    # ------------------------------------------------------------------
    def _assemble_codes(
        self,
        parsed: Sequence[ParsedTx],
        sig_results: Dict[int, bool],
        flags: ValidationFlags,
        txid_array: List[str],
    ) -> PolicyGroups:
        """Reference-ordered early code assembly; returns policy groups
        {id(definition): (definition, [tx indices])} for phase 4."""
        groups: PolicyGroups = {}
        for tx in parsed:
            i = tx.index
            if not tx.structurally_valid:
                flags.set_flag(i, tx.code)
                continue
            # creator signature (ValidateTransaction -> BAD_CREATOR_SIGNATURE)
            if not sig_results[id(tx.creator_sig_job)]:
                flags.set_flag(i, TxValidationCode.BAD_CREATOR_SIGNATURE)
                continue
            # channel routing (v20/validator.go:349-357)
            if tx.channel_id != self.channel_id:
                flags.set_flag(i, TxValidationCode.TARGET_CHAIN_NOT_FOUND)
                continue
            if tx.header_type == common_pb2.CONFIG:
                try:
                    if self.apply_config is not None:
                        self.apply_config(tx.config_data)
                        # config change can rotate MSPs/CRLs/policies:
                        # drop every derived cache (reference: channel
                        # resources bundle hot-swap invalidates them)
                        self.invalidate_identity_caches()
                except Exception as e:
                    raise ValidationError(
                        f"error validating config tx: {e}"
                    ) from e
                continue  # VALID (assigned later)
            if tx.header_type != common_pb2.ENDORSER_TRANSACTION:
                flags.set_flag(i, TxValidationCode.UNKNOWN_TX_TYPE)
                continue
            # ledger-duplicate check BEFORE policy dispatch: a replayed
            # txid is DUPLICATE_TXID even when its policy would also fail
            # (v20/validator.go:349 checkTxIdDupsLedger runs before the
            # plugin dispatch; same order in the v14 driver)
            if tx.tx_id and self.tx_exists(tx.tx_id):
                flags.set_flag(i, TxValidationCode.DUPLICATE_TXID)
                continue
            # the invoked chaincode plus every namespace the tx writes to
            # is validated against ITS OWN policy (reference
            # plugindispatcher/dispatcher.go:174-218); ns_entries avoids
            # materializing the rwset tree on the native parse path
            wr_ns = [tx.namespace]
            illegal = False
            entries = tx.ns_entries
            if entries is not None:
                seen_ns = set()
                for ns_name, ns_writes in entries:
                    if ns_name in seen_ns:
                        illegal = True  # dup namespace (dispatcher.go:175-178)
                        break
                    seen_ns.add(ns_name)
                    if ns_name != tx.namespace and ns_writes:
                        wr_ns.append(ns_name)
            if illegal:
                flags.set_flag(i, TxValidationCode.ILLEGAL_WRITESET)
                continue
            if self.writeset_check is not None:
                why = self.writeset_check(tx.rwset, tx.namespace)
                if why is not None:
                    flags.set_flag(i, TxValidationCode.ILLEGAL_WRITESET)
                    continue
            defs = []
            for ns in wr_ns:
                definition = self.registry.get(ns)
                if definition is None:
                    flags.set_flag(i, TxValidationCode.INVALID_CHAINCODE)
                    break
                defs.append((ns, definition))
            else:
                for ns, definition in defs:
                    # key by policy content, not object identity —
                    # LifecycleRegistry builds a fresh definition per get()
                    # and id()-keying would defeat batching entirely
                    key = (definition.endorsement_policy, definition.plugin)
                    groups.setdefault(key, (definition, []))[1].append((i, ns))
        return groups

    # ------------------------------------------------------------------
    def _satisfies(
        self,
        ident: Identity,
        principal: msp_principal_pb2.MSPPrincipal,
        principal_bytes: Optional[bytes] = None,
    ) -> bool:
        key = (
            ident.fingerprint(),
            principal_bytes
            if principal_bytes is not None
            else principal.SerializeToString(),
        )
        hit = self._principal_cache.get(key)
        if hit is None:
            try:
                self.msp_manager.get_msp(ident.msp_id).satisfies_principal(
                    ident, principal
                )
                hit = True
            except MSPError:
                hit = False
            if len(self._principal_cache) > 65536:
                self._principal_cache.clear()
            self._principal_cache[key] = hit
        return hit

    def _dispatch_custom_plugins(
        self,
        groups: PolicyGroups,
        parsed: Sequence[ParsedTx],
        flags: ValidationFlags,
        block: common_pb2.Block,
    ):
        """Route policy groups bound to a CUSTOM validation plugin
        (reference plugindispatcher: plugin.Validate per written
        namespace); groups on the builtin plugin pass through to the
        batched/SBE evaluation. Outcome mapping per plugin_api.

        Returns (remaining_groups, plugin_results) where plugin_results
        is {tx_index: {namespace: ok}} — the SBE pass needs the per-
        namespace verdicts so a VALID plugin-validated tx's key-metadata
        writes register as APPLIED in BlockDependencies (a later tx must
        validate against the updated key policy, not the stale one)."""
        from fabric_tpu.validation.plugin_api import (
            EndorsementInvalid,
            SignerInfo,
            ValidationContext,
        )

        remaining: PolicyGroups = {}
        plugin_results: Dict[int, Dict[str, bool]] = {}
        for key, (definition, entries) in groups.items():
            plugin = None
            if self.plugin_registry is not None:
                plugin = self.plugin_registry.get(definition.plugin)
            if not callable(getattr(plugin, "validate", None)):
                if definition.plugin not in ("builtin", "vscc"):
                    # named plugin missing from the registry: the
                    # definition is unusable (reference
                    # plugin_validator.go getOrCreatePlugin error)
                    for i, _ns in entries:
                        flags.set_flag(i, TxValidationCode.INVALID_CHAINCODE)
                    continue
                remaining[key] = (definition, entries)
                continue
            for i, ns in entries:
                if flags.flag(i) != TxValidationCode.NOT_VALIDATED:
                    continue
                tx = parsed[i]
                signers = []
                for job in tx.endorsement_jobs:
                    ident = self._job_identity.get(id(job))
                    signers.append(
                        SignerInfo(
                            msp_id=ident.msp_id if ident else "",
                            identity_bytes=job.identity_bytes,
                            sig_valid=self._sig_ok(job),
                        )
                    )
                ctx = ValidationContext(
                    channel_id=self.channel_id,
                    block_num=block.header.number,
                    tx_index=i,
                    namespace=ns,
                    tx_id=tx.tx_id,
                    envelope_bytes=bytes(block.data.data[i]),
                    policy=definition.endorsement_policy,
                    signers=signers,
                    default_check=lambda _tx=tx, _env=definition.endorsement_policy: (
                        self._eval_policy_host(_tx, _env)
                    ),
                    get_state_metadata=self.get_state_metadata,
                    ns_entries=tuple(tx.ns_entries or ()),
                )
                try:
                    plugin.validate(ctx)
                    plugin_results.setdefault(i, {})[ns] = True
                except EndorsementInvalid:
                    flags.set_flag(
                        i, TxValidationCode.ENDORSEMENT_POLICY_FAILURE
                    )
                    plugin_results.setdefault(i, {})[ns] = False
                except Exception as exc:  # noqa: BLE001
                    # reference VSCCExecutionFailureError: an infra
                    # fault must halt the block, never mark the tx
                    raise ValidationError(
                        f"validation plugin {definition.plugin!r} failed "
                        f"on tx {i} ns {ns}: {exc}"
                    ) from exc
        return remaining, plugin_results

    def _evaluate_policies(
        self,
        groups: PolicyGroups,
        parsed: Sequence[ParsedTx],
        flags: ValidationFlags,
        plugin_results: Optional[Dict[int, Dict[str, bool]]] = None,
    ) -> None:
        """Endorsement-policy evaluation. The common case — no key-level
        validation parameters anywhere in sight — takes the batched
        device path; blocks touching state-based endorsement fall back
        to the exact sequential key-level pass (reference
        validator_keylevel.go semantics)."""
        # SBE gate: the cheap per-tx md-write flag first (no rwset
        # materialization on the native path), then the metadata probe
        # over written keys; both false -> the batched path is exact
        if any(tx.has_md_writes for tx in parsed) or (
            self._any_vp_on_written_keys(groups, parsed)
        ):
            deps = BlockDependencies([tx.rwset for tx in parsed])
            self._evaluate_policies_sbe(
                groups, parsed, flags, deps, plugin_results or {}
            )
        else:
            self._evaluate_policies_batched(groups, parsed, flags)

    def _any_vp_on_written_keys(
        self,
        groups: PolicyGroups,
        parsed: Sequence[ParsedTx],
    ) -> bool:
        wk_iter = getattr(parsed, "iter_written_keys", None)
        if wk_iter is not None:
            # columnar written-keys table from the native parse; it also
            # covers txs invalidated before dispatch (bad creator sig,
            # dup txid, ...) whose metadata probes would both cost state
            # reads and let invalid txs force the sequential SBE path —
            # restrict to tx indices actually dispatched, matching the
            # fallback scan below
            dispatched = {
                i for _d, entries in groups.values() for i, _ns in entries
            }
            for i, ns, coll, key in wk_iter():
                if i in dispatched and self._has_vp(ns, coll, key):
                    return True
            return False
        seen = set()
        for _definition, entries in groups.values():
            for i, _ns in entries:
                if i in seen:
                    continue
                seen.add(i)
                rwset = parsed[i].rwset
                if rwset is None:
                    continue
                for ns_rw in rwset.ns_rw_sets:
                    ns = ns_rw.namespace
                    for w in ns_rw.writes:
                        if self._has_vp(ns, "", w.key):
                            return True
                    for coll in ns_rw.coll_hashed:
                        for hw in coll.hashed_writes:
                            if self._has_vp(ns, coll.collection_name, hw.key_hash):
                                return True
        return False

    def _has_vp(self, ns: str, coll: str, key) -> bool:
        md = deserialize_metadata(self.get_state_metadata(ns, coll, key))
        return bool(md) and VALIDATION_PARAMETER in md

    def _evaluate_policies_sbe(
        self,
        groups: PolicyGroups,
        parsed: Sequence[ParsedTx],
        flags: ValidationFlags,
        deps: BlockDependencies,
        plugin_results: Dict[int, Dict[str, bool]],
    ) -> None:
        """Sequential key-level pass in tx order. Signature verification
        already happened in the batched device phase; per-policy checks
        reduce to cached circuit walks over satisfaction bits."""
        pairs_by_tx: Dict[int, List[Tuple[str, ChaincodeDefinition]]] = {}
        for definition, entries in groups.values():
            for i, ns in entries:
                pairs_by_tx.setdefault(i, []).append((ns, definition))

        for tx in parsed:
            i = tx.index
            rwset = tx.rwset
            namespaces = (
                [ns.namespace for ns in rwset.ns_rw_sets] if rwset else []
            )
            pairs = pairs_by_tx.get(i)
            if pairs is None or rwset is None:
                # custom-plugin-validated tx: its per-namespace verdicts
                # were decided in _dispatch_custom_plugins — a VALID
                # tx's key-metadata writes must register as APPLIED so
                # later txs validate against the updated key policies
                plug = plugin_results.get(i)
                if plug is not None and rwset is not None:
                    still_valid = (
                        flags.flag(i) == TxValidationCode.NOT_VALIDATED
                    )
                    for ns in namespaces:
                        deps.set_result(
                            i, ns, still_valid and plug.get(ns, True)
                        )
                    continue
                # invalidated earlier / config tx: its metadata writes do
                # not update validation parameters
                for ns in namespaces:
                    deps.set_result(i, ns, False)
                continue
            # each written namespace validates against its OWN policy
            # (dispatcher.go:190); first failure invalidates the tx and
            # leaves the remaining namespaces unvalidated (= failed).
            # A tx spanning plugin-bound AND builtin namespaces carries
            # its plugin verdicts in (they count toward `failed` too —
            # the plugin may already have set the failure flag).
            plug = plugin_results.get(i) or {}
            validated: Dict[str, bool] = dict(plug)
            failed = not all(plug.values()) if plug else False
            for ns, definition in pairs:
                if failed:
                    validated[ns] = False
                    continue
                evaluator = KeyLevelEvaluator(
                    definition.endorsement_policy,
                    deps,
                    self.get_state_metadata,
                    lambda env, _tx_num, _tx=tx: self._eval_policy_host(_tx, env),
                    self.get_collection_ep,
                )
                ok, _why = evaluator.evaluate(rwset, ns, i)
                validated[ns] = ok
                if not ok:
                    failed = True
            if failed:
                flags.set_flag(i, TxValidationCode.ENDORSEMENT_POLICY_FAILURE)
            for ns in set(namespaces) | {tx.namespace}:
                deps.set_result(i, ns, validated.get(ns, False) and not failed)

    def _eval_policy_host(
        self, tx: ParsedTx, env: SignaturePolicyEnvelope
    ) -> bool:
        sat = self._signer_sat_rows(tx, env)
        return evaluate_host(env, sat)

    def _signer_sat_rows(
        self, tx: ParsedTx, env: SignaturePolicyEnvelope
    ) -> np.ndarray:
        """(valid deduped signers x principals) satisfaction matrix for
        one tx (SignatureSetToValidIdentities + principal matching)."""
        pairs = self._principal_pairs(env)
        rows = []
        seen_ids = set()
        for job in tx.endorsement_jobs:
            ident = self._job_identity.get(id(job))
            if ident is None:
                continue
            fp = (ident.msp_id, ident.fingerprint())
            if fp in seen_ids:
                continue
            seen_ids.add(fp)
            if not self._sig_ok(job):
                continue
            rows.append(
                [self._satisfies(ident, pr, b) for pr, b in pairs]
            )
        return np.array(rows, dtype=bool).reshape(len(rows), len(pairs))

    def _pattern_key(self, tx: ParsedTx) -> tuple:
        """The tx's signer pattern: (Identity, sig_ok) per endorsement
        job with a resolvable identity, in job order. Two txs with equal
        patterns produce identical satisfaction rows for any policy, so
        the circuit verdict is memoizable per (policy, pattern). Keys
        hold the Identity objects themselves (strong refs) — id() reuse
        after GC can never alias entries."""
        parts = []
        for job in tx.endorsement_jobs:
            ident = self._job_identity.get(id(job))
            if ident is None:
                continue
            parts.append((ident, self._sig_ok(job)))
        return tuple(parts)

    def _evaluate_policies_batched(
        self,
        groups: PolicyGroups,
        parsed: Sequence[ParsedTx],
        flags: ValidationFlags,
    ) -> None:
        """Batched endorsement-policy evaluation per chaincode definition.
        A tx appears once per written namespace (each namespace's policy
        must pass, dispatcher.go:190). Typical blocks contain few
        distinct signer patterns (the same orgs endorse every tx), so
        the circuit runs once per unique (policy, pattern) and the
        verdict fans out."""
        if len(self._pattern_memo) > 64:
            self._pattern_memo.clear()
        for definition, entries in groups.values():
            env = definition.endorsement_policy
            tx_indices = [i for i, _ns in entries]
            memo = self._pattern_memo.setdefault(env, {})
            if len(memo) > 4096:
                memo.clear()
            fresh: Dict[tuple, List[int]] = {}
            for i in tx_indices:
                key = self._pattern_key(parsed[i])
                verdict = memo.get(key)
                if verdict is None:
                    fresh.setdefault(key, []).append(i)
                elif verdict is False:
                    flags.set_flag(i, TxValidationCode.ENDORSEMENT_POLICY_FAILURE)
            if not fresh:
                continue
            # evaluate one representative per unique pattern
            reps = [txs[0] for txs in fresh.values()]
            # SignatureSetToValidIdentities: dedupe by identity, drop
            # non-verifying signers, preserve order (policy.go:365-402)
            per_rep_sat: List[np.ndarray] = [
                self._signer_sat_rows(parsed[i], env) for i in reps
            ]
            max_signers = max((s.shape[0] for s in per_rep_sat), default=0)
            if max_signers == 0:
                ok = np.zeros(len(reps), dtype=bool)
            else:
                batch = np.zeros(
                    (len(reps), max_signers, len(env.identities)), dtype=bool
                )
                for j, sat in enumerate(per_rep_sat):
                    batch[j, : sat.shape[0]] = sat
                fn = self._policy_fn(env)
                ok = np.asarray(fn(batch))
                # a rep with zero valid signers can never satisfy the
                # policy regardless of the circuit's padding behavior
                for j, sat in enumerate(per_rep_sat):
                    if sat.shape[0] == 0:
                        ok[j] = False
            for j, (key, txs) in enumerate(fresh.items()):
                memo[key] = bool(ok[j])
                if not ok[j]:
                    for i in txs:
                        flags.set_flag(
                            i, TxValidationCode.ENDORSEMENT_POLICY_FAILURE
                        )

    def _sig_ok(self, job: SigJob) -> bool:
        return self._sig_results.get(id(job), False)

    def _policy_fn(self, env: SignaturePolicyEnvelope):
        fn = self._policy_fn_cache.get(env)
        if fn is None:
            # host NumPy epilogue: the circuit is tiny and the signature
            # work already ran on the device — eager jnp here would pay a
            # device roundtrip per mask update (policy/evaluator.py)
            fn = compile_batched_numpy(env)
            self._policy_fn_cache[env] = fn
        return fn

    def _principal_pairs(
        self, env: SignaturePolicyEnvelope
    ) -> List[Tuple[msp_principal_pb2.MSPPrincipal, bytes]]:
        """[(principal, serialized)] — the bytes key the satisfaction
        cache, and serializing once per policy instead of once per
        (signer, principal) probe keeps the hot loop allocation-free."""
        ps = self._principals_cache.get(env)
        if ps is None:
            ps = [
                (pr, pr.SerializeToString())
                for pr in (principal_for(p) for p in env.identities)
            ]
            self._principals_cache[env] = ps
        return ps
