"""Block-level structural parse: one native C++ pass over every
envelope (native/blockparse.cc), falling back to the per-tx Python
parser when the shared object is unavailable.

Reference hot spots this replaces on the host path (SURVEY §3.1):
core/common/validation/msgvalidation.go:248-330 (ValidateTransaction)
and core/handlers/validation/builtin/v20/validation_logic.go:109-177
(extractValidationArtifacts) — the per-tx proto unwrap that dominated
the Python block pipeline (~55% of host ms/block measured round 4).

The native pass returns columnar arrays; this module materializes the
compatibility `ParsedTx` objects (with lazy rwsets — the native walk
already validated rwset structure) and keeps the columnar written-keys
table on the returned `ParsedBlock` for the state-based endorsement
gate, so the common no-SBE block never builds a Python rwset tree at
validation time at all.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from fabric_tpu.utils import native as _native
from fabric_tpu.ledger.txparse import (
    ParsedTx,
    SigJob,
    parse_transaction,
)
from fabric_tpu.common.txflags import TxValidationCode


class ParsedBlock(list):
    """List of ParsedTx, plus the columnar written-keys table from the
    native pass (consumed by BlockValidator._any_vp_on_written_keys
    without materializing rwsets)."""

    __slots__ = ("_buf", "_wk_tx", "_wk_ns", "_wk_hashed", "_wk_coll",
                 "_wk_key", "_ns_tx", "_ns_str", "native")

    def __init__(self, txs: Sequence[ParsedTx]):
        super().__init__(txs)
        self.native = False
        self._buf = b""
        self._wk_tx = self._wk_ns = self._wk_hashed = None
        self._wk_coll = self._wk_key = None
        self._ns_tx = self._ns_str = None

    def iter_written_keys(self) -> Iterator[Tuple[int, str, str, object]]:
        """(tx_index, namespace, collection, key) for every written key
        of every structurally-valid endorser tx. Public keys are str,
        collection-hashed keys are bytes (statebased KeyPolicyRequest)."""
        if not self.native:
            for tx in self:
                rwset = tx.rwset
                if rwset is None:
                    continue
                for ns_rw in rwset.ns_rw_sets:
                    for w in ns_rw.writes:
                        yield tx.index, ns_rw.namespace, "", w.key
                    for coll in ns_rw.coll_hashed:
                        for hw in coll.hashed_writes:
                            yield (
                                tx.index,
                                ns_rw.namespace,
                                coll.collection_name,
                                hw.key_hash,
                            )
            return
        buf = self._buf
        ns_names = {}
        for k in range(len(self._wk_tx)):
            ns_idx = int(self._wk_ns[k])
            name = ns_names.get(ns_idx)
            if name is None:
                o, l = self._ns_str[2 * ns_idx], self._ns_str[2 * ns_idx + 1]
                name = buf[o:o + l].decode("utf-8")
                ns_names[ns_idx] = name
            co, cl = self._wk_coll[2 * k], self._wk_coll[2 * k + 1]
            ko, kl = self._wk_key[2 * k], self._wk_key[2 * k + 1]
            key_bytes = buf[ko:ko + kl]
            if self._wk_hashed[k]:
                yield int(self._wk_tx[k]), name, buf[co:co + cl].decode(
                    "utf-8"
                ), key_bytes
            else:
                yield int(self._wk_tx[k]), name, "", key_bytes.decode("utf-8")


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def available() -> bool:
    lib = _native._load()
    return lib is not None and hasattr(lib, "fn_block_parse")


def parse_block(datas: Sequence[bytes]) -> ParsedBlock:
    """Parse every envelope of a block (reference: the per-goroutine
    validateTx fan-out in v20/validator.go:180-265, collapsed into one
    columnar host pass)."""
    lib = _native._load()
    if lib is None or not hasattr(lib, "fn_block_parse"):
        return ParsedBlock([parse_transaction(i, d) for i, d in enumerate(datas)])

    n = len(datas)
    if n == 0:
        return ParsedBlock([])
    buf = b"".join(datas)
    lens = np.array([len(d) for d in datas], dtype=np.uint64)
    offs = np.zeros(n, dtype=np.uint64)
    if n > 1:
        offs[1:] = np.cumsum(lens[:-1])
    blob = np.frombuffer(buf, dtype=np.uint8)
    if blob.size == 0:
        blob = np.zeros(1, dtype=np.uint8)

    h = lib.fn_block_parse(
        _native._u8(blob), _native._u64(offs), _native._u64(lens), n
    )
    try:
        counts = np.zeros(4, dtype=np.int64)
        lib.fn_block_counts(h, _i64(counts))
        n_jobs, n_uniq, n_ns, n_wk = (int(x) for x in counts)

        code = np.zeros(n, dtype=np.int32)
        header_type = np.zeros(n, dtype=np.int32)
        has_md = np.zeros(n, dtype=np.uint8)
        strs = np.zeros(n * 12, dtype=np.uint64)
        lib.fn_block_pertx(h, _i32(code), _i32(header_type),
                           _native._u8(has_md), _native._u64(strs))

        job_tx = np.zeros(max(n_jobs, 1), dtype=np.int64)
        job_ident = np.zeros(max(n_jobs, 1), dtype=np.int64)
        job_is_creator = np.zeros(max(n_jobs, 1), dtype=np.uint8)
        job_sig = np.zeros(max(n_jobs, 1) * 2, dtype=np.uint64)
        job_data = np.zeros(max(n_jobs, 1) * 2, dtype=np.uint64)
        job_digest = np.zeros(max(n_jobs, 1) * 32, dtype=np.uint8)
        if n_jobs:
            lib.fn_block_jobs(h, _i64(job_tx), _i64(job_ident),
                              _native._u8(job_is_creator),
                              _native._u64(job_sig), _native._u64(job_data),
                              _native._u8(job_digest))

        uniq = np.zeros(max(n_uniq, 1) * 2, dtype=np.uint64)
        if n_uniq:
            lib.fn_block_uniq(h, _native._u64(uniq))

        ns_tx = np.zeros(max(n_ns, 1), dtype=np.int64)
        ns_writes = np.zeros(max(n_ns, 1), dtype=np.uint8)
        ns_str = np.zeros(max(n_ns, 1) * 2, dtype=np.uint64)
        if n_ns:
            lib.fn_block_ns(h, _i64(ns_tx), _native._u8(ns_writes),
                            _native._u64(ns_str))

        wk_tx = np.zeros(max(n_wk, 1), dtype=np.int64)
        wk_ns = np.zeros(max(n_wk, 1), dtype=np.int64)
        wk_hashed = np.zeros(max(n_wk, 1), dtype=np.uint8)
        wk_coll = np.zeros(max(n_wk, 1) * 2, dtype=np.uint64)
        wk_key = np.zeros(max(n_wk, 1) * 2, dtype=np.uint64)
        if n_wk:
            lib.fn_block_wkeys(h, _i64(wk_tx), _i64(wk_ns),
                               _native._u8(wk_hashed), _native._u64(wk_coll),
                               _native._u64(wk_key))
    finally:
        lib.fn_block_free(h)

    # numpy scalar indexing in a tight Python loop costs ~10x a list
    # index; one tolist() per column keeps the 1k-tx materialization in
    # the single-digit-ms class (round-5 block_1k host-path cut)
    code_l = code.tolist()
    header_l = header_type.tolist()
    has_md_l = has_md.tolist()
    strs_l = strs.tolist()
    uniq_l = uniq.tolist()
    ns_tx_l, ns_writes_l, ns_str_l = (
        ns_tx.tolist(), ns_writes.tolist(), ns_str.tolist()
    )
    job_tx_l, job_ident_l = job_tx.tolist(), job_ident.tolist()
    job_is_creator_l, job_sig_l = job_is_creator.tolist(), job_sig.tolist()

    # unique serialized identities: ONE bytes object per distinct
    # identity — downstream caches key on the object, so every job of
    # the same signer shares one dict entry and one hash computation
    uniq_bytes: List[bytes] = []
    for u in range(n_uniq):
        o, l = uniq_l[2 * u], uniq_l[2 * u + 1]
        uniq_bytes.append(buf[o:o + l])

    digest_blob = job_digest.tobytes()

    ENDORSER = 3
    CONFIG = 1
    NOT_VALIDATED = TxValidationCode.NOT_VALIDATED
    txs: List[ParsedTx] = []
    for i in range(n):
        tx = ParsedTx(i)
        c = code_l[i]
        tx.code = NOT_VALIDATED if c == 254 else TxValidationCode(c)
        ht = header_l[i]
        tx.header_type = ht
        if ht >= 0:
            base = i * 12
            o, l = strs_l[base], strs_l[base + 1]
            tx.channel_id = buf[o:o + l].decode("utf-8")
            o, l = strs_l[base + 2], strs_l[base + 3]
            tx.tx_id = buf[o:o + l].decode("utf-8")
            o, l = strs_l[base + 4], strs_l[base + 5]
            tx.creator = buf[o:o + l]
            if ht == CONFIG:
                o, l = strs_l[base + 6], strs_l[base + 7]
                tx.config_data = buf[o:o + l]
            elif ht == ENDORSER and c == 254:
                o, l = strs_l[base + 8], strs_l[base + 9]
                tx.namespace = buf[o:o + l].decode("utf-8")
                o, l = strs_l[base + 10], strs_l[base + 11]
                tx._rwset_raw = buf[o:o + l]
                tx._has_md_writes = bool(has_md_l[i])
                tx._ns_entries = []
        txs.append(tx)

    # namespace entries per tx (rwset order preserved)
    for e in range(n_ns):
        i = ns_tx_l[e]
        o, l = ns_str_l[2 * e], ns_str_l[2 * e + 1]
        txs[i]._ns_entries.append(
            (buf[o:o + l].decode("utf-8"), bool(ns_writes_l[e]))
        )

    # signature jobs
    for k in range(n_jobs):
        i = job_tx_l[k]
        so, sl = job_sig_l[2 * k], job_sig_l[2 * k + 1]
        job = SigJob(
            uniq_bytes[job_ident_l[k]],
            buf[so:so + sl],
            b"",
            digest_blob[32 * k:32 * k + 32],
        )
        if job_is_creator_l[k]:
            txs[i].creator_sig_job = job
        else:
            txs[i].endorsement_jobs.append(job)

    out = ParsedBlock(txs)
    out.native = True
    out._buf = buf
    out._wk_tx, out._wk_ns, out._wk_hashed = wk_tx[:n_wk], wk_ns, wk_hashed
    out._wk_coll, out._wk_key = wk_coll, wk_key
    out._ns_tx, out._ns_str = ns_tx, ns_str
    return out
