"""Pluggable validation SPI (reference core/handlers/validation/api/
validation.go Plugin + plugin_validator.go dispatch semantics).

A validation plugin decides, per (transaction, written namespace),
whether the endorsement is acceptable. The reference loads Go shared
objects (core/handlers/library/registry.go:134 plugin.Open) and calls
`Validate(block, namespace, position, 0, policyBytes...)`; the TPU-
native form loads Python modules by "module.path:Attribute" reference
(dispatcher.PluginRegistry.load) and calls
`validate(ValidationContext)`.

Outcome mapping (plugin_validator.go:100-118):
- return normally            -> the namespace validates
- raise EndorsementInvalid   -> tx marked ENDORSEMENT_POLICY_FAILURE
  (the reference's *commonerrors.VSCCEndorsementPolicyError)
- raise anything else        -> ValidationError halts the whole block
  (the reference's VSCCExecutionFailureError: retriable infra fault,
  never silently invalidates a tx)

Unlike the reference — where each plugin re-verifies endorsement
signatures itself — signature verification has ALREADY run in the
batched device phase by the time a plugin is consulted; the context
exposes the per-endorser verdicts (`signers`) plus a `default_check()`
escape hatch running the builtin policy circuit, so a plugin composes
with the TPU batch instead of paying per-tx host crypto.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple


class EndorsementInvalid(Exception):
    """The tx's endorsement does not satisfy the plugin's rules."""


class PluginExecutionError(Exception):
    """Infrastructure failure inside a plugin — halts block processing."""


@dataclass
class SignerInfo:
    """One endorsement signature, post device batch."""

    msp_id: str
    identity_bytes: bytes
    sig_valid: bool


@dataclass
class ValidationContext:
    """Everything a validation plugin may consult for one (tx, ns)."""

    channel_id: str
    block_num: int
    tx_index: int
    namespace: str
    tx_id: str
    envelope_bytes: bytes
    # the namespace's endorsement policy (policy.ast envelope), as the
    # reference passes serialized policy bytes to plugin.Validate
    policy: object
    # post-device-batch endorsement verdicts for this tx
    signers: List[SignerInfo]
    # runs the builtin policy circuit for this tx against `policy`
    # (plugins that only ADD rules on top of the default check call this
    # first, like the reference builtin wrapped by custom plugins)
    default_check: Callable[[], bool]
    # committed state metadata probe: (ns, coll, key) -> bytes | None
    get_state_metadata: Callable[[str, str, object], Optional[bytes]] = (
        lambda ns, coll, key: None
    )
    # (namespace, writes?) pairs of the tx's rwset, rwset order
    ns_entries: Tuple = ()


class ValidationPlugin:
    """Base class for custom validation plugins. Subclasses override
    `validate`; `init` receives nothing today but reserves the
    reference's dependency-injection slot (validation.go Init)."""

    def init(self, **deps) -> None:  # noqa: D401 - SPI hook
        pass

    def validate(self, ctx: ValidationContext) -> None:
        raise NotImplementedError
