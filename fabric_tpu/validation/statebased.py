"""State-based (key-level) endorsement validation.

Reference: core/common/validation/statebased/{validator_keylevel.go,
vpmanagerimpl.go, v20.go}. Semantics reproduced:

- each key a tx writes (public value/metadata writes and per-collection
  hashed value/metadata writes) is checked against the key's
  VALIDATION_PARAMETER metadata if set, else the chaincode (or
  collection) endorsement policy;
- if an earlier tx in the same block wrote metadata for that key and
  that tx validated successfully, the later tx is invalidated
  (ValidationParameterUpdatedError -> policy error), because its
  endorsements predate the new policy;
- the chaincode EP is evaluated at most once per (tx, namespace) and is
  always evaluated if nothing else was checked (FAB-9473,
  v20.go CheckCCEPIfNoEPChecked).

The reference runs txs concurrently and synchronizes with per-key waits
(vpmanagerimpl.go:293-308). Here validation is phased: signatures are
batch-verified on the device first (SURVEY.md §2.13 P1/P2), so the
key-level pass is a deterministic in-order host scan whose policy
evaluations hit the pre-computed (signer x principal) satisfaction bits
— same partial order, no locks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from fabric_tpu.ledger.mvcc import deserialize_metadata
from fabric_tpu.ledger.rwset import TxRwSet
from fabric_tpu.policy.ast import SignaturePolicyEnvelope
from fabric_tpu.policy.proto_convert import (
    PolicyConversionError,
    unmarshal_application_policy,
)

VALIDATION_PARAMETER = "VALIDATION_PARAMETER"


class ValidationParameterUpdatedError(Exception):
    """A preceding valid tx in this block updated the key's validation
    parameters — the tx's endorsements predate the new policy."""


class SBEExecutionError(Exception):
    """Unexpected (non-deterministic) failure: halts channel processing
    (reference VSCCExecutionFailureError)."""


@dataclass
class KeyPolicyRequest:
    """One key-level check: which policy must the tx's signature set
    satisfy for this written key."""

    ns: str
    coll: str
    key: object  # str for public keys, bytes for hashed keys


class BlockDependencies:
    """Per-block in-block validation-parameter dependency tracking
    (vpmanagerimpl.go validationContext, made deterministic)."""

    def __init__(self, rwsets: Sequence[Optional[TxRwSet]]):
        # (ns, coll, key) -> sorted tx indices that metadata-write it
        self._writers: Dict[Tuple[str, str, object], List[int]] = {}
        # tx -> {ns: validated_ok}
        self._results: Dict[int, Dict[str, bool]] = {}
        for tx_num, rwset in enumerate(rwsets):
            if rwset is None:
                continue
            for ns_rw in rwset.ns_rw_sets:
                for mw in ns_rw.metadata_writes:
                    self._writers.setdefault(
                        (ns_rw.namespace, "", mw.key), []
                    ).append(tx_num)
                for coll in ns_rw.coll_hashed:
                    for mw in coll.metadata_writes:
                        self._writers.setdefault(
                            (
                                ns_rw.namespace,
                                coll.collection_name,
                                mw.key_hash,
                            ),
                            [],
                        ).append(tx_num)

    def has_writers(self) -> bool:
        """True if any tx in the block writes key metadata — the trigger
        for the sequential SBE pass (otherwise the batched device path
        is exact)."""
        return bool(self._writers)

    def set_result(self, tx_num: int, ns: str, ok: bool) -> None:
        """SetTxValidationResult: record tx_num's verdict for ns."""
        self._results.setdefault(tx_num, {})[ns] = ok

    def updated_by_earlier_valid_tx(
        self, ns: str, coll: str, key, tx_num: int
    ) -> bool:
        """waitForValidationResults: does any tx with a lower index that
        metadata-writes this key have a successful validation result for
        this namespace? Requires txs to be processed in index order.

        A missing result means the writer tx was invalidated before its
        SBE stage ran; that is treated like a failed validation (no
        dependency conflict) — the same outcome as the reference when
        the writer reaches the plugin and fails, and it avoids the
        reference's unresolvable wait when the writer never reaches the
        plugin at all."""
        for writer in self._writers.get((ns, coll, key), ()):
            if writer >= tx_num:
                break
            if self._results.get(writer, {}).get(ns):
                return True
        return False


class KeyLevelEvaluator:
    """Per-tx/namespace evaluator (baseEvaluator + policyCheckerV20).

    evaluate_policy(policy_env, tx_index) -> bool is supplied by the
    caller and is expected to consult the batch-verified signature /
    principal-satisfaction data for that tx's endorsements.
    """

    def __init__(
        self,
        cc_ep: SignaturePolicyEnvelope,
        deps: BlockDependencies,
        get_metadata: Callable[[str, str, object], Optional[bytes]],
        evaluate_policy: Callable[[SignaturePolicyEnvelope, int], bool],
        get_collection_ep: Optional[
            Callable[[str, str], Optional[SignaturePolicyEnvelope]]
        ] = None,
    ):
        self.cc_ep = cc_ep
        self.deps = deps
        self.get_metadata = get_metadata
        self.evaluate_policy = evaluate_policy
        self.get_collection_ep = get_collection_ep or (lambda cc, coll: None)
        # per-tx evaluation state (policyCheckerV20)
        self._ns_ep_checked: Set[str] = set()
        self._some_ep_checked = False

    def _reset_tx_state(self) -> None:
        self._ns_ep_checked = set()
        self._some_ep_checked = False

    def evaluate(
        self, rwset: TxRwSet, ns: str, tx_num: int
    ) -> Tuple[bool, str]:
        """baseEvaluator.Evaluate for one (tx, namespace). Returns
        (ok, reason)."""
        self._reset_tx_state()
        for ns_rw in rwset.ns_rw_sets:
            if ns_rw.namespace != ns:
                continue
            for w in ns_rw.writes:
                ok, why = self._check_key(ns, "", w.key, tx_num)
                if not ok:
                    return False, why
            for mw in ns_rw.metadata_writes:
                ok, why = self._check_key(ns, "", mw.key, tx_num)
                if not ok:
                    return False, why
            for coll in ns_rw.coll_hashed:
                cname = coll.collection_name
                for hw in coll.hashed_writes:
                    ok, why = self._check_key(ns, cname, hw.key_hash, tx_num)
                    if not ok:
                        return False, why
                for mw in coll.metadata_writes:
                    ok, why = self._check_key(ns, cname, mw.key_hash, tx_num)
                    if not ok:
                        return False, why
        # FAB-9473: always check at least the chaincode EP
        if not self._some_ep_checked:
            if not self.evaluate_policy(self.cc_ep, tx_num):
                return False, f"chaincode EP failed for ns {ns!r}"
            self._ns_ep_checked.add("")
            self._some_ep_checked = True
        return True, ""

    def _check_key(
        self, ns: str, coll: str, key, tx_num: int
    ) -> Tuple[bool, str]:
        """checkSBAndCCEP for one written key."""
        if self.deps.updated_by_earlier_valid_tx(ns, coll, key, tx_num):
            return False, (
                f"validation parameters for key {key!r} "
                f"(coll {coll!r}, ns {ns!r}) updated in this block"
            )
        vp_bytes = self._validation_parameter(ns, coll, key)
        if vp_bytes:
            try:
                policy = unmarshal_application_policy(vp_bytes)
            except PolicyConversionError as e:
                raise SBEExecutionError(
                    f"could not translate policy for {ns}:{key!r}: {e}"
                ) from e
            if not self.evaluate_policy(policy, tx_num):
                return False, (
                    f"key-level policy for key {key!r} failed"
                )
            self._some_ep_checked = True
            return True, ""
        return self._check_ccep_if_not_checked(ns, coll, tx_num)

    def _validation_parameter(self, ns: str, coll: str, key) -> Optional[bytes]:
        md = deserialize_metadata(self.get_metadata(ns, coll, key))
        if not md:
            return None
        return md.get(VALIDATION_PARAMETER)

    def _check_ccep_if_not_checked(
        self, ns: str, coll: str, tx_num: int
    ) -> Tuple[bool, str]:
        if coll:
            if coll in self._ns_ep_checked:
                return True, ""
            coll_ep = self.get_collection_ep(ns, coll)
            if coll_ep is not None:
                if not self.evaluate_policy(coll_ep, tx_num):
                    return False, (
                        f"collection EP for {coll!r} failed"
                    )
                self._ns_ep_checked.add(coll)
                self._some_ep_checked = True
                return True, ""
            # fall through to the chaincode EP
        if "" in self._ns_ep_checked:
            return True, ""
        if not self.evaluate_policy(self.cc_ep, tx_num):
            return False, f"chaincode EP failed for ns {ns!r}"
        self._ns_ep_checked.add("")
        self._some_ep_checked = True
        return True, ""
