"""Compatibility shim: msgvalidation moved to ``fabric_tpu.ledger.txparse``.

The structural tx parser (ParsedTx/SigJob/parse_transaction/
parse_tx_rwset) is metadata deserialization consumed by both the ledger
(kvledger commit, history re-parse) and the validation pipeline; keeping
it under validation/ created the ledger<->validation import cycle the
fabdep layering gate forbids, so the implementation now lives next to
the rwset types it builds.  This shim aliases the real module, so
``fabric_tpu.validation.msgvalidation is fabric_tpu.ledger.txparse``
and every historical import keeps working.
"""

import sys as _sys

from fabric_tpu.ledger import txparse as _impl

_sys.modules[__name__] = _impl
