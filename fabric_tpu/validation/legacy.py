"""Legacy (pre-2.0) validation: the v12-era LSCC-backed policy source,
write-set guards and the capability router (reference
core/handlers/validation/builtin/v12/validation_logic.go,
core/committer/txvalidator/v14 + router.go:34-50).

Pre-V2_0 channels resolve a chaincode's endorsement policy from LSCC's
ChaincodeData record in state — not from the _lifecycle namespace — and
apply the v12 write-set rules: a normal transaction must not write to
the LSCC namespace or any system chaincode namespace, and an LSCC
deploy/upgrade must be shaped as one (validation_logic.go
validateDeployRWSetAndCollection / checkInstantiationPolicy lineage).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from fabric_tpu.policy.proto_convert import (
    PolicyConversionError,
    unmarshal_envelope,
)
from fabric_tpu.protos import peer_pb2

SYSTEM_NAMESPACES = ("lscc", "cscc", "qscc", "escc", "vscc", "_lifecycle")


class LSCCRegistry:
    """ChaincodeRegistry drop-in resolving definitions from LSCC state
    (v12 validation_logic.go getVSCCInfo path: ChaincodeData.policy)."""

    def __init__(self, state_get: Callable[[str, str], Optional[bytes]]):
        """state_get(ns, key) -> committed bytes; definitions live at
        ("lscc", <chaincode name>)."""
        from fabric_tpu.validation.validator import ChaincodeDefinition

        self._cd_cls = ChaincodeDefinition
        self._state_get = state_get

    def get(self, name: str):
        raw = self._state_get("lscc", name)
        if raw is None:
            return None
        data = peer_pb2.ChaincodeData()
        try:
            data.ParseFromString(raw)
        except Exception:  # noqa: BLE001 - malformed record = undefined
            return None
        try:
            policy = unmarshal_envelope(data.policy)
        except PolicyConversionError:
            return None
        return self._cd_cls(name, policy, plugin=data.vscc or "vscc")

    def names(self) -> List[str]:
        return []  # enumeration needs a range scan; unused by validation


def check_v12_writeset(rwset, invoked_namespace: str) -> Optional[str]:
    """The v12 write-set guards. Returns an error string (maps to
    ILLEGAL_WRITESET) or None.

    - writes to LSCC are only legal when the tx INVOKES lscc (deploy /
      upgrade), and then only to the deployed chaincode's own key
      (validation_logic.go:  "LSCC can only issue a single putState");
    - writes to any other system chaincode namespace are always illegal.
    """
    if rwset is None:
        return None
    for ns_rw in rwset.ns_rw_sets:
        ns = ns_rw.namespace
        if ns == "lscc":
            if invoked_namespace != "lscc":
                if ns_rw.writes or ns_rw.metadata_writes:
                    return (
                        "chaincode is not lscc but writes to the lscc "
                        "namespace"
                    )
            else:
                if len(ns_rw.writes) > 1:
                    return "lscc deploy must write exactly one key"
                # the reference additionally pins the single key to the
                # deployed chaincode's name (validateDeployRWSetAndCollection);
                # the invoke args are not threaded here, so pin what we
                # can: the key must not shadow a system chaincode record
                for w in ns_rw.writes:
                    if w.key in SYSTEM_NAMESPACES:
                        return (
                            f"lscc deploy may not overwrite system "
                            f"chaincode {w.key}"
                        )
        elif ns in SYSTEM_NAMESPACES and ns != invoked_namespace:
            if ns_rw.writes or ns_rw.metadata_writes:
                return f"writes to system namespace {ns} are not allowed"
    return None


class ValidationRouter:
    """router.go:34-50: pick the v20 (_lifecycle) or legacy (LSCC)
    definition source by the channel's application capabilities."""

    def __init__(
        self,
        lifecycle_registry,
        lscc_registry: LSCCRegistry,
        capabilities: Callable[[], Sequence[str]],
    ):
        self._v20 = lifecycle_registry
        self._legacy = lscc_registry
        self._capabilities = capabilities

    @property
    def v20_active(self) -> bool:
        return "V2_0" in tuple(self._capabilities())

    def get(self, name: str):
        if self.v20_active:
            return self._v20.get(name)
        return self._legacy.get(name)

    def names(self) -> List[str]:
        return self._v20.names() if self.v20_active else self._legacy.names()
