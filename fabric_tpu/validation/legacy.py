"""Legacy (pre-2.0) validation: the v12/v13-era LSCC-backed policy
source, write-set guards, collection-config validation and the
capability router (reference
core/handlers/validation/builtin/v12/validation_logic.go,
core/handlers/validation/builtin/v13/validation_logic.go
validateRWSetAndCollection / validateNewCollectionConfigsAgainstCommitted,
core/committer/txvalidator/v14 + router.go:34-50).

Pre-V2_0 channels resolve a chaincode's endorsement policy from LSCC's
ChaincodeData record in state — not from the _lifecycle namespace — and
apply the v12 write-set rules: a normal transaction must not write to
the LSCC namespace or any system chaincode namespace, and an LSCC
deploy/upgrade must be shaped as one.  v13 adds private-collection
support at deploy time: the deploy may write a SECOND key,
"<chaincode>~collection", holding a CollectionConfigPackage that must
validate structurally, and an upgrade may only EXPAND the committed
package — existing collections cannot be dropped or modified
(v13 validation_logic.go:  validateNewCollectionConfigs +
validateNewCollectionConfigsAgainstCommitted).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from fabric_tpu.policy.proto_convert import (
    PolicyConversionError,
    unmarshal_envelope,
)
from fabric_tpu.protos import collection_pb2, msp_principal_pb2, peer_pb2

SYSTEM_NAMESPACES = ("lscc", "cscc", "qscc", "escc", "vscc", "_lifecycle")

# privdata.BuildCollectionKVSKey separator (core/common/privdata/store.go)
COLLECTION_SEPARATOR = "~"


def collection_key(chaincode: str) -> str:
    return chaincode + COLLECTION_SEPARATOR + "collection"


class LSCCRegistry:
    """ChaincodeRegistry drop-in resolving definitions from LSCC state
    (v12 validation_logic.go getVSCCInfo path: ChaincodeData.policy)."""

    def __init__(self, state_get: Callable[[str, str], Optional[bytes]]):
        """state_get(ns, key) -> committed bytes; definitions live at
        ("lscc", <chaincode name>)."""
        from fabric_tpu.validation.validator import ChaincodeDefinition

        self._cd_cls = ChaincodeDefinition
        self._state_get = state_get

    def get(self, name: str):
        raw = self._state_get("lscc", name)
        if raw is None:
            return None
        data = peer_pb2.ChaincodeData()
        try:
            data.ParseFromString(raw)
        except Exception:  # fablint: disable=broad-except  # malformed record = chaincode undefined (explicit None)
            return None
        try:
            policy = unmarshal_envelope(data.policy)
        except PolicyConversionError:
            return None
        return self._cd_cls(name, policy, plugin=data.vscc or "vscc")

    def names(self) -> List[str]:
        return []  # enumeration needs a range scan; unused by validation


def check_v12_writeset(rwset, invoked_namespace: str) -> Optional[str]:
    """The v12 write-set guards. Returns an error string (maps to
    ILLEGAL_WRITESET) or None.

    - writes to LSCC are only legal when the tx INVOKES lscc (deploy /
      upgrade), and then only to the deployed chaincode's own key
      (validation_logic.go:  "LSCC can only issue a single putState");
    - writes to any other system chaincode namespace are always illegal.
    """
    return _check_legacy_writeset(rwset, invoked_namespace, v13=False)


def check_v13_writeset(
    rwset,
    invoked_namespace: str,
    committed_collections_get: Optional[Callable[[str], Optional[bytes]]] = None,
) -> Optional[str]:
    """v13 guards: v12 rules plus collection support on deploy/upgrade
    (v13 validation_logic.go validateRWSetAndCollection).  The deploy may
    write "<cc>~collection" alongside the ChaincodeData key; the package
    must validate, and against `committed_collections_get(cc)` an upgrade
    may only expand (existing collections immutable)."""
    return _check_legacy_writeset(
        rwset,
        invoked_namespace,
        v13=True,
        committed_collections_get=committed_collections_get,
    )


def _check_legacy_writeset(
    rwset,
    invoked_namespace: str,
    v13: bool,
    committed_collections_get=None,
) -> Optional[str]:
    if rwset is None:
        return None
    for ns_rw in rwset.ns_rw_sets:
        ns = ns_rw.namespace
        if ns == "lscc":
            if invoked_namespace != "lscc":
                if ns_rw.writes or ns_rw.metadata_writes:
                    return (
                        "chaincode is not lscc but writes to the lscc "
                        "namespace"
                    )
                continue
            cc_writes = [
                w for w in ns_rw.writes
                if COLLECTION_SEPARATOR not in w.key
            ]
            coll_writes = [
                w for w in ns_rw.writes
                if COLLECTION_SEPARATOR in w.key
            ]
            if len(cc_writes) > 1:
                return "lscc deploy must write exactly one chaincode key"
            if coll_writes and not v13:
                return (
                    "collection configurations require the V1_2 "
                    "application capability (v13 validator)"
                )
            if len(coll_writes) > 1:
                return "lscc deploy may write at most one collection key"
            # the reference additionally pins the single key to the
            # deployed chaincode's name (validateDeployRWSetAndCollection);
            # the invoke args are not threaded here, so pin what we
            # can: the key must not shadow a system chaincode record
            for w in cc_writes:
                if w.key in SYSTEM_NAMESPACES:
                    return (
                        f"lscc deploy may not overwrite system "
                        f"chaincode {w.key}"
                    )
            if coll_writes:
                w = coll_writes[0]
                if not cc_writes:
                    return "collection write without a chaincode deploy"
                cc = cc_writes[0].key
                if w.key != collection_key(cc):
                    return (
                        f"collection key {w.key!r} must be "
                        f"{collection_key(cc)!r}"
                    )
                committed = (
                    committed_collections_get(cc)
                    if committed_collections_get is not None
                    else None
                )
                why = validate_collection_config_package(w.value, committed)
                if why is not None:
                    return why
        elif ns in SYSTEM_NAMESPACES and ns != invoked_namespace:
            if ns_rw.writes or ns_rw.metadata_writes:
                return f"writes to system namespace {ns} are not allowed"
    return None


_ALLOWED_PRINCIPAL_TYPES = (
    msp_principal_pb2.MSPPrincipal.ROLE,
    msp_principal_pb2.MSPPrincipal.ORGANIZATION_UNIT,
    msp_principal_pb2.MSPPrincipal.IDENTITY,
)


def validate_collection_config_package(
    raw: bytes, committed_raw: Optional[bytes] = None
) -> Optional[str]:
    """Structural validation of a CollectionConfigPackage, plus the
    expand-only rule against the committed package (v13
    validateNewCollectionConfigs +
    validateNewCollectionConfigsAgainstCommitted).  Returns an error
    string or None."""
    pkg = collection_pb2.CollectionConfigPackage()
    try:
        pkg.ParseFromString(raw)
    except Exception:  # fablint: disable=broad-except  # malformed proto = explicit error string (tx invalid)
        return "invalid collection configuration supplied"
    seen = set()
    for cfg in pkg.config:
        if cfg.WhichOneof("payload") != "static_collection_config":
            return "unknown collection configuration type"
        static = cfg.static_collection_config
        if not static.name:
            return "collection-name cannot be empty"
        if static.name in seen:
            return (
                f"collection-name: {static.name} -- found duplicate "
                f"collection configuration"
            )
        seen.add(static.name)
        if static.maximum_peer_count < static.required_peer_count:
            return (
                f"collection-name: {static.name} -- maximum peer count "
                f"({static.maximum_peer_count}) cannot be less than the "
                f"required peer count ({static.required_peer_count})"
            )
        if not static.member_orgs_policy.HasField("signature_policy"):
            return (
                f"collection-name: {static.name} -- collection member "
                f"policy is not set"
            )
        env = static.member_orgs_policy.signature_policy
        if not env.identities:
            return (
                f"collection-name: {static.name} -- collection member "
                f"policy has no identities"
            )
        for principal in env.identities:
            if principal.principal_classification not in _ALLOWED_PRINCIPAL_TYPES:
                return (
                    f"collection-name: {static.name} -- collection "
                    f"member policy contains an unsupported principal "
                    f"type {principal.principal_classification}"
                )
    if committed_raw:
        old = collection_pb2.CollectionConfigPackage()
        try:
            old.ParseFromString(committed_raw)
        except Exception:  # fablint: disable=broad-except  # corrupt committed record = explicit error string (tx invalid)
            return "committed collection configuration is unreadable"
        new_by_name = {
            c.static_collection_config.name: c.SerializeToString()
            for c in pkg.config
        }
        for c in old.config:
            name = c.static_collection_config.name
            if name not in new_by_name:
                return (
                    f"the following existing collections are missing in "
                    f"the new collection configuration package: [{name}]"
                )
            if new_by_name[name] != c.SerializeToString():
                return (
                    f"the collection configuration for collection "
                    f"{name!r} cannot be modified on upgrade"
                )
    return None


class ValidationRouter:
    """router.go:34-50: pick the v20 (_lifecycle) or legacy (LSCC)
    definition source by the channel's application capabilities."""

    def __init__(
        self,
        lifecycle_registry,
        lscc_registry: LSCCRegistry,
        capabilities: Callable[[], Sequence[str]],
    ):
        self._v20 = lifecycle_registry
        self._legacy = lscc_registry
        self._capabilities = capabilities

    @property
    def v20_active(self) -> bool:
        return "V2_0" in tuple(self._capabilities())

    def get(self, name: str):
        if self.v20_active:
            return self._v20.get(name)
        return self._legacy.get(name)

    def names(self) -> List[str]:
        return self._v20.names() if self.v20_active else self._legacy.names()
