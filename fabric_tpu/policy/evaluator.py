"""Signature-policy evaluation: host oracle + batched device form.

The reference compiles a SignaturePolicy AST into closures over
([]msp.Identity, used []bool) with *greedy, order-dependent* semantics
(common/cauthdsl/cauthdsl.go:24-92):

- SignedBy(i): walk signers in order; the first NOT-yet-used signer that
  satisfies identities[i] is marked used and the leaf succeeds.
- NOutOf(n, rules): evaluate EVERY child in order (no short-circuit), each
  against a scratch copy of `used`; a succeeding child commits its copy
  back. Succeed iff >= n children succeeded.

These exact semantics (one signer satisfies at most one leaf along a
successful branch; order matters) must be reproduced bit-for-bit for
TRANSACTIONS_FILTER parity.

The batched form exploits that the policy is static per (channel,
chaincode) while transactions are many: principal matching happens on the
host (producing a bool satisfaction tensor), and the greedy walk becomes a
fixed sequence of vectorized mask updates over lanes = transactions. The
per-lane commit `used = where(ok, used_child, used)` IS Go's
copy-on-success, vectorized.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fabric_tpu.policy.ast import NOutOf, SignaturePolicyEnvelope, SignedBy


def evaluate_host(env: SignaturePolicyEnvelope, sat: np.ndarray) -> bool:
    """Oracle evaluation for ONE transaction.

    sat: (num_signers, num_principals) bool — sat[s, p] true iff signer s
    satisfies identities[p] (and its signature verified; reference
    SignatureSetToValidIdentities drops non-verifying signers *before*
    evaluation, policies/policy.go:365-402).
    """
    num_signers = sat.shape[0]
    used = [False] * num_signers

    def walk(rule, used: List[bool]) -> bool:
        if isinstance(rule, SignedBy):
            for s in range(num_signers):
                if used[s]:
                    continue
                if sat[s, rule.index]:
                    used[s] = True
                    return True
            return False
        assert isinstance(rule, NOutOf)
        verified = 0
        for child in rule.rules:
            scratch = list(used)
            if walk(child, scratch):
                verified += 1
                used[:] = scratch
        return verified >= rule.n

    return walk(env.rule, used)


def compile_batched(
    env: SignaturePolicyEnvelope, num_signers: int
) -> Callable[[jax.Array], jax.Array]:
    """Compile the policy into a jittable function over batched satisfaction
    tensors: sat (B, num_signers, num_principals) bool -> (B,) bool."""

    def walk(rule, sat, used):
        # used: (B, S) bool; returns (ok (B,), used' (B, S))
        if isinstance(rule, SignedBy):
            elig = sat[:, :, rule.index] & ~used  # (B, S)
            ok = jnp.any(elig, axis=1)
            first = jnp.argmax(elig, axis=1)  # first True (argmax on bool)
            claim = jax.nn.one_hot(first, used.shape[1], dtype=bool) & ok[:, None]
            return ok, used | claim
        assert isinstance(rule, NOutOf)
        verified = jnp.zeros(used.shape[0], dtype=jnp.int32)
        for child in rule.rules:
            ok, used_child = walk(child, sat, used)
            verified = verified + ok.astype(jnp.int32)
            used = jnp.where(ok[:, None], used_child, used)
        return verified >= rule.n, used

    def run(sat: jax.Array) -> jax.Array:
        used0 = jnp.zeros((sat.shape[0], num_signers), dtype=bool)
        ok, _ = walk(env.rule, sat, used0)
        return ok

    return run


def compile_batched_numpy(
    env: SignaturePolicyEnvelope,
) -> Callable[[np.ndarray], np.ndarray]:
    """The batched greedy walk in vectorized NumPy: sat (B, S, P) bool ->
    (B,) bool, bit-identical to `compile_batched` / `evaluate_host`.

    This is the validator's default epilogue: policy circuits are a few
    dozen mask updates over small bool tensors — microseconds on host,
    whereas eager jnp dispatch pays a device (tunnel) roundtrip per op.
    The jax form remains for fused multi-channel device steps where the
    satisfaction tensor already lives on the device."""

    def walk(rule, sat, used):
        if isinstance(rule, SignedBy):
            elig = sat[:, :, rule.index] & ~used  # (B, S)
            ok = elig.any(axis=1)
            first = elig.argmax(axis=1)  # first True (argmax on bool)
            claim = np.zeros_like(used)
            claim[np.arange(used.shape[0]), first] = ok
            return ok, used | claim
        assert isinstance(rule, NOutOf)
        verified = np.zeros(used.shape[0], dtype=np.int32)
        for child in rule.rules:
            ok, used_child = walk(child, sat, used)
            verified = verified + ok.astype(np.int32)
            used = np.where(ok[:, None], used_child, used)
        return verified >= rule.n, used

    def run(sat: np.ndarray) -> np.ndarray:
        sat = np.asarray(sat, dtype=bool)
        used0 = np.zeros(sat.shape[:2], dtype=bool)
        ok, _ = walk(env.rule, sat, used0)
        return ok

    return run


def build_satisfaction_tensor(
    env: SignaturePolicyEnvelope,
    signer_principals: Sequence[Sequence[bool]],
) -> np.ndarray:
    """Stack per-signer principal-satisfaction rows into the (S, P) oracle
    input / one lane of the batched input."""
    num_p = len(env.identities)
    out = np.zeros((len(signer_principals), num_p), dtype=bool)
    for s, row in enumerate(signer_principals):
        assert len(row) == num_p
        out[s] = row
    return out
