"""Signature policies (reference common/cauthdsl + common/policydsl)."""

from fabric_tpu.policy.ast import (
    MSPPrincipal,
    MSPRole,
    NOutOf,
    Role,
    SignaturePolicyEnvelope,
    SignedBy,
    from_dsl,
)
from fabric_tpu.policy.evaluator import compile_batched, evaluate_host

__all__ = [
    "MSPPrincipal",
    "MSPRole",
    "NOutOf",
    "Role",
    "SignaturePolicyEnvelope",
    "SignedBy",
    "from_dsl",
    "compile_batched",
    "evaluate_host",
]
