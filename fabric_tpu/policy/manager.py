"""Policy manager hierarchy (reference common/policies/policy.go +
implicitmeta.go + cauthdsl/policy.go).

The manager tree mirrors the channel config group tree: one Manager per
config group, holding that group's policies plus child managers. Paths are
resolved like the reference: "/Channel/Application/Writers" walks the
hierarchy from the root; a bare name resolves in the current manager.

Policy kinds:
- SignaturePolicy (cauthdsl): verify-then-evaluate over SignedData, with
  the pre-verification dedupe by identity bytes
  (SignatureSetToValidIdentities, policies/policy.go:365-402);
- ImplicitMetaPolicy: ANY/ALL/MAJORITY over the same-named sub-policy of
  every child manager (implicitmeta.go).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fabric_tpu.policy import proto_convert
from fabric_tpu.policy.ast import SignaturePolicyEnvelope
from fabric_tpu.policy.evaluator import evaluate_host
from fabric_tpu.protos import policies_pb2

# Reference common/policies/policy.go:27-47 — well-known policy names.
CHANNEL_PREFIX = "Channel"
APPLICATION_PREFIX = "Application"
ORDERER_PREFIX = "Orderer"
CHANNEL_READERS = "/Channel/Readers"
CHANNEL_WRITERS = "/Channel/Writers"
CHANNEL_APPLICATION_READERS = "/Channel/Application/Readers"
CHANNEL_APPLICATION_WRITERS = "/Channel/Application/Writers"
CHANNEL_APPLICATION_ADMINS = "/Channel/Application/Admins"
BLOCK_VALIDATION = "/Channel/Orderer/BlockValidation"


@dataclass(frozen=True)
class SignedData:
    """One (data, identity, signature) triple (reference protoutil
    signeddata.go SignedData)."""

    data: bytes
    identity: bytes
    signature: bytes


class PolicyError(Exception):
    pass


class Policy:
    """Reference policies.Policy interface."""

    def evaluate_signed_data(self, signature_set: Sequence[SignedData]) -> None:
        """Raise PolicyError unless the signature set satisfies the policy."""
        raise NotImplementedError


class SignaturePolicy(Policy):
    """cauthdsl policy: deserialize + dedupe + verify signers, then run the
    compiled greedy evaluation (reference common/cauthdsl/policy.go:87-95)."""

    def __init__(self, envelope: SignaturePolicyEnvelope, msp_manager, provider):
        self.envelope = envelope
        self._msp_manager = msp_manager
        self._provider = provider

    def evaluate_signed_data(self, signature_set: Sequence[SignedData]) -> None:
        from fabric_tpu.policy.proto_convert import principal_for

        # Dedupe by raw identity bytes BEFORE verifying (anti-DoS,
        # policies/policy.go:383-388).
        seen = set()
        deduped: List[SignedData] = []
        for sd in signature_set:
            if sd.identity in seen:
                continue
            seen.add(sd.identity)
            deduped.append(sd)

        valid: List = []
        for sd in deduped:
            try:
                identity, msp = self._msp_manager.deserialize_identity(sd.identity)
                identity.verify(sd.data, sd.signature)
            except Exception:  # fablint: disable=broad-except  # bad signature = lane dropped; PolicyError raised below if none valid
                continue
            valid.append((identity, msp))
        if not valid:
            raise PolicyError(
                "signature set did not satisfy policy: no valid signatures"
            )

        num_p = len(self.envelope.identities)
        sat = np.zeros((len(valid), num_p), dtype=bool)
        principals = [principal_for(p) for p in self.envelope.identities]
        for s, (identity, msp) in enumerate(valid):
            for p, principal in enumerate(principals):
                try:
                    msp.satisfies_principal(identity, principal)
                    sat[s, p] = True
                except Exception:  # fablint: disable=broad-except  # mismatch = sat stays False, the explicit mask write
                    pass
        if not evaluate_host(self.envelope, sat):
            raise PolicyError("signature set did not satisfy policy")


class ImplicitMetaPolicy(Policy):
    """ANY/ALL/MAJORITY of the same-named sub-policy across child managers
    (reference common/policies/implicitmeta.go)."""

    def __init__(self, rule: int, sub_policy: str, sub_policies: Sequence[Policy]):
        self.rule = rule
        self.sub_policy = sub_policy
        self._subs = list(sub_policies)
        n = len(self._subs)
        R = policies_pb2.ImplicitMetaPolicy
        if rule == R.ANY:
            self.threshold = 1  # an empty sub-policy set always denies
        elif rule == R.ALL:
            self.threshold = n
        elif rule == R.MAJORITY:
            self.threshold = n // 2 + 1
        else:
            raise PolicyError(f"unknown implicit meta rule {rule}")

    def evaluate_signed_data(self, signature_set: Sequence[SignedData]) -> None:
        remaining = self.threshold
        if remaining == 0:
            return
        failures = []
        for sub in self._subs:
            try:
                sub.evaluate_signed_data(signature_set)
            except Exception as e:  # fablint: disable=broad-except  # failure recorded; aggregated PolicyError raised after the loop
                failures.append(str(e))
                continue
            remaining -= 1
            if remaining == 0:
                return
        raise PolicyError(
            f"implicit policy evaluation failed - {self.threshold - remaining} "
            f"sub-policies were satisfied, but this policy requires "
            f"{self.threshold} of the '{self.sub_policy}' sub-policies to be "
            f"satisfied"
        )


class RejectPolicy(Policy):
    """Placeholder for undefined policies referenced by the tree (the
    reference returns an error from Manager.GetPolicy; callers treat a
    missing policy as always-deny)."""

    def __init__(self, name: str):
        self.name = name

    def evaluate_signed_data(self, signature_set: Sequence[SignedData]) -> None:
        raise PolicyError(f"no such policy: '{self.name}'")


class Manager:
    """One config-group's policies + children (reference ManagerImpl,
    common/policies/policy.go:152-236)."""

    def __init__(
        self,
        path: str,
        policies: Optional[Dict[str, Policy]] = None,
        children: Optional[Dict[str, "Manager"]] = None,
    ):
        self.path = path
        self._policies = dict(policies or {})
        self._children = dict(children or {})

    def manager(self, relpath: Sequence[str]) -> Optional["Manager"]:
        m: Optional[Manager] = self
        for seg in relpath:
            if m is None:
                return None
            m = m._children.get(seg)
        return m

    def get_policy(self, name: str) -> Tuple[Policy, bool]:
        """Returns (policy, found). Absolute paths ('/Channel/...') resolve
        from this manager as root, like the reference's root manager."""
        if name.startswith("/"):
            segs = [s for s in name.split("/") if s]
            # segs[0] names the root group itself (e.g. "Channel")
            if not segs:
                return RejectPolicy(name), False
            m: Optional[Manager] = self
            for seg in segs[1:-1]:
                m = m._children.get(seg) if m else None
            if m is None:
                return RejectPolicy(name), False
            return m.get_policy(segs[-1])
        p = self._policies.get(name)
        if p is None:
            return RejectPolicy(name), False
        return p, True

    @property
    def policy_names(self) -> List[str]:
        return sorted(self._policies)

    @property
    def children(self) -> Dict[str, "Manager"]:
        return dict(self._children)


def build_manager(
    path: str,
    group,
    msp_manager,
    provider,
) -> Manager:
    """Recursively build the manager tree from a ConfigGroup
    (reference NewManagerImpl walking ConfigGroup.Policies/Groups)."""
    children = {
        name: build_manager(f"{path}/{name}", sub, msp_manager, provider)
        for name, sub in group.groups.items()
    }
    policies: Dict[str, Policy] = {}
    P = policies_pb2.Policy
    for name, cfg_policy in group.policies.items():
        pol = cfg_policy.policy
        if pol.type == P.SIGNATURE:
            env = proto_convert.unmarshal_envelope(pol.value)
            policies[name] = SignaturePolicy(env, msp_manager, provider)
        elif pol.type == P.IMPLICIT_META:
            meta = policies_pb2.ImplicitMetaPolicy()
            meta.ParseFromString(pol.value)
            # Every child counts toward the denominator; a child lacking
            # the sub-policy contributes an always-deny RejectPolicy
            # (implicitmeta.go counts all children, so MAJORITY/ALL must
            # not shrink when a child omits the policy).
            subs = [
                child.get_policy(meta.sub_policy)[0]
                for child in children.values()
            ]
            policies[name] = ImplicitMetaPolicy(meta.rule, meta.sub_policy, subs)
        else:
            policies[name] = RejectPolicy(f"{name} (unsupported type {pol.type})")
    return Manager(path, policies, children)
