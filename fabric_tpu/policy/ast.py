"""Signature-policy datamodel + the policy string DSL.

Mirrors the proto shapes the reference evaluates (fabric-protos
common/policies.proto: SignaturePolicyEnvelope{version, rule, identities},
SignaturePolicy = SignedBy(int32) | NOutOf{n, rules}) and the human DSL of
common/policydsl ("AND('Org1.member','Org2.member')", "OutOf(2, ...)").
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from typing import List, Tuple, Union


class Role(enum.Enum):
    MEMBER = "member"
    ADMIN = "admin"
    CLIENT = "client"
    PEER = "peer"
    ORDERER = "orderer"


@dataclass(frozen=True)
class MSPRole:
    """PRINCIPAL_ROLE principal: (msp_id, role)."""

    msp_id: str
    role: Role


# Future classifications (OU, identity-equality) slot in here.
MSPPrincipal = MSPRole


@dataclass(frozen=True)
class SignedBy:
    """Leaf: satisfied by one not-yet-used signer matching identities[index]."""

    index: int


@dataclass(frozen=True)
class NOutOf:
    n: int
    rules: Tuple["SignaturePolicy", ...]

    def __init__(self, n: int, rules):
        object.__setattr__(self, "n", n)
        object.__setattr__(self, "rules", tuple(rules))


SignaturePolicy = Union[SignedBy, NOutOf]


@dataclass(frozen=True)
class SignaturePolicyEnvelope:
    rule: SignaturePolicy
    identities: Tuple[MSPPrincipal, ...]
    version: int = 0

    def __init__(self, rule, identities, version=0):
        object.__setattr__(self, "rule", rule)
        object.__setattr__(self, "identities", tuple(identities))
        object.__setattr__(self, "version", version)

    def __hash__(self):
        # envelopes key every validator cache (policy fn, principals,
        # pattern memo, policy groups) and the recursive dataclass hash
        # walks the whole rule tree — at 1k-tx blocks that recomputation
        # showed up as ~10% of the host path. Frozen => cache it.
        h = self.__dict__.get("_hash")
        if h is None:
            h = hash((self.rule, self.identities, self.version))
            object.__setattr__(self, "_hash", h)
        return h


# ---------------------------------------------------------------------------
# DSL: AND / OR / OutOf over 'Msp.role' terms (reference common/policydsl)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"\s*(?:(?P<name>AND|OR|OutOf)|(?P<lparen>\()|(?P<rparen>\))|(?P<comma>,)"
    r"|(?P<num>\d+)|'(?P<term>[^']+)')"
)


class DslError(ValueError):
    pass


def _parse_term(term: str) -> MSPRole:
    if "." not in term:
        raise DslError(f"bad principal term {term!r}")
    msp_id, role_name = term.rsplit(".", 1)
    try:
        role = Role(role_name.lower())
    except ValueError as e:
        raise DslError(f"unknown role in {term!r}") from e
    return MSPRole(msp_id, role)


def from_dsl(text: str) -> SignaturePolicyEnvelope:
    """Parse e.g. "AND('Org1.member', OR('Org2.admin','Org3.member'))".

    Each distinct principal term gets one identities[] slot, deduplicated
    like the reference DSL compiler does.
    """
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                break
            raise DslError(f"syntax error at {text[pos:pos + 20]!r}")
        pos = m.end()
        for kind in ("name", "lparen", "rparen", "comma", "num", "term"):
            if m.group(kind) is not None:
                tokens.append((kind, m.group(kind)))
                break

    identities: List[MSPRole] = []
    index_of = {}

    def principal_index(term: str) -> int:
        pr = _parse_term(term)
        if pr not in index_of:
            index_of[pr] = len(identities)
            identities.append(pr)
        return index_of[pr]

    def parse(i: int) -> Tuple[SignaturePolicy, int]:
        kind, val = tokens[i]
        if kind == "term":
            return SignedBy(principal_index(val)), i + 1
        if kind != "name":
            raise DslError(f"expected operator or term, got {val!r}")
        op = val
        i += 1
        if tokens[i][0] != "lparen":
            raise DslError(f"expected ( after {op}")
        i += 1
        n_required = None
        if op == "OutOf":
            if tokens[i][0] != "num":
                raise DslError("OutOf requires a leading count")
            n_required = int(tokens[i][1])
            i += 1
            if tokens[i][0] == "comma":
                i += 1
        rules = []
        while True:
            rule, i = parse(i)
            rules.append(rule)
            kind = tokens[i][0]
            i += 1
            if kind == "rparen":
                break
            if kind != "comma":
                raise DslError("expected , or )")
        if op == "AND":
            n_required = len(rules)
        elif op == "OR":
            n_required = 1
        assert n_required is not None
        return NOutOf(n_required, rules), i

    if not tokens:
        raise DslError("empty policy expression")
    try:
        rule, i = parse(0)
    except IndexError as e:
        raise DslError("truncated policy expression") from e
    if i != len(tokens):
        raise DslError("trailing tokens")
    return SignaturePolicyEnvelope(rule, identities)
