"""ast <-> proto conversion for signature policies.

The validation plane receives policies as serialized proto
SignaturePolicyEnvelope (chaincode definitions, key-level VALIDATION_
PARAMETER metadata wrapped in ApplicationPolicy — the v20 dispatcher's
toApplicationPolicyTranslator, reference core/handlers/validation/
builtin/v20/validation_logic.go:44-67) and evaluates the compiled ast
form (fabric_tpu.policy.ast).
"""

from __future__ import annotations

from fabric_tpu.policy.ast import (
    MSPRole,
    NOutOf,
    Role,
    SignaturePolicyEnvelope,
    SignedBy,
)
from fabric_tpu.protos import msp_principal_pb2, policies_pb2

_ROLE_TO_PROTO = {
    Role.MEMBER: msp_principal_pb2.MSPRole.MEMBER,
    Role.ADMIN: msp_principal_pb2.MSPRole.ADMIN,
    Role.CLIENT: msp_principal_pb2.MSPRole.CLIENT,
    Role.PEER: msp_principal_pb2.MSPRole.PEER,
    Role.ORDERER: msp_principal_pb2.MSPRole.ORDERER,
}
_ROLE_FROM_PROTO = {v: k for k, v in _ROLE_TO_PROTO.items()}


class PolicyConversionError(ValueError):
    pass


def principal_for(ast_principal) -> msp_principal_pb2.MSPPrincipal:
    """fabric_tpu.policy.ast principal -> proto MSPPrincipal.

    Lives here (historically validation.validator, which still re-exports
    it) so the policy manager and ledger collections never import the
    validation layer — that edge was the policy<->validation cycle."""
    if not isinstance(ast_principal, MSPRole):
        raise TypeError(
            f"unsupported policy principal {type(ast_principal).__name__!r}"
        )
    role = msp_principal_pb2.MSPRole()
    role.msp_identifier = ast_principal.msp_id
    role.role = _ROLE_TO_PROTO[ast_principal.role]
    out = msp_principal_pb2.MSPPrincipal()
    out.principal_classification = msp_principal_pb2.MSPPrincipal.ROLE
    out.principal = role.SerializeToString()
    return out


def envelope_to_proto(env: SignaturePolicyEnvelope) -> policies_pb2.SignaturePolicyEnvelope:
    out = policies_pb2.SignaturePolicyEnvelope()
    out.version = env.version
    out.rule.CopyFrom(_rule_to_proto(env.rule))
    for pr in env.identities:
        p = out.identities.add()
        p.principal_classification = msp_principal_pb2.MSPPrincipal.ROLE
        role = msp_principal_pb2.MSPRole()
        role.msp_identifier = pr.msp_id
        role.role = _ROLE_TO_PROTO[pr.role]
        p.principal = role.SerializeToString()
    return out


def _rule_to_proto(rule) -> policies_pb2.SignaturePolicy:
    out = policies_pb2.SignaturePolicy()
    if isinstance(rule, SignedBy):
        out.signed_by = rule.index
    else:
        out.n_out_of.n = rule.n
        for sub in rule.rules:
            out.n_out_of.rules.append(_rule_to_proto(sub))
    return out


def envelope_from_proto(
    msg: policies_pb2.SignaturePolicyEnvelope,
) -> SignaturePolicyEnvelope:
    identities = []
    for p in msg.identities:
        if p.principal_classification != msp_principal_pb2.MSPPrincipal.ROLE:
            raise PolicyConversionError(
                f"unsupported principal classification "
                f"{p.principal_classification}"
            )
        role = msp_principal_pb2.MSPRole()
        role.ParseFromString(p.principal)
        identities.append(
            MSPRole(role.msp_identifier, _ROLE_FROM_PROTO[role.role])
        )
    return SignaturePolicyEnvelope(_rule_from_proto(msg.rule), identities, msg.version)


def _rule_from_proto(msg: policies_pb2.SignaturePolicy):
    kind = msg.WhichOneof("Type")
    if kind == "signed_by":
        return SignedBy(msg.signed_by)
    if kind == "n_out_of":
        return NOutOf(
            msg.n_out_of.n,
            [_rule_from_proto(r) for r in msg.n_out_of.rules],
        )
    raise PolicyConversionError("empty signature policy rule")


def marshal_envelope(env: SignaturePolicyEnvelope) -> bytes:
    return envelope_to_proto(env).SerializeToString()


def unmarshal_envelope(raw: bytes) -> SignaturePolicyEnvelope:
    msg = policies_pb2.SignaturePolicyEnvelope()
    msg.ParseFromString(raw)
    return envelope_from_proto(msg)


def marshal_application_policy(env: SignaturePolicyEnvelope) -> bytes:
    """Wrap as peer.ApplicationPolicy{signature_policy} — the on-ledger
    form of chaincode EPs and key-level validation parameters."""
    ap = policies_pb2.ApplicationPolicy()
    ap.signature_policy.CopyFrom(envelope_to_proto(env))
    return ap.SerializeToString()


def unmarshal_application_policy(raw: bytes) -> SignaturePolicyEnvelope:
    ap = policies_pb2.ApplicationPolicy()
    ap.ParseFromString(raw)
    kind = ap.WhichOneof("Type")
    if kind != "signature_policy":
        raise PolicyConversionError(
            f"unsupported application policy type {kind!r}"
        )
    return envelope_from_proto(ap.signature_policy)
