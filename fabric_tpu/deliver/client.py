"""Block-deliver client (reference core/deliverservice +
usable-inter-nal/pkg/peer/blocksprovider/blocksprovider.go).

Pulls blocks from an ordering endpoint with the reference's failure
discipline: exponential backoff with base 1.2 capped per-sleep and by a
total-duration budget (blocksprovider.go:109-146), endpoint failover on
error, endpoint refresh when the channel config changes.

Transport-agnostic: an endpoint is any callable
`(seek_envelope) -> iterator of DeliverResponse` (the gRPC layer adapts
the AtomicBroadcast/Deliver streams to this shape).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Iterable, List, Optional, Sequence

from fabric_tpu.common.faults import InjectedFault, fault_point
from fabric_tpu.common.retry import DELIVER_POLICY, Backoff, RetryPolicy
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil

# the reference ramp now lives in retry.DELIVER_POLICY (blocksprovider
# .go:109 base 1.2); aliased here for back-compat with older callers
BACKOFF_BASE = DELIVER_POLICY.multiplier
MAX_RETRY_DELAY = 10.0
MAX_TOTAL_DELAY = 60.0 * 60


def seek_envelope(
    channel_id: str,
    start,
    signer=None,
    stop=2**64 - 1,
) -> common_pb2.Envelope:
    """SeekInfo [start, stop] envelope, signed when a signer is given.
    start/stop are block numbers or the strings "oldest"/"newest"
    (ab.SeekPosition oneof)."""
    seek = ab_pb2.SeekInfo()
    for pos, value in ((seek.start, start), (seek.stop, stop)):
        if value == "oldest":
            pos.oldest.SetInParent()
        elif value == "newest":
            pos.newest.SetInParent()
        else:
            pos.specified.number = value
    seek.behavior = ab_pb2.SeekInfo.BLOCK_UNTIL_READY
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(
        common_pb2.DELIVER_SEEK_INFO, channel_id
    )
    payload.header.channel_header = chdr.SerializeToString()
    if signer is not None:
        shdr = protoutil.make_signature_header(
            signer.serialize(), signer.new_nonce()
        )
        payload.header.signature_header = shdr.SerializeToString()
    else:
        payload.header.signature_header = (
            common_pb2.SignatureHeader().SerializeToString()
        )
    payload.data = seek.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    if signer is not None:
        env.signature = signer.sign(env.payload)
    return env


@dataclass
class DelivererStats:
    connect_attempts: int = 0
    blocks_received: int = 0
    failures: int = 0


class BlockDeliverer:
    """Per-channel block pull loop (reference Deliverer.DeliverBlocks)."""

    def __init__(
        self,
        channel_id: str,
        endpoints: Sequence[Callable],
        on_block: Callable[[common_pb2.Block], None],
        next_block: Callable[[], int],
        signer=None,
        verify_block: Optional[Callable[[common_pb2.Block], bool]] = None,
        sleeper: Callable[[float], None] = time.sleep,
        max_retry_delay: float = MAX_RETRY_DELAY,
        max_total_delay: float = MAX_TOTAL_DELAY,
        retry_policy: Optional[RetryPolicy] = None,
        retry_seed: Optional[int] = None,
    ):
        self.channel_id = channel_id
        self._endpoints = list(endpoints)
        self._on_block = on_block
        self._next_block = next_block
        self._signer = signer
        self._verify_block = verify_block
        self._sleeper = sleeper
        # the reference backoff (retry.DELIVER_POLICY: 1.2**n * 50ms,
        # capped per-sleep and by a total-duration budget) with the
        # legacy knobs overriding the caps; retry_policy overrides
        # wholesale.  retry_seed arms ±20% seeded jitter so a fleet of
        # deliverers retrying the same dead orderer desynchronizes —
        # only when the chosen policy doesn't already set its own.
        if retry_policy is None:
            retry_policy = replace(
                DELIVER_POLICY,
                cap_s=max_retry_delay,
                deadline_s=max_total_delay,
            )
        if retry_seed is not None and retry_policy.jitter == 0.0:
            retry_policy = replace(retry_policy, jitter=0.2)
        self._retry_policy = retry_policy
        self._retry_seed = retry_seed
        self.stats = DelivererStats()
        self._stop = threading.Event()
        self._endpoint_idx = 0
        # the pull thread (failover bump in run) and the config-update
        # path (update_endpoints, called from the commit thread) both
        # write _endpoints/_endpoint_idx (fabdep unguarded-shared-write):
        # unsynchronized, a refresh can land between the list swap and
        # the index reset and the next pull indexes the OLD list
        self._ep_lock = threading.Lock()

    def update_endpoints(self, endpoints: Sequence[Callable]) -> None:
        """Channel-config change handed us fresh orderer endpoints
        (reference deliveryclient endpoint refresh)."""
        with self._ep_lock:
            self._endpoints = list(endpoints)
            self._endpoint_idx = 0

    def _current_endpoint(self) -> Optional[Callable]:
        with self._ep_lock:
            if not self._endpoints:
                return None
            return self._endpoints[self._endpoint_idx % len(self._endpoints)]

    def _failover(self) -> None:
        with self._ep_lock:
            self._endpoint_idx += 1

    def stop(self) -> None:
        self._stop.set()

    def run(self, max_blocks: Optional[int] = None) -> int:
        """Pull until stopped, the budget is exhausted, or max_blocks
        arrive. Returns blocks received."""
        received = 0
        backoff = Backoff(
            self._retry_policy, seed=self._retry_seed, sleeper=self._sleeper
        )
        while not self._stop.is_set():
            endpoint = self._current_endpoint()
            if endpoint is None:
                return received
            self.stats.connect_attempts += 1
            try:
                # chaos seam: keyed per connection attempt, so a seeded
                # plan flaps a deterministic prefix of attempts
                fault_point("deliver.pull", key=self.stats.connect_attempts)
                env = seek_envelope(
                    self.channel_id, self._next_block(), self._signer
                )
                for resp in endpoint(env):
                    if self._stop.is_set():
                        return received
                    kind = resp.WhichOneof("Type")
                    if kind != "block":
                        raise ConnectionError(f"deliver status {resp.status}")
                    block = resp.block
                    if block.header.number != self._next_block():
                        raise ConnectionError(
                            f"got block {block.header.number}, want "
                            f"{self._next_block()}"
                        )
                    if self._verify_block is not None and not self._verify_block(
                        block
                    ):
                        raise ConnectionError(
                            f"block {block.header.number} failed verification"
                        )
                    self._on_block(block)
                    received += 1
                    self.stats.blocks_received += 1
                    backoff.reset()  # progress restarts the ramp
                    if max_blocks is not None and received >= max_blocks:
                        return received
                # clean end of stream: session served its range
                return received
            except (ConnectionError, OSError, StopIteration, InjectedFault):
                self.stats.failures += 1
                self._failover()
                if not backoff.sleep():
                    # per-policy retry budget exhausted (deadline or
                    # attempt cap): surface what we have instead of
                    # sleeping forever against a dead fabric
                    return received
        return received
