"""Deliver engine (reference common/deliver/deliver.go Handle + the peer's
DeliverFiltered variants, core/peer/deliverevents.go).

Serves block ranges described by SeekInfo over any source exposing
`height` and `get_block(n)` (orderer chains, peer ledgers). Sessions are
policy-checked once per delivery (and re-checked when the config
sequence advances — reference deliver.go SessionAccessControl) and bound
to a cert-expiry deadline (ExpirationCheckFunc).
"""

from __future__ import annotations

import datetime
import threading
from typing import Callable, Iterator, Optional

try:  # guarded: only identity_expiration needs X.509 parsing; its
    # caller already treats any failure as "no expiry known"
    from cryptography import x509
except ImportError:  # pragma: no cover - exercised in minimal envs
    x509 = None  # type: ignore

from fabric_tpu.policy.manager import PolicyError, SignedData
from fabric_tpu.protos import ab_pb2, common_pb2, identities_pb2, protoutil
from fabric_tpu.common.txflags import TxValidationCode, ValidationFlags


class DeliverError(Exception):
    def __init__(self, status: int, msg: str = ""):
        super().__init__(msg or f"status {status}")
        self.status = status


class BlockSource:
    """What the engine needs from a chain/ledger. `wait_for(n)` blocks
    until height > n (BLOCK_UNTIL_READY) or raises on timeout."""

    def __init__(self, get_block, height_fn, wait_for=None):
        self.get_block = get_block
        self._height_fn = height_fn
        self._wait_for = wait_for

    @property
    def height(self) -> int:
        return self._height_fn()

    def wait_for(self, number: int, timeout: float) -> bool:
        if self._wait_for is not None:
            return self._wait_for(number, timeout)
        return self.height > number


def identity_expiration(creator: bytes) -> Optional[datetime.datetime]:
    """Cert notAfter for session expiry (reference crypto/expiration.go)."""
    try:
        sid = protoutil.unmarshal(identities_pb2.SerializedIdentity, creator)
        cert = x509.load_pem_x509_certificate(sid.id_bytes)
        return cert.not_valid_after_utc
    except Exception:
        return None


class DeliverHandler:
    def __init__(
        self,
        sources: Callable[[str], Optional[BlockSource]],
        policy_checker: Optional[Callable[[str, SignedData], None]] = None,
        wait_timeout: float = 10.0,
    ):
        """sources: channel_id -> BlockSource; policy_checker raises to
        deny (reference: the Readers policy of the channel)."""
        self._sources = sources
        self._policy_checker = policy_checker
        self._wait_timeout = wait_timeout

    def deliver_blocks(
        self, envelope: common_pb2.Envelope
    ) -> Iterator[ab_pb2.DeliverResponse]:
        """One seek session: yields block responses then a status."""
        try:
            payload = protoutil.unmarshal(common_pb2.Payload, envelope.payload)
            if not payload.header.channel_header:
                raise DeliverError(common_pb2.BAD_REQUEST, "missing channel header")
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            seek = protoutil.unmarshal(ab_pb2.SeekInfo, payload.data)
            source = self._sources(chdr.channel_id)
            if source is None:
                raise DeliverError(
                    common_pb2.NOT_FOUND, f"channel {chdr.channel_id} not found"
                )

            expires = None
            if payload.header.signature_header:
                shdr = protoutil.unmarshal(
                    common_pb2.SignatureHeader, payload.header.signature_header
                )
                expires = identity_expiration(shdr.creator)
                if expires is not None and expires < datetime.datetime.now(  # fabdet: disable=wallclock-in-det  # cert-expiry admission gate: SEMANTICALLY time-dependent (identity validity window) — it gates stream access; the delivered block bytes come solely from the store
                    datetime.timezone.utc
                ):
                    raise DeliverError(common_pb2.FORBIDDEN, "client identity expired")
            if self._policy_checker is not None:
                if not payload.header.signature_header:
                    raise DeliverError(common_pb2.FORBIDDEN, "missing signature header")
                sd = SignedData(envelope.payload, shdr.creator, envelope.signature)
                try:
                    self._policy_checker(chdr.channel_id, sd)
                except Exception as e:
                    raise DeliverError(common_pb2.FORBIDDEN, str(e))

            start, stop = self._resolve_range(seek, source)
            number = start
            while number <= stop:
                if expires is not None and expires < datetime.datetime.now(  # fabdet: disable=wallclock-in-det  # mid-stream session-expiry recheck (deliver.go toFilteredBlock loop): semantically time-dependent access control, not block-content nondeterminism
                    datetime.timezone.utc
                ):
                    raise DeliverError(common_pb2.FORBIDDEN, "session expired")
                if number >= source.height:
                    if seek.behavior == ab_pb2.SeekInfo.FAIL_IF_NOT_READY:
                        raise DeliverError(
                            common_pb2.NOT_FOUND,
                            f"block {number} not yet available",
                        )
                    if not source.wait_for(number, self._wait_timeout):
                        raise DeliverError(
                            common_pb2.SERVICE_UNAVAILABLE, "timed out waiting"
                        )
                block = source.get_block(number)
                if block is None:
                    raise DeliverError(common_pb2.NOT_FOUND, f"block {number} missing")
                resp = ab_pb2.DeliverResponse()
                resp.block.CopyFrom(block)
                yield resp
                number += 1
            done = ab_pb2.DeliverResponse()
            done.status = common_pb2.SUCCESS
            yield done
        except DeliverError as e:
            resp = ab_pb2.DeliverResponse()
            resp.status = e.status
            yield resp
        except ValueError as e:
            resp = ab_pb2.DeliverResponse()
            resp.status = common_pb2.BAD_REQUEST
            yield resp

    def _resolve_range(self, seek: ab_pb2.SeekInfo, source: BlockSource):
        def pos(p: ab_pb2.SeekPosition, default: int) -> int:
            kind = p.WhichOneof("Type")
            if kind == "oldest":
                return 0
            if kind == "newest":
                return max(source.height - 1, 0)
            if kind == "specified":
                return p.specified.number
            if kind == "next_commit":
                return source.height
            return default

        start = pos(seek.start, 0)
        stop = pos(seek.stop, start) if seek.HasField("stop") else start
        if stop == 2**64 - 1:  # "max" convention: deliver forever
            stop = 2**63
        if stop < start:
            raise DeliverError(
                common_pb2.BAD_REQUEST, "start number greater than stop number"
            )
        return start, stop


def filter_block(
    block: common_pb2.Block, channel_id: str
) -> ab_pb2.FilteredBlock:
    """Full block -> FilteredBlock (reference core/peer/deliverevents.go
    blockResponseSenderWithFilteredBlocks): txid/type/validation code only."""
    fb = ab_pb2.FilteredBlock()
    fb.channel_id = channel_id
    fb.number = block.header.number
    flags = None
    if len(block.metadata.metadata) > common_pb2.TRANSACTIONS_FILTER:
        raw = block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER]
        if raw:
            flags = list(raw)
    for i, data in enumerate(block.data.data):
        try:
            env = protoutil.get_envelope_from_block_data(data)
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
        except ValueError:
            continue
        ft = fb.filtered_transactions.add()
        ft.txid = chdr.tx_id
        ft.type = chdr.type
        ft.tx_validation_code = (
            flags[i] if flags is not None and i < len(flags)
            else TxValidationCode.NOT_VALIDATED
        )
    return fb


def deliver_filtered(
    handler: DeliverHandler, envelope: common_pb2.Envelope
) -> Iterator[ab_pb2.DeliverResponse]:
    """DeliverFiltered stream: same engine, filtered payloads."""
    payload = protoutil.unmarshal(common_pb2.Payload, envelope.payload)
    chdr = protoutil.unmarshal(
        common_pb2.ChannelHeader, payload.header.channel_header
    )
    for resp in handler.deliver_blocks(envelope):
        if resp.WhichOneof("Type") == "block":
            out = ab_pb2.DeliverResponse()
            out.filtered_block.CopyFrom(filter_block(resp.block, chdr.channel_id))
            yield out
        else:
            yield resp


def pvt_data_map(entries) -> dict:
    """Stored PvtEntry rows for one block -> {tx_num: TxPvtReadWriteSet}
    (the wire shape of core/ledger TxPvtData in BlockAndPvtData)."""
    from fabric_tpu.protos import rwset_pb2

    by_tx: dict = {}
    for e in sorted(entries, key=lambda e: (e.tx_num, e.namespace, e.collection)):
        tx = by_tx.setdefault(e.tx_num, rwset_pb2.TxPvtReadWriteSet())
        ns = None
        for cand in tx.ns_pvt_rwset:
            if cand.namespace == e.namespace:
                ns = cand
                break
        if ns is None:
            ns = tx.ns_pvt_rwset.add()
            ns.namespace = e.namespace
        coll = ns.collection_pvt_rwset.add()
        coll.collection_name = e.collection
        coll.rwset = e.rwset
    return by_tx


def deliver_with_pvtdata(
    handler: DeliverHandler,
    envelope: common_pb2.Envelope,
    pvt_entries: Callable[[str, int], list],
    policy_checker: Optional[Callable] = None,
) -> Iterator[ab_pb2.DeliverResponse]:
    """DeliverWithPrivateData stream (reference
    core/peer/deliverevents.go:270 blockResponseSenderWithPrivateData):
    each block response carries the peer's stored cleartext private
    rwsets for that block, keyed by tx index.  Blocks whose private data
    the peer never held (ineligible / purged by BTL) simply have no map
    entry, exactly like the reference's DeliverWithPrivateData.

    Unlike plain Deliver (public data), this stream exposes private
    collection cleartext, so when a ``policy_checker(channel_id,
    SignedData)`` is configured the request MUST be signed and satisfy it
    (the reference gates the event ACL the same way); violations get a
    FORBIDDEN status and no blocks."""
    try:
        payload = protoutil.unmarshal(common_pb2.Payload, envelope.payload)
        chdr = protoutil.unmarshal(
            common_pb2.ChannelHeader, payload.header.channel_header
        )
    except ValueError:
        resp = ab_pb2.DeliverResponse()
        resp.status = common_pb2.BAD_REQUEST
        yield resp
        return
    if policy_checker is not None:
        forbidden = ab_pb2.DeliverResponse()
        forbidden.status = common_pb2.FORBIDDEN
        if not payload.header.signature_header:
            yield forbidden
            return
        shdr = protoutil.unmarshal(
            common_pb2.SignatureHeader, payload.header.signature_header
        )
        try:
            policy_checker(
                chdr.channel_id,
                SignedData(envelope.payload, shdr.creator, envelope.signature),
            )
        except Exception:  # noqa: BLE001 - any policy failure is FORBIDDEN
            yield forbidden
            return
    for resp in handler.deliver_blocks(envelope):
        if resp.WhichOneof("Type") == "block":
            out = ab_pb2.DeliverResponse()
            bpd = out.block_and_private_data
            bpd.block.CopyFrom(resp.block)
            entries = pvt_entries(chdr.channel_id, resp.block.header.number)
            for tx_num, tx_pvt in pvt_data_map(entries).items():
                bpd.private_data_map[tx_num].CopyFrom(tx_pvt)
            yield out
        else:
            yield resp
