"""Endorsement-side transaction construction (reference core/endorser +
protoutil/txutils.go CreateSignedTx)."""

from fabric_tpu.endorser.txbuilder import (
    ProposalBundle,
    create_proposal,
    create_signed_tx,
    endorse_proposal,
)

__all__ = [
    "ProposalBundle",
    "create_proposal",
    "create_signed_tx",
    "endorse_proposal",
]
