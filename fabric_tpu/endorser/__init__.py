"""Endorsement-side transaction construction (reference core/endorser +
protoutil/txutils.go CreateSignedTx)."""

from fabric_tpu.endorser.txbuilder import (
    ProposalBundle,
    create_proposal,
    create_signed_tx,
    endorse_proposal,
)

# ProposalBundle stays importable but is no longer claimed in __all__:
# nothing outside this package references it (fabdep dead-export)
__all__ = [
    "create_proposal",
    "create_signed_tx",
    "endorse_proposal",
]
