"""Endorser service — ProcessProposal (reference core/endorser/
endorser.go:296 + preProcess :250-294 + SimulateProposal :178).

Pipeline per proposal:
1. unpack SignedProposal -> Proposal -> headers (UnpackProposal);
2. validate: channel header type, TxID recompute, creator deserialize +
   certificate validation + client signature over proposal_bytes
   (validateProcessProposal -> checkSignatureFromCreator analog);
3. ACL check (aclmgmt hook);
4. duplicate TxID check against the ledger;
5. simulate: TxSimulator over committed state + ChaincodeSupport.Execute;
6. endorse: ProposalResponsePayload{proposal_hash, ChaincodeAction} signed
   as sig(prp || endorser_identity) — the default endorsement plugin
   (plugin_endorser.go / builtin ESCC).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from fabric_tpu.chaincode.support import ChaincodeSupport, TxParams
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.msp.identity import MSPError, MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil


class ProposalError(Exception):
    """Rejected before/while simulation; maps to a 500 ProposalResponse."""


@dataclass
class UnpackedProposal:
    signed_proposal: peer_pb2.SignedProposal
    proposal: peer_pb2.Proposal
    channel_header: common_pb2.ChannelHeader
    signature_header: common_pb2.SignatureHeader
    chaincode_name: str
    input: peer_pb2.ChaincodeInput
    transient: Dict[str, bytes]


def unpack_proposal(signed: peer_pb2.SignedProposal) -> UnpackedProposal:
    """protoutil.UnpackProposal + header checks (endorser.go:250-270)."""
    prop = protoutil.unmarshal(peer_pb2.Proposal, signed.proposal_bytes)
    header = protoutil.unmarshal(common_pb2.Header, prop.header)
    chdr = protoutil.unmarshal(
        common_pb2.ChannelHeader, header.channel_header
    )
    shdr = protoutil.unmarshal(
        common_pb2.SignatureHeader, header.signature_header
    )
    if chdr.type != common_pb2.ENDORSER_TRANSACTION:
        raise ProposalError(
            f"invalid header type {chdr.type}, expected ENDORSER_TRANSACTION"
        )
    ext = protoutil.unmarshal(
        peer_pb2.ChaincodeHeaderExtension, chdr.extension
    )
    if not ext.chaincode_id.name:
        raise ProposalError("ChaincodeHeaderExtension.ChaincodeId.Name is empty")
    ccpp = protoutil.unmarshal(
        peer_pb2.ChaincodeProposalPayload, prop.payload
    )
    cis = protoutil.unmarshal(peer_pb2.ChaincodeInvocationSpec, ccpp.input)
    return UnpackedProposal(
        signed_proposal=signed,
        proposal=prop,
        channel_header=chdr,
        signature_header=shdr,
        chaincode_name=ext.chaincode_id.name,
        input=cis.chaincode_spec.input,
        transient=dict(ccpp.TransientMap),
    )


class Endorser:
    def __init__(
        self,
        local_signer: SigningIdentity,
        msp_manager: MSPManager,
        support: ChaincodeSupport,
        get_ledger: Callable[[str], Optional[KVLedger]],
        acl_check: Optional[Callable[[UnpackedProposal], None]] = None,
        on_pvt_results=None,  # (channel, tx_id, [(ns, coll, kvrwset)])
    ):
        self.signer = local_signer
        self.msp_manager = msp_manager
        self.support = support
        self.get_ledger = get_ledger
        self.acl_check = acl_check
        self.on_pvt_results = on_pvt_results

    # -- the gRPC entry point --
    def process_proposal(
        self, signed: peer_pb2.SignedProposal
    ) -> peer_pb2.ProposalResponse:
        try:
            unpacked = unpack_proposal(signed)
            self._validate(unpacked)
            return self._simulate_and_endorse(unpacked)
        except (ProposalError, ValueError) as err:
            resp = peer_pb2.ProposalResponse()
            resp.response.status = 500
            resp.response.message = str(err)
            return resp

    # -- preProcess (endorser.go:250-294) --
    def _validate(self, up: UnpackedProposal) -> None:
        shdr = up.signature_header
        if not shdr.nonce:
            raise ProposalError("nonce is empty")
        if not shdr.creator:
            raise ProposalError("creator is empty")
        expected = protoutil.compute_tx_id(shdr.nonce, shdr.creator)
        if up.channel_header.tx_id != expected:
            raise ProposalError(
                f"incorrect txid; expected {expected}, got "
                f"{up.channel_header.tx_id}"
            )
        try:
            identity, msp = self.msp_manager.deserialize_identity(shdr.creator)
            msp.validate(identity)
            identity.verify(
                up.signed_proposal.proposal_bytes, up.signed_proposal.signature
            )
        except MSPError as err:
            raise ProposalError(f"access denied: {err}") from err
        if self.acl_check is not None:
            self.acl_check(up)

    # -- SimulateProposal + endorsement --
    def _simulate_and_endorse(
        self, up: UnpackedProposal
    ) -> peer_pb2.ProposalResponse:
        channel_id = up.channel_header.channel_id
        tx_id = up.channel_header.tx_id
        if channel_id:
            ledger = self.get_ledger(channel_id)
            if ledger is None:
                raise ProposalError(f"channel {channel_id} not found")
            if ledger.tx_exists(tx_id):
                raise ProposalError(f"duplicate transaction found [{tx_id}]")
            sim = TxSimulator(ledger.state_db, tx_id=tx_id)
        else:
            # channel-less proposal (lifecycle install, cscc JoinChain):
            # no ledger, a throwaway simulator whose rwset is discarded
            # (endorser.go: acquire a tx simulator only if chainID != "")
            from fabric_tpu.ledger.statedb import VersionedDB

            sim = TxSimulator(VersionedDB(), tx_id=tx_id)
        resp, event = self.support.execute(
            TxParams(
                channel_id=channel_id,
                tx_id=tx_id,
                simulator=sim,
                creator=up.signature_header.creator,
                transient=up.transient,
            ),
            up.chaincode_name,
            list(up.input.args),
        )
        if resp.status >= 400:
            # Chaincode errors return the response unsigned
            # (endorser.go:347-352: no endorsement on failure).
            out = peer_pb2.ProposalResponse()
            out.response.status = resp.status
            out.response.message = resp.message
            out.response.payload = resp.payload
            return out

        results = sim.get_tx_simulation_results()

        action = peer_pb2.ChaincodeAction()
        action.results = results.public_bytes
        if event is not None:
            action.events = event.SerializeToString()
        action.response.status = resp.status
        action.response.message = resp.message
        action.response.payload = resp.payload
        action.chaincode_id.name = up.chaincode_name

        prp = peer_pb2.ProposalResponsePayload()
        prp.proposal_hash = self._proposal_hash(up)
        prp.extension = action.SerializeToString()
        prp_bytes = prp.SerializeToString()

        endorser_bytes = self.signer.serialize()
        out = peer_pb2.ProposalResponse()
        out.version = 1
        out.response.status = resp.status
        out.response.message = resp.message
        out.response.payload = resp.payload
        out.payload = prp_bytes
        out.endorsement.endorser = endorser_bytes
        out.endorsement.signature = self.signer.sign(prp_bytes + endorser_bytes)
        # Private write-sets never ride in the block; they go to the local
        # transient store and out to eligible peers NOW (endorser.go
        # distributePrivateData -> gossip/privdata pull.go push).
        self.last_pvt_results = results
        if results.pvt_writes and self.on_pvt_results is not None:
            from fabric_tpu.ledger.simulator import collection_kvrwset_bytes

            pvt_writes = [
                (ns, coll, collection_kvrwset_bytes(writes))
                for (ns, coll), writes in sorted(results.pvt_writes.items())
            ]
            self.on_pvt_results(channel_id, tx_id, pvt_writes)
        return out

    def _proposal_hash(self, up: UnpackedProposal) -> bytes:
        """GetProposalHash1: headers + sanitized payload (no transient)."""
        ccpp = protoutil.unmarshal(
            peer_pb2.ChaincodeProposalPayload, up.proposal.payload
        )
        sanitized = peer_pb2.ChaincodeProposalPayload()
        sanitized.input = ccpp.input
        header = protoutil.unmarshal(common_pb2.Header, up.proposal.header)
        h = hashlib.sha256()
        h.update(header.channel_header)
        h.update(header.signature_header)
        h.update(sanitized.SerializeToString())
        return h.digest()
