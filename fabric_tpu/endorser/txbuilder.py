"""Proposal/transaction assembly (reference protoutil/txutils.go:
CreateChaincodeProposal, CreateProposalResponse/GetProposalHash1,
CreateSignedTx; and the endorsement-plugin signature of
plugin_endorser.go)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil


@dataclass
class ProposalBundle:
    """A proposal plus the pieces later steps need."""

    channel_id: str
    tx_id: str
    channel_header: bytes
    signature_header: bytes
    cc_proposal_payload: bytes  # WITH transient fields (endorser input)
    cc_proposal_payload_tx: bytes  # sanitized: no transient map (goes in tx)
    chaincode_name: str


def create_proposal(
    signer: SigningIdentity,
    channel_id: str,
    chaincode_name: str,
    args: Sequence[bytes],
    transient: Optional[Dict[str, bytes]] = None,
) -> ProposalBundle:
    nonce = signer.new_nonce()
    creator = signer.serialize()
    tx_id = protoutil.compute_tx_id(nonce, creator)

    ext = peer_pb2.ChaincodeHeaderExtension()
    ext.chaincode_id.name = chaincode_name
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION,
        channel_id,
        tx_id=tx_id,
        extension=ext.SerializeToString(),
    )
    shdr = protoutil.make_signature_header(creator, nonce)

    cis = peer_pb2.ChaincodeInvocationSpec()
    cis.chaincode_spec.type = peer_pb2.ChaincodeSpec.GOLANG
    cis.chaincode_spec.chaincode_id.name = chaincode_name
    cis.chaincode_spec.input.args.extend(args)

    ccpp = peer_pb2.ChaincodeProposalPayload()
    ccpp.input = cis.SerializeToString()
    for k, v in (transient or {}).items():
        ccpp.TransientMap[k] = v
    ccpp_tx = peer_pb2.ChaincodeProposalPayload()
    ccpp_tx.input = ccpp.input  # sanitized copy (GetBytesProposalPayloadForTx)

    return ProposalBundle(
        channel_id=channel_id,
        tx_id=tx_id,
        channel_header=chdr.SerializeToString(),
        signature_header=shdr.SerializeToString(),
        cc_proposal_payload=ccpp.SerializeToString(),
        cc_proposal_payload_tx=ccpp_tx.SerializeToString(),
        chaincode_name=chaincode_name,
    )


def create_signed_proposal(
    bundle: ProposalBundle, signer: SigningIdentity
) -> peer_pb2.SignedProposal:
    """protoutil.GetSignedProposal: Proposal{header, payload-with-transient}
    signed by the client over the serialized proposal bytes."""
    header = common_pb2.Header()
    header.channel_header = bundle.channel_header
    header.signature_header = bundle.signature_header
    prop = peer_pb2.Proposal()
    prop.header = header.SerializeToString()
    prop.payload = bundle.cc_proposal_payload
    out = peer_pb2.SignedProposal()
    out.proposal_bytes = prop.SerializeToString()
    out.signature = signer.sign(out.proposal_bytes)
    return out


def proposal_hash(bundle: ProposalBundle) -> bytes:
    """GetProposalHash1: sha256 over channel header || signature header ||
    sanitized chaincode proposal payload."""
    h = hashlib.sha256()
    h.update(bundle.channel_header)
    h.update(bundle.signature_header)
    h.update(bundle.cc_proposal_payload_tx)
    return h.digest()


def endorse_proposal(
    bundle: ProposalBundle,
    endorser: SigningIdentity,
    results: bytes,
    response_payload: bytes = b"",
    events: bytes = b"",
) -> peer_pb2.ProposalResponse:
    """Simulate-free endorsement: wrap the given simulation `results`
    (serialized TxReadWriteSet) and sign prp || endorser identity
    (reference CreateProposalResponse + plugin_endorser)."""
    action = peer_pb2.ChaincodeAction()
    action.results = results
    action.events = events
    action.response.status = 200
    action.response.payload = response_payload
    action.chaincode_id.name = bundle.chaincode_name

    prp = peer_pb2.ProposalResponsePayload()
    prp.proposal_hash = proposal_hash(bundle)
    prp.extension = action.SerializeToString()
    prp_bytes = prp.SerializeToString()

    endorser_bytes = endorser.serialize()
    out = peer_pb2.ProposalResponse()
    out.version = 1
    out.response.status = 200
    out.payload = prp_bytes
    out.endorsement.endorser = endorser_bytes
    out.endorsement.signature = endorser.sign(prp_bytes + endorser_bytes)
    return out


def create_signed_tx(
    bundle: ProposalBundle,
    signer: SigningIdentity,
    responses: Sequence[peer_pb2.ProposalResponse],
) -> common_pb2.Envelope:
    """Assemble the final envelope (protoutil.CreateSignedTx): all
    endorsements must agree on the proposal response payload."""
    if not responses:
        raise ValueError("at least one proposal response is required")
    for r in responses:
        # protoutil.CreateSignedTx rejects non-success endorsements
        if not (200 <= r.response.status < 400):
            raise ValueError(
                f"proposal response was not successful, error code "
                f"{r.response.status}, msg {r.response.message}"
            )
    payload_bytes = responses[0].payload
    for r in responses[1:]:
        if r.payload != payload_bytes:
            raise ValueError("ProposalResponsePayloads do not match")

    cap = peer_pb2.ChaincodeActionPayload()
    cap.chaincode_proposal_payload = bundle.cc_proposal_payload_tx
    cap.action.proposal_response_payload = payload_bytes
    for r in responses:
        e = cap.action.endorsements.add()
        e.endorser = r.endorsement.endorser
        e.signature = r.endorsement.signature

    taa = peer_pb2.TransactionAction()
    taa.header = bundle.signature_header
    taa.payload = cap.SerializeToString()
    tx = peer_pb2.Transaction()
    tx.actions.append(taa)

    payload = common_pb2.Payload()
    payload.header.channel_header = bundle.channel_header
    payload.header.signature_header = bundle.signature_header
    payload.data = tx.SerializeToString()
    payload_ser = payload.SerializeToString()

    env = common_pb2.Envelope()
    env.payload = payload_ser
    env.signature = signer.sign(payload_ser)
    return env
