"""Idemix credential scheme on FP256BN (reference idemix/*.go).

Implements, with byte-exact Fiat-Shamir transcripts:
- issuer key generation + public-key ZK proof (issuerkey.go)
- credential request (credrequest.go)
- credential issuance/verification, a BBS+ signature (credential.go)
- signature of knowledge over a credential (signature.go NewSignature/Ver)
- pseudonym signatures (nymsignature.go)
- weak Boneh-Boyen signatures (weak-bb.go)
- revocation authority: long-term ECDSA-P384 key, per-epoch CRI
  (revocation_authority.go); only ALG_NO_REVOCATION is implemented, as
  in the reference.

All transcript layouts (labels, G1/G2/BIG byte appends, double-hash with
nonce) mirror idemix/signature.go:161-194 and friends so that a signature
produced here verifies under any faithful implementation and vice versa.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu.protos import idemix_pb2

SIGN_LABEL = b"sign"
CRED_REQUEST_LABEL = b"credRequest"

ALG_NO_REVOCATION = 0

# per-algorithm byte length of the non-revocation FS contribution
PROOF_BYTES = {ALG_NO_REVOCATION: 0}

FIELD_BYTES = bn.FIELD_BYTES
G1_BYTES = 2 * FIELD_BYTES + 1


class IdemixError(Exception):
    pass


# --------------------------------------------------------------------------
# proto converters (util.go EcpToProto & co.)
# --------------------------------------------------------------------------


def ecp_to_proto(pt: bn.G1Point) -> idemix_pb2.ECP:
    out = idemix_pb2.ECP()
    out.x = bn.big_to_bytes(pt[0] if pt else 0)
    out.y = bn.big_to_bytes(pt[1] if pt else 0)
    return out


def ecp_from_proto(msg: idemix_pb2.ECP) -> bn.G1Point:
    pt = (bn.big_from_bytes(msg.x), bn.big_from_bytes(msg.y))
    if pt == (0, 0):
        return None
    if not bn.g1_is_on_curve(pt):
        raise IdemixError("G1 point not on curve")
    return pt


def ecp2_to_proto(pt: bn.G2Point) -> idemix_pb2.ECP2:
    out = idemix_pb2.ECP2()
    (xa, xb), (ya, yb) = pt if pt else ((0, 0), (0, 0))
    out.xa = bn.big_to_bytes(xa)
    out.xb = bn.big_to_bytes(xb)
    out.ya = bn.big_to_bytes(ya)
    out.yb = bn.big_to_bytes(yb)
    return out


def ecp2_from_proto(msg: idemix_pb2.ECP2) -> bn.G2Point:
    pt = (
        (bn.big_from_bytes(msg.xa), bn.big_from_bytes(msg.xb)),
        (bn.big_from_bytes(msg.ya), bn.big_from_bytes(msg.yb)),
    )
    if pt == ((0, 0), (0, 0)):
        return None
    if not bn.g2_is_on_curve(pt):
        raise IdemixError("G2 point not on twist")
    return pt


def _append_g1(buf: bytearray, pt: bn.G1Point) -> None:
    buf += bn.g1_to_bytes(pt)


def _append_g2(buf: bytearray, pt: bn.G2Point) -> None:
    buf += bn.g2_to_bytes(pt)


def _append_big(buf: bytearray, v: int) -> None:
    buf += bn.big_to_bytes(v)


def _hidden_indices(disclosure: Sequence[int]) -> List[int]:
    return [i for i, d in enumerate(disclosure) if d == 0]


def _mod(a: int) -> int:
    return a % bn.R


# --------------------------------------------------------------------------
# Issuer key (issuerkey.go)
# --------------------------------------------------------------------------


def new_issuer_key(attribute_names: Sequence[str], rng) -> idemix_pb2.IssuerKey:
    if len(set(attribute_names)) != len(attribute_names):
        raise IdemixError("attribute list contains duplicates")

    isk = bn.rand_mod_order(rng)
    key = idemix_pb2.IssuerKey()
    key.isk = bn.big_to_bytes(isk)
    ipk = key.ipk
    ipk.attribute_names.extend(attribute_names)

    w = bn.g2_mul(bn.G2_GEN, isk)
    ipk.w.CopyFrom(ecp2_to_proto(w))

    for _ in attribute_names:
        ipk.h_attrs.append(
            ecp_to_proto(bn.g1_mul(bn.G1_GEN, bn.rand_mod_order(rng)))
        )
    h_sk = bn.g1_mul(bn.G1_GEN, bn.rand_mod_order(rng))
    ipk.h_sk.CopyFrom(ecp_to_proto(h_sk))
    h_rand = bn.g1_mul(bn.G1_GEN, bn.rand_mod_order(rng))
    ipk.h_rand.CopyFrom(ecp_to_proto(h_rand))
    bar_g1 = bn.g1_mul(bn.G1_GEN, bn.rand_mod_order(rng))
    ipk.bar_g1.CopyFrom(ecp_to_proto(bar_g1))
    bar_g2 = bn.g1_mul(bar_g1, isk)
    ipk.bar_g2.CopyFrom(ecp_to_proto(bar_g2))

    # ZK PoK of isk in W and BarG2 (issuerkey.go:76-100)
    r = bn.rand_mod_order(rng)
    t1 = bn.g2_mul(bn.G2_GEN, r)
    t2 = bn.g1_mul(bar_g1, r)
    buf = bytearray()
    _append_g2(buf, t1)
    _append_g1(buf, t2)
    _append_g2(buf, bn.G2_GEN)
    _append_g1(buf, bar_g1)
    _append_g2(buf, w)
    _append_g1(buf, bar_g2)
    proof_c = bn.hash_mod_order(bytes(buf))
    ipk.proof_c = bn.big_to_bytes(proof_c)
    ipk.proof_s = bn.big_to_bytes(_mod(proof_c * isk + r))

    ipk.hash = bn.big_to_bytes(
        bn.hash_mod_order(ipk.SerializeToString())
    )
    return key


def check_issuer_public_key(ipk: idemix_pb2.IssuerPublicKey) -> None:
    """IssuerPublicKey.Check: well-formedness + PoK verify; recomputes
    the embedded hash (SetHash)."""
    num_attrs = len(ipk.attribute_names)
    if len(ipk.h_attrs) < num_attrs:
        raise IdemixError("some part of the public key is undefined")
    h_sk = ecp_from_proto(ipk.h_sk)
    h_rand = ecp_from_proto(ipk.h_rand)
    bar_g1 = ecp_from_proto(ipk.bar_g1)
    bar_g2 = ecp_from_proto(ipk.bar_g2)
    w = ecp2_from_proto(ipk.w)
    if h_sk is None or h_rand is None or bar_g1 is None:
        raise IdemixError("some part of the public key is undefined")
    proof_c = bn.big_from_bytes(ipk.proof_c)
    proof_s = bn.big_from_bytes(ipk.proof_s)

    neg_c = _mod(-proof_c)
    t1 = bn.g2_add(bn.g2_mul(bn.G2_GEN, proof_s), bn.g2_mul(w, neg_c))
    t2 = bn.g1_add(bn.g1_mul(bar_g1, proof_s), bn.g1_mul(bar_g2, neg_c))
    buf = bytearray()
    _append_g2(buf, t1)
    _append_g1(buf, t2)
    _append_g2(buf, bn.G2_GEN)
    _append_g1(buf, bar_g1)
    _append_g2(buf, w)
    _append_g1(buf, bar_g2)
    if proof_c != bn.hash_mod_order(bytes(buf)):
        raise IdemixError("zero knowledge proof in public key invalid")

    tmp = idemix_pb2.IssuerPublicKey()
    tmp.CopyFrom(ipk)
    tmp.hash = b""
    ipk.hash = bn.big_to_bytes(bn.hash_mod_order(tmp.SerializeToString()))


# --------------------------------------------------------------------------
# Credential request (credrequest.go)
# --------------------------------------------------------------------------


def new_cred_request(
    sk: int, issuer_nonce: bytes, ipk: idemix_pb2.IssuerPublicKey, rng
) -> idemix_pb2.CredRequest:
    h_sk = ecp_from_proto(ipk.h_sk)
    nym = bn.g1_mul(h_sk, sk)
    r_sk = bn.rand_mod_order(rng)
    t = bn.g1_mul(h_sk, r_sk)
    buf = bytearray()
    buf += CRED_REQUEST_LABEL
    _append_g1(buf, t)
    _append_g1(buf, h_sk)
    _append_g1(buf, nym)
    buf += issuer_nonce
    buf += ipk.hash
    proof_c = bn.hash_mod_order(bytes(buf))
    proof_s = _mod(proof_c * sk + r_sk)

    out = idemix_pb2.CredRequest()
    out.nym.CopyFrom(ecp_to_proto(nym))
    out.issuer_nonce = issuer_nonce
    out.proof_c = bn.big_to_bytes(proof_c)
    out.proof_s = bn.big_to_bytes(proof_s)
    return out


def verify_cred_request(
    req: idemix_pb2.CredRequest, ipk: idemix_pb2.IssuerPublicKey
) -> None:
    nym = ecp_from_proto(req.nym)
    proof_c = bn.big_from_bytes(req.proof_c)
    proof_s = bn.big_from_bytes(req.proof_s)
    h_sk = ecp_from_proto(ipk.h_sk)
    t = bn.g1_add(
        bn.g1_mul(h_sk, proof_s), bn.g1_neg(bn.g1_mul(nym, proof_c))
    )
    buf = bytearray()
    buf += CRED_REQUEST_LABEL
    _append_g1(buf, t)
    _append_g1(buf, h_sk)
    _append_g1(buf, nym)
    buf += req.issuer_nonce
    buf += ipk.hash
    if proof_c != bn.hash_mod_order(bytes(buf)):
        raise IdemixError("zero knowledge proof is invalid")


# --------------------------------------------------------------------------
# Credential = BBS+ signature (credential.go)
# --------------------------------------------------------------------------


def _attr_bases_product(
    ipk: idemix_pb2.IssuerPublicKey, scalars: Sequence[int]
) -> bn.G1Point:
    """prod_i HAttrs[i]^scalars[i]."""
    acc: bn.G1Point = None
    for base, s in zip(ipk.h_attrs, scalars):
        acc = bn.g1_add(acc, bn.g1_mul(ecp_from_proto(base), s))
    return acc


def new_credential(
    key: idemix_pb2.IssuerKey,
    req: idemix_pb2.CredRequest,
    attrs: Sequence[int],
    rng,
) -> idemix_pb2.Credential:
    verify_cred_request(req, key.ipk)
    if len(attrs) != len(key.ipk.attribute_names):
        raise IdemixError("incorrect number of attribute values passed")

    e = bn.rand_mod_order(rng)
    s = bn.rand_mod_order(rng)

    b = bn.G1_GEN
    b = bn.g1_add(b, ecp_from_proto(req.nym))
    b = bn.g1_add(b, bn.g1_mul(ecp_from_proto(key.ipk.h_rand), s))
    b = bn.g1_add(b, _attr_bases_product(key.ipk, attrs))

    isk = bn.big_from_bytes(key.isk)
    exp = pow(_mod(isk + e), bn.R - 2, bn.R)  # 1/(e + isk) mod r
    a = bn.g1_mul(b, exp)

    out = idemix_pb2.Credential()
    out.a.CopyFrom(ecp_to_proto(a))
    out.b.CopyFrom(ecp_to_proto(b))
    out.e = bn.big_to_bytes(e)
    out.s = bn.big_to_bytes(s)
    out.attrs.extend(bn.big_to_bytes(v) for v in attrs)
    return out


def verify_credential(
    cred: idemix_pb2.Credential, sk: int, ipk: idemix_pb2.IssuerPublicKey
) -> None:
    a = ecp_from_proto(cred.a)
    b = ecp_from_proto(cred.b)
    e = bn.big_from_bytes(cred.e)
    s = bn.big_from_bytes(cred.s)
    attrs = [bn.big_from_bytes(v) for v in cred.attrs]

    b_prime = bn.G1_GEN
    b_prime = bn.g1_add(
        b_prime,
        bn.g1_mul2(
            ecp_from_proto(ipk.h_sk), sk, ecp_from_proto(ipk.h_rand), s
        ),
    )
    b_prime = bn.g1_add(b_prime, _attr_bases_product(ipk, attrs))
    if b != b_prime:
        raise IdemixError(
            "b-value from credential does not match the attribute values"
        )

    # e(w * g2^e, A) == e(g2, B)
    lhs_g2 = bn.g2_add(bn.g2_mul(bn.G2_GEN, e), ecp2_from_proto(ipk.w))
    left = bn.pairing(lhs_g2, a)
    right = bn.pairing(bn.G2_GEN, b)
    if left != right:
        raise IdemixError("credential is not cryptographically valid")


# --------------------------------------------------------------------------
# Pseudonyms (util.go MakeNym)
# --------------------------------------------------------------------------


def make_nym(
    sk: int, ipk: idemix_pb2.IssuerPublicKey, rng
) -> Tuple[bn.G1Point, int]:
    rand_nym = bn.rand_mod_order(rng)
    nym = bn.g1_mul2(
        ecp_from_proto(ipk.h_sk), sk, ecp_from_proto(ipk.h_rand), rand_nym
    )
    return nym, rand_nym


# --------------------------------------------------------------------------
# Signature of knowledge (signature.go)
# --------------------------------------------------------------------------


def new_signature(
    cred: idemix_pb2.Credential,
    sk: int,
    nym: bn.G1Point,
    r_nym: int,
    ipk: idemix_pb2.IssuerPublicKey,
    disclosure: Sequence[int],
    msg: bytes,
    rh_index: int,
    cri: idemix_pb2.CredentialRevocationInformation,
    rng,
) -> idemix_pb2.Signature:
    if rh_index < 0 or rh_index >= len(ipk.attribute_names) or len(
        disclosure
    ) != len(ipk.attribute_names):
        raise IdemixError("cannot create idemix signature: invalid input")
    if cri.revocation_alg != ALG_NO_REVOCATION and disclosure[rh_index] == 1:
        raise IdemixError("revocation handle attribute must remain hidden")
    if cri.revocation_alg != ALG_NO_REVOCATION:
        raise IdemixError(
            f"unknown revocation algorithm {cri.revocation_alg}"
        )

    hidden = _hidden_indices(disclosure)

    r1 = bn.rand_mod_order(rng)
    r2 = bn.rand_mod_order(rng)
    r3 = pow(r1, bn.R - 2, bn.R)
    nonce = bn.rand_mod_order(rng)

    a = ecp_from_proto(cred.a)
    b = ecp_from_proto(cred.b)
    e = bn.big_from_bytes(cred.e)
    s = bn.big_from_bytes(cred.s)

    a_prime = bn.g1_mul(a, r1)
    a_bar = bn.g1_add(bn.g1_mul(b, r1), bn.g1_neg(bn.g1_mul(a_prime, e)))
    h_rand = ecp_from_proto(ipk.h_rand)
    h_sk = ecp_from_proto(ipk.h_sk)
    b_prime = bn.g1_add(bn.g1_mul(b, r1), bn.g1_neg(bn.g1_mul(h_rand, r2)))

    s_prime = _mod(s - r2 * r3)

    r_sk = bn.rand_mod_order(rng)
    r_e = bn.rand_mod_order(rng)
    r_r2 = bn.rand_mod_order(rng)
    r_r3 = bn.rand_mod_order(rng)
    r_s_prime = bn.rand_mod_order(rng)
    r_r_nym = bn.rand_mod_order(rng)
    r_attrs = [bn.rand_mod_order(rng) for _ in hidden]

    # non-revocation FS contribution: empty for ALG_NO_REVOCATION
    non_revoked_hash_data = b""

    # t-values (signature.go:136-159)
    t1 = bn.g1_mul2(a_prime, r_e, h_rand, r_r2)
    t2 = bn.g1_add(
        bn.g1_mul(h_rand, r_s_prime), bn.g1_mul2(b_prime, r_r3, h_sk, r_sk)
    )
    t2 = bn.g1_add(
        t2,
        _attr_bases_product_indices(ipk, hidden, r_attrs),
    )
    t3 = bn.g1_mul2(h_sk, r_sk, h_rand, r_r_nym)

    c = _signature_challenge(
        t1, t2, t3, a_prime, a_bar, b_prime, nym,
        non_revoked_hash_data, ipk.hash, disclosure, msg,
    )
    proof_c = _second_challenge(c, nonce)

    proof_s_sk = _mod(r_sk + proof_c * sk)
    proof_s_e = _mod(r_e - proof_c * e)
    proof_s_r2 = _mod(r_r2 + proof_c * r2)
    proof_s_r3 = _mod(r_r3 - proof_c * r3)
    proof_s_s_prime = _mod(r_s_prime + proof_c * s_prime)
    proof_s_r_nym = _mod(r_r_nym + proof_c * r_nym)
    proof_s_attrs = [
        bn.big_to_bytes(
            _mod(r_attrs[i] + proof_c * bn.big_from_bytes(cred.attrs[j]))
        )
        for i, j in enumerate(hidden)
    ]

    sig = idemix_pb2.Signature()
    sig.a_prime.CopyFrom(ecp_to_proto(a_prime))
    sig.a_bar.CopyFrom(ecp_to_proto(a_bar))
    sig.b_prime.CopyFrom(ecp_to_proto(b_prime))
    sig.proof_c = bn.big_to_bytes(proof_c)
    sig.proof_s_sk = bn.big_to_bytes(proof_s_sk)
    sig.proof_s_e = bn.big_to_bytes(proof_s_e)
    sig.proof_s_r2 = bn.big_to_bytes(proof_s_r2)
    sig.proof_s_r3 = bn.big_to_bytes(proof_s_r3)
    sig.proof_s_s_prime = bn.big_to_bytes(proof_s_s_prime)
    sig.proof_s_attrs.extend(proof_s_attrs)
    sig.nonce = bn.big_to_bytes(nonce)
    sig.nym.CopyFrom(ecp_to_proto(nym))
    sig.proof_s_r_nym = bn.big_to_bytes(proof_s_r_nym)
    sig.revocation_epoch_pk.CopyFrom(cri.epoch_pk)
    sig.revocation_pk_sig = cri.epoch_pk_sig
    sig.epoch = cri.epoch
    sig.non_revocation_proof.revocation_alg = ALG_NO_REVOCATION
    return sig


def _attr_bases_product_indices(
    ipk: idemix_pb2.IssuerPublicKey,
    indices: Sequence[int],
    scalars: Sequence[int],
) -> bn.G1Point:
    acc: bn.G1Point = None
    for idx, s in zip(indices, scalars):
        acc = bn.g1_add(acc, bn.g1_mul(ecp_from_proto(ipk.h_attrs[idx]), s))
    return acc


def _signature_challenge(
    t1, t2, t3, a_prime, a_bar, b_prime, nym,
    non_revoked_bytes: bytes, ipk_hash: bytes,
    disclosure: Sequence[int], msg: bytes,
) -> int:
    """First Fiat-Shamir hash over the fixed transcript layout
    (signature.go:161-187)."""
    buf = bytearray()
    buf += SIGN_LABEL
    for pt in (t1, t2, t3, a_prime, a_bar, b_prime, nym):
        _append_g1(buf, pt)
    buf += non_revoked_bytes
    buf += ipk_hash
    buf += bytes(disclosure)
    buf += msg
    return bn.hash_mod_order(bytes(buf))


def _second_challenge(c: int, nonce: int) -> int:
    """signature.go:189-194: ProofC = H(c || nonce)."""
    buf = bytearray()
    _append_big(buf, c)
    _append_big(buf, nonce)
    return bn.hash_mod_order(bytes(buf))


def verify_signature(
    sig: idemix_pb2.Signature,
    disclosure: Sequence[int],
    ipk: idemix_pb2.IssuerPublicKey,
    msg: bytes,
    attribute_values: Sequence[Optional[int]],
    rh_index: int,
    rev_pk,
    epoch: int,
) -> None:
    """Signature.Ver (signature.go:243-405). attribute_values[i] is
    checked for each disclosed attribute i. rev_pk is the revocation
    authority's long-term ECDSA public key (may be None to skip the
    epoch-PK check the way the reference's msp layer does when no
    revocation is configured)."""
    if rh_index < 0 or rh_index >= len(ipk.attribute_names) or len(
        disclosure
    ) != len(ipk.attribute_names):
        raise IdemixError("cannot verify idemix signature: invalid input")
    alg = sig.non_revocation_proof.revocation_alg
    if alg != ALG_NO_REVOCATION:
        raise IdemixError(f"unknown revocation algorithm {alg}")
    if alg != ALG_NO_REVOCATION and disclosure[rh_index] == 1:
        raise IdemixError("revocation handle must remain hidden")

    hidden = _hidden_indices(disclosure)

    a_prime = ecp_from_proto(sig.a_prime)
    a_bar = ecp_from_proto(sig.a_bar)
    b_prime = ecp_from_proto(sig.b_prime)
    nym = ecp_from_proto(sig.nym)
    proof_c = bn.big_from_bytes(sig.proof_c)
    proof_s_sk = bn.big_from_bytes(sig.proof_s_sk)
    proof_s_e = bn.big_from_bytes(sig.proof_s_e)
    proof_s_r2 = bn.big_from_bytes(sig.proof_s_r2)
    proof_s_r3 = bn.big_from_bytes(sig.proof_s_r3)
    proof_s_s_prime = bn.big_from_bytes(sig.proof_s_s_prime)
    proof_s_r_nym = bn.big_from_bytes(sig.proof_s_r_nym)
    if len(sig.proof_s_attrs) != len(hidden):
        raise IdemixError(
            "signature invalid: incorrect amount of s-values for "
            "AttributeProofSpec"
        )
    proof_s_attrs = [bn.big_from_bytes(v) for v in sig.proof_s_attrs]
    nonce = bn.big_from_bytes(sig.nonce)

    w = ecp2_from_proto(ipk.w)
    h_rand = ecp_from_proto(ipk.h_rand)
    h_sk = ecp_from_proto(ipk.h_sk)

    if a_prime is None:
        raise IdemixError("signature invalid: APrime = 1")

    # pairing check: e(W, A') * e(g2, ABar)^-1 == 1 (Ate output is not
    # unitary, so a true Fp12 inverse is needed, not the conjugate)
    t = bn.fp12_mul(
        bn.ate(w, a_prime), bn.fp12_inv(bn.ate(bn.G2_GEN, a_bar))
    )
    if not bn.gt_is_unity(bn.fexp(t)):
        raise IdemixError(
            "signature invalid: APrime and ABar don't have the expected "
            "structure"
        )

    # recompute t1
    t1 = bn.g1_mul2(a_prime, proof_s_e, h_rand, proof_s_r2)
    temp = bn.g1_add(a_bar, bn.g1_neg(b_prime))
    t1 = bn.g1_add(t1, bn.g1_neg(bn.g1_mul(temp, proof_c)))

    # recompute t2
    t2 = bn.g1_add(
        bn.g1_mul(h_rand, proof_s_s_prime),
        bn.g1_mul2(b_prime, proof_s_r3, h_sk, proof_s_sk),
    )
    t2 = bn.g1_add(
        t2, _attr_bases_product_indices(ipk, hidden, proof_s_attrs)
    )
    temp = bn.G1_GEN
    for index, disclose in enumerate(disclosure):
        if disclose != 0:
            temp = bn.g1_add(
                temp,
                bn.g1_mul(
                    ecp_from_proto(ipk.h_attrs[index]),
                    attribute_values[index],
                ),
            )
    t2 = bn.g1_add(t2, bn.g1_mul(temp, proof_c))

    # recompute t3
    t3 = bn.g1_mul2(h_sk, proof_s_sk, h_rand, proof_s_r_nym)
    t3 = bn.g1_add(t3, bn.g1_neg(bn.g1_mul(nym, proof_c)))

    non_revoked_bytes = b""  # ALG_NO_REVOCATION recompute contribution

    c = _signature_challenge(
        t1, t2, t3, a_prime, a_bar, b_prime, nym,
        non_revoked_bytes, ipk.hash, disclosure, msg,
    )
    if proof_c != _second_challenge(c, nonce):
        raise IdemixError(
            "signature invalid: zero-knowledge proof is invalid"
        )


# --------------------------------------------------------------------------
# Nym signatures (nymsignature.go)
# --------------------------------------------------------------------------


def new_nym_signature(
    sk: int,
    nym: bn.G1Point,
    r_nym: int,
    ipk: idemix_pb2.IssuerPublicKey,
    msg: bytes,
    rng,
) -> idemix_pb2.NymSignature:
    nonce = bn.rand_mod_order(rng)
    h_rand = ecp_from_proto(ipk.h_rand)
    h_sk = ecp_from_proto(ipk.h_sk)

    r_sk = bn.rand_mod_order(rng)
    r_r_nym = bn.rand_mod_order(rng)
    t = bn.g1_mul2(h_sk, r_sk, h_rand, r_r_nym)

    c = _nym_challenge(t, nym, ipk.hash, msg)
    proof_c = _second_challenge(c, nonce)

    out = idemix_pb2.NymSignature()
    out.proof_c = bn.big_to_bytes(proof_c)
    out.proof_s_sk = bn.big_to_bytes(_mod(r_sk + proof_c * sk))
    out.proof_s_r_nym = bn.big_to_bytes(_mod(r_r_nym + proof_c * r_nym))
    out.nonce = bn.big_to_bytes(nonce)
    return out


def _nym_challenge(t, nym, ipk_hash: bytes, msg: bytes) -> int:
    buf = bytearray()
    buf += SIGN_LABEL
    _append_g1(buf, t)
    _append_g1(buf, nym)
    buf += ipk_hash
    buf += msg
    return bn.hash_mod_order(bytes(buf))


def verify_nym_signature(
    sig: idemix_pb2.NymSignature,
    nym: bn.G1Point,
    ipk: idemix_pb2.IssuerPublicKey,
    msg: bytes,
) -> None:
    proof_c = bn.big_from_bytes(sig.proof_c)
    proof_s_sk = bn.big_from_bytes(sig.proof_s_sk)
    proof_s_r_nym = bn.big_from_bytes(sig.proof_s_r_nym)
    nonce = bn.big_from_bytes(sig.nonce)
    h_rand = ecp_from_proto(ipk.h_rand)
    h_sk = ecp_from_proto(ipk.h_sk)

    t = bn.g1_mul2(h_sk, proof_s_sk, h_rand, proof_s_r_nym)
    t = bn.g1_add(t, bn.g1_neg(bn.g1_mul(nym, proof_c)))

    c = _nym_challenge(t, nym, ipk.hash, msg)
    if proof_c != _second_challenge(c, nonce):
        raise IdemixError(
            "pseudonym signature invalid: zero-knowledge proof is invalid"
        )


# --------------------------------------------------------------------------
# Weak Boneh-Boyen signatures (weak-bb.go)
# --------------------------------------------------------------------------


def wbb_keygen(rng) -> Tuple[int, bn.G2Point]:
    sk = bn.rand_mod_order(rng)
    return sk, bn.g2_mul(bn.G2_GEN, sk)


def wbb_sign(sk: int, m: int) -> bn.G1Point:
    exp = pow(_mod(sk + m), bn.R - 2, bn.R)
    return bn.g1_mul(bn.G1_GEN, exp)


_GEN_GT = None


def _gen_gt():
    global _GEN_GT
    if _GEN_GT is None:
        _GEN_GT = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    return _GEN_GT


def wbb_verify(pk: bn.G2Point, sig: bn.G1Point, m: int) -> None:
    if pk is None or sig is None:
        raise IdemixError("Weak-BB signature invalid: received nil input")
    p = bn.g2_add(pk, bn.g2_mul(bn.G2_GEN, m))
    if bn.pairing(p, sig) != _gen_gt():
        raise IdemixError("Weak-BB signature is invalid")


# --------------------------------------------------------------------------
# Revocation authority (revocation_authority.go)
# --------------------------------------------------------------------------


def generate_long_term_revocation_key():
    """Long-term revocation key: ECDSA on P-384 like the reference."""
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP384R1())


def create_cri(
    key, unrevoked_handles: Sequence[int], epoch: int, alg: int, rng
) -> idemix_pb2.CredentialRevocationInformation:
    if alg != ALG_NO_REVOCATION:
        raise IdemixError(
            "the specified revocation algorithm is not supported."
        )
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = alg
    cri.epoch = epoch
    cri.epoch_pk.CopyFrom(ecp2_to_proto(bn.G2_GEN))  # dummy PK

    to_sign = cri.SerializeToString()
    digest = hashlib.sha256(to_sign).digest()
    cri.epoch_pk_sig = key.sign(
        digest, ec.ECDSA(Prehashed_sha256())
    )
    return cri


def Prehashed_sha256():
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.utils import Prehashed

    return Prehashed(hashes.SHA256())


def verify_epoch_pk(
    pk, epoch_pk: idemix_pb2.ECP2, epoch_pk_sig: bytes, epoch: int, alg: int
) -> None:
    """VerifyEpochPK: check the revocation authority's signature over the
    (alg, epoch_pk, epoch) CRI prefix."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ec

    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = alg
    cri.epoch_pk.CopyFrom(epoch_pk)
    cri.epoch = epoch
    digest = hashlib.sha256(cri.SerializeToString()).digest()
    try:
        pk.verify(epoch_pk_sig, digest, ec.ECDSA(Prehashed_sha256()))
    except InvalidSignature as e:
        raise IdemixError("EpochPKSig invalid") from e
