"""Batched Idemix signature verification (reference idemix/signature.go
Signature.Ver, SURVEY.md §7 Stage 5 / BASELINE config #3).

Per-block batching splits Signature.Ver into:

* host: proto parse, the Ate-pairing structure check (Miller loop +
  final exponentiation — still on the host oracle this round; the G1
  work below is the device half of Stage 5), Fiat–Shamir SHA-256
  recompute and challenge comparison;
* device: the t1/t2/t3 commitment recomputations — each is a G1
  multi-scalar multiplication — evaluated as ONE batched MSM kernel
  call with 3 lanes per signature (fabric_tpu.ops.bn256_kernel).

Failure semantics per lane mirror verify_signature: every failed check
maps to False in the result mask, never an exception across lanes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu.idemix.scheme import (
    ALG_NO_REVOCATION,
    IdemixError,
    _hidden_indices,
    _second_challenge,
    _signature_challenge,
    ecp_from_proto,
    ecp2_from_proto,
)
from fabric_tpu.protos import idemix_pb2


class _Parsed:
    """Host-parsed signature with its three MSM jobs."""

    def __init__(self, sig, disclosure, ipk, attribute_values, rh_index):
        hidden = _hidden_indices(disclosure)
        self.sig = sig
        self.disclosure = disclosure
        self.a_prime = ecp_from_proto(sig.a_prime)
        self.a_bar = ecp_from_proto(sig.a_bar)
        self.b_prime = ecp_from_proto(sig.b_prime)
        self.nym = ecp_from_proto(sig.nym)
        if self.a_prime is None:
            raise IdemixError("signature invalid: APrime = 1")
        if len(sig.proof_s_attrs) != len(hidden):
            raise IdemixError("incorrect amount of s-values")
        if sig.non_revocation_proof.revocation_alg != ALG_NO_REVOCATION:
            raise IdemixError("unknown revocation algorithm")

        c = bn.big_from_bytes(sig.proof_c)
        s_sk = bn.big_from_bytes(sig.proof_s_sk)
        s_e = bn.big_from_bytes(sig.proof_s_e)
        s_r2 = bn.big_from_bytes(sig.proof_s_r2)
        s_r3 = bn.big_from_bytes(sig.proof_s_r3)
        s_s_prime = bn.big_from_bytes(sig.proof_s_s_prime)
        s_r_nym = bn.big_from_bytes(sig.proof_s_r_nym)
        s_attrs = [bn.big_from_bytes(v) for v in sig.proof_s_attrs]
        self.proof_c = c
        self.nonce = bn.big_from_bytes(sig.nonce)

        h_rand = ecp_from_proto(ipk.h_rand)
        h_sk = ecp_from_proto(ipk.h_sk)
        neg_c = (-c) % bn.R

        # t1 = s_e·A' + s_r2·HRand − c·(ABar − B')
        self.t1_job = (
            [self.a_prime, h_rand, bn.g1_add(self.a_bar, bn.g1_neg(self.b_prime))],
            [s_e, s_r2, neg_c],
        )
        # t2 = s_s'·HRand + s_r3·B' + s_sk·HSk + Σ_hidden s_i·HAttr_i
        #      + c·(G1 + Σ_disclosed a_i·HAttr_i)
        bases = [h_rand, self.b_prime, h_sk]
        scalars = [s_s_prime, s_r3, s_sk]
        for j, idx in enumerate(hidden):
            bases.append(ecp_from_proto(ipk.h_attrs[idx]))
            scalars.append(s_attrs[j])
        bases.append(bn.G1_GEN)
        scalars.append(c)
        for idx, disclose in enumerate(disclosure):
            if disclose != 0:
                bases.append(ecp_from_proto(ipk.h_attrs[idx]))
                scalars.append((c * attribute_values[idx]) % bn.R)
        self.t2_job = (bases, scalars)
        # t3 = s_sk·HSk + s_r_nym·HRand − c·Nym
        self.t3_job = ([h_sk, h_rand, self.nym], [s_sk, s_r_nym, neg_c])


def verify_signatures_batch(
    signatures: Sequence[idemix_pb2.Signature],
    disclosures: Sequence[Sequence[int]],
    ipk: idemix_pb2.IssuerPublicKey,
    msgs: Sequence[bytes],
    attribute_values_list: Sequence[Sequence[Optional[int]]],
    rh_index: int,
    device_pairing: bool = False,
) -> List[bool]:
    """One device MSM pass for the whole batch; returns a per-signature
    validity mask (BASELINE config #3's bit-exact mask contract).

    device_pairing=True runs the Ate2 structure check on the
    accelerator too (ops/pairing_kernel.py: precomputed-line Miller
    loop, batched over the signatures); False keeps the host oracle
    pairing (idemix/signature.go:288-296 semantics either way)."""
    from fabric_tpu.ops.bn256_kernel import msm_host_batch

    n = len(signatures)
    parsed: List[Optional[_Parsed]] = []
    for sig, disclosure, values in zip(
        signatures, disclosures, attribute_values_list
    ):
        try:
            if rh_index < 0 or rh_index >= len(ipk.attribute_names) or len(
                disclosure
            ) != len(ipk.attribute_names):
                raise IdemixError("invalid input")
            parsed.append(_Parsed(sig, disclosure, ipk, values, rh_index))
        except Exception:  # fablint: disable=broad-except  # lane becomes parsed=None, reported INVALID in the output mask
            parsed.append(None)

    # pairing structure check: e(W, A') * e(g2, ABar)^-1 == 1
    w = ecp2_from_proto(ipk.w)
    if device_pairing:
        from fabric_tpu.ops.pairing_kernel import kernel_for_issuer

        kernel = kernel_for_issuer(bn.g2_to_bytes(w))
        pairing_ok = kernel.check(
            [
                (p.a_prime, p.a_bar) if p is not None else None
                for p in parsed
            ]
        )
    else:
        pairing_ok = []
        for p in parsed:
            if p is None:
                pairing_ok.append(False)
                continue
            t = bn.fp12_mul(
                bn.ate(w, p.a_prime), bn.fp12_inv(bn.ate(bn.G2_GEN, p.a_bar))
            )
            pairing_ok.append(bn.gt_is_unity(bn.fexp(t)))

    # device: 3 MSM lanes per live signature, one kernel batch
    jobs: List[Tuple[list, list]] = []
    owners: List[int] = []
    for i, p in enumerate(parsed):
        if p is None or not pairing_ok[i]:
            continue
        for job in (p.t1_job, p.t2_job, p.t3_job):
            jobs.append(job)
            owners.append(i)
    results = [False] * n
    if jobs:
        k_max = max(len(b) for b, _ in jobs)
        bases = [list(b) + [None] * (k_max - len(b)) for b, _ in jobs]
        scalars = [list(s) + [0] * (k_max - len(s)) for _, s in jobs]
        points = msm_host_batch(bases, scalars)
        by_owner = {}
        for owner, pt in zip(owners, points):
            by_owner.setdefault(owner, []).append(pt)
        for i, ts in by_owner.items():
            p = parsed[i]
            t1, t2, t3 = ts
            c = _signature_challenge(
                t1, t2, t3, p.a_prime, p.a_bar, p.b_prime, p.nym,
                b"", ipk.hash, p.disclosure, msgs[i],
            )
            results[i] = p.proof_c == _second_challenge(c, p.nonce)
    return results
