"""Batched Idemix signature verification (reference idemix/signature.go
Signature.Ver, SURVEY.md §7 Stage 5 / BASELINE config #3).

Per-block batching splits Signature.Ver into:

* host: proto parse, Fiat–Shamir SHA-256 recompute and challenge
  comparison (shared by every rung);
* batch math: the Ate-pairing structure check and the t1/t2/t3
  commitment recomputations (G1 multi-scalar multiplications), routed
  through the Idemix backend ladder (crypto/bccsp.py IDEMIX_TIERS):

    hostbn  — numpy limb-matrix FP256BN lanes (crypto/hostbn.py):
              fused-tower batched Miller loops + batched MSM, with
              shared-nothing process-pool sharding for big batches
              (degrade-to-inline on any pool failure);
    scheme  — the per-signature idemix/scheme.py oracle loop (the
              clarity-first rung; bench warns loudly when active);

  plus the explicit device paths (``device_pairing=True`` runs the
  precomputed-line Ate2 kernel, ops/pairing_kernel.py; ``backend="msm"``
  keeps the host-oracle pairing with the XLA MSM kernel).

Failure semantics per lane mirror verify_signature: every failed check
maps to False in the result mask, never an exception across lanes, and
every rung produces the SAME mask bit-exactly (differentially tested,
chaos-asserted via the ``idemix.verdict`` corrupt seam).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import corrupt_verdicts, fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import CooldownGate
from fabric_tpu.crypto import bccsp
from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu.crypto import hostec
from fabric_tpu.idemix.scheme import (
    ALG_NO_REVOCATION,
    IdemixError,
    _hidden_indices,
    _second_challenge,
    _signature_challenge,
    ecp_from_proto,
    ecp2_from_proto,
    verify_signature,
)
from fabric_tpu.protos import idemix_pb2

logger = must_get_logger("idemix.batch")


class _Parsed:
    """Host-parsed signature with its three MSM jobs."""

    def __init__(self, sig, disclosure, ipk, attribute_values, rh_index):
        hidden = _hidden_indices(disclosure)
        self.sig = sig
        self.disclosure = disclosure
        self.a_prime = ecp_from_proto(sig.a_prime)
        self.a_bar = ecp_from_proto(sig.a_bar)
        self.b_prime = ecp_from_proto(sig.b_prime)
        self.nym = ecp_from_proto(sig.nym)
        if self.a_prime is None:
            raise IdemixError("signature invalid: APrime = 1")
        if len(sig.proof_s_attrs) != len(hidden):
            raise IdemixError("incorrect amount of s-values")
        if sig.non_revocation_proof.revocation_alg != ALG_NO_REVOCATION:
            raise IdemixError("unknown revocation algorithm")

        c = bn.big_from_bytes(sig.proof_c)
        s_sk = bn.big_from_bytes(sig.proof_s_sk)
        s_e = bn.big_from_bytes(sig.proof_s_e)
        s_r2 = bn.big_from_bytes(sig.proof_s_r2)
        s_r3 = bn.big_from_bytes(sig.proof_s_r3)
        s_s_prime = bn.big_from_bytes(sig.proof_s_s_prime)
        s_r_nym = bn.big_from_bytes(sig.proof_s_r_nym)
        s_attrs = [bn.big_from_bytes(v) for v in sig.proof_s_attrs]
        self.proof_c = c
        self.nonce = bn.big_from_bytes(sig.nonce)

        h_rand = ecp_from_proto(ipk.h_rand)
        h_sk = ecp_from_proto(ipk.h_sk)
        neg_c = (-c) % bn.R

        # t1 = s_e·A' + s_r2·HRand − c·(ABar − B')
        self.t1_job = (
            [self.a_prime, h_rand, bn.g1_add(self.a_bar, bn.g1_neg(self.b_prime))],
            [s_e, s_r2, neg_c],
        )
        # t2 = s_s'·HRand + s_r3·B' + s_sk·HSk + Σ_hidden s_i·HAttr_i
        #      + c·(G1 + Σ_disclosed a_i·HAttr_i)
        bases = [h_rand, self.b_prime, h_sk]
        scalars = [s_s_prime, s_r3, s_sk]
        for j, idx in enumerate(hidden):
            bases.append(ecp_from_proto(ipk.h_attrs[idx]))
            scalars.append(s_attrs[j])
        bases.append(bn.G1_GEN)
        scalars.append(c)
        for idx, disclose in enumerate(disclosure):
            if disclose != 0:
                bases.append(ecp_from_proto(ipk.h_attrs[idx]))
                scalars.append((c * attribute_values[idx]) % bn.R)
        self.t2_job = (bases, scalars)
        # t3 = s_sk·HSk + s_r_nym·HRand − c·Nym
        self.t3_job = ([h_sk, h_rand, self.nym], [s_sk, s_r_nym, neg_c])


def _parse_lanes(signatures, disclosures, ipk, attribute_values_list, rh_index):
    parsed: List[Optional[_Parsed]] = []
    for sig, disclosure, values in zip(
        signatures, disclosures, attribute_values_list
    ):
        try:
            if rh_index < 0 or rh_index >= len(ipk.attribute_names) or len(
                disclosure
            ) != len(ipk.attribute_names):
                raise IdemixError("invalid input")
            parsed.append(_Parsed(sig, disclosure, ipk, values, rh_index))
        except Exception:  # fablint: disable=broad-except  # lane becomes parsed=None, reported INVALID in the output mask
            parsed.append(None)
    return parsed


def _challenge_results(parsed, ipk, msgs, t_points) -> List[bool]:
    """Fiat–Shamir recompute over the batch's t1/t2/t3 points.
    ``t_points``: lane index -> (t1, t2, t3)."""
    results = [False] * len(parsed)
    for i, ts in t_points.items():
        p = parsed[i]
        t1, t2, t3 = ts
        c = _signature_challenge(
            t1, t2, t3, p.a_prime, p.a_bar, p.b_prime, p.nym,
            b"", ipk.hash, p.disclosure, msgs[i],
        )
        results[i] = p.proof_c == _second_challenge(c, p.nonce)
    return results


def _chaos_verdicts(out: List[bool]) -> List[bool]:
    """``idemix.verdict`` corrupt seam (the batch-rung analog of
    ``bccsp.verdict``): only an installed fault plan reaches the flip —
    it exists so the fabchaos idemix_storm gate can prove its bit-exact
    mask assertion CATCHES a corrupted verdict."""
    spec = fault_point("idemix.verdict", interprets=("corrupt",))
    if spec is not None and spec.action == "corrupt":
        return corrupt_verdicts(out, spec)
    return out


def verify_signatures_batch(
    signatures: Sequence[idemix_pb2.Signature],
    disclosures: Sequence[Sequence[int]],
    ipk: idemix_pb2.IssuerPublicKey,
    msgs: Sequence[bytes],
    attribute_values_list: Sequence[Sequence[Optional[int]]],
    rh_index: int,
    device_pairing: bool = False,
    backend: Optional[str] = None,
    _pool_ok: bool = True,
) -> List[bool]:
    """Batch Signature.Ver; returns the per-signature validity mask
    (BASELINE config #3's bit-exact mask contract — identical across
    every rung).

    Routing: ``device_pairing=True`` forces the device path (Ate2
    pairing kernel + XLA MSM).  Otherwise ``backend`` picks a rung
    explicitly ("hostbn" / "scheme" / "msm" — the legacy host-oracle
    pairing + XLA MSM path), and None follows the process-wide ladder
    (bccsp.idemix_backend_name())."""
    n = len(signatures)
    if n == 0:
        return []
    if device_pairing:
        backend = "device"
    elif backend is None:
        backend = bccsp.idemix_backend_name()
    t0 = time.perf_counter()

    if backend == "hostbn":
        out = _verify_hostbn(
            signatures, disclosures, ipk, msgs, attribute_values_list,
            rh_index, pool_ok=_pool_ok,
        )
    elif backend == "scheme":
        out = _verify_scheme(
            signatures, disclosures, ipk, msgs, attribute_values_list,
            rh_index,
        )
    elif backend in ("device", "msm"):
        out = _verify_device(
            signatures, disclosures, ipk, msgs, attribute_values_list,
            rh_index, device_pairing=(backend == "device"),
        )
    else:
        raise ValueError(f"unknown idemix batch backend {backend!r}")
    if _pool_ok:  # coordinating process only; shard workers stay silent
        fabobs.obs_count("fabric_verify_lanes_total", n, rung=backend)
        fabobs.obs_observe(
            "fabric_verify_seconds", time.perf_counter() - t0, rung=backend
        )
    # the corrupt seam fires ONCE per batch, in the coordinating
    # process: pool workers (re-entering with _pool_ok=False) inherit an
    # env-installed plan and would otherwise corrupt each shard AND the
    # parent would corrupt the concatenation — two flips cancel and an
    # armed fault could become a silent no-op
    return _chaos_verdicts(out) if _pool_ok else out


# ---------------------------------------------------------------------------
# scheme rung: the per-signature oracle loop
# ---------------------------------------------------------------------------


def _verify_scheme(
    signatures, disclosures, ipk, msgs, attribute_values_list, rh_index
) -> List[bool]:
    out = []
    for sig, disclosure, msg, values in zip(
        signatures, disclosures, msgs, attribute_values_list
    ):
        try:
            verify_signature(
                sig, disclosure, ipk, msg, values, rh_index, None, 0
            )
            out.append(True)
        except Exception:  # fablint: disable=broad-except  # oracle rejection (any flavor) is a False lane, never a batch error
            out.append(False)
    return out


# ---------------------------------------------------------------------------
# hostbn rung: numpy limb-matrix lanes (+ process-pool sharding)
# ---------------------------------------------------------------------------

MIN_POOL_SIGS = 64  # below this a pool round-trip costs more than it buys
MIN_SHARD_SIGS = 16  # never split shards smaller than this


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            pass
    return default


def _verify_hostbn(
    signatures, disclosures, ipk, msgs, attribute_values_list, rh_index,
    pool_ok: bool = True,
) -> List[bool]:
    from fabric_tpu.crypto import hostbn

    n = len(signatures)
    if pool_ok and n >= _env_int(
        "FABRIC_TPU_HOSTBN_MIN_POOL", MIN_POOL_SIGS
    ):
        out = _verify_hostbn_pooled(
            signatures, disclosures, ipk, msgs, attribute_values_list,
            rh_index,
        )
        if out is not None:
            return out

    parsed = _parse_lanes(
        signatures, disclosures, ipk, attribute_values_list, rh_index
    )
    w = ecp2_from_proto(ipk.w)
    pairing_ok = hostbn.pairing_check_batch(
        w,
        [
            (p.a_prime, p.a_bar) if p is not None else None
            for p in parsed
        ],
    )
    jobs: List[Tuple[list, list]] = []
    owners: List[int] = []
    for i, p in enumerate(parsed):
        if p is None or not pairing_ok[i]:
            continue
        for job in (p.t1_job, p.t2_job, p.t3_job):
            jobs.append(job)
            owners.append(i)
    t_points = {}
    if jobs:
        points = hostbn.msm_batch(jobs)
        by_owner: dict = {}
        for owner, pt in zip(owners, points):
            by_owner.setdefault(owner, []).append(pt)
        t_points = by_owner
    return _challenge_results(parsed, ipk, msgs, t_points)


# shared-nothing pool: shards are chunks of SIGNATURES (serialized
# protos — the parse cost is trivial next to the lane math), workers run
# the inline hostbn path and the parent concatenates in order
_POOL = None
_POOL_PROCS = 1
_POOL_LOCK = threading.Lock()
_POOL_GATE = CooldownGate()


def pool_procs() -> int:
    """Worker count (1 = pool disabled); FABRIC_TPU_HOSTBN_PROCS
    overrides, falling back to hostec's discipline (malformed values
    degrade to the default, never raise)."""
    procs = os.environ.get("FABRIC_TPU_HOSTBN_PROCS", "")
    if procs:
        try:
            return max(int(procs), 1)
        except ValueError:
            pass
    return hostec.pool_procs()


def _pool():
    """Lazy shared ProcessPoolExecutor (forkserver/spawn preferred).
    Broken or unavailable pools degrade to inline compute, never die."""
    global _POOL, _POOL_PROCS
    with _POOL_LOCK:
        if _POOL is None:
            if not _POOL_GATE.ready():
                return None
            procs = pool_procs()
            _POOL_PROCS = procs
            if procs <= 1:
                _POOL = False
                return None
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            methods = multiprocessing.get_all_start_methods()
            # FABRIC_TPU_HOSTEC_START is the process-wide start-method
            # knob shared by every host pool (hostec, hostec_np, and
            # this one — the PR 5 convention): the fork-with-threads
            # hazard it guards against is per-interpreter, not per-pool
            start = os.environ.get("FABRIC_TPU_HOSTEC_START", "")
            if start not in methods:
                for start in ("forkserver", "spawn", "fork"):
                    if start in methods:
                        break
            try:
                _POOL = ProcessPoolExecutor(
                    max_workers=procs,
                    mp_context=multiprocessing.get_context(start),
                )
                fabobs.obs_count("fabric_pool_rebuilds_total", pool="hostbn")
            except Exception as exc:  # pragma: no cover - sandboxes
                logger.warning(
                    "idemix pool unavailable (%s); verifying inline", exc
                )
                _POOL = False
    return _POOL or None


def reset_pool_cooldown() -> None:
    """Close the rebuild cooldown and reset its ramp (harness seam:
    fabchaos exercises the ``hostbn.pool.submit`` and
    ``hostbn.pool.resolve`` faults back-to-back without waiting out
    the exponential cooldown a broken-pool teardown arms)."""
    _POOL_GATE.record_success()


def shutdown_pool(broken: bool = False) -> None:
    """Tear the pool down; ``broken=True`` arms the rebuild cooldown
    (degrade paths only — clean teardowns leave the gate closed)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL:
            _POOL.shutdown(wait=False, cancel_futures=True)
        _POOL = None
        if broken:
            _POOL_GATE.record_failure()
    if broken:
        fabobs.obs_count("fabric_pool_cooldowns_total", pool="hostbn")
        fabobs.obs_count("fabric_degrade_total", seam="hostbn.pool")
        fabobs.obs_trigger("hostbn.pool_broken")


def _pool_worker(
    ipk_bytes, sig_blobs, disclosures, msgs, values, rh_index
) -> List[bool]:
    """Runs in a pool worker: re-parse the chunk and verify inline on
    the hostbn rung (per-worker issuer schedules are cached across
    batches by crypto/hostbn)."""
    ipk = idemix_pb2.IssuerPublicKey.FromString(ipk_bytes)
    sigs = [idemix_pb2.Signature.FromString(b) for b in sig_blobs]
    return verify_signatures_batch(
        sigs, disclosures, ipk, msgs, values, rh_index,
        backend="hostbn", _pool_ok=False,
    )


def _verify_hostbn_pooled(
    signatures, disclosures, ipk, msgs, attribute_values_list, rh_index
) -> Optional[List[bool]]:
    """Shard the batch across the process pool; None = caller verifies
    inline (no pool, submit failure, worker death — degrade, never
    die)."""
    pool = _pool()
    if pool is None:
        return None
    n = len(signatures)
    nshards = min(
        _POOL_PROCS,
        max(n // _env_int("FABRIC_TPU_HOSTBN_MIN_SHARD", MIN_SHARD_SIGS), 1),
    )
    if nshards <= 1:
        return None
    step = (n + nshards - 1) // nshards
    ipk_bytes = ipk.SerializeToString()
    try:
        fault_point("hostbn.pool.submit")
        futures = [
            pool.submit(
                _pool_worker,
                ipk_bytes,
                [s.SerializeToString() for s in signatures[lo : lo + step]],
                list(disclosures[lo : lo + step]),
                list(msgs[lo : lo + step]),
                list(attribute_values_list[lo : lo + step]),
                rh_index,
            )
            for lo in range(0, n, step)
        ]
    except Exception as exc:  # BrokenProcessPool / shutdown race
        logger.warning(
            "idemix pool submit failed (%s); verifying inline", exc
        )
        shutdown_pool(broken=True)
        return None
    try:
        fault_point("hostbn.pool.resolve")
        out: List[bool] = []
        for f in futures:
            out.extend(f.result())
        with _POOL_LOCK:
            # a batch that made it THROUGH the pool resets the rebuild
            # cooldown ramp (construction alone proves nothing)
            _POOL_GATE.record_success()
        return out
    except Exception as exc:  # worker died mid-run: inline fallback
        logger.warning(
            "idemix pool worker died mid-batch (%s); verifying inline", exc
        )
        shutdown_pool(broken=True)
        return None


# ---------------------------------------------------------------------------
# device / legacy-msm paths (XLA kernels)
# ---------------------------------------------------------------------------


def _verify_device(
    signatures, disclosures, ipk, msgs, attribute_values_list, rh_index,
    device_pairing: bool,
) -> List[bool]:
    """One device MSM pass for the whole batch; ``device_pairing=True``
    runs the Ate2 structure check on the accelerator too
    (ops/pairing_kernel.py), False keeps the host oracle pairing
    (idemix/signature.go:288-296 semantics either way)."""
    from fabric_tpu.ops.bn256_kernel import msm_host_batch

    parsed = _parse_lanes(
        signatures, disclosures, ipk, attribute_values_list, rh_index
    )

    # pairing structure check: e(W, A') * e(g2, ABar)^-1 == 1
    w = ecp2_from_proto(ipk.w)
    if device_pairing:
        from fabric_tpu.ops.pairing_kernel import kernel_for_issuer

        kernel = kernel_for_issuer(bn.g2_to_bytes(w))
        pairing_ok = kernel.check(
            [
                (p.a_prime, p.a_bar) if p is not None else None
                for p in parsed
            ]
        )
    else:
        pairing_ok = []
        for p in parsed:
            if p is None:
                pairing_ok.append(False)
                continue
            t = bn.fp12_mul(
                bn.ate(w, p.a_prime), bn.fp12_inv(bn.ate(bn.G2_GEN, p.a_bar))
            )
            pairing_ok.append(bn.gt_is_unity(bn.fexp(t)))

    # device: 3 MSM lanes per live signature, one kernel batch
    jobs: List[Tuple[list, list]] = []
    owners: List[int] = []
    for i, p in enumerate(parsed):
        if p is None or not pairing_ok[i]:
            continue
        for job in (p.t1_job, p.t2_job, p.t3_job):
            jobs.append(job)
            owners.append(i)
    t_points = {}
    if jobs:
        k_max = max(len(b) for b, _ in jobs)
        bases = [list(b) + [None] * (k_max - len(b)) for b, _ in jobs]
        scalars = [list(s) + [0] * (k_max - len(s)) for _, s in jobs]
        points = msm_host_batch(bases, scalars)
        by_owner: dict = {}
        for owner, pt in zip(owners, points):
            by_owner.setdefault(owner, []).append(pt)
        t_points = by_owner
    return _challenge_results(parsed, ipk, msgs, t_points)
