"""Idemix anonymous-credential suite (reference idemix/ package).

BBS+-style credentials and signatures of knowledge on FP256BN
(fabric_tpu.crypto.fp256bn host oracle; batched device kernels in
fabric_tpu.ops). Wire messages in fabric_tpu.protos.idemix_pb2 are
field-compatible with the reference's idemix.proto.
"""

from fabric_tpu.idemix.scheme import (
    ALG_NO_REVOCATION,
    IdemixError,
    ecp2_from_proto,
    ecp2_to_proto,
    ecp_from_proto,
    ecp_to_proto,
    check_issuer_public_key,
    create_cri,
    generate_long_term_revocation_key,
    make_nym,
    new_cred_request,
    new_credential,
    new_issuer_key,
    new_nym_signature,
    new_signature,
    verify_cred_request,
    verify_credential,
    verify_epoch_pk,
    verify_nym_signature,
    verify_signature,
    wbb_keygen,
    wbb_sign,
    wbb_verify,
)

__all__ = [
    "ALG_NO_REVOCATION",
    "IdemixError",
    # ecp2_from_proto dropped from __all__: intra-package only
    # (fabdep dead-export); still importable as a module attribute
    "ecp2_to_proto",
    "ecp_from_proto",
    "ecp_to_proto",
    "check_issuer_public_key",
    "create_cri",
    "generate_long_term_revocation_key",
    "make_nym",
    "new_cred_request",
    "new_credential",
    "new_issuer_key",
    "new_nym_signature",
    "new_signature",
    "verify_cred_request",
    "verify_credential",
    "verify_epoch_pk",
    "verify_nym_signature",
    "verify_signature",
    "wbb_keygen",
    "wbb_sign",
    "wbb_verify",
]
