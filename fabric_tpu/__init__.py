"""fabric-tpu: a TPU-native framework with the capabilities of Hyperledger Fabric.

The reference system (mounted read-only at /root/reference) is Hyperledger
Fabric v2.x: a permissioned blockchain whose commit-time validation pipeline
(batch ECDSA-P256 endorsement verification, signature-policy evaluation, MVCC
read-set conflict checks) is the performance-critical core. This package
rebuilds that system TPU-first:

- ``fabric_tpu.crypto``     -- BCCSP providers: OpenSSL software, batched TPU,
                               PKCS#11 HSM (Cryptoki ctypes); config factory.
- ``fabric_tpu.ops``        -- JAX/XLA kernels: limb bignum, batched P-256
                               ECDSA, FP256BN G1 MSM, Fp12 tower + Ate2 pairing
                               (mesh-shardable).
- ``fabric_tpu.parallel``   -- jax.sharding mesh layer: data/channel-axis
                               sharded verification, RTT-adaptive batcher.
- ``fabric_tpu.policy``     -- signature-policy (cauthdsl) compile + eval.
- ``fabric_tpu.msp``        -- X.509 + Idemix MSPs, cryptogen (MSP + TLS).
- ``fabric_tpu.idemix``     -- BBS+-style scheme, batched verification.
- ``fabric_tpu.validation`` -- batched block validator, native columnar
                               parse, SBE, pluggable validation SPI.
- ``fabric_tpu.ledger``     -- kvledger commit, MVCC (host/device/resident),
                               block+pvtdata stores, snapshots, queries,
                               CouchDB REST mirror.
- ``fabric_tpu.peer`` / ``orderer`` / ``nodes`` / ``cli`` -- channel commit
                               pipeline, solo+raft ordering, composition
                               roots, the seven reference CLIs.
- ``fabric_tpu.gossip``     -- SWIM membership + suspicion probes, push +
                               pull mediators, TLS-bound handshake, pvtdata.
- ``fabric_tpu.comm``       -- gRPC + mTLS (hot cert rotation, per-service
                               limits), interceptors.
- ``fabric_tpu.protos``     -- Fabric-wire-compatible datamodel (protobuf).

Parity contract: per-transaction VALID/INVALID bitmask (uint8
TxValidationCode, reference usable-inter-nal/pkg/txflags/validation_flags.go)
is bit-exact with the reference software path.
"""

__version__ = "0.5.0"
