"""fabric-tpu: a TPU-native framework with the capabilities of Hyperledger Fabric.

The reference system (mounted read-only at /root/reference) is Hyperledger
Fabric v2.x: a permissioned blockchain whose commit-time validation pipeline
(batch ECDSA-P256 endorsement verification, signature-policy evaluation, MVCC
read-set conflict checks) is the performance-critical core. This package
rebuilds that system TPU-first:

- ``fabric_tpu.crypto``     -- BCCSP-style pluggable crypto providers
                               (host software provider + batched TPU provider).
- ``fabric_tpu.ops``        -- JAX/XLA device kernels: limb bignum arithmetic,
                               batched P-256 ECDSA verification.
- ``fabric_tpu.policy``     -- signature-policy (cauthdsl) compile + eval.
- ``fabric_tpu.msp``        -- X.509 identity layer (deserialize/validate/
                               principal matching) + test-crypto generator.
- ``fabric_tpu.ledger``     -- rwsets, versioned state DB, MVCC validation.
- ``fabric_tpu.validation`` -- txflags bitmask + block validator pipeline.
- ``fabric_tpu.protos``     -- Fabric-wire-compatible datamodel (protobuf).

Planned next (SURVEY.md §7 stages 3-6): block store/kvledger commit,
ordering service, device MVCC probes, gossip/state transfer, Idemix.

Parity contract: per-transaction VALID/INVALID bitmask (uint8
TxValidationCode, reference usable-inter-nal/pkg/txflags/validation_flags.go)
is bit-exact with the reference software path.
"""

__version__ = "0.1.0"
