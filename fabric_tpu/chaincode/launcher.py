"""Chaincode process entry point (what the built-in python builder runs).

Loads `chaincode.py` from the built source dir, instantiates its
`chaincode` object (or a `Chaincode` class), and serves the shim stream
against the peer (reference: the chaincode binary's main calling
shim.Start).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys


def load_chaincode(source_dir: str):
    path = os.path.join(source_dir, "chaincode.py")
    if not os.path.exists(path):
        raise SystemExit(f"no chaincode.py in {source_dir}")
    spec = importlib.util.spec_from_file_location("user_chaincode", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["user_chaincode"] = mod
    spec.loader.exec_module(mod)
    cc = getattr(mod, "chaincode", None)
    if cc is None:
        cls = getattr(mod, "Chaincode", None)
        if cls is None:
            raise SystemExit(
                "chaincode.py must define `chaincode` or class `Chaincode`"
            )
        cc = cls()
    return cc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="chaincode-launcher")
    parser.add_argument("--source-dir", required=True)
    parser.add_argument("--peer-address", required=True)
    parser.add_argument("--chaincode-id", required=True)
    args = parser.parse_args(argv)

    from fabric_tpu.chaincode import extshim

    cc = load_chaincode(args.source_dir)
    extshim.start(cc, args.peer_address, args.chaincode_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
