from fabric_tpu.chaincode.shim import (  # noqa: F401
    Chaincode,
    ChaincodeStub,
    Response,
    error_response,
    success,
)
from fabric_tpu.chaincode.support import ChaincodeSupport  # noqa: F401
