"""External chaincode-side shim: connect to the peer, REGISTER, serve
transactions (reference fabric-chaincode-go shim.Start + the handler's
chat protocol, run from the chaincode process).

Usage from a packaged chaincode's entry point:

    from fabric_tpu.chaincode import extshim
    extshim.start(MyChaincode(), peer_address, chaincode_id)
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Iterator, List, Optional, Tuple

from fabric_tpu.chaincode.shim import Response, error_response
from fabric_tpu.comm.server import channel_to
from fabric_tpu.protos import peer_pb2

CCM = peer_pb2.ChaincodeMessage


class ShimError(Exception):
    pass


class ProxyStub:
    """The chaincode-side stub: every state access is a stream round-trip
    (GET_STATE -> RESPONSE), mirroring the reference shim handler."""

    def __init__(self, session: "_Session", tx_id: str, channel_id: str, args: List[bytes]):
        self._session = session
        self.tx_id = tx_id
        self.channel_id = channel_id
        self._args = args
        self._event: Optional[peer_pb2.ChaincodeEvent] = None

    # -- args ------------------------------------------------------------
    def get_args(self) -> List[bytes]:
        return list(self._args)

    def get_function_and_parameters(self) -> Tuple[str, List[str]]:
        args = self.get_args()
        if not args:
            return "", []
        return args[0].decode(), [a.decode() for a in args[1:]]

    # -- state round-trips -------------------------------------------------
    def _roundtrip(self, mtype, payload: bytes) -> bytes:
        return self._session.roundtrip(self, mtype, payload)

    def get_state(self, key: str) -> Optional[bytes]:
        req = peer_pb2.GetState()
        req.key = key
        out = self._roundtrip(CCM.GET_STATE, req.SerializeToString())
        return out or None

    def put_state(self, key: str, value: bytes) -> None:
        req = peer_pb2.PutState()
        req.key = key
        req.value = value
        self._roundtrip(CCM.PUT_STATE, req.SerializeToString())

    def del_state(self, key: str) -> None:
        req = peer_pb2.DelState()
        req.key = key
        self._roundtrip(CCM.DEL_STATE, req.SerializeToString())

    def get_private_data(self, collection: str, key: str) -> Optional[bytes]:
        req = peer_pb2.GetState()
        req.key = key
        req.collection = collection
        out = self._roundtrip(CCM.GET_STATE, req.SerializeToString())
        return out or None

    def put_private_data(self, collection: str, key: str, value: bytes) -> None:
        req = peer_pb2.PutState()
        req.key = key
        req.value = value
        req.collection = collection
        self._roundtrip(CCM.PUT_STATE, req.SerializeToString())

    def get_state_by_range(self, start: str, end: str):
        req = peer_pb2.GetStateByRange()
        req.startKey = start
        req.endKey = end
        raw = self._roundtrip(CCM.GET_STATE_BY_RANGE, req.SerializeToString())
        resp = peer_pb2.QueryResponse()
        resp.ParseFromString(raw)
        out = []
        for r in resp.results:
            doc = json.loads(r.resultBytes)
            out.append((doc["key"], doc["value"].encode()))
        return iter(out)

    def get_query_result(self, query) -> Iterator[Tuple[str, bytes]]:
        req = peer_pb2.GetQueryResult()
        req.query = query if isinstance(query, str) else json.dumps(query)
        raw = self._roundtrip(CCM.GET_QUERY_RESULT, req.SerializeToString())
        resp = peer_pb2.QueryResponse()
        resp.ParseFromString(raw)
        return iter(
            (json.loads(r.resultBytes)["key"], json.loads(r.resultBytes)["value"].encode())
            for r in resp.results
        )

    def _paginated(self, msg_type, req, page_size: int, bookmark: str):
        if page_size <= 0:
            # QueryMetadata(0, "") serializes to zero bytes, which the
            # server would read as "not paginated" — reject here so both
            # deployment modes behave like the in-process shim
            raise ValueError("pageSize must be a positive integer")
        req.metadata = peer_pb2.QueryMetadata(
            pageSize=page_size, bookmark=bookmark
        ).SerializeToString()
        raw = self._roundtrip(msg_type, req.SerializeToString())
        resp = peer_pb2.QueryResponse()
        resp.ParseFromString(raw)
        rm = peer_pb2.QueryResponseMetadata()
        rm.ParseFromString(resp.metadata)
        rows = [
            (json.loads(r.resultBytes)["key"],
             json.loads(r.resultBytes)["value"].encode())
            for r in resp.results
        ]
        return rows, rm.bookmark

    def get_state_by_range_with_pagination(
        self, start: str, end: str, page_size: int, bookmark: str = ""
    ):
        req = peer_pb2.GetStateByRange()
        req.startKey = start
        req.endKey = end
        return self._paginated(CCM.GET_STATE_BY_RANGE, req, page_size, bookmark)

    def get_query_result_with_pagination(
        self, query, page_size: int, bookmark: str = ""
    ):
        req = peer_pb2.GetQueryResult()
        req.query = query if isinstance(query, str) else json.dumps(query)
        return self._paginated(CCM.GET_QUERY_RESULT, req, page_size, bookmark)

    def set_event(self, name: str, payload: bytes) -> None:
        ev = peer_pb2.ChaincodeEvent()
        ev.event_name = name
        ev.payload = payload
        self._event = ev


class _Session:
    """One Register stream connection."""

    def __init__(
        self,
        chaincode,
        peer_address: Optional[str],
        chaincode_id: str,
        root_ca=None,
    ):
        self.chaincode = chaincode
        self.chaincode_id = chaincode_id
        self.out_q: "queue.Queue[Optional[CCM]]" = queue.Queue()
        self.resp_q: "queue.Queue[CCM]" = queue.Queue()
        # ccaas mode serves instead of dialing: no peer channel
        self.channel = (
            channel_to(peer_address, root_ca) if peer_address else None
        )
        self.ready = threading.Event()
        self.stopped = threading.Event()
        # the serve thread when start(block=False) spawned one; stop()
        # reaps it so a torn-down session leaves no reader behind
        self._thread: Optional[threading.Thread] = None

    def _gen(self):
        reg = CCM()
        reg.type = CCM.REGISTER
        ccid = peer_pb2.ChaincodeID()
        ccid.name = self.chaincode_id
        reg.payload = ccid.SerializeToString()
        yield reg
        while True:
            msg = self.out_q.get()
            if msg is None:
                return
            yield msg

    def roundtrip(self, stub: ProxyStub, mtype, payload: bytes) -> bytes:
        msg = CCM()
        msg.type = mtype
        msg.payload = payload
        msg.txid = stub.tx_id
        msg.channel_id = stub.channel_id
        self.out_q.put(msg)
        reply = self.resp_q.get(timeout=30.0)
        if reply.type == CCM.ERROR:
            raise ShimError(reply.payload.decode("utf-8", "replace"))
        return reply.payload

    def _run_tx(self, msg: CCM) -> None:
        inp = peer_pb2.ChaincodeInput()
        inp.ParseFromString(msg.payload)
        stub = ProxyStub(self, msg.txid, msg.channel_id, list(inp.args))
        try:
            if msg.type == CCM.INIT:
                resp = self.chaincode.init(stub)
            else:
                resp = self.chaincode.invoke(stub)
            if not isinstance(resp, Response):
                resp = error_response("chaincode returned no Response")
        except Exception as exc:  # noqa: BLE001 - user chaincode panic
            resp = error_response(f"chaincode failed: {exc}")
        out = CCM()
        out.type = CCM.COMPLETED
        pr = peer_pb2.Response()
        pr.status = resp.status
        pr.message = resp.message
        pr.payload = resp.payload
        out.payload = pr.SerializeToString()
        out.txid = msg.txid
        out.channel_id = msg.channel_id
        if stub._event is not None:
            out.chaincode_event.CopyFrom(stub._event)
        self.out_q.put(out)

    def _dispatch(self, msg: CCM) -> None:
        """One peer->chaincode message (shared by the dial-out Register
        stream and the chaincode-as-a-service Connect stream — the
        protocol is identical, only the transport direction flips)."""
        if msg.type == CCM.REGISTERED:
            return
        if msg.type == CCM.READY:
            self.ready.set()
            return
        if msg.type in (CCM.INIT, CCM.TRANSACTION):
            threading.Thread(  # fablife: disable=thread-unjoined  # per-transaction executor bounded by the tx round-trip: its verdict returns through out_q, and stop()'s out_q None sentinel unblocks the stream it feeds
                target=self._run_tx, args=(msg,), daemon=True
            ).start()
        elif msg.type in (CCM.RESPONSE, CCM.ERROR):
            self.resp_q.put(msg)

    def serve(self) -> None:
        try:
            stream = self.channel.stream_stream(
                "/protos.ChaincodeSupport/Register",
                request_serializer=CCM.SerializeToString,
                response_deserializer=CCM.FromString,
            )(self._gen())
            for msg in stream:
                self._dispatch(msg)
                if self.stopped.is_set():
                    break
        except Exception:
            # stop() closes the channel under the reader: the resulting
            # CANCELLED is the teardown handshake, not an error — but a
            # live session's stream failure must stay loud
            if not self.stopped.is_set():
                raise

    def stop(self) -> None:
        self.stopped.set()
        self.out_q.put(None)
        if self.channel is not None:
            self.channel.close()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)


class CcaasServer:
    """Chaincode-as-a-service: the chaincode HOSTS `protos.Chaincode/
    Connect` and the PEER dials in (reference fabric-chaincode-go
    shim.ChaincodeServer; ccaas external builder). The message protocol
    is byte-identical to the Register stream — REGISTER first from the
    chaincode side, then the normal chat — only who dials whom flips."""

    def __init__(self, chaincode, chaincode_id: str, listen_address: str = "127.0.0.1:0"):
        from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM

        self.chaincode = chaincode
        self.chaincode_id = chaincode_id
        self._sessions: List[_Session] = []
        # appended by gRPC handler threads, pruned by per-session read
        # threads, iterated by stop() (fabdep unguarded-shared-write):
        # an unlocked remove during stop()'s iteration silently skips a
        # session, leaving its reader thread alive after shutdown
        self._sessions_lock = threading.Lock()
        self.server = GRPCServer(listen_address)
        self.server.register(
            "protos.Chaincode",
            {
                "Connect": (
                    STREAM_STREAM,
                    self._connect,
                    CCM.FromString,
                    CCM.SerializeToString,
                )
            },
        )

    def _connect(self, request_iterator, context):
        session = _Session(self.chaincode, None, self.chaincode_id)
        with self._sessions_lock:
            self._sessions.append(session)

        def read_loop():
            try:
                for msg in request_iterator:
                    session._dispatch(msg)
            except Exception:  # noqa: BLE001 - peer went away
                pass
            finally:
                session.stopped.set()
                session.out_q.put(None)
                # finished sessions leave the registry (a reconnecting
                # peer must not accumulate dead queues for the process
                # lifetime)
                with self._sessions_lock:
                    try:
                        self._sessions.remove(session)
                    except ValueError:
                        pass

        rt = threading.Thread(
            target=read_loop, name=f"ccaas-read-{self.chaincode_id}", daemon=True
        )
        session._thread = rt  # session.stop() reaps its reader
        rt.start()
        # response stream: REGISTER first, then the session's replies
        yield from session._gen()

    def start(self) -> str:
        return self.server.start()

    def stop(self) -> None:
        with self._sessions_lock:
            sessions = list(self._sessions)
        for s in sessions:
            s.stop()
        self.server.stop()

    @property
    def addr(self) -> str:
        return self.server.addr


def start(
    chaincode,
    peer_address: str,
    chaincode_id: str,
    block: bool = True,
    root_ca=None,
) -> Optional[_Session]:
    """Connect to the peer's chaincode listener and serve transactions.
    With block=False, serves on a daemon thread and returns the session."""
    session = _Session(chaincode, peer_address, chaincode_id, root_ca)
    if block:
        session.serve()
        return None
    t = threading.Thread(
        target=session.serve, name=f"ccshim-{chaincode_id}", daemon=True
    )
    session._thread = t
    t.start()
    return session
