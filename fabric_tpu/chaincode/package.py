"""Chaincode packaging + installed-package store (reference
`peer lifecycle chaincode package` / `install`: core/chaincode/persistence
+ lifecycle.go InstallChaincode, ChaincodePackageLocator).

Package layout mirrors the reference's lifecycle tgz:

  <label>.tar.gz
  ├── metadata.json    {"type": "python", "label": "<label>"}
  └── code.tar.gz      the chaincode source tree

package_id = "<label>:<sha256-hex of the package bytes>" — identical
derivation to the reference (persistence/chaincode_package.go).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


class PackageError(ValueError):
    pass


def package(
    label: str,
    code_files: Dict[str, bytes],
    cc_type: str = "python",
    path: str = "",
) -> bytes:
    """Build a chaincode package from {relative path: bytes}. `path`
    lands in metadata.json like the reference's platform path field
    (persistence/chaincode_package.go ChaincodePackageMetadata)."""
    if not label or any(c in label for c in ":/\\"):
        raise PackageError(f"invalid label {label!r}")
    code_buf = io.BytesIO()
    with tarfile.open(fileobj=code_buf, mode="w:gz") as tar:
        for name in sorted(code_files):
            data = code_files[name]
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0  # deterministic package bytes
            tar.addfile(info, io.BytesIO(data))
    meta_dict = {"type": cc_type, "label": label}
    if path:
        meta_dict["path"] = path
    meta = json.dumps(meta_dict, sort_keys=True).encode()

    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w:gz") as tar:
        for name, data in (
            ("metadata.json", meta),
            ("code.tar.gz", code_buf.getvalue()),
        ):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            tar.addfile(info, io.BytesIO(data))
    return out.getvalue()


def parse_package(raw: bytes) -> Tuple[dict, Dict[str, bytes]]:
    """Package bytes -> (metadata dict, {path: bytes} of the code tree)."""
    try:
        with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
            names = tar.getnames()
            if "metadata.json" not in names or "code.tar.gz" not in names:
                raise PackageError(
                    f"package must contain metadata.json + code.tar.gz, got {names}"
                )
            meta = json.loads(tar.extractfile("metadata.json").read())
            code_raw = tar.extractfile("code.tar.gz").read()
        files: Dict[str, bytes] = {}
        with tarfile.open(fileobj=io.BytesIO(code_raw), mode="r:gz") as tar:
            for member in tar.getmembers():
                if not member.isfile():
                    continue
                if member.name.startswith(("/", "..")):
                    raise PackageError(f"unsafe path {member.name!r}")
                files[member.name] = tar.extractfile(member).read()
    except (tarfile.TarError, json.JSONDecodeError, KeyError) as e:
        raise PackageError(f"malformed chaincode package: {e}") from e
    if "label" not in meta:
        raise PackageError("metadata.json missing label")
    return meta, files


def package_id(raw: bytes) -> str:
    meta, _files = parse_package(raw)
    return f"{meta['label']}:{hashlib.sha256(raw).hexdigest()}"


@dataclass
class InstalledPackage:
    package_id: str
    label: str
    cc_type: str
    path: str


class PackageStore:
    """Installed chaincodes on the peer's filesystem (reference
    core/chaincode/persistence Store: <ski>/<packageid>.tar.gz)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, pid: str) -> str:
        return os.path.join(self.root, pid.replace(":", ".") + ".tar.gz")

    def install(self, raw: bytes) -> InstalledPackage:
        meta, _files = parse_package(raw)
        pid = package_id(raw)
        path = self._path(pid)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
        return InstalledPackage(pid, meta["label"], meta.get("type", "python"), path)

    def load(self, pid: str) -> bytes:
        path = self._path(pid)
        if not os.path.exists(path):
            raise PackageError(f"package {pid} is not installed")
        with open(path, "rb") as f:
            return f.read()

    def list_installed(self) -> List[InstalledPackage]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".tar.gz"):
                continue
            pid = name[: -len(".tar.gz")]
            # filename uses '.' for ':' — recover label:hash
            label, _, digest = pid.rpartition(".")
            with open(os.path.join(self.root, name), "rb") as f:
                raw = f.read()
            meta, _ = parse_package(raw)
            out.append(
                InstalledPackage(
                    f"{label}:{digest}",
                    meta["label"],
                    meta.get("type", "python"),
                    os.path.join(self.root, name),
                )
            )
        return out
