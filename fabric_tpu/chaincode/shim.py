"""Chaincode programming interface (reference fabric-chaincode-go shim +
core/chaincode/handler.go message loop).

The reference runs chaincode out-of-process behind a gRPC bidi stream;
every GetState/PutState is a stream round-trip handled by
core/chaincode/handler.go (GET_STATE/PUT_STATE/... messages) that calls
back into the tx's simulator. Here the stub calls the simulator directly
— same state semantics, no serialization tax — and the out-of-process
path is provided by the external chaincode server (extcc analog) which
speaks the same stub API over a socket.

A chaincode is any object with ``init(stub) -> Response`` and
``invoke(stub) -> Response``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Protocol, Tuple

from fabric_tpu.ledger.simulator import (
    TxSimulator,
    create_composite_key,
    split_composite_key,
)
from fabric_tpu.protos import peer_pb2

OK = 200
ERROR = 500


@dataclass
class Response:
    status: int
    message: str = ""
    payload: bytes = b""


def success(payload: bytes = b"") -> Response:
    return Response(OK, "", payload)


def error_response(message: str) -> Response:
    return Response(ERROR, message)


class Chaincode(Protocol):
    def init(self, stub: "ChaincodeStub") -> Response: ...

    def invoke(self, stub: "ChaincodeStub") -> Response: ...


class ChaincodeStub:
    """Per-invocation API surface (shim.ChaincodeStubInterface)."""

    def __init__(
        self,
        namespace: str,
        channel_id: str,
        tx_id: str,
        args: List[bytes],
        simulator: TxSimulator,
        creator: bytes = b"",
        transient: Optional[Dict[str, bytes]] = None,
        support: Optional["object"] = None,  # ChaincodeSupport, for cc2cc
    ):
        self._ns = namespace
        self.channel_id = channel_id
        self.tx_id = tx_id
        self._args = args
        self._sim = simulator
        self._creator = creator
        self._transient = dict(transient or {})
        self._support = support
        self._event: Optional[peer_pb2.ChaincodeEvent] = None

    # -- invocation context --
    def get_args(self) -> List[bytes]:
        return list(self._args)

    def get_function_and_parameters(self) -> Tuple[str, List[str]]:
        if not self._args:
            return "", []
        return self._args[0].decode(), [a.decode() for a in self._args[1:]]

    def get_creator(self) -> bytes:
        return self._creator

    def get_transient(self) -> Dict[str, bytes]:
        return dict(self._transient)

    # -- world state --
    def get_state(self, key: str) -> Optional[bytes]:
        return self._sim.get_state(self._ns, key)

    def put_state(self, key: str, value: bytes) -> None:
        self._sim.set_state(self._ns, key, value)

    def del_state(self, key: str) -> None:
        self._sim.delete_state(self._ns, key)

    def get_state_by_range(
        self, start_key: str, end_key: str
    ) -> Iterator[Tuple[str, bytes]]:
        return self._sim.get_state_range_scan_iterator(
            self._ns, start_key, end_key
        )

    def get_state_by_partial_composite_key(
        self, object_type: str, attributes: List[str]
    ) -> Iterator[Tuple[str, bytes]]:
        start = create_composite_key(object_type, attributes)
        return self._sim.get_state_range_scan_iterator(
            self._ns, start, start + "\U0010ffff"
        )

    def get_query_result(self, query) -> Iterator[Tuple[str, bytes]]:
        """Rich selector query over this namespace's JSON state
        (reference shim GetQueryResult -> statecouchdb.go:695; not
        phantom-protected, like the reference)."""
        return iter(self._sim.execute_query(self._ns, query))

    def get_query_result_with_pagination(
        self, query, page_size: int, bookmark: str = ""
    ) -> Tuple[List[Tuple[str, bytes]], str]:
        """Shim GetQueryResultWithPagination: (page, next bookmark);
        read-only transactions only (simulator enforces)."""
        return self._sim.execute_query_with_pagination(
            self._ns, query, page_size, bookmark
        )

    def get_state_by_range_with_pagination(
        self, start_key: str, end_key: str, page_size: int, bookmark: str = ""
    ) -> Tuple[List[Tuple[str, bytes]], str]:
        """Shim GetStateByRangeWithPagination: bookmark = next key."""
        return self._sim.get_state_range_with_pagination(
            self._ns, start_key, end_key, page_size, bookmark
        )

    # -- key-level endorsement (SBE) --
    def set_state_validation_parameter(self, key: str, policy: bytes) -> None:
        self._sim.set_state_metadata(
            self._ns, key, {"VALIDATION_PARAMETER": policy}
        )

    def get_state_validation_parameter(self, key: str) -> Optional[bytes]:
        from fabric_tpu.ledger.mvcc import deserialize_metadata

        meta = deserialize_metadata(self._sim.get_state_metadata(self._ns, key))
        if not meta:
            return None
        return meta.get("VALIDATION_PARAMETER")

    # -- private data --
    def get_private_data(self, collection: str, key: str) -> Optional[bytes]:
        return self._sim.get_private_data(self._ns, collection, key)

    def get_private_data_hash(self, collection: str, key: str) -> Optional[bytes]:
        return self._sim.get_private_data_hash(self._ns, collection, key)

    def put_private_data(self, collection: str, key: str, value: bytes) -> None:
        self._sim.set_private_data(self._ns, collection, key, value)

    def del_private_data(self, collection: str, key: str) -> None:
        self._sim.delete_private_data(self._ns, collection, key)

    # -- composite keys --
    def create_composite_key(self, object_type: str, attributes: List[str]) -> str:
        return create_composite_key(object_type, attributes)

    def split_composite_key(self, key: str) -> Tuple[str, List[str]]:
        return split_composite_key(key)

    # -- events --
    def set_event(self, name: str, payload: bytes) -> None:
        if not name:
            raise ValueError("event name cannot be empty")
        ev = peer_pb2.ChaincodeEvent()
        ev.chaincode_id = self._ns
        ev.tx_id = self.tx_id
        ev.event_name = name
        ev.payload = payload
        self._event = ev

    @property
    def chaincode_event(self) -> Optional[peer_pb2.ChaincodeEvent]:
        return self._event

    # -- chaincode-to-chaincode --
    def invoke_chaincode(
        self, chaincode_name: str, args: List[bytes], channel: str = ""
    ) -> Response:
        """Same-channel cc2cc shares this tx's simulator (writes merge into
        one rwset under the callee's namespace); cross-channel calls are
        read-only against the other channel per the reference's rule
        (handler.go handleInvokeChaincode)."""
        if self._support is None:
            return error_response("chaincode support not wired for cc2cc")
        return self._support.invoke_cc2cc(self, chaincode_name, args, channel)
