"""External chaincode builders + the subprocess launcher (reference
core/container/externalbuilder: exec out-of-process bin/detect, bin/build
and bin/run with the documented directory arguments; plus the built-in
launcher that runs python chaincode packages as real subprocesses which
dial back into the peer's chaincode listener).

Builder contract (externalbuilder.go):

  <builder>/bin/detect  CHAINCODE_SOURCE_DIR CHAINCODE_METADATA_DIR
  <builder>/bin/build   CHAINCODE_SOURCE_DIR CHAINCODE_METADATA_DIR BUILD_OUTPUT_DIR
  <builder>/bin/run     BUILD_OUTPUT_DIR RUN_METADATA_DIR

detect exits 0 to claim a package; run gets RUN_METADATA_DIR/chaincode.json
with {"chaincode_id", "peer_address"} (the reference's connection info).
The built-in python builder needs no bin/ scripts: it extracts code.tar.gz
and runs `python -m fabric_tpu.chaincode.launcher`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

from fabric_tpu.chaincode.package import InstalledPackage, PackageError, parse_package


class BuildError(Exception):
    pass


class ExternalBuilder:
    """One builder directory with bin/{detect,build,run} (reference
    externalbuilder.Detect/Build/Run)."""

    def __init__(self, path: str, name: Optional[str] = None):
        self.path = path
        self.name = name or os.path.basename(path.rstrip("/"))

    def _bin(self, tool: str) -> str:
        return os.path.join(self.path, "bin", tool)

    def _exec(self, tool: str, args: List[str], check: bool) -> bool:
        exe = self._bin(tool)
        if not os.access(exe, os.X_OK):
            if check:
                raise BuildError(f"builder {self.name} lacks bin/{tool}")
            return False
        proc = subprocess.run(
            [exe] + args, capture_output=True, text=True
        )
        if proc.returncode != 0 and check:
            raise BuildError(
                f"{self.name}/bin/{tool} failed rc={proc.returncode}: "
                f"{proc.stderr.strip()}"
            )
        return proc.returncode == 0

    def detect(self, source_dir: str, metadata_dir: str) -> bool:
        return self._exec("detect", [source_dir, metadata_dir], check=False)

    def build(self, source_dir: str, metadata_dir: str, output_dir: str) -> None:
        self._exec("build", [source_dir, metadata_dir, output_dir], check=True)

    def run(self, output_dir: str, run_metadata_dir: str) -> subprocess.Popen:
        exe = self._bin("run")
        if not os.access(exe, os.X_OK):
            raise BuildError(f"builder {self.name} lacks bin/run")
        return subprocess.Popen([exe, output_dir, run_metadata_dir])


class Launcher:
    """Build + run installed packages as real subprocesses (the
    dockercontroller/externalbuilder Router slot in container.go)."""

    def __init__(
        self,
        work_dir: str,
        builders: Optional[List[ExternalBuilder]] = None,
    ):
        self.work_dir = work_dir
        self.builders = list(builders or [])
        self._procs: Dict[str, subprocess.Popen] = {}

    def _dirs(self, pkg: InstalledPackage):
        base = os.path.join(
            self.work_dir, pkg.package_id.replace(":", ".")
        )
        dirs = {
            "source": os.path.join(base, "src"),
            "metadata": os.path.join(base, "metadata"),
            "output": os.path.join(base, "bld"),
            "run_metadata": os.path.join(base, "run"),
        }
        for d in dirs.values():
            os.makedirs(d, exist_ok=True)
        return dirs

    def _materialize(self, pkg: InstalledPackage, dirs) -> dict:
        with open(pkg.path, "rb") as f:
            raw = f.read()
        meta, files = parse_package(raw)
        for rel, data in files.items():
            dest = os.path.join(dirs["source"], rel)
            os.makedirs(os.path.dirname(dest) or dirs["source"], exist_ok=True)
            with open(dest, "wb") as f:
                f.write(data)
        with open(os.path.join(dirs["metadata"], "metadata.json"), "w") as f:
            json.dump(meta, f, sort_keys=True)
        return meta

    def launch(
        self, pkg: InstalledPackage, peer_address: str
    ) -> subprocess.Popen:
        """Build (once) and start the chaincode process; it connects back
        to `peer_address` and REGISTERs as its package-id."""
        existing = self._procs.get(pkg.package_id)
        if existing is not None and existing.poll() is None:
            return existing
        dirs = self._dirs(pkg)
        meta = self._materialize(pkg, dirs)
        with open(
            os.path.join(dirs["run_metadata"], "chaincode.json"), "w"
        ) as f:
            json.dump(
                {"chaincode_id": pkg.package_id, "peer_address": peer_address},
                f,
                sort_keys=True,
            )

        # external builders get first claim (externalbuilder.go detect loop)
        for builder in self.builders:
            if builder.detect(dirs["source"], dirs["metadata"]):
                builder.build(dirs["source"], dirs["metadata"], dirs["output"])
                proc = builder.run(dirs["output"], dirs["run_metadata"])
                self._procs[pkg.package_id] = proc
                return proc

        if meta.get("type", "python") != "python":
            raise BuildError(
                f"no builder claimed package {pkg.package_id} "
                f"(type {meta.get('type')})"
            )
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "fabric_tpu.chaincode.launcher",
                "--source-dir",
                dirs["source"],
                "--peer-address",
                peer_address,
                "--chaincode-id",
                pkg.package_id,
            ],
            env={**os.environ, "PYTHONPATH": _pythonpath()},
        )
        self._procs[pkg.package_id] = proc
        return proc

    def stop(self, package_id: Optional[str] = None) -> None:
        targets = (
            [package_id] if package_id is not None else list(self._procs)
        )
        for pid in targets:
            proc = self._procs.pop(pid, None)
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()


def _pythonpath() -> str:
    """The launcher subprocess must import fabric_tpu (the shim library),
    like reference chaincodes vendoring fabric-chaincode-go."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    current = os.environ.get("PYTHONPATH", "")
    return f"{repo_root}:{current}" if current else repo_root
