"""Peer-side external chaincode runtime (reference core/chaincode/
handler.go message loop + chaincode_support.go Launch/Execute, with the
chaincode running OUT of process and connecting back over gRPC).

An external chaincode process opens the `protos.ChaincodeSupport/Register`
bidi stream, REGISTERs with its package-id, and then serves transactions:
the peer sends INIT/TRANSACTION, the chaincode answers with state-access
messages (GET_STATE/PUT_STATE/... — each applied to the executing tx's
simulator, exactly where the reference's handler.go calls back into the
ledger) and finishes with COMPLETED carrying its Response.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Dict, Iterator, Optional

from fabric_tpu.chaincode.shim import ERROR, OK, Response, error_response
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM
from fabric_tpu.protos import peer_pb2

logger = must_get_logger("chaincode.extserver")

CCM = peer_pb2.ChaincodeMessage
SERVICE_NAME = "protos.ChaincodeSupport"


class ExternalChaincodeError(Exception):
    pass


class _StreamHandler:
    """One connected chaincode process."""

    def __init__(self, name: str):
        self.name = name
        self.out_q: "queue.Queue[Optional[CCM]]" = queue.Queue()
        # one transaction at a time per chaincode stream; the reference
        # multiplexes by txid, the serialization keeps bookkeeping simple
        self._tx_lock = threading.Lock()
        self._stub = None
        self._done: "queue.Queue[CCM]" = queue.Queue()
        self.closed = threading.Event()

    # -- peer -> chaincode -----------------------------------------------
    def execute(self, stub, args, is_init: bool, timeout: float = 60.0) -> Response:
        if self.closed.is_set():
            return error_response(f"chaincode {self.name} disconnected")
        with self._tx_lock:
            self._stub = stub
            inp = peer_pb2.ChaincodeInput()
            for a in args:
                inp.args.append(a)
            msg = CCM()
            msg.type = CCM.INIT if is_init else CCM.TRANSACTION
            msg.payload = inp.SerializeToString()
            msg.txid = stub.tx_id
            msg.channel_id = stub.channel_id
            self.out_q.put(msg)
            try:
                final = self._done.get(timeout=timeout)
            except queue.Empty:
                self.closed.set()
                return error_response(f"chaincode {self.name} timed out")
            finally:
                self._stub = None
            if final.type == CCM.ERROR:
                return Response(ERROR, final.payload.decode("utf-8", "replace"), b"")
            resp = peer_pb2.Response()
            resp.ParseFromString(final.payload)
            out = Response(resp.status, resp.message, resp.payload)
            if final.HasField("chaincode_event"):
                stub.set_event(
                    final.chaincode_event.event_name,
                    final.chaincode_event.payload,
                )
            return out

    # -- chaincode -> peer (the handler.go message loop) -------------------
    def on_message(self, msg: CCM) -> None:
        if msg.type in (CCM.COMPLETED, CCM.ERROR):
            self._done.put(msg)
            return
        if msg.type == CCM.KEEPALIVE:
            return
        stub = self._stub
        reply = CCM()
        reply.txid = msg.txid
        reply.channel_id = msg.channel_id
        if stub is None or msg.txid != stub.tx_id:
            reply.type = CCM.ERROR
            reply.payload = b"no transaction in flight"
            self.out_q.put(reply)
            return
        try:
            reply.type = CCM.RESPONSE
            reply.payload = self._handle_state_op(stub, msg)
        except Exception as exc:  # noqa: BLE001 - simulator errors -> shim error
            reply.type = CCM.ERROR
            reply.payload = str(exc).encode()
        self.out_q.put(reply)

    def _handle_state_op(self, stub, msg: CCM) -> bytes:
        t = msg.type
        if t == CCM.GET_STATE:
            req = peer_pb2.GetState()
            req.ParseFromString(msg.payload)
            if req.collection:
                value = stub.get_private_data(req.collection, req.key)
            else:
                value = stub.get_state(req.key)
            return value or b""
        if t == CCM.GET_PRIVATE_DATA_HASH:
            req = peer_pb2.GetState()
            req.ParseFromString(msg.payload)
            return stub.get_private_data_hash(req.collection, req.key) or b""
        if t == CCM.PUT_STATE:
            req = peer_pb2.PutState()
            req.ParseFromString(msg.payload)
            if req.collection:
                stub.put_private_data(req.collection, req.key, req.value)
            else:
                stub.put_state(req.key, req.value)
            return b""
        if t == CCM.DEL_STATE:
            req = peer_pb2.DelState()
            req.ParseFromString(msg.payload)
            if req.collection:
                stub.del_private_data(req.collection, req.key)
            else:
                stub.del_state(req.key)
            return b""
        if t == CCM.GET_STATE_BY_RANGE:
            req = peer_pb2.GetStateByRange()
            req.ParseFromString(msg.payload)
            out = peer_pb2.QueryResponse()
            if req.metadata:  # paginated form (QueryMetadata present)
                qm = peer_pb2.QueryMetadata()
                qm.ParseFromString(req.metadata)
                rows, bookmark = stub.get_state_by_range_with_pagination(
                    req.startKey, req.endKey, qm.pageSize, qm.bookmark
                )
                rm = peer_pb2.QueryResponseMetadata(
                    fetched_records_count=len(rows), bookmark=bookmark
                )
                out.metadata = rm.SerializeToString()
            else:
                rows = stub.get_state_by_range(req.startKey, req.endKey)
            for key, value in rows:
                r = out.results.add()
                r.resultBytes = json.dumps(
                    {"key": key, "value": value.decode("utf-8", "replace")}
                ).encode()
            out.has_more = False
            return out.SerializeToString()
        if t == CCM.GET_QUERY_RESULT:
            req = peer_pb2.GetQueryResult()
            req.ParseFromString(msg.payload)
            out = peer_pb2.QueryResponse()
            if req.metadata:  # paginated form
                qm = peer_pb2.QueryMetadata()
                qm.ParseFromString(req.metadata)
                rows, bookmark = stub.get_query_result_with_pagination(
                    req.query, qm.pageSize, qm.bookmark
                )
                rm = peer_pb2.QueryResponseMetadata(
                    fetched_records_count=len(rows), bookmark=bookmark
                )
                out.metadata = rm.SerializeToString()
            else:
                rows = stub.get_query_result(req.query)
            for key, value in rows:
                r = out.results.add()
                r.resultBytes = json.dumps(
                    {"key": key, "value": value.decode("utf-8", "replace")}
                ).encode()
            out.has_more = False
            return out.SerializeToString()
        if t == CCM.GET_STATE_METADATA:
            req = peer_pb2.GetStateMetadata()
            req.ParseFromString(msg.payload)
            out = peer_pb2.StateMetadataResult()
            vp = stub.get_state_validation_parameter(req.key)
            if vp is not None:
                e = out.entries.add()
                e.metakey = "VALIDATION_PARAMETER"
                e.value = vp
            return out.SerializeToString()
        if t == CCM.PUT_STATE_METADATA:
            req = peer_pb2.PutStateMetadata()
            req.ParseFromString(msg.payload)
            stub.set_state_validation_parameter(req.key, req.metadata.value)
            return b""
        raise ExternalChaincodeError(f"unsupported shim message type {t}")

    def close(self) -> None:
        self.closed.set()
        self.out_q.put(None)


class ExternalChaincode:
    """Chaincode-protocol adapter over a connected stream handler, so
    ChaincodeSupport.execute treats out-of-process chaincodes uniformly."""

    def __init__(self, handler: _StreamHandler):
        self._handler = handler

    def init(self, stub) -> Response:
        return self._handler.execute(stub, stub.get_args(), is_init=True)

    def invoke(self, stub) -> Response:
        return self._handler.execute(stub, stub.get_args(), is_init=False)


class ChaincodeListener:
    """The peer's chaincode-support gRPC service: accepts Register
    streams from external chaincode processes."""

    def __init__(self):
        self._handlers: Dict[str, _StreamHandler] = {}
        self._cv = threading.Condition()

    def register(self, server: GRPCServer) -> None:
        server.register(
            SERVICE_NAME,
            {
                "Register": (
                    STREAM_STREAM,
                    self._serve,
                    CCM.FromString,
                    CCM.SerializeToString,
                )
            },
        )

    # -- service -----------------------------------------------------------
    def _serve(self, request_iterator, context) -> Iterator[CCM]:
        try:
            first = next(request_iterator)
        except StopIteration:
            return
        if first.type != CCM.REGISTER:
            return
        ccid = peer_pb2.ChaincodeID()
        ccid.ParseFromString(first.payload)
        handler = _StreamHandler(ccid.name)
        with self._cv:
            self._handlers[ccid.name] = handler
            self._cv.notify_all()

        reader = threading.Thread(
            target=self._read_loop,
            args=(handler, request_iterator),
            name=f"cc-read-{ccid.name}",
            daemon=True,
        )
        reader.start()  # fablife: disable=thread-unjoined  # stream-lifetime reader: it exits when the gRPC request_iterator is exhausted at stream teardown, and handler.close() unblocks the write side via the out_q sentinel — the RPC framework owns the stream, so there is no owner stop() to join from

        registered = CCM()
        registered.type = CCM.REGISTERED
        yield registered
        ready = CCM()
        ready.type = CCM.READY
        yield ready
        while True:
            msg = handler.out_q.get()
            if msg is None:
                return
            yield msg

    def _read_loop(self, handler: _StreamHandler, request_iterator) -> None:
        try:
            for msg in request_iterator:
                handler.on_message(msg)
        except Exception as exc:
            logger.debug("chaincode stream ended: %s", exc)
        finally:
            handler.close()
            with self._cv:
                if self._handlers.get(handler.name) is handler:
                    del self._handlers[handler.name]

    # -- chaincode-as-a-service (peer dials the chaincode) -----------------
    def connect_ccaas(
        self,
        address: str,
        timeout: float = 10.0,
        root_ca=None,
        expected_name: Optional[str] = None,
    ) -> str:
        """Dial a chaincode server's `protos.Chaincode/Connect` stream
        (reference ccaas external builder / chaincode_server.go): the
        chaincode sends REGISTER as its first response, then the normal
        chat runs with roles unchanged — only the transport direction is
        reversed.

        `timeout` bounds BOTH channel readiness and the REGISTER
        handshake (a service that accepts the connection but never
        registers must not hang the invoking transaction thread). With
        `expected_name`, the handler registers under that name — the
        lifecycle package-id — regardless of what the server called
        itself (reference convention CORE_CHAINCODE_ID_NAME=package-id),
        so disconnect cleanup removes the right registry entry. The
        channel closes on handshake failure and when the stream dies."""
        import grpc as _grpc

        from fabric_tpu.comm.server import channel_to

        conn = channel_to(address, root_ca)
        out_q: "queue.Queue[Optional[CCM]]" = queue.Queue()

        def outgoing():
            while True:
                m = out_q.get()
                if m is None:
                    return
                yield m

        try:
            _grpc.channel_ready_future(conn).result(timeout=timeout)
            responses = conn.stream_stream(
                "/protos.Chaincode/Connect",
                request_serializer=CCM.SerializeToString,
                response_deserializer=CCM.FromString,
            )(outgoing())
            # bounded REGISTER wait: next() has no deadline of its own;
            # stream errors (UNIMPLEMENTED target, reset) surface through
            # the queue too — a fast failure must not become a full-
            # timeout hang with a misleading message
            first_q: "queue.Queue" = queue.Queue()

            def _take_first():
                try:
                    first_q.put(next(iter(responses), None))
                except Exception as exc:  # noqa: BLE001 - RpcError et al.
                    first_q.put(exc)

            threading.Thread(target=_take_first, daemon=True).start()  # fablife: disable=thread-unjoined  # one-shot iterator poke bounded by first_q.get's timeout below: it exits the moment next() yields or raises, and the gRPC iterator it wraps has no joinable owner
            try:
                first = first_q.get(timeout=timeout)
            except queue.Empty:
                responses.cancel()
                raise ExternalChaincodeError(
                    f"ccaas server at {address}: no REGISTER in {timeout}s"
                )
            if isinstance(first, BaseException):
                raise ExternalChaincodeError(
                    f"ccaas server at {address}: {first}"
                ) from first
            if first is None or first.type != CCM.REGISTER:
                raise ExternalChaincodeError(
                    f"ccaas server at {address} did not REGISTER"
                )
            ccid = peer_pb2.ChaincodeID()
            ccid.ParseFromString(first.payload)
        except Exception:
            out_q.put(None)
            conn.close()
            raise
        name = expected_name or ccid.name
        handler = _StreamHandler(name)
        handler.out_q = out_q  # peer->cc messages ride the request stream
        with self._cv:
            self._handlers[name] = handler
            self._cv.notify_all()
        registered = CCM()
        registered.type = CCM.REGISTERED
        out_q.put(registered)
        ready = CCM()
        ready.type = CCM.READY
        out_q.put(ready)

        def read_then_close():
            try:
                self._read_loop(handler, responses)
            finally:
                conn.close()

        threading.Thread(  # fablife: disable=thread-unjoined  # connection-lifetime reader: it exits when the dialed ccaas stream ends and closes its conn in its own finally — the stream teardown IS the release path, there is no owner stop() to join from
            target=read_then_close,
            name=f"ccaas-read-{name}",
            daemon=True,
        ).start()
        return name

    # -- lookups -----------------------------------------------------------
    def wait_for(self, name: str, timeout: float = 10.0) -> bool:
        with self._cv:
            return self._cv.wait_for(lambda: name in self._handlers, timeout)

    def connected(self, name: str) -> bool:
        with self._cv:
            return name in self._handlers

    def chaincode(self, name: str) -> ExternalChaincode:
        with self._cv:
            handler = self._handlers.get(name)
        if handler is None:
            raise ExternalChaincodeError(f"chaincode {name} is not connected")
        return ExternalChaincode(handler)
