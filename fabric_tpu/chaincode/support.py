"""Chaincode execution support (reference core/chaincode/
chaincode_support.go + handler.go + the launch registry).

The reference launches chaincode containers lazily and multiplexes tx
executions over each chaincode's gRPC stream; system chaincodes run
in-process over inprocstream (core/scc/inprocstream.go). Here every
registered chaincode executes in-process against the tx's simulator, and
cc2cc calls (handler.go handleInvokeChaincode) share the caller's
simulator in the same channel or get a read-only snapshot of another
channel's state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.chaincode.shim import (
    Chaincode,
    ChaincodeStub,
    Response,
    error_response,
)
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.protos import peer_pb2


def _parse_go_duration(value, default: float) -> float:
    """Go duration string ("10s", "500ms", "1m30s") -> seconds; the
    reference ccaas builder's connection.json uses this format. Falls
    back to `default` only for absent/empty values; a malformed string
    also defaults (matching the builder's lenient parse) but never
    silently truncates a valid unit."""
    if not value or not isinstance(value, str):
        return default
    import re

    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001, "us": 1e-6}
    # longest units first: "m" before "ms" would split "500ms" wrong
    parts = re.findall(r"(\d+(?:\.\d+)?)(ms|us|h|m|s)", value)
    if not parts or "".join(n + u for n, u in parts) != value:
        return default
    return sum(float(n) * units[u] for n, u in parts)


class LaunchError(Exception):
    pass


@dataclass
class TxParams:
    """Per-execution context (reference ccprovider.TxParams)."""

    channel_id: str
    tx_id: str
    simulator: TxSimulator
    creator: bytes = b""
    transient: Optional[Dict[str, bytes]] = None


class ChaincodeSupport:
    """Registry + executor. ``state_getter(channel_id)`` resolves another
    channel's committed-state DB for cross-channel cc2cc reads."""

    def __init__(
        self,
        state_getter: Optional[Callable[[str], object]] = None,
        listener=None,  # extserver.ChaincodeListener (peer's cc endpoint)
        launcher=None,  # extbuilder.Launcher (subprocess runner)
        package_store=None,  # package.PackageStore (installed tgz's)
        source_resolver: Optional[Callable[[str, str], Optional[str]]] = None,
        chaincode_address: Optional[Callable[[], str]] = None,
    ):
        self._chaincodes: Dict[str, Chaincode] = {}
        self._system: Dict[str, bool] = {}
        self._state_getter = state_getter
        # out-of-process runtime (reference container.Router +
        # chaincode_support.go Launch): resolve name -> package-id via
        # the channel's lifecycle, launch the installed package as a
        # subprocess if it is not already connected, then execute over
        # its shim stream.
        self.listener = listener
        self.launcher = launcher
        self.package_store = package_store
        self._source_resolver = source_resolver
        self._chaincode_address = chaincode_address

    def register(
        self, name: str, chaincode: Chaincode, system: bool = False
    ) -> None:
        """Launch analog: a registered chaincode is a running one."""
        if name in self._chaincodes:
            raise LaunchError(f"chaincode {name} already registered")
        self._chaincodes[name] = chaincode
        self._system[name] = system

    def is_system_chaincode(self, name: str) -> bool:
        return self._system.get(name, False)

    def launched(self, name: str) -> bool:
        return name in self._chaincodes

    def execute(
        self,
        tx_params: TxParams,
        name: str,
        args: List[bytes],
        is_init: bool = False,
    ) -> Tuple[Response, Optional[peer_pb2.ChaincodeEvent]]:
        """ChaincodeSupport.Execute: run one invocation, return the
        chaincode Response plus its event (at most one per tx)."""
        cc = self._chaincodes.get(name)
        if cc is None:
            cc = self._resolve_external(tx_params.channel_id, name)
        if cc is None:
            raise LaunchError(f"chaincode {name} is not installed/launched")
        stub = ChaincodeStub(
            namespace=name,
            channel_id=tx_params.channel_id,
            tx_id=tx_params.tx_id,
            args=args,
            simulator=tx_params.simulator,
            creator=tx_params.creator,
            transient=tx_params.transient,
            support=self,
        )
        try:
            resp = cc.init(stub) if is_init else cc.invoke(stub)
        except Exception as exc:  # noqa: BLE001 - chaincode panic analog
            return error_response(f"chaincode {name} failed: {exc}"), None
        if not isinstance(resp, Response):
            return error_response(f"chaincode {name} returned no Response"), None
        return resp, stub.chaincode_event

    def _resolve_external(self, channel_id: str, name: str):
        """Out-of-process path: lifecycle package-id -> ensure launched ->
        shim-stream adapter (chaincode_support.go Launch)."""
        if self.listener is None:
            return None
        pid = None
        if self._source_resolver is not None:
            pid = self._source_resolver(channel_id, name)
        if pid is None:
            # a pre-connected chaincode-as-external-service registered
            # under its plain name (extcc analog)
            if self.listener.connected(name):
                return self.listener.chaincode(name)
            return None
        if not self.listener.connected(pid):
            if self.launcher is None or self.package_store is None:
                return None
            from fabric_tpu.chaincode.package import PackageError

            try:
                installed = next(
                    p
                    for p in self.package_store.list_installed()
                    if p.package_id == pid
                )
            except (StopIteration, PackageError):
                raise LaunchError(
                    f"chaincode {name} package {pid} is not installed"
                )
            if installed.cc_type == "ccaas":
                # chaincode-as-a-service (reference ccaas builder): the
                # package carries connection.json and the PEER dials the
                # already-running chaincode server
                self._connect_ccaas(installed, pid)
            else:
                addr = (
                    self._chaincode_address()
                    if self._chaincode_address is not None
                    else None
                )
                if addr is None:
                    raise LaunchError("no chaincode listener address")
                self.launcher.launch(installed, addr)
            if not self.listener.wait_for(pid, timeout=20.0):
                raise LaunchError(
                    f"chaincode {name} ({pid}) did not register in time"
                )
        return self.listener.chaincode(pid)

    def _connect_ccaas(self, installed, pid: str) -> None:
        import json as _json

        from fabric_tpu.chaincode.package import parse_package

        with open(installed.path, "rb") as f:
            _meta, files = parse_package(f.read())
        raw = files.get("connection.json") or files.get("src/connection.json")
        if raw is None:
            raise LaunchError(
                f"ccaas package {pid} has no connection.json"
            )
        try:
            conn_cfg = _json.loads(raw)
            address = conn_cfg["address"]
        except (ValueError, KeyError) as exc:
            raise LaunchError(
                f"ccaas package {pid}: bad connection.json: {exc}"
            ) from exc
        timeout = _parse_go_duration(conn_cfg.get("dial_timeout"), 10.0)
        # reference ccaas schema: tls_required + PEM root_cert
        root_ca = None
        if conn_cfg.get("tls_required"):
            pem = conn_cfg.get("root_cert", "")
            if not pem:
                raise LaunchError(
                    f"ccaas {pid}: tls_required without root_cert"
                )
            root_ca = pem.encode() if isinstance(pem, str) else pem
        try:
            self.listener.connect_ccaas(
                address, timeout=timeout, root_ca=root_ca, expected_name=pid
            )
        except Exception as exc:  # noqa: BLE001 - dial/handshake failure
            raise LaunchError(
                f"ccaas {pid}: cannot connect to {address}: {exc}"
            ) from exc

    def invoke_cc2cc(
        self,
        caller_stub: ChaincodeStub,
        name: str,
        args: List[bytes],
        channel: str = "",
    ) -> Response:
        cc = self._chaincodes.get(name)
        if cc is None:
            try:
                cc = self._resolve_external(
                    channel or caller_stub.channel_id, name
                )
            except LaunchError:
                cc = None
        if cc is None:
            return error_response(f"chaincode {name} is not installed/launched")
        same_channel = not channel or channel == caller_stub.channel_id
        if same_channel:
            sim = caller_stub._sim
        else:
            if self._state_getter is None:
                return error_response(
                    "cross-channel invocation requires a state getter"
                )
            other_db = self._state_getter(channel)
            if other_db is None:
                return error_response(f"channel {channel} not found")
            # Read-only: a throwaway simulator whose results are discarded
            # (handler.go: cross-channel cc2cc rwset is not recorded).
            sim = TxSimulator(other_db, tx_id=caller_stub.tx_id)
        stub = ChaincodeStub(
            namespace=name,
            channel_id=channel or caller_stub.channel_id,
            tx_id=caller_stub.tx_id,
            args=args,
            simulator=sim,
            creator=caller_stub.get_creator(),
            transient=caller_stub.get_transient(),
            support=self,
        )
        try:
            return cc.invoke(stub)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"chaincode {name} failed: {exc}")
