"""Chaincode execution support (reference core/chaincode/
chaincode_support.go + handler.go + the launch registry).

The reference launches chaincode containers lazily and multiplexes tx
executions over each chaincode's gRPC stream; system chaincodes run
in-process over inprocstream (core/scc/inprocstream.go). Here every
registered chaincode executes in-process against the tx's simulator, and
cc2cc calls (handler.go handleInvokeChaincode) share the caller's
simulator in the same channel or get a read-only snapshot of another
channel's state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from fabric_tpu.chaincode.shim import (
    Chaincode,
    ChaincodeStub,
    Response,
    error_response,
)
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.protos import peer_pb2


class LaunchError(Exception):
    pass


@dataclass
class TxParams:
    """Per-execution context (reference ccprovider.TxParams)."""

    channel_id: str
    tx_id: str
    simulator: TxSimulator
    creator: bytes = b""
    transient: Optional[Dict[str, bytes]] = None


class ChaincodeSupport:
    """Registry + executor. ``state_getter(channel_id)`` resolves another
    channel's committed-state DB for cross-channel cc2cc reads."""

    def __init__(
        self,
        state_getter: Optional[Callable[[str], object]] = None,
    ):
        self._chaincodes: Dict[str, Chaincode] = {}
        self._system: Dict[str, bool] = {}
        self._state_getter = state_getter

    def register(
        self, name: str, chaincode: Chaincode, system: bool = False
    ) -> None:
        """Launch analog: a registered chaincode is a running one."""
        if name in self._chaincodes:
            raise LaunchError(f"chaincode {name} already registered")
        self._chaincodes[name] = chaincode
        self._system[name] = system

    def is_system_chaincode(self, name: str) -> bool:
        return self._system.get(name, False)

    def launched(self, name: str) -> bool:
        return name in self._chaincodes

    def execute(
        self,
        tx_params: TxParams,
        name: str,
        args: List[bytes],
        is_init: bool = False,
    ) -> Tuple[Response, Optional[peer_pb2.ChaincodeEvent]]:
        """ChaincodeSupport.Execute: run one invocation, return the
        chaincode Response plus its event (at most one per tx)."""
        cc = self._chaincodes.get(name)
        if cc is None:
            raise LaunchError(f"chaincode {name} is not installed/launched")
        stub = ChaincodeStub(
            namespace=name,
            channel_id=tx_params.channel_id,
            tx_id=tx_params.tx_id,
            args=args,
            simulator=tx_params.simulator,
            creator=tx_params.creator,
            transient=tx_params.transient,
            support=self,
        )
        try:
            resp = cc.init(stub) if is_init else cc.invoke(stub)
        except Exception as exc:  # noqa: BLE001 - chaincode panic analog
            return error_response(f"chaincode {name} failed: {exc}"), None
        if not isinstance(resp, Response):
            return error_response(f"chaincode {name} returned no Response"), None
        return resp, stub.chaincode_event

    def invoke_cc2cc(
        self,
        caller_stub: ChaincodeStub,
        name: str,
        args: List[bytes],
        channel: str = "",
    ) -> Response:
        cc = self._chaincodes.get(name)
        if cc is None:
            return error_response(f"chaincode {name} is not installed/launched")
        same_channel = not channel or channel == caller_stub.channel_id
        if same_channel:
            sim = caller_stub._sim
        else:
            if self._state_getter is None:
                return error_response(
                    "cross-channel invocation requires a state getter"
                )
            other_db = self._state_getter(channel)
            if other_db is None:
                return error_response(f"channel {channel} not found")
            # Read-only: a throwaway simulator whose results are discarded
            # (handler.go: cross-channel cc2cc rwset is not recorded).
            sim = TxSimulator(other_db, tx_id=caller_stub.tx_id)
        stub = ChaincodeStub(
            namespace=name,
            channel_id=channel or caller_stub.channel_id,
            tx_id=caller_stub.tx_id,
            args=args,
            simulator=sim,
            creator=caller_stub.get_creator(),
            transient=caller_stub.get_transient(),
            support=self,
        )
        try:
            return cc.invoke(stub)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"chaincode {name} failed: {exc}")
