"""Typed view over the on-ledger channel config tree (reference
common/channelconfig/bundle.go + {channel,orderer,application,org,msp}
config handlers).

A Bundle is an immutable snapshot of one Config: typed accessors for the
channel/orderer/application values, the per-channel MSPManager assembled
from every org's MSP config value, and the policy Manager tree. Config
blocks swap in a whole new Bundle (reference bundlesource.go) — nothing
here mutates.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from fabric_tpu.channelconfig import capabilities as caps
from fabric_tpu.msp.identity import MSP, MSPConfig, MSPManager, NodeOUs
from fabric_tpu.policy.manager import Manager, build_manager
from fabric_tpu.protos import (
    common_pb2,
    configtx_pb2,
    configuration_pb2,
    msp_config_pb2,
    protoutil,
)

# Config tree group names (reference common/channelconfig/channel.go etc.)
APPLICATION_GROUP = "Application"
ORDERER_GROUP = "Orderer"
CONSORTIUMS_GROUP = "Consortiums"

# Config value names
HASHING_ALGORITHM_KEY = "HashingAlgorithm"
BLOCK_DATA_HASHING_STRUCTURE_KEY = "BlockDataHashingStructure"
ORDERER_ADDRESSES_KEY = "OrdererAddresses"
CONSORTIUM_KEY = "Consortium"
CAPABILITIES_KEY = "Capabilities"
MSP_KEY = "MSP"
ANCHOR_PEERS_KEY = "AnchorPeers"
ACLS_KEY = "ACLs"
ENDPOINTS_KEY = "Endpoints"
CONSENSUS_TYPE_KEY = "ConsensusType"
BATCH_SIZE_KEY = "BatchSize"
BATCH_TIMEOUT_KEY = "BatchTimeout"
CHANNEL_RESTRICTIONS_KEY = "ChannelRestrictions"
CHANNEL_CREATION_POLICY_KEY = "ChannelCreationPolicy"

# MSPConfig.type values (reference msp/msp.go ProviderType)
MSP_TYPE_FABRIC = 0
MSP_TYPE_IDEMIX = 1


class ConfigError(Exception):
    pass


def _value(group: configtx_pb2.ConfigGroup, key: str, msg_cls):
    cv = group.values.get(key)
    if cv is None:
        return None
    return protoutil.unmarshal(msg_cls, cv.value)


def _capability_names(group: configtx_pb2.ConfigGroup) -> List[str]:
    v = _value(group, CAPABILITIES_KEY, configuration_pb2.Capabilities)
    return sorted(v.capabilities) if v is not None else []


@dataclass(frozen=True)
class OrgConfig:
    name: str
    msp_id: str
    anchor_peers: Tuple[Tuple[str, int], ...] = ()
    ordererendpoints: Tuple[str, ...] = ()


@dataclass(frozen=True)
class OrdererConfig:
    consensus_type: str
    consensus_metadata: bytes
    consensus_state: int
    batch_size_max_messages: int
    batch_size_absolute_max_bytes: int
    batch_size_preferred_max_bytes: int
    batch_timeout: str
    orgs: Tuple[OrgConfig, ...]
    capabilities: caps.OrdererCapabilities
    max_channels: int = 0


@dataclass(frozen=True)
class ApplicationConfig:
    orgs: Tuple[OrgConfig, ...]
    capabilities: caps.ApplicationCapabilities
    acls: Dict[str, str] = field(default_factory=dict)


def fabric_msp_config_to_local(cfg: msp_config_pb2.FabricMSPConfig) -> MSPConfig:
    node_ous = NodeOUs()
    if cfg.HasField("fabric_node_ous"):
        f = cfg.fabric_node_ous
        node_ous = NodeOUs(
            enable=f.enable,
            client_ou=f.client_ou_identifier.organizational_unit_identifier
            or "client",
            peer_ou=f.peer_ou_identifier.organizational_unit_identifier or "peer",
            admin_ou=f.admin_ou_identifier.organizational_unit_identifier
            or "admin",
            orderer_ou=f.orderer_ou_identifier.organizational_unit_identifier
            or "orderer",
        )
    return MSPConfig(
        msp_id=cfg.name,
        root_certs=list(cfg.root_certs),
        intermediate_certs=list(cfg.intermediate_certs),
        admins=list(cfg.admins),
        revocation_list=list(cfg.revocation_list),
        node_ous=node_ous,
    )


def local_msp_config_to_proto(cfg: MSPConfig) -> msp_config_pb2.MSPConfig:
    f = msp_config_pb2.FabricMSPConfig()
    f.name = cfg.msp_id
    f.root_certs.extend(cfg.root_certs)
    f.intermediate_certs.extend(cfg.intermediate_certs)
    f.admins.extend(cfg.admins)
    f.revocation_list.extend(cfg.revocation_list)
    if cfg.node_ous.enable:
        f.fabric_node_ous.enable = True
        f.fabric_node_ous.client_ou_identifier.organizational_unit_identifier = (
            cfg.node_ous.client_ou
        )
        f.fabric_node_ous.peer_ou_identifier.organizational_unit_identifier = (
            cfg.node_ous.peer_ou
        )
        f.fabric_node_ous.admin_ou_identifier.organizational_unit_identifier = (
            cfg.node_ous.admin_ou
        )
        f.fabric_node_ous.orderer_ou_identifier.organizational_unit_identifier = (
            cfg.node_ous.orderer_ou
        )
    out = msp_config_pb2.MSPConfig()
    out.type = MSP_TYPE_FABRIC
    out.config = f.SerializeToString()
    return out


def _parse_org(name: str, group: configtx_pb2.ConfigGroup, provider) -> Tuple[OrgConfig, Optional[MSP]]:
    msp_cfg = _value(group, MSP_KEY, msp_config_pb2.MSPConfig)
    msp_obj = None
    msp_id = name
    if msp_cfg is not None and msp_cfg.type == MSP_TYPE_FABRIC:
        fabric_cfg = protoutil.unmarshal(
            msp_config_pb2.FabricMSPConfig, msp_cfg.config
        )
        local = fabric_msp_config_to_local(fabric_cfg)
        msp_id = local.msp_id
        msp_obj = MSP(local, provider)
    anchors: Tuple[Tuple[str, int], ...] = ()
    ap = _value(group, ANCHOR_PEERS_KEY, configuration_pb2.AnchorPeers)
    if ap is not None:
        anchors = tuple((p.host, p.port) for p in ap.anchor_peers)
    endpoints: Tuple[str, ...] = ()
    ep = _value(group, ENDPOINTS_KEY, configuration_pb2.OrdererAddresses)
    if ep is not None:
        endpoints = tuple(ep.addresses)
    return OrgConfig(name, msp_id, anchors, endpoints), msp_obj


class Bundle:
    """Immutable typed snapshot of one channel Config."""

    def __init__(
        self,
        channel_id: str,
        config: configtx_pb2.Config,
        provider=None,
    ):
        if not config.HasField("channel_group"):
            raise ConfigError("config must contain a channel group")
        if provider is None:
            from fabric_tpu.crypto.bccsp import default_provider

            provider = default_provider()
        self.channel_id = channel_id
        self.config = config
        root = config.channel_group

        # -- channel-level values ------------------------------------------
        ha = _value(root, HASHING_ALGORITHM_KEY, configuration_pb2.HashingAlgorithm)
        self.hashing_algorithm = ha.name if ha is not None else "SHA256"
        if self.hashing_algorithm not in ("SHA256", "SHA2_256"):
            raise ConfigError(
                f"unsupported hashing algorithm {self.hashing_algorithm}"
            )
        bdhs = _value(
            root,
            BLOCK_DATA_HASHING_STRUCTURE_KEY,
            configuration_pb2.BlockDataHashingStructure,
        )
        self.block_data_hashing_width = bdhs.width if bdhs is not None else 2**32 - 1
        oa = _value(root, ORDERER_ADDRESSES_KEY, configuration_pb2.OrdererAddresses)
        self.orderer_addresses = list(oa.addresses) if oa is not None else []
        cons = _value(root, CONSORTIUM_KEY, configuration_pb2.Consortium)
        self.consortium_name = cons.name if cons is not None else ""
        self.channel_capabilities = caps.ChannelCapabilities(_capability_names(root))

        msps: List[MSP] = []

        # -- orderer group --------------------------------------------------
        self.orderer: Optional[OrdererConfig] = None
        og = root.groups.get(ORDERER_GROUP)
        if og is not None:
            ct = _value(og, CONSENSUS_TYPE_KEY, configuration_pb2.ConsensusType)
            bs = _value(og, BATCH_SIZE_KEY, configuration_pb2.BatchSize)
            bt = _value(og, BATCH_TIMEOUT_KEY, configuration_pb2.BatchTimeout)
            cr = _value(
                og, CHANNEL_RESTRICTIONS_KEY, configuration_pb2.ChannelRestrictions
            )
            orgs = []
            for name, sub in sorted(og.groups.items()):
                org, msp_obj = _parse_org(name, sub, provider)
                orgs.append(org)
                if msp_obj is not None:
                    msps.append(msp_obj)
            self.orderer = OrdererConfig(
                consensus_type=ct.type if ct is not None else "solo",
                consensus_metadata=ct.metadata if ct is not None else b"",
                consensus_state=ct.state if ct is not None else 0,
                batch_size_max_messages=bs.max_message_count if bs else 500,
                batch_size_absolute_max_bytes=bs.absolute_max_bytes
                if bs
                else 10 * 1024 * 1024,
                batch_size_preferred_max_bytes=bs.preferred_max_bytes
                if bs
                else 2 * 1024 * 1024,
                batch_timeout=bt.timeout if bt is not None else "2s",
                orgs=tuple(orgs),
                capabilities=caps.OrdererCapabilities(_capability_names(og)),
                max_channels=cr.max_count if cr is not None else 0,
            )

        # -- application group ----------------------------------------------
        self.application: Optional[ApplicationConfig] = None
        ag = root.groups.get(APPLICATION_GROUP)
        if ag is not None:
            orgs = []
            for name, sub in sorted(ag.groups.items()):
                org, msp_obj = _parse_org(name, sub, provider)
                orgs.append(org)
                if msp_obj is not None:
                    msps.append(msp_obj)
            acls: Dict[str, str] = {}
            av = _value(ag, ACLS_KEY, configuration_pb2.ACLs)
            if av is not None:
                acls = {k: v.policy_ref for k, v in av.acls.items()}
            self.application = ApplicationConfig(
                orgs=tuple(orgs),
                capabilities=caps.ApplicationCapabilities(_capability_names(ag)),
                acls=acls,
            )

        # -- consortiums (system channel only) ------------------------------
        self.consortiums: Dict[str, List[OrgConfig]] = {}
        cg = root.groups.get(CONSORTIUMS_GROUP)
        if cg is not None:
            for cname, consortium in sorted(cg.groups.items()):
                corgs = []
                for name, sub in sorted(consortium.groups.items()):
                    org, msp_obj = _parse_org(name, sub, provider)
                    corgs.append(org)
                    if msp_obj is not None:
                        msps.append(msp_obj)
                self.consortiums[cname] = corgs

        self.msp_manager = MSPManager(msps)
        self.policy_manager: Manager = build_manager(
            "Channel", root, self.msp_manager, provider
        )

    # convenience ----------------------------------------------------------
    @property
    def sequence(self) -> int:
        return self.config.sequence

    def acl_policy_ref(self, resource: str, default: str) -> str:
        if self.application is not None and resource in self.application.acls:
            ref = self.application.acls[resource]
            return ref if ref.startswith("/") else f"/Channel/Application/{ref}"
        return default


def bundle_from_envelope(env: common_pb2.Envelope, provider=None) -> Bundle:
    """Extract a Bundle from a CONFIG envelope (e.g. from a genesis block)."""
    payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
    chdr = protoutil.unmarshal(
        common_pb2.ChannelHeader, payload.header.channel_header
    )
    cenv = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
    return Bundle(chdr.channel_id, cenv.config, provider)


def bundle_from_genesis_block(block: common_pb2.Block, provider=None) -> Bundle:
    env = protoutil.get_envelope_from_block_data(block.data.data[0])
    return bundle_from_envelope(env, provider)
