"""Genesis/config-tx generation (reference cmd/configtxgen +
usable-inter-nal/configtxgen/encoder/encoder.go).

Profiles are plain dataclasses (the reference reads configtx.yaml into
equivalent structs). The encoder builds the ConfigGroup tree with the
reference's default implicit-meta channel policies and per-org signature
policies, then wraps it as a genesis block or a channel-creation
ConfigUpdate envelope.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from fabric_tpu.channelconfig import bundle as bundlemod
from fabric_tpu.msp.identity import MSPConfig
from fabric_tpu.policy import ast as policy_ast
from fabric_tpu.policy import proto_convert
from fabric_tpu.protos import (
    common_pb2,
    configtx_pb2,
    configuration_pb2,
    policies_pb2,
    protoutil,
)

ADMINS_POLICY_KEY = "Admins"
READERS_POLICY_KEY = "Readers"
WRITERS_POLICY_KEY = "Writers"
ENDORSEMENT_POLICY_KEY = "Endorsement"
LIFECYCLE_ENDORSEMENT_POLICY_KEY = "LifecycleEndorsement"
BLOCK_VALIDATION_POLICY_KEY = "BlockValidation"


@dataclass
class OrganizationProfile:
    name: str
    msp: MSPConfig
    anchor_peers: List[Tuple[str, int]] = field(default_factory=list)
    orderer_endpoints: List[str] = field(default_factory=list)
    # policy name -> policy DSL string; defaults derived from msp_id if empty
    policies: Dict[str, str] = field(default_factory=dict)


@dataclass
class ApplicationProfile:
    organizations: List[OrganizationProfile] = field(default_factory=list)
    capabilities: List[str] = field(default_factory=lambda: ["V2_0"])
    acls: Dict[str, str] = field(default_factory=dict)


@dataclass
class OrdererProfile:
    orderer_type: str = "solo"
    addresses: List[str] = field(default_factory=list)
    batch_timeout: str = "2s"
    max_message_count: int = 500
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024
    organizations: List[OrganizationProfile] = field(default_factory=list)
    capabilities: List[str] = field(default_factory=lambda: ["V2_0"])
    raft_consenters: List[Tuple[str, int, bytes, bytes]] = field(
        default_factory=list
    )  # (host, port, client_tls_cert, server_tls_cert)


@dataclass
class Profile:
    """One configtx.yaml profile."""

    consortium: str = ""
    application: Optional[ApplicationProfile] = None
    orderer: Optional[OrdererProfile] = None
    consortiums: Dict[str, List[OrganizationProfile]] = field(default_factory=dict)
    capabilities: List[str] = field(default_factory=lambda: ["V2_0"])
    policies: Dict[str, str] = field(default_factory=dict)


class EncoderError(Exception):
    pass


def _implicit_meta(rule: int, sub_policy: str) -> policies_pb2.Policy:
    meta = policies_pb2.ImplicitMetaPolicy()
    meta.rule = rule
    meta.sub_policy = sub_policy
    out = policies_pb2.Policy()
    out.type = policies_pb2.Policy.IMPLICIT_META
    out.value = meta.SerializeToString()
    return out


def _signature_policy(dsl: str) -> policies_pb2.Policy:
    env = policy_ast.from_dsl(dsl)
    out = policies_pb2.Policy()
    out.type = policies_pb2.Policy.SIGNATURE
    out.value = proto_convert.marshal_envelope(env)
    return out


def _add_policy(
    group: configtx_pb2.ConfigGroup,
    name: str,
    policy: policies_pb2.Policy,
    mod_policy: str = ADMINS_POLICY_KEY,
) -> None:
    cp = group.policies[name]
    cp.policy.CopyFrom(policy)
    cp.mod_policy = mod_policy


def _add_value(
    group: configtx_pb2.ConfigGroup,
    name: str,
    msg,
    mod_policy: str = ADMINS_POLICY_KEY,
) -> None:
    cv = group.values[name]
    cv.value = msg.SerializeToString()
    cv.mod_policy = mod_policy


def _implicit_meta_defaults(group: configtx_pb2.ConfigGroup) -> None:
    R = policies_pb2.ImplicitMetaPolicy
    _add_policy(group, READERS_POLICY_KEY, _implicit_meta(R.ANY, READERS_POLICY_KEY))
    _add_policy(group, WRITERS_POLICY_KEY, _implicit_meta(R.ANY, WRITERS_POLICY_KEY))
    _add_policy(
        group, ADMINS_POLICY_KEY, _implicit_meta(R.MAJORITY, ADMINS_POLICY_KEY)
    )


def _capabilities_value(names: Sequence[str]) -> configuration_pb2.Capabilities:
    v = configuration_pb2.Capabilities()
    for n in names:
        v.capabilities[n].SetInParent()
    return v


def new_org_group(
    org: OrganizationProfile, with_anchors: bool = False, orderer_org: bool = False
) -> configtx_pb2.ConfigGroup:
    """Reference encoder.NewOrgConfigGroup: MSP value + org-scoped
    Readers/Writers/Admins (+Endorsement) signature policies."""
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    msp_id = org.msp.msp_id
    defaults = {
        READERS_POLICY_KEY: f"OR('{msp_id}.member')",
        WRITERS_POLICY_KEY: f"OR('{msp_id}.member')",
        ADMINS_POLICY_KEY: f"OR('{msp_id}.admin')",
    }
    if not orderer_org:
        defaults[ENDORSEMENT_POLICY_KEY] = f"OR('{msp_id}.member')"
    defaults.update(org.policies)
    for name, dsl in defaults.items():
        _add_policy(g, name, _signature_policy(dsl))
    _add_value(g, bundlemod.MSP_KEY, bundlemod.local_msp_config_to_proto(org.msp))
    if with_anchors and org.anchor_peers:
        ap = configuration_pb2.AnchorPeers()
        for host, port in org.anchor_peers:
            p = ap.anchor_peers.add()
            p.host = host
            p.port = port
        _add_value(g, bundlemod.ANCHOR_PEERS_KEY, ap)
    if orderer_org and org.orderer_endpoints:
        ep = configuration_pb2.OrdererAddresses()
        ep.addresses.extend(org.orderer_endpoints)
        _add_value(g, bundlemod.ENDPOINTS_KEY, ep)
    return g


def new_application_group(profile: ApplicationProfile) -> configtx_pb2.ConfigGroup:
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    _implicit_meta_defaults(g)
    R = policies_pb2.ImplicitMetaPolicy
    _add_policy(
        g,
        ENDORSEMENT_POLICY_KEY,
        _implicit_meta(R.MAJORITY, ENDORSEMENT_POLICY_KEY),
    )
    _add_policy(
        g,
        LIFECYCLE_ENDORSEMENT_POLICY_KEY,
        _implicit_meta(R.MAJORITY, ENDORSEMENT_POLICY_KEY),
    )
    if profile.capabilities:
        _add_value(
            g, bundlemod.CAPABILITIES_KEY, _capabilities_value(profile.capabilities)
        )
    if profile.acls:
        acls = configuration_pb2.ACLs()
        for k, ref in profile.acls.items():
            acls.acls[k].policy_ref = ref
        _add_value(g, bundlemod.ACLS_KEY, acls)
    for org in profile.organizations:
        g.groups[org.name].CopyFrom(new_org_group(org, with_anchors=True))
    return g


def new_orderer_group(profile: OrdererProfile) -> configtx_pb2.ConfigGroup:
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    _implicit_meta_defaults(g)
    R = policies_pb2.ImplicitMetaPolicy
    _add_policy(
        g,
        BLOCK_VALIDATION_POLICY_KEY,
        _implicit_meta(R.ANY, WRITERS_POLICY_KEY),
    )
    ct = configuration_pb2.ConsensusType()
    ct.type = profile.orderer_type
    if profile.orderer_type == "etcdraft":
        meta = configuration_pb2.RaftConfigMetadata()
        for host, port, client_cert, server_cert in profile.raft_consenters:
            c = meta.consenters.add()
            c.host = host
            c.port = port
            c.client_tls_cert = client_cert
            c.server_tls_cert = server_cert
        meta.options.tick_interval = "500ms"
        meta.options.election_tick = 10
        meta.options.heartbeat_tick = 1
        meta.options.max_inflight_blocks = 5
        meta.options.snapshot_interval_size = 16 * 1024 * 1024
        ct.metadata = meta.SerializeToString()
    _add_value(g, bundlemod.CONSENSUS_TYPE_KEY, ct)
    bs = configuration_pb2.BatchSize()
    bs.max_message_count = profile.max_message_count
    bs.absolute_max_bytes = profile.absolute_max_bytes
    bs.preferred_max_bytes = profile.preferred_max_bytes
    _add_value(g, bundlemod.BATCH_SIZE_KEY, bs)
    bt = configuration_pb2.BatchTimeout()
    bt.timeout = profile.batch_timeout
    _add_value(g, bundlemod.BATCH_TIMEOUT_KEY, bt)
    if profile.capabilities:
        _add_value(
            g, bundlemod.CAPABILITIES_KEY, _capabilities_value(profile.capabilities)
        )
    for org in profile.organizations:
        g.groups[org.name].CopyFrom(new_org_group(org, orderer_org=True))
    return g


def new_channel_group(profile: Profile) -> configtx_pb2.ConfigGroup:
    """Reference encoder.NewChannelGroup."""
    root = configtx_pb2.ConfigGroup()
    root.mod_policy = ADMINS_POLICY_KEY
    _implicit_meta_defaults(root)
    ha = configuration_pb2.HashingAlgorithm()
    ha.name = "SHA256"
    _add_value(root, bundlemod.HASHING_ALGORITHM_KEY, ha)
    bdhs = configuration_pb2.BlockDataHashingStructure()
    bdhs.width = 2**32 - 1
    _add_value(root, bundlemod.BLOCK_DATA_HASHING_STRUCTURE_KEY, bdhs)
    if profile.orderer is not None and profile.orderer.addresses:
        oa = configuration_pb2.OrdererAddresses()
        oa.addresses.extend(profile.orderer.addresses)
        _add_value(root, bundlemod.ORDERER_ADDRESSES_KEY, oa)
    if profile.consortium:
        cons = configuration_pb2.Consortium()
        cons.name = profile.consortium
        _add_value(root, bundlemod.CONSORTIUM_KEY, cons)
    if profile.capabilities:
        _add_value(
            root, bundlemod.CAPABILITIES_KEY, _capabilities_value(profile.capabilities)
        )
    if profile.orderer is not None:
        root.groups[bundlemod.ORDERER_GROUP].CopyFrom(
            new_orderer_group(profile.orderer)
        )
    if profile.application is not None:
        root.groups[bundlemod.APPLICATION_GROUP].CopyFrom(
            new_application_group(profile.application)
        )
    if profile.consortiums:
        cg = configtx_pb2.ConfigGroup()
        cg.mod_policy = "/Channel/Orderer/Admins"
        for cname, orgs in profile.consortiums.items():
            consortium = configtx_pb2.ConfigGroup()
            consortium.mod_policy = "/Channel/Orderer/Admins"
            ccp = configtx_pb2.ConfigPolicy()
            ccp.policy.CopyFrom(
                _implicit_meta(policies_pb2.ImplicitMetaPolicy.ANY, ADMINS_POLICY_KEY)
            )
            consortium.values[bundlemod.CHANNEL_CREATION_POLICY_KEY].value = (
                ccp.policy.SerializeToString()
            )
            for org in orgs:
                consortium.groups[org.name].CopyFrom(new_org_group(org))
            cg.groups[cname].CopyFrom(consortium)
        root.groups[bundlemod.CONSORTIUMS_GROUP].CopyFrom(cg)
    return root


def new_config(profile: Profile, sequence: int = 0) -> configtx_pb2.Config:
    cfg = configtx_pb2.Config()
    cfg.sequence = sequence
    cfg.channel_group.CopyFrom(new_channel_group(profile))
    return cfg


def genesis_block(profile: Profile, channel_id: str) -> common_pb2.Block:
    """Reference encoder.Bootstrapper.GenesisBlockForChannel: block 0 holds
    one CONFIG envelope carrying the full Config."""
    cenv = configtx_pb2.ConfigEnvelope()
    cenv.config.CopyFrom(new_config(profile))

    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.CONFIG, channel_id)
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = common_pb2.SignatureHeader().SerializeToString()
    payload.data = cenv.SerializeToString()

    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()

    block = protoutil.new_block(0, b"")
    block.data.data.append(env.SerializeToString())
    protoutil.seal_block(block)
    return block


def channel_creation_config_update(
    channel_id: str, consortium: str, application: ApplicationProfile
) -> configtx_pb2.ConfigUpdate:
    """Reference encoder.NewChannelCreateConfigUpdate (template form): the
    read set pins consortium + org groups at version 0; the write set
    bumps the Application group to version 1 with the full app config."""
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = channel_id

    cons = configuration_pb2.Consortium()
    cons.name = consortium
    update.read_set.values[bundlemod.CONSORTIUM_KEY].value = cons.SerializeToString()
    rs_app = update.read_set.groups[bundlemod.APPLICATION_GROUP]
    for org in application.organizations:
        rs_app.groups[org.name].SetInParent()

    update.write_set.values[bundlemod.CONSORTIUM_KEY].value = (
        cons.SerializeToString()
    )
    ws_app = update.write_set.groups[bundlemod.APPLICATION_GROUP]
    ws_app.CopyFrom(new_application_group(application))
    ws_app.version = 1
    return update
