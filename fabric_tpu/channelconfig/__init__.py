"""On-ledger channel configuration (reference common/channelconfig +
common/configtx + common/capabilities + configtxgen encoder)."""

from fabric_tpu.channelconfig.bundle import (
    Bundle,
    ConfigError,
    bundle_from_envelope,
    bundle_from_genesis_block,
)
from fabric_tpu.channelconfig.configtx import ConfigTxError, Validator
from fabric_tpu.channelconfig.encoder import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
    new_channel_group,
    new_config,
)

# ConfigError/bundle_from_envelope/new_channel_group are reachable as
# module attributes but no longer claimed in __all__: nothing outside
# this package references them (fabdep dead-export)
__all__ = [
    "ApplicationProfile",
    "Bundle",
    "ConfigTxError",
    "OrdererProfile",
    "OrganizationProfile",
    "Profile",
    "Validator",
    "bundle_from_genesis_block",
    "genesis_block",
    "new_config",
]
