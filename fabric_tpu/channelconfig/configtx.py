"""Config transaction validation (reference common/configtx/validator.go,
update.go).

A ConfigUpdate names a read set (elements whose versions must match the
current config) and a write set (the new state). The delta = write-set
elements whose version advanced; each delta element must advance by
exactly one and be authorized by the MOD_POLICY of the existing element
(for new elements: the enclosing group's mod policy), evaluated over the
ConfigSignatures. The result is the current config with the write set
merged and sequence+1.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fabric_tpu.policy.manager import Manager, PolicyError, SignedData
from fabric_tpu.protos import common_pb2, configtx_pb2, protoutil


class ConfigTxError(Exception):
    pass


# ---------------------------------------------------------------------------
# Flatten the config tree into path-keyed elements (update.go works on
# "scoped values"; paths here are ("groups", name, ...) tuples).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Elem:
    kind: str  # "group" | "value" | "policy"
    path: Tuple[str, ...]  # group path from root (excluding the root)
    name: str  # "" for the group itself
    version: int
    mod_policy: str
    data: bytes  # serialized payload for equality checks


def _flatten(group: configtx_pb2.ConfigGroup, path: Tuple[str, ...] = ()) -> Dict:
    out: Dict[Tuple[str, str, Tuple[str, ...]], _Elem] = {}
    out[("group", "", path)] = _Elem(
        "group", path, "", group.version, group.mod_policy, b""
    )
    for name, cv in group.values.items():
        out[("value", name, path)] = _Elem(
            "value", path, name, cv.version, cv.mod_policy, cv.value
        )
    for name, cp in group.policies.items():
        out[("policy", name, path)] = _Elem(
            "policy",
            path,
            name,
            cp.version,
            cp.mod_policy,
            cp.policy.SerializeToString(),
        )
    for name, sub in group.groups.items():
        out.update(_flatten(sub, path + (name,)))
    return out


def _group_at(root: configtx_pb2.ConfigGroup, path: Tuple[str, ...]):
    g = root
    for seg in path:
        if seg not in g.groups:
            return None
        g = g.groups[seg]
    return g


def _resolve_mod_policy(mod_policy: str, path: Tuple[str, ...]) -> str:
    """Relative mod policies resolve against the element's group path
    (reference policies/util.go / validator relativity rules)."""
    if not mod_policy:
        return ""
    if mod_policy.startswith("/"):
        return mod_policy
    return "/" + "/".join(("Channel",) + path + (mod_policy,))


class Validator:
    """Per-channel config state machine (reference configtx.ValidatorImpl)."""

    def __init__(
        self,
        channel_id: str,
        config: configtx_pb2.Config,
        policy_manager: Optional[Manager] = None,
    ):
        if not config.HasField("channel_group"):
            raise ConfigTxError("config did not contain a channel group")
        self.channel_id = channel_id
        self.config = config
        self.policy_manager = policy_manager

    @property
    def sequence(self) -> int:
        return self.config.sequence

    def propose_config_update(
        self, update_env: common_pb2.Envelope
    ) -> configtx_pb2.ConfigEnvelope:
        """CONFIG_UPDATE envelope -> the resulting ConfigEnvelope, or raise."""
        payload = protoutil.unmarshal(common_pb2.Payload, update_env.payload)
        cue = protoutil.unmarshal(configtx_pb2.ConfigUpdateEnvelope, payload.data)
        return self.propose_config_update_envelope(cue, last_update=update_env)

    def propose_config_update_envelope(
        self,
        cue: configtx_pb2.ConfigUpdateEnvelope,
        last_update: Optional[common_pb2.Envelope] = None,
    ) -> configtx_pb2.ConfigEnvelope:
        update = protoutil.unmarshal(configtx_pb2.ConfigUpdate, cue.config_update)
        if update.channel_id != self.channel_id:
            raise ConfigTxError(
                f"update is for channel {update.channel_id!r}, not "
                f"{self.channel_id!r}"
            )

        current = _flatten(self.config.channel_group)
        read_set = _flatten(update.read_set)
        write_set = _flatten(update.write_set)

        # 1. verify read set versions (update.go verifyReadSet)
        for key, elem in read_set.items():
            cur = current.get(key)
            if cur is None:
                raise ConfigTxError(
                    f"existing config does not contain element for "
                    f"{key[0]} {'/'.join(key[2] + (key[1],))} but was in the read set"
                )
            if cur.version != elem.version:
                raise ConfigTxError(
                    f"readset expected key {'/'.join(key[2] + (key[1],))} at "
                    f"version {elem.version}, but got version {cur.version}"
                )

        # 2. compute the delta set (update.go computeDeltaSet)
        delta: Dict[Tuple[str, str, Tuple[str, ...]], _Elem] = {}
        for key, elem in write_set.items():
            read = read_set.get(key)
            if read is not None and read.version == elem.version:
                continue  # unmodified carry-over
            delta[key] = elem

        # 3. verify the delta set + authorize (update.go verifyDeltaSet)
        signed_data = []
        for s in cue.signatures:
            data, creator = _config_update_signed_data(cue, s)
            signed_data.append(SignedData(data, creator, s.signature))
        for key, elem in delta.items():
            cur = current.get(key)
            expected = (cur.version + 1) if cur is not None else 0
            if elem.version != expected:
                raise ConfigTxError(
                    f"attempt to set key {'/'.join(key[2] + (key[1],))} to "
                    f"version {elem.version}, but key is at version "
                    f"{cur.version if cur else '<absent>'}"
                )
            mod_policy = (
                cur.mod_policy
                if cur is not None
                else self._new_item_mod_policy(key, write_set, current)
            )
            self._authorize(mod_policy, key, signed_data)

        # 4. apply: overlay ONLY the delta onto the current config (reference
        # computeUpdateResult, update.go:192-203 — same-version write-set
        # content is discarded, keeping current bytes, so tampered
        # unmodified-version elements cannot bypass authorization).
        new_group = _merge_delta(
            self.config.channel_group, update.write_set, delta, ()
        )

        out = configtx_pb2.ConfigEnvelope()
        out.config.sequence = self.config.sequence + 1
        out.config.channel_group.CopyFrom(new_group)
        if last_update is not None:
            out.last_update.CopyFrom(last_update)
        return out

    def validate(self, config_env: configtx_pb2.ConfigEnvelope) -> None:
        """Validate a proposed full config against the current one
        (reference Validator.Validate): recompute from last_update and
        require equality."""
        if config_env.config.sequence != self.config.sequence + 1:
            raise ConfigTxError(
                f"config currently at sequence {self.config.sequence}, cannot "
                f"validate config at sequence {config_env.config.sequence}"
            )
        if config_env.HasField("last_update"):
            computed = self.propose_config_update(config_env.last_update)
            if (
                computed.config.channel_group.SerializeToString(deterministic=True)
                != config_env.config.channel_group.SerializeToString(
                    deterministic=True
                )
            ):
                raise ConfigTxError(
                    "config proposed does not match calculated config"
                )

    def apply(self, config_env: configtx_pb2.ConfigEnvelope) -> None:
        self.validate(config_env)
        self.config = configtx_pb2.Config()
        self.config.CopyFrom(config_env.config)

    # -- helpers -----------------------------------------------------------

    def _new_item_mod_policy(self, key, write_set, current) -> str:
        """New elements are governed by the nearest existing ancestor
        group's mod policy (reference update.go verifyDeltaSet uses the
        group's mod_policy for adds)."""
        path = key[2]
        while True:
            cur = current.get(("group", "", path))
            if cur is not None:
                return cur.mod_policy
            if not path:
                return ""
            path = path[:-1]

    def _authorize(self, mod_policy: str, key, signed_data) -> None:
        if self.policy_manager is None:
            return  # unauthenticated mode (tests / local tooling)
        if not mod_policy:
            raise ConfigTxError(
                f"key {'/'.join(key[2] + (key[1],))} has no mod policy; "
                f"cannot modify"
            )
        resolved = _resolve_mod_policy(mod_policy, key[2])
        policy, ok = self.policy_manager.get_policy(resolved)
        if not ok:
            raise ConfigTxError(f"mod policy {resolved} not found")
        try:
            policy.evaluate_signed_data(signed_data)
        except PolicyError as e:
            raise ConfigTxError(
                f"config update is not authorized by mod policy {resolved}: {e}"
            ) from e


def _config_update_signed_data(
    cue: configtx_pb2.ConfigUpdateEnvelope, sig: configtx_pb2.ConfigSignature
) -> Tuple[bytes, bytes]:
    """Signed bytes = signature_header || config_update (reference
    ConfigUpdateEnvelope.AsSignedData, protoutil/signeddata.go:35-53);
    returns (data, creator identity bytes)."""
    sh = protoutil.unmarshal(common_pb2.SignatureHeader, sig.signature_header)
    return sig.signature_header + cue.config_update, sh.creator


def sign_config_update(cue: configtx_pb2.ConfigUpdateEnvelope, signer) -> None:
    """Append one ConfigSignature using a fabric_tpu.msp.signer-style signer
    (has .serialize() and .sign(bytes))."""
    import os

    sig = cue.signatures.add()
    sh = common_pb2.SignatureHeader()
    sh.creator = signer.serialize()
    sh.nonce = os.urandom(24)
    sig.signature_header = sh.SerializeToString()
    sig.signature = signer.sign(sig.signature_header + cue.config_update)


def _merge_delta(
    current: Optional[configtx_pb2.ConfigGroup],
    write: Optional[configtx_pb2.ConfigGroup],
    delta: Dict,
    path: Tuple[str, ...],
) -> configtx_pb2.ConfigGroup:
    """Current tree with delta elements overlaid. Content for non-delta
    elements always comes from CURRENT (never the write set). Group
    membership follows the write set only when the group itself is in the
    delta (a version bump authorizes adds/removes); otherwise membership
    is current plus any new delta children."""
    out = configtx_pb2.ConfigGroup()
    group_in_delta = ("group", "", path) in delta
    meta_src = write if (group_in_delta and write is not None) else current
    if meta_src is not None:
        out.version = meta_src.version
        out.mod_policy = meta_src.mod_policy

    cur_values = dict(current.values) if current is not None else {}
    cur_policies = dict(current.policies) if current is not None else {}
    cur_groups = dict(current.groups) if current is not None else {}
    wr_values = dict(write.values) if write is not None else {}
    wr_policies = dict(write.policies) if write is not None else {}
    wr_groups = dict(write.groups) if write is not None else {}

    if group_in_delta:
        value_names = set(wr_values)
        policy_names = set(wr_policies)
        group_names = set(wr_groups)
    else:
        value_names = set(cur_values) | {
            n for n in wr_values if ("value", n, path) in delta
        }
        policy_names = set(cur_policies) | {
            n for n in wr_policies if ("policy", n, path) in delta
        }
        group_names = set(cur_groups) | {
            n for n in wr_groups if _subtree_has_delta(delta, path + (n,))
        }

    for name in value_names:
        src = (
            wr_values[name]
            if ("value", name, path) in delta
            else cur_values.get(name)
        )
        if src is not None:
            out.values[name].CopyFrom(src)
    for name in policy_names:
        src = (
            wr_policies[name]
            if ("policy", name, path) in delta
            else cur_policies.get(name)
        )
        if src is not None:
            out.policies[name].CopyFrom(src)
    for name in group_names:
        sub_path = path + (name,)
        if _subtree_has_delta(delta, sub_path):
            out.groups[name].CopyFrom(
                _merge_delta(
                    cur_groups.get(name), wr_groups.get(name), delta, sub_path
                )
            )
        elif name in cur_groups:
            out.groups[name].CopyFrom(cur_groups[name])
    return out


def _subtree_has_delta(delta: Dict, path: Tuple[str, ...]) -> bool:
    return any(key[2][: len(path)] == path for key in delta)
