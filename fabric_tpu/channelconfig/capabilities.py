"""Capability feature gates from channel config (reference
common/capabilities/{application,channel,orderer}.go).

Capabilities are opaque string keys inside a Capabilities config value;
a node must "support" every required capability or refuse to process the
channel. The gates that change behavior here mirror the reference:
ApplicationCapabilities.V2_0Validation selects the v20 validation path
(reference common/capabilities/application.go:29,113), V1_2Validation
gates key-level endorsement, V1_1Validation gates tx flags validation.
"""

from __future__ import annotations

from typing import Dict, Iterable

V1_1 = "V1_1"
V1_2 = "V1_2"
V1_3 = "V1_3"
V1_4_2 = "V1_4_2"
V1_4_3 = "V1_4_3"
V2_0 = "V2_0"

_ORDERED = (V1_1, V1_2, V1_3, V1_4_2, V1_4_3, V2_0)


class CapabilityError(Exception):
    pass


class _Registry:
    def __init__(self, kind: str, supported: Iterable[str], capabilities: Iterable[str]):
        self.kind = kind
        self._supported = set(supported)
        self.required = set(capabilities)

    def supported(self) -> None:
        missing = self.required - self._supported
        if missing:
            raise CapabilityError(
                f"{self.kind} capabilities {sorted(missing)} are required but "
                f"not supported"
            )

    def _at_least(self, version: str) -> bool:
        idx = _ORDERED.index(version)
        return any(c in self.required for c in _ORDERED[idx:])


class ApplicationCapabilities(_Registry):
    def __init__(self, capabilities: Iterable[str] = ()):
        super().__init__("Application", _ORDERED, capabilities)

    @property
    def v20_validation(self) -> bool:
        return V2_0 in self.required

    @property
    def v12_validation(self) -> bool:
        return self._at_least(V1_2)

    @property
    def v11_validation(self) -> bool:
        return self._at_least(V1_1)

    @property
    def key_level_endorsement(self) -> bool:
        return self._at_least(V1_3)

    @property
    def storage_pvt_data_experimental(self) -> bool:
        return self._at_least(V1_2)

    @property
    def lifecycle_v20(self) -> bool:
        return V2_0 in self.required


class ChannelCapabilities(_Registry):
    def __init__(self, capabilities: Iterable[str] = ()):
        super().__init__("Channel", (V1_3, V1_4_2, V1_4_3, V2_0), capabilities)

    @property
    def consensus_type_migration(self) -> bool:
        return V1_4_2 in self.required or V2_0 in self.required


class OrdererCapabilities(_Registry):
    def __init__(self, capabilities: Iterable[str] = ()):
        super().__init__("Orderer", (V1_1, V1_4_2, V2_0), capabilities)

    @property
    def use_channel_creation_policy_as_admins(self) -> bool:
        return V2_0 in self.required
