// fabric_native — C++ host runtime for the hot irregular byte work that
// feeds the TPU kernels (SURVEY.md §7 hard part 5: DER/proto parsing
// throughput on host). Exposed as a plain C ABI consumed via ctypes.
//
//  * fn_batch_sha256: digest N variable-length messages.
//  * fn_batch_der_parse: unmarshal N ECDSA-P256 DER signatures into
//    fixed-width (r, s) big-endian 32-byte words with per-lane validity
//    + low-S flags, matching fabric_tpu.crypto.der semantics (strict
//    DER: minimal integer encoding, no trailing bytes).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

#include "sha256c.h"

extern "C" {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), straightforward portable implementation.
// (Retained as documentation/fallback; fn_batch_sha256 routes through
// sha256c, which picks up libcrypto's assembly paths when present.)
// ---------------------------------------------------------------------------

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256_one(const uint8_t* msg, uint64_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t total = len;
  uint8_t block[64];
  uint64_t off = 0;
  bool appended_one = false, appended_len = false;
  while (!appended_len) {
    uint64_t take = (len > off) ? (len - off) : 0;
    if (take > 64) take = 64;
    std::memcpy(block, msg + off, (size_t)take);
    uint64_t pos = take;
    if (pos < 64 && !appended_one) {
      block[pos++] = 0x80;
      appended_one = true;
    }
    if (pos <= 56) {
      std::memset(block + pos, 0, 56 - (size_t)pos);
      uint64_t bits = total * 8;
      for (int i = 0; i < 8; i++)
        block[56 + i] = (uint8_t)(bits >> (56 - 8 * i));
      appended_len = true;
    } else {
      std::memset(block + pos, 0, 64 - (size_t)pos);
    }
    // compress
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
             ((uint32_t)block[4 * i + 2] << 8) | (uint32_t)block[4 * i + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    off += 64;
  }
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)(h[i] >> 24);
    out[4 * i + 1] = (uint8_t)(h[i] >> 16);
    out[4 * i + 2] = (uint8_t)(h[i] >> 8);
    out[4 * i + 3] = (uint8_t)h[i];
  }
}

// msgs: concatenated bytes; offsets[i], lens[i] describe message i.
// out: n * 32 bytes.
void fn_batch_sha256(const uint8_t* msgs, const uint64_t* offsets,
                     const uint64_t* lens, int64_t n, uint8_t* out) {
  if (sha256c_backend()) {
    for (int64_t i = 0; i < n; i++)
      sha256c_oneshot(msgs + offsets[i], lens[i], out + 32 * i);
  } else {
    for (int64_t i = 0; i < n; i++)
      sha256_one(msgs + offsets[i], lens[i], out + 32 * i);
  }
}

// ---------------------------------------------------------------------------
// Strict-DER ECDSA signature parse (mirrors fabric_tpu/crypto/der.py):
//   SEQUENCE { INTEGER r, INTEGER s } — minimal lengths, no trailing data.
// P-256 group order for the low-S check.
// ---------------------------------------------------------------------------

static const uint8_t N_BE[32] = {
    0xff, 0xff, 0xff, 0xff, 0x00, 0x00, 0x00, 0x00, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xbc, 0xe6, 0xfa, 0xad, 0xa7, 0x17,
    0x9e, 0x84, 0xf3, 0xb9, 0xca, 0xc2, 0xfc, 0x63, 0x25, 0x51};

static const uint8_t HALF_N_BE[32] = {
    0x7f, 0xff, 0xff, 0xff, 0x80, 0x00, 0x00, 0x00, 0x7f, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xde, 0x73, 0x7d, 0x56, 0xd3, 0x8b,
    0xcf, 0x42, 0x79, 0xdc, 0xe5, 0x61, 0x7e, 0x31, 0x92, 0xa8};

// -1, 0, 1 for a < b, a == b, a > b over 32-byte big-endian words
static int cmp_be(const uint8_t* a, const uint8_t* b) {
  for (int i = 0; i < 32; i++) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

static bool is_zero_be(const uint8_t* a) {
  for (int i = 0; i < 32; i++)
    if (a[i]) return false;
  return true;
}

// DER length parse mirroring fabric_tpu/crypto/der.py _parse_length:
// short form, or minimal long form (no indefinite, no leading zeros,
// long form only for lengths >= 0x80). Returns false on malformed.
static bool parse_length(const uint8_t* buf, uint64_t len, uint64_t* pos,
                         uint64_t* out_len) {
  if (*pos >= len) return false;
  uint8_t b = buf[(*pos)++];
  if (!(b & 0x80)) {
    *out_len = b;
    return true;
  }
  uint64_t num = b & 0x7f;
  if (num == 0 || num > 8) return false;  // indefinite / absurd
  uint64_t value = 0;
  for (uint64_t i = 0; i < num; i++) {
    if (*pos >= len) return false;
    if (value >= (1ull << 23)) return false;
    value = (value << 8) | buf[(*pos)++];
    if (value == 0) return false;  // superfluous leading zero byte
  }
  if (value < 0x80) return false;  // non-minimal long form
  *out_len = value;
  return true;
}

// Parse one INTEGER at buf[*pos] within [.., end); write 32-byte BE
// value. Mirrors der.py _parse_int + the r>0 / range gates: rejects
// negative, non-minimal, zero, and values >= 2^256 (which could never
// pass the r,s < n check anyway).
static bool parse_int(const uint8_t* buf, uint64_t end, uint64_t* pos,
                      uint8_t out[32]) {
  if (*pos >= end) return false;
  if (buf[*pos] != 0x02) return false;
  (*pos)++;
  uint64_t ilen;
  if (!parse_length(buf, end, pos, &ilen)) return false;
  if (*pos + ilen > end || ilen == 0) return false;
  const uint8_t* p = buf + *pos;
  // negative => r/s <= 0 reject; non-minimal 0x00 prefix reject
  // (the 0xFF-prefix non-minimal case is already negative)
  if (p[0] & 0x80) return false;
  if (ilen > 1 && p[0] == 0x00 && !(p[1] & 0x80)) return false;
  uint64_t skip = (p[0] == 0x00) ? 1 : 0;
  uint64_t vlen = ilen - skip;
  if (vlen > 32) return false;
  std::memset(out, 0, 32);
  std::memcpy(out + (32 - vlen), p + skip, (size_t)vlen);
  *pos += ilen;
  return true;
}

// sigs: concatenated DER; offsets/lens per signature.
// out_r/out_s: n*32 bytes; out_ok[i]: 1 = well-formed; out_low_s[i]:
// 1 = s <= n/2 (callers reject high-S like the reference's IsLowS gate).
// Trailing bytes inside and after the SEQUENCE are tolerated, exactly
// like der.py unmarshal_signature (the Go asn1 quirk) — the two parsers
// MUST agree or peers with/without the native library diverge.
void fn_batch_der_parse(const uint8_t* sigs, const uint64_t* offsets,
                        const uint64_t* lens, int64_t n, uint8_t* out_r,
                        uint8_t* out_s, uint8_t* out_ok, uint8_t* out_low_s) {
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* buf = sigs + offsets[i];
    uint64_t len = lens[i];
    uint8_t* r = out_r + 32 * i;
    uint8_t* s = out_s + 32 * i;
    out_ok[i] = 0;
    out_low_s[i] = 0;
    if (len == 0 || buf[0] != 0x30) continue;
    uint64_t pos = 1;
    uint64_t seq_len;
    if (!parse_length(buf, len, &pos, &seq_len)) continue;
    uint64_t end = pos + seq_len;
    if (end > len) continue;  // sequence overruns input
    if (!parse_int(buf, end, &pos, r)) continue;
    if (!parse_int(buf, end, &pos, s)) continue;
    // 1 <= r,s < n
    if (is_zero_be(r) || is_zero_be(s)) continue;
    if (cmp_be(r, N_BE) >= 0 || cmp_be(s, N_BE) >= 0) continue;
    out_ok[i] = 1;
    out_low_s[i] = (cmp_be(s, HALF_N_BE) <= 0) ? 1 : 0;
  }
}

}  // extern "C"
