// Streaming SHA-256 for the native host runtime. At startup dlopen()s
// libcrypto.so.3 (OpenSSL's assembly/SHA-NI paths, ~10x the portable
// loop); falls back to the portable FIPS 180-4 implementation when
// libcrypto is absent so libfabric_native.so itself has no hard
// dependency beyond libc.
#pragma once

#include <cstddef>
#include <cstdint>

// Opaque context: large enough for OpenSSL's SHA256_CTX (112 bytes) or
// the portable state.
struct ShaCtx {
  alignas(8) uint8_t space[160];
};

void sha256c_init(ShaCtx* c);
void sha256c_update(ShaCtx* c, const uint8_t* p, size_t len);
void sha256c_final(ShaCtx* c, uint8_t out[32]);
void sha256c_oneshot(const uint8_t* p, size_t len, uint8_t out[32]);
// 1 = OpenSSL backend active (for tests / diagnostics)
int sha256c_backend();
