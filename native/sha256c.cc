#include "sha256c.h"

#include <cstring>
#include <dlfcn.h>

// ---------------------------------------------------------------------------
// Portable fallback (FIPS 180-4), streaming form.
// ---------------------------------------------------------------------------

namespace {

const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct PortableCtx {
  uint32_t h[8];
  uint64_t total;
  uint8_t buf[64];
  size_t buflen;
};

void compress(uint32_t* h, const uint8_t* block) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
           ((uint32_t)block[4 * i + 2] << 8) | (uint32_t)block[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

void portable_init(PortableCtx* c) {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(c->h, H0, sizeof H0);
  c->total = 0;
  c->buflen = 0;
}

void portable_update(PortableCtx* c, const uint8_t* p, size_t len) {
  c->total += len;
  if (c->buflen) {
    size_t take = 64 - c->buflen;
    if (take > len) take = len;
    std::memcpy(c->buf + c->buflen, p, take);
    c->buflen += take;
    p += take;
    len -= take;
    if (c->buflen == 64) {
      compress(c->h, c->buf);
      c->buflen = 0;
    }
  }
  while (len >= 64) {
    compress(c->h, p);
    p += 64;
    len -= 64;
  }
  if (len) {
    std::memcpy(c->buf, p, len);
    c->buflen = len;
  }
}

void portable_final(PortableCtx* c, uint8_t out[32]) {
  uint64_t bits = c->total * 8;
  uint8_t pad = 0x80;
  portable_update(c, &pad, 1);
  uint8_t zero = 0;
  while (c->buflen != 56) portable_update(c, &zero, 1);
  uint8_t lenbuf[8];
  for (int i = 0; i < 8; i++) lenbuf[i] = (uint8_t)(bits >> (56 - 8 * i));
  portable_update(c, lenbuf, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)(c->h[i] >> 24);
    out[4 * i + 1] = (uint8_t)(c->h[i] >> 16);
    out[4 * i + 2] = (uint8_t)(c->h[i] >> 8);
    out[4 * i + 3] = (uint8_t)c->h[i];
  }
}

// ---------------------------------------------------------------------------
// OpenSSL backend via dlopen (no link-time dependency).
// ---------------------------------------------------------------------------

struct OpenSSL {
  int (*init)(void*);
  int (*update)(void*, const void*, size_t);
  int (*fin)(unsigned char*, void*);
  unsigned char* (*oneshot)(const unsigned char*, size_t, unsigned char*);
  bool ok = false;
};

const OpenSSL& ossl() {
  static OpenSSL g = [] {
    OpenSSL o;
    void* lib = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) lib = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) return o;
    o.init = (int (*)(void*))dlsym(lib, "SHA256_Init");
    o.update = (int (*)(void*, const void*, size_t))dlsym(lib, "SHA256_Update");
    o.fin = (int (*)(unsigned char*, void*))dlsym(lib, "SHA256_Final");
    o.oneshot = (unsigned char* (*)(const unsigned char*, size_t,
                                    unsigned char*))dlsym(lib, "SHA256");
    o.ok = o.init && o.update && o.fin && o.oneshot;
    return o;
  }();
  return g;
}

}  // namespace

void sha256c_init(ShaCtx* c) {
  const OpenSSL& o = ossl();
  if (o.ok) {
    o.init(c->space);
  } else {
    portable_init(reinterpret_cast<PortableCtx*>(c->space));
  }
}

void sha256c_update(ShaCtx* c, const uint8_t* p, size_t len) {
  const OpenSSL& o = ossl();
  if (o.ok) {
    o.update(c->space, p, len);
  } else {
    portable_update(reinterpret_cast<PortableCtx*>(c->space), p, len);
  }
}

void sha256c_final(ShaCtx* c, uint8_t out[32]) {
  const OpenSSL& o = ossl();
  if (o.ok) {
    o.fin(out, c->space);
  } else {
    portable_final(reinterpret_cast<PortableCtx*>(c->space), out);
  }
}

void sha256c_oneshot(const uint8_t* p, size_t len, uint8_t out[32]) {
  const OpenSSL& o = ossl();
  if (o.ok) {
    o.oneshot(p, len, out);
  } else {
    PortableCtx c;
    portable_init(&c);
    portable_update(&c, p, len);
    portable_final(&c, out);
  }
}

int sha256c_backend() { return ossl().ok ? 1 : 0; }

static_assert(sizeof(PortableCtx) <= sizeof(ShaCtx::space),
              "ShaCtx too small for portable state");
