// Native block-structure parser: the host-side hot loop of commit-time
// validation (reference core/common/validation/msgvalidation.go
// ValidateTransaction :248-330 plus the artifact extraction of
// core/handlers/validation/builtin/v20/validation_logic.go:109-177),
// executed over EVERY envelope of a block in one C++ pass.
//
// It re-implements exactly the protobuf WIRE semantics the Python path
// (google.protobuf upb ParseFromString) applies, verified by a
// differential fuzzer (tests/test_blockparse_native.py):
//   * unknown fields skipped (varint/64-bit/length-delimited/32-bit and
//     balanced groups); known field with mismatched wire type is
//     treated as unknown;
//   * repeated occurrences of a singular scalar field: last wins;
//     repeated occurrences of a singular MESSAGE field: merge
//     (sub-fields overwrite, repeated sub-fields append);
//   * string fields must be valid UTF-8 (strict: no surrogates, no
//     overlongs, <= U+10FFFF);
//   * varints are at most 10 bytes; truncation, field number 0 and wire
//     types 6/7 are parse errors; submessages are validated eagerly.
//
// Outputs are columnar arrays: per-tx validation codes + field slices
// (offsets into the caller's concatenated buffer), a flattened
// signature-job table with per-job SHA-256 digests (creator signature
// over the payload bytes; endorsement signatures over
// proposal_response_payload || endorser, statebased
// validator_keylevel.go:243-251), a deduplicated serialized-identity
// table, per-namespace write flags, and the written-keys table used by
// the state-based endorsement gate.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "sha256c.h"

namespace {

// TxValidationCode values (fabric-protos peer/transaction.proto).
enum Code : int32_t {
  OK = 254,  // NOT_VALIDATED: structurally valid, later phases decide
  NIL_ENVELOPE = 1,
  BAD_PAYLOAD = 2,
  BAD_COMMON_HEADER = 3,
  INVALID_ENDORSER_TRANSACTION = 5,
  BAD_PROPOSAL_TXID = 8,
  BAD_RESPONSE_PAYLOAD = 21,
  BAD_RWSET = 22,
  INVALID_OTHER_REASON = 255,
};

// common.proto HeaderType
enum : int32_t { HT_CONFIG = 1, HT_CONFIG_UPDATE = 2, HT_ENDORSER = 3 };

struct Slice {
  uint64_t off = 0;
  uint64_t len = 0;
};

struct Rd {
  const uint8_t* base;
  uint64_t pos, end;
};

bool rd_varint(Rd& r, uint64_t* v) {
  uint64_t result = 0;
  for (int i = 0; i < 10; i++) {
    if (r.pos >= r.end) return false;
    uint8_t b = r.base[r.pos++];
    result |= (uint64_t)(b & 0x7f) << (7 * i);
    if (!(b & 0x80)) {
      *v = result;
      return true;
    }
  }
  return false;  // 11+ byte varint
}

bool rd_tag(Rd& r, uint32_t* fn, uint32_t* wt) {
  uint64_t tag;
  if (!rd_varint(r, &tag)) return false;
  *fn = (uint32_t)(tag >> 3);
  *wt = (uint32_t)(tag & 7);
  // field number 1..2^29-1 (upb rejects 0 and anything larger)
  if (tag >> 3 == 0 || (tag >> 3) > 536870911ull) return false;
  return true;
}

bool rd_len_delim(Rd& r, Slice* s) {
  uint64_t len;
  if (!rd_varint(r, &len)) return false;
  if (len > r.end - r.pos) return false;
  s->off = r.pos;
  s->len = len;
  r.pos += len;
  return true;
}

bool skip_field(Rd& r, uint32_t fn, uint32_t wt, int depth) {
  switch (wt) {
    case 0: {
      uint64_t v;
      return rd_varint(r, &v);
    }
    case 1:
      if (r.end - r.pos < 8) return false;
      r.pos += 8;
      return true;
    case 2: {
      Slice s;
      return rd_len_delim(r, &s);
    }
    case 5:
      if (r.end - r.pos < 4) return false;
      r.pos += 4;
      return true;
    case 3: {  // group: skip until matching end-group tag
      // a group at nesting level d enters here with depth == d-1; reject
      // at level 101 exactly like python-protobuf (upb recursion limit
      // 100: 100-deep balanced groups parse, 101 raise DecodeError) so
      // native and fallback deployments accept identical envelopes
      if (depth > 99) return false;
      for (;;) {
        uint32_t f2, w2;
        if (!rd_tag(r, &f2, &w2)) return false;
        if (w2 == 4) return f2 == fn;
        if (!skip_field(r, f2, w2, depth + 1)) return false;
      }
    }
    default:
      return false;  // wt 4 unmatched, 6, 7
  }
}

// Structural validation for submessages with no string-typed fields
// (Timestamp, Version, QueryReadsMerkleSummary, ...): for those, upb
// acceptance == generic wire well-formedness.
bool validate_wire(const uint8_t* base, Slice s, int depth) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (!skip_field(r, f, w, depth)) return false;
  }
  return true;
}

// Strict UTF-8 (what upb enforces on proto3 string fields).
bool utf8_ok(const uint8_t* p, uint64_t len) {
  uint64_t i = 0;
  while (i < len) {
    uint8_t c = p[i];
    if (c < 0x80) {
      i++;
    } else if (c < 0xC2) {
      return false;  // bare continuation / overlong 2-byte
    } else if (c < 0xE0) {
      if (i + 1 >= len || (p[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if (c < 0xF0) {
      if (i + 2 >= len) return false;
      uint8_t c1 = p[i + 1], c2 = p[i + 2];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80) return false;
      if (c == 0xE0 && c1 < 0xA0) return false;   // overlong
      if (c == 0xED && c1 >= 0xA0) return false;  // surrogate
      i += 3;
    } else if (c < 0xF5) {
      if (i + 3 >= len) return false;
      uint8_t c1 = p[i + 1], c2 = p[i + 2], c3 = p[i + 3];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80 || (c3 & 0xC0) != 0x80)
        return false;
      if (c == 0xF0 && c1 < 0x90) return false;   // overlong
      if (c == 0xF4 && c1 >= 0x90) return false;  // > U+10FFFF
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

bool utf8_slice(const uint8_t* base, Slice s) {
  return utf8_ok(base + s.off, s.len);
}

// ---------------------------------------------------------------------------
// Per-message walkers. Each returns false when upb ParseFromString on
// the same bytes would raise. "Merge" targets are passed by reference so
// a repeated singular-message occurrence continues filling the same
// logical struct (proto3 merge semantics).
// ---------------------------------------------------------------------------

struct Envelope {
  Slice payload, signature;
};

bool parse_envelope(const uint8_t* base, Slice s, Envelope* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->payload)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->signature)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct Header {
  Slice channel_header, signature_header;
};

// Header sits one level below the Payload ParseFromString root
bool parse_header(const uint8_t* base, Slice s, Header* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->channel_header)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->signature_header)) return false;
    } else if (!skip_field(r, f, w, 1)) {
      return false;
    }
  }
  return true;
}

struct Payload {
  bool has_header = false;
  Header header;
  Slice data;
};

bool parse_payload(const uint8_t* base, Slice s, Payload* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      Slice hs;
      if (!rd_len_delim(r, &hs)) return false;
      if (!parse_header(base, hs, &out->header)) return false;
      out->has_header = true;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->data)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct ChannelHeader {
  int32_t type = 0;
  Slice channel_id, tx_id;
  uint64_t epoch = 0;
};

bool parse_channel_header(const uint8_t* base, Slice s, ChannelHeader* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 0) {
      uint64_t v;
      if (!rd_varint(r, &v)) return false;
      out->type = (int32_t)(uint32_t)v;
    } else if (f == 3 && w == 2) {  // Timestamp: eager submessage check
      Slice ts;
      if (!rd_len_delim(r, &ts)) return false;
      if (!validate_wire(base, ts, 1)) return false;
    } else if (f == 4 && w == 2) {
      if (!rd_len_delim(r, &out->channel_id)) return false;
      if (!utf8_slice(base, out->channel_id)) return false;
    } else if (f == 5 && w == 2) {
      if (!rd_len_delim(r, &out->tx_id)) return false;
      if (!utf8_slice(base, out->tx_id)) return false;
    } else if (f == 6 && w == 0) {
      if (!rd_varint(r, &out->epoch)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct SignatureHeader {
  Slice creator, nonce;
};

bool parse_signature_header(const uint8_t* base, Slice s,
                            SignatureHeader* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->creator)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->nonce)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct TransactionAction {
  Slice header, payload;
};

bool parse_transaction_action(const uint8_t* base, Slice s,
                              TransactionAction* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->header)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->payload)) return false;
    } else if (!skip_field(r, f, w, 1)) {
      return false;
    }
  }
  return true;
}

struct Transaction {
  std::vector<TransactionAction> actions;
};

bool parse_transaction_msg(const uint8_t* base, Slice s, Transaction* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      Slice as;
      if (!rd_len_delim(r, &as)) return false;
      TransactionAction a;
      if (!parse_transaction_action(base, as, &a)) return false;
      out->actions.push_back(a);
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct EndorsementMsg {
  Slice endorser, signature;
};

struct ChaincodeEndorsedAction {  // merge target across occurrences
  Slice prp;                      // proposal_response_payload
  std::vector<EndorsementMsg> endorsements;
};

bool parse_endorsed_action(const uint8_t* base, Slice s,
                           ChaincodeEndorsedAction* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->prp)) return false;
    } else if (f == 2 && w == 2) {
      Slice es;
      if (!rd_len_delim(r, &es)) return false;
      EndorsementMsg e;
      Rd r2{base, es.off, es.off + es.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          if (!rd_len_delim(r2, &e.endorser)) return false;
        } else if (f2 == 2 && w2 == 2) {
          if (!rd_len_delim(r2, &e.signature)) return false;
        } else if (!skip_field(r2, f2, w2, 2)) {
          return false;
        }
      }
      out->endorsements.push_back(e);
    } else if (!skip_field(r, f, w, 1)) {
      return false;
    }
  }
  return true;
}

struct ChaincodeActionPayload {
  Slice chaincode_proposal_payload;
  ChaincodeEndorsedAction action;  // proto3 merge across occurrences
};

bool parse_cap(const uint8_t* base, Slice s, ChaincodeActionPayload* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->chaincode_proposal_payload)) return false;
    } else if (f == 2 && w == 2) {
      Slice as;
      if (!rd_len_delim(r, &as)) return false;
      if (!parse_endorsed_action(base, as, &out->action)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

struct ProposalResponsePayload {
  Slice proposal_hash, extension;
};

bool parse_prp(const uint8_t* base, Slice s, ProposalResponsePayload* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->proposal_hash)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->extension)) return false;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

// Response { int32 status = 1; string message = 2; bytes payload = 3; }
bool validate_response(const uint8_t* base, Slice s) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 2 && w == 2) {
      Slice m;
      if (!rd_len_delim(r, &m)) return false;
      if (!utf8_slice(base, m)) return false;
    } else if (!skip_field(r, f, w, 1)) {
      return false;
    }
  }
  return true;
}

struct ChaincodeID {  // merge target
  Slice name;
};

bool parse_chaincode_id(const uint8_t* base, Slice s, ChaincodeID* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if ((f == 1 || f == 3) && w == 2) {  // path / version: utf8 only
      Slice v;
      if (!rd_len_delim(r, &v)) return false;
      if (!utf8_slice(base, v)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->name)) return false;
      if (!utf8_slice(base, out->name)) return false;
    } else if (!skip_field(r, f, w, 1)) {
      return false;
    }
  }
  return true;
}

struct ChaincodeAction {
  Slice results, events;
  bool has_chaincode_id = false;
  ChaincodeID chaincode_id;
};

bool parse_chaincode_action(const uint8_t* base, Slice s,
                            ChaincodeAction* out) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      if (!rd_len_delim(r, &out->results)) return false;
    } else if (f == 2 && w == 2) {
      if (!rd_len_delim(r, &out->events)) return false;
    } else if (f == 3 && w == 2) {
      Slice resp;
      if (!rd_len_delim(r, &resp)) return false;
      if (!validate_response(base, resp)) return false;
    } else if (f == 4 && w == 2) {
      Slice cid;
      if (!rd_len_delim(r, &cid)) return false;
      if (!parse_chaincode_id(base, cid, &out->chaincode_id)) return false;
      out->has_chaincode_id = true;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// rwset tree walk: structural validation (what Python's eager
// parse_tx_rwset would accept) + namespace/write-key harvesting.
// ---------------------------------------------------------------------------

struct WKey {
  Slice coll;       // empty for public writes
  Slice key;        // public: string key; hashed: key_hash bytes
  uint8_t hashed;   // 1 = collection hashed write (bytes key)
};

struct NsEntry {
  Slice name;
  uint8_t writes = 0;  // txWritesToNamespace (dispatcher.go:174-218)
  std::vector<WKey> wkeys;
  bool has_md = false;
};

// KVRead { string key = 1; Version version = 2; }  `depth` = this
// message's nesting level below the enclosing python ParseFromString
// root (upb's recursion limit counts message levels AND group levels
// from that root, budget 100 — parity demands the native walker track
// the same accumulated depth, not restart at 0 per submessage).
bool validate_kvread(const uint8_t* base, Slice s, int depth) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      Slice k;
      if (!rd_len_delim(r, &k)) return false;
      if (!utf8_slice(base, k)) return false;
    } else if (f == 2 && w == 2) {
      Slice v;
      if (!rd_len_delim(r, &v)) return false;
      if (!validate_wire(base, v, depth + 1)) return false;
    } else if (!skip_field(r, f, w, depth)) {
      return false;
    }
  }
  return true;
}

// KVMetadataWrite / KVMetadataWriteHash share shape:
// { key(1: string|bytes); repeated KVMetadataEntry entries = 2 }
// KVMetadataEntry { string name = 1; bytes value = 2; }
bool validate_md_write(const uint8_t* base, Slice s, bool key_is_string,
                       int depth) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      Slice k;
      if (!rd_len_delim(r, &k)) return false;
      if (key_is_string && !utf8_slice(base, k)) return false;
    } else if (f == 2 && w == 2) {
      Slice e;
      if (!rd_len_delim(r, &e)) return false;
      Rd r2{base, e.off, e.off + e.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          Slice nm;
          if (!rd_len_delim(r2, &nm)) return false;
          if (!utf8_slice(base, nm)) return false;
        } else if (!skip_field(r2, f2, w2, depth + 1)) {
          return false;
        }
      }
    } else if (!skip_field(r, f, w, depth)) {
      return false;
    }
  }
  return true;
}

// RangeQueryInfo { start/end(1,2: string); itr(3); raw_reads(4);
// reads_merkle_hashes(5) }
bool validate_rqi(const uint8_t* base, Slice s, int depth) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if ((f == 1 || f == 2) && w == 2) {
      Slice k;
      if (!rd_len_delim(r, &k)) return false;
      if (!utf8_slice(base, k)) return false;
    } else if (f == 4 && w == 2) {  // QueryReads { repeated KVRead = 1 }
      Slice q;
      if (!rd_len_delim(r, &q)) return false;
      Rd r2{base, q.off, q.off + q.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          Slice kr;
          if (!rd_len_delim(r2, &kr)) return false;
          if (!validate_kvread(base, kr, depth + 2)) return false;
        } else if (!skip_field(r2, f2, w2, depth + 1)) {
          return false;
        }
      }
    } else if (f == 5 && w == 2) {  // merkle summary: no strings
      Slice m;
      if (!rd_len_delim(r, &m)) return false;
      if (!validate_wire(base, m, depth + 1)) return false;
    } else if (!skip_field(r, f, w, depth)) {
      return false;
    }
  }
  return true;
}

// KVRWSet { reads=1; range_queries_info=2; writes=3; metadata_writes=4 }
bool walk_kvrwset(const uint8_t* base, Slice s, NsEntry* ns) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {
      Slice kr;
      if (!rd_len_delim(r, &kr)) return false;
      if (!validate_kvread(base, kr, 1)) return false;
    } else if (f == 2 && w == 2) {
      Slice q;
      if (!rd_len_delim(r, &q)) return false;
      if (!validate_rqi(base, q, 1)) return false;
    } else if (f == 3 && w == 2) {  // KVWrite { key=1; is_delete=2; value=3 }
      Slice ws;
      if (!rd_len_delim(r, &ws)) return false;
      Slice key{0, 0};
      Rd r2{base, ws.off, ws.off + ws.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          if (!rd_len_delim(r2, &key)) return false;
          if (!utf8_slice(base, key)) return false;
        } else if (!skip_field(r2, f2, w2, 1)) {
          return false;
        }
      }
      ns->writes = 1;
      ns->wkeys.push_back(WKey{Slice{0, 0}, key, 0});
    } else if (f == 4 && w == 2) {
      Slice mw;
      if (!rd_len_delim(r, &mw)) return false;
      if (!validate_md_write(base, mw, true, 1)) return false;
      ns->writes = 1;
      ns->has_md = true;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

// HashedRWSet { hashed_reads=1; hashed_writes=2; metadata_writes=3 }
bool walk_hashed_rwset(const uint8_t* base, Slice s, Slice coll_name,
                       NsEntry* ns) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 1 && w == 2) {  // KVReadHash { key_hash=1; version=2 }
      Slice hr;
      if (!rd_len_delim(r, &hr)) return false;
      Rd r2{base, hr.off, hr.off + hr.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 2 && w2 == 2) {
          Slice v;
          if (!rd_len_delim(r2, &v)) return false;
          if (!validate_wire(base, v, 2)) return false;
        } else if (!skip_field(r2, f2, w2, 1)) {
          return false;
        }
      }
    } else if (f == 2 && w == 2) {  // KVWriteHash { key_hash=1 }
      Slice hw;
      if (!rd_len_delim(r, &hw)) return false;
      Slice key{0, 0};
      Rd r2{base, hw.off, hw.off + hw.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          if (!rd_len_delim(r2, &key)) return false;
        } else if (!skip_field(r2, f2, w2, 1)) {
          return false;
        }
      }
      ns->writes = 1;
      ns->wkeys.push_back(WKey{coll_name, key, 1});
    } else if (f == 3 && w == 2) {
      Slice mw;
      if (!rd_len_delim(r, &mw)) return false;
      if (!validate_md_write(base, mw, false, 1)) return false;
      ns->writes = 1;
      ns->has_md = true;
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

// TxReadWriteSet { data_model=1; repeated NsReadWriteSet ns_rwset=2 }
// NsReadWriteSet { namespace=1; rwset=2(KVRWSet bytes);
//                  repeated CollectionHashedReadWriteSet=3 }
bool walk_tx_rwset(const uint8_t* base, Slice s, std::vector<NsEntry>* out,
                   bool* has_md) {
  Rd r{base, s.off, s.off + s.len};
  while (r.pos < r.end) {
    uint32_t f, w;
    if (!rd_tag(r, &f, &w)) return false;
    if (w == 4) return false;
    if (f == 2 && w == 2) {
      Slice nss;
      if (!rd_len_delim(r, &nss)) return false;
      NsEntry ns;
      Slice kv{0, 0};
      struct Coll {
        Slice name, hashed;
      };
      std::vector<Coll> colls;
      Rd r2{base, nss.off, nss.off + nss.len};
      while (r2.pos < r2.end) {
        uint32_t f2, w2;
        if (!rd_tag(r2, &f2, &w2)) return false;
        if (w2 == 4) return false;
        if (f2 == 1 && w2 == 2) {
          if (!rd_len_delim(r2, &ns.name)) return false;
          if (!utf8_slice(base, ns.name)) return false;
        } else if (f2 == 2 && w2 == 2) {
          if (!rd_len_delim(r2, &kv)) return false;
        } else if (f2 == 3 && w2 == 2) {
          Slice cs;
          if (!rd_len_delim(r2, &cs)) return false;
          Coll c{{0, 0}, {0, 0}};
          Rd r3{base, cs.off, cs.off + cs.len};
          while (r3.pos < r3.end) {
            uint32_t f3, w3;
            if (!rd_tag(r3, &f3, &w3)) return false;
            if (w3 == 4) return false;
            if (f3 == 1 && w3 == 2) {
              if (!rd_len_delim(r3, &c.name)) return false;
              if (!utf8_slice(base, c.name)) return false;
            } else if (f3 == 2 && w3 == 2) {
              if (!rd_len_delim(r3, &c.hashed)) return false;
            } else if (!skip_field(r3, f3, w3, 2)) {
              return false;
            }
          }
          colls.push_back(c);
        } else if (!skip_field(r2, f2, w2, 1)) {
          return false;
        }
      }
      // final (merged) kv rwset + per-collection hashed walks
      if (!walk_kvrwset(base, kv, &ns)) return false;
      for (const Coll& c : colls) {
        if (!walk_hashed_rwset(base, c.hashed, c.name, &ns)) return false;
      }
      if (ns.has_md) *has_md = true;
      out->push_back(std::move(ns));
    } else if (!skip_field(r, f, w, 0)) {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Result container (opaque handle returned to Python).
// ---------------------------------------------------------------------------

struct BlockParseResult {
  int64_t n_txs;
  std::vector<int32_t> code, header_type;
  std::vector<uint8_t> has_md;
  std::vector<uint64_t> strs;  // n*12: chan, txid, creator, config, ns, results
  std::vector<int64_t> job_tx, job_ident;
  std::vector<uint8_t> job_is_creator;
  std::vector<uint64_t> job_sig, job_data;  // *2 (off, len)
  std::vector<uint8_t> job_digest;          // *32
  std::vector<uint64_t> uniq;               // *2
  std::vector<int64_t> ns_tx;
  std::vector<uint8_t> ns_writes;
  std::vector<uint64_t> ns_str;  // *2
  std::vector<int64_t> wk_tx, wk_ns;
  std::vector<uint8_t> wk_hashed;
  std::vector<uint64_t> wk_coll, wk_key;  // *2 each
};

struct SliceKey {
  const uint8_t* p;
  uint64_t len;
  bool operator==(const SliceKey& o) const {
    return len == o.len && std::memcmp(p, o.p, len) == 0;
  }
};

struct SliceKeyHash {
  size_t operator()(const SliceKey& k) const {
    // FNV-1a over the bytes
    uint64_t h = 1469598103934665603ull;
    for (uint64_t i = 0; i < k.len; i++) {
      h ^= k.p[i];
      h *= 1099511628211ull;
    }
    return (size_t)h;
  }
};

void hex32(const uint8_t d[32], char out[64]) {
  static const char* hexd = "0123456789abcdef";
  for (int i = 0; i < 32; i++) {
    out[2 * i] = hexd[d[i] >> 4];
    out[2 * i + 1] = hexd[d[i] & 0xf];
  }
}

}  // namespace

extern "C" {

void* fn_block_parse(const uint8_t* buf, const uint64_t* offs,
                     const uint64_t* lens, int64_t n_txs) {
  auto* res = new BlockParseResult();
  res->n_txs = n_txs;
  res->code.assign(n_txs, OK);
  res->header_type.assign(n_txs, -1);
  res->has_md.assign(n_txs, 0);
  res->strs.assign((size_t)n_txs * 12, 0);

  std::unordered_map<SliceKey, int64_t, SliceKeyHash> uniq_map;
  auto intern = [&](Slice s) -> int64_t {
    SliceKey k{buf + s.off, s.len};
    auto it = uniq_map.find(k);
    if (it != uniq_map.end()) return it->second;
    int64_t idx = (int64_t)uniq_map.size();
    uniq_map.emplace(k, idx);
    res->uniq.push_back(s.off);
    res->uniq.push_back(s.len);
    return idx;
  };

  for (int64_t i = 0; i < n_txs; i++) {
    Slice env_s{offs[i], lens[i]};
    uint64_t* strs = &res->strs[(size_t)i * 12];
    if (env_s.len == 0) {
      res->code[i] = NIL_ENVELOPE;
      continue;
    }
    Envelope env;
    if (!parse_envelope(buf, env_s, &env)) {
      res->code[i] = INVALID_OTHER_REASON;
      continue;
    }
    if (env.payload.len == 0) {
      res->code[i] = BAD_PAYLOAD;
      continue;
    }
    Payload payload;
    if (!parse_payload(buf, env.payload, &payload)) {
      res->code[i] = BAD_PAYLOAD;
      continue;
    }
    // validateCommonHeader (msgvalidation.go)
    if (!payload.has_header) {
      res->code[i] = BAD_COMMON_HEADER;
      continue;
    }
    ChannelHeader chdr;
    SignatureHeader shdr;
    if (!parse_channel_header(buf, payload.header.channel_header, &chdr) ||
        !parse_signature_header(buf, payload.header.signature_header, &shdr)) {
      res->code[i] = BAD_COMMON_HEADER;
      continue;
    }
    if ((chdr.type != HT_ENDORSER && chdr.type != HT_CONFIG &&
         chdr.type != HT_CONFIG_UPDATE) ||
        chdr.epoch != 0) {
      res->code[i] = BAD_COMMON_HEADER;
      continue;
    }
    if (shdr.nonce.len == 0 || shdr.creator.len == 0) {
      res->code[i] = BAD_COMMON_HEADER;
      continue;
    }
    res->header_type[i] = chdr.type;
    strs[0] = chdr.channel_id.off;
    strs[1] = chdr.channel_id.len;
    strs[2] = chdr.tx_id.off;
    strs[3] = chdr.tx_id.len;
    strs[4] = shdr.creator.off;
    strs[5] = shdr.creator.len;

    // creator signature job: env.Signature over env.Payload
    // (checkSignatureFromCreator, msgvalidation.go:284)
    {
      res->job_tx.push_back(i);
      res->job_ident.push_back(intern(shdr.creator));
      res->job_is_creator.push_back(1);
      res->job_sig.push_back(env.signature.off);
      res->job_sig.push_back(env.signature.len);
      res->job_data.push_back(env.payload.off);
      res->job_data.push_back(env.payload.len);
      uint8_t d[32];
      sha256c_oneshot(buf + env.payload.off, env.payload.len, d);
      res->job_digest.insert(res->job_digest.end(), d, d + 32);
    }

    if (chdr.type == HT_CONFIG) {
      strs[6] = payload.data.off;
      strs[7] = payload.data.len;
      continue;
    }
    if (chdr.type == HT_CONFIG_UPDATE) continue;

    // --- ENDORSER_TRANSACTION ---
    // TxID recompute: sha256(nonce || creator) hex (protoutil.CheckTxID)
    {
      ShaCtx c;
      sha256c_init(&c);
      sha256c_update(&c, buf + shdr.nonce.off, shdr.nonce.len);
      sha256c_update(&c, buf + shdr.creator.off, shdr.creator.len);
      uint8_t d[32];
      sha256c_final(&c, d);
      char hex[64];
      hex32(d, hex);
      if (chdr.tx_id.len != 64 ||
          std::memcmp(buf + chdr.tx_id.off, hex, 64) != 0) {
        res->code[i] = BAD_PROPOSAL_TXID;
        continue;
      }
    }
    Transaction tx;
    if (!parse_transaction_msg(buf, payload.data, &tx) ||
        tx.actions.size() != 1) {
      res->code[i] = INVALID_ENDORSER_TRANSACTION;
      continue;
    }
    const TransactionAction& action = tx.actions[0];
    SignatureHeader act_shdr;
    if (!parse_signature_header(buf, action.header, &act_shdr) ||
        act_shdr.nonce.len == 0 || act_shdr.creator.len == 0) {
      res->code[i] = INVALID_ENDORSER_TRANSACTION;
      continue;
    }
    ChaincodeActionPayload cap;
    ProposalResponsePayload prp;
    if (!parse_cap(buf, action.payload, &cap) ||
        !parse_prp(buf, cap.action.prp, &prp)) {
      res->code[i] = INVALID_ENDORSER_TRANSACTION;
      continue;
    }
    // proposal-hash binding: sha256(channel_header || action sig header
    // || chaincode proposal payload) == prp.proposal_hash
    // (GetProposalHash2, protoutil/txutils.go:431)
    {
      ShaCtx c;
      sha256c_init(&c);
      sha256c_update(&c, buf + payload.header.channel_header.off,
                     payload.header.channel_header.len);
      sha256c_update(&c, buf + action.header.off, action.header.len);
      sha256c_update(&c, buf + cap.chaincode_proposal_payload.off,
                     cap.chaincode_proposal_payload.len);
      uint8_t d[32];
      sha256c_final(&c, d);
      if (prp.proposal_hash.len != 32 ||
          std::memcmp(buf + prp.proposal_hash.off, d, 32) != 0) {
        res->code[i] = INVALID_ENDORSER_TRANSACTION;
        continue;
      }
    }
    ChaincodeAction cc_action;
    if (!parse_chaincode_action(buf, prp.extension, &cc_action)) {
      res->code[i] = BAD_RESPONSE_PAYLOAD;
      continue;
    }
    if (!cc_action.has_chaincode_id || cc_action.chaincode_id.name.len == 0) {
      res->code[i] = INVALID_OTHER_REASON;
      continue;
    }
    std::vector<NsEntry> ns_entries;
    bool has_md = false;
    if (!walk_tx_rwset(buf, cc_action.results, &ns_entries, &has_md)) {
      res->code[i] = BAD_RWSET;
      continue;
    }
    // fully valid endorser tx: commit artifacts + endorsement jobs
    strs[8] = cc_action.chaincode_id.name.off;
    strs[9] = cc_action.chaincode_id.name.len;
    strs[10] = cc_action.results.off;
    strs[11] = cc_action.results.len;
    res->has_md[i] = has_md ? 1 : 0;
    for (NsEntry& ns : ns_entries) {
      int64_t ns_idx = (int64_t)res->ns_tx.size();
      res->ns_tx.push_back(i);
      res->ns_writes.push_back(ns.writes);
      res->ns_str.push_back(ns.name.off);
      res->ns_str.push_back(ns.name.len);
      for (const WKey& wk : ns.wkeys) {
        res->wk_tx.push_back(i);
        res->wk_ns.push_back(ns_idx);
        res->wk_hashed.push_back(wk.hashed);
        res->wk_coll.push_back(wk.coll.off);
        res->wk_coll.push_back(wk.coll.len);
        res->wk_key.push_back(wk.key.off);
        res->wk_key.push_back(wk.key.len);
      }
    }
    for (const EndorsementMsg& e : cap.action.endorsements) {
      res->job_tx.push_back(i);
      res->job_ident.push_back(intern(e.endorser));
      res->job_is_creator.push_back(0);
      res->job_sig.push_back(e.signature.off);
      res->job_sig.push_back(e.signature.len);
      res->job_data.push_back(cap.action.prp.off);
      res->job_data.push_back(cap.action.prp.len);
      // endorsement verifies over prp_bytes || endorser
      // (validator_keylevel.go:243-251)
      ShaCtx c;
      sha256c_init(&c);
      sha256c_update(&c, buf + cap.action.prp.off, cap.action.prp.len);
      sha256c_update(&c, buf + e.endorser.off, e.endorser.len);
      uint8_t d[32];
      sha256c_final(&c, d);
      res->job_digest.insert(res->job_digest.end(), d, d + 32);
    }
  }
  return res;
}

void fn_block_counts(const void* h, int64_t* out) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  out[0] = (int64_t)r->job_tx.size();
  out[1] = (int64_t)(r->uniq.size() / 2);
  out[2] = (int64_t)r->ns_tx.size();
  out[3] = (int64_t)r->wk_tx.size();
}

void fn_block_pertx(const void* h, int32_t* code, int32_t* header_type,
                    uint8_t* has_md, uint64_t* strs) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  std::memcpy(code, r->code.data(), r->code.size() * sizeof(int32_t));
  std::memcpy(header_type, r->header_type.data(),
              r->header_type.size() * sizeof(int32_t));
  std::memcpy(has_md, r->has_md.data(), r->has_md.size());
  std::memcpy(strs, r->strs.data(), r->strs.size() * sizeof(uint64_t));
}

void fn_block_jobs(const void* h, int64_t* job_tx, int64_t* job_ident,
                   uint8_t* job_is_creator, uint64_t* job_sig,
                   uint64_t* job_data, uint8_t* job_digest) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  std::memcpy(job_tx, r->job_tx.data(), r->job_tx.size() * sizeof(int64_t));
  std::memcpy(job_ident, r->job_ident.data(),
              r->job_ident.size() * sizeof(int64_t));
  std::memcpy(job_is_creator, r->job_is_creator.data(),
              r->job_is_creator.size());
  std::memcpy(job_sig, r->job_sig.data(),
              r->job_sig.size() * sizeof(uint64_t));
  std::memcpy(job_data, r->job_data.data(),
              r->job_data.size() * sizeof(uint64_t));
  std::memcpy(job_digest, r->job_digest.data(), r->job_digest.size());
}

void fn_block_uniq(const void* h, uint64_t* uniq) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  std::memcpy(uniq, r->uniq.data(), r->uniq.size() * sizeof(uint64_t));
}

void fn_block_ns(const void* h, int64_t* ns_tx, uint8_t* ns_writes,
                 uint64_t* ns_str) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  std::memcpy(ns_tx, r->ns_tx.data(), r->ns_tx.size() * sizeof(int64_t));
  std::memcpy(ns_writes, r->ns_writes.data(), r->ns_writes.size());
  std::memcpy(ns_str, r->ns_str.data(), r->ns_str.size() * sizeof(uint64_t));
}

void fn_block_wkeys(const void* h, int64_t* wk_tx, int64_t* wk_ns,
                    uint8_t* wk_hashed, uint64_t* wk_coll, uint64_t* wk_key) {
  const auto* r = static_cast<const BlockParseResult*>(h);
  std::memcpy(wk_tx, r->wk_tx.data(), r->wk_tx.size() * sizeof(int64_t));
  std::memcpy(wk_ns, r->wk_ns.data(), r->wk_ns.size() * sizeof(int64_t));
  std::memcpy(wk_hashed, r->wk_hashed.data(), r->wk_hashed.size());
  std::memcpy(wk_coll, r->wk_coll.data(),
              r->wk_coll.size() * sizeof(uint64_t));
  std::memcpy(wk_key, r->wk_key.data(), r->wk_key.size() * sizeof(uint64_t));
}

void fn_block_free(void* h) { delete static_cast<BlockParseResult*>(h); }

int fn_sha256_backend() { return sha256c_backend(); }

}  // extern "C"
