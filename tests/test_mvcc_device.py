"""Differential tests: device MVCC fixpoint vs the host-sequential oracle.

The oracle (fabric_tpu.ledger.mvcc.Validator) mirrors reference
validator.go:82-281; the device path must produce identical codes and
identical update batches for every block shape it accepts, and must fall
back to the oracle for shapes outside its scope (range queries, metadata
writes).
"""

import random

import pytest

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.mvcc_device import DeviceValidator
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_tpu.validation.txflags import TxValidationCode

VALID = TxValidationCode.VALID


def seeded_db(n_keys=40, n_colls=2):
    db = VersionedDB()
    seed = UpdateBatch()
    for i in range(n_keys):
        seed.put("cc", f"k{i}", b"v0", rw.Version(0, i))
    from fabric_tpu.ledger.statedb import HashedUpdateBatch

    hseed = HashedUpdateBatch()
    for c in range(n_colls):
        for i in range(n_keys // 2):
            hseed.put(
                "cc", f"coll{c}", f"h{i}".encode(), b"\x01" * 32, rw.Version(0, i)
            )
    db.apply_updates(seed, hashed=hseed)
    return db


def batches_equal(a, b):
    return dict(a.items()) == dict(b.items())


def assert_same(db, block_num, rwsets, incoming):
    host_codes, host_up, host_hup = Validator(db).validate_and_prepare_batch(
        block_num, rwsets, list(incoming)
    )
    dev = DeviceValidator(db)
    dev_codes, dev_up, dev_hup = dev.validate_and_prepare_batch(
        block_num, rwsets, list(incoming)
    )
    assert dev_codes == host_codes
    assert batches_equal(dev_up, host_up)
    assert batches_equal(dev_hup, host_hup)
    return dev


def test_basic_conflicts_match_oracle():
    db = seeded_db()
    rwsets = [
        # valid: reads own key at committed version, writes it
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (rw.KVRead("k0", rw.Version(0, 0)),),
                    (rw.KVWrite("k0", False, b"v1"),),
                ),
            )
        ),
        # conflict: reads k0 which tx0 (valid) already wrote
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (rw.KVRead("k0", rw.Version(0, 0)),),
                    (rw.KVWrite("k5", False, b"v1"),),
                ),
            )
        ),
        # stale committed version -> conflict regardless of block
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (rw.KVRead("k9", rw.Version(0, 3)),),
                    (rw.KVWrite("k9", False, b"v1"),),
                ),
            )
        ),
        # blind write only -> valid
        rw.TxRwSet(
            (rw.NsRwSet("cc", (), (rw.KVWrite("k30", False, b"v1"),)),)
        ),
        # reads k5: tx1 wrote k5 but tx1 is INVALID -> no conflict
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (rw.KVRead("k5", rw.Version(0, 5)),),
                    (),
                ),
            )
        ),
    ]
    dev = assert_same(db, 7, rwsets, [VALID] * len(rwsets))
    assert dev.last_path == "device"


def test_alternating_chain_needs_multiple_sweeps():
    """tx_i reads the key tx_{i-1} writes (at the committed version), so
    sequential validity alternates valid/invalid/valid/... — the Jacobi
    sweep must iterate chain-depth times to agree with the oracle."""
    db = seeded_db(n_keys=64)
    n = 24
    rwsets = []
    for i in range(n):
        reads = ()
        if i > 0:
            reads = (rw.KVRead(f"k{i - 1}", rw.Version(0, i - 1)),)
        rwsets.append(
            rw.TxRwSet(
                (rw.NsRwSet("cc", reads, (rw.KVWrite(f"k{i}", False, b"n"),)),)
            )
        )
    dev = assert_same(db, 3, rwsets, [VALID] * n)
    assert dev.last_path == "device"


def test_deletes_block_later_reads():
    db = seeded_db()
    rwsets = [
        rw.TxRwSet((rw.NsRwSet("cc", (), (rw.KVWrite("k2", True),)),)),
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k2", rw.Version(0, 2)),), ()),)
        ),
    ]
    assert_same(db, 2, rwsets, [VALID, VALID])


def test_hashed_reads_and_writes():
    db = seeded_db()
    rwsets = [
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (),
                    coll_hashed=(
                        rw.CollHashedRwSet(
                            "coll0",
                            (rw.KVReadHash(b"h0", rw.Version(0, 0)),),
                            (rw.KVWriteHash(b"h1", False, b"\x02" * 32),),
                        ),
                    ),
                ),
            )
        ),
        # conflicts: tx0 wrote h1 in coll0
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (),
                    coll_hashed=(
                        rw.CollHashedRwSet(
                            "coll0",
                            (rw.KVReadHash(b"h1", rw.Version(0, 1)),),
                            (),
                        ),
                    ),
                ),
            )
        ),
        # same key-hash in a DIFFERENT collection: no conflict
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (),
                    coll_hashed=(
                        rw.CollHashedRwSet(
                            "coll1",
                            (rw.KVReadHash(b"h1", rw.Version(0, 1)),),
                            (),
                        ),
                    ),
                ),
            )
        ),
    ]
    assert_same(db, 4, rwsets, [VALID] * 3)


def test_incoming_invalid_and_none_rwsets_pass_through():
    db = seeded_db()
    rwsets = [
        rw.TxRwSet(
            (rw.NsRwSet("cc", (), (rw.KVWrite("k0", False, b"x"),)),)
        ),
        None,
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k0", rw.Version(0, 0)),), ()),)
        ),
    ]
    incoming = [
        TxValidationCode.BAD_CREATOR_SIGNATURE,  # excluded: its write must not count
        VALID,
        VALID,
    ]
    host_codes, *_ = Validator(db).validate_and_prepare_batch(
        1, rwsets, list(incoming)
    )
    assert_same(db, 1, rwsets, incoming)
    # tx0 invalid upstream, so tx2's read of k0 must NOT conflict
    assert host_codes[2] == VALID


def test_range_query_falls_back_to_host():
    db = seeded_db()
    rwsets = [
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (rw.KVWrite("k0", False, b"x"),),
                    range_queries=(
                        rw.RangeQueryInfo(
                            "k0",
                            "k3",
                            True,
                            raw_reads=(
                                rw.KVRead("k0", rw.Version(0, 0)),
                                rw.KVRead("k1", rw.Version(0, 1)),
                                rw.KVRead("k2", rw.Version(0, 2)),
                            ),
                        ),
                    ),
                ),
            )
        ),
    ]
    dev = assert_same(db, 1, rwsets, [VALID])
    assert dev.last_path == "host"


def test_metadata_write_falls_back_to_host():
    db = seeded_db()
    rwsets = [
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (rw.KVWrite("k0", False, b"x"),),
                    metadata_writes=(
                        rw.KVMetadataWrite("k0", (("owner", b"org1"),)),
                    ),
                ),
            )
        ),
    ]
    dev = assert_same(db, 1, rwsets, [VALID])
    assert dev.last_path == "host"


def test_randomized_blocks_match_oracle():
    rng = random.Random(20260731)
    for trial in range(8):
        db = seeded_db(n_keys=30)
        n = rng.randrange(1, 60)
        rwsets = []
        incoming = []
        for t in range(n):
            if rng.random() < 0.05:
                rwsets.append(None)
                incoming.append(VALID)
                continue
            incoming.append(
                VALID
                if rng.random() < 0.9
                else TxValidationCode.ENDORSEMENT_POLICY_FAILURE
            )
            reads = []
            for _ in range(rng.randrange(0, 4)):
                i = rng.randrange(30)
                # mostly correct committed version, sometimes stale/absent
                roll = rng.random()
                if roll < 0.7:
                    ver = rw.Version(0, i)
                elif roll < 0.85:
                    ver = rw.Version(0, i + 1)
                else:
                    ver = None
                reads.append(rw.KVRead(f"k{i}", ver))
            writes = []
            for _ in range(rng.randrange(0, 4)):
                i = rng.randrange(35)
                writes.append(
                    rw.KVWrite(f"k{i}", rng.random() < 0.2, b"w%d" % t)
                )
            colls = []
            if rng.random() < 0.3:
                hreads = []
                for _ in range(rng.randrange(0, 3)):
                    i = rng.randrange(15)
                    hreads.append(
                        rw.KVReadHash(
                            f"h{i}".encode(),
                            rw.Version(0, i) if rng.random() < 0.8 else None,
                        )
                    )
                hwrites = []
                for _ in range(rng.randrange(0, 3)):
                    i = rng.randrange(18)
                    hwrites.append(
                        rw.KVWriteHash(
                            f"h{i}".encode(), rng.random() < 0.2, b"\x03" * 32
                        )
                    )
                colls.append(
                    rw.CollHashedRwSet(
                        f"coll{rng.randrange(2)}", tuple(hreads), tuple(hwrites)
                    )
                )
            rwsets.append(
                rw.TxRwSet(
                    (
                        rw.NsRwSet(
                            "cc",
                            tuple(reads),
                            tuple(writes),
                            coll_hashed=tuple(colls),
                        ),
                    )
                )
            )
        assert_same(db, trial + 1, rwsets, incoming)


# ----------------------------------------------------------------------
# device-RESIDENT version table (round 5): multi-block sequences through
# ONE validator must match a fresh host oracle per block
# ----------------------------------------------------------------------


def test_resident_multi_block_sequence_matches_oracle():
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator

    db = seeded_db()
    res = ResidentDeviceValidator(db, capacity=64)  # force growth too
    rng = random.Random(42)

    for block_num in range(1, 8):
        rwsets = []
        for t in range(12):
            reads = []
            writes = []
            for _ in range(rng.randrange(3)):
                i = rng.randrange(50)  # some keys beyond the seed -> absent
                committed = db.get_version("cc", f"k{i}")
                claim = (
                    committed
                    if rng.random() < 0.7
                    else rw.Version(9, 9)  # stale claim -> conflict
                )
                reads.append(rw.KVRead(f"k{i}", claim))
            for _ in range(rng.randrange(3)):
                i = rng.randrange(50)
                writes.append(
                    rw.KVWrite(f"k{i}", rng.random() < 0.15, b"v")
                )
            # occasional hashed activity
            colls = ()
            if rng.random() < 0.3:
                hi = rng.randrange(25)
                colls = (
                    rw.CollHashedRwSet(
                        "coll0",
                        (
                            rw.KVReadHash(
                                f"h{hi}".encode(),
                                db.get_key_hash_version(
                                    "cc", "coll0", f"h{hi}".encode()
                                ),
                            ),
                        ),
                        (
                            rw.KVWriteHash(
                                f"h{hi}".encode(), False, b"\x02" * 32
                            ),
                        ),
                        (),
                    ),
                )
            rwsets.append(
                rw.TxRwSet(
                    (rw.NsRwSet("cc", tuple(reads), tuple(writes), (), colls),)
                )
            )
        incoming = [VALID] * len(rwsets)
        host_codes, host_up, host_hup = Validator(db).validate_and_prepare_batch(
            block_num, rwsets, list(incoming)
        )
        res_codes, res_up, res_hup = res.validate_and_prepare_batch(
            block_num, rwsets, list(incoming)
        )
        assert res.last_path == "device"
        assert res_codes == host_codes, f"block {block_num}"
        assert batches_equal(res_up, host_up)
        assert batches_equal(res_hup, host_hup)
        db.apply_updates(host_up, hashed=host_hup)


def test_resident_capacity_growth_multiple_doublings_in_one_batch():
    """PR 18 regression (fabtrace transfer-in-loop): capacity growth now
    resolves the final capacity on host and extends the device version
    table with ONE concatenate instead of one per doubling.  A first
    batch that jumps the index 8x past the initial capacity exercises
    the multi-doubling path; verdicts and the refreshed table must stay
    oracle-exact across the growth event and a follow-up block."""
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator

    db = seeded_db(n_keys=70)
    res = ResidentDeviceValidator(db, capacity=8)  # index will pass 64
    for block_num in (1, 2):
        rwsets = []
        for t in range(20):
            i = (block_num * 20 + t * 3) % 70
            reads = [rw.KVRead(f"k{i}", db.get_version("cc", f"k{i}"))]
            writes = [rw.KVWrite(f"k{(i + 1) % 70}", False, b"v")]
            rwsets.append(
                rw.TxRwSet(
                    (rw.NsRwSet("cc", tuple(reads), tuple(writes), (), ()),)
                )
            )
        incoming = [VALID] * len(rwsets)
        host_codes, host_up, host_hup = Validator(db).validate_and_prepare_batch(
            block_num, rwsets, list(incoming)
        )
        res_codes, res_up, res_hup = res.validate_and_prepare_batch(
            block_num, rwsets, list(incoming)
        )
        assert res.last_path == "device"
        assert res_codes == host_codes
        assert batches_equal(res_up, host_up)
        assert batches_equal(res_hup, host_hup)
        db.apply_updates(host_up, hashed=host_hup)
    assert res._cap >= len(res._index)


def test_resident_host_fallback_refreshes_table():
    """A range-query block takes the host path; the resident table must
    refresh the keys it wrote, so the NEXT device block still agrees."""
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator

    db = seeded_db()
    res = ResidentDeviceValidator(db)

    # block 1 (device): touch k0 so it becomes resident
    b1 = [
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (rw.KVRead("k0", rw.Version(0, 0)),),
                    (rw.KVWrite("k0", False, b"v1"),),
                ),
            )
        )
    ]
    codes, up, hup = res.validate_and_prepare_batch(1, b1, [VALID])
    assert res.last_path == "device" and codes == [VALID]
    db.apply_updates(up, hashed=hup)

    # block 2 (host fallback: metadata write present) ALSO writes k0
    b2 = [
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc",
                    (),
                    (rw.KVWrite("k0", False, b"v2"),),
                    (),
                    (),
                    (rw.KVMetadataWrite("k30", (("p", b"x"),)),),
                ),
            )
        )
    ]
    codes, up, hup = res.validate_and_prepare_batch(2, b2, [VALID])
    assert res.last_path == "host" and codes == [VALID]
    db.apply_updates(up, hashed=hup)

    # block 3 (device): a read claiming k0@(2,0) must be VALID; one
    # claiming the stale (1,0) must conflict — both against the
    # REFRESHED resident entry
    b3 = [
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k0", rw.Version(2, 0)),), ()),)
        ),
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k0", rw.Version(1, 0)),), ()),)
        ),
    ]
    codes, _up, _hup = res.validate_and_prepare_batch(3, b3, [VALID, VALID])
    assert res.last_path == "device"
    assert codes == [VALID, TxValidationCode.MVCC_READ_CONFLICT]


def test_resident_aborted_encode_keeps_slots_seeded():
    """An encode that aborts midway (metadata write later in the block)
    has already assigned slots; their seeds must survive via the pending
    queue or later device blocks see uninitialized sentinels (review r5
    finding)."""
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator

    db = seeded_db()
    res = ResidentDeviceValidator(db)
    # tx0 reads k5 (slot assigned + seed collected), tx1 forces abort
    b1 = [
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k5", rw.Version(0, 5)),), ()),)
        ),
        rw.TxRwSet(
            (
                rw.NsRwSet(
                    "cc", (), (), (), (),
                    (rw.KVMetadataWrite("k9", (("p", b"x"),)),),
                ),
            )
        ),
    ]
    codes, up, hup = res.validate_and_prepare_batch(1, b1, [VALID, VALID])
    assert res.last_path == "host" and codes == [VALID, VALID]
    db.apply_updates(up, hashed=hup)

    # device block: k5's read at its TRUE committed version must pass
    b2 = [
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k5", rw.Version(0, 5)),), ()),)
        ),
        rw.TxRwSet(
            (rw.NsRwSet("cc", (rw.KVRead("k5", rw.Version(7, 7)),), ()),)
        ),
    ]
    codes, _u, _h = res.validate_and_prepare_batch(2, b2, [VALID, VALID])
    assert res.last_path == "device"
    assert codes == [VALID, TxValidationCode.MVCC_READ_CONFLICT]
