"""Orderer stack: registrar + broadcast handler + msgprocessor over a real
channel config (reference orderer/common/{broadcast,msgprocessor,
multichannel})."""

import pytest

pytest.importorskip(
    "cryptography", reason="orderer processors verify X.509 org identities"
)

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.channelconfig import encoder
from fabric_tpu.channelconfig.configtx import sign_config_update
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos import common_pb2, configtx_pb2, protoutil


@pytest.fixture(scope="module")
def world():
    org1 = generate_org("org1")
    org2 = generate_org("org2")
    oorg = generate_org("ord")
    profile = Profile(
        consortium="SampleConsortium",
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("org1MSP", org1.msp_config()),
                OrganizationProfile("org2MSP", org2.msp_config()),
            ],
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            max_message_count=2,
            organizations=[OrganizationProfile("ordMSP", oorg.msp_config())],
        ),
    )
    return org1, org2, oorg, profile


def make_envelope(signer: SigningIdentity, channel_id: str, body: bytes):
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id
    )
    payload.header.channel_header = chdr.SerializeToString()
    shdr = protoutil.make_signature_header(signer.serialize(), signer.new_nonce())
    payload.header.signature_header = shdr.SerializeToString()
    payload.data = body
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    env.signature = signer.sign(env.payload)
    return env


def test_broadcast_orders_signed_envelopes(tmp_path, world):
    org1, org2, oorg, profile = world
    reg = Registrar(str(tmp_path), signer=SigningIdentity(oorg.peers[0]))
    blocks = []
    reg.on_block(lambda ch, b: blocks.append((ch, b)))
    reg.join_channel(genesis_block(profile, "mychannel"))
    h = BroadcastHandler(reg)

    writer = SigningIdentity(org1.peers[0])
    status, info = h.process_message(make_envelope(writer, "mychannel", b"tx1"))
    assert status == common_pb2.SUCCESS, info
    status, _ = h.process_message(make_envelope(writer, "mychannel", b"tx2"))
    assert status == common_pb2.SUCCESS
    # max_message_count=2 -> one block cut
    assert reg.get_chain("mychannel").height == 2  # genesis + 1
    # genesis + the cut block both hit the deliver sink
    assert [b.header.number for _, b in blocks] == [0, 1]


def test_broadcast_rejects_unsigned_and_unknown(tmp_path, world):
    org1, org2, oorg, profile = world
    reg = Registrar(str(tmp_path))
    reg.join_channel(genesis_block(profile, "mychannel"))
    h = BroadcastHandler(reg)

    env = common_pb2.Envelope()
    env.payload = b"garbage"
    status, _ = h.process_message(env)
    assert status == common_pb2.BAD_REQUEST

    # unknown channel, normal message
    writer = SigningIdentity(org1.peers[0])
    status, _ = h.process_message(make_envelope(writer, "nochannel", b"tx"))
    assert status == common_pb2.NOT_FOUND

    # forged signature -> FORBIDDEN
    env = make_envelope(writer, "mychannel", b"tx")
    env.signature = b"\x30\x06\x02\x01\x01\x02\x01\x01"
    status, _ = h.process_message(env)
    assert status == common_pb2.FORBIDDEN


def test_stranger_cannot_write(tmp_path, world):
    _, _, _, profile = world
    stranger = generate_org("org1")  # same MSP id, different CA
    reg = Registrar(str(tmp_path))
    reg.join_channel(genesis_block(profile, "mychannel"))
    h = BroadcastHandler(reg)
    env = make_envelope(SigningIdentity(stranger.peers[0]), "mychannel", b"tx")
    status, _ = h.process_message(env)
    assert status == common_pb2.FORBIDDEN


def test_config_update_via_broadcast(tmp_path, world):
    org1, org2, oorg, profile = world
    reg = Registrar(str(tmp_path), signer=SigningIdentity(oorg.peers[0]))
    reg.join_channel(genesis_block(profile, "mychannel"))
    h = BroadcastHandler(reg)
    support = reg.get_chain("mychannel")
    cur = support.validator.config.channel_group

    # orderer admin bumps BatchSize via CONFIG_UPDATE
    from fabric_tpu.protos import configuration_pb2
    from fabric_tpu.channelconfig import configtx as configtx_mod

    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "mychannel"
    rs = update.read_set.groups["Orderer"]
    rs.values["BatchSize"].SetInParent()
    ws = update.write_set.groups["Orderer"]
    bs = configuration_pb2.BatchSize()
    bs.max_message_count = 3
    bs.absolute_max_bytes = 1 << 20
    bs.preferred_max_bytes = 1 << 19
    ws.values["BatchSize"].value = bs.SerializeToString()
    ws.values["BatchSize"].version = 1
    ws.values["BatchSize"].mod_policy = "Admins"
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    configtx_mod.sign_config_update(cue, SigningIdentity(oorg.admin))

    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.CONFIG_UPDATE, "mychannel")
    payload.header.channel_header = chdr.SerializeToString()
    signer = SigningIdentity(oorg.admin)
    shdr = protoutil.make_signature_header(signer.serialize(), signer.new_nonce())
    payload.header.signature_header = shdr.SerializeToString()
    payload.data = cue.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    env.signature = signer.sign(env.payload)

    status, info = h.process_message(env)
    assert status == common_pb2.SUCCESS, info
    # config block written alone; processor hot-swapped to the new bundle
    assert support.height == 2
    assert support.bundle.orderer.batch_size_max_messages == 3
    assert support.validator.sequence == 1
    # the config block carries last_update for peer-side re-validation
    block = support.get_block(1)
    env2 = protoutil.get_envelope_from_block_data(block.data.data[0])
    payload2 = protoutil.unmarshal(common_pb2.Payload, env2.payload)
    cenv = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload2.data)
    assert cenv.HasField("last_update")


def test_system_channel_creates_channel(tmp_path, world):
    org1, org2, oorg, profile = world
    sys_profile = Profile(
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[OrganizationProfile("ordMSP", oorg.msp_config())],
        ),
        consortiums={
            "SampleConsortium": [
                OrganizationProfile("org1MSP", org1.msp_config()),
                OrganizationProfile("org2MSP", org2.msp_config()),
            ]
        },
    )
    reg = Registrar(
        str(tmp_path),
        signer=SigningIdentity(oorg.peers[0]),
        system_channel_id="syschannel",
    )
    reg.join_channel(genesis_block(sys_profile, "syschannel"))
    h = BroadcastHandler(reg)

    update = encoder.channel_creation_config_update(
        "appchannel",
        "SampleConsortium",
        ApplicationProfile(
            organizations=[
                OrganizationProfile("org1MSP", org1.msp_config()),
                OrganizationProfile("org2MSP", org2.msp_config()),
            ]
        ),
    )
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    # The consortium's ChannelCreationPolicy (ANY Admins) is enforced over
    # the ConfigUpdateEnvelope signatures — sign as an org admin.
    sign_config_update(cue, SigningIdentity(org1.admin))

    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.CONFIG_UPDATE, "appchannel")
    payload.header.channel_header = chdr.SerializeToString()
    signer = SigningIdentity(org1.admin)
    shdr = protoutil.make_signature_header(signer.serialize(), signer.new_nonce())
    payload.header.signature_header = shdr.SerializeToString()
    payload.data = cue.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    env.signature = signer.sign(env.payload)

    status, info = h.process_message(env)
    assert status == common_pb2.SUCCESS, info
    assert "appchannel" in reg.channel_list()
    app_support = reg.get_chain("appchannel")
    assert app_support.height == 1  # its genesis config block
    assert {o.msp_id for o in app_support.bundle.application.orgs} == {
        "org1MSP",
        "org2MSP",
    }

    # the new channel accepts writes from consortium members
    status, info = h.process_message(
        make_envelope(SigningIdentity(org1.peers[0]), "appchannel", b"tx")
    )
    assert status == common_pb2.SUCCESS, info


def test_channel_creation_requires_creation_policy_signature(tmp_path, world):
    """Regression: an UNSIGNED config update must not create a channel —
    the consortium ChannelCreationPolicy (ANY Admins) is enforced."""
    org1, org2, oorg, profile = world
    sys_profile = Profile(
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[OrganizationProfile("ordMSP", oorg.msp_config())],
        ),
        consortiums={
            "SampleConsortium": [
                OrganizationProfile("org1MSP", org1.msp_config()),
                OrganizationProfile("org2MSP", org2.msp_config()),
            ]
        },
    )
    reg = Registrar(
        str(tmp_path),
        signer=SigningIdentity(oorg.peers[0]),
        system_channel_id="syschannel",
    )
    reg.join_channel(genesis_block(sys_profile, "syschannel"))
    h = BroadcastHandler(reg)

    update = encoder.channel_creation_config_update(
        "rogue",
        "SampleConsortium",
        ApplicationProfile(
            organizations=[OrganizationProfile("org1MSP", org1.msp_config())]
        ),
    )
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    # no sign_config_update: zero ConfigSignatures
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.CONFIG_UPDATE, "rogue")
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = (
        common_pb2.SignatureHeader().SerializeToString()
    )
    payload.data = cue.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()

    status, info = h.process_message(env)
    assert status != common_pb2.SUCCESS
    assert "rogue" not in reg.channel_list()
    # non-admin signature (a peer) is also insufficient for ANY Admins
    sign_config_update(cue, SigningIdentity(org1.peers[0]))
    payload.data = cue.SerializeToString()
    env.payload = payload.SerializeToString()
    status, info = h.process_message(env)
    assert status != common_pb2.SUCCESS
    assert "rogue" not in reg.channel_list()
