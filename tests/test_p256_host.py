"""Oracle tests: fabric_tpu.crypto.p256 vs the `cryptography` package."""

import hashlib
import secrets

import pytest

pytest.importorskip(
    "cryptography", reason="oracle-vs-OpenSSL tests need cryptography"
)

from cryptography.exceptions import InvalidSignature  # noqa: E402
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)

from fabric_tpu.crypto import der, p256
from fabric_tpu.crypto.bccsp import SoftwareProvider, VerifyError


def _cryptography_verify(pub, digest, r, s) -> bool:
    key = ec.EllipticCurvePublicNumbers(pub[0], pub[1], ec.SECP256R1()).public_key()
    try:
        key.verify(
            encode_dss_signature(r, s), digest, ec.ECDSA(Prehashed(hashes.SHA256()))
        )
        return True
    except InvalidSignature:
        return False


def test_generator_on_curve():
    assert p256.is_on_curve(p256.GENERATOR)
    assert p256.scalar_mult(p256.N, p256.GENERATOR) is None


def test_sign_verify_roundtrip():
    kp = p256.generate_keypair()
    digest = hashlib.sha256(b"hello fabric").digest()
    r, s = p256.sign_digest(kp.priv, digest)
    assert p256.is_low_s(s)
    assert p256.verify_digest(kp.pub, digest, r, s)
    assert not p256.verify_digest(kp.pub, digest, r, (s + 1) % p256.N)
    assert not p256.verify_digest(kp.pub, hashlib.sha256(b"x").digest(), r, s)


def test_verify_matches_cryptography_library():
    for _ in range(8):
        kp = p256.generate_keypair()
        digest = hashlib.sha256(secrets.token_bytes(32)).digest()
        r, s = p256.sign_digest(kp.priv, digest, low_s=False)
        assert p256.verify_digest(kp.pub, digest, r, s)
        assert _cryptography_verify(kp.pub, digest, r, s)
        # Corrupt cases agree too.
        bad = (r, (s * 2) % p256.N)
        assert p256.verify_digest(kp.pub, digest, *bad) == _cryptography_verify(
            kp.pub, digest, *bad
        )


def test_cryptography_signature_verifies_in_oracle():
    key = ec.generate_private_key(ec.SECP256R1())
    msg = b"signed by the cryptography package"
    sig = key.sign(msg, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(sig)
    pub_nums = key.public_key().public_numbers()
    pub = (pub_nums.x, pub_nums.y)
    assert p256.verify_digest(pub, hashlib.sha256(msg).digest(), r, s)


def test_edge_scalars():
    kp = p256.generate_keypair()
    digest = hashlib.sha256(b"edge").digest()
    assert not p256.verify_digest(kp.pub, digest, 0, 1)
    assert not p256.verify_digest(kp.pub, digest, 1, 0)
    assert not p256.verify_digest(kp.pub, digest, p256.N, 1)
    assert not p256.verify_digest(kp.pub, digest, 1, p256.N)


class TestDer:
    def test_roundtrip(self):
        for r, s in [(1, 1), (p256.N - 1, p256.HALF_N), (2**255, 127), (128, 255)]:
            raw = der.marshal_signature(r, s)
            assert der.unmarshal_signature(raw) == (r, s)

    def test_matches_cryptography_encoding(self):
        for _ in range(4):
            r = secrets.randbelow(p256.N - 1) + 1
            s = secrets.randbelow(p256.N - 1) + 1
            assert der.marshal_signature(r, s) == encode_dss_signature(r, s)

    def test_rejects_zero_and_negative(self):
        # R = 0
        with pytest.raises(der.DerError):
            der.unmarshal_signature(bytes.fromhex("3006020100020101"))
        # R = -1 (0xFF single byte)
        with pytest.raises(der.DerError):
            der.unmarshal_signature(bytes.fromhex("30060201FF020101"))

    def test_rejects_non_minimal_integer(self):
        # R = 1 encoded as 00 01
        with pytest.raises(der.DerError):
            der.unmarshal_signature(bytes.fromhex("3007020200010201 01".replace(" ", "")))

    def test_rejects_non_minimal_length(self):
        # SEQUENCE length 6 encoded in long form 0x81 0x06
        with pytest.raises(der.DerError):
            der.unmarshal_signature(bytes.fromhex("308106020101020101"))

    def test_rejects_indefinite_length(self):
        with pytest.raises(der.DerError):
            der.unmarshal_signature(bytes.fromhex("3080020101020101 0000".replace(" ", "")))

    def test_trailing_bytes_after_sequence_tolerated(self):
        raw = der.marshal_signature(5, 7) + b"\xde\xad"
        assert der.unmarshal_signature(raw) == (5, 7)

    def test_extra_bytes_inside_sequence_tolerated(self):
        # Go allows extra members at the end of a SEQUENCE.
        body = b"\x02\x01\x05" + b"\x02\x01\x07" + b"\x01\x01\x00"
        raw = b"\x30" + bytes([len(body)]) + body
        assert der.unmarshal_signature(raw) == (5, 7)

    def test_truncated(self):
        raw = der.marshal_signature(5, 7)
        with pytest.raises(der.DerError):
            der.unmarshal_signature(raw[:-1])


class TestSoftwareProvider:
    def test_verify_semantics(self):
        prov = SoftwareProvider()
        key = prov.key_gen()
        digest = prov.hash(b"payload bytes")
        sig = prov.sign(key, digest)
        assert prov.verify(key.public, sig, digest)

        # High-S rejection is an *error*, like the reference.
        r, s = der.unmarshal_signature(sig)
        high = der.marshal_signature(r, p256.N - s)
        with pytest.raises(VerifyError):
            prov.verify(key.public, high, digest)

        # Malformed DER is an error.
        with pytest.raises(VerifyError):
            prov.verify(key.public, b"\x30\x00", digest)

        # Wrong digest is a clean False.
        assert not prov.verify(key.public, sig, prov.hash(b"other"))

    def test_batch_verify_mask(self):
        prov = SoftwareProvider()
        keys, sigs, digests, expect = [], [], [], []
        key = prov.key_gen()
        for i in range(16):
            digest = prov.hash(f"msg {i}".encode())
            sig = prov.sign(key, digest)
            ok = i % 3 != 0
            if not ok:
                digest = prov.hash(f"tampered {i}".encode())
            keys.append(key.public)
            sigs.append(sig)
            digests.append(digest)
            expect.append(ok)
        assert prov.batch_verify(keys, sigs, digests) == expect
