"""Randomized TRANSACTIONS_FILTER parity fuzz: blocks with a mix of
valid txs, corrupted creator/endorser signatures, wrong-channel txs,
unknown chaincodes, under-endorsed txs and in-block duplicate txids,
validated twice — once through the batched validator with the OpenSSL
SoftwareProvider, once with the clarity-first PurePythonProvider oracle —
asserting the byte-identical filter (reference parity surface:
TRANSACTIONS_FILTER, v20/validator.go).

This pins the batched assembly/policy pipeline against provider-level
differences; the device kernel's own parity is covered by
tests/test_p256_kernel.py and tests/test_parallel.py."""

import random

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.crypto.bccsp import PurePythonProvider, SoftwareProvider
from fabric_tpu.endorser import (
    create_proposal,
    create_signed_tx,
    endorse_proposal,
)
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.validation.validator import (
    BlockValidator,
    ChaincodeDefinition,
    ChaincodeRegistry,
)

CHANNEL = "fuzzchan"
RNG = random.Random(20260801)


@pytest.fixture(scope="module")
def world():
    sw = SoftwareProvider()
    orgs = [generate_org(f"org{i}.fuzz", f"Org{i}MSP") for i in (1, 2, 3)]
    mgr = MSPManager([o.msp(provider=sw) for o in orgs])
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "fuzzcc",
                from_dsl(
                    "OutOf(2,'Org1MSP.member','Org2MSP.member',"
                    "'Org3MSP.member')"
                ),
            )
        ]
    )
    client = SigningIdentity(orgs[0].users[0], sw)
    endorsers = [SigningIdentity(o.peers[0], sw) for o in orgs]
    return {
        "mgr": mgr,
        "registry": registry,
        "client": client,
        "endorsers": endorsers,
    }


def _tx(world, i, mutate: str):
    results = serialize_tx_rwset(
        rw.TxRwSet(
            (rw.NsRwSet("fuzzcc", (), (rw.KVWrite(f"k{i}", False, b"v"),)),)
        )
    )
    channel = "otherchan" if mutate == "wrong_channel" else CHANNEL
    cc = "ghostcc" if mutate == "unknown_cc" else "fuzzcc"
    bundle = create_proposal(world["client"], channel, cc, [b"x", b"%d" % i])
    n_endorse = 1 if mutate == "under_endorsed" else 2
    picks = RNG.sample(world["endorsers"], n_endorse)
    responses = [endorse_proposal(bundle, e, results) for e in picks]
    env = create_signed_tx(bundle, world["client"], responses)
    raw = bytearray(env.SerializeToString())
    if mutate == "corrupt_bytes":
        # flip one byte near the tail (inside some signature/payload);
        # both providers must agree on WHATEVER code this produces
        raw[-RNG.randrange(1, 40)] ^= 0x40
    return bytes(raw)


MUTATIONS = [
    "valid",
    "valid",
    "valid",
    "wrong_channel",
    "unknown_cc",
    "under_endorsed",
    "corrupt_bytes",
]


def _block(world, n_txs, number=7):
    block = protoutil.new_block(number, b"\x42" * 32)
    datas = []
    for i in range(n_txs):
        datas.append(_tx(world, i, RNG.choice(MUTATIONS)))
    if n_txs >= 4 and RNG.random() < 0.8:
        # in-block duplicate txid: a later copy of an earlier envelope
        datas[RNG.randrange(n_txs // 2, n_txs)] = datas[
            RNG.randrange(0, n_txs // 2)
        ]
    for d in datas:
        block.data.data.append(d)
    protoutil.seal_block(block)
    return block


@pytest.mark.parametrize("round_num", range(6))
def test_filter_parity_under_fuzz(world, round_num):
    block = _block(world, n_txs=RNG.randrange(6, 18), number=round_num + 1)

    masks = []
    for provider in (SoftwareProvider(), PurePythonProvider()):
        b = common_pb2.Block()
        b.CopyFrom(block)
        validator = BlockValidator(
            CHANNEL, world["mgr"], provider, world["registry"]
        )
        masks.append(validator.validate(b).tobytes())
    assert masks[0] == masks[1]
    # sanity: the fuzz actually produced a mix, not all-valid blocks
    if round_num == 0:
        assert len(set(masks[0])) >= 2


# ----------------------------------------------------------------------
# plugin dispatch under fuzz (round 5): a block mixing plugin-bound and
# builtin namespaces with the same mutation corpus must produce
# identical filters across providers, and the plugin's verdicts must
# deterministically shape the mask
# ----------------------------------------------------------------------


@pytest.mark.parametrize("round_num", range(4))
def test_filter_parity_with_plugin_dispatch(world, round_num):
    from fabric_tpu.validation.dispatcher import PluginRegistry
    from fabric_tpu.validation.plugin_api import (
        EndorsementInvalid,
        ValidationPlugin,
    )

    class ParityPlugin(ValidationPlugin):
        """Deterministic rules only (provider-independent): default
        policy must hold AND the tx_id's last hex digit must be even —
        an arbitrary but stable extra rule so the plugin actually
        invalidates a subset."""

        def validate(self, ctx):
            if not ctx.default_check():
                raise EndorsementInvalid("policy")
            if ctx.tx_id and int(ctx.tx_id[-1], 16) % 2 == 1:
                raise EndorsementInvalid("odd txid")

    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "fuzzcc",
                from_dsl(
                    "OutOf(2,'Org1MSP.member','Org2MSP.member',"
                    "'Org3MSP.member')"
                ),
                plugin="parity",
            )
        ]
    )
    block = _block(world, n_txs=RNG.randrange(6, 14), number=round_num + 20)

    masks = []
    for provider in (SoftwareProvider(), PurePythonProvider()):
        plugins = PluginRegistry()
        plugins.register("parity", ParityPlugin())
        b = common_pb2.Block()
        b.CopyFrom(block)
        validator = BlockValidator(
            CHANNEL, world["mgr"], provider, registry,
            plugin_registry=plugins,
        )
        masks.append(validator.validate(b).tobytes())
    assert masks[0] == masks[1]

    # cross-check against the builtin path: any tx the BUILTIN validator
    # rejects must also be rejected under the plugin (it only ADDS a
    # rule on top of default_check)
    b = common_pb2.Block()
    b.CopyFrom(block)
    builtin_mask = BlockValidator(
        CHANNEL, world["mgr"], SoftwareProvider(), world["registry"]
    ).validate(b).tobytes()
    for plugin_code, builtin_code in zip(masks[0], builtin_mask):
        if builtin_code != 0:
            assert plugin_code != 0, (plugin_code, builtin_code)
