"""End-to-end block validation: build real envelopes with real crypto and
check the TRANSACTIONS_FILTER mask scenario by scenario (modeled on the
reference's txvalidator_test.go)."""

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import (
    BlockValidator,
    ChaincodeDefinition,
    ChaincodeRegistry,
)

CHANNEL = "testchannel"
PROVIDER = SoftwareProvider()


@pytest.fixture(scope="module")
def net():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "mycc", from_dsl("AND('Org1MSP.member','Org2MSP.member')")
            ),
            ChaincodeDefinition("anycc", from_dsl("OR('Org1MSP.member','Org2MSP.member')")),
        ]
    )
    return {
        "org1": org1,
        "org2": org2,
        "mgr": mgr,
        "registry": registry,
        "client": SigningIdentity(org1.users[0], PROVIDER),
        "p1": SigningIdentity(org1.peers[0], PROVIDER),
        "p2": SigningIdentity(org2.peers[0], PROVIDER),
    }


def results_bytes(key="k1", value=b"v1", ns="mycc"):
    return serialize_tx_rwset(
        rw.TxRwSet(
            (rw.NsRwSet(ns, (), (rw.KVWrite(key, False, value),)),)
        )
    )


def make_tx(net, cc="mycc", endorsers=("p1", "p2"), channel=CHANNEL, mangle=None):
    bundle = create_proposal(net["client"], channel, cc, [b"invoke", b"a"])
    responses = [
        endorse_proposal(bundle, net[e], results_bytes(ns=cc)) for e in endorsers
    ]
    env = create_signed_tx(bundle, net["client"], responses)
    if mangle:
        env = mangle(env, bundle)
    return env


def make_block(envelopes, number=7):
    block = protoutil.new_block(number, b"\x11" * 32)
    for env in envelopes:
        data = env if isinstance(env, bytes) else env.SerializeToString()
        block.data.data.append(data)
    protoutil.seal_block(block)
    return block


def validator(net, tx_exists=None):
    return BlockValidator(
        CHANNEL,
        net["mgr"],
        PROVIDER,
        net["registry"],
        tx_exists=tx_exists,
    )


V = TxValidationCode


class TestBlockValidation:
    def test_scenarios(self, net):
        def bad_creator_sig(env, bundle):
            env.signature = env.signature[:-6] + b"\x00\x01\x02\x03\x04\x05"
            return env

        def bad_txid(env, bundle):
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            chdr.tx_id = "deadbeef" * 8
            payload.header.channel_header = chdr.SerializeToString()
            env.payload = payload.SerializeToString()
            env.signature = net["client"].sign(env.payload)
            return env

        def tampered_proposal_payload(env, bundle):
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            tx = protoutil.unmarshal(peer_pb2.Transaction, payload.data)
            cap = protoutil.unmarshal(
                peer_pb2.ChaincodeActionPayload, tx.actions[0].payload
            )
            cap.chaincode_proposal_payload = cap.chaincode_proposal_payload + b"x"
            tx.actions[0].payload = cap.SerializeToString()
            payload.data = tx.SerializeToString()
            env.payload = payload.SerializeToString()
            env.signature = net["client"].sign(env.payload)
            return env

        def tampered_endorsement(env, bundle):
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            tx = protoutil.unmarshal(peer_pb2.Transaction, payload.data)
            cap = protoutil.unmarshal(
                peer_pb2.ChaincodeActionPayload, tx.actions[0].payload
            )
            sig = bytearray(cap.action.endorsements[1].signature)
            sig[-1] ^= 0xFF
            cap.action.endorsements[1].signature = bytes(sig)
            tx.actions[0].payload = cap.SerializeToString()
            payload.data = tx.SerializeToString()
            env.payload = payload.SerializeToString()
            env.signature = net["client"].sign(env.payload)
            return env

        dup = make_tx(net)
        envs = [
            make_tx(net),  # 0 VALID
            make_tx(net, endorsers=("p1",)),  # 1 policy failure (1 of 2)
            make_tx(net, mangle=bad_creator_sig),  # 2
            make_tx(net, mangle=bad_txid),  # 3
            b"\x03\x01garbage-not-an-envelope",  # 4
            b"",  # 5 nil
            dup,  # 6 VALID
            dup,  # 7 duplicate of 6
            make_tx(net, cc="nosuchcc"),  # 8 unknown chaincode
            make_tx(net, channel="otherchannel"),  # 9 wrong channel
            make_tx(net, mangle=tampered_proposal_payload),  # 10
            make_tx(net, mangle=tampered_endorsement),  # 11 sig fails -> 1of2
            make_tx(net, cc="anycc", endorsers=("p2",)),  # 12 OR policy
        ]
        block = make_block(envs)
        flags = validator(net).validate(block)
        expected = [
            V.VALID,
            V.ENDORSEMENT_POLICY_FAILURE,
            V.BAD_CREATOR_SIGNATURE,
            V.BAD_PROPOSAL_TXID,
            V.INVALID_OTHER_REASON,
            V.NIL_ENVELOPE,
            V.VALID,
            V.DUPLICATE_TXID,
            V.INVALID_CHAINCODE,
            V.TARGET_CHAIN_NOT_FOUND,
            V.INVALID_ENDORSER_TRANSACTION,
            V.ENDORSEMENT_POLICY_FAILURE,
            V.VALID,
        ]
        got = [flags.flag(i) for i in range(len(envs))]
        assert got == expected
        # metadata write parity: uint8 array in TRANSACTIONS_FILTER slot
        assert block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] == bytes(
            int(c) for c in expected
        )

    def test_ledger_duplicate(self, net):
        env = make_tx(net)
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        chdr = protoutil.unmarshal(common_pb2.ChannelHeader, payload.header.channel_header)
        block = make_block([env])
        flags = validator(net, tx_exists=lambda t: t == chdr.tx_id).validate(block)
        assert flags.flag(0) == V.DUPLICATE_TXID

    def test_duplicate_endorsements_dedupe(self, net):
        # The same endorser twice satisfies AND(Org1, Org2) only once ->
        # dedupe must make this fail (policy.go:383-388 anti-DoS).
        env = make_tx(net, endorsers=("p1", "p1"))
        flags = validator(net).validate(make_block([env]))
        assert flags.flag(0) == V.ENDORSEMENT_POLICY_FAILURE

    def test_revoked_endorser(self, net):
        org1, org2 = net["org1"], net["org2"]
        revoked = org1.ca.enroll("peer9.org1.example.com", ou="peer")
        org1.ca.revoke(revoked)
        mgr = MSPManager(
            [org1.msp(provider=PROVIDER, with_crl=True), org2.msp(provider=PROVIDER)]
        )
        v = BlockValidator(CHANNEL, mgr, PROVIDER, net["registry"])
        env = make_tx(
            {**net, "p1": SigningIdentity(revoked, PROVIDER)},
        )
        flags = v.validate(make_block([env]))
        assert flags.flag(0) == V.ENDORSEMENT_POLICY_FAILURE

    def test_config_tx_valid(self, net):
        applied = []
        env = common_pb2.Envelope()
        payload = common_pb2.Payload()
        chdr = protoutil.make_channel_header(common_pb2.CONFIG, CHANNEL)
        payload.header.channel_header = chdr.SerializeToString()
        shdr = protoutil.make_signature_header(net["client"].serialize(), b"\x01" * 24)
        payload.header.signature_header = shdr.SerializeToString()
        payload.data = b"\x0a\x00"  # empty-ish config envelope
        env.payload = payload.SerializeToString()
        env.signature = net["client"].sign(env.payload)
        v = BlockValidator(
            CHANNEL,
            net["mgr"],
            PROVIDER,
            net["registry"],
            apply_config=lambda d: applied.append(d),
        )
        flags = v.validate(make_block([env]))
        assert flags.flag(0) == V.VALID
        assert applied


class TestCrossNamespaceDispatch:
    """Every written namespace validates against ITS OWN policy
    (reference plugindispatcher/dispatcher.go:174-218)."""

    def _tx(self, net, endorsers):
        bundle = create_proposal(net["client"], CHANNEL, "anycc", [b"i"])
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet("anycc", (), (rw.KVWrite("a", False, b"1"),)),
                    rw.NsRwSet("mycc", (), (rw.KVWrite("k", False, b"2"),)),
                )
            )
        )
        responses = [
            endorse_proposal(bundle, net[e], results) for e in endorsers
        ]
        return create_signed_tx(bundle, net["client"], responses)

    def test_foreign_namespace_policy_enforced(self, net):
        # anycc's OR policy passes with p2 alone, but the write into
        # mycc (2-of-2) must also satisfy mycc's policy -> failure
        flags = validator(net).validate(make_block([self._tx(net, ("p2",))]))
        assert flags.flag(0) == V.ENDORSEMENT_POLICY_FAILURE

    def test_all_policies_satisfied(self, net):
        flags = validator(net).validate(
            make_block([self._tx(net, ("p1", "p2"))])
        )
        assert flags.flag(0) == V.VALID

    def test_duplicate_namespace_illegal_writeset(self, net):
        bundle = create_proposal(net["client"], CHANNEL, "mycc", [b"i"])
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet("mycc", (), (rw.KVWrite("a", False, b"1"),)),
                    rw.NsRwSet("mycc", (), (rw.KVWrite("b", False, b"2"),)),
                )
            )
        )
        responses = [
            endorse_proposal(bundle, net[e], results) for e in ("p1", "p2")
        ]
        env = create_signed_tx(bundle, net["client"], responses)
        flags = validator(net).validate(make_block([env]))
        assert flags.flag(0) == V.ILLEGAL_WRITESET

    def test_read_only_foreign_namespace_not_policy_checked(self, net):
        # reads from another namespace don't drag in its policy
        bundle = create_proposal(net["client"], CHANNEL, "anycc", [b"i"])
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet("anycc", (), (rw.KVWrite("a", False, b"1"),)),
                    rw.NsRwSet(
                        "mycc", (rw.KVRead("k", rw.Version(1, 0)),), ()
                    ),
                )
            )
        )
        responses = [endorse_proposal(bundle, net["p2"], results)]
        env = create_signed_tx(bundle, net["client"], responses)
        flags = validator(net).validate(make_block([env]))
        assert flags.flag(0) == V.VALID
