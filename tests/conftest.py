"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(jax.sharding.Mesh over 8 devices) are exercised without TPU hardware.

The driver environment imports jax at interpreter startup (an axon
sitecustomize registers the TPU-tunnel PJRT plugin and pins
JAX_PLATFORMS=axon), so env vars set here are too late for jax's
config defaults — everything must go through jax.config.update, which is
read dynamically. XLA_FLAGS is still honored because no backend is
initialized until the first jax use inside the tests.

The big ECDSA verify kernel costs minutes of XLA:CPU compile time the
first run; the persistent compilation cache in .jax_cache makes every
later run fast. Keep that directory out of git but on disk.
"""

import importlib.util
import os

import pytest

# Shared marker: tests needing X.509 / TLS material skip cleanly in
# minimal environments (test modules `from conftest import requires_crypto`).
requires_crypto = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="needs the cryptography package (X.509 / TLS material)",
)

os.environ.setdefault("FABRIC_TPU_CIOS_UNROLL", "0")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

from fabric_tpu.utils.jaxcache import enable_compile_cache  # noqa: E402

jax.config.update("jax_platforms", "cpu")
enable_compile_cache()

# Opt-in persistent-cache forensics: FABRIC_TPU_CACHE_DEBUG=1 logs every
# compilation-cache hit/miss/write with its key (the env-var route is
# too late here for the same reason as above).
if os.environ.get("FABRIC_TPU_CACHE_DEBUG") == "1":
    jax.config.update(
        "jax_debug_log_modules",
        "jax._src.compiler,jax._src.compilation_cache",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from tier-1 (-m 'not slow')",
    )
