"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(jax.sharding.Mesh over 8 devices) are exercised without TPU hardware.
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Keep XLA compiles fast on the CPU test backend (see fabric_tpu.ops.bignum).
os.environ.setdefault("FABRIC_TPU_CIOS_UNROLL", "0")
# Persistent compile cache: the ECDSA kernel costs minutes to compile.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
