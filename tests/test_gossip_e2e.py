"""Gossip-enabled subprocess network: two REAL peer processes on one
channel; the elected leader pulls from the orderer and the follower —
which has NO deliver client of its own — converges via gossip push/pull
(reference: gossip service + deliveryclient leader election, the
default peer deployment shape). Gossip runs over mTLS with the
ConnEstablish cert-hash handshake, using the tls/ material cryptogen
now emits per peer."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytest.importorskip(
    "cryptography", reason="gossip e2e rides TLS + X.509 identities"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(mod, *args, timeout=90):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", mod, *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    assert out.returncode == 0, f"{mod} {args}:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def spawn(mod, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )


def wait_line(proc, needle, timeout=60):
    deadline = time.time() + timeout
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"process exited {proc.returncode}: {''.join(lines)}"
                )
            continue
        lines.append(line)
        if needle in line:
            return line.rsplit(" ", 1)[-1].strip()
    raise AssertionError(f"never saw {needle!r}: {''.join(lines)}")


@pytest.fixture(scope="module")
def gossip_net(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gossipnet")
    crypto = tmp / "crypto-config"
    (tmp / "crypto-config.yaml").write_text(
        """
PeerOrgs:
  - Name: Org1
    Domain: org1.example.com
    MSPID: Org1MSP
    Template: {Count: 2}
    Users: {Count: 1}
OrdererOrgs:
  - Name: Orderer
    Domain: orderer.example.com
    MSPID: OrdererMSP
"""
    )
    run_cli(
        "fabric_tpu.cli.cryptogen", "generate",
        "--config", str(tmp / "crypto-config.yaml"),
        "--output", str(crypto),
    )
    org1 = crypto / "peerOrganizations" / "org1.example.com"
    oorg = crypto / "ordererOrganizations" / "orderer.example.com"

    (tmp / "configtx.yaml").write_text(
        f"""
Profiles:
  OneOrgChannel:
    Orderer:
      OrdererType: solo
      BatchTimeout: 100ms
      BatchSize: {{MaxMessageCount: 10}}
      Organizations:
        - Name: OrdererMSP
          MSPID: OrdererMSP
          MSPDir: {oorg}/msp
    Application:
      Organizations:
        - Name: Org1MSP
          MSPID: Org1MSP
          MSPDir: {org1}/msp
"""
    )
    gblock = tmp / "gchan.block"
    run_cli(
        "fabric_tpu.cli.configtxgen",
        "-profile", "OneOrgChannel", "-channelID", "gchan",
        "-configPath", str(tmp / "configtx.yaml"),
        "-outputBlock", str(gblock),
    )

    (tmp / "orderer.yaml").write_text(
        f"""
General:
  ListenAddress: 127.0.0.1
  ListenPort: 0
  LocalMSPID: OrdererMSP
  LocalMSPDir: {oorg}/users/Admin@orderer.example.com/msp
  BootstrapFile: {gblock}
  WorkDir: {tmp}/orderer-data
"""
    )
    orderer_proc = spawn(
        "fabric_tpu.cli.orderer", "start", "--config", str(tmp / "orderer.yaml")
    )
    orderer_addr = wait_line(orderer_proc, "orderer listening on")

    (tmp / "kvcc_chaincode.py").write_text(
        "from fabric_tpu.chaincode import success, error_response\n"
        "class KVChaincode:\n"
        "    def init(self, stub):\n"
        "        return success()\n"
        "    def invoke(self, stub):\n"
        "        fn, params = stub.get_function_and_parameters()\n"
        "        if fn == 'put':\n"
        "            stub.put_state(params[0], params[1].encode())\n"
        "            return success(b'ok')\n"
        "        if fn == 'get':\n"
        "            return success(stub.get_state(params[0]) or b'')\n"
        "        return error_response('unknown ' + fn)\n"
    )

    def core_yaml(i, bootstrap, with_orderer=True):
        boot = f"[{bootstrap}]" if bootstrap else "[]"
        orderer_line = (
            f"ordererEndpoint: {orderer_addr}" if with_orderer else ""
        )
        return f"""
BCCSP:
  Default: SW
peer:
  listenAddress: 127.0.0.1:0
  localMspId: Org1MSP
  mspConfigPath: {org1}/peers/peer{i}.org1.example.com/msp
  fileSystemPath: {tmp}/peer{i}-data
  orgMspDirs:
    Org1MSP: {org1}/msp
  {orderer_line}
  genesisBlocks: [{gblock}]
  gossip:
    enabled: true
    bootstrap: {boot}
    tls:
      cert: {org1}/peers/peer{i}.org1.example.com/tls/server.crt
      key: {org1}/peers/peer{i}.org1.example.com/tls/server.key
      rootCAs: [{org1}/peers/peer{i}.org1.example.com/tls/ca.crt]
  chaincodes:
    kvcc: "OR('Org1MSP.member')"
  chaincodePath: [{tmp}]
  chaincodePlugins:
    kvcc: "kvcc_chaincode:KVChaincode"
"""

    (tmp / "core0.yaml").write_text(core_yaml(0, ""))
    peer0 = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "core0.yaml")
    )
    gossip0 = wait_line(peer0, "gossip gchan on")
    peer0_addr = wait_line(peer0, "peer listening on")

    (tmp / "core1.yaml").write_text(core_yaml(1, gossip0))
    peer1 = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "core1.yaml")
    )
    wait_line(peer1, "gossip gchan on")
    peer1_addr = wait_line(peer1, "peer listening on")

    late_procs = []
    yield {
        "tmp": tmp,
        "orderer_addr": orderer_addr,
        "peer0_addr": peer0_addr,
        "peer1_addr": peer1_addr,
        "gossip0": gossip0,
        "core_yaml": core_yaml,
        "spawn_late": late_procs.append,
        "user_msp": str(org1 / "users" / "User0@org1.example.com" / "msp"),
    }
    for proc in (orderer_proc, peer0, peer1, *late_procs):
        proc.send_signal(signal.SIGTERM)
    for proc in (orderer_proc, peer0, peer1, *late_procs):
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _query(nw, peer_addr, *fn_args):
    import base64

    out = run_cli(
        "fabric_tpu.cli.peer", "chaincode", "query",
        "--peerAddresses", peer_addr,
        "-C", "gchan", "-n", "kvcc",
        "-c", json.dumps({"Args": list(fn_args)}),
        "--mspDir", nw["user_msp"], "--mspID", "Org1MSP", "--b64",
    )
    return base64.b64decode(out.strip())


def test_gossip_network_converges_both_peers(gossip_net):
    nw = gossip_net
    run_cli(
        "fabric_tpu.cli.peer", "chaincode", "invoke",
        "--peerAddresses", nw["peer0_addr"],
        "-o", nw["orderer_addr"],
        "-C", "gchan", "-n", "kvcc",
        "-c", json.dumps({"Args": ["put", "gk", "gv"]}),
        "--mspDir", nw["user_msp"], "--mspID", "Org1MSP",
    )
    # BOTH peers converge: one pulled from the orderer as gossip
    # leader, the other received the block via gossip only
    deadline = time.time() + 45
    vals = {}
    while time.time() < deadline:
        vals = {
            p: _query(nw, nw[p], "get", "gk")
            for p in ("peer0_addr", "peer1_addr")
        }
        if all(v == b"gv" for v in vals.values()):
            break
        time.sleep(0.5)
    assert all(v == b"gv" for v in vals.values()), vals


def test_late_joiner_catches_up_via_gossip_only(gossip_net):
    """A peer started AFTER blocks committed, with NO ordererEndpoint at
    all: its ledger can only come from gossip (push + block pull +
    anti-entropy) — the reference's peer-joins-running-channel shape."""
    nw = gossip_net
    # peer0/peer1 already committed "gk" in the previous test; reuse
    # peer0's (Count=2 crypto) msp for the late joiner under a fresh
    # fileSystemPath by reusing index 1's identity with its own data dir
    tmp = nw["tmp"]
    late_yaml = nw["core_yaml"](1, nw["gossip0"], with_orderer=False)
    late_yaml = late_yaml.replace("peer1-data", "late-data")
    (tmp / "late.yaml").write_text(late_yaml)
    late = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "late.yaml")
    )
    nw["spawn_late"](late)
    wait_line(late, "gossip gchan on")
    late_addr = wait_line(late, "peer listening on")

    deadline = time.time() + 60
    val = b""
    while time.time() < deadline:
        try:
            val = _query(nw, late_addr, "get", "gk")
        except AssertionError:
            val = b""
        if val == b"gv":
            break
        time.sleep(0.5)
    assert val == b"gv"
