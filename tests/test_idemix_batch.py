"""Batched Idemix verification: per-lane validity mask must be bit-exact
with the scalar verify_signature oracle (BASELINE config #3)."""

import random

import pytest

pytest.importorskip(
    "cryptography", reason="idemix issuance needs the cryptography package"
)

from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu import idemix
from fabric_tpu.idemix.batch import verify_signatures_batch
from fabric_tpu.protos import idemix_pb2

RNG = random.Random(7)
ATTR_NAMES = ["OU", "Role", "EnrollmentID", "RevocationHandle"]
ATTR_VALUES = [11, 22, 33, 44]
RH_INDEX = 3


@pytest.fixture(scope="module")
def world():
    ik = idemix.new_issuer_key(ATTR_NAMES, RNG)
    sk = bn.rand_mod_order(RNG)
    nonce = bn.big_to_bytes(bn.rand_mod_order(RNG))
    req = idemix.new_cred_request(sk, nonce, ik.ipk, RNG)
    cred = idemix.new_credential(ik, req, ATTR_VALUES, RNG)
    rev_key = idemix.generate_long_term_revocation_key()
    cri = idemix.create_cri(rev_key, [], 0, idemix.ALG_NO_REVOCATION, RNG)
    return ik, sk, cred, cri


def make_sig(world, disclosure, msg):
    ik, sk, cred, cri = world
    nym, r_nym = idemix.make_nym(sk, ik.ipk, RNG)
    return idemix.new_signature(
        cred, sk, nym, r_nym, ik.ipk, disclosure, msg, RH_INDEX, cri, RNG
    )


def test_batch_matches_scalar_verify(world):
    ik = world[0]
    disclosure_a = [0, 0, 0, 0]
    disclosure_b = [0, 1, 0, 0]
    sigs, disclosures, msgs, values = [], [], [], []

    # valid, no disclosure
    sigs.append(make_sig(world, disclosure_a, b"m0"))
    disclosures.append(disclosure_a)
    msgs.append(b"m0")
    values.append([None] * 4)

    # valid, selective disclosure
    sigs.append(make_sig(world, disclosure_b, b"m1"))
    disclosures.append(disclosure_b)
    msgs.append(b"m1")
    values.append([None, ATTR_VALUES[1], None, None])

    # wrong message -> invalid
    sigs.append(make_sig(world, disclosure_a, b"m2"))
    disclosures.append(disclosure_a)
    msgs.append(b"WRONG")
    values.append([None] * 4)

    # tampered proof -> invalid
    bad = idemix_pb2.Signature()
    bad.CopyFrom(make_sig(world, disclosure_a, b"m3"))
    bad.proof_s_sk = bn.big_to_bytes((bn.big_from_bytes(bad.proof_s_sk) + 1) % bn.R)
    sigs.append(bad)
    disclosures.append(disclosure_a)
    msgs.append(b"m3")
    values.append([None] * 4)

    # wrong disclosed value -> invalid
    sigs.append(make_sig(world, disclosure_b, b"m4"))
    disclosures.append(disclosure_b)
    msgs.append(b"m4")
    values.append([None, 999, None, None])

    got = verify_signatures_batch(
        sigs, disclosures, ik.ipk, msgs, values, RH_INDEX
    )

    want = []
    for sig, disclosure, msg, vals in zip(sigs, disclosures, msgs, values):
        try:
            idemix.verify_signature(
                sig, disclosure, ik.ipk, msg, vals, RH_INDEX, None, 0
            )
            want.append(True)
        except idemix.IdemixError:
            want.append(False)
    assert want == [True, True, False, False, False]
    assert got == want
