"""MVCC validator tests — table-driven, modeled on the reference's
validation/validator_test.go scenarios."""

from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.rwset import (
    CollHashedRwSet,
    KVRead,
    KVReadHash,
    KVWrite,
    KVWriteHash,
    NsRwSet,
    RangeQueryInfo,
    TxRwSet,
    Version,
)
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_tpu.validation.txflags import TxValidationCode

V = TxValidationCode.VALID
MVCC = TxValidationCode.MVCC_READ_CONFLICT
PHANTOM = TxValidationCode.PHANTOM_READ_CONFLICT


def seed_db(entries):
    db = VersionedDB()
    batch = UpdateBatch()
    for ns, key, value, ver in entries:
        batch.put(ns, key, value, ver)
    db.apply_updates(batch)
    return db


def tx(reads=(), writes=(), rq=(), coll=(), ns="cc1"):
    return TxRwSet((NsRwSet(ns, tuple(reads), tuple(writes), tuple(rq), tuple(coll)),))


def run(db, txs, block_num=5):
    v = Validator(db)
    codes, updates, hashed = v.validate_and_prepare_batch(
        block_num, txs, [V] * len(txs)
    )
    return codes, updates, hashed


def test_version_match_and_mismatch():
    db = seed_db([("cc1", "k1", b"v1", Version(1, 0)), ("cc1", "k2", b"v2", Version(1, 1))])
    txs = [
        tx(reads=[KVRead("k1", Version(1, 0))], writes=[KVWrite("k1", value=b"new")]),
        tx(reads=[KVRead("k2", Version(9, 9))]),  # stale
        tx(reads=[KVRead("missing", None)]),  # correctly read-as-absent
        tx(reads=[KVRead("missing", Version(1, 0))]),  # phantom existence
    ]
    codes, updates, _ = run(db, txs)
    assert codes == [V, MVCC, V, MVCC]
    assert updates.get("cc1", "k1") == (b"new", Version(5, 0), None)


def test_intra_block_conflict_and_apply_as_you_go():
    db = seed_db([("cc1", "k1", b"v1", Version(1, 0))])
    txs = [
        tx(reads=[KVRead("k1", Version(1, 0))], writes=[KVWrite("k1", value=b"a")]),
        # reads k1 at committed version, but tx0 wrote it in-block -> conflict
        tx(reads=[KVRead("k1", Version(1, 0))]),
        # doesn't read k1; writes something else -> fine
        tx(writes=[KVWrite("k9", value=b"z")]),
    ]
    codes, updates, _ = run(db, txs)
    assert codes == [V, MVCC, V]
    assert updates.get("cc1", "k9") == (b"z", Version(5, 2), None)


def test_invalid_tx_does_not_apply_writes():
    db = seed_db([("cc1", "k1", b"v1", Version(1, 0))])
    txs = [
        tx(reads=[KVRead("k1", Version(0, 0))], writes=[KVWrite("k2", value=b"x")]),
        tx(reads=[KVRead("k2", None)]),  # k2 not written since tx0 invalid
    ]
    codes, updates, _ = run(db, txs)
    assert codes == [MVCC, V]
    assert updates.get("cc1", "k2") is None


def test_upstream_invalid_skipped():
    db = seed_db([])
    txs = [tx(writes=[KVWrite("k", value=b"v")])] * 2
    v = Validator(db)
    codes, updates, _ = v.validate_and_prepare_batch(
        7, txs, [TxValidationCode.ENDORSEMENT_POLICY_FAILURE, V]
    )
    assert codes == [TxValidationCode.ENDORSEMENT_POLICY_FAILURE, V]
    assert updates.get("cc1", "k") == (b"v", Version(7, 1), None)


def test_delete_write_and_read_of_deleted():
    db = seed_db([("cc1", "k1", b"v1", Version(1, 0))])
    txs = [
        tx(reads=[KVRead("k1", Version(1, 0))], writes=[KVWrite("k1", is_delete=True)]),
    ]
    codes, updates, _ = run(db, txs)
    assert codes == [V]
    db.apply_updates(updates)
    assert db.get_state("cc1", "k1") is None


class TestRangeQueries:
    def seed(self):
        return seed_db(
            [("cc1", f"k{i}", b"v", Version(1, i)) for i in range(1, 6)]
        )  # k1..k5

    def rq(self, start, end, reads, exhausted=True):
        return RangeQueryInfo(start, end, exhausted, tuple(reads))

    def test_unchanged_range_ok(self):
        db = self.seed()
        reads = [KVRead(f"k{i}", Version(1, i)) for i in range(1, 4)]  # k1..k3 < k4
        txs = [tx(rq=[self.rq("k1", "k4", reads)])]
        codes, _, _ = run(db, txs)
        assert codes == [V]

    def test_phantom_insert_by_prior_tx(self):
        db = self.seed()
        reads = [KVRead(f"k{i}", Version(1, i)) for i in range(1, 4)]
        txs = [
            tx(writes=[KVWrite("k25", value=b"new")]),  # k25 sorts inside [k1,k4)
            tx(rq=[self.rq("k1", "k4", reads)]),
        ]
        codes, _, _ = run(db, txs)
        assert codes == [V, PHANTOM]

    def test_phantom_delete_by_prior_tx(self):
        db = self.seed()
        reads = [KVRead(f"k{i}", Version(1, i)) for i in range(1, 4)]
        txs = [
            tx(writes=[KVWrite("k2", is_delete=True)]),
            tx(rq=[self.rq("k1", "k4", reads)]),
        ]
        codes, _, _ = run(db, txs)
        assert codes == [V, PHANTOM]

    def test_version_change_in_range(self):
        db = self.seed()
        reads = [KVRead(f"k{i}", Version(1, i)) for i in range(1, 4)]
        txs = [
            tx(writes=[KVWrite("k2", value=b"upd")]),
            tx(rq=[self.rq("k1", "k4", reads)]),
        ]
        codes, _, _ = run(db, txs)
        assert codes == [V, PHANTOM]

    def test_itr_not_exhausted_includes_end_key(self):
        db = self.seed()
        # Simulation stopped at k3: EndKey=k3 must be included on re-check.
        reads = [KVRead(f"k{i}", Version(1, i)) for i in range(1, 4)]
        txs = [tx(rq=[self.rq("k1", "k3", reads, exhausted=False)])]
        codes, _, _ = run(db, txs)
        assert codes == [V]
        # A write to k3 by a prior tx now matters.
        txs = [
            tx(writes=[KVWrite("k3", value=b"!")]),
            tx(rq=[self.rq("k1", "k3", reads, exhausted=False)]),
        ]
        codes, _, _ = run(db, txs)
        assert codes == [V, PHANTOM]


class TestHashedReads:
    def test_hashed_read_conflicts(self):
        db = VersionedDB()
        from fabric_tpu.ledger.statedb import HashedUpdateBatch

        pre = HashedUpdateBatch()
        pre.put("cc1", "collA", b"\x01" * 32, b"\xaa" * 32, Version(1, 0))
        db.apply_updates(UpdateBatch(), pre)

        ok_read = KVReadHash(b"\x01" * 32, Version(1, 0))
        stale_read = KVReadHash(b"\x01" * 32, Version(0, 0))
        txs = [
            tx(coll=[CollHashedRwSet("collA", (ok_read,))]),
            tx(coll=[CollHashedRwSet("collA", (stale_read,))]),
            # writes the hash, then a later tx reads it -> in-block conflict
            tx(coll=[CollHashedRwSet("collA", (), (KVWriteHash(b"\x01" * 32, value_hash=b"\xbb" * 32),))]),
            tx(coll=[CollHashedRwSet("collA", (ok_read,))]),
        ]
        codes, _, hashed = run(db, txs)
        assert codes == [V, MVCC, V, MVCC]
        assert len(hashed) == 1
