"""cauthdsl semantics tests: DSL parse, greedy oracle, batched parity."""

import itertools
import random

import numpy as np

from fabric_tpu.policy import (
    NOutOf,
    Role,
    SignaturePolicyEnvelope,
    SignedBy,
    compile_batched,
    evaluate_host,
    from_dsl,
)


class TestDsl:
    def test_and(self):
        env = from_dsl("AND('Org1.member','Org2.member')")
        assert env.rule == NOutOf(2, [SignedBy(0), SignedBy(1)])
        assert [p.msp_id for p in env.identities] == ["Org1", "Org2"]
        assert env.identities[0].role is Role.MEMBER

    def test_or_nested_outof(self):
        env = from_dsl("OutOf(2, 'A.admin', OR('B.member','C.peer'), 'A.admin')")
        rule = env.rule
        assert isinstance(rule, NOutOf) and rule.n == 2 and len(rule.rules) == 3
        # duplicate principal terms share one identities slot
        assert rule.rules[0] == rule.rules[2] == SignedBy(0)
        assert len(env.identities) == 3


def _2of3():
    return from_dsl("OutOf(2,'A.member','B.member','C.member')")


class TestGreedySemantics:
    def test_2of3(self):
        env = _2of3()
        sat = np.array([[1, 0, 0], [0, 1, 0]], dtype=bool)
        assert evaluate_host(env, sat)
        sat = np.array([[1, 0, 0]], dtype=bool)
        assert not evaluate_host(env, sat)

    def test_identity_not_reusable_within_branch(self):
        # AND(A.member, A.member) needs TWO distinct signers even though one
        # signer satisfies the principal twice.
        env = from_dsl("AND('A.member','A.member')")
        one = np.array([[1]], dtype=bool)
        two = np.array([[1], [1]], dtype=bool)
        assert not evaluate_host(env, one)
        assert evaluate_host(env, two)

    def test_greedy_ordering_can_fail(self):
        # Classic greedy artifact: signer0 satisfies BOTH principals,
        # signer1 satisfies only P0. AND(P0, P1) with signer order
        # [s0, s1]: s0 is consumed by the P0 leaf, then the P1 leaf has
        # only s1 left, which does not match -> the whole policy FAILS
        # even though assignment (s1->P0, s0->P1) exists. The reference
        # behaves this way; we must too.
        env = from_dsl("AND('A.member','B.member')")
        sat = np.array([[1, 1], [1, 0]], dtype=bool)
        assert not evaluate_host(env, sat)
        # Swapped signer order succeeds.
        assert evaluate_host(env, sat[::-1].copy())

    def test_failed_branch_does_not_consume(self):
        # OutOf(1, AND(A,B), A): the failing AND child must not leave the
        # A-signer marked used (scratch-copy semantics).
        env = from_dsl("OutOf(1, AND('A.member','B.member'), 'A.member')")
        sat = np.array([[1, 0]], dtype=bool)  # one signer, satisfies A only
        assert evaluate_host(env, sat)

    def test_all_children_evaluated_no_short_circuit(self):
        # NOutOf evaluates EVERY child (no short-circuit), and every
        # SUCCEEDING child commits its signer consumption. So an OR whose
        # two branches match two different signers consumes BOTH signers.
        env = from_dsl(
            "AND( OR('A.member','B.member'), 'B.member' )"
        )
        # signer0: A only; signer1: B only. The OR succeeds via both
        # branches and consumes both signers; the outer B leaf starves.
        sat = np.array([[1, 0], [0, 1]], dtype=bool)
        assert not evaluate_host(env, sat)
        # single signer satisfying both: OR consumes it via the A branch
        # only (B branch finds it used), but the outer B leaf still starves.
        sat = np.array([[1, 1]], dtype=bool)
        assert not evaluate_host(env, sat)
        # a third signer un-starves the outer leaf.
        sat = np.array([[1, 0], [0, 1], [0, 1]], dtype=bool)
        assert evaluate_host(env, sat)


def random_policy(rng, num_principals, depth=0):
    if depth >= 2 or rng.random() < 0.4:
        return SignedBy(rng.randrange(num_principals))
    k = rng.randint(1, 3)
    rules = [random_policy(rng, num_principals, depth + 1) for _ in range(k)]
    return NOutOf(rng.randint(1, k), rules)


class TestBatchedParity:
    def test_exhaustive_small(self):
        """Every sat matrix for 2 signers x 2 principals, several policies."""
        policies = [
            from_dsl("AND('A.member','B.member')"),
            from_dsl("OR('A.member','B.member')"),
            from_dsl("AND('A.member','A.member')"),
            from_dsl("OutOf(1, AND('A.member','B.member'), 'B.member')"),
            from_dsl("OutOf(2, 'A.member', 'B.member', 'A.member')"),
        ]
        for env in policies:
            num_p = len(env.identities)
            mats = []
            for bits in itertools.product([0, 1], repeat=2 * num_p):
                mats.append(np.array(bits, dtype=bool).reshape(2, num_p))
            batch = np.stack(mats)
            fn = compile_batched(env, num_signers=2)
            got = np.asarray(fn(batch))
            want = np.array([evaluate_host(env, m) for m in mats])
            assert (got == want).all(), env

    def test_randomized(self):
        rng = random.Random(1234)
        for trial in range(25):
            num_p = rng.randint(1, 4)
            num_s = rng.randint(1, 4)
            ids = [object()] * num_p  # placeholder principals
            env = SignaturePolicyEnvelope(random_policy(rng, num_p), ids)
            batch = np.random.default_rng(trial).random((16, num_s, num_p)) < 0.45
            fn = compile_batched(env, num_signers=num_s)
            got = np.asarray(fn(batch))
            want = np.array([evaluate_host(env, m) for m in batch])
            assert (got == want).all(), (trial, env.rule)
