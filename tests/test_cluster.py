"""Orderer cluster over real sockets: the Step RPC carrying raft messages
between OrdererNode processes' gRPC servers, follower->leader Submit
forwarding, and kill-the-leader failover (reference orderer/common/
cluster/comm.go:117,127 + integration/raft failover suites)."""

import socket
import time

import pytest

from conftest import requires_crypto

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.comm.services import broadcast_envelope
from fabric_tpu.comm.server import channel_to
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.nodes.orderer import OrdererNode
from fabric_tpu.orderer.raft import Entry, Message, message_from_bytes, message_to_bytes
from fabric_tpu.protos import common_pb2, protoutil

CHANNEL = "clusterchan"


def test_message_codec_roundtrip():
    m = Message(
        kind="append",
        term=7,
        frm=2,
        to=3,
        prev_index=11,
        prev_term=6,
        entries=(
            Entry(12, 7, 0, b"block-bytes"),
            Entry(13, 7, 1, b"1,2,3"),
        ),
        commit=11,
        snap_data=b"",
    )
    assert message_from_bytes(message_to_bytes(m)) == m
    m2 = Message(kind="snap", term=3, frm=1, to=2, snap_index=40, snap_term=2,
                 snap_data=b"\x00" * 64)
    assert message_from_bytes(message_to_bytes(m2)) == m2


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster(tmp_path):
    org1 = generate_org("org1.example.com", "Org1MSP")
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    ports = _free_ports(3)
    profile = Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="etcdraft",
            batch_timeout="100ms",
            max_message_count=1,
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config())
            ],
            raft_consenters=[("127.0.0.1", p, b"", b"") for p in ports],
        ),
    )
    gblock = genesis_block(profile, CHANNEL)

    nodes = []
    for i, port in enumerate(ports):
        node = OrdererNode(
            str(tmp_path / f"orderer{i}"),
            signer=SigningIdentity(oorg.peers[0]),
            listen_address=f"127.0.0.1:{port}",
            raft_node_id=i + 1,
            raft_tick_seconds=0.05,
        )
        node.join_channel(gblock)
        node.start()
        nodes.append(node)

    yield {"nodes": nodes, "org1": org1, "gblock": gblock}
    for node in nodes:
        try:
            node.stop()
        except Exception:
            pass


def _leaders(nodes):
    return [
        n
        for n in nodes
        if n.registrar.get_chain(CHANNEL) is not None
        and n.registrar.get_chain(CHANNEL).chain.node.role == "leader"
    ]


def _make_envelope(signer, body):
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, CHANNEL
    )
    payload.header.channel_header = chdr.SerializeToString()
    shdr = protoutil.make_signature_header(
        signer.serialize(), signer.new_nonce()
    )
    payload.header.signature_header = shdr.SerializeToString()
    payload.data = body
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    env.signature = signer.sign(env.payload)
    return env


@requires_crypto
def test_cluster_elects_forwards_and_fails_over(cluster):
    nodes = cluster["nodes"]
    client = SigningIdentity(cluster["org1"].users[0])

    # a single leader emerges over the socket transport
    assert _wait(lambda: len(_leaders(nodes)) == 1)
    leader = _leaders(nodes)[0]
    followers = [n for n in nodes if n is not leader]

    # submit to a FOLLOWER: forwarded to the leader over the cluster
    # Submit RPC, ordered, and replicated to every node
    ch = channel_to(followers[0].addr)
    resp = broadcast_envelope(ch, _make_envelope(client, b"tx-1"))
    assert resp.status == common_pb2.SUCCESS
    assert _wait(
        lambda: all(
            n.registrar.get_chain(CHANNEL).chain.height >= 2 for n in nodes
        )
    ), [n.registrar.get_chain(CHANNEL).chain.height for n in nodes]
    ch.close()

    # kill the leader: the survivors re-elect and keep ordering
    leader.stop()
    survivors = followers
    assert _wait(lambda: len(_leaders(survivors)) == 1)

    target = [n for n in survivors if n not in _leaders(survivors)][0]
    ch = channel_to(target.addr)
    resp = None
    deadline = time.time() + 20
    while time.time() < deadline:
        resp = broadcast_envelope(ch, _make_envelope(client, b"tx-2"))
        if resp.status == common_pb2.SUCCESS:
            break
        time.sleep(0.2)
    assert resp is not None and resp.status == common_pb2.SUCCESS
    assert _wait(
        lambda: all(
            n.registrar.get_chain(CHANNEL).chain.height >= 3
            for n in survivors
        )
    ), [n.registrar.get_chain(CHANNEL).chain.height for n in survivors]
    ch.close()


@requires_crypto
def test_raft_cluster_over_tls(tmp_path):
    """3-node etcdraft cluster with every listener serving TLS and
    cluster_root_ca on the intra-cluster dials (Step + follower pulls):
    a leader elects and a broadcast commits on all nodes — enabling
    server TLS must not break consensus (review r5 finding)."""
    from fabric_tpu.comm.server import CertReloader, channel_to
    from fabric_tpu.comm.services import broadcast_envelope
    from fabric_tpu.msp.cryptogen import OrgCA

    org1 = generate_org("org1.example.com", "Org1MSP")
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    tls_ca = OrgCA("tls.example.com", "TLSCA")
    ports = _free_ports(3)
    profile = Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="etcdraft",
            batch_timeout="100ms",
            max_message_count=1,
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config())
            ],
            raft_consenters=[("127.0.0.1", p, b"", b"") for p in ports],
        ),
    )
    gblock = genesis_block(profile, CHANNEL)

    nodes = []
    for i, port in enumerate(ports):
        pair = tls_ca.enroll_tls(f"orderer{i}.tls")
        cert = tmp_path / f"o{i}.crt"
        key = tmp_path / f"o{i}.key"
        cert.write_bytes(pair.cert_pem)
        key.write_bytes(pair.key_pem)
        node = OrdererNode(
            str(tmp_path / f"orderer{i}"),
            signer=SigningIdentity(oorg.peers[0]),
            listen_address=f"127.0.0.1:{port}",
            raft_node_id=i + 1,
            raft_tick_seconds=0.05,
            tls_credentials=CertReloader(str(cert), str(key)).credentials(),
            cluster_root_ca=tls_ca.cert_pem,
        )
        node.join_channel(gblock)
        node.start()
        nodes.append(node)
    try:
        assert _wait(lambda: len(_leaders(nodes)) == 1, timeout=30)
        signer = SigningIdentity(org1.users[0])
        env = _make_envelope(signer, b"tls-cluster-payload")
        leader = _leaders(nodes)[0]
        conn = channel_to(leader.addr, tls_ca.cert_pem)
        ack = broadcast_envelope(conn, env)
        conn.close()
        assert ack.status == common_pb2.SUCCESS, ack.info
        assert _wait(
            lambda: all(
                n.registrar.get_chain(CHANNEL).chain.height >= 2
                for n in nodes
            ),
            timeout=30,
        )
    finally:
        for node in nodes:
            try:
                node.stop()
            except Exception:
                pass
