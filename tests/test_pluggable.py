"""Pluggable validation: custom plugins dispatched by namespace binding
(reference core/handlers/validation SPI + core/handlers/library/
registry.go module loading + integration/pluggable/pluggable_test.go).

Unit layer: BlockValidator routes policy groups bound to a custom
plugin through plugin.validate(ctx) with the documented outcome mapping.
E2E layer: a REAL subprocess orderer+peer network loads a plugin by
module path from node config; the plugin both records its invocations
and rejects writes to a guarded key, and the committed chain reflects
its verdicts.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from conftest import requires_crypto
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import protoutil
from fabric_tpu.validation.dispatcher import PluginRegistry
from fabric_tpu.validation.plugin_api import (
    EndorsementInvalid,
    ValidationContext,
    ValidationPlugin,
)
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import (
    BlockValidator,
    ChaincodeDefinition,
    ChaincodeRegistry,
    ValidationError,
)

CHANNEL = "plugchannel"
PROVIDER = SoftwareProvider()
V = TxValidationCode


@pytest.fixture(scope="module")
def net():
    org1 = generate_org("org1.plug", "Org1MSP")
    org2 = generate_org("org2.plug", "Org2MSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)])
    return {
        "mgr": mgr,
        "client": SigningIdentity(org1.users[0], PROVIDER),
        "p1": SigningIdentity(org1.peers[0], PROVIDER),
        "p2": SigningIdentity(org2.peers[0], PROVIDER),
    }


def make_block(net, cc="plugcc", key="k1", number=7):
    results = serialize_tx_rwset(
        rw.TxRwSet((rw.NsRwSet(cc, (), (rw.KVWrite(key, False, b"v"),)),))
    )
    bundle = create_proposal(net["client"], CHANNEL, cc, [b"invoke", b"a"])
    responses = [
        endorse_proposal(bundle, net[e], results) for e in ("p1", "p2")
    ]
    env = create_signed_tx(bundle, net["client"], responses)
    block = protoutil.new_block(number, b"\x11" * 32)
    block.data.data.append(env.SerializeToString())
    protoutil.seal_block(block)
    return block


def validator(net, plugin_name, plugin=None):
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "plugcc",
                from_dsl("AND('Org1MSP.member','Org2MSP.member')"),
                plugin=plugin_name,
            )
        ]
    )
    plugins = PluginRegistry()
    if plugin is not None:
        plugins.register(plugin_name, plugin)
    return BlockValidator(
        CHANNEL, net["mgr"], PROVIDER, registry, plugin_registry=plugins
    )


class RecordingPlugin(ValidationPlugin):
    def __init__(self):
        self.contexts = []

    def validate(self, ctx: ValidationContext) -> None:
        self.contexts.append(ctx)
        if not ctx.default_check():
            raise EndorsementInvalid("default policy failed")


class TestUnitDispatch:
    @requires_crypto
    def test_plugin_accepts_and_sees_context(self, net):
        plugin = RecordingPlugin()
        v = validator(net, "recorder", plugin)
        flags = v.validate(make_block(net))
        assert flags.flag(0) == V.VALID
        (ctx,) = plugin.contexts
        assert ctx.channel_id == CHANNEL
        assert ctx.namespace == "plugcc"
        assert ctx.block_num == 7
        assert ctx.tx_id
        assert ctx.envelope_bytes
        assert len(ctx.signers) == 2
        assert all(s.sig_valid for s in ctx.signers)
        assert {s.msp_id for s in ctx.signers} == {"Org1MSP", "Org2MSP"}

    @requires_crypto
    def test_plugin_rejects(self, net):
        class Reject(ValidationPlugin):
            def validate(self, ctx):
                raise EndorsementInvalid("nope")

        v = validator(net, "reject", Reject())
        flags = v.validate(make_block(net))
        assert flags.flag(0) == V.ENDORSEMENT_POLICY_FAILURE

    @requires_crypto
    def test_plugin_execution_failure_halts_block(self, net):
        class Boom(ValidationPlugin):
            def validate(self, ctx):
                raise RuntimeError("infra down")

        v = validator(net, "boom", Boom())
        with pytest.raises(ValidationError):
            v.validate(make_block(net))

    @requires_crypto
    def test_unresolvable_plugin_invalidates(self, net):
        v = validator(net, "ghost", plugin=None)
        flags = v.validate(make_block(net))
        assert flags.flag(0) == V.INVALID_CHAINCODE

    def test_registry_load_by_module_path(self, tmp_path):
        (tmp_path / "ext_plug.py").write_text(
            "from fabric_tpu.validation.plugin_api import ValidationPlugin\n"
            "class MyPlugin(ValidationPlugin):\n"
            "    def validate(self, ctx):\n"
            "        pass\n"
        )
        sys.path.insert(0, str(tmp_path))
        try:
            reg = PluginRegistry()
            plugin = reg.load("mine", "ext_plug:MyPlugin")
            assert callable(plugin.validate)
            assert reg.get("mine") is plugin
        finally:
            sys.path.remove(str(tmp_path))


class TestPluginSBEInterplay:
    """A VALID plugin-validated tx's key-metadata writes must register
    as APPLIED in BlockDependencies: a later builtin tx writing the same
    key inside the block is invalidated because its endorsements predate
    the new key policy (validator_keylevel.go semantics)."""

    def _mixed_tx(self, net, with_vp):
        from fabric_tpu.policy.proto_convert import marshal_application_policy
        from fabric_tpu.validation.statebased import VALIDATION_PARAMETER

        ns_sets = [
            rw.NsRwSet("plugcc", (), (rw.KVWrite("p", False, b"v"),)),
        ]
        if with_vp:
            vp = (
                (
                    VALIDATION_PARAMETER,
                    marshal_application_policy(from_dsl("OR('Org1MSP.member')")),
                ),
            )
            ns_sets.append(
                rw.NsRwSet(
                    "bincc",
                    (),
                    (rw.KVWrite("k", False, b"v0"),),
                    metadata_writes=(rw.KVMetadataWrite("k", vp),),
                )
            )
        results = serialize_tx_rwset(rw.TxRwSet(tuple(ns_sets)))
        bundle = create_proposal(net["client"], CHANNEL, "plugcc", [b"put"])
        responses = [
            endorse_proposal(bundle, net[e], results) for e in ("p1", "p2")
        ]
        return create_signed_tx(bundle, net["client"], responses)

    def _bin_tx(self, net):
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (rw.NsRwSet("bincc", (), (rw.KVWrite("k", False, b"v1"),)),)
            )
        )
        bundle = create_proposal(net["client"], CHANNEL, "bincc", [b"put"])
        responses = [
            endorse_proposal(bundle, net[e], results) for e in ("p1", "p2")
        ]
        return create_signed_tx(bundle, net["client"], responses)

    def _validate(self, net, envs):
        registry = ChaincodeRegistry(
            [
                ChaincodeDefinition(
                    "plugcc",
                    from_dsl("AND('Org1MSP.member','Org2MSP.member')"),
                    plugin="recorder",
                ),
                ChaincodeDefinition(
                    "bincc",
                    from_dsl("OR('Org1MSP.member','Org2MSP.member')"),
                ),
            ]
        )
        plugins = PluginRegistry()
        plugins.register("recorder", RecordingPlugin())
        v = BlockValidator(
            CHANNEL, net["mgr"], PROVIDER, registry, plugin_registry=plugins
        )
        block = protoutil.new_block(3, b"\x22" * 32)
        for env in envs:
            block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        return v.validate(block)

    @requires_crypto
    def test_plugin_md_write_applies_to_later_builtin_tx(self, net):
        flags = self._validate(
            net, [self._mixed_tx(net, with_vp=True), self._bin_tx(net)]
        )
        assert flags.flag(0) == V.VALID
        # tx1's endorsements predate tx0's in-block VP update -> failure
        assert flags.flag(1) == V.ENDORSEMENT_POLICY_FAILURE

    @requires_crypto
    def test_no_vp_write_leaves_later_tx_valid(self, net):
        flags = self._validate(
            net, [self._mixed_tx(net, with_vp=False), self._bin_tx(net)]
        )
        assert flags.flag(0) == V.VALID
        assert flags.flag(1) == V.VALID


# ----------------------------------------------------------------------
# subprocess e2e (integration/pluggable/pluggable_test.go analog)
# ----------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(mod, *args, timeout=90):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", mod, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )
    assert out.returncode == 0, f"{mod} {args} failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def spawn(mod, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", mod, *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )


def wait_listening(proc, needle, timeout=60):
    deadline = time.time() + timeout
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"process exited {proc.returncode}: {''.join(lines)}"
                )
            continue
        lines.append(line)
        if needle in line:
            return line.rsplit(" ", 1)[-1].strip()
    raise AssertionError(f"never saw {needle!r}: {''.join(lines)}")


@pytest.fixture(scope="module")
def plug_network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("pluggable")
    crypto = tmp / "crypto-config"
    (tmp / "crypto-config.yaml").write_text(
        """
PeerOrgs:
  - Name: Org1
    Domain: org1.example.com
    MSPID: Org1MSP
    Template: {Count: 1}
    Users: {Count: 1}
OrdererOrgs:
  - Name: Orderer
    Domain: orderer.example.com
    MSPID: OrdererMSP
"""
    )
    run_cli(
        "fabric_tpu.cli.cryptogen", "generate",
        "--config", str(tmp / "crypto-config.yaml"),
        "--output", str(crypto),
    )
    org1 = crypto / "peerOrganizations" / "org1.example.com"
    oorg = crypto / "ordererOrganizations" / "orderer.example.com"

    (tmp / "configtx.yaml").write_text(
        f"""
Profiles:
  OneOrgChannel:
    Orderer:
      OrdererType: solo
      BatchTimeout: 100ms
      BatchSize: {{MaxMessageCount: 10}}
      Organizations:
        - Name: OrdererMSP
          MSPID: OrdererMSP
          MSPDir: {oorg}/msp
    Application:
      Organizations:
        - Name: Org1MSP
          MSPID: Org1MSP
          MSPDir: {org1}/msp
"""
    )
    gblock = tmp / "plugchan.block"
    run_cli(
        "fabric_tpu.cli.configtxgen",
        "-profile", "OneOrgChannel",
        "-channelID", "plugchan",
        "-configPath", str(tmp / "configtx.yaml"),
        "-outputBlock", str(gblock),
    )

    (tmp / "orderer.yaml").write_text(
        f"""
General:
  ListenAddress: 127.0.0.1
  ListenPort: 0
  LocalMSPID: OrdererMSP
  LocalMSPDir: {oorg}/users/Admin@orderer.example.com/msp
  BootstrapFile: {gblock}
  WorkDir: {tmp}/orderer-data
"""
    )
    orderer_proc = spawn(
        "fabric_tpu.cli.orderer", "start", "--config", str(tmp / "orderer.yaml")
    )
    orderer_addr = wait_listening(orderer_proc, "orderer listening on")

    marker = tmp / "plugin-invocations.log"
    # the custom validation plugin, loaded by module path from node
    # config: records every consultation and guards key "forbidden"
    (tmp / "guard_plugin.py").write_text(
        f'''
from fabric_tpu.validation.plugin_api import (
    EndorsementInvalid, ValidationPlugin,
)
from fabric_tpu.validation.msgvalidation import parse_transaction

MARKER = {str(marker)!r}

class GuardPlugin(ValidationPlugin):
    def validate(self, ctx):
        with open(MARKER, "a") as f:
            f.write(ctx.namespace + " " + ctx.tx_id + "\\n")
        if not ctx.default_check():
            raise EndorsementInvalid("endorsement policy not satisfied")
        tx = parse_transaction(ctx.tx_index, ctx.envelope_bytes)
        rwset = tx.rwset
        for ns_rw in (rwset.ns_rw_sets if rwset else ()):
            for w in ns_rw.writes:
                if w.key.startswith("forbidden"):
                    raise EndorsementInvalid("write to guarded key")
'''
    )
    (tmp / "kvcc_chaincode.py").write_text(
        '''
from fabric_tpu.chaincode import success, error_response

class KVChaincode:
    def init(self, stub):
        return success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return success(b"ok")
        if fn == "get":
            return success(stub.get_state(params[0]) or b"")
        return error_response("unknown " + fn)
'''
    )
    (tmp / "core.yaml").write_text(
        f"""
BCCSP:
  Default: SW
peer:
  listenAddress: 127.0.0.1:0
  localMspId: Org1MSP
  mspConfigPath: {org1}/peers/peer0.org1.example.com/msp
  fileSystemPath: {tmp}/peer0-data
  orgMspDirs:
    Org1MSP: {org1}/msp
  ordererEndpoint: {orderer_addr}
  genesisBlocks: [{gblock}]
  handlersPath: [{tmp}]
  handlers:
    validation:
      guard: "guard_plugin:GuardPlugin"
  chaincodes:
    guardcc:
      policy: "OR('Org1MSP.member')"
      plugin: guard
  chaincodePath: [{tmp}]
  chaincodePlugins:
    guardcc: "kvcc_chaincode:KVChaincode"
"""
    )
    peer_proc = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "core.yaml")
    )
    peer_addr = wait_listening(peer_proc, "peer listening on")

    yield {
        "tmp": tmp,
        "marker": marker,
        "orderer_addr": orderer_addr,
        "peer_addr": peer_addr,
        "user_msp": str(org1 / "users" / "User0@org1.example.com" / "msp"),
    }
    for proc in (orderer_proc, peer_proc):
        proc.send_signal(signal.SIGTERM)
    for proc in (orderer_proc, peer_proc):
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _invoke(nw, *fn_args):
    return run_cli(
        "fabric_tpu.cli.peer", "chaincode", "invoke",
        "--peerAddresses", nw["peer_addr"],
        "-o", nw["orderer_addr"],
        "-C", "plugchan", "-n", "guardcc",
        "-c", json.dumps({"Args": list(fn_args)}),
        "--mspDir", nw["user_msp"], "--mspID", "Org1MSP",
    )


def _query(nw, *fn_args):
    import base64

    out = run_cli(
        "fabric_tpu.cli.peer", "chaincode", "query",
        "--peerAddresses", nw["peer_addr"],
        "-C", "plugchan", "-n", "guardcc",
        "-c", json.dumps({"Args": list(fn_args)}),
        "--mspDir", nw["user_msp"], "--mspID", "Org1MSP",
        "--b64",
    )
    return base64.b64decode(out.strip())


@requires_crypto
def test_pluggable_e2e(plug_network):
    nw = plug_network
    # 1. allowed write commits through the custom plugin
    _invoke(nw, "put", "open-key", "open-value")
    deadline = time.time() + 30
    value = b""
    while time.time() < deadline:
        value = _query(nw, "get", "open-key")
        if value == b"open-value":
            break
        time.sleep(0.3)
    assert value == b"open-value"

    # 2. guarded write is endorsed and ordered, but the plugin
    # invalidates it at commit time: state never changes
    _invoke(nw, "put", "forbidden-key", "evil")
    time.sleep(3.0)  # > BatchTimeout + commit
    assert _query(nw, "get", "forbidden-key") == b""

    # 3. the plugin ran inside the subprocess peer for both txs
    invocations = nw["marker"].read_text().splitlines()
    assert len(invocations) >= 2
    assert all(line.startswith("guardcc ") for line in invocations)
