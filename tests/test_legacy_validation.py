"""Legacy v12 validation: LSCC-backed policy resolution, the v12
write-set guards, the capability router, and dynamic plugin loading
(reference builtin/v12/validation_logic.go + router.go:34-50 +
library/registry.go:134)."""

import pytest

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.policy import from_dsl
from fabric_tpu.policy.proto_convert import marshal_envelope
from fabric_tpu.protos import peer_pb2
from fabric_tpu.validation.dispatcher import PluginRegistry
from fabric_tpu.validation.legacy import (
    LSCCRegistry,
    ValidationRouter,
    check_v12_writeset,
)
from fabric_tpu.validation.validator import (
    ChaincodeDefinition,
    ChaincodeRegistry,
)


def _lscc_state(defs):
    table = {}
    for name, dsl in defs.items():
        data = peer_pb2.ChaincodeData()
        data.name = name
        data.version = "1.0"
        data.vscc = "vscc"
        data.policy = marshal_envelope(from_dsl(dsl))
        table[("lscc", name)] = data.SerializeToString()
    return lambda ns, key: table.get((ns, key))


def test_lscc_registry_resolves_chaincode_data():
    reg = LSCCRegistry(_lscc_state({"oldcc": "OR('Org1MSP.member')"}))
    definition = reg.get("oldcc")
    assert definition is not None
    assert definition.name == "oldcc"
    assert definition.plugin == "vscc"
    assert reg.get("ghost") is None
    # malformed record -> undefined
    bad = LSCCRegistry(lambda ns, key: b"\xff\xfe")
    assert bad.get("oldcc") is None


def test_v12_writeset_guards():
    def ws(ns, writes):
        return rw.TxRwSet(
            (rw.NsRwSet(ns, (), tuple(rw.KVWrite(k, False, b"v") for k in writes)),)
        )

    # normal invoke writing its own namespace: fine
    assert check_v12_writeset(ws("mycc", ["a"]), "mycc") is None
    # non-lscc tx writing lscc: illegal
    assert check_v12_writeset(ws("lscc", ["mycc"]), "mycc") is not None
    # lscc deploy writing one key: legal
    assert check_v12_writeset(ws("lscc", ["mycc"]), "lscc") is None
    # lscc writing two keys: illegal
    assert check_v12_writeset(ws("lscc", ["a", "b"]), "lscc") is not None
    # writes to another system namespace: illegal
    assert check_v12_writeset(ws("cscc", ["x"]), "mycc") is not None
    assert check_v12_writeset(None, "mycc") is None


def test_validation_router_by_capability():
    v20 = ChaincodeRegistry(
        [ChaincodeDefinition("newcc", from_dsl("OR('Org1MSP.member')"))]
    )
    legacy = LSCCRegistry(_lscc_state({"oldcc": "OR('Org1MSP.member')"}))
    caps = ["V2_0"]
    router = ValidationRouter(v20, legacy, lambda: caps)
    assert router.v20_active
    assert router.get("newcc") is not None
    assert router.get("oldcc") is None  # lifecycle knows nothing of it
    caps.clear()
    caps.append("V1_4_2")
    assert not router.v20_active
    assert router.get("oldcc") is not None
    assert router.get("newcc") is None


def test_plugin_registry_dynamic_load():
    reg = PluginRegistry()
    # load a module attribute like registry.go's plugin.Open + Lookup
    plugin = reg.load("jsonplugin", "json:dumps")
    assert reg.exists("jsonplugin") and plugin is not None
    with pytest.raises(ModuleNotFoundError):
        reg.load("nope", "no_such_module_xyz:thing")


# -- v13 collection-config validation (v13 validation_logic.go) ---------


def _pkg_bytes(collections):
    from fabric_tpu.ledger.collections import build_collection_config_package

    return build_collection_config_package(collections).SerializeToString()


def _deploy_ws(cc, coll_value=None, coll_key=None):
    """LSCC deploy write-set: ChaincodeData key + optional collection key."""
    from fabric_tpu.ledger import rwset as rw

    writes = [rw.KVWrite(cc, False, b"ccdata")]
    if coll_value is not None:
        writes.append(
            rw.KVWrite(coll_key or legacy.collection_key(cc), False, coll_value)
        )
    return rw.TxRwSet((rw.NsRwSet("lscc", (), tuple(writes)),))


from fabric_tpu.validation import legacy  # noqa: E402


class TestV13Collections:
    def test_valid_collection_deploy(self):
        raw = _pkg_bytes([{"name": "secret", "policy": "OR('Org1MSP.member')"}])
        assert legacy.check_v13_writeset(_deploy_ws("mycc", raw), "lscc") is None

    def test_v12_rejects_collection_writes(self):
        raw = _pkg_bytes([{"name": "secret", "policy": "OR('Org1MSP.member')"}])
        why = legacy.check_v12_writeset(_deploy_ws("mycc", raw), "lscc")
        assert why is not None and "V1_2" in why

    def test_wrong_collection_key_rejected(self):
        raw = _pkg_bytes([{"name": "c", "policy": "OR('Org1MSP.member')"}])
        why = legacy.check_v13_writeset(
            _deploy_ws("mycc", raw, coll_key="othercc~collection"), "lscc"
        )
        assert why is not None and "othercc~collection" in why

    def test_malformed_package_rejected(self):
        why = legacy.check_v13_writeset(
            _deploy_ws("mycc", b"\xff\xfe\xfd"), "lscc"
        )
        assert why is not None and "invalid collection" in why

    def test_duplicate_collection_names_rejected(self):
        raw = _pkg_bytes(
            [
                {"name": "c1", "policy": "OR('Org1MSP.member')"},
                {"name": "c1", "policy": "OR('Org1MSP.member')"},
            ]
        )
        why = legacy.check_v13_writeset(_deploy_ws("mycc", raw), "lscc")
        assert why is not None and "duplicate" in why

    def test_peer_count_bounds(self):
        raw = _pkg_bytes(
            [
                {
                    "name": "c",
                    "policy": "OR('Org1MSP.member')",
                    "required_peer_count": 3,
                    "maximum_peer_count": 1,
                }
            ]
        )
        why = legacy.check_v13_writeset(_deploy_ws("mycc", raw), "lscc")
        assert why is not None and "maximum peer count" in why

    def test_missing_member_policy_rejected(self):
        raw = _pkg_bytes([{"name": "c"}])
        why = legacy.check_v13_writeset(_deploy_ws("mycc", raw), "lscc")
        assert why is not None and "member policy is not set" in why

    def test_upgrade_may_only_expand(self):
        old = _pkg_bytes([{"name": "c1", "policy": "OR('Org1MSP.member')"}])
        grown = _pkg_bytes(
            [
                {"name": "c1", "policy": "OR('Org1MSP.member')"},
                {"name": "c2", "policy": "OR('Org1MSP.member')"},
            ]
        )
        dropped = _pkg_bytes([{"name": "c2", "policy": "OR('Org1MSP.member')"}])
        modified = _pkg_bytes(
            [{"name": "c1", "policy": "OR('Org2MSP.member')"}]
        )
        get_old = lambda cc: old  # noqa: E731
        assert (
            legacy.check_v13_writeset(
                _deploy_ws("mycc", grown), "lscc", get_old
            )
            is None
        )
        why = legacy.check_v13_writeset(
            _deploy_ws("mycc", dropped), "lscc", get_old
        )
        assert why is not None and "missing" in why
        why = legacy.check_v13_writeset(
            _deploy_ws("mycc", modified), "lscc", get_old
        )
        assert why is not None and "cannot be modified" in why
