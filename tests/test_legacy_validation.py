"""Legacy v12 validation: LSCC-backed policy resolution, the v12
write-set guards, the capability router, and dynamic plugin loading
(reference builtin/v12/validation_logic.go + router.go:34-50 +
library/registry.go:134)."""

import pytest

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.policy import from_dsl
from fabric_tpu.policy.proto_convert import marshal_envelope
from fabric_tpu.protos import peer_pb2
from fabric_tpu.validation.dispatcher import PluginRegistry
from fabric_tpu.validation.legacy import (
    LSCCRegistry,
    ValidationRouter,
    check_v12_writeset,
)
from fabric_tpu.validation.validator import (
    ChaincodeDefinition,
    ChaincodeRegistry,
)


def _lscc_state(defs):
    table = {}
    for name, dsl in defs.items():
        data = peer_pb2.ChaincodeData()
        data.name = name
        data.version = "1.0"
        data.vscc = "vscc"
        data.policy = marshal_envelope(from_dsl(dsl))
        table[("lscc", name)] = data.SerializeToString()
    return lambda ns, key: table.get((ns, key))


def test_lscc_registry_resolves_chaincode_data():
    reg = LSCCRegistry(_lscc_state({"oldcc": "OR('Org1MSP.member')"}))
    definition = reg.get("oldcc")
    assert definition is not None
    assert definition.name == "oldcc"
    assert definition.plugin == "vscc"
    assert reg.get("ghost") is None
    # malformed record -> undefined
    bad = LSCCRegistry(lambda ns, key: b"\xff\xfe")
    assert bad.get("oldcc") is None


def test_v12_writeset_guards():
    def ws(ns, writes):
        return rw.TxRwSet(
            (rw.NsRwSet(ns, (), tuple(rw.KVWrite(k, False, b"v") for k in writes)),)
        )

    # normal invoke writing its own namespace: fine
    assert check_v12_writeset(ws("mycc", ["a"]), "mycc") is None
    # non-lscc tx writing lscc: illegal
    assert check_v12_writeset(ws("lscc", ["mycc"]), "mycc") is not None
    # lscc deploy writing one key: legal
    assert check_v12_writeset(ws("lscc", ["mycc"]), "lscc") is None
    # lscc writing two keys: illegal
    assert check_v12_writeset(ws("lscc", ["a", "b"]), "lscc") is not None
    # writes to another system namespace: illegal
    assert check_v12_writeset(ws("cscc", ["x"]), "mycc") is not None
    assert check_v12_writeset(None, "mycc") is None


def test_validation_router_by_capability():
    v20 = ChaincodeRegistry(
        [ChaincodeDefinition("newcc", from_dsl("OR('Org1MSP.member')"))]
    )
    legacy = LSCCRegistry(_lscc_state({"oldcc": "OR('Org1MSP.member')"}))
    caps = ["V2_0"]
    router = ValidationRouter(v20, legacy, lambda: caps)
    assert router.v20_active
    assert router.get("newcc") is not None
    assert router.get("oldcc") is None  # lifecycle knows nothing of it
    caps.clear()
    caps.append("V1_4_2")
    assert not router.v20_active
    assert router.get("oldcc") is not None
    assert router.get("newcc") is None


def test_plugin_registry_dynamic_load():
    reg = PluginRegistry()
    # load a module attribute like registry.go's plugin.Open + Lookup
    plugin = reg.load("jsonplugin", "json:dumps")
    assert reg.exists("jsonplugin") and plugin is not None
    with pytest.raises(ModuleNotFoundError):
        reg.load("nope", "no_such_module_xyz:thing")
