"""gRPC server interceptors (reference common/grpclogging +
common/grpcmetrics): RPC logs and counters/durations on the metrics SPI."""

import grpc
import pytest

from conftest import requires_crypto

from fabric_tpu.common.metrics import PrometheusProvider
from fabric_tpu.comm.interceptors import LoggingInterceptor, MetricsInterceptor
from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM, UNARY, channel_to


@pytest.fixture
def echo_server():
    provider = PrometheusProvider()
    server = GRPCServer(
        "127.0.0.1:0",
        interceptors=[LoggingInterceptor(), MetricsInterceptor(provider)],
    )

    def echo(request, context):
        if request == b"boom":
            raise ValueError("boom")
        return request

    def echo_stream(request_iterator, context):
        for req in request_iterator:
            yield req

    server.register(
        "test.Echo",
        {
            "Call": (UNARY, echo, lambda b: b, lambda b: b),
            "Stream": (STREAM_STREAM, echo_stream, lambda b: b, lambda b: b),
        },
    )
    addr = server.start()
    yield provider, addr
    server.stop()


def test_metrics_interceptor_counts_unary_and_stream(echo_server):
    provider, addr = echo_server
    ch = channel_to(addr)
    call = ch.unary_unary("/test.Echo/Call")
    assert call(b"hello") == b"hello"
    assert call(b"hello") == b"hello"
    stream = ch.stream_stream("/test.Echo/Stream")
    assert list(stream(iter([b"a", b"b"]))) == [b"a", b"b"]
    with pytest.raises(grpc.RpcError):
        call(b"boom")
    ch.close()

    text = provider.gather()
    assert (
        'grpc_server_unary_requests_received{service="test.Echo",'
        'method="Call"} 3' in text
    )
    assert (
        'grpc_server_unary_requests_completed{service="test.Echo",'
        'method="Call",code="OK"} 2' in text
    )
    assert (
        'grpc_server_unary_requests_completed{service="test.Echo",'
        'method="Call",code="Unknown"} 1' in text
    )
    assert (
        'grpc_server_stream_requests_received{service="test.Echo",'
        'method="Stream"} 1' in text
    )
    assert (
        'grpc_server_stream_requests_completed{service="test.Echo",'
        'method="Stream",code="OK"} 1' in text
    )
    assert "grpc_server_unary_request_duration" in text
    assert "grpc_server_stream_request_duration" in text


def test_payload_logging_at_debug_level():
    """grpclogging payload logger: DEBUG level => every request/response
    message logged with direction and size (grpclogging/server.go)."""
    import logging

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    plog = logging.getLogger("test.grpc.payload")
    plog.setLevel(logging.DEBUG)
    plog.addHandler(Capture())
    plog.propagate = False

    server = GRPCServer(
        "127.0.0.1:0",
        interceptors=[LoggingInterceptor(payload_logger=plog)],
    )
    server.register(
        "test.Echo2",
        {
            "Call": (UNARY, lambda req, ctx: req, lambda b: b, lambda b: b),
            "Stream": (
                STREAM_STREAM,
                lambda it, ctx: (x for x in it),
                lambda b: b,
                lambda b: b,
            ),
        },
    )
    addr = server.start()
    try:
        ch = channel_to(addr)
        assert ch.unary_unary("/test.Echo2/Call")(b"ping") == b"ping"
        assert list(ch.stream_stream("/test.Echo2/Stream")(iter([b"a", b"b"]))) == [
            b"a",
            b"b",
        ]
        ch.close()
    finally:
        server.stop()

    recv = [r for r in records if "payload recv" in r]
    send = [r for r in records if "payload send" in r]
    assert len(recv) == 3  # 1 unary + 2 streamed requests
    assert len(send) == 3  # 1 unary + 2 streamed responses
    assert all("grpc.service=test.Echo2" in r for r in records)

    # silent when the payload logger is above DEBUG
    records.clear()
    plog.setLevel(logging.INFO)
    server2 = GRPCServer(
        "127.0.0.1:0",
        interceptors=[LoggingInterceptor(payload_logger=plog)],
    )
    server2.register(
        "test.Echo3",
        {"Call": (UNARY, lambda req, ctx: req, lambda b: b, lambda b: b)},
    )
    addr2 = server2.start()
    try:
        ch = channel_to(addr2)
        assert ch.unary_unary("/test.Echo3/Call")(b"ping") == b"ping"
        ch.close()
    finally:
        server2.stop()
    assert records == []


# ----------------------------------------------------------------------
# per-service concurrency limits + cert hot reload (round 5;
# usable-inter-nal/peer/node/grpc_limiters.go + pkg/comm server.go:44)
# ----------------------------------------------------------------------


def test_concurrency_limiter_rejects_over_limit():
    import threading
    import time

    import grpc

    from fabric_tpu.comm.server import (
        ConcurrencyLimiter,
        GRPCServer,
        UNARY,
        channel_to,
    )

    gate = threading.Event()
    started = threading.Event()

    def slow_echo(request, context):
        started.set()
        gate.wait(5.0)
        return request

    server = GRPCServer(
        "127.0.0.1:0",
        interceptors=[ConcurrencyLimiter({"test.Slow": 1})],
    )
    server.register(
        "test.Slow", {"Go": (UNARY, slow_echo, bytes, bytes)}
    )
    addr = server.start()
    try:
        conn = channel_to(addr)
        call = conn.unary_unary("/test.Slow/Go")
        fut = call.future(b"a")  # occupies the single slot
        assert started.wait(5.0)
        with pytest.raises(grpc.RpcError) as err:
            call(b"b", timeout=5.0)  # second concurrent -> refused
        assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        gate.set()
        assert fut.result(timeout=5.0) == b"a"
        # slot released: next call passes
        assert call(b"c", timeout=5.0) == b"c"
        conn.close()
    finally:
        server.stop()


@requires_crypto
def test_cert_reloader_tracks_file_changes(tmp_path):
    from fabric_tpu.comm.server import CertReloader
    from fabric_tpu.msp.cryptogen import OrgCA

    ca = OrgCA("reload.test", "Org1MSP")
    pair1 = ca.enroll_tls("node1")
    pair2 = ca.enroll_tls("node1")  # rotated material, same CA

    cert = tmp_path / "server.crt"
    key = tmp_path / "server.key"
    cert.write_bytes(pair1.cert_pem)
    key.write_bytes(pair1.key_pem)

    reloader = CertReloader(str(cert), str(key))
    assert reloader.reloads == 1
    reloader._fetch()
    assert reloader.reloads == 1  # unchanged files: no re-read

    import os

    cert.write_bytes(pair2.cert_pem)
    key.write_bytes(pair2.key_pem)
    os.utime(cert)  # ensure fresh mtime even on coarse clocks
    reloader._fetch()
    assert reloader.reloads == 2  # rotation picked up

    # rotation-in-progress: a missing file keeps the last good config
    key.unlink()
    cfg = reloader._fetch()
    assert cfg is not None and reloader.reloads == 2
    assert reloader.credentials() is not None


@requires_crypto
def test_tls_credentials_from_config_dialects(tmp_path):
    """Both node config spellings resolve; enabled-but-incomplete is a
    hard error; absent/disabled sections mean plaintext."""
    import pytest as _pytest

    from fabric_tpu.comm.server import tls_credentials_from_config
    from fabric_tpu.msp.cryptogen import OrgCA

    pair = OrgCA("cfg.test", "Org1MSP").enroll_tls("node")
    cert = tmp_path / "c.pem"
    key = tmp_path / "k.pem"
    ca = tmp_path / "ca.pem"
    cert.write_bytes(pair.cert_pem)
    key.write_bytes(pair.key_pem)
    ca.write_bytes(pair.ca_pem)

    # peer spelling
    assert tls_credentials_from_config(
        {"enabled": True, "cert": str(cert), "key": str(key)}
    ) is not None
    # orderer spelling + list-valued ClientRootCAs
    assert tls_credentials_from_config(
        {
            "Enabled": True,
            "Certificate": str(cert),
            "PrivateKey": str(key),
            "ClientRootCAs": [str(ca)],
        }
    ) is not None
    # plaintext cases
    assert tls_credentials_from_config(None) is None
    assert tls_credentials_from_config({}) is None
    assert tls_credentials_from_config({"enabled": False, "cert": str(cert)}) is None
    # enabled but incomplete: refuse to start rather than silent plaintext
    with _pytest.raises(ValueError):
        tls_credentials_from_config({"Enabled": True, "Certificate": str(cert)})
    with _pytest.raises(ValueError):
        tls_credentials_from_config({"enabled": True})
