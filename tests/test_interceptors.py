"""gRPC server interceptors (reference common/grpclogging +
common/grpcmetrics): RPC logs and counters/durations on the metrics SPI."""

import grpc
import pytest

from fabric_tpu.common.metrics import PrometheusProvider
from fabric_tpu.comm.interceptors import LoggingInterceptor, MetricsInterceptor
from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM, UNARY, channel_to


@pytest.fixture
def echo_server():
    provider = PrometheusProvider()
    server = GRPCServer(
        "127.0.0.1:0",
        interceptors=[LoggingInterceptor(), MetricsInterceptor(provider)],
    )

    def echo(request, context):
        if request == b"boom":
            raise ValueError("boom")
        return request

    def echo_stream(request_iterator, context):
        for req in request_iterator:
            yield req

    server.register(
        "test.Echo",
        {
            "Call": (UNARY, echo, lambda b: b, lambda b: b),
            "Stream": (STREAM_STREAM, echo_stream, lambda b: b, lambda b: b),
        },
    )
    addr = server.start()
    yield provider, addr
    server.stop()


def test_metrics_interceptor_counts_unary_and_stream(echo_server):
    provider, addr = echo_server
    ch = channel_to(addr)
    call = ch.unary_unary("/test.Echo/Call")
    assert call(b"hello") == b"hello"
    assert call(b"hello") == b"hello"
    stream = ch.stream_stream("/test.Echo/Stream")
    assert list(stream(iter([b"a", b"b"]))) == [b"a", b"b"]
    with pytest.raises(grpc.RpcError):
        call(b"boom")
    ch.close()

    text = provider.gather()
    assert (
        'grpc_server_unary_requests_received{service="test.Echo",'
        'method="Call"} 3' in text
    )
    assert (
        'grpc_server_unary_requests_completed{service="test.Echo",'
        'method="Call",code="OK"} 2' in text
    )
    assert (
        'grpc_server_unary_requests_completed{service="test.Echo",'
        'method="Call",code="Unknown"} 1' in text
    )
    assert (
        'grpc_server_stream_requests_received{service="test.Echo",'
        'method="Stream"} 1' in text
    )
    assert (
        'grpc_server_stream_requests_completed{service="test.Echo",'
        'method="Stream",code="OK"} 1' in text
    )
    assert "grpc_server_unary_request_duration" in text
    assert "grpc_server_stream_request_duration" in text
