"""Chaincode-as-a-service + reference-format platform packages
(reference ccaas external builder / chaincode_server.go, and
core/chaincode/platforms golang/node lifecycle package layout).

- A ccaas package (metadata type "ccaas" + connection.json) makes the
  PEER dial the already-running chaincode server; the shim protocol is
  unchanged, only who dials whom flips.
- A stock reference-format golang package (metadata.json with
  type/path/label, source under src/) round-trips package -> install ->
  external-builder detect/build/run -> invoke.
"""

import io
import json
import os
import stat
import tarfile
import textwrap

import pytest

from fabric_tpu.chaincode import shim
from fabric_tpu.chaincode.extbuilder import ExternalBuilder, Launcher
from fabric_tpu.chaincode.extserver import ChaincodeListener
from fabric_tpu.chaincode.extshim import CcaasServer
from fabric_tpu.chaincode.package import (
    PackageStore,
    package,
    package_id,
    parse_package,
)
from fabric_tpu.chaincode.support import ChaincodeSupport, TxParams
from fabric_tpu.comm.server import GRPCServer
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.ledger.statedb import VersionedDB


class KV:
    def init(self, stub):
        return shim.success(b"")

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return shim.success(b"stored")
        if fn == "get":
            return shim.success(stub.get_state(params[0]) or b"")
        return shim.error_response("unknown " + fn)


@pytest.fixture
def listener_server():
    listener = ChaincodeListener()
    server = GRPCServer("127.0.0.1:0")
    listener.register(server)
    server.start()
    yield listener, server.addr
    server.stop()


def _exec(support, name, args):
    db = VersionedDB()
    sim = TxSimulator(db, "tx1")
    params = TxParams(channel_id="ch", tx_id="tx1", simulator=sim)
    resp, _ = support.execute(params, name, args)
    return resp, sim


def test_ccaas_package_install_connect_invoke(tmp_path, listener_server):
    listener, _addr = listener_server

    # the chaincode runs FIRST, as its own server (ccaas deployment)
    raw_probe = package(
        "kvccaas", {"connection.json": b"{}"}, cc_type="ccaas"
    )
    pid = package_id(raw_probe)
    server = CcaasServer(KV(), pid)
    cc_addr = server.start()
    try:
        # the installed package carries the server's address
        raw = package(
            "kvccaas",
            {
                "connection.json": json.dumps(
                    {"address": cc_addr, "dial_timeout": "10s",
                     "tls_required": False}
                ).encode()
            },
            cc_type="ccaas",
        )
        store = PackageStore(str(tmp_path / "pkgs"))
        installed = store.install(raw)
        assert installed.cc_type == "ccaas"

        support = ChaincodeSupport(
            listener=listener,
            launcher=Launcher(str(tmp_path / "build")),
            package_store=store,
            # lifecycle maps the name to THIS installed package id; the
            # ccaas server registered under the probe pid so alias logic
            # is exercised too
            source_resolver=lambda cid, name: (
                installed.package_id if name == "kvcc" else None
            ),
            chaincode_address=lambda: None,
        )
        resp, sim = _exec(support, "kvcc", [b"put", b"k1", b"v1"])
        assert resp.status == shim.OK, resp.message
        results = sim.get_tx_simulation_results()
        ns = [n for n in results.rwset.ns_rw_sets if n.namespace == "kvcc"]
        assert ns and [w.key for w in ns[0].writes] == ["k1"]
    finally:
        server.stop()


def test_go_duration_parse():
    from fabric_tpu.chaincode.support import _parse_go_duration

    assert _parse_go_duration("10s", 99.0) == 10.0
    assert _parse_go_duration("500ms", 99.0) == 0.5
    assert _parse_go_duration("1m30s", 99.0) == 90.0
    assert _parse_go_duration("1.5s", 99.0) == 1.5
    assert _parse_go_duration("bogus", 99.0) == 99.0
    assert _parse_go_duration(None, 99.0) == 99.0
    assert _parse_go_duration("", 99.0) == 99.0


def test_ccaas_dead_address_fails_fast(tmp_path, listener_server):
    """A ccaas target that is not a chaincode server must fail the
    launch within dial_timeout, not hang the transaction thread."""
    import time as _time

    from fabric_tpu.chaincode.support import LaunchError

    listener, _addr = listener_server
    raw = package(
        "deadcc",
        {
            "connection.json": json.dumps(
                {"address": "127.0.0.1:1", "dial_timeout": "1s"}
            ).encode()
        },
        cc_type="ccaas",
    )
    store = PackageStore(str(tmp_path / "pkgs"))
    installed = store.install(raw)
    support = ChaincodeSupport(
        listener=listener,
        launcher=Launcher(str(tmp_path / "build")),
        package_store=store,
        source_resolver=lambda cid, name: installed.package_id,
        chaincode_address=lambda: None,
    )
    db = VersionedDB()
    sim = TxSimulator(db, "tx1")
    params = TxParams(channel_id="ch", tx_id="tx1", simulator=sim)
    t0 = _time.time()
    with pytest.raises(LaunchError):
        try:
            support.execute(params, "deadcc", [b"put", b"k", b"v"])
        except Exception as exc:
            raise exc if isinstance(exc, LaunchError) else LaunchError(exc)
    assert _time.time() - t0 < 8.0


GO_MOD = b"module example.com/asset\n\ngo 1.21\n"
MAIN_GO = b"package main\n\nfunc main() {}\n"


def _reference_golang_package(label="asset_1"):
    """Handcraft the EXACT reference lifecycle tgz layout — built with
    raw tarfile calls, not our packager, to prove acceptance of foreign
    package bytes (persistence/chaincode_package.go)."""
    code_buf = io.BytesIO()
    with tarfile.open(fileobj=code_buf, mode="w:gz") as tar:
        for name, data in (
            ("src/go.mod", GO_MOD),
            ("src/main.go", MAIN_GO),
            ("META-INF/statedb/couchdb/indexes/indexOwner.json",
             b'{"index":{"fields":["owner"]}}'),
        ):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    meta = json.dumps(
        {"path": "example.com/asset", "type": "golang", "label": label}
    ).encode()
    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w:gz") as tar:
        for name, data in (
            ("metadata.json", meta),
            ("code.tar.gz", code_buf.getvalue()),
        ):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return out.getvalue()


def _golang_builder(tmp_path) -> ExternalBuilder:
    """A fake golang toolchain honoring the external-builder contract:
    detect claims type golang; build 'compiles' (drops a runnable shim
    program); run starts it against the peer from chaincode.json."""
    bdir = tmp_path / "gobuilder"
    bindir = bdir / "bin"
    os.makedirs(bindir)

    detect = bindir / "detect"
    detect.write_text(
        "#!/bin/sh\n"
        'grep -q \'"type": *"golang"\' "$2/metadata.json"\n'
    )
    build = bindir / "build"
    runner_src = textwrap.dedent(
        '''
        from fabric_tpu.chaincode.shim import success, error_response

        class Chaincode:
            def init(self, stub):
                return success(b"")
            def invoke(self, stub):
                fn, params = stub.get_function_and_parameters()
                if fn == "put":
                    stub.put_state(params[0], params[1].encode())
                    return success(b"stored-go")
                return error_response("unknown " + fn)
        chaincode = Chaincode()
        '''
    )
    build.write_text(
        "#!/bin/sh\n"
        "set -e\n"
        'test -f "$1/src/go.mod"\n'  # the golang layout arrived intact
        'cp -r "$1" "$3/src-copy"\n'
        f'cat > "$3/chaincode.py" << \'EOF\'\n{runner_src}\nEOF\n'
    )
    run = bindir / "run"
    run.write_text(
        "#!/bin/sh\n"
        "exec python - \"$1\" \"$2\" << 'EOF'\n"
        "import json, subprocess, sys\n"
        "out_dir, run_dir = sys.argv[1], sys.argv[2]\n"
        "cfg = json.load(open(run_dir + '/chaincode.json'))\n"
        "subprocess.run([sys.executable, '-m',\n"
        "    'fabric_tpu.chaincode.launcher',\n"
        "    '--source-dir', out_dir,\n"
        "    '--peer-address', cfg['peer_address'],\n"
        "    '--chaincode-id', cfg['chaincode_id']])\n"
        "EOF\n"
    )
    for f in (detect, build, run):
        f.chmod(f.stat().st_mode | stat.S_IEXEC)
    return ExternalBuilder(str(bdir))


def test_reference_golang_package_via_external_builder(
    tmp_path, listener_server
):
    listener, addr = listener_server
    raw = _reference_golang_package()
    meta, files = parse_package(raw)
    assert meta["type"] == "golang" and meta["path"] == "example.com/asset"
    assert "src/go.mod" in files  # reference src/ layout accepted

    store = PackageStore(str(tmp_path / "pkgs"))
    installed = store.install(raw)
    assert installed.cc_type == "golang"

    launcher = Launcher(
        str(tmp_path / "build"), builders=[_golang_builder(tmp_path)]
    )
    support = ChaincodeSupport(
        listener=listener,
        launcher=launcher,
        package_store=store,
        source_resolver=lambda cid, name: (
            installed.package_id if name == "asset" else None
        ),
        chaincode_address=lambda: addr,
    )
    try:
        resp, sim = _exec(support, "asset", [b"put", b"k9", b"gopher"])
        assert resp.status == shim.OK, resp.message
        assert resp.payload == b"stored-go"
        results = sim.get_tx_simulation_results()
        ns = [n for n in results.rwset.ns_rw_sets if n.namespace == "asset"]
        assert ns and [w.key for w in ns[0].writes] == ["k9"]
    finally:
        launcher.stop()


def test_cli_package_golang_layout(tmp_path):
    """peer lifecycle chaincode package --lang golang emits the
    reference layout (src/ roots + path in metadata)."""
    import sys

    from fabric_tpu.cli.peer import main as peer_main

    src = tmp_path / "gosrc"
    os.makedirs(src)
    (src / "go.mod").write_bytes(GO_MOD)
    (src / "main.go").write_bytes(MAIN_GO)
    out = tmp_path / "asset.tar.gz"
    rc = peer_main(
        [
            "lifecycle", "chaincode", "package", str(out),
            "--path", str(src), "--label", "asset_1", "--lang", "golang",
        ]
    )
    assert rc == 0
    meta, files = parse_package(out.read_bytes())
    assert meta["type"] == "golang"
    assert meta["label"] == "asset_1"
    assert meta["path"] == str(src)
    assert set(files) == {"src/go.mod", "src/main.go"}


def test_ccaas_reconnects_after_server_restart(tmp_path, listener_server):
    """Stream death (chaincode server restart) must not wedge the name:
    the handler leaves the registry and the NEXT invoke re-dials the
    (re-started) server at the same address."""
    import socket
    import time as _time

    listener, _addr = listener_server
    raw_probe = package("rcc", {"connection.json": b"{}"}, cc_type="ccaas")
    pid = package_id(raw_probe)

    # pin a port so the restarted server reuses the address
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cc_addr = f"127.0.0.1:{port}"

    server = CcaasServer(KV(), pid, listen_address=cc_addr)
    server.start()
    raw = package(
        "rcc",
        {
            "connection.json": json.dumps(
                {"address": cc_addr, "dial_timeout": "5s"}
            ).encode()
        },
        cc_type="ccaas",
    )
    store = PackageStore(str(tmp_path / "pkgs"))
    installed = store.install(raw)
    support = ChaincodeSupport(
        listener=listener,
        launcher=Launcher(str(tmp_path / "build")),
        package_store=store,
        source_resolver=lambda cid, name: installed.package_id,
        chaincode_address=lambda: None,
    )
    resp, _ = _exec(support, "rcc", [b"put", b"a", b"1"])
    assert resp.status == shim.OK, resp.message

    # restart the chaincode server (stream dies server-side)
    server.stop()
    deadline = _time.time() + 10
    while listener.connected(installed.package_id) and _time.time() < deadline:
        _time.sleep(0.05)
    assert not listener.connected(installed.package_id), "stale handler"

    server2 = CcaasServer(KV(), pid, listen_address=cc_addr)
    server2.start()
    try:
        # the re-dial happens per invoke; retry briefly while the OS
        # releases the old port / the fresh server finishes binding
        deadline = _time.time() + 10
        while True:
            try:
                resp, _ = _exec(support, "rcc", [b"put", b"b", b"2"])
                break
            except Exception:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.2)
        assert resp.status == shim.OK, resp.message  # re-dialed
    finally:
        server2.stop()
