"""fabwire unit tests: a firing fixture + negative control per rule
(with the two HISTORICAL wire bugs re-created in fixture form: the
pre-PR-8 unclamped ``retry_after_ms`` sleep fires
``unbounded-wire-alloc`` and an ``encode_lanes`` body emitted without
the ``version=`` key fires ``encode-decode-skew`` — the shipped fixed
shapes are the negative controls), suppression semantics, loud
wire.toml parse errors, CLI plumbing, the toolkit analyzer-registry
protocol, and the repo self-check (the CI gate invariant:
``fabwire fabric_tpu/`` reports 0 unsuppressed findings).

Fixture code lives in *strings* on purpose: only genuine AST shapes
may feed the rules, and the fixtures deliberately contain skewed and
unbounded frames that must never look like package code."""

import json
import textwrap
from pathlib import Path

import pytest

from fabric_tpu.tools import fabreg, fabwire, toolkit
from fabric_tpu.tools.fabwire import WireSpec, parse_wire

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG = "fabric_tpu/m.py"


def wire(text):
    return parse_wire(textwrap.dedent(text), "<test-wire>")


def analyze(src, path=PKG, rules=None, spec=None):
    findings, _n = fabwire.analyze_source(
        textwrap.dedent(src), path, rules,
        wire=spec if spec is not None else WireSpec(),
    )
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


#: a codec row binding encode_rec/decode_rec in the fixture module
PAIR_WIRE = """
    [[codec]]
    name = "fix.rec"
    module = "fabric_tpu/m.py"
    encoder = "encode_rec"
    decoder = "decode_rec"
    revs = [1]
"""


# ---------------------------------------------------------------------------
# encode-decode-skew: layout symmetry
# ---------------------------------------------------------------------------


def test_skew_width_divergence_fires():
    findings = analyze(
        """
        import struct

        def encode_rec(a, b):
            return struct.pack(">HI", a, b)

        def decode_rec(buf):
            a, b = struct.unpack(">II", buf)
            return a, b
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "width skew" in findings[0].message


def test_skew_negative_control_symmetric_pair():
    findings = analyze(
        """
        import struct

        def encode_rec(a, b):
            return struct.pack(">HI", a, b)

        def decode_rec(buf):
            a, b = struct.unpack(">HI", buf)
            return a, b
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert findings == []


def test_skew_endianness_divergence_fires():
    findings = analyze(
        """
        import struct

        def encode_rec(a, b):
            return struct.pack(">HI", a, b)

        def decode_rec(buf):
            a, b = struct.unpack("<HI", buf)
            return a, b
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "endianness skew" in findings[0].message


def test_skew_extra_decoder_field_fires():
    findings = analyze(
        """
        import struct

        def encode_rec(a):
            return struct.pack(">I", a)

        def decode_rec(buf):
            a, b = struct.unpack(">IH", buf)
            return a, b
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "decoder" in findings[0].message and "extra" in findings[0].message


def test_skew_repeated_group_layouts_compare_and_diverge():
    clean = """
        import struct

        def encode_rec(items):
            out = [struct.pack(">H", len(items))]
            for it in items:
                out.append(struct.pack(">I", it))
            return b"".join(out)

        def decode_rec(buf):
            (n,) = struct.unpack_from(">H", buf, 0)
            return [
                struct.unpack_from(">I", buf, 2 + 4 * i)[0]
                for i in range(n)
            ]
        """
    assert analyze(clean, rules=["encode-decode-skew"],
                   spec=wire(PAIR_WIRE)) == []
    skewed = clean.replace('unpack_from(">I", buf, 2 + 4 * i)',
                           'unpack_from(">H", buf, 2 + 2 * i)')
    findings = analyze(skewed, rules=["encode-decode-skew"],
                       spec=wire(PAIR_WIRE))
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "group" in findings[0].message


def test_skew_socket_framed_pair_with_fetch_helper_is_symmetric():
    src = """
        import struct

        _HEADER = struct.Struct(">2sBBII")

        def _recv_exact(sock, n):
            buf = b""
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                buf += chunk
            return buf

        def encode_rec(version, opcode, req_id, payload):
            return _HEADER.pack(
                b"FT", version, opcode, req_id, len(payload)
            ) + payload

        def decode_rec(sock):
            head = _recv_exact(sock, _HEADER.size)
            magic, version, opcode, req_id, length = _HEADER.unpack(head)
            payload = _recv_exact(sock, length)
            return version, opcode, req_id, payload
        """
    assert analyze(src, rules=["encode-decode-skew"],
                   spec=wire(PAIR_WIRE)) == []
    skewed = src.replace(
        "magic, version, opcode, req_id, length = _HEADER.unpack(head)",
        'magic, version, opcode, length = struct.unpack(">2sBBI", head)',
    )
    findings = analyze(skewed, rules=["encode-decode-skew"],
                       spec=wire(PAIR_WIRE))
    assert rule_ids(findings) == ["encode-decode-skew"]


def test_skew_renamed_codec_function_is_loud():
    findings = analyze(
        """
        import struct

        def encode_rec_v2(a):
            return struct.pack(">I", a)

        def decode_rec(buf):
            (a,) = struct.unpack(">I", buf)
            return a
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "not found" in findings[0].message


# the PR 14 historical shape: a body emitted at the caller's current
# revision onto a connection that may have negotiated an older one —
# judged against the packaged wire.toml [[contract]] row
ENCODE_LANES_PRE_PR14 = """
def send(client, k, s, d):
    payload = encode_lanes(k, s, d)
    return client.submit(OP_VERIFY, payload)
"""

ENCODE_LANES_FIXED = """
def send(client, k, s, d):
    payload = encode_lanes(k, s, d, version=client.version)
    return client.submit(OP_VERIFY, payload)
"""


def test_skew_fires_on_pre_pr14_encode_lanes_without_version():
    findings = analyze(ENCODE_LANES_PRE_PR14,
                       rules=["encode-decode-skew"],
                       spec=fabwire.load_default_wire())
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "version=" in findings[0].message


def test_skew_negative_control_is_the_version_threaded_call():
    findings = analyze(ENCODE_LANES_FIXED,
                       rules=["encode-decode-skew"],
                       spec=fabwire.load_default_wire())
    assert findings == []


def test_skew_unsupported_struct_code_is_loud_not_silent():
    findings = analyze(
        """
        import struct

        def encode_rec(x):
            return struct.pack(">f", x)

        def decode_rec(buf):
            (x,) = struct.unpack(">f", buf)
            return x
        """,
        rules=["encode-decode-skew"],
        spec=wire(PAIR_WIRE),
    )
    assert rule_ids(findings) == ["encode-decode-skew"]
    assert "cannot summarize" in findings[0].message


# ---------------------------------------------------------------------------
# rev-gate-drift: revision-gated fields
# ---------------------------------------------------------------------------

GATED_WIRE = """
    [[codec]]
    name = "fix.rec"
    module = "fabric_tpu/m.py"
    encoder = "encode_rec"
    decoder = "decode_rec"
    revs = [1, 2]

    [[field]]
    codec = "fix.rec"
    name = "extra"
    rev = 2
    gate = "extra"
"""

GATED_OK = """
import struct

def encode_rec(x, version, extra=None):
    out = [struct.pack(">I", x)]
    if version >= 2:
        out.append(struct.pack(">H", extra))
    return b"".join(out)

def decode_rec(buf, version):
    (x,) = struct.unpack_from(">I", buf, 0)
    extra = None
    if version >= 2:
        (extra,) = struct.unpack_from(">H", buf, 4)
    return x, extra
"""


def test_gate_correctly_gated_field_is_clean_at_every_rev():
    assert analyze(GATED_OK, spec=wire(GATED_WIRE)) == []


def test_gate_ungated_decoder_read_fires():
    src = GATED_OK.replace(
        "    extra = None\n    if version >= 2:\n"
        '        (extra,) = struct.unpack_from(">H", buf, 4)',
        '    (extra,) = struct.unpack_from(">H", buf, 4)',
    )
    findings = analyze(src, rules=["rev-gate-drift"],
                       spec=wire(GATED_WIRE))
    assert rule_ids(findings) == ["rev-gate-drift"]
    assert "rev 1" in findings[0].message


def test_gate_wrong_rev_encoder_write_fires():
    src = GATED_OK.replace("if version >= 2:\n        out.append",
                           "if version >= 3:\n        out.append")
    findings = analyze(src, rules=["rev-gate-drift"],
                       spec=wire(GATED_WIRE))
    assert "rev-gate-drift" in rule_ids(findings)


def test_gate_declared_field_with_no_token_is_table_drift():
    findings = analyze(
        """
        import struct

        def encode_rec(x, version):
            return struct.pack(">I", x)

        def decode_rec(buf, version):
            (x,) = struct.unpack(">I", buf)
            return x
        """,
        rules=["rev-gate-drift"],
        spec=wire(GATED_WIRE),
    )
    assert rule_ids(findings) == ["rev-gate-drift", "rev-gate-drift"]
    assert "drifted" in findings[0].message


# ---------------------------------------------------------------------------
# unbounded-wire-alloc: decoded lengths into sinks
# ---------------------------------------------------------------------------

# the pre-PR-8 shape: a u32 the SERVER chose, slept verbatim — a
# hostile or buggy peer parks the client for 49 days
RETRY_PRE_PR8 = """
import struct
import time

def wait_hint(hdr):
    status, retry_after_ms, n = struct.unpack(">BII", hdr)
    time.sleep(retry_after_ms / 1000.0)
"""

RETRY_FIXED = """
import struct
import time

def wait_hint(hdr):
    status, retry_after_ms, n = struct.unpack(">BII", hdr)
    time.sleep(min(retry_after_ms, 5000) / 1000.0)
"""


def test_alloc_fires_on_pre_pr8_retry_after_ms_sleep():
    findings = analyze(RETRY_PRE_PR8, rules=["unbounded-wire-alloc"])
    assert rule_ids(findings) == ["unbounded-wire-alloc"]
    assert "retry_after_ms" in findings[0].message


def test_alloc_negative_control_is_the_clamped_shape():
    assert analyze(RETRY_FIXED, rules=["unbounded-wire-alloc"]) == []


def test_alloc_u8_u16_fields_are_width_bounded():
    findings = analyze(
        """
        import struct

        def read_small(r, sock):
            n = r.u16()
            m, = struct.unpack(">H", sock.recv(2))
            return sock.recv(n) + sock.recv(m)
        """,
        rules=["unbounded-wire-alloc"],
    )
    assert findings == []


def test_alloc_reader_u32_into_range_and_recv_fires():
    findings = analyze(
        """
        def read_table(r, sock):
            n = r.u32()
            rows = [r.u16() for _ in range(n)]
            return sock.recv(n), rows
        """,
        rules=["unbounded-wire-alloc"],
    )
    assert len(findings) == 2
    assert set(rule_ids(findings)) == {"unbounded-wire-alloc"}


def test_alloc_guard_and_raise_dominates_the_sink():
    findings = analyze(
        """
        MAX_PAYLOAD = 64 << 20

        def read_body(r, sock):
            n = r.u32()
            if n > MAX_PAYLOAD:
                raise ValueError("oversized frame")
            return sock.recv(n)
        """,
        rules=["unbounded-wire-alloc"],
    )
    assert findings == []


def test_alloc_trusted_source_rows_are_clean_without_one_fires():
    src = """
        def read_rec(f):
            ln = decode_length(f.read(8))
            return f.read(ln)
        """
    trusted = wire(
        """
        [[trusted]]
        function = "decode_length"
        """
    )
    assert analyze(src, rules=["unbounded-wire-alloc"],
                   spec=trusted) == []
    findings = analyze(src, rules=["unbounded-wire-alloc"])
    assert rule_ids(findings) == ["unbounded-wire-alloc"]


def test_alloc_sink_rows_extend_the_builtin_sinks():
    src = """
        import struct

        def read_rec(sock, hdr):
            (ln,) = struct.unpack(">I", hdr)
            return _recv_exact(sock, ln)
        """
    assert analyze(src, rules=["unbounded-wire-alloc"]) == []
    sink = wire(
        """
        [[sink]]
        function = "_recv_exact"
        arg = 1
        """
    )
    findings = analyze(src, rules=["unbounded-wire-alloc"], spec=sink)
    assert rule_ids(findings) == ["unbounded-wire-alloc"]


def test_alloc_sequence_repeat_allocation_fires():
    findings = analyze(
        """
        import struct

        def blow_up(hdr):
            (n,) = struct.unpack(">Q", hdr)
            return b"\\x00" * n
        """,
        rules=["unbounded-wire-alloc"],
    )
    assert rule_ids(findings) == ["unbounded-wire-alloc"]


# ---------------------------------------------------------------------------
# status-untotal: dispatch totality over wire-constant families
# ---------------------------------------------------------------------------

ENUM_WIRE = """
    [[enum]]
    prefix = "ST_"
    module = "fabric_tpu/m.py"
    members = ["ST_OK", "ST_BUSY", "ST_ERROR"]
"""

ENUM_CONSTS = """
ST_OK = 0
ST_BUSY = 1
ST_ERROR = 2
"""


def test_untotal_missing_member_without_else_fires():
    findings = analyze(
        ENUM_CONSTS + """
def handle(status):
    if status == ST_OK:
        return "ok"
    elif status == ST_BUSY:
        return "busy"
""",
        rules=["status-untotal"],
        spec=wire(ENUM_WIRE),
    )
    assert rule_ids(findings) == ["status-untotal"]
    assert "ST_ERROR" in findings[0].message


def test_untotal_fail_closed_else_satisfies():
    findings = analyze(
        ENUM_CONSTS + """
def handle(status):
    if status == ST_OK:
        return "ok"
    elif status == ST_BUSY:
        return "busy"
    else:
        raise ValueError(status)
""",
        rules=["status-untotal"],
        spec=wire(ENUM_WIRE),
    )
    assert findings == []


def test_untotal_full_coverage_including_in_tuple_satisfies():
    findings = analyze(
        ENUM_CONSTS + """
def handle(status):
    if status == ST_OK:
        return "ok"
    elif status in (ST_BUSY, ST_ERROR):
        return "retry"
""",
        rules=["status-untotal"],
        spec=wire(ENUM_WIRE),
    )
    assert findings == []


def test_untotal_single_if_fallthrough_is_not_a_dispatch():
    findings = analyze(
        ENUM_CONSTS + """
def handle(status):
    if status == ST_BUSY:
        return "busy"
    return "pass through"
""",
        rules=["status-untotal"],
        spec=wire(ENUM_WIRE),
    )
    assert findings == []


def test_untotal_member_list_drift_from_module_is_loud():
    findings = analyze(
        ENUM_CONSTS + "ST_STOPPING = 3\n",
        rules=["status-untotal"],
        spec=wire(ENUM_WIRE),
    )
    assert rule_ids(findings) == ["status-untotal"]
    assert "drifted" in findings[0].message
    assert "ST_STOPPING" in findings[0].message


# ---------------------------------------------------------------------------
# frame-crc-gap: durability-store write/read twins
# ---------------------------------------------------------------------------

STORE_WIRE = """
    [[store]]
    name = "fix"
    module = "fabric_tpu/m.py"
    writers = ["Store.write_rec"]
    readers = ["Store.read_rec"]
    checks = ["header", "payload"]
"""

STORE_OK = """
import struct
import zlib

def frame_header(n):
    hdr = struct.pack("<I", n)
    return hdr + struct.pack("<I", zlib.crc32(hdr))

def read_frame_header(raw8):
    ln, hcrc = struct.unpack("<II", raw8)
    if zlib.crc32(raw8[:4]) != hcrc:
        return None
    return ln

class Store:
    def write_rec(self, f, raw):
        f.write(frame_header(len(raw)))
        f.write(raw)
        f.write(struct.pack("<I", zlib.crc32(raw)))

    def read_rec(self, f):
        hdr = f.read(8)
        ln = read_frame_header(hdr)
        raw = f.read(ln)
        (crc,) = struct.unpack("<I", f.read(4))
        if zlib.crc32(raw) != crc:
            return None
        return raw
"""


def test_crc_gap_matched_twins_are_clean():
    assert analyze(STORE_OK, rules=["frame-crc-gap"],
                   spec=wire(STORE_WIRE)) == []


def test_crc_gap_reader_skipping_payload_crc_fires():
    src = STORE_OK.replace(
        '        (crc,) = struct.unpack("<I", f.read(4))\n'
        "        if zlib.crc32(raw) != crc:\n"
        "            return None\n"
        "        return raw",
        "        f.read(4)\n        return raw",
    )
    findings = analyze(src, rules=["frame-crc-gap"],
                       spec=wire(STORE_WIRE))
    assert rule_ids(findings) == ["frame-crc-gap"]
    assert "payload crc32" in findings[0].message


def test_crc_gap_reader_skipping_header_verify_fires():
    src = STORE_OK.replace(
        "        ln = read_frame_header(hdr)",
        '        (ln, _hcrc) = struct.unpack("<II", hdr)',
    )
    findings = analyze(src, rules=["frame-crc-gap"],
                       spec=wire(STORE_WIRE))
    assert rule_ids(findings) == ["frame-crc-gap"]
    assert "header crc" in findings[0].message


def test_crc_gap_writer_without_checksum_fires():
    src = STORE_OK.replace(
        '        f.write(struct.pack("<I", zlib.crc32(raw)))\n', ""
    )
    findings = analyze(src, rules=["frame-crc-gap"],
                       spec=wire(STORE_WIRE))
    assert rule_ids(findings) == ["frame-crc-gap"]
    assert "no payload checksum" in findings[0].message


def test_crc_gap_unlisted_frame_toucher_fires():
    src = STORE_OK + """
def side_channel(f, raw):
    f.write(struct.pack("<I", zlib.crc32(raw)))
"""
    findings = analyze(src, rules=["frame-crc-gap"],
                       spec=wire(STORE_WIRE))
    assert rule_ids(findings) == ["frame-crc-gap"]
    assert "not " in findings[0].message and "listed" in findings[0].message


def test_crc_gap_stale_store_row_is_loud():
    spec = wire(STORE_WIRE.replace("Store.read_rec", "Store.gone"))
    findings = analyze(STORE_OK, rules=["frame-crc-gap"], spec=spec)
    # the vanished reader is loud twice over: the row is stale AND the
    # real read_rec is no longer covered by any store row
    assert set(rule_ids(findings)) == {"frame-crc-gap"}
    assert any("stale" in f.message for f in findings)
    assert any("escape" in f.message for f in findings)


# ---------------------------------------------------------------------------
# wire.toml: packaged table + loud parse errors
# ---------------------------------------------------------------------------


def test_packaged_wire_table_parses_and_names_the_surfaces():
    spec = fabwire.load_default_wire()
    codec_names = {c.name for c in spec.codecs}
    assert "serve.verify_request" in codec_names
    assert "serve.verify_response" in codec_names
    assert "orderer.raft_message" in codec_names
    assert {f.name for f in spec.fields} == {
        "qos_class", "channel", "deadline_ms"
    }
    assert {e.prefix for e in spec.enums} == {"OP_", "ST_"}
    assert {s.name for s in spec.stores} == {
        "blockstore", "pvtdatastore", "raft_wal", "raft_snapshot"
    }
    assert ("encode_lanes", "version") in spec.contracts
    assert "read_frame_header" in spec.trusted
    assert ("_recv_exact", 1) in spec.sinks
    # every codec/enum/store module is also a declared surface
    surfaces = set(spec.surfaces)
    for module in (
        [c.module for c in spec.codecs]
        + [e.module for e in spec.enums]
        + [s.module for s in spec.stores]
    ):
        assert module in surfaces, f"{module} missing a [[surface]] row"


@pytest.mark.parametrize(
    "text,err",
    [
        ("[[bogus]]\n", "unknown section"),
        ("[[codec]]\nname = \"x\"\n", "missing required key"),
        ("module = \"x\"\n", "outside a"),
        ("[[codec]]\nrevs = [maybe]\n", "list items"),
        ("[[sink]]\nfunction = \"f\"\narg = \"one\"\n", "arg must be"),
        ("[[enum]]\nprefix = \"X_\"\nmodule = \"m\"\nmembers = []\n",
         "non-empty"),
        ("[[store]]\nname = \"s\"\nmodule = \"m\"\nwriters = \"w\"\n"
         "readers = \"r\"\nchecks = [\"both\"]\n", "header"),
        ("[[field]]\ncodec = \"ghost\"\nname = \"f\"\nrev = 2\n",
         "unknown codec"),
        ("[[codec]]\nname - \"x\"\n", "expected 'key = value'"),
    ],
)
def test_wire_table_parse_errors_are_loud(text, err):
    with pytest.raises(ValueError, match=err):
        parse_wire(text, "<bad>")


def test_cli_rejects_bad_wire_table(tmp_path, capsys):
    bad = tmp_path / "wire.toml"
    bad.write_text("[[bogus]]\n")
    target = tmp_path / "fabric_tpu" / "m.py"
    target.parent.mkdir()
    target.write_text("x = 1\n")
    rc = fabwire.main(["--wire", str(bad), str(target)])
    assert rc == 2
    assert "wire table" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# suppressions, CLI, syntax errors
# ---------------------------------------------------------------------------


def test_suppression_absorbs_finding_and_is_counted():
    src = textwrap.dedent(
        """
        def send(client, k, s, d):
            return encode_lanes(k, s, d)  # fabwire: disable=encode-decode-skew  # fixture exercises the raw layout
        """
    )
    findings, n = fabwire.analyze_source(
        src, PKG, ["encode-decode-skew"],
        wire=fabwire.load_default_wire(),
    )
    assert findings == []
    assert n == 1


def test_suppression_disable_all_silences_the_line():
    src = textwrap.dedent(
        """
        import struct
        import time

        def wait_hint(hdr):
            status, retry_after_ms, n = struct.unpack(">BII", hdr)
            time.sleep(retry_after_ms / 1000.0)  # fabwire: disable=all  # fixture
        """
    )
    findings, n = fabwire.analyze_source(src, PKG, wire=WireSpec())
    assert findings == []
    assert n == 1


def test_cli_json_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "fabric_tpu" / "m.py"
    bad.parent.mkdir()
    bad.write_text(
        "import struct\nimport time\n\n"
        "def wait_hint(hdr):\n"
        '    status, retry_after_ms, n = struct.unpack(">BII", hdr)\n'
        "    time.sleep(retry_after_ms / 1000.0)\n"
    )
    rc = fabwire.main(["--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert [f["rule"] for f in out["findings"]] == ["unbounded-wire-alloc"]

    clean = tmp_path / "fabric_tpu" / "ok.py"
    clean.write_text("x = 1\n")
    assert fabwire.main([str(clean)]) == 0
    capsys.readouterr()

    assert fabwire.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rid in fabwire.RULES:
        assert rid in listed

    assert fabwire.main(["--rules", "no-such-rule", str(clean)]) == 2
    assert fabwire.main([str(tmp_path / "missing.py")]) == 2
    assert fabwire.main([]) == 2


def test_syntax_error_is_reported_not_raised():
    findings = analyze("def broken(:\n", rules=["unbounded-wire-alloc"])
    assert rule_ids(findings) == ["syntax-error"]


# ---------------------------------------------------------------------------
# toolkit registry + fabreg staleness protocol
# ---------------------------------------------------------------------------


def test_fabwire_is_registered_with_the_toolkit():
    assert "fabwire" in toolkit.ANALYZER_TOOLS
    spec = toolkit.analyzer_spec("fabwire")
    assert spec is not None
    assert spec.module == "fabric_tpu.tools.fabwire"
    # package-scoped: tests craft skewed/truncated frames by design
    assert spec.pkg_scope_only is True


def test_live_suppression_keys_reports_absorbing_comments():
    src = textwrap.dedent(
        """
        def send(client, k, s, d):
            return encode_lanes(k, s, d)  # fabwire: disable=encode-decode-skew  # raw-layout fixture
        """
    )
    keys = fabwire.live_suppression_keys({PKG: src},
                                         {"encode-decode-skew"})
    assert len(keys) == 1
    ((path, line, rule),) = keys
    assert rule == "encode-decode-skew"
    assert path.endswith("fabric_tpu/m.py")


def test_fabreg_suppression_stale_judges_fabwire_via_the_registry():
    live = textwrap.dedent(
        """
        def send(client, k, s, d):
            return encode_lanes(k, s, d)  # fabwire: disable=encode-decode-skew  # raw-layout fixture
        """
    )
    stale = textwrap.dedent(
        """
        def quiet():
            x = 1  # fabwire: disable=unbounded-wire-alloc  # outlived its cause
            return x
        """
    )
    findings, _stats = fabreg.analyze_sources(
        {"fabric_tpu/live.py": live, "fabric_tpu/stale.py": stale},
        rule_ids=["suppression-stale"],
    )
    assert rule_ids(findings) == ["suppression-stale"]
    assert findings[0].path == "fabric_tpu/stale.py"
    assert "fabwire" in findings[0].message


# ---------------------------------------------------------------------------
# repo self-check: the CI gate invariant
# ---------------------------------------------------------------------------


def test_repo_has_zero_unsuppressed_findings():
    findings, stats = fabwire.analyze_paths([str(REPO_ROOT / "fabric_tpu")])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings
    )
    # the triaged by-design suppressions (NOTES_BUILD PR 17) are live:
    # the sha256-sealed snapshot reader and the operator-owned AOT cache
    assert stats["suppressed"] == 2
