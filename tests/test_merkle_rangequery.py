"""Merkle-summarized range-query simulation + phantom-read validation
(reference rwsetutil/query_results_helper.go and
validation/rangequery_validator.go rangeQueryHashValidator)."""

import hashlib

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.merkle import RangeQueryResultsHelper, serialize_kv_reads
from fabric_tpu.ledger.mvcc import Validator
from fabric_tpu.ledger.simulator import TxSimulator
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB
from fabric_tpu.validation.txflags import TxValidationCode


def seeded_db(n=100):
    db = VersionedDB()
    seed = UpdateBatch()
    for i in range(n):
        seed.put("cc", f"k{i:04d}", b"v%d" % i, rw.Version(0, i))
    db.apply_updates(seed)
    return db


def reads(n, start=0):
    return [rw.KVRead(f"k{i:04d}", rw.Version(0, i)) for i in range(start, start + n)]


def test_small_result_set_stays_raw():
    h = RangeQueryResultsHelper(True, 3)
    for r in reads(3):
        h.add_result(r)
    raw, summary = h.done()
    assert summary is None
    assert raw == tuple(reads(3))


def test_summary_structure_pinned():
    """maxDegree=2: leaves are batches of 3 reads (pending spills when it
    EXCEEDS maxDegree). done() hashes the 1-read tail into a third
    level-1 node, which overflows maxDegree and collapses the level into
    one level-2 node — the exact shape query_results_helper.go produces."""
    h = RangeQueryResultsHelper(True, 2)
    rs = reads(7)
    for r in rs:
        h.add_result(r)
    raw, summary = h.done()
    assert raw == ()
    sha = lambda b: hashlib.sha256(b).digest()  # noqa: E731
    leaf1 = sha(serialize_kv_reads(rs[0:3]))
    leaf2 = sha(serialize_kv_reads(rs[3:6]))
    tail = sha(serialize_kv_reads(rs[6:7]))  # done() processes pending
    assert summary == (2, 2, (sha(leaf1 + leaf2 + tail),))


def test_deep_tree_spills_levels():
    h = RangeQueryResultsHelper(True, 2)
    for r in reads(40):
        h.add_result(r)
    _raw, (deg, level, hashes) = h.done()
    assert deg == 2
    assert level >= 2
    assert 1 <= len(hashes) <= 2


def sim_range(db, max_degree):
    sim = TxSimulator(db, "t1", range_query_hashing_max_degree=max_degree)
    list(sim.get_state_range_scan_iterator("cc", "k0000", "k0090"))
    sim.set_state("cc", "k0000", b"new")
    return sim.get_tx_simulation_results().rwset


def test_simulate_validate_roundtrip_clean():
    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    rqi = txrw.ns_rw_sets[0].range_queries[0]
    assert rqi.reads_merkle_hashes is not None  # 90 results >> degree 4
    assert rqi.raw_reads == ()
    codes, *_ = Validator(db).validate_and_prepare_batch(
        1, [txrw], [TxValidationCode.VALID]
    )
    assert codes == [TxValidationCode.VALID]


def test_phantom_insert_detected():
    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    extra = UpdateBatch()
    extra.put("cc", "k0050a", b"phantom", rw.Version(1, 0))
    db.apply_updates(extra)
    codes, *_ = Validator(db).validate_and_prepare_batch(
        2, [txrw], [TxValidationCode.VALID]
    )
    assert codes == [TxValidationCode.PHANTOM_READ_CONFLICT]


def test_phantom_delete_detected():
    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    extra = UpdateBatch()
    extra.delete("cc", "k0030", rw.Version(1, 0))
    db.apply_updates(extra)
    codes, *_ = Validator(db).validate_and_prepare_batch(
        2, [txrw], [TxValidationCode.VALID]
    )
    assert codes == [TxValidationCode.PHANTOM_READ_CONFLICT]


def test_early_version_change_detected():
    """Mismatch in the first leaf batch exits via the incremental
    comparison (not only the final summary equality)."""
    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    extra = UpdateBatch()
    extra.put("cc", "k0001", b"bumped", rw.Version(1, 0))
    db.apply_updates(extra)
    codes, *_ = Validator(db).validate_and_prepare_batch(
        2, [txrw], [TxValidationCode.VALID]
    )
    assert codes == [TxValidationCode.PHANTOM_READ_CONFLICT]


def test_in_block_shadow_write_conflicts():
    """An earlier in-block valid tx writing inside the scanned range
    changes the re-executed result set (combined iterator)."""
    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    writer = rw.TxRwSet(
        (rw.NsRwSet("cc", (), (rw.KVWrite("k0042", False, b"w"),)),)
    )
    codes, *_ = Validator(db).validate_and_prepare_batch(
        1, [writer, txrw], [TxValidationCode.VALID, TxValidationCode.VALID]
    )
    assert codes == [
        TxValidationCode.VALID,
        TxValidationCode.PHANTOM_READ_CONFLICT,
    ]


def test_proto_roundtrip_preserves_summary():
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.validation.msgvalidation import parse_tx_rwset

    db = seeded_db()
    txrw = sim_range(db, max_degree=4)
    parsed = parse_tx_rwset(serialize_tx_rwset(txrw))
    got = parsed.ns_rw_sets[0].range_queries[0]
    want = txrw.ns_rw_sets[0].range_queries[0]
    assert got.reads_merkle_hashes == want.reads_merkle_hashes
