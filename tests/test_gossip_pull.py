"""Gossip pull mediator + msgstore + TLS-bound stream handshake
(reference gossip/gossip/pull/pullstore.go, gossip/msgstore/msgs.go,
gossip/comm/comm_impl.go:563 authenticateRemotePeer).

- MessageStore: dedup, rank invalidation, TTL expiry.
- Block pull: a late joiner whose height metadata never spread (no
  anti-entropy trigger) converges through the digest/request/response
  four-step alone.
- mTLS handshake: a peer whose ConnEstablish binds the WRONG TLS cert
  hash — a stolen identity replayed over the attacker's own TLS
  session — is refused; the correctly-bound peer is served.
"""


from conftest import requires_crypto

import hashlib
import time

from fabric_tpu.comm.server import tls_server_credentials
from fabric_tpu.gossip.comm import GossipNode
from fabric_tpu.gossip.msgstore import MessageStore
from fabric_tpu.gossip.state import StateProvider
from fabric_tpu.protos import protoutil


def make_chain(n):
    blocks = []
    prev = b""
    for i in range(n):
        b = protoutil.new_block(i, prev)
        b.data.data.append(f"tx{i}".encode())
        protoutil.seal_block(b)
        prev = protoutil.block_header_hash(b.header)
        blocks.append(b)
    return blocks


class FakeLedger:
    def __init__(self, blocks=()):
        self.blocks = list(blocks)

    def commit(self, block):
        assert block.header.number == len(self.blocks)
        self.blocks.append(block)

    def get_block(self, n):
        return self.blocks[n] if n < len(self.blocks) else None

    @property
    def height(self):
        return len(self.blocks)


def make_node(name, ledger, tick=0.05, **kw):
    state = StateProvider("gchannel", ledger.commit, lambda: ledger.height)
    return GossipNode(
        name,
        "gchannel",
        state,
        ledger.get_block,
        lambda: ledger.height,
        tick_interval=tick,
        **kw,
    )


def wait_until(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


# ----------------------------------------------------------------------
# MessageStore
# ----------------------------------------------------------------------


class TestMessageStore:
    def test_dedup_and_rank(self):
        s = MessageStore(ttl_s=30.0)
        assert s.add(("alive", "p1"), rank=1)
        assert not s.add(("alive", "p1"), rank=1)  # duplicate
        assert not s.add(("alive", "p1"), rank=0)  # older rank invalidated
        assert s.add(("alive", "p1"), rank=2)  # newer invalidates stored
        assert s.add(("data", 7))
        assert not s.add(("data", 7))
        assert s.seen(("data", 7))

    def test_ttl_expiry(self):
        s = MessageStore(ttl_s=0.05)
        assert s.add("k")
        assert not s.add("k")
        time.sleep(0.08)
        assert s.add("k")  # expired: can circulate again

    def test_bounded(self):
        s = MessageStore(ttl_s=300.0, max_entries=64)
        for i in range(200):
            s.add(("k", i))
        assert len(s) <= 64


# ----------------------------------------------------------------------
# block pull
# ----------------------------------------------------------------------


def test_block_pull_round_direct():
    """One full hello->digest->request->update exchange moves blocks,
    no membership or height metadata involved."""
    chain = make_chain(4)
    tall, joiner = FakeLedger(chain), FakeLedger()
    n_tall, n_join = make_node("tall", tall, tick=5), make_node(
        "join", joiner, tick=5
    )
    n_tall.start()
    n_join.start()
    try:
        n_join._send(n_tall.addr, [n_join.pull.hello_blocks()])
        assert wait_until(lambda: joiner.height == 4), joiner.height
    finally:
        n_tall.stop()
        n_join.stop()


def test_late_joiner_converges_via_pull_alone():
    """Height-driven anti-entropy disabled (simulating lost metadata):
    the periodic pull round still converges the late joiner."""
    chain = make_chain(3)
    tall, joiner = FakeLedger(chain), FakeLedger()
    n_tall, n_join = make_node("tall", tall), make_node("join", joiner)
    # disable state anti-entropy + leader push on the joiner
    n_join._taller_peer_endpoints = lambda needed: []
    n_join.state.missing_range = lambda heights: None
    n_tall.start()
    n_join.start()
    try:
        n_join.connect(n_tall.addr)
        assert wait_until(lambda: joiner.height == 3), joiner.height
    finally:
        n_tall.stop()
        n_join.stop()


# ----------------------------------------------------------------------
# TLS-bound handshake
# ----------------------------------------------------------------------


def _sig_hooks(identity_bytes):
    """Toy signer: 'signature' = sha256(identity || data). Enough to
    prove the BINDING logic (who signed what over which TLS cert); real
    deployments pass MSP signer/verifier hooks here."""

    def sign(data, _id=identity_bytes):
        return hashlib.sha256(_id + data).digest()

    def verify(identity, data, sig):
        return hashlib.sha256(identity + data).digest() == sig

    return sign, verify


def _tls_nodes(tmp_pair_a, tmp_pair_b, joiner_cert_der_for_claim=None):
    """Two mTLS gossip nodes; the joiner claims `joiner_cert_der_for_claim`
    (defaults to its real cert) in its handshake."""
    serve_creds = tls_server_credentials(
        tmp_pair_a.cert_pem, tmp_pair_a.key_pem, client_ca_pem=tmp_pair_a.ca_pem
    )
    sign_a, verify = _sig_hooks(b"identity-tall")
    tall = make_node(
        "tall",
        FakeLedger(make_chain(2)),
        identity_bytes=b"identity-tall",
        sign_message=sign_a,
        pvt_verify_member_sig=verify,
        tls_server_creds=serve_creds,
        tls_client=(tmp_pair_a.ca_pem, (tmp_pair_a.key_pem, tmp_pair_a.cert_pem)),
        self_tls_cert_der=tmp_pair_a.cert_der,
        require_handshake=True,
    )
    sign_b, _ = _sig_hooks(b"identity-join")
    claim_der = joiner_cert_der_for_claim or tmp_pair_b.cert_der
    joiner_ledger = FakeLedger()
    joiner = make_node(
        "join",
        joiner_ledger,
        identity_bytes=b"identity-join",
        sign_message=sign_b,
        pvt_verify_member_sig=verify,
        tls_client=(tmp_pair_a.ca_pem, (tmp_pair_b.key_pem, tmp_pair_b.cert_pem)),
        self_tls_cert_der=claim_der,
        require_handshake=True,
    )
    return tall, joiner, joiner_ledger


def _org_tls():
    from fabric_tpu.msp.cryptogen import OrgCA

    ca = OrgCA("org1.tls.test", "Org1MSP")
    return ca.enroll_tls("peer0.org1.tls.test"), ca.enroll_tls(
        "peer1.org1.tls.test"
    )


@requires_crypto
def test_handshake_right_cert_served():
    pair_a, pair_b = _org_tls()
    tall, joiner, jl = _tls_nodes(pair_a, pair_b)
    tall.start()
    joiner.start()
    try:
        joiner._send(tall.addr, [joiner.pull.hello_blocks()])
        assert wait_until(lambda: jl.height == 2, timeout=15), jl.height
    finally:
        tall.stop()
        joiner.stop()


@requires_crypto
def test_handshake_wrong_cert_rejected():
    """The joiner presents pair_b on the wire but its signed handshake
    binds pair_a's cert hash (stolen-claim splice): server refuses the
    stream, no blocks flow."""
    pair_a, pair_b = _org_tls()
    tall, joiner, jl = _tls_nodes(
        pair_a, pair_b, joiner_cert_der_for_claim=pair_a.cert_der
    )
    tall.start()
    joiner.start()
    try:
        joiner._send(tall.addr, [joiner.pull.hello_blocks()])
        time.sleep(1.5)
        assert jl.height == 0
    finally:
        tall.stop()
        joiner.stop()


@requires_crypto
def test_handshake_spoofed_pki_id_rejected():
    """A valid member handshaking under ANOTHER peer's pki_id is
    refused: the certstore verify hook is the pki<->identity binding
    authority, so the first-bind-wins store cannot be pre-poisoned."""
    pair_a, pair_b = _org_tls()
    tall, joiner, jl = _tls_nodes(pair_a, pair_b)
    # binding authority on the server: pki_id must match the identity
    tall.certstore._verify = lambda pki, ident: (
        ident == b"identity-" + pki.decode().encode()
    )
    tall.start()
    joiner.start()
    try:
        # the joiner claims the pki_id "victim" with its own identity;
        # its signature and TLS binding are otherwise perfectly valid
        joiner.self_id = "victim"
        joiner.certstore._store[b"victim"] = b"identity-join"
        joiner._conn_msg_cache = None  # rebuild with the spoofed claim
        joiner._send(tall.addr, [joiner.pull.hello_blocks()])
        time.sleep(1.5)
        assert jl.height == 0
        assert tall.certstore.get(b"victim") is None  # store not poisoned
    finally:
        tall.stop()
        joiner.stop()


@requires_crypto
def test_no_handshake_rejected_in_strict_mode():
    """A client that skips ConnEstablish entirely gets no service."""
    pair_a, pair_b = _org_tls()
    tall, joiner, jl = _tls_nodes(pair_a, pair_b)
    # strip the joiner's handshake capability
    joiner._require_handshake = False
    joiner._self_tls_cert_der = b""
    tall.start()
    joiner.start()
    try:
        joiner._send(tall.addr, [joiner.pull.hello_blocks()])
        time.sleep(1.5)
        assert jl.height == 0
    finally:
        tall.stop()
        joiner.stop()


@requires_crypto
def test_handshake_fuzz_mutations_never_authenticate():
    """Random mutations of a valid ConnEstablish (flipped pki, wrong
    channel, truncated/garbled signature, swapped cert hash) must never
    pass _handshake_ok on a strict server."""
    import random

    from fabric_tpu.gossip.comm import _conn_signing_bytes
    from fabric_tpu.protos import gossip_pb2

    pair_a, pair_b = _org_tls()
    tall, joiner, _jl = _tls_nodes(pair_a, pair_b)
    # binding authority: pki must match identity suffix
    tall.certstore._verify = lambda pki, ident: (
        ident == b"identity-" + pki.decode().encode()
    )

    class Ctx:  # mTLS context presenting the joiner's real client cert
        def auth_context(self):
            return {"x509_pem_cert": [pair_b.cert_pem]}

    valid = gossip_pb2.ConnEstablish()
    valid.pki_id = b"join"
    valid.identity = b"identity-join"
    valid.tls_cert_hash = hashlib.sha256(pair_b.cert_der).digest()
    sign, _v = _sig_hooks(b"identity-join")
    valid.signature = sign(
        _conn_signing_bytes("gchannel", b"join", valid.tls_cert_hash)
    )
    assert tall._handshake_ok(valid, Ctx())  # baseline sanity

    rng = random.Random(99)
    for _ in range(200):
        m = gossip_pb2.ConnEstablish()
        m.CopyFrom(valid)
        field = rng.choice(["pki", "ident", "sig", "hash", "chan"])
        if field == "pki":
            m.pki_id = bytes(rng.randrange(256) for _ in range(4))
        elif field == "ident":
            m.identity = bytes(rng.randrange(256) for _ in range(8))
        elif field == "sig":
            sig = bytearray(m.signature)
            if sig:
                sig[rng.randrange(len(sig))] ^= 1 << rng.randrange(8)
            m.signature = bytes(sig)
        elif field == "hash":
            h = bytearray(m.tls_cert_hash)
            h[rng.randrange(len(h))] ^= 1 << rng.randrange(8)
            m.tls_cert_hash = bytes(h)
        else:
            # signature computed over a DIFFERENT channel must fail here
            m.signature = sign(
                _conn_signing_bytes("otherchan", b"join", m.tls_cert_hash)
            )
        assert not tall._handshake_ok(m, Ctx()), field
    tall.stop()
    joiner.stop()


# ----------------------------------------------------------------------
# SWIM suspicion (reference discovery: probe-before-declare-dead)
# ----------------------------------------------------------------------


class TestSuspicion:
    def test_alive_suspect_dead_transitions(self):
        from fabric_tpu.gossip.membership import Membership

        m = Membership("me", alive_expiration_ticks=10, suspect_ticks=4)
        m.handle_alive({"id": "p1", "endpoint": "e1", "seq": 1})
        for _ in range(5):
            m.tick()
        assert m.suspect_peers() == ["p1"]
        assert m.alive_peers() == ["p1"]  # suspect is still routable
        assert m.newly_suspect() == ["p1"]
        assert m.newly_suspect() == []  # probed once per episode
        # refutation: a FRESH alive clears suspicion and re-arms probing
        m.handle_alive({"id": "p1", "endpoint": "e1", "seq": 2})
        assert m.suspect_peers() == []
        for _ in range(5):
            m.tick()
        assert m.newly_suspect() == ["p1"]  # new episode, new probe
        # silence past expiration -> dead
        for _ in range(7):
            m.tick()
        assert m.alive_peers() == [] and m.dead_peers() == ["p1"]
        assert m.suspect_peers() == []

    def test_stale_alive_does_not_refute(self):
        from fabric_tpu.gossip.membership import Membership

        m = Membership("me", alive_expiration_ticks=10, suspect_ticks=2)
        m.handle_alive({"id": "p1", "endpoint": "e1", "seq": 5})
        for _ in range(3):
            m.tick()
        assert m.suspect_peers() == ["p1"]
        assert not m.handle_alive({"id": "p1", "endpoint": "e1", "seq": 5})
        assert m.suspect_peers() == ["p1"]  # replayed seq changes nothing

    def test_probe_refutes_suspicion_when_pushes_stop(self):
        """Node A stops BROADCASTING alives (push loss) but still
        answers probes: B must keep A alive via the direct membership
        probe instead of expiring it (SWIM's core property)."""
        a_ledger, b_ledger = FakeLedger(), FakeLedger()
        a = make_node("peerA", a_ledger, tick=0.05)
        b = make_node("peerB", b_ledger, tick=0.05)
        # tight suspicion window, but an expiry horizon the test cannot
        # reach even on a starved CPU (full-suite contention flaked the
        # earlier 60-tick horizon): the property under test is that the
        # probe reply REFRESHES the peer, not wall-clock survival
        b.membership.suspect_ticks = 5
        b.membership.expiration = 100000
        a.start()
        b.start()
        try:
            a.connect(b.addr)
            assert wait_until(
                lambda: "peerA" in b.membership.alive_peers()
            )
            # A goes push-silent (its ticker no longer broadcasts) but
            # its server still answers membership probes
            a._intro_messages = lambda: []
            assert wait_until(
                lambda: b.membership._alive.get("peerA") is not None
                and b.membership._alive["peerA"].probed,
                timeout=30,
            ), "B never probed the silent peer"
            # the probe reply carries a FRESH seq: B's view of A
            # advances (suspicion refuted) even though A pushes nothing
            probed_seq = b.membership._alive["peerA"].seq
            assert wait_until(
                lambda: b.membership._alive.get("peerA") is not None
                and b.membership._alive["peerA"].seq > probed_seq
                and "peerA" not in b.membership.suspect_peers(),
                timeout=30,
            ), "probe reply never refuted the suspicion"
            assert "peerA" in b.membership.alive_peers()
            assert "peerA" not in b.membership.dead_peers()
        finally:
            a.stop()
            b.stop()
