"""Mesh-sharded validation (SURVEY.md §2.13 P3/P6, BASELINE config #5):
the sharded provider and the multi-channel single-step validator must be
bit-exact with the host SoftwareProvider path."""

import hashlib

import jax
import numpy as np
import pytest

from conftest import requires_crypto
from fabric_tpu.crypto import p256
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    SoftwareProvider,
    VerifyError,
)
from fabric_tpu.crypto.der import marshal_signature
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.parallel import (
    MeshTPUProvider,
    MultiChannelValidator,
    flat_mesh,
    grid_mesh,
)
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.validation.txflags import TxValidationCode
from fabric_tpu.validation.validator import (
    BlockValidator,
    ChaincodeDefinition,
    ChaincodeRegistry,
)

PROVIDER = SoftwareProvider()


@pytest.fixture(scope="module")
def cpu8():
    devices = jax.devices("cpu")
    if len(devices) < 8:
        pytest.skip("needs 8 virtual CPU devices (XLA_FLAGS in conftest)")
    return devices[:8]


# ----------------------------------------------------------------------
# flat (data-axis) sharding: MeshTPUProvider vs SoftwareProvider
# ----------------------------------------------------------------------


def _sig_cases(n):
    """(key, sig, digest, expected) mixing valid, wrong-digest, corrupt-DER
    and high-S lanes."""
    cases = []
    for i in range(n):
        priv = (i * 0x9E3779B97F4A7C15 + 11) % (p256.N - 1) + 1
        pub = p256.scalar_mult(priv, p256.GENERATOR)
        key = ECDSAPublicKey(pub[0], pub[1])
        digest = hashlib.sha256(f"case {i}".encode()).digest()
        k = (i * 0xD6E8FEB86659FD93 + 7) % (p256.N - 1) + 1
        r, s = p256.sign_digest(priv, digest, k=k)
        sig = marshal_signature(r, s)
        kind = i % 4
        if kind == 0:
            cases.append((key, sig, digest))
        elif kind == 1:  # wrong digest
            cases.append((key, sig, hashlib.sha256(b"other").digest()))
        elif kind == 2:  # corrupt DER
            cases.append((key, b"\x30\x03\x02\x01\x01", digest))
        else:  # high-S (rejected by the low-S rule, bccsp/sw/ecdsa.go:41)
            cases.append((key, marshal_signature(r, p256.N - s), digest))
    return cases


@pytest.mark.slow  # ~2min WARM on the 2-vCPU gate box (pure sharded-
# program execution, cache already hit — NOTES_BUILD tier-1 budget
# forensics); the multichannel grid test below keeps sharded-dispatch
# parity in tier-1.
def test_flat_sharded_matches_host(cpu8):
    cases = _sig_cases(48)
    expected = []
    for key, sig, digest in cases:
        try:
            expected.append(PROVIDER.verify(key, sig, digest))
        except VerifyError:
            expected.append(False)

    provider = MeshTPUProvider(flat_mesh(cpu8))
    got = provider.batch_verify(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    assert got == expected
    assert any(expected) and not all(expected)


# ----------------------------------------------------------------------
# channel-axis sharding: MultiChannelValidator vs per-channel oracle
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def net():
    org1 = generate_org("org1.example.com", "Org1MSP")
    org2 = generate_org("org2.example.com", "Org2MSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER), org2.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "mycc", from_dsl("AND('Org1MSP.member','Org2MSP.member')")
            )
        ]
    )
    return {
        "mgr": mgr,
        "registry": registry,
        "client": SigningIdentity(org1.users[0], PROVIDER),
        "p1": SigningIdentity(org1.peers[0], PROVIDER),
        "p2": SigningIdentity(org2.peers[0], PROVIDER),
    }


def _results_bytes(key):
    return serialize_tx_rwset(
        rw.TxRwSet((rw.NsRwSet("mycc", (), (rw.KVWrite(key, False, b"v"),)),))
    )


def _make_tx(net, channel, key, endorsers=("p1", "p2"), mangle=None):
    bundle = create_proposal(net["client"], channel, "mycc", [b"invoke", key.encode()])
    responses = [
        endorse_proposal(bundle, net[e], _results_bytes(key)) for e in endorsers
    ]
    env = create_signed_tx(bundle, net["client"], responses)
    if mangle:
        env = mangle(env)
    return env


def _make_block(envelopes, number):
    block = protoutil.new_block(number, b"\x22" * 32)
    for env in envelopes:
        block.data.data.append(env.SerializeToString())
    protoutil.seal_block(block)
    return block


def _bad_creator(env):
    env.signature = env.signature[:-4] + b"\x00\x00\x00\x00"
    return env


def _channel_block(net, channel, number):
    """A block mixing VALID, BAD_CREATOR_SIGNATURE and
    ENDORSEMENT_POLICY_FAILURE txs, unique per channel."""
    txs = [
        _make_tx(net, channel, f"{channel}-k0"),
        _make_tx(net, channel, f"{channel}-k1", mangle=_bad_creator),
        _make_tx(net, channel, f"{channel}-k2", endorsers=("p1",)),
        _make_tx(net, channel, f"{channel}-k3"),
    ]
    return _make_block(txs, number)


def _validator(net, channel):
    return BlockValidator(
        channel, net["mgr"], SoftwareProvider(), net["registry"]
    )


@requires_crypto
def test_multichannel_grid_bit_exact(cpu8, net):
    channels = [f"ch{i}" for i in range(4)]
    blocks = {ch: _channel_block(net, ch, 5) for ch in channels}

    # oracle: each channel through the host-only validator
    expected = {}
    for ch in channels:
        block = common_pb2.Block()
        block.CopyFrom(blocks[ch])
        expected[ch] = _validator(net, ch).validate(block).tobytes()

    mesh = grid_mesh(4, 2, cpu8)
    mc = MultiChannelValidator(
        mesh, {ch: _validator(net, ch) for ch in channels}
    )
    flags = mc.validate(blocks)

    for ch in channels:
        assert flags[ch].tobytes() == expected[ch], ch
        assert (
            blocks[ch].metadata.metadata[common_pb2.TRANSACTIONS_FILTER]
            == expected[ch]
        )
    # the scenario mix actually exercised all three codes
    codes = set(expected["ch0"])
    assert codes == {
        TxValidationCode.VALID,
        TxValidationCode.BAD_CREATOR_SIGNATURE,
        TxValidationCode.ENDORSEMENT_POLICY_FAILURE,
    }


@requires_crypto
def test_multichannel_rejects_unknown_channel(cpu8, net):
    mesh = grid_mesh(4, 2, cpu8)
    mc = MultiChannelValidator(mesh, {"ch0": _validator(net, "ch0")})
    with pytest.raises(KeyError):
        mc.validate({"nope": _channel_block(net, "nope", 1)})


def test_multichannel_epilogue_slices_host_mask_per_channel(monkeypatch):
    """PR 18 regression (fabtrace transfer-in-loop): the per-channel
    epilogue slices the ONE host materialization of the sharded mask —
    no second np.asarray copy per channel.  Fakes keep it device-free:
    each channel's ok_list must be exactly its own mask row's first n
    lanes, with the padded tail dropped."""
    from types import SimpleNamespace

    from fabric_tpu.parallel import multichannel as mc

    class FakeSharded:
        data_size = 1
        channel_size = 1

        def verify_channels(self, *stacked):
            return stacked[-1]  # the (channels, lanes) ok plane

    class FakePrep:
        def prep_limbs(self, keys, sigs, digests):
            import fabric_tpu.ops.bignum as bn

            n = len(keys)
            limbs = tuple(
                np.zeros((bn.NLIMBS, n), dtype=np.uint32) for _ in range(5)
            )
            ok = np.array([i % 2 == 0 for i in range(n)])
            return (*limbs, ok)

    class FakeValidator:
        def __init__(self, n):
            self.n = n

        def collect_sig_jobs(self, parsed):
            jobs = list(range(self.n))
            return jobs, jobs, jobs, jobs, jobs

        def finish_sig_results(self, jobs, job_identity, ok_list):
            return ok_list

        def validate(self, block, parsed, sig_results=None):
            return sig_results

    monkeypatch.setattr(mc, "parse_block", lambda data: data)
    v = mc.MultiChannelValidator.__new__(mc.MultiChannelValidator)
    v.validators = {"a": FakeValidator(3), "b": FakeValidator(5)}
    v.sharded = FakeSharded()
    v._prep = FakePrep()
    v.last_device_ms = 0.0

    block = SimpleNamespace(data=SimpleNamespace(data=[]))
    out = v.validate({"a": block, "b": block})
    assert out["a"] == [True, False, True]
    assert out["b"] == [True, False, True, False, True]
