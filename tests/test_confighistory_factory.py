"""Collection-config history (core/ledger/confighistory/mgr.go) and the
config-driven BCCSP factory (bccsp/factory/factory.go:64)."""

import pytest

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.crypto.factory import FactoryError, provider_from_config
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.confighistory import ConfigHistoryMgr
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.persistent import SqliteVersionedDB
from fabric_tpu.ledger.statedb import UpdateBatch
from fabric_tpu.protos import protoutil


@pytest.mark.parametrize("persistent", [False, True])
def test_confighistory_records_and_queries(tmp_path, persistent):
    db = (
        SqliteVersionedDB(str(tmp_path / "s.db")) if persistent else None
    )
    mgr = ConfigHistoryMgr(db)
    for block, cfg in ((3, b"cfg-a"), (7, b"cfg-b"), (12, b"cfg-c")):
        updates = UpdateBatch()
        updates.put(
            "_lifecycle",
            "namespaces/fields/mycc/Collections",
            cfg,
            rw.Version(block, 0),
        )
        updates.put("othercc", "unrelated", b"x", rw.Version(block, 1))
        mgr.record_from_updates(block, updates)

    assert mgr.most_recent_below("mycc", 3) is None
    assert mgr.most_recent_below("mycc", 4) == (3, b"cfg-a")
    assert mgr.most_recent_below("mycc", 12) == (7, b"cfg-b")
    assert mgr.most_recent_below("mycc", 100) == (12, b"cfg-c")
    assert mgr.most_recent_below("othercc", 100) is None


def test_confighistory_wired_into_commit(tmp_path):
    ledger = KVLedger(str(tmp_path), "ch")
    rwset = rw.TxRwSet(
        (
            rw.NsRwSet(
                "_lifecycle",
                (),
                (
                    rw.KVWrite(
                        "namespaces/fields/asset/Collections",
                        False,
                        b"coll-config-v1",
                    ),
                ),
            ),
        )
    )
    block = protoutil.new_block(0, b"")
    block.data.data.append(b"\x00")
    protoutil.seal_block(block)
    ledger.commit(block, rwsets=[rwset])
    assert ledger.config_history.most_recent_below("asset", 99) == (
        0,
        b"coll-config-v1",
    )
    # history survives reopen (persistent ledger)
    ledger.block_store.close()
    ledger.pvt_store.close()
    ledger.state_db.close()
    again = KVLedger(str(tmp_path), "ch")
    assert again.config_history.most_recent_below("asset", 99) == (
        0,
        b"coll-config-v1",
    )


def test_bccsp_factory_selection():
    assert isinstance(
        provider_from_config({"Default": "SW"}), SoftwareProvider
    )
    # default config prefers the device provider but must degrade
    # gracefully when no accelerator exists — either type is a Provider
    p = provider_from_config(None)
    assert hasattr(p, "batch_verify")
    with pytest.raises(FactoryError):
        provider_from_config({"Default": "HSM9000"})
    with pytest.raises(FactoryError):
        provider_from_config({"SW": {"Hash": "SHA3"}})
    tpu = provider_from_config(
        {"Default": "TPU", "TPU": {"MinDeviceBatch": 7}}
    )
    if type(tpu).__name__ == "TPUProvider":
        assert tpu.MIN_DEVICE_BATCH == 7
