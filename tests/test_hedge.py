"""fabtail hedged verification + gray-failure eviction (serve/router)
and the OP_CANCEL races (serve/server): hedge delay from observed
quantiles, token-bucket budget math, first-verdict-wins with loser
cancellation, cancel-after-settle / settle-after-cancel /
cancel-before-dispatch, hedge-loser-after-degrade discarded unseen,
latency-outlier eviction, and the short-timeout health probe.  The
fleet-scale soaks are slow-marked; the unit tests here are their
tier-1 canaries."""

import threading
import time

import pytest

from fabric_tpu.common.faults import FaultPlan, plan_installed
from fabric_tpu.common.retry import RetryPolicy
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.client import SidecarClient, encode_lanes
from fabric_tpu.serve.router import (
    SidecarRouter,
    _HedgeBudget,
    _LatencyTracker,
    hedge_fraction_from_env,
    hedge_min_ms_from_env,
)
from fabric_tpu.serve.server import SidecarServer

from tests.test_serve import mixed_lanes

FAST_GATE = RetryPolicy(
    base_s=0.05, multiplier=2.0, cap_s=0.5, deadline_s=float("inf")
)


def start_sidecar(addr, chaos_key=None, provider=None):
    server = SidecarServer(
        str(addr), engine="host", warm_ladder="off", buckets=(64, 256),
        chaos_key=chaos_key, provider=provider,
    )
    if provider is None:
        server.warm()
    server.start()
    return server


class _GatedProvider:
    """Dispatch stalls behind a re-armable gate: compute happens
    eagerly (masks stay exact), the resolver is withheld until
    release — settle timing becomes a construction, not a race."""

    def __init__(self):
        self._sw = SoftwareProvider()
        self.gate = threading.Event()
        self.entered = threading.Event()

    def batch_verify(self, keys, sigs, digests):
        return self._sw.batch_verify(keys, sigs, digests)

    def batch_verify_async(self, keys, sigs, digests):
        out = self._sw.batch_verify(keys, sigs, digests)
        self.entered.set()
        self.gate.wait(20.0)
        return lambda: out

    def release(self):
        self.gate.set()

    def rearm(self):
        self.gate.clear()
        self.entered.clear()


# ---------------------------------------------------------------------------
# units: tracker + budget + env knobs
# ---------------------------------------------------------------------------


class TestLatencyTracker:
    def test_quantiles_and_ewma(self):
        t = _LatencyTracker()
        assert t.quantile(0.95) is None
        for ms in (10, 20, 30, 40, 1000):
            t.record(ms / 1000.0)
        assert t.samples == 5
        assert t.quantile(0.0) == 0.010
        assert t.quantile(1.0) == 1.0
        assert 0.0 < t.ewma_s < 1.0

    def test_window_is_bounded_newest_win(self):
        t = _LatencyTracker()
        for _ in range(t.WINDOW + 50):
            t.record(0.001)
        t.record(5.0)
        assert len(t._window) == t.WINDOW
        assert t.quantile(1.0) == 5.0  # the newest sample survived


class TestHedgeBudget:
    def test_fraction_bounds_spend(self):
        b = _HedgeBudget(fraction=0.5, burst=2.0)
        assert b.try_spend()  # the initial token
        assert not b.try_spend()  # bucket empty
        b.earn()
        assert not b.try_spend()  # 0.5 tokens: not yet
        b.earn()
        assert b.try_spend()  # 1.0 earned across 2 primaries
        # lifetime bound: spends <= burst + fraction * earned, always
        spends = 2
        assert spends <= b.burst + b.fraction * b.earned

    def test_burst_caps_idle_accrual(self):
        b = _HedgeBudget(fraction=1.0, burst=2.0)
        for _ in range(100):
            b.earn()
        assert b.try_spend() and b.try_spend()
        assert not b.try_spend()  # never more than burst banked

    def test_zero_fraction_disables(self):
        b = _HedgeBudget(fraction=0.0)
        b.earn()
        assert not b.try_spend()

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("FABRIC_TPU_SERVE_HEDGE_FRACTION", "0.2")
        monkeypatch.setenv("FABRIC_TPU_SERVE_HEDGE_MIN_MS", "7.5")
        assert hedge_fraction_from_env() == 0.2
        assert hedge_min_ms_from_env() == 7.5
        monkeypatch.setenv("FABRIC_TPU_SERVE_HEDGE_FRACTION", "junk")
        monkeypatch.setenv("FABRIC_TPU_SERVE_HEDGE_MIN_MS", "junk")
        assert hedge_fraction_from_env() == 0.05  # malformed: default
        assert hedge_min_ms_from_env() == 20.0


# ---------------------------------------------------------------------------
# hedged verification end to end
# ---------------------------------------------------------------------------


class TestHedgedVerify:
    def test_hedge_wins_against_gray_endpoint(self, tmp_path):
        """One sidecar delay-faulted (alive, answers PING, dead slow):
        the hedge fires after the learned delay, wins on the healthy
        peer, the mask is bit-exact, and the gray loser's reply is
        suppressed server-side (OP_CANCEL) or dropped client-side."""
        servers = {
            str(tmp_path / f"h{i}.sock"): start_sidecar(
                tmp_path / f"h{i}.sock", chaos_key=i + 1
            )
            for i in range(2)
        }
        router = SidecarRouter(
            endpoints=list(servers), gate_policy=FAST_GATE,
            hedge_fraction=1.0, hedge_min_ms=10_000.0,  # disarmed for warm
        )
        try:
            k, s, d, e = mixed_lanes(32)
            # warm: the preferred endpoint's tracker learns its shape
            for _ in range(3):
                assert list(router.batch_verify(k, s, d)) == e
            assert router.hedges == 0
            router.hedge_min_s = 0.010  # armed: floor 10ms
            victim = router._order(32)[0]
            gray = servers[victim.address]
            plan = FaultPlan.parse(
                f"serve.dispatch=delay:1.0:ms=1500:at={gray.chaos_key}",
                seed=3,
            )
            with plan_installed(plan):
                t0 = time.monotonic()
                mask = router.batch_verify(k, s, d)
                wall = time.monotonic() - t0
            assert list(mask) == e
            assert router.hedges == 1 and router.hedge_wins == 1
            assert wall < 1.5  # bounded by the hedge, not the gray delay
            assert not router.degraded
            # the loser is eventually accounted: either its OP_CANCEL
            # landed before dispatch/reply (cancelled_*) — never a
            # protocol error, never a served double-count
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                st = gray.stats.summary()
                if st["cancelled_pre"] + st["cancelled_post"] >= 1:
                    break
                time.sleep(0.05)
            st = gray.stats.summary()
            assert st["cancelled_pre"] + st["cancelled_post"] == 1
            assert gray.qos.balance()["leaked"] == 0
        finally:
            router.stop()
            for srv in servers.values():
                srv.stop()

    def test_hedge_budget_denies_without_tokens(self, tmp_path):
        """fraction=0 turns hedging off entirely: the gray endpoint is
        simply waited on (legacy behavior) — proof the budget gates the
        hedge path, so an overloaded fleet cannot be amplified."""
        servers = [
            start_sidecar(tmp_path / f"n{i}.sock", chaos_key=i + 1)
            for i in range(2)
        ]
        router = SidecarRouter(
            endpoints=[s.address for s in servers],
            gate_policy=FAST_GATE, hedge_fraction=0.0, hedge_min_ms=5.0,
        )
        try:
            k, s, d, e = mixed_lanes(16)
            victim_addr = router._order(16)[0].address
            gray = next(x for x in servers if x.address == victim_addr)
            plan = FaultPlan.parse(
                f"serve.dispatch=delay:1.0:ms=300:at={gray.chaos_key}",
                seed=3,
            )
            with plan_installed(plan):
                t0 = time.monotonic()
                mask = router.batch_verify(k, s, d)
                wall = time.monotonic() - t0
            assert list(mask) == e
            assert router.hedges == 0
            assert wall >= 0.3  # waited the gray delay out: no hedge
        finally:
            router.stop()
            for srv in servers:
                srv.stop()

    def test_hedge_loser_after_degrade_discarded_unseen(self, tmp_path):
        """Both endpoints dead slow + a tight budget: the router
        degrades to the in-process ladder (bit-exact), and the late
        verdicts — primary's AND any hedge's — are discarded unseen
        (cancelled server-side or dropped by the demux).  The ledger
        must still balance once the slow workers finish."""
        servers = [
            start_sidecar(tmp_path / f"s{i}.sock", chaos_key=i + 1)
            for i in range(2)
        ]
        router = SidecarRouter(
            endpoints=[s.address for s in servers],
            gate_policy=FAST_GATE, hedge_fraction=1.0, hedge_min_ms=5.0,
            deadline_ms=80,
        )
        try:
            k, s, d, e = mixed_lanes(24)
            plan = FaultPlan.parse("serve.dispatch=delay:1.0:ms=600", seed=3)
            with plan_installed(plan):
                t0 = time.monotonic()
                mask = router.batch_verify(k, s, d)
                wall = time.monotonic() - t0
            assert list(mask) == e  # the in-process ladder, bit-exact
            assert router.deadline_expired == 1
            assert router.degraded
            assert wall < 0.5  # the budget, not the 600ms delay
            # late verdicts from the abandoned sockets must vanish:
            # wait for the slow workers, then check nothing leaked
            deadline = time.monotonic() + 5.0
            for srv in servers:
                while time.monotonic() < deadline:
                    if srv.qos.balance()["in_flight"] == 0:
                        break
                    time.sleep(0.05)
                assert srv.qos.balance()["leaked"] == 0
            # and the router still serves normally afterwards
            mask2 = router.batch_verify(k, s, d)
            assert list(mask2) == e
        finally:
            router.stop()
            for srv in servers:
                srv.stop()


# ---------------------------------------------------------------------------
# gray-failure eviction
# ---------------------------------------------------------------------------


class TestGrayEviction:
    def test_consecutive_hedge_losses_evict(self, tmp_path):
        """Two straight lost hedges pull the gray endpoint from
        rotation through the CooldownGate ladder (counted as a slow
        eviction), and with the fault lifted it earns its way back
        through a probe — the same ladder as death."""
        servers = {
            str(tmp_path / f"e{i}.sock"): start_sidecar(
                tmp_path / f"e{i}.sock", chaos_key=i + 1
            )
            for i in range(2)
        }
        router = SidecarRouter(
            endpoints=list(servers), gate_policy=FAST_GATE,
            hedge_fraction=1.0, hedge_min_ms=10_000.0,  # disarmed for warm
        )
        try:
            k, s, d, e = mixed_lanes(32)
            for _ in range(3):
                assert list(router.batch_verify(k, s, d)) == e
            router.hedge_min_s = 0.010  # armed: floor 10ms
            victim = router._order(32)[0]
            gray = servers[victim.address]
            plan = FaultPlan.parse(
                f"serve.dispatch=delay:1.0:ms=1500:at={gray.chaos_key}",
                seed=5,
            )
            with plan_installed(plan):
                for _ in range(router.HEDGE_LOSS_EVICT):
                    assert list(router.batch_verify(k, s, d)) == e
                assert router.slow_evictions == 1
                assert not victim.healthy
                # while evicted, traffic routes direct to the healthy
                # peer — no hedge, no gray wait
                t0 = time.monotonic()
                assert list(router.batch_verify(k, s, d)) == e
                assert time.monotonic() - t0 < 1.0
            # fault lifted: the probe ladder brings it back
            deadline = time.monotonic() + 5.0
            back = False
            while time.monotonic() < deadline:
                if victim.gate.ready() and router._probe_ok(victim):
                    back = True
                    break
                time.sleep(0.02)
            assert back and victim.healthy
        finally:
            router.stop()
            for srv in servers.values():
                srv.stop()

    def test_last_endpoint_never_slow_evicted(self, tmp_path):
        """Gray eviction is a RELATIVE judgment: with every peer dead,
        the slow survivor stays in rotation (a slow verdict beats
        degrading the fleet in-process) — and a dead peer's frozen
        healthy-era EWMA must not serve as the outlier baseline."""
        servers = [
            start_sidecar(tmp_path / f"l{i}.sock") for i in range(2)
        ]
        router = SidecarRouter(
            endpoints=[s.address for s in servers], gate_policy=FAST_GATE,
        )
        try:
            fast, slow = router.endpoints
            # fast serves quickly, then dies with its EWMA frozen
            for _ in range(router.SLOW_MIN_SAMPLES):
                fast.tracker.record(0.005)
            fast.mark_down("crashed")
            # the survivor is 60ms — an outlier against the ghost's
            # 5ms, but the only endpoint in rotation: never evicted
            for _ in range(router.SLOW_MIN_SAMPLES * 2):
                router._note_latency(slow, 0.06)
            assert router.slow_evictions == 0
            assert slow.healthy
            k, s, d, e = mixed_lanes(16)
            assert list(router.batch_verify(k, s, d)) == e
            assert not router.degraded
        finally:
            router.stop()
            for srv in servers:
                srv.stop()

    def test_ewma_outlier_eviction_math(self, tmp_path):
        """The latency-outlier rule on recorded samples: an endpoint
        whose EWMA sits far above the fleet best (and the absolute
        floor) is evicted on its next served verdict."""
        servers = [
            start_sidecar(tmp_path / f"w{i}.sock") for i in range(2)
        ]
        router = SidecarRouter(
            endpoints=[s.address for s in servers], gate_policy=FAST_GATE,
        )
        try:
            fast, slow = router.endpoints
            for _ in range(router.SLOW_MIN_SAMPLES):
                fast.tracker.record(0.01)
                slow.tracker.record(0.01)
            # the slow endpoint drifts: its EWMA crosses 4x fleet best
            for _ in range(router.SLOW_MIN_SAMPLES):
                router._note_latency(slow, 0.5)
            assert router.slow_evictions >= 1
            assert not slow.healthy
            assert fast.healthy
        finally:
            router.stop()
            for srv in servers:
                srv.stop()


# ---------------------------------------------------------------------------
# OP_CANCEL races (the bookkeeping the tentpole calls the hard part)
# ---------------------------------------------------------------------------


class TestCancelRaces:
    def _lanes_payload(self, n=16, seed=0, deadline_ms=0):
        k, s, d, e = mixed_lanes(n, seed=seed)
        return encode_lanes(k, s, d, deadline_ms=deadline_ms), e

    def test_cancel_after_settle_is_a_noop(self, tmp_path):
        """A cancel that loses the race to the settlement: the client
        already consumed the reply, the server's stale cancel id ages
        out of the bounded set — nothing double-counts, the connection
        keeps serving."""
        server = start_sidecar(tmp_path / "c1.sock")
        client = SidecarClient(server.address)
        try:
            payload, e = self._lanes_payload()
            token = client.submit(proto.OP_VERIFY, payload)
            status, _, mask, _ = proto.decode_verify_response(
                client.await_reply(token)
            )
            assert status == proto.ST_OK and list(mask) == e
            client.cancel(token)  # local no-op: already consumed
            # the stale server-side cancel (raw frame, same rid)
            proto.send_frame(
                client._sock, proto.OP_CANCEL, token, b"", version=3
            )
            payload2, e2 = self._lanes_payload(seed=2)
            status2, _, mask2, _ = proto.decode_verify_response(
                client.request(proto.OP_VERIFY, payload2)
            )
            assert status2 == proto.ST_OK and list(mask2) == e2
            st = server.stats.summary()
            assert st["cancelled_pre"] == 0 and st["cancelled_post"] == 0
        finally:
            client.close()
            server.stop()

    def test_settle_after_cancel_suppresses_reply(self, tmp_path):
        """A cancel that beats the settlement: the verdict is computed
        but the reply is suppressed (cancelled_post), lanes release
        exactly once (no leak, no double-release), and the connection
        keeps serving."""
        gp = _GatedProvider()
        server = start_sidecar(tmp_path / "c2.sock", provider=gp)
        client = SidecarClient(server.address)
        try:
            payload, _e = self._lanes_payload()
            token = client.submit(proto.OP_VERIFY, payload)
            assert gp.entered.wait(5.0)  # dispatched, held at the gate
            client.cancel(token)
            time.sleep(0.1)  # let the cancel frame land in the set
            gp.release()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if server.stats.summary()["cancelled_post"] == 1:
                    break
                time.sleep(0.02)
            st = server.stats.summary()
            assert st["cancelled_post"] == 1
            assert st["requests"] == 0  # never recorded as served
            assert server.qos.balance()["leaked"] == 0
            gp.rearm()
            payload2, e2 = self._lanes_payload(seed=3)
            tok2 = client.submit(proto.OP_VERIFY, payload2)
            assert gp.entered.wait(5.0)
            gp.release()
            status, _, mask, _ = proto.decode_verify_response(
                client.await_reply(tok2)
            )
            assert status == proto.ST_OK and list(mask) == e2
        finally:
            gp.release()
            client.close()
            server.stop()

    def test_cancel_before_dispatch_sheds_uncomputed(self, tmp_path):
        """A cancel that arrives while the worker is still ahead of
        admission (pinned by a dispatch delay): the request is shed
        uncomputed (cancelled_pre), the QoS ledger never sees it."""
        server = start_sidecar(tmp_path / "c3.sock")
        client = SidecarClient(server.address)
        try:
            acquired_before = server.qos.balance()["acquired"]
            plan = FaultPlan.parse("serve.dispatch=delay:1.0:ms=300", seed=1)
            payload, _e = self._lanes_payload()
            with plan_installed(plan):
                token = client.submit(proto.OP_VERIFY, payload)
                client.cancel(token)  # lands while the worker sleeps
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if server.stats.summary()["cancelled_pre"] == 1:
                        break
                    time.sleep(0.02)
            st = server.stats.summary()
            assert st["cancelled_pre"] == 1
            assert server.qos.balance()["acquired"] == acquired_before
            payload2, e2 = self._lanes_payload(seed=4)
            status, _, mask, _ = proto.decode_verify_response(
                client.request(proto.OP_VERIFY, payload2)
            )
            assert status == proto.ST_OK and list(mask) == e2
        finally:
            client.close()
            server.stop()

    def test_cancel_not_sent_below_v3(self, tmp_path):
        """A v2-latched connection never emits OP_CANCEL (an old server
        would kill the stream on the unknown opcode): cancel() is a
        local drop only."""
        server = start_sidecar(tmp_path / "c4.sock")
        client = SidecarClient(server.address)
        try:
            payload, _e = self._lanes_payload()
            client.ensure_connected()
            client.version = 2  # the old-vintage latch
            sent = []
            orig = proto.send_frame

            def spy(sock, opcode, req_id, body, version=3):
                sent.append(opcode)
                return orig(sock, opcode, req_id, body, version=version)

            token = client.submit(proto.OP_VERIFY, payload)
            import fabric_tpu.serve.client as client_mod

            client_mod.proto.send_frame, restore = spy, orig
            try:
                client.cancel(token)
            finally:
                client_mod.proto.send_frame = restore
            assert proto.OP_CANCEL not in sent
        finally:
            client.close()
            server.stop()


# ---------------------------------------------------------------------------
# short-timeout health probes (satellite regression)
# ---------------------------------------------------------------------------


class TestProbeTimeout:
    def test_probe_does_not_ride_full_request_timeout(self, tmp_path):
        """An endpoint that accepts connections but never answers (the
        gray worst case) must fail a health probe within the probe's
        own short budget — pre-fix it held the probe path for the full
        120s request timeout."""
        import socket as _socket

        addr = str(tmp_path / "black.sock")
        listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        listener.bind(addr)
        listener.listen(4)
        held = []

        def hold_forever():
            while True:
                try:
                    conn, _ = listener.accept()
                except OSError:
                    return
                held.append(conn)  # accept, never answer

        t = threading.Thread(target=hold_forever, daemon=True)
        t.start()
        router = SidecarRouter(endpoints=[addr], gate_policy=FAST_GATE)
        try:
            target = router.endpoints[0]
            target.mark_down("make the probe path run")
            t0 = time.monotonic()
            assert not router._probe_ok(target)
            wall = time.monotonic() - t0
            # dial + hello ride the connect budget, the ping its probe
            # budget: seconds, never the 120s request timeout
            assert wall < 15.0
        finally:
            router.stop()
            listener.close()
            for c in held:
                c.close()
            t.join(timeout=2.0)


# ---------------------------------------------------------------------------
# fleet-scale soaks (slow; the scenarios above are the tier-1 canaries)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_gray_failure_soak_rotating_seeds():
    from fabric_tpu.tools.fabchaos import SCENARIOS, StageClock

    for i in range(3):
        det, _ = SCENARIOS["gray_failure"](31 + i * 101, StageClock(), 1.0)
        assert det["tail_bounded"] and det["gray_evicted"] and det["recovered"]


@pytest.mark.slow
def test_hedge_storm_soak_rotating_seeds():
    from fabric_tpu.tools.fabchaos import SCENARIOS, StageClock

    for i in range(3):
        det, _ = SCENARIOS["hedge_storm"](47 + i * 101, StageClock(), 1.0)
        assert det["hedges_within_budget"] and det["ledger_balanced"]
