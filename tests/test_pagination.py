"""Bookmark pagination for rich queries and range scans (reference
statecouchdb.go:567 GetStateRangeScanIteratorWithPagination, :653
ExecuteQueryWithPagination; chaincode QueryMetadata/QueryResponseMetadata
contract)."""

import json

import pytest

from fabric_tpu.ledger import queries
from fabric_tpu.ledger.rwset import Version
from fabric_tpu.ledger.simulator import SimulationError, TxSimulator
from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB


def _db(n=10):
    db = VersionedDB()
    batch = UpdateBatch()
    for i in range(n):
        batch.put(
            "cc", f"k{i:02d}", json.dumps({"v": i}).encode(), Version(1, i)
        )
    db.apply_updates(batch)
    return db


QUERY = {"selector": {"v": {"$gte": 2}}}


class TestQueryEngine:
    def test_pages_and_final_short_page(self):
        db = _db()
        p1, bm1 = db.execute_query_paginated("cc", QUERY, 3)
        p2, bm2 = db.execute_query_paginated("cc", QUERY, 3, bm1)
        p3, bm3 = db.execute_query_paginated("cc", QUERY, 3, bm2)
        assert [k for k, _ in p1] == ["k02", "k03", "k04"]
        assert [k for k, _ in p2] == ["k05", "k06", "k07"]
        assert [k for k, _ in p3] == ["k08", "k09"]  # short page: caller stops
        tail, _ = db.execute_query_paginated("cc", QUERY, 3, bm3)
        assert tail == []

    def test_pagination_is_stable_across_calls(self):
        # same query + same bookmark -> same page (CouchDB bookmark
        # semantics over a stable snapshot)
        db = _db()
        _, bm = db.execute_query_paginated("cc", QUERY, 2)
        again, _ = db.execute_query_paginated("cc", QUERY, 2, bm)
        repeat, _ = db.execute_query_paginated("cc", QUERY, 2, bm)
        assert again == repeat

    def test_limit_plus_pagination_rejected(self):
        with pytest.raises(queries.QueryError):
            queries.execute_paginated(
                [], {"selector": {}, "limit": 5}, 2
            )

    def test_bad_bookmark_rejected(self):
        with pytest.raises(queries.QueryError):
            queries.execute_paginated([], {"selector": {}}, 2, "not-a-bookmark")

    def test_bad_page_size_rejected(self):
        with pytest.raises(queries.QueryError):
            queries.execute_paginated([], {"selector": {}}, 0)


class TestSimulator:
    def test_range_pagination_bookmark_is_next_key(self):
        sim = TxSimulator(_db(), "tx1")
        rows, bm = sim.get_state_range_with_pagination("cc", "k00", "k08", 3)
        assert [k for k, _ in rows] == ["k00", "k01", "k02"]
        assert bm == "k03"
        rows2, bm2 = sim.get_state_range_with_pagination(
            "cc", "k00", "k08", 3, bm
        )
        assert [k for k, _ in rows2] == ["k03", "k04", "k05"]
        rows3, bm3 = sim.get_state_range_with_pagination(
            "cc", "k00", "k08", 3, bm2
        )
        assert [k for k, _ in rows3] == ["k06", "k07"]
        assert bm3 == ""  # exhausted

    def test_paginated_reads_are_mvcc_recorded(self):
        sim = TxSimulator(_db(), "tx1")
        sim.get_state_range_with_pagination("cc", "k00", "k03", 2)
        rwset = sim.get_tx_simulation_results().rwset
        ns = {n.namespace: n for n in rwset.ns_rw_sets}["cc"]
        read_keys = {r.key for r in ns.reads}
        assert read_keys == {"k00", "k01"}
        # but NO phantom-protecting range record (reference paginated
        # contract)
        assert not ns.range_queries

    def test_writes_after_paginated_query_rejected(self):
        sim = TxSimulator(_db(), "tx1")
        sim.execute_query_with_pagination("cc", QUERY, 2)
        with pytest.raises(SimulationError):
            sim.set_state("cc", "k00", b"nope")

    def test_sqlite_backend_paginates_too(self, tmp_path):
        from fabric_tpu.ledger.persistent import SqliteVersionedDB

        db = SqliteVersionedDB(str(tmp_path / "state.sqlite"))
        batch = UpdateBatch()
        for i in range(6):
            batch.put(
                "cc", f"k{i}", json.dumps({"v": i}).encode(), Version(1, i)
            )
        db.apply_updates(batch)
        p1, bm = db.execute_query_paginated("cc", QUERY, 3)
        p2, _ = db.execute_query_paginated("cc", QUERY, 3, bm)
        assert [k for k, _ in p1] == ["k2", "k3", "k4"]
        assert [k for k, _ in p2] == ["k5"]
