"""History queries, ledger snapshots (export/verify/join), rollback and
rebuild-dbs (reference core/ledger/kvledger/snapshot.go, history/,
reset.go/rollback.go)."""

import json
import os

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx, endorse_proposal
from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.history import get_history_for_key
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
from fabric_tpu.ledger.snapshot import (
    create_from_snapshot,
    generate_snapshot,
    verify_snapshot,
)
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer import SoloChain
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.peer import Channel
from fabric_tpu.policy import from_dsl
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

PROVIDER = SoftwareProvider()
CHANNEL = "snapchannel"


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """A channel with three blocks of real committed txs."""
    tmp = tmp_path_factory.mktemp("snap")
    org1 = generate_org("org1.example.com", "Org1MSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("mycc", from_dsl("OR('Org1MSP.member')"))]
    )
    channel = Channel(CHANNEL, str(tmp), mgr, registry, PROVIDER)
    client = SigningIdentity(org1.users[0], PROVIDER)
    peer = SigningIdentity(org1.peers[0], PROVIDER)

    blocks = []
    chain = SoloChain(
        CHANNEL,
        signer=peer,
        batch_config=BatchConfig(max_message_count=1),
        deliver=blocks.append,
    )

    def put(key, value, delete=False):
        results = serialize_tx_rwset(
            rw.TxRwSet(
                (
                    rw.NsRwSet(
                        "mycc", (), (rw.KVWrite(key, delete, value),)
                    ),
                )
            )
        )
        bundle = create_proposal(client, CHANNEL, "mycc", [b"put", key.encode()])
        env = create_signed_tx(
            bundle, client, [endorse_proposal(bundle, peer, results)]
        )
        chain.order(env)
        return bundle.tx_id

    txids = [put("a", b"1"), put("a", b"2"), put("b", b"x")]
    for b in blocks:
        channel.store_block(b)
    return {
        "dir": tmp,
        "channel": channel,
        "org1": org1,
        "txids": txids,
        "blocks": blocks,
    }


def test_history_for_key_newest_first(world):
    ledger = world["channel"].ledger
    mods = get_history_for_key(ledger, "mycc", "a")
    assert [(m.value, m.is_delete) for m in mods] == [(b"2", False), (b"1", False)]
    assert mods[0].tx_id == world["txids"][1]
    assert mods[1].tx_id == world["txids"][0]
    assert get_history_for_key(ledger, "mycc", "missing") == []


def test_snapshot_export_and_verify(world, tmp_path):
    ledger = world["channel"].ledger
    snap = str(tmp_path / "snap")
    meta = generate_snapshot(ledger, snap)
    assert meta["channel_name"] == CHANNEL
    assert meta["last_block_number"] == 2
    assert verify_snapshot(snap) == meta
    # deterministic: exporting again yields identical signable metadata
    snap2 = str(tmp_path / "snap2")
    assert generate_snapshot(ledger, snap2) == meta
    # tamper detection
    with open(os.path.join(snap, "txids.data"), "ab") as f:
        f.write(b"junk")
    with pytest.raises(ValueError):
        verify_snapshot(snap)


def test_join_from_snapshot(world, tmp_path):
    ledger = world["channel"].ledger
    snap = str(tmp_path / "snap")
    generate_snapshot(ledger, snap)

    joined = create_from_snapshot(snap, str(tmp_path / "newpeer"))
    assert joined.height == ledger.height
    assert joined.get_state("mycc", "a") == b"2"
    assert joined.get_state("mycc", "b") == b"x"
    # duplicate-TxID detection covers pre-snapshot txs
    assert joined.tx_exists(world["txids"][0])
    # the next block continues the chain (hash continuity enforced)
    assert joined.block_store.last_block_hash == ledger.block_store.last_block_hash
    assert joined.block_store.base_height == 3


def test_rollback_and_rebuild(world, tmp_path):
    """Rollback on a copy of the chain; state rewinds to the old block."""
    import shutil

    src = world["dir"]
    dst = tmp_path / "copy"
    shutil.copytree(src, dst)
    ledger = KVLedger(str(dst), CHANNEL)
    assert ledger.height == 3
    assert ledger.get_state("mycc", "b") == b"x"

    ledger.rollback(1)  # keep blocks 0..1
    assert ledger.height == 2
    assert ledger.get_state("mycc", "a") == b"2"
    assert ledger.get_state("mycc", "b") is None
    assert not ledger.tx_exists(world["txids"][2])

    ledger.rebuild_dbs()
    assert ledger.get_state("mycc", "a") == b"2"
