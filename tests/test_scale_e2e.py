"""North-star scale e2e (reference integration/nwo shape): a 1k-tx
2-of-3 endorsement block ordered by a REAL subprocess orderer, delivered
to a REAL subprocess peer, validated there with the peer's default
provider (the TPU provider on accelerator machines), committed, and the
resulting TRANSACTIONS_FILTER checked bit-exact against a local
re-validation with the OpenSSL SoftwareProvider."""

import json
import signal
import subprocess
import time

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from tests.test_cli_network import run_cli, spawn, wait_listening

CHANNEL = "scalechan"
N_TXS = 1000


@pytest.fixture(scope="module")
def scale_network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("scale")
    crypto = tmp / "crypto-config"

    (tmp / "crypto-config.yaml").write_text(
        """
PeerOrgs:
  - Name: Org1
    Domain: org1.example.com
    MSPID: Org1MSP
    Template: {Count: 1}
    Users: {Count: 1}
  - Name: Org2
    Domain: org2.example.com
    MSPID: Org2MSP
    Template: {Count: 1}
    Users: {Count: 1}
  - Name: Org3
    Domain: org3.example.com
    MSPID: Org3MSP
    Template: {Count: 1}
    Users: {Count: 1}
OrdererOrgs:
  - Name: Orderer
    Domain: orderer.example.com
    MSPID: OrdererMSP
"""
    )
    run_cli(
        "fabric_tpu.cli.cryptogen",
        "generate",
        "--config",
        str(tmp / "crypto-config.yaml"),
        "--output",
        str(crypto),
    )
    orgs = {
        i: crypto / "peerOrganizations" / f"org{i}.example.com"
        for i in (1, 2, 3)
    }
    oorg = crypto / "ordererOrganizations" / "orderer.example.com"

    org_profiles = "\n".join(
        f"""        - Name: Org{i}MSP
          MSPID: Org{i}MSP
          MSPDir: {orgs[i]}/msp"""
        for i in (1, 2, 3)
    )
    (tmp / "configtx.yaml").write_text(
        f"""
Profiles:
  ScaleChannel:
    Orderer:
      OrdererType: solo
      BatchTimeout: 10s  # cuts the small warm-up block; the measured
                         # 1k block cuts on MaxMessageCount
      BatchSize:
        MaxMessageCount: {N_TXS}
        PreferredMaxBytes: 16 MB
        AbsoluteMaxBytes: 32 MB
      Organizations:
        - Name: OrdererMSP
          MSPID: OrdererMSP
          MSPDir: {oorg}/msp
    Application:
      Organizations:
{org_profiles}
"""
    )
    gblock = tmp / "scalechan.block"
    run_cli(
        "fabric_tpu.cli.configtxgen",
        "-profile",
        "ScaleChannel",
        "-channelID",
        CHANNEL,
        "-configPath",
        str(tmp / "configtx.yaml"),
        "-outputBlock",
        str(gblock),
    )

    (tmp / "orderer.yaml").write_text(
        f"""
General:
  ListenAddress: 127.0.0.1
  ListenPort: 0
  LocalMSPID: OrdererMSP
  LocalMSPDir: {oorg}/users/Admin@orderer.example.com/msp
  BootstrapFile: {gblock}
  WorkDir: {tmp}/orderer-data
"""
    )
    orderer_proc = spawn(
        "fabric_tpu.cli.orderer", "start", "--config", str(tmp / "orderer.yaml")
    )
    orderer_addr = wait_listening(orderer_proc, "orderer listening on")

    org_msp_dirs = "\n".join(
        f"    Org{i}MSP: {orgs[i]}/msp" for i in (1, 2, 3)
    )
    (tmp / "core.yaml").write_text(
        f"""
peer:
  listenAddress: 127.0.0.1:0
  localMspId: Org1MSP
  mspConfigPath: {orgs[1]}/peers/peer0.org1.example.com/msp
  fileSystemPath: {tmp}/peer0-data
  orgMspDirs:
{org_msp_dirs}
  ordererEndpoint: {orderer_addr}
  genesisBlocks: [{gblock}]
  chaincodes:
    scalecc: "OutOf(2,'Org1MSP.member','Org2MSP.member','Org3MSP.member')"
"""
    )
    peer_proc = spawn(
        "fabric_tpu.cli.peer", "node", "start", "--config", str(tmp / "core.yaml")
    )
    peer_addr = wait_listening(peer_proc, "peer listening on")

    yield {
        "tmp": tmp,
        "orderer_addr": orderer_addr,
        "peer_addr": peer_addr,
        "orgs": orgs,
        "procs": (orderer_proc, peer_proc),
    }
    for proc in (orderer_proc, peer_proc):
        proc.send_signal(signal.SIGTERM)
    for proc in (orderer_proc, peer_proc):
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_thousand_tx_block_through_real_nodes(scale_network):
    from fabric_tpu.comm.server import channel_to
    from fabric_tpu.comm.services import deliver_stream
    from fabric_tpu.crypto.bccsp import SoftwareProvider
    from fabric_tpu.deliver.client import seek_envelope
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
    from fabric_tpu.msp.configbuilder import load_msp, load_signing_identity
    from fabric_tpu.msp.identity import MSPManager
    from fabric_tpu.policy import from_dsl
    from fabric_tpu.protos import ab_pb2, common_pb2, protoutil
    from fabric_tpu.validation.validator import (
        BlockValidator,
        ChaincodeDefinition,
        ChaincodeRegistry,
    )
    from fabric_tpu.validation.txflags import TxValidationCode

    orgs = scale_network["orgs"]
    sw = SoftwareProvider()
    client = load_signing_identity(
        str(orgs[1] / "users" / "User0@org1.example.com" / "msp"), "Org1MSP"
    )
    endorsers = [
        load_signing_identity(
            str(orgs[i] / "peers" / f"peer0.org{i}.example.com" / "msp"),
            f"Org{i}MSP",
        )
        for i in (1, 2)
    ]

    def make_envs(tag, count):
        envs = []
        for i in range(count):
            results = serialize_tx_rwset(
                rw.TxRwSet(
                    (
                        rw.NsRwSet(
                            "scalecc",
                            (),
                            (rw.KVWrite(f"k{tag}-{i}", False, b"v"),),
                        ),
                    )
                )
            )
            bundle = create_proposal(
                client, CHANNEL, "scalecc", [b"put", b"%d" % i]
            )
            responses = [
                endorse_proposal(bundle, e, results) for e in endorsers
            ]
            envs.append(create_signed_tx(bundle, client, responses))
        return envs

    def broadcast(envs):
        conn = channel_to(scale_network["orderer_addr"])
        try:
            stub = conn.stream_stream(
                "/orderer.AtomicBroadcast/Broadcast",
                request_serializer=common_pb2.Envelope.SerializeToString,
                response_deserializer=ab_pb2.BroadcastResponse.FromString,
            )
            acks = list(stub(iter(envs)))
        finally:
            conn.close()
        assert len(acks) == len(envs)
        assert all(a.status == common_pb2.SUCCESS for a in acks)

    def fetch_block(number, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline:
            conn = channel_to(scale_network["peer_addr"])
            try:
                resps = list(
                    deliver_stream(
                        conn,
                        seek_envelope(
                            CHANNEL, number, signer=client, stop=number
                        ),
                        service="protos.Deliver",
                        method="Deliver",
                    )
                )
            finally:
                conn.close()
            got = [r for r in resps if r.WhichOneof("Type") == "block"]
            if got:
                return got[0].block
            time.sleep(0.3)
        return None

    # warm-up block at FULL size: first use makes the peer process load
    # its cached device program for this lane bucket and initialize the
    # accelerator client (~1 min) — node-lifetime cost, not per-block
    # cost, so it stays out of the measured number (a small warm-up would
    # warm the wrong bucket and the 1k block would pay the load anyway)
    warm = make_envs("warm", N_TXS)
    broadcast(warm)
    assert fetch_block(1, 240) is not None, "warm-up block never committed"

    # the measured 1k-tx 2-of-3 block through the REAL nodes
    envs = make_envs("main", N_TXS)
    t_broadcast = time.perf_counter()
    broadcast(envs)
    block = fetch_block(2, 180)
    committed_ms = (time.perf_counter() - t_broadcast) * 1000.0
    assert block is not None, "peer never committed the 1k-tx block"
    assert block.header.number == 2
    assert len(block.data.data) == N_TXS

    flags = bytes(block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER])
    assert len(flags) == N_TXS
    assert set(flags) == {TxValidationCode.VALID}

    # mask parity: re-validate the exact committed block locally with the
    # OpenSSL software provider
    mgr = MSPManager(
        [
            load_msp(str(orgs[i] / "msp"), f"Org{i}MSP", provider=sw)
            for i in (1, 2, 3)
        ]
    )
    registry = ChaincodeRegistry(
        [
            ChaincodeDefinition(
                "scalecc",
                from_dsl(
                    "OutOf(2,'Org1MSP.member','Org2MSP.member',"
                    "'Org3MSP.member')"
                ),
            )
        ]
    )
    check = common_pb2.Block()
    check.CopyFrom(block)
    local = BlockValidator(CHANNEL, mgr, sw, registry)
    local_flags = local.validate(check)
    assert local_flags.tobytes() == flags  # bit-exact device/host parity

    # recorded for the bench narrative (broadcast -> committed, wall)
    print(
        json.dumps(
            {
                "scale_e2e_ms_broadcast_to_committed": round(committed_ms, 1),
                "txs": N_TXS,
            }
        )
    )
