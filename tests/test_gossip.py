"""Gossip layer: payload buffer ordering, anti-entropy, membership
expiry, leader election, privdata coordinator (reference gossip/state,
gossip/discovery, gossip/election, gossip/privdata)."""

import pytest

from fabric_tpu.gossip.coordinator import (
    Coordinator,
    PvtDataRequirement,
    PvtKey,
    TransientStore,
)
from fabric_tpu.gossip.membership import LeaderElection, Membership
from fabric_tpu.gossip.state import CommitFailure, PayloadBuffer, StateProvider
from fabric_tpu.protos import common_pb2, protoutil


def make_block(n: int) -> common_pb2.Block:
    b = protoutil.new_block(n, b"\x00" * 32)
    b.data.data.append(b"tx")
    return protoutil.seal_block(b)


class TestPayloadBuffer:
    def test_ordered_drain(self):
        committed = []
        sp = StateProvider("ch", committed.append, lambda: 0)
        sp.add_payload(make_block(2))
        sp.add_payload(make_block(0))
        assert sp.deliver_payloads() == 1  # only block 0 is in order
        sp.add_payload(make_block(1))
        assert sp.deliver_payloads() == 2  # 1 then 2
        assert [b.header.number for b in committed] == [0, 1, 2]

    def test_stale_and_duplicate_dropped(self):
        sp = StateProvider("ch", lambda b: None, lambda: 5)
        assert not sp.add_payload(make_block(3))  # below height
        assert sp.add_payload(make_block(7))
        assert not sp.add_payload(make_block(7))  # duplicate
        assert sp.buffer.dropped == 2

    def test_gossip_flood_protection(self):
        sp = StateProvider("ch", lambda b: None, lambda: 0, max_block_dist=10)
        assert not sp.add_payload(make_block(50))  # too far ahead
        assert sp.add_payload(make_block(50), from_gossip=False)  # direct ok

    def test_commit_failure_marks_channel(self):
        def boom(block):
            raise RuntimeError("vscc failure")

        sp = StateProvider("ch", boom, lambda: 0)
        sp.add_payload(make_block(0))
        with pytest.raises(CommitFailure):
            sp.deliver_payloads()
        with pytest.raises(CommitFailure):
            sp.deliver_payloads()


class TestAntiEntropy:
    def test_missing_range_and_response(self):
        committed = []
        sp = StateProvider("ch", committed.append, lambda: 0)
        rng = sp.missing_range([4, 2])
        assert rng == range(0, 4)
        blocks = {n: make_block(n) for n in rng}
        # a taller peer serves the request from its ledger
        tall = StateProvider("ch", lambda b: None, lambda: 4)
        served = tall.handle_state_request(0, 4, lambda n: blocks.get(n))
        assert [b.header.number for b in served] == [0, 1, 2, 3]
        assert sp.handle_state_response(served) == 4
        assert sp.missing_range([4]) is None

    def test_request_capped(self):
        sp = StateProvider("ch", lambda b: None, lambda: 0)
        served = sp.handle_state_request(
            0, 1000, lambda n: make_block(n), max_blocks=10
        )
        assert len(served) == 10


class TestMembership:
    def test_alive_dead_transitions(self):
        m = Membership("p0", alive_expiration_ticks=3)
        m.handle_alive({"id": "p1", "endpoint": "h1:7051", "seq": 1})
        assert m.alive_peers() == ["p1"]
        for _ in range(5):
            m.tick()
        assert m.alive_peers() == []
        assert m.dead_peers() == ["p1"]
        # resurrection needs a FRESHER seq
        assert not m.handle_alive({"id": "p1", "seq": 1})
        assert m.handle_alive({"id": "p1", "seq": 2})
        assert m.alive_peers() == ["p1"]

    def test_stale_seq_not_forwarded(self):
        m = Membership("p0")
        assert m.handle_alive({"id": "p1", "seq": 5})
        assert not m.handle_alive({"id": "p1", "seq": 4})

    def test_own_alive_ignored(self):
        m = Membership("p0")
        assert not m.handle_alive({"id": "p0", "seq": 9})


class TestElection:
    def test_smallest_alive_leads(self):
        m = Membership("p1", alive_expiration_ticks=2)
        el = LeaderElection(m)
        changes = []
        el.on_leadership_change = changes.append
        assert el.evaluate()  # alone -> leader
        m.handle_alive({"id": "p0", "seq": 1})
        assert not el.evaluate()  # p0 takes over
        for _ in range(4):
            m.tick()
        assert el.evaluate()  # p0 expired -> leadership regained
        assert changes == [True, False, True]


class TestCoordinator:
    def test_pvtdata_from_transient_then_peers(self):
        store = TransientStore()
        store.persist("tx0", "cc", "collA", b"pvt-A")
        key_a = PvtKey(0, "cc", "collA")
        key_b = PvtKey(0, "cc", "collB")
        fetched = {key_b: b"pvt-B"}
        commits = []

        coord = Coordinator(
            "ch",
            validate=lambda b: "FLAGS",
            commit=lambda b, pvt: commits.append(pvt) or "OK",
            transient=store,
            fetch_from_peers=lambda keys: {
                k: fetched[k] for k in keys if k in fetched
            },
            pvt_requirements=lambda b, f: [
                PvtDataRequirement("tx0", [key_a, key_b])
            ],
        )
        result = coord.store_block(make_block(0))
        assert result == "OK"
        assert commits[0] == {key_a: b"pvt-A", key_b: b"pvt-B"}
        assert not coord.missing
        # transient store purged after commit
        assert store.get("tx0", "cc", "collA") is None

    def test_missing_pvtdata_goes_to_reconciler(self):
        key = PvtKey(0, "cc", "collX")
        coord = Coordinator(
            "ch",
            validate=lambda b: "FLAGS",
            commit=lambda b, pvt: "OK",
            fetch_from_peers=lambda keys: {},
            pvt_requirements=lambda b, f: [PvtDataRequirement("t", [key])],
            pull_retries=2,
        )
        coord.store_block(make_block(0))
        assert key in coord.missing

        # data shows up later: reconciler recovers it
        coord._fetch = lambda keys: {key: b"late"}
        recovered = []
        assert coord.reconcile(lambda k, d: recovered.append((k, d))) == 1
        assert recovered == [(key, b"late")]
        assert not coord.missing
