"""End-to-end over TLS: orderer + peer both serve TLS (hot-reloading
CertReloader creds), the peer's deliver client dials the orderer with
the root CA, and a client endorses/broadcasts over TLS — a block
commits through the full wire path (reference e2e with TLS enabled,
usable-inter-nal/pkg/comm creds + deliveryclient tls.rootcert)."""

import time

import pytest

pytest.importorskip(
    "cryptography", reason="MSP material needs the cryptography package"
)

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.chaincode import success
from fabric_tpu.comm.server import CertReloader, channel_to
from fabric_tpu.comm.services import (
    broadcast_envelope,
    process_proposal,
)
from fabric_tpu.crypto.bccsp import SoftwareProvider
from fabric_tpu.endorser import create_proposal, create_signed_tx
from fabric_tpu.endorser.txbuilder import create_signed_proposal
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.nodes import OrdererNode, PeerNode
from fabric_tpu.policy import from_dsl
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

PROVIDER = SoftwareProvider()
CHANNEL = "tlschannel"


class KV:
    def init(self, stub):
        return success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return success(b"ok")
        return success(b"")


def _creds(tmp_path, pair, name):
    cert = tmp_path / f"{name}.crt"
    key = tmp_path / f"{name}.key"
    cert.write_bytes(pair.cert_pem)
    key.write_bytes(pair.key_pem)
    return CertReloader(str(cert), str(key)).credentials()


@pytest.fixture(scope="module")
def tls_net(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tlsnet")
    org1 = generate_org("org1.example.com", "Org1MSP")
    oorg = generate_org("orderer.example.com", "OrdererMSP")
    mgr = MSPManager([org1.msp(provider=PROVIDER)])
    tls_pair_o = org1.ca.enroll_tls("orderer.tls")
    tls_pair_p = org1.ca.enroll_tls("peer0.tls")
    root_ca = org1.ca.cert_pem

    def registry_factory(channel_id):
        return ChaincodeRegistry(
            [ChaincodeDefinition("kvcc", from_dsl("OR('Org1MSP.member')"))]
        )

    profile = Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config())
            ],
        ),
    )
    gblock = genesis_block(profile, CHANNEL)

    orderer = OrdererNode(
        str(tmp / "orderer"),
        signer=SigningIdentity(oorg.peers[0], PROVIDER),
        tls_credentials=_creds(tmp, tls_pair_o, "orderer"),
    )
    orderer.join_channel(gblock)
    orderer.start()

    peer = PeerNode(
        str(tmp / "peer0"),
        mgr,
        SigningIdentity(org1.peers[0], PROVIDER),
        registry_factory,
        provider=PROVIDER,
        tls_credentials=_creds(tmp, tls_pair_p, "peer"),
        orderer_root_ca=root_ca,
    )
    peer.support.register("kvcc", KV())
    peer.join_channel(gblock)
    peer.start()
    peer.start_deliver_for_channel(CHANNEL, orderer.addr)

    yield {
        "orderer": orderer,
        "peer": peer,
        "root_ca": root_ca,
        "client": SigningIdentity(org1.users[0], PROVIDER),
    }
    peer.stop()
    orderer.stop()


def test_tls_end_to_end(tls_net):
    client = tls_net["client"]
    root_ca = tls_net["root_ca"]
    peer = tls_net["peer"]

    # plaintext dial against the TLS peer must FAIL (no silent fallback)
    import grpc

    bundle = create_proposal(client, CHANNEL, "kvcc", [b"put", b"k", b"v"])
    signed = create_signed_proposal(bundle, client)
    conn = channel_to(peer.addr)  # insecure
    with pytest.raises(grpc.RpcError):
        process_proposal(conn, signed)
    conn.close()

    # TLS endorse + TLS broadcast
    conn = channel_to(peer.addr, root_ca)
    resp = process_proposal(conn, signed)
    conn.close()
    assert resp.response.status == 200, resp.response.message
    env = create_signed_tx(bundle, client, [resp])
    conn = channel_to(tls_net["orderer"].addr, root_ca)
    ack = broadcast_envelope(conn, env)
    conn.close()
    from fabric_tpu.protos import common_pb2

    assert ack.status == common_pb2.SUCCESS, ack.info

    # the peer's deliver loop (TLS dial to the orderer) commits it
    deadline = time.time() + 20
    while time.time() < deadline:
        if peer.channels[CHANNEL].ledger.get_state("kvcc", "k") == b"v":
            break
        time.sleep(0.1)
    assert peer.channels[CHANNEL].ledger.get_state("kvcc", "k") == b"v"
    assert not peer.deliver_errors.get(CHANNEL)
