"""fabobs: process-wide observability registry (metrics SPI + spans +
flight recorder) and its wiring through the validation data plane.

Discipline mirrors tests/test_faults.py: the disabled path is a no-op,
installation is scoped, and — the mask-safety contract — an
observability failure can never raise into (or alter) a verify path.
"""

import json
import threading
import time

import pytest

from fabric_tpu.common import fabobs
from fabric_tpu.common.fabobs import (
    CANONICAL_METRICS,
    CANONICAL_BY_NAME,
    ObsRegistry,
    obs_installed,
)
from fabric_tpu.common.faults import FaultPlan, InjectedFault, plan_installed
from fabric_tpu.common.metrics import (
    DisabledProvider,
    HistogramOpts,
    PrometheusProvider,
    new_histogram_state,
    observe_into,
    summary_from_histogram_state,
)


@pytest.fixture(autouse=True)
def _no_ambient_obs():
    """Every test starts and ends with the registry disabled (an
    env-enabled run must not leak series between tests)."""
    prev = fabobs.active()
    fabobs.disable()
    yield
    fabobs.disable()
    if prev is not None:
        with fabobs._OBS_LOCK:
            fabobs._OBS = prev


# ---------------- disabled path ----------------


def test_disabled_hooks_are_noops():
    assert not fabobs.enabled()
    fabobs.obs_count("fabric_verify_lanes_total", 5, rung="hostec")
    fabobs.obs_gauge("fabric_batcher_pending_lanes", 1)
    fabobs.obs_observe("fabric_verify_seconds", 0.1, rung="hostec")
    fabobs.obs_event("anything")
    assert fabobs.obs_trigger("anything") is None
    assert fabobs.snapshot() == {}
    s = fabobs.span("x", lanes=3)
    with s:
        pass
    # the shared no-op span: no allocation per call
    assert fabobs.span("y") is fabobs.span("z")


def test_disabled_span_is_reentrant():
    s = fabobs.span("x")
    with s:
        with s:
            pass


# ---------------- installation ----------------


def test_obs_installed_scopes_and_restores():
    assert fabobs.active() is None
    with obs_installed() as reg:
        assert fabobs.active() is reg
        assert fabobs.enabled()
        inner = ObsRegistry()
        with obs_installed(inner):
            assert fabobs.active() is inner
        assert fabobs.active() is reg
    assert fabobs.active() is None


def test_ensure_enabled_first_wins():
    with obs_installed() as reg:
        again = fabobs.ensure_enabled(provider=PrometheusProvider())
        assert again is reg  # existing registry kept, new provider ignored


def test_env_install_semantics(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_OBS", "0")
    fabobs._install_from_env()
    assert not fabobs.enabled()
    monkeypatch.setenv("FABRIC_TPU_OBS", "1")
    monkeypatch.setenv("FABRIC_TPU_OBS_RING", "notanint")  # degrade, no raise
    fabobs._install_from_env()
    assert fabobs.enabled()
    fabobs.disable()


# ---------------- canonical table + metric sinks ----------------


def test_every_canonical_family_registers_eagerly():
    with obs_installed() as reg:
        text = reg.render()
        for spec in CANONICAL_METRICS:
            assert f"# TYPE {spec.name} {spec.kind}" in text
        # table introspection (README generation surface)
        rows = fabobs.metric_table()
        assert {r["name"] for r in rows} == set(CANONICAL_BY_NAME)


def test_counter_gauge_histogram_record():
    with obs_installed() as reg:
        fabobs.obs_count("fabric_verify_lanes_total", 64, rung="hostec_np")
        fabobs.obs_count("fabric_verify_lanes_total", 36, rung="hostec_np")
        fabobs.obs_gauge("fabric_batcher_pending_lanes", 17)
        fabobs.obs_observe("fabric_verify_seconds", 0.03, rung="hostec_np")
        text = reg.render()
        assert 'fabric_verify_lanes_total{rung="hostec_np"} 100' in text
        assert "fabric_batcher_pending_lanes 17" in text
        assert 'fabric_verify_seconds_count{rung="hostec_np"} 1' in text
        snap = reg.snapshot()
        assert snap["fabric_verify_lanes_total"]["series"]["rung=hostec_np"] == 100
        hist = snap["fabric_verify_seconds"]["series"]["rung=hostec_np"]
        assert hist["n"] == 1


def test_unknown_family_and_bad_labels_swallowed():
    with obs_installed() as reg:
        fabobs.obs_count("not_in_the_table")
        fabobs.obs_count("fabric_verify_lanes_total", 1, wrong_label="x")
        assert reg.dropped >= 1  # bad labels accounted
        # neither call raised, and the good series still works
        fabobs.obs_count("fabric_verify_lanes_total", 1, rung="p256")
        assert 'rung="p256"} 1' in reg.render()


def test_obs_failure_cannot_raise_into_caller():
    class ExplodingProvider(PrometheusProvider):
        def new_counter(self, opts):
            raise RuntimeError("boom")

        def new_gauge(self, opts):
            raise RuntimeError("boom")

        def new_histogram(self, opts):
            raise RuntimeError("boom")

    with obs_installed(ObsRegistry(provider=ExplodingProvider())) as reg:
        # construction swallowed every family; sinks still no-op cleanly
        fabobs.obs_count("fabric_verify_lanes_total", 1, rung="hostec")
        fabobs.obs_gauge("fabric_batcher_pending_lanes", 1)
        with fabobs.span("still.works"):
            pass
        assert reg.dropped >= len(CANONICAL_METRICS)


def test_counter_threads_sum_exactly():
    with obs_installed() as reg:
        def hammer():
            for _ in range(500):
                fabobs.obs_count("fabric_retry_attempts_total")

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert "fabric_retry_attempts_total 4000" in reg.render()


# ---------------- spans + flight recorder ----------------


def test_span_nesting_and_trace_dump():
    with obs_installed() as reg:
        with fabobs.span("outer", kind="test") as outer:
            with fabobs.span("inner") as inner:
                time.sleep(0.002)
            assert inner.parent_id == outer.span_id
        events = reg.trace_events()
        names = [e["name"] for e in events]
        assert names == ["inner", "outer"]  # completion order
        inner_ev = events[0]
        assert inner_ev["ph"] == "X"
        assert inner_ev["dur"] >= 1000  # us
        payload = json.loads(reg.dump())
        assert payload["traceEvents"][1]["args"]["kind"] == "test"
        assert payload["displayTimeUnit"] == "ms"


def test_span_exception_annotated_and_propagated():
    with obs_installed() as reg:
        with pytest.raises(ValueError):
            with fabobs.span("failing"):
                raise ValueError("real error passes through")
        (ev,) = reg.trace_events()
        assert ev["args"]["error"] == "ValueError"
        assert fabobs.current_span() is None  # stack popped


def test_cross_thread_parent_propagation():
    with obs_installed() as reg:
        captured = {}

        def worker(parent):
            with fabobs.span("child", parent=parent) as c:
                captured["parent_id"] = c.parent_id

        with fabobs.span("root") as root:
            t = threading.Thread(target=worker, args=(root,))
            t.start()
            t.join()
        assert captured["parent_id"] == root.span_id


def test_flight_ring_is_bounded():
    with obs_installed(ObsRegistry(ring=32)) as reg:
        for i in range(100):
            fabobs.obs_event("tick", i=i)
        events = reg.trace_events()
        assert len(events) == 32
        assert events[-1]["args"]["i"] == 99  # newest win


def test_trigger_dumps_bounded_files(tmp_path):
    reg = ObsRegistry(dump_dir=str(tmp_path), max_dumps=2)
    with obs_installed(reg):
        fabobs.obs_event("before the fall")
        p1 = fabobs.obs_trigger("batcher.fail_closed", requests=3)
        p2 = fabobs.obs_trigger("serve.client_degraded")
        p3 = fabobs.obs_trigger("one too many")
        assert p1 and p2 and p3 is None  # capped
        assert reg.dumped_paths() == [p1, p2]
        payload = json.loads(open(p1).read())
        names = [e["name"] for e in payload["traceEvents"]]
        assert "before the fall" in names
        assert "trigger:batcher.fail_closed" in names


def test_trigger_without_dump_dir_records_event_only():
    with obs_installed() as reg:
        assert fabobs.obs_trigger("no.dir") is None
        assert reg.trace_events()[-1]["name"] == "trigger:no.dir"


# ---------------- histogram-state summary (metrics helper) ----------------


def test_summary_from_histogram_state():
    buckets = (0.001, 0.01, 0.1, 1.0)
    state = new_histogram_state(buckets)
    assert summary_from_histogram_state(state, buckets) == {"n": 0}
    for v in (0.0005, 0.005, 0.005, 0.05, 5.0):
        observe_into(state, buckets, v)
    out = summary_from_histogram_state(state, buckets)
    assert out["n"] == 5
    assert out["p50_ms"] == 10.0  # 0.01 bucket upper bound
    assert out["mean_ms"] == pytest.approx(1012.1, abs=0.1)
    # the rank lands in the +Inf bucket: report a lower bound on THAT
    # bucket's mean — never below the top finite bound, never the
    # global mean (which would hide the very tail +Inf recorded)
    assert out["p99_ms"] >= 1000.0
    assert out["p99_ms"] == pytest.approx(1060.5, abs=0.1)
    # a tail-heavy series must not report p99 under the ladder top
    tail = new_histogram_state(buckets)
    for _ in range(99):
        observe_into(tail, buckets, 0.001)
    observe_into(tail, buckets, 100.0)
    assert summary_from_histogram_state(tail, buckets)["p99_ms"] >= 1000.0


# ---------------- data-plane wiring ----------------


class _StubProvider:
    """Provider whose batches verify (lane % 2 == 0)."""

    def batch_verify(self, keys, sigs, digests):
        return [k % 2 == 0 for k in keys]


def test_batcher_emits_canonical_series():
    from fabric_tpu.parallel.batcher import VerifyBatcher

    with obs_installed() as reg:
        b = VerifyBatcher(_StubProvider(), max_pending_lanes=64)
        try:
            resolver = b.submit(list(range(8)), [b""] * 8, [b""] * 8)
            assert resolver() == [True, False] * 4
        finally:
            b.stop()
        text = reg.render()
        assert 'fabric_batcher_launches_total{mode="coalesce"} 1' in text
        assert "fabric_batcher_batch_lanes_count 1" in text
        assert "fabric_batcher_submit_wait_seconds_count 1" in text


def test_batcher_busy_reject_counted():
    from fabric_tpu.parallel.batcher import VerifyBatcher

    class _Slow:
        def batch_verify(self, keys, sigs, digests):
            time.sleep(0.2)
            return [True] * len(keys)

    with obs_installed() as reg:
        b = VerifyBatcher(_Slow(), max_pending_lanes=4, linger_s=0.05)
        try:
            b.submit([1, 2, 3], [b""] * 3, [b""] * 3)
            assert b.try_submit([1, 2, 3], [b""] * 3, [b""] * 3) is None
        finally:
            b.stop()
        assert "fabric_batcher_busy_rejects_total 1" in reg.render()


def test_batcher_fail_closed_counted_and_triggers_dump(tmp_path):
    from fabric_tpu.parallel.batcher import VerifyBatcher

    hang = threading.Event()

    class _Hung:
        def batch_verify_async(self, keys, sigs, digests):
            def resolve():
                hang.wait(5.0)
                return [True] * len(keys)

            return resolve

    reg = ObsRegistry(dump_dir=str(tmp_path), max_dumps=4)
    with obs_installed(reg):
        b = VerifyBatcher(_Hung(), join_timeout_s=0.2)
        r = b.submit([1], [b""], [b""])
        time.sleep(0.05)  # let the dispatcher pick it up
        b.stop()
        hang.set()
        assert r() == [False]  # settled fail-closed
        assert "fabric_batcher_fail_closed_total 1" in reg.render()
        assert len(reg.dumped_paths()) == 1  # trigger dumped the ring


def test_bccsp_rung_series():
    from fabric_tpu.crypto.bccsp import SoftwareProvider, ec_backend_name

    from fabric_tpu.common import der, p256
    from fabric_tpu.crypto import hostec
    import hashlib

    d = 0xA11CE
    pub_pt = hostec.scalar_base_mult(d)
    from fabric_tpu.crypto.bccsp import ECDSAPublicKey

    digest = hashlib.sha256(b"obs lane").digest()
    r, s = hostec.sign_digest(d, digest)
    sig = der.marshal_signature(r, s)
    key = ECDSAPublicKey(*pub_pt)
    with obs_installed() as reg:
        mask = SoftwareProvider().batch_verify([key] * 4, [sig] * 4, [digest] * 4)
        assert mask == [True] * 4
        rung = ec_backend_name()
        assert f'fabric_verify_lanes_total{{rung="{rung}"}} 4' in reg.render()


def test_obs_cannot_alter_mask():
    """The mask-safety contract, empirically: a registry whose every
    series write explodes must not change one verdict bit of a batch
    routed through the instrumented provider path."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    provider = SoftwareProvider()
    keys = [None] * 3
    sigs = [b"\x00bad"] * 3
    digests = [b"\x00" * 32] * 3
    baseline = provider.batch_verify(keys, sigs, digests)

    reg = ObsRegistry()

    def explode(*a, **k):
        raise RuntimeError("series write exploded")

    for inst in reg._instruments.values():
        for attr in ("add", "observe", "set", "with_labels"):
            if hasattr(inst, attr):
                setattr(inst, attr, explode)
    with obs_installed(reg):
        mask = provider.batch_verify(keys, sigs, digests)
    assert mask == baseline == [False, False, False]
    assert reg.dropped > 0


def test_pipeline_stage_stats_and_series():
    from fabric_tpu.peer.pipeline import CommitPipeline
    from fabric_tpu.protos import common_pb2

    class _Chan:
        channel_id = "obs-ch"

        def prepare_block(self, block):
            return "prep"

        def store_block(self, block, prepared=None):
            return "flags"

    with obs_installed() as reg:
        p = CommitPipeline(_Chan())
        try:
            for n in range(3):
                blk = common_pb2.Block()
                blk.header.number = n
                p.submit(blk)
            assert p.drain(5.0)
        finally:
            p.stop()
        stats = p.stage_stats()
        assert stats["prepare"]["n"] == 3
        assert stats["commit"]["n"] == 3
        assert stats["commit"]["p50_ms"] >= 0
        text = reg.render()
        assert 'fabric_pipeline_stage_seconds_count{stage="prepare"} 3' in text
        assert 'fabric_pipeline_stage_seconds_count{stage="commit"} 3' in text


def test_fault_fires_counted():
    from fabric_tpu.common.faults import fault_point

    with obs_installed() as reg:
        with plan_installed(FaultPlan.parse("obs.site=raise:1.0:max=2")):
            for _ in range(3):
                try:
                    fault_point("obs.site")
                except InjectedFault:
                    pass
        assert 'fabric_fault_fired_total{site="obs.site"} 2' in reg.render()


def test_retry_attempts_counted():
    from fabric_tpu.common.retry import RetryPolicy, call_with_retry

    calls = {"n": 0}

    def flaky(attempt):
        calls["n"] += 1
        if attempt < 2:
            raise ConnectionError("flap")
        return "ok"

    with obs_installed() as reg:
        out = call_with_retry(
            flaky,
            policy=RetryPolicy(base_s=0.001, max_attempts=5),
            sleeper=lambda s: None,
        )
        assert out == "ok" and calls["n"] == 3
        text = reg.render()
        assert "fabric_retry_attempts_total 2" in text
        assert "fabric_retry_backoff_seconds_count 2" in text


def test_serve_stats_emits_spi_series():
    from fabric_tpu.serve.server import ServeStats

    with obs_installed() as reg:
        stats = ServeStats()
        stats.record(lanes=128, bucket=128, seconds=0.004)
        stats.record(lanes=64, bucket=128, seconds=0.002)
        stats.reject()
        stats.error()
        stats.stopping_reply()
        # the exact local summary API is unchanged...
        summary = stats.summary()
        assert summary["requests"] == 2 and summary["rejects"] == 1
        assert summary["request_latency"]["n"] == 2
        # ...and the same calls drove the SPI series
        text = reg.render()
        assert 'fabric_serve_requests_total{status="ok"} 2' in text
        assert 'fabric_serve_requests_total{status="busy"} 1' in text
        assert 'fabric_serve_requests_total{status="error"} 1' in text
        assert 'fabric_serve_requests_total{status="stopping"} 1' in text
        assert "fabric_serve_lanes_total 192" in text
        assert 'fabric_serve_bucket_requests_total{bucket="128"} 2' in text


def test_sidecar_ops_mount_metrics_and_healthz(tmp_path):
    """The acceptance-criteria path: a sidecar with obs enabled answers
    /metrics with the canonical families and /healthz flips 503 with the
    named checker when the batcher dies."""
    import urllib.error
    import urllib.request

    from fabric_tpu.serve.client import SidecarProvider
    from fabric_tpu.serve.server import SidecarServer

    with obs_installed():
        server = SidecarServer(
            str(tmp_path / "obs.sock"), engine="host",
            ops_address="127.0.0.1:0",
        )
        try:
            server.warm()
            addr = server.start()
            ops = server.ops_address
            assert server.ops is not None

            import hashlib

            from fabric_tpu.common import der
            from fabric_tpu.crypto import hostec
            from fabric_tpu.crypto.bccsp import ECDSAPublicKey

            d = 0xB0B
            pub = ECDSAPublicKey(*hostec.scalar_base_mult(d))
            digest = hashlib.sha256(b"ops lane").digest()
            r, s = hostec.sign_digest(d, digest)
            sig = der.marshal_signature(r, s)
            provider = SidecarProvider(address=addr)
            mask = provider.batch_verify([pub] * 8, [sig] * 8, [digest] * 8)
            assert mask == [True] * 8

            with urllib.request.urlopen(f"http://{ops}/metrics") as resp:
                text = resp.read().decode()
            for spec in CANONICAL_METRICS:
                assert f"# TYPE {spec.name}" in text
            assert 'fabric_serve_requests_total{status="ok"} 1' in text
            with urllib.request.urlopen(f"http://{ops}/healthz") as resp:
                assert json.load(resp)["status"] == "OK"
            # the flight recorder is served on demand
            with urllib.request.urlopen(f"http://{ops}/trace") as resp:
                trace = json.load(resp)
            assert any(
                e["name"] == "serve.verify" for e in trace["traceEvents"]
            )

            server.batcher.stop()
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"http://{ops}/healthz")
            payload = json.load(exc.value)
            failed = {c["component"] for c in payload["failed_checks"]}
            assert "batcher" in failed
        finally:
            server.stop()
