"""CouchDB REST state adapter vs an in-process fake CouchDB (the image
has no external service): doc shape round-trip (JSON fields vs binary
attachment), bulk commit with revision-cache preload + conflict retry,
range scans, /_find selector pass-through with CouchDB-opaque bookmarks
(reference statecouchdb.go)."""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from fabric_tpu.ledger import rwset as rw
from fabric_tpu.ledger.statecouch import (
    CouchClient,
    CouchError,
    CouchStateAdapter,
    couch_db_name,
)
from fabric_tpu.ledger.statedb import UpdateBatch


class FakeCouch(BaseHTTPRequestHandler):
    """Enough of CouchDB's dialect for the adapter: per-db doc stores
    with MVCC _rev checking, _bulk_docs, _all_docs, _find."""

    dbs: dict = {}
    revs: dict = {}
    find_calls: list = []
    bulk_get_counter: list = []

    def log_message(self, *a):  # quiet
        pass

    @staticmethod
    def _maybe_stub(doc, inline):
        """Real CouchDB returns attachment STUBS unless asked to
        inline (and /_find can never inline) — the adapter must cope."""
        if inline or not doc.get("_attachments"):
            return doc
        out = dict(doc)
        out["_attachments"] = {
            name: {k: v for k, v in att.items() if k != "data"}
            | {"stub": True, "length": 1}
            for name, att in doc["_attachments"].items()
        }
        return out

    def _json(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n)) if n else {}

    def do_PUT(self):
        db = self.path.strip("/")
        cls = type(self)
        if db in cls.dbs:
            self._json(412, {"error": "file_exists"})
        else:
            cls.dbs[db] = {}
            cls.revs[db] = {}
            self._json(201, {"ok": True})

    def do_GET(self):
        cls = type(self)
        parsed = urlparse(self.path)
        parts = parsed.path.strip("/").split("/")
        if len(parts) == 2 and parts[1] == "_all_docs":
            qs = parse_qs(parsed.query)
            docs = cls.dbs.get(parts[0], {})
            keys = sorted(docs)
            start = json.loads(qs["startkey"][0]) if "startkey" in qs else None
            end = json.loads(qs["endkey"][0]) if "endkey" in qs else None
            rows = []
            for k in keys:
                if start is not None and k < start:
                    continue
                if end is not None and k >= end:
                    continue
                row = {
                    "id": k,
                    "value": {"rev": cls.revs[parts[0]][k]},
                }
                if qs.get("include_docs") == ["true"]:
                    row["doc"] = self._maybe_stub(
                        docs[k], qs.get("attachments") == ["true"]
                    )
                rows.append(row)
            if "limit" in qs:
                rows = rows[: int(qs["limit"][0])]
            self._json(200, {"rows": rows})
            return
        if len(parts) == 2:
            db, key = parts[0], unquote(parts[1])
            doc = cls.dbs.get(db, {}).get(key)
            if doc is None:
                self._json(404, {"error": "not_found"})
            else:
                self._json(200, doc)
            return
        self._json(404, {"error": "not_found"})

    def do_POST(self):
        cls = type(self)
        parts = self.path.strip("/").split("/")
        db = parts[0]
        body = self._body()
        if parts[1] == "_bulk_docs":
            cls.bulk_get_counter.append(len(body.get("docs", [])))
            out = []
            for doc in body["docs"]:
                key = doc["_id"]
                current_rev = cls.revs[db].get(key)
                given = doc.get("_rev")
                if current_rev is not None and given != current_rev:
                    out.append({"id": key, "error": "conflict"})
                    continue
                n = int((current_rev or "0-x").split("-")[0]) + 1
                rev = f"{n}-{'%08x' % abs(hash(key)) }"[:14]
                if doc.get("_deleted"):
                    cls.dbs[db].pop(key, None)
                    cls.revs[db].pop(key, None)
                    out.append({"id": key, "ok": True, "rev": rev})
                    continue
                stored = {
                    k: v for k, v in doc.items() if k not in ("_rev",)
                }
                stored["_rev"] = rev
                cls.dbs[db][key] = stored
                cls.revs[db][key] = rev
                out.append({"id": key, "ok": True, "rev": rev})
            self._json(201, out)
            return
        if parts[1] == "_all_docs":
            rows = []
            for k in body.get("keys", []):
                rev = cls.revs.get(db, {}).get(k)
                if rev is None:
                    rows.append({"key": k, "error": "not_found"})
                else:
                    rows.append({"id": k, "value": {"rev": rev}})
            self._json(200, {"rows": rows})
            return
        if parts[1] == "_find":
            cls.find_calls.append(body)
            selector = body.get("selector", {})
            docs = []
            for k in sorted(cls.dbs.get(db, {})):
                doc = cls.dbs[db][k]
                ok = True
                for field, cond in selector.items():
                    val = doc.get(field)
                    if isinstance(cond, dict):
                        for op, ref in cond.items():
                            if op == "$gt" and not (
                                val is not None and val > ref
                            ):
                                ok = False
                            if op == "$lt" and not (
                                val is not None and val < ref
                            ):
                                ok = False
                    elif val != cond:
                        ok = False
                if ok:
                    docs.append(doc)
            docs = [self._maybe_stub(d, False) for d in docs]
            offset = 0
            if body.get("bookmark"):
                offset = int(
                    base64.b64decode(body["bookmark"]).decode()
                )
            limit = body.get("limit", 25)  # CouchDB's silent default
            page = docs[offset : offset + limit]
            bookmark = base64.b64encode(
                str(offset + len(page)).encode()
            ).decode()
            self._json(200, {"docs": page, "bookmark": bookmark})
            return
        self._json(404, {"error": "not_found"})


@pytest.fixture
def couch():
    FakeCouch.dbs = {}
    FakeCouch.revs = {}
    FakeCouch.find_calls = []
    FakeCouch.bulk_get_counter = []
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeCouch)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield CouchClient(f"http://127.0.0.1:{server.server_port}")
    server.shutdown()


def _commit(adapter, block, entries):
    batch = UpdateBatch()
    for t, (key, value) in enumerate(entries):
        if value is None:
            batch.delete("cc", key, rw.Version(block, t))
        else:
            batch.put("cc", key, value, rw.Version(block, t))
    adapter.apply_updates(batch)


def test_doc_shape_roundtrip_and_versions(couch):
    a = CouchStateAdapter(couch, "mychannel")
    _commit(a, 1, [
        ("json1", json.dumps({"owner": "alice", "qty": 3}).encode()),
        ("bin1", b"\x00\x01binary"),
    ])
    vv = a.get_state("cc", "json1")
    assert json.loads(vv.value) == {"owner": "alice", "qty": 3}
    assert vv.version == rw.Version(1, 0)
    # JSON docs store their fields INLINE (reference doc shape): couch
    # tooling sees queryable fields, not a blob
    raw = FakeCouch.dbs[couch_db_name("mychannel", "cc")]["json1"]
    assert raw["owner"] == "alice" and raw["~version"] == "1:0"
    # binary rides the valueBytes attachment
    vv = a.get_state("cc", "bin1")
    assert vv.value == b"\x00\x01binary" and vv.version == rw.Version(1, 1)
    assert a.get_state("cc", "ghost") is None
    assert a.get_version("cc", "bin1") == rw.Version(1, 1)


def test_bulk_update_with_revision_preload_and_delete(couch):
    a = CouchStateAdapter(couch, "ch")
    _commit(a, 1, [(f"k{i}", b"v1") for i in range(5)])
    # fresh adapter (restart): revisions must come from ONE bulk preload
    b = CouchStateAdapter(couch, "ch")
    _commit(b, 2, [(f"k{i}", b"v2") for i in range(5)] + [("k0", None)])
    assert b.get_state("cc", "k0") is None  # delete won
    assert b.get_state("cc", "k3").value == b"v2"
    assert b.get_state("cc", "k3").version == rw.Version(2, 3)


def test_conflict_refreshes_and_retries(couch):
    a = CouchStateAdapter(couch, "ch")
    b = CouchStateAdapter(couch, "ch")
    _commit(a, 1, [("k", b"from-a")])
    # b's cache is stale (never saw a's rev): its commit conflicts once,
    # refreshes the rev, retries, and lands
    _commit(b, 2, [("k", b"from-b")])
    assert a.get_state("cc", "k").value == b"from-b"


def test_range_scan_excludes_end(couch):
    a = CouchStateAdapter(couch, "ch")
    _commit(a, 1, [(f"k{i}", b"v") for i in range(6)])
    rows = list(a.get_state_range("cc", "k1", "k4"))
    assert [k for k, _vv in rows] == ["k1", "k2", "k3"]


def test_find_passthrough_with_opaque_bookmark(couch):
    a = CouchStateAdapter(couch, "ch")
    _commit(a, 1, [
        (f"asset{i}", json.dumps({"owner": "alice", "qty": i}).encode())
        for i in range(7)
    ] + [("other", json.dumps({"owner": "bob"}).encode())])
    sel = {"owner": "alice", "qty": {"$gt": 1}}
    page1, bm1 = a.execute_query("cc", sel, page_size=3)
    assert len(page1) == 3 and bm1
    page2, bm2 = a.execute_query("cc", sel, page_size=3, bookmark=bm1)
    assert len(page2) == 2  # qty in 2..6 -> 5 total
    assert {k for k, _v in page1} | {k for k, _v in page2} == {
        "asset2", "asset3", "asset4", "asset5", "asset6"
    }
    # the selector reached /_find VERBATIM (pass-through contract)
    assert FakeCouch.find_calls[0]["selector"] == sel
    # restarted iterator: the bookmark is CouchDB's, so a FRESH adapter
    # resumes exactly where the old one stopped
    fresh = CouchStateAdapter(couch, "ch")
    page2b, _ = fresh.execute_query("cc", sel, page_size=3, bookmark=bm1)
    assert [k for k, _v in page2b] == [k for k, _v in page2]


def test_db_name_mangling():
    assert couch_db_name("MyChannel", "MyCC") == "mychannel_mycc"
    assert couch_db_name("ch", "cc.v2") == "ch_cc$v2"


def test_binary_values_survive_scans_and_queries(couch):
    """Real CouchDB returns attachment STUBS from scans (/_find always,
    _all_docs unless attachments=true): binary values must still
    round-trip, via inline attachments or the point re-fetch."""
    a = CouchStateAdapter(couch, "ch")
    _commit(a, 1, [
        ("binkey", b"\x00\x01raw"),
        ("j", json.dumps({"owner": "alice"}).encode()),
    ])
    rows = dict(a.get_state_range("cc", "", ""))
    assert rows["binkey"].value == b"\x00\x01raw"
    # selector matching the binary doc (no JSON fields): match-all on a
    # field it lacks won't hit it, so query by _id via owner-less doc —
    # use an empty selector page and look for the binary key
    page, _bm = a.execute_query("cc", {}, page_size=10)
    assert (("binkey", b"\x00\x01raw")) in page


def test_kvledger_mirror_commit_and_outage(couch, tmp_path):
    """KVLedger with a state_mirror: each committed block's public
    updates land in CouchDB; a mirror outage never blocks the commit
    path (best-effort, logged)."""
    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.ledger.statecouch import CouchStateAdapter
    from fabric_tpu.protos import common_pb2, protoutil

    mirror = CouchStateAdapter(couch, "mych")
    ledger = KVLedger(
        str(tmp_path), "mych", persistent=False, state_mirror=mirror
    )
    genesis = protoutil.new_block(0, b"")
    protoutil.seal_block(genesis)
    ledger.commit(genesis)

    block = protoutil.new_block(1, protoutil.block_header_hash(genesis.header))
    protoutil.seal_block(block)
    rwsets = [
        rw.TxRwSet(
            (rw.NsRwSet("cc", (), (rw.KVWrite("mk", False, b"mv"),)),)
        )
    ]
    block.data.data.append(b"\x00")  # placeholder envelope for 1 tx
    from fabric_tpu.validation.txflags import ValidationFlags

    flags = ValidationFlags(1)
    flags.set_flag(0, 0)  # VALID
    protoutil.init_block_metadata(block)
    block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()
    ledger.commit(block, rwsets=rwsets)
    assert mirror.get_state("cc", "mk").value == b"mv"

    # outage: break the client; the NEXT commit still succeeds
    mirror.client.base = "http://127.0.0.1:1"
    block2 = protoutil.new_block(2, protoutil.block_header_hash(block.header))
    block2.data.data.append(b"\x00")
    protoutil.seal_block(block2)
    protoutil.init_block_metadata(block2)
    block2.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = flags.tobytes()
    ledger.commit(
        block2,
        rwsets=[
            rw.TxRwSet(
                (rw.NsRwSet("cc", (), (rw.KVWrite("k2", False, b"v"),)),)
            )
        ],
    )
    assert ledger.get_state("cc", "k2") == b"v"  # commit unaffected
