"""Multi-peer shared-sidecar fleet soak (serve/fleetload.py): N real
peer PROCESSES multiplex one warm sidecar with zipf channel skew, per
the PR 8 tier-1 budget discipline — the minute-scale soak is
slow-marked with a cheap tier-1 canary left behind."""

import json
import os
import subprocess
import sys

import pytest

from fabric_tpu.serve.fleetload import build_lanes, run as fleet_run
from fabric_tpu.serve.server import SidecarServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def sidecar(tmp_path):
    srv = SidecarServer(
        str(tmp_path / "fleet.sock"), engine="host", warm_ladder="off",
        buckets=(64, 256),
    )
    srv.warm()
    srv.start()
    yield srv
    srv.stop()


def _spawn_peer(addr, channel, qos, requests, lanes, seed):
    return subprocess.Popen(
        [
            sys.executable, "-m", "fabric_tpu.serve.fleetload",
            "--address", addr, "--channel", channel, "--qos", qos,
            "--requests", str(requests), "--lanes", str(lanes),
            "--seed", str(seed),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _collect(proc, label):
    stdout, stderr = proc.communicate(timeout=180)
    assert proc.returncode == 0, (
        f"peer {label} rc={proc.returncode}: {stderr.decode()[-400:]}"
    )
    return json.loads(stdout.decode().strip().splitlines()[-1])


def test_build_lanes_ground_truth():
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    keys, sigs, digests, expected = build_lanes(24, seed=3)
    assert any(expected) and not all(expected)
    assert list(
        SoftwareProvider().batch_verify(keys, sigs, digests)
    ) == expected


def test_fleet_canary_one_real_peer_process(sidecar):
    """Tier-1 canary for the slow soak: ONE real fleetload subprocess
    drives the sidecar over the socket — masks exact, class accounted,
    nothing degraded."""
    summary = _collect(
        _spawn_peer(sidecar.address, "paychan", "high", 3, 64, 1),
        "canary",
    )
    assert summary["ok"] == 3 and summary["mask_mismatches"] == 0
    assert not summary["degraded"]
    per_class = sidecar.stats.summary()["per_class"]
    assert per_class["high"]["served"] == 3
    assert per_class["high"]["lanes"] == 3 * 64


def test_fleet_inprocess_run_helper(sidecar):
    """The in-process half of the fleetload contract (what bench and
    the canary lean on) stays green without a subprocess."""
    summary = fleet_run(
        address=sidecar.address, channel="spam1", qos="bulk",
        n_requests=2, lanes=32, seed=9,
    )
    assert summary["ok"] == 2 and summary["mask_mismatches"] == 0
    assert summary["cls"] == "bulk"
    assert summary["lanes_per_s"] > 0


@pytest.mark.slow
def test_fleet_soak_four_peer_processes_zipf(sidecar):
    """The ROADMAP fleet-scale leg: >= 4 peer processes share one
    sidecar under a 10:1 zipf spam:paying skew.  Every peer's masks
    bit-exact, no degrade, aggregate throughput positive, per-class
    serving visible with the paying channel fully served."""
    specs = [
        ("paychan", "high", 4, 256, 1),
        ("spam1", "bulk", 14, 96, 2),
        ("spam2", "bulk", 14, 96, 3),
        ("spam3", "bulk", 12, 96, 4),
    ]
    procs = [
        _spawn_peer(sidecar.address, chan, qos, reqs, lanes, seed)
        for chan, qos, reqs, lanes, seed in specs
    ]
    peers = [
        _collect(p, spec[0]) for p, spec in zip(procs, specs)
    ]
    assert sum(p["mask_mismatches"] for p in peers) == 0
    assert not any(p["degraded"] for p in peers)
    paying = peers[0]
    assert paying["ok"] == paying["requests"]  # fully served
    total_lanes = sum(p["requests"] * p["lanes_per_request"] for p in peers)
    assert total_lanes == sum(
        row["lanes"]
        for row in sidecar.stats.summary()["per_class"].values()
    )
    per_class = sidecar.stats.summary()["per_class"]
    assert per_class["high"]["served"] == 4
    assert per_class["bulk"]["served"] == 40
    assert per_class["high"]["latency"]["p99_ms"] is not None
