"""TPUProvider bytes-path (device-side unpack + key gather) differential
vs the software oracle, including the distinct-key-bucket fallback."""

import hashlib

import pytest

from fabric_tpu.crypto import p256
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider, VerifyError
from fabric_tpu.crypto.der import marshal_signature
from fabric_tpu.crypto.tpu_provider import TPUProvider

SW = SoftwareProvider()


def _cases(n, num_keys):
    keys = []
    for k in range(num_keys):
        priv = (k * 0x9E3779B97F4A7C15 + 77) % (p256.N - 1) + 1
        pub = p256.scalar_mult(priv, p256.GENERATOR)
        keys.append((priv, ECDSAPublicKey(pub[0], pub[1])))
    out = []
    for i in range(n):
        priv, key = keys[i % num_keys]
        digest = hashlib.sha256(f"bytes {i}".encode()).digest()
        kk = (i * 0xD6E8FEB86659FD93 + 3) % (p256.N - 1) + 1
        r, s = p256.sign_digest(priv, digest, k=kk)
        kind = i % 4
        if kind == 1:
            digest = hashlib.sha256(b"other").digest()
        elif kind == 2:
            sig = b"\x30\x01\x00"
            out.append((key, sig, digest))
            continue
        elif kind == 3:
            s = p256.N - s  # high-S
        out.append((key, marshal_signature(r, s), digest))
    return out


# 40 > KEY_BUCKET exercises the fallback; the in-bucket 5-key case is
# ~90s of warm device execution on the 2-vCPU gate box (NOTES_BUILD
# tier-1 budget forensics), so it is slow-marked and tier-1 keeps the
# fallback case (which loads the same programs and the same mixed-lane
# parity assertion).
@pytest.mark.parametrize(
    "num_keys", [pytest.param(5, marks=pytest.mark.slow), 40]
)
def test_bytes_path_matches_software(num_keys):
    cases = _cases(48, num_keys)
    expected = []
    for key, sig, dig in cases:
        try:
            expected.append(SW.verify(key, sig, dig))
        except VerifyError:
            expected.append(False)
    prov = TPUProvider()
    got = prov.batch_verify(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    assert got == expected
    assert any(expected) and not all(expected)


@pytest.mark.slow  # ~2min of warm bytes-path execution on the gate box
# (NOTES_BUILD tier-1 budget forensics); async resolver ordering stays
# covered in tier-1 by test_pipeline's channel-level async tests
def test_async_resolver_order():
    cases = _cases(40, 4)
    prov = TPUProvider()
    r1 = prov.batch_verify_async(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    r2 = prov.batch_verify_async(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    assert r1() == r2()


def test_key_columns_vectorized_matches_per_key_reference():
    """PR 18 regression (fabtrace transfer-in-loop): the key-column
    dedup now converts cache-miss keys with one vectorized
    be_bytes_to_limbs call per coordinate instead of a per-key
    int_to_limbs loop.  Columns, on-curve flags, lane indices and the
    SKI cache must match the per-key reference exactly — including an
    off-curve key, id()-deduped repeats, and a pure cache-hit pass."""
    import numpy as np

    from fabric_tpu.ops import bignum as bn

    pts = []
    acc = None
    for _ in range(4):
        acc = p256.point_add(acc, p256.GENERATOR)
        pts.append(acc)
    keys = [ECDSAPublicKey(x, y) for x, y in pts]
    keys.append(ECDSAPublicKey(12345, 67890))  # off-curve

    prov = TPUProvider.__new__(TPUProvider)  # no device/jax needed
    prov._key_limb_cache = {}
    seq = [keys[0], keys[1], keys[0], keys[4], keys[2], keys[1], keys[3]]
    kx, ky, on_curve, idx = prov._dedup_key_columns(seq)
    assert list(idx) == [0, 1, 0, 2, 3, 1, 4]
    order = [keys[0], keys[1], keys[4], keys[2], keys[3]]
    for col, key in enumerate(order):
        assert np.array_equal(kx[col], bn.int_to_limbs(key.x))
        assert np.array_equal(ky[col], bn.int_to_limbs(key.y))
        assert on_curve[col] == p256.is_on_curve((key.x, key.y))
    assert list(on_curve) == [True, True, False, True, True]
    # second pass is pure cache hits and must return identical columns
    kx2, ky2, on_curve2, idx2 = prov._dedup_key_columns(seq)
    assert list(idx2) == list(idx) and list(on_curve2) == list(on_curve)
    assert all(np.array_equal(a, b) for a, b in zip(kx, kx2))
    assert all(np.array_equal(a, b) for a, b in zip(ky, ky2))
