"""TPUProvider bytes-path (device-side unpack + key gather) differential
vs the software oracle, including the distinct-key-bucket fallback."""

import hashlib

import pytest

from fabric_tpu.crypto import p256
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider, VerifyError
from fabric_tpu.crypto.der import marshal_signature
from fabric_tpu.crypto.tpu_provider import TPUProvider

SW = SoftwareProvider()


def _cases(n, num_keys):
    keys = []
    for k in range(num_keys):
        priv = (k * 0x9E3779B97F4A7C15 + 77) % (p256.N - 1) + 1
        pub = p256.scalar_mult(priv, p256.GENERATOR)
        keys.append((priv, ECDSAPublicKey(pub[0], pub[1])))
    out = []
    for i in range(n):
        priv, key = keys[i % num_keys]
        digest = hashlib.sha256(f"bytes {i}".encode()).digest()
        kk = (i * 0xD6E8FEB86659FD93 + 3) % (p256.N - 1) + 1
        r, s = p256.sign_digest(priv, digest, k=kk)
        kind = i % 4
        if kind == 1:
            digest = hashlib.sha256(b"other").digest()
        elif kind == 2:
            sig = b"\x30\x01\x00"
            out.append((key, sig, digest))
            continue
        elif kind == 3:
            s = p256.N - s  # high-S
        out.append((key, marshal_signature(r, s), digest))
    return out


# 40 > KEY_BUCKET exercises the fallback; the in-bucket 5-key case is
# ~90s of warm device execution on the 2-vCPU gate box (NOTES_BUILD
# tier-1 budget forensics), so it is slow-marked and tier-1 keeps the
# fallback case (which loads the same programs and the same mixed-lane
# parity assertion).
@pytest.mark.parametrize(
    "num_keys", [pytest.param(5, marks=pytest.mark.slow), 40]
)
def test_bytes_path_matches_software(num_keys):
    cases = _cases(48, num_keys)
    expected = []
    for key, sig, dig in cases:
        try:
            expected.append(SW.verify(key, sig, dig))
        except VerifyError:
            expected.append(False)
    prov = TPUProvider()
    got = prov.batch_verify(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    assert got == expected
    assert any(expected) and not all(expected)


@pytest.mark.slow  # ~2min of warm bytes-path execution on the gate box
# (NOTES_BUILD tier-1 budget forensics); async resolver ordering stays
# covered in tier-1 by test_pipeline's channel-level async tests
def test_async_resolver_order():
    cases = _cases(40, 4)
    prov = TPUProvider()
    r1 = prov.batch_verify_async(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    r2 = prov.batch_verify_async(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    assert r1() == r2()
