"""Stable consenter -> raft-id tracking (reference etcdraft BlockMetadata,
orderer/consensus/etcdraft/etcdraft.proto + util.go MembershipChanges).

The positional rule (id == list index) breaks on non-tail removals: the
highest id is evicted instead of the departed node.  These tests pin the
stable-id semantics through the tracker, the chain's block stamping, and
restart recovery from block metadata.
"""


from conftest import requires_crypto

import time

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    genesis_block,
)
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer.consenter_ids import (
    ConsenterIdTracker,
    consenters_from_config_block,
)
from fabric_tpu.orderer.raft import ENTRY_NORMAL, Entry
from fabric_tpu.protos import common_pb2, protoutil

CHANNEL = "idtrackchan"


class TestTracker:
    def test_bootstrap_is_positional(self):
        t = ConsenterIdTracker.bootstrap(["a:1", "b:2", "c:3"])
        assert t.ids == {"a:1": 1, "b:2": 2, "c:3": 3}
        assert t.next_id == 4

    def test_non_tail_removal_keeps_survivor_ids(self):
        t = ConsenterIdTracker.bootstrap(["a:1", "b:2", "c:3"])
        t.apply(["b:2", "c:3"])  # remove the FIRST consenter
        assert t.peer_ids() == [2, 3]  # NOT {1, 2}
        assert not t.is_member(1)

    def test_reorder_changes_nothing(self):
        t = ConsenterIdTracker.bootstrap(["a:1", "b:2", "c:3"])
        t.apply(["c:3", "a:1", "b:2"])
        assert t.ids == {"a:1": 1, "b:2": 2, "c:3": 3}

    def test_readd_draws_a_fresh_id(self):
        t = ConsenterIdTracker.bootstrap(["a:1", "b:2"])
        t.apply(["b:2"])
        t.apply(["b:2", "a:1"])  # a returns: retired id 1 is NOT reused
        assert t.ids == {"b:2": 2, "a:1": 3}
        assert t.next_id == 4

    def test_block_metadata_roundtrip(self):
        t = ConsenterIdTracker.bootstrap(["a:1", "b:2", "c:3"])
        t.apply(["b:2", "c:3", "d:4"])
        block = protoutil.new_block(5, b"\x00" * 32)
        protoutil.seal_block(block)
        t.stamp(block)
        back = ConsenterIdTracker.from_block(block)
        assert back is not None
        assert back.ids == t.ids
        assert back.next_id == t.next_id

    def test_from_block_without_metadata_is_none(self):
        block = protoutil.new_block(0, b"")
        protoutil.seal_block(block)
        assert ConsenterIdTracker.from_block(block) is None
        assert ConsenterIdTracker.from_block(None) is None


def _profile(org1, oorg, consenter_ports):
    return Profile(
        application=ApplicationProfile(
            organizations=[OrganizationProfile("Org1MSP", org1.msp_config())]
        ),
        orderer=OrdererProfile(
            orderer_type="etcdraft",
            batch_timeout="100ms",
            max_message_count=1,
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config())
            ],
            raft_consenters=[
                ("127.0.0.1", p, b"", b"") for p in consenter_ports
            ],
        ),
    )


@requires_crypto
def test_chain_applies_and_stamps_stable_ids(tmp_path):
    """Write a non-tail-removal config block through the chain's apply
    path: the survivor keeps its id, the block is stamped with the new
    mapping, and a restarted chain recovers peers from the metadata (not
    positionally)."""
    from fabric_tpu.orderer.multichannel import Registrar
    from fabric_tpu.orderer.raft_chain import RaftChain

    org1 = generate_org("org1.idtrack", "Org1MSP")
    oorg = generate_org("orderer.idtrack", "OrdererMSP")
    pa, pb, pc = 7101, 7102, 7103
    gblock = genesis_block(_profile(org1, oorg, [pa, pb, pc]), CHANNEL)

    registrar = Registrar(
        str(tmp_path / "orderer"),
        signer=SigningIdentity(oorg.peers[0]),
        raft_node_id=1,
    )
    support = registrar.join_channel(gblock)
    chain = support.chain
    assert chain.node.peers == {1, 2, 3}
    assert chain.tracker.peer_ids() == [1, 2, 3]
    # genesis got stamped so later joiners read the mapping from block 0
    stored = chain.get_block(0)
    assert ConsenterIdTracker.from_block(stored).ids == chain.tracker.ids

    # config block dropping the FIRST consenter (pa): b and c keep 2, 3
    shrunk = genesis_block(_profile(org1, oorg, [pb, pc]), CHANNEL)
    assert consenters_from_config_block(shrunk) == [
        f"127.0.0.1:{pb}",
        f"127.0.0.1:{pc}",
    ]
    config_block = protoutil.new_block(1, chain.block_store.last_block_hash)
    for d in shrunk.data.data:
        config_block.data.data.append(d)
    protoutil.seal_block(config_block)

    # drive the committed-entry apply path directly (the raft commit
    # itself is covered by test_follower's grow test; a 3-peer quorum
    # cannot form in-process here)
    chain._apply_entry(
        Entry(
            index=1,
            term=1,
            type=ENTRY_NORMAL,
            data=b"\x01" + config_block.SerializeToString(),
        )
    )
    assert chain.height == 2
    assert chain.tracker.peer_ids() == [2, 3]  # positional would say [1, 2]
    assert not chain.tracker.is_member(1)
    stamped = ConsenterIdTracker.from_block(chain.get_block(1))
    assert stamped.ids == {f"127.0.0.1:{pb}": 2, f"127.0.0.1:{pc}": 3}
    # the registrar's bridge derived its desired set from the tracker
    # (propose_conf_change was skipped only because we are not leader)
    assert support.bundle.orderer is not None

    # restart: peers recovered from the last block's ORDERER metadata
    chain2 = RaftChain(
        CHANNEL,
        2,
        [1, 2],  # wrong positional fallback on purpose
        wal_dir=str(tmp_path / "orderer" / "etcdraft"),
        initial_consenters=[f"127.0.0.1:{pb}", f"127.0.0.1:{pc}"],
    )
    assert chain2.node.peers == {2, 3}
    assert chain2.tracker.ids == stamped.ids
