"""Lifecycle (_lifecycle analog) tests — reference flows from
core/chaincode/lifecycle/lifecycle_test.go: approve/check-readiness/
commit sequencing, parameter mismatch detection, validation info."""

import pytest

from fabric_tpu.lifecycle import (
    ChaincodeDefinition,
    LifecycleError,
    LifecycleResources,
)


@pytest.fixture()
def resources():
    pub, orgs = {}, {}
    lr = LifecycleResources(
        pub.get,
        pub.__setitem__,
        lambda o, k: orgs.get((o, k)),
        lambda o, k, v: orgs.__setitem__((o, k), v),
        ["Org1", "Org2", "Org3"],
    )
    return lr


def test_approve_then_commit_majority(resources):
    cd = ChaincodeDefinition(sequence=1, validation_parameter=b"pol")
    resources.approve_chaincode_definition_for_org("Org1", "cc", cd, "pkg1")
    assert resources.check_commit_readiness("cc", cd) == {
        "Org1": True,
        "Org2": False,
        "Org3": False,
    }
    with pytest.raises(LifecycleError):
        resources.commit_chaincode_definition("cc", cd)
    resources.approve_chaincode_definition_for_org("Org2", "cc", cd)
    approvals = resources.commit_chaincode_definition("cc", cd)
    assert approvals["Org1"] and approvals["Org2"] and not approvals["Org3"]
    assert resources.current_sequence("cc") == 1
    assert resources.validation_info("cc") == ("vscc", b"pol")


def test_sequence_must_advance_by_one(resources):
    cd = ChaincodeDefinition(sequence=3)
    with pytest.raises(LifecycleError):
        resources.approve_chaincode_definition_for_org("Org1", "cc", cd)
    with pytest.raises(LifecycleError):
        resources.check_commit_readiness("cc", cd)


def test_approval_with_different_params_not_ready(resources):
    cd1 = ChaincodeDefinition(sequence=1, validation_parameter=b"a")
    cd2 = ChaincodeDefinition(sequence=1, validation_parameter=b"b")
    resources.approve_chaincode_definition_for_org("Org1", "cc", cd1)
    resources.approve_chaincode_definition_for_org("Org2", "cc", cd2)
    # readiness is per exact parameter match
    assert resources.check_commit_readiness("cc", cd1) == {
        "Org1": True,
        "Org2": False,
        "Org3": False,
    }


def test_upgrade_sequence(resources):
    cd1 = ChaincodeDefinition(sequence=1)
    for org in ("Org1", "Org2"):
        resources.approve_chaincode_definition_for_org(org, "cc", cd1)
    resources.commit_chaincode_definition("cc", cd1)

    # re-approving the committed sequence with identical params is fine
    resources.approve_chaincode_definition_for_org("Org3", "cc", cd1)
    # ... but with different params is rejected
    with pytest.raises(LifecycleError):
        resources.approve_chaincode_definition_for_org(
            "Org3", "cc", ChaincodeDefinition(sequence=1, version="2.0")
        )

    cd2 = ChaincodeDefinition(sequence=2, version="2.0")
    for org in ("Org2", "Org3"):
        resources.approve_chaincode_definition_for_org(org, "cc", cd2)
    resources.commit_chaincode_definition("cc", cd2)
    assert resources.current_sequence("cc") == 2
    assert resources.query_chaincode_definition("cc").version == "2.0"


def test_undefined_chaincode(resources):
    assert resources.query_chaincode_definition("nope") is None
    assert resources.validation_info("nope") is None
    assert resources.current_sequence("nope") == 0
