"""Bounded accelerator probe (utils/deviceprobe): a hung backend init
must never block callers past their timeout, a slow-but-alive backend
flips later calls to success, and an init error is cached as failure.

The module holds process-global state; tests operate on reloaded
copies so the real probe (used by default_provider) is untouched."""

import importlib
import threading
import time


def _fresh():
    from fabric_tpu.utils import deviceprobe

    mod = importlib.reload(deviceprobe)
    return mod


def test_hung_probe_returns_none_within_timeout(monkeypatch):
    mod = _fresh()
    release = threading.Event()
    monkeypatch.setattr(mod, "_worker", lambda: release.wait(30))
    t0 = time.monotonic()
    assert mod.probe_devices(0.2) is None
    assert time.monotonic() - t0 < 2.0  # bounded, not hung
    assert "timed out" in (mod.probe_error() or "")
    release.set()


def test_slow_probe_flips_to_success(monkeypatch):
    mod = _fresh()
    release = threading.Event()
    fake_devices = ["fake-tpu"]

    def worker():
        release.wait(10)
        with mod._lock:
            mod._state["status"] = "ok"
            mod._state["devices"] = fake_devices

    monkeypatch.setattr(mod, "_worker", worker)
    assert mod.probe_devices(0.1) is None  # first call times out
    release.set()
    deadline = time.time() + 5
    while time.time() < deadline:
        if mod.probe_devices(0.2) == fake_devices:
            break
    assert mod.probe_devices(0.1) == fake_devices  # cached success
    assert mod.probe_error() is None


def test_init_error_cached_as_failure(monkeypatch):
    mod = _fresh()

    def worker():
        with mod._lock:
            mod._state["status"] = "error"
            mod._state["error"] = "UNAVAILABLE: tunnel down"

    monkeypatch.setattr(mod, "_worker", worker)
    assert mod.probe_devices(2.0) is None
    assert "UNAVAILABLE" in mod.probe_error()
    assert not mod.accelerator_present(0.1)


def test_accelerator_present_filters_cpu(monkeypatch):
    mod = _fresh()

    class Dev:
        platform = "cpu"

    def worker():
        with mod._lock:
            mod._state["status"] = "ok"
            mod._state["devices"] = [Dev()]

    monkeypatch.setattr(mod, "_worker", worker)
    assert mod.probe_devices(2.0) is not None
    assert not mod.accelerator_present(0.1)  # cpu-only != accelerator
