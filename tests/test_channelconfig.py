"""Channel config: encoder -> Bundle round-trip, policy manager hierarchy,
implicit meta evaluation, config update validation (reference
common/channelconfig + common/configtx + configtxgen encoder)."""

import pytest

pytest.importorskip(
    "cryptography", reason="channel config trees are built from real X.509 org material"
)

from fabric_tpu.channelconfig import (
    ApplicationProfile,
    Bundle,
    ConfigTxError,
    OrdererProfile,
    OrganizationProfile,
    Profile,
    Validator,
    bundle_from_genesis_block,
    genesis_block,
    new_config,
)
from fabric_tpu.channelconfig import configtx as configtx_mod
from fabric_tpu.msp.cryptogen import generate_org
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.policy.manager import SignedData
from fabric_tpu.protos import configtx_pb2, protoutil


@pytest.fixture(scope="module")
def orgs():
    return generate_org("org1"), generate_org("org2"), generate_org("orderer-org")


@pytest.fixture(scope="module")
def profile(orgs):
    org1, org2, oorg = orgs
    return Profile(
        consortium="SampleConsortium",
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("Org1MSP", org1.msp_config()),
                OrganizationProfile("Org2MSP", org2.msp_config()),
            ],
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            addresses=["127.0.0.1:7050"],
            organizations=[
                OrganizationProfile("OrdererMSP", oorg.msp_config()),
            ],
        ),
    )


@pytest.fixture(scope="module")
def bundle(profile):
    block = genesis_block(profile, "testchannel")
    return bundle_from_genesis_block(block)


def test_genesis_block_shape(profile):
    block = genesis_block(profile, "testchannel")
    assert block.header.number == 0
    assert block.header.data_hash == protoutil.block_data_hash(block.data)


def test_bundle_typed_views(bundle):
    assert bundle.channel_id == "testchannel"
    assert bundle.hashing_algorithm == "SHA256"
    assert bundle.orderer_addresses == ["127.0.0.1:7050"]
    assert bundle.consortium_name == "SampleConsortium"
    assert bundle.orderer.consensus_type == "solo"
    assert bundle.orderer.batch_size_max_messages == 500
    assert {o.msp_id for o in bundle.application.orgs} == {"org1MSP", "org2MSP"}
    assert bundle.application.capabilities.v20_validation
    # MSPs from both app orgs + the orderer org are registered
    ids = {m.msp_id for m in bundle.msp_manager.msps()}
    assert ids == {"org1MSP", "org2MSP", "orderer-orgMSP"}


def test_policy_manager_paths(bundle):
    pm = bundle.policy_manager
    for path in (
        "/Channel/Readers",
        "/Channel/Writers",
        "/Channel/Admins",
        "/Channel/Application/Readers",
        "/Channel/Application/Writers",
        "/Channel/Application/Admins",
        "/Channel/Application/Endorsement",
        "/Channel/Orderer/BlockValidation",
    ):
        _, ok = pm.get_policy(path)
        assert ok, path
    _, ok = pm.get_policy("/Channel/Nope")
    assert not ok


def _signed_by(identity_node, msg=b"payload"):
    signer = SigningIdentity(identity_node)
    return SignedData(msg, signer.serialize(), signer.sign(msg))


def test_implicit_meta_any_writer(bundle, orgs):
    org1, _, _ = orgs
    pol, ok = bundle.policy_manager.get_policy("/Channel/Application/Writers")
    assert ok
    pol.evaluate_signed_data([_signed_by(org1.peers[0])])


def test_implicit_meta_majority_admins(bundle, orgs):
    org1, org2, _ = orgs
    pol, ok = bundle.policy_manager.get_policy("/Channel/Application/Admins")
    assert ok
    # one org's admin is not a 2-org majority
    with pytest.raises(Exception):
        pol.evaluate_signed_data([_signed_by(org1.admin)])
    pol.evaluate_signed_data([_signed_by(org1.admin), _signed_by(org2.admin)])


def test_implicit_meta_counts_children_missing_subpolicy(orgs):
    """Regression: a child group lacking the named sub-policy still counts
    in the MAJORITY/ALL denominator as an always-deny (implicitmeta.go
    counts every child)."""
    from fabric_tpu.channelconfig.bundle import Bundle

    org1, org2, oorg = orgs
    profile = Profile(
        application=ApplicationProfile(
            organizations=[
                OrganizationProfile("Org1MSP", org1.msp_config()),
                OrganizationProfile("Org2MSP", org2.msp_config()),
            ]
        ),
        orderer=OrdererProfile(
            orderer_type="solo",
            organizations=[OrganizationProfile("OrdererMSP", oorg.msp_config())],
        ),
    )
    cfg = new_config(profile)
    app = cfg.channel_group.groups["Application"]
    # strip org2's Admins policy: MAJORITY Admins over 2 children must
    # still require 2, making it unsatisfiable by org1 alone
    del app.groups["Org2MSP"].policies["Admins"]
    bundle = Bundle("testchannel", cfg)
    pol, ok = bundle.policy_manager.get_policy("/Channel/Application/Admins")
    assert ok
    with pytest.raises(Exception):
        pol.evaluate_signed_data([_signed_by(org1.admin)])


def test_non_member_rejected(bundle):
    stranger = generate_org("org1")  # same MSP name, different CA
    pol, ok = bundle.policy_manager.get_policy("/Channel/Application/Writers")
    assert ok
    with pytest.raises(Exception):
        pol.evaluate_signed_data([_signed_by(stranger.peers[0])])


def test_config_update_applies(profile):
    cfg = new_config(profile)
    v = Validator("testchannel", cfg)

    # Bump the batch size: write set carries the modified value at version+1.
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "testchannel"
    cur_orderer = cfg.channel_group.groups["Orderer"]
    rs = update.read_set.groups["Orderer"]
    rs.version = cur_orderer.version
    rs.values["BatchSize"].version = cur_orderer.values["BatchSize"].version
    ws = update.write_set.groups["Orderer"]
    ws.version = cur_orderer.version
    from fabric_tpu.protos import configuration_pb2

    bs = configuration_pb2.BatchSize()
    bs.max_message_count = 100
    bs.absolute_max_bytes = 1 << 20
    bs.preferred_max_bytes = 1 << 19
    ws.values["BatchSize"].value = bs.SerializeToString()
    ws.values["BatchSize"].version = cur_orderer.values["BatchSize"].version + 1
    ws.values["BatchSize"].mod_policy = "Admins"

    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    result = v.propose_config_update_envelope(cue)
    assert result.config.sequence == 1
    new_bundle = Bundle("testchannel", result.config)
    assert new_bundle.orderer.batch_size_max_messages == 100
    # unmodified elements carried over
    assert new_bundle.application is not None
    assert new_bundle.orderer.batch_timeout == "2s"


def test_same_version_tampered_content_discarded(profile):
    """A write-set element at the unchanged version contributes NOTHING:
    content comes from current config (reference computeUpdateResult
    overlays only the delta) — tampering can't bypass mod-policy auth."""
    cfg = new_config(profile)
    v = Validator("testchannel", cfg)
    from fabric_tpu.protos import configuration_pb2

    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "testchannel"
    cur_orderer = cfg.channel_group.groups["Orderer"]
    rs = update.read_set.groups["Orderer"]
    rs.values["BatchSize"].SetInParent()
    rs.values["BatchTimeout"].SetInParent()
    ws = update.write_set.groups["Orderer"]
    # legit delta: BatchSize at version 1
    bs = configuration_pb2.BatchSize()
    bs.max_message_count = 42
    ws.values["BatchSize"].value = bs.SerializeToString()
    ws.values["BatchSize"].version = 1
    ws.values["BatchSize"].mod_policy = "Admins"
    # tamper attempt: BatchTimeout content changed but version NOT bumped
    bt = configuration_pb2.BatchTimeout()
    bt.timeout = "666s"
    ws.values["BatchTimeout"].value = bt.SerializeToString()
    ws.values["BatchTimeout"].version = 0

    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    result = v.propose_config_update_envelope(cue)
    new_bundle = Bundle("testchannel", result.config)
    assert new_bundle.orderer.batch_size_max_messages == 42  # delta applied
    assert new_bundle.orderer.batch_timeout == "2s"  # tamper discarded


def test_config_update_bad_read_version(profile):
    cfg = new_config(profile)
    v = Validator("testchannel", cfg)
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "testchannel"
    update.read_set.groups["Orderer"].values["BatchSize"].version = 7
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    with pytest.raises(ConfigTxError):
        v.propose_config_update_envelope(cue)


def test_config_update_version_skip_rejected(profile):
    cfg = new_config(profile)
    v = Validator("testchannel", cfg)
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "testchannel"
    ws = update.write_set.groups["Orderer"]
    ws.values["BatchSize"].value = b"x"
    ws.values["BatchSize"].version = 5  # current is 0; must be exactly 1
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    with pytest.raises(ConfigTxError):
        v.propose_config_update_envelope(cue)


def test_config_update_wrong_channel(profile):
    cfg = new_config(profile)
    v = Validator("testchannel", cfg)
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "other"
    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    with pytest.raises(ConfigTxError):
        v.propose_config_update_envelope(cue)


def test_config_update_mod_policy_authorization(profile, orgs, bundle):
    """With a policy manager attached, delta elements need mod-policy
    authorization: orderer Admins signatures."""
    org1, org2, oorg = orgs
    cfg = new_config(profile)
    v = Validator("testchannel", cfg, policy_manager=bundle.policy_manager)

    update = configtx_pb2.ConfigUpdate()
    update.channel_id = "testchannel"
    from fabric_tpu.protos import configuration_pb2

    rs = update.read_set.groups["Orderer"]
    rs.values["BatchSize"].SetInParent()
    bs = configuration_pb2.BatchSize()
    bs.max_message_count = 10
    ws = update.write_set.groups["Orderer"]
    ws.values["BatchSize"].value = bs.SerializeToString()
    ws.values["BatchSize"].version = 1
    ws.values["BatchSize"].mod_policy = "Admins"

    cue = configtx_pb2.ConfigUpdateEnvelope()
    cue.config_update = update.SerializeToString()
    with pytest.raises(ConfigTxError):  # unsigned
        v.propose_config_update_envelope(cue)

    configtx_mod.sign_config_update(cue, SigningIdentity(oorg.admin))
    result = v.propose_config_update_envelope(cue)
    assert result.config.sequence == 1

    # a non-admin signature does not satisfy the orderer Admins policy
    cue2 = configtx_pb2.ConfigUpdateEnvelope()
    cue2.config_update = update.SerializeToString()
    configtx_mod.sign_config_update(cue2, SigningIdentity(org1.peers[0]))
    with pytest.raises(ConfigTxError):
        v.propose_config_update_envelope(cue2)
